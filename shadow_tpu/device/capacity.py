"""Occupancy-driven capacity planning for the device engine.

Every hot-path cost in the device engine scales with a statically
provisioned capacity: heap merges are E + IN rows wide, the flush's
flat sort covers H*OB (or H*CX) rows, and the all_to_all exchange
ships [n_shards, CAP] buffers auto-sized with 4x headroom "for skewed
traffic" (engine.py) — so on sparse or bursty workloads most of the
sort width and ICI bandwidth moves padding. The engine now accumulates
per-segment occupancy HIGH-WATER MARKS in its state (state["occ_*"],
reductions only, no extra sorts); this module turns those measurements
into tight capacities and back:

* ``measure(engine, state)``  — occupancy record (a JSON-able dict)
  from a run's final state: measured maxima + the effective
  capacities that held them.
* ``plan(record, ...)``       — EngineConfig capacity overrides sized
  to the measurements with headroom.
* ``widen(knobs, dims, eff)`` — double the offending dimension(s)
  after a loud overflow (the runner's re-plan/retry loop).
* ``grow_heaps(host_state, new_e)`` / ``transfer(engine, starts,
  host_state)`` — carry a saved state into a re-planned engine whose
  event_capacity grew.

Safety argument: a plan that undershoots (the warm-up slice missed
steady state) trips the engine's LOUD overflow counters; the runner
re-plans with doubled headroom on the offending dimension and re-runs
the segment from the last known-good state instead of failing the
run. Traces are bit-identical across capacity choices whenever
nothing overflows (the engine's determinism contract, pinned by
tests), so planning is purely a performance lever.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

FORMAT = 1
# planned = ceil(measured * HEADROOM) + SLACK: the warm-up slice is a
# lower bound on steady-state occupancy, and the retry loop makes an
# undershoot cost one re-run, never the run
HEADROOM = 1.5
SLACK = 2
# re-plan attempts before the run is allowed to fail loudly (each
# attempt doubles the offending dimension, so 6 covers a 64x miss)
MAX_REPLANS = 6

# overflow counter -> the capacity dimensions it implicates. The
# merge/arrival `overflow` counter cannot distinguish a short heap
# from a short arrival window, so both grow together; `x_overflow`
# covers both the shard-pair CAP and the compaction width.
OVERFLOW_DIMS = {
    "overflow": ("event_capacity", "exchange_in_capacity"),
    "x_overflow": ("exchange_capacity", "outbox_compact"),
}


def app_scalars(app) -> dict:
    """The app's scalar config surface (bool/int/float/str instance
    attrs — device apps keep per-host state in the engine state dict,
    so scalars are the configuration surface). burst_pops is a
    trace-invariant lane-width knob and is excluded, so retuning
    width neither splits occupancy records nor poisons checkpoint
    fingerprints. Shared by app_fingerprint and the checkpoint
    fingerprint — an app knob that must join or leave the identity
    changes in exactly one place."""
    out = {k: v for k, v in sorted(vars(app).items())
           if isinstance(v, (bool, int, float, str))}
    out.pop("burst_pops", None)
    return out


def app_fingerprint(app) -> str:
    """Workload-variant fingerprint of a device app: its scalar
    config surface plus its per-host parameter arrays (tgen counts/
    pauses, tor relay ids, ...). Two same-class, same-host-count
    apps with different traffic shapes have different occupancy —
    they must not share a record."""
    import hashlib

    h = hashlib.sha256(
        json.dumps(app_scalars(app), sort_keys=True).encode())
    for k, v in sorted(vars(app).items()):
        if isinstance(v, np.ndarray):
            h.update(k.encode())
            h.update(str(v.shape).encode())
            h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()[:12]


def measure(engine, state, source: str = "run") -> dict:
    """Build an occupancy record from a (finished) run's state. The
    occ_* entries are a handful of small per-shard arrays — fetching
    them costs microseconds, never the [H, E] heaps."""
    from shadow_tpu._jax import jax

    H = engine.config.n_hosts
    occ = {k: np.asarray(jax.device_get(state[k]))
           for k in ("occ_heap", "occ_ob", "occ_in", "occ_x",
                     "occ_trips", "occ_phases", "overflow",
                     "x_overflow")}
    eff = dict(engine.effective)
    measured = {
        "heap_rows_max": int(occ["occ_heap"][:H].max(initial=0)),
        "outbox_rows_max": int(occ["occ_ob"][:H].max(initial=0)),
        "arrivals_per_flush_max": int(occ["occ_in"][:H].max(initial=0)),
        "exchange_rows_max": int(occ["occ_x"].max(initial=0)),
        "pop_trips_max": int(occ["occ_trips"].max(initial=0)),
        "phases": int(occ["occ_phases"].max(initial=0)),
        "overflow": int(occ["overflow"][:H].sum()),
        "x_overflow": int(occ["x_overflow"][:H].sum()),
    }
    return {
        "format": FORMAT,
        "source": source,
        "workload": {
            "app": type(engine.app).__name__,
            "app_fp": app_fingerprint(engine.app),
            "n_hosts": H,
            "seed": int(engine.config.seed),
            "stop_time": int(engine.config.stop_time),
        },
        "measured": measured,
        "effective": eff,
    }


def plan(record: dict, per_iter: int, floor_iters: int = 4,
         n_shards: int = 1, headroom: float = HEADROOM) -> dict:
    """Measured occupancies -> EngineConfig capacity overrides.

    per_iter is the outbox row cost of one pop iteration (K_eff + T
    [+ READY]); outbox_capacity is planned in iterations so the
    engine's B = outbox // per_iter arithmetic lands exactly.

    Saved records carry both the warm-up slice maxima (`measured`)
    and, once the runner finishes, the full run's (`final_measured`)
    — plan from the elementwise max so a capacity_plan: <path> replay
    sizes for steady state, not just the warm-up prefix."""
    m = dict(record["measured"])
    for k, v in record.get("final_measured", {}).items():
        if k in m:
            m[k] = max(m[k], v)

    def pad(x: int) -> int:
        return int(math.ceil(x * headroom)) + SLACK

    event_capacity = max(2, pad(m["heap_rows_max"]))
    exchange_in = max(1, pad(m["arrivals_per_flush_max"]))
    # too few iterations per phase costs one collective exchange per
    # few events; too many only pads the (compactable) outbox
    iters = max(floor_iters, pad(m["pop_trips_max"]))
    outbox_capacity = iters * max(1, per_iter)
    # compaction wins only when the busiest host's real fan-out is
    # well under the outbox width (the lane sort must buy sort rows)
    cx = pad(m["outbox_rows_max"])
    outbox_compact = cx if cx < (3 * outbox_capacity) // 4 else 0
    # per shard-pair exchange rows: only meaningful multi-shard; 0
    # keeps the engine's own auto-sizing when nothing was measured
    if n_shards > 1 and m["exchange_rows_max"] > 0:
        exchange_capacity = max(8, pad(m["exchange_rows_max"]))
    else:
        exchange_capacity = 0
    return {
        "event_capacity": event_capacity,
        "outbox_capacity": outbox_capacity,
        "exchange_capacity": exchange_capacity,
        "exchange_in_capacity": exchange_in,
        "outbox_compact": outbox_compact,
    }


def widen(knobs: dict, dims: tuple, effective: dict) -> dict:
    """Double the offending capacity dimension(s) after a loud
    overflow. `knobs` are the current EngineConfig overrides (may hold
    zeros meaning auto); `effective` supplies the auto-sized values so
    doubling always starts from what actually ran."""
    out = dict(knobs)
    for dim in dims:
        if dim == "event_capacity":
            out[dim] = 2 * max(out.get(dim) or 0, effective["E"])
        elif dim == "exchange_in_capacity":
            out[dim] = 2 * max(out.get(dim) or 0, effective["IN"])
        elif dim == "exchange_capacity":
            if effective["CAP"] > 0:
                out[dim] = 2 * max(out.get(dim) or 0, effective["CAP"])
        elif dim == "outbox_compact":
            # a compaction width that lost rows first doubles, then
            # turns off once it stops paying for itself
            cx, ob = effective["CX"], effective["OB"]
            if cx < ob:
                ncx = 2 * cx
                out[dim] = ncx if ncx < ob else 0
    return out


def overflow_dims(state) -> tuple:
    """Which capacity dimensions the state's loud counters implicate
    (empty tuple = clean). Costs two tiny device_gets."""
    from shadow_tpu._jax import jax

    dims = ()
    for counter, d in OVERFLOW_DIMS.items():
        if int(np.asarray(jax.device_get(state[counter])).sum()):
            dims += d
    return dims


def grow_heaps(host_state: dict, new_e: int) -> dict:
    """Pad the five [..., H, E] heap arrays of a host-side state
    snapshot to a larger event_capacity (rows are sorted; empty slots
    sort last, so tail padding preserves the heap invariant). Works
    on standalone [H, E] states and on ensemble [R, H, E] stacks —
    the slot axis is always last."""
    INF = np.int64(1) << np.int64(62)
    IMAX = np.int64(np.iinfo(np.int64).max)
    out = dict(host_state)
    *lead, e = host_state["ht"].shape
    if new_e < e:
        raise ValueError(f"cannot shrink event_capacity {e} -> {new_e} "
                         "on a live state")
    if new_e == e:
        return out
    fills = {"ht": INF, "hk": IMAX, "hm": 0, "hv": 0, "hw": 0}
    for k, fill in fills.items():
        pad = np.full(tuple(lead) + (new_e - e,), fill,
                      dtype=np.int64)
        out[k] = np.concatenate([np.asarray(host_state[k]), pad], -1)
    return out


def transfer(engine, starts, host_state: dict,
             template: dict = None) -> dict:
    """Place a host-side state snapshot onto a (re-planned) engine:
    pads the heaps to the engine's event_capacity and device_puts
    every leaf with the sharding of a freshly built template state.
    `template` overrides the standalone init_state template (the
    ensemble runner passes its [R, ...] init_ensemble_state)."""
    from shadow_tpu._jax import jax

    host_state = grow_heaps(host_state, engine.config.event_capacity)
    if template is None:
        template = engine.init_state(starts)
    if set(template) != set(host_state):
        raise ValueError(
            "state keys changed across re-plan: "
            f"{sorted(set(template) ^ set(host_state))}")
    out = {}
    for k, tmpl in template.items():
        arr = np.asarray(host_state[k])
        if arr.shape != tmpl.shape or arr.dtype != np.dtype(tmpl.dtype):
            raise ValueError(
                f"state leaf {k} is {arr.shape}/{arr.dtype}, the "
                f"re-planned engine expects {tmpl.shape}/{tmpl.dtype}")
        out[k] = jax.device_put(arr, tmpl.sharding)
    return out


def record_path(engine, directory: str = "") -> str:
    """Canonical OCC record path for a workload: app class + host
    count + workload fingerprint (deterministic, so tune_10k.py and
    repeat runs find it; the fingerprint keeps two traffic-shape
    variants of the same app from clobbering each other's record).
    SHADOW_TPU_OCC_DIR overrides the directory (tests point it at a
    tmpdir so runs never litter the repo's artifacts/)."""
    directory = directory or os.environ.get("SHADOW_TPU_OCC_DIR",
                                            "artifacts")
    return os.path.join(
        directory,
        f"OCC_{type(engine.app).__name__}_{engine.config.n_hosts}"
        f"_{app_fingerprint(engine.app)}.json")


def save_record(record: dict, path: str) -> None:
    from shadow_tpu.utils.artifacts import atomic_write_json

    atomic_write_json(record, path)


def load_record(path: str) -> dict:
    with open(path) as f:
        record = json.load(f)
    if record.get("format") != FORMAT:
        raise ValueError(
            f"occupancy record {path}: format {record.get('format')} "
            f"(this build reads format {FORMAT})")
    for key in ("measured", "workload"):
        if key not in record:
            raise ValueError(f"occupancy record {path}: missing {key!r}")
    return record
