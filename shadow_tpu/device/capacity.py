"""Occupancy-driven capacity planning for the device engine.

Every hot-path cost in the device engine scales with a statically
provisioned capacity: heap merges are E + IN rows wide, the flush's
flat sort covers H*OB (or H*CX) rows, and the all_to_all exchange
ships [n_shards, CAP] buffers auto-sized with 4x headroom "for skewed
traffic" (engine.py) — so on sparse or bursty workloads most of the
sort width and ICI bandwidth moves padding. The engine now accumulates
per-segment occupancy HIGH-WATER MARKS in its state (state["occ_*"],
reductions only, no extra sorts); this module turns those measurements
into tight capacities and back:

* ``measure(engine, state)``  — occupancy record (a JSON-able dict)
  from a run's final state: measured maxima + the effective
  capacities that held them.
* ``plan(record, ...)``       — EngineConfig capacity overrides sized
  to the measurements with headroom.
* ``widen(knobs, dims, eff)`` — double the offending dimension(s)
  after a loud overflow (the runner's re-plan/retry loop).
* ``grow_heaps(host_state, new_e)`` / ``transfer(engine, starts,
  host_state)`` — carry a saved state into a re-planned engine whose
  event_capacity grew.

Safety argument: a plan that undershoots (the warm-up slice missed
steady state) trips the engine's LOUD overflow counters; the runner
re-plans with doubled headroom on the offending dimension and re-runs
the segment from the last known-good state instead of failing the
run. Traces are bit-identical across capacity choices whenever
nothing overflows (the engine's determinism contract, pinned by
tests), so planning is purely a performance lever.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

from shadow_tpu.utils.slog import get_logger

log = get_logger("capacity")

FORMAT = 1
# planned = ceil(measured * HEADROOM) + SLACK: the warm-up slice is a
# lower bound on steady-state occupancy, and the retry loop makes an
# undershoot cost one re-run, never the run
HEADROOM = 1.5
SLACK = 2
# re-plan attempts before the run is allowed to fail loudly (each
# attempt doubles the offending dimension, so 6 covers a 64x miss)
MAX_REPLANS = 6

# the engine's full capacity-knob surface, in one place: the planner
# plans them, checkpoints stamp them, resumes adopt them, and the
# runners snapshot the static baseline from them — a new knob joins
# here and every consumer follows.
CAPACITY_KNOBS = ("event_capacity", "outbox_capacity",
                  "exchange_capacity", "exchange_capacity2",
                  "exchange_in_capacity", "outbox_compact")

# overflow counter -> the capacity dimensions it implicates. The
# merge/arrival `overflow` counter cannot distinguish a short heap
# from a short arrival window, so both grow together; `x_overflow`
# covers the shard-pair CAP (both phases of a two_phase schedule)
# and the compaction width.
OVERFLOW_DIMS = {
    "overflow": ("event_capacity", "exchange_in_capacity"),
    "x_overflow": ("exchange_capacity", "exchange_capacity2",
                   "outbox_compact"),
}

# two_phase must beat the direct all_to_all's estimated ICI volume by
# this factor before `exchange: auto` picks it (two collectives + an
# extra on-device sort are only worth real bandwidth savings)
TWO_PHASE_MARGIN = 0.9


def dense_auto_cap(h_loc: int, outbox: int, event_capacity: int,
                   n_shards: int) -> int:
    """The engine's blind per-pair CAP when exchange_capacity is 0:
    4x the balanced share of the whole outbox, "for skewed traffic".
    ONE definition, shared by the engine's auto-sizing and by the
    bench/micro reports that quote the dense baseline a measured plan
    replaces — the reduction factor must never be computed against a
    stale copy of this heuristic."""
    r = h_loc * outbox
    return min(r, max(64, event_capacity,
                      (4 * r + n_shards - 1) // n_shards))


def group_split(n_shards: int) -> tuple[int, int]:
    """Two-phase exchange group factorization: n_shards = g * ng with
    g (the intra-group size, phase 1) the largest divisor <= sqrt —
    so both phases have as few peers as possible. A prime shard count
    degenerates to (1, n_shards): phase 1 is empty and phase 2 is the
    direct exchange, correct but profitless (auto never picks it)."""
    g = 1
    for d in range(2, int(math.isqrt(n_shards)) + 1):
        if n_shards % d == 0:
            g = d
    # isqrt catches d <= sqrt; the co-divisor may be the better g when
    # n_shards is a perfect square times a small factor — keep g as
    # the largest divisor not exceeding isqrt (g <= ng always)
    return g, n_shards // g


def app_scalars(app) -> dict:
    """The app's scalar config surface (bool/int/float/str instance
    attrs — device apps keep per-host state in the engine state dict,
    so scalars are the configuration surface). burst_pops is a
    trace-invariant lane-width knob and is excluded, so retuning
    width neither splits occupancy records nor poisons checkpoint
    fingerprints. Shared by app_fingerprint and the checkpoint
    fingerprint — an app knob that must join or leave the identity
    changes in exactly one place."""
    out = {k: v for k, v in sorted(vars(app).items())
           if isinstance(v, (bool, int, float, str))}
    out.pop("burst_pops", None)
    return out


def app_fingerprint(app) -> str:
    """Workload-variant fingerprint of a device app: its scalar
    config surface plus its per-host parameter arrays (tgen counts/
    pauses, tor relay ids, ...). Two same-class, same-host-count
    apps with different traffic shapes have different occupancy —
    they must not share a record."""
    import hashlib

    h = hashlib.sha256(
        json.dumps(app_scalars(app), sort_keys=True).encode())
    for k, v in sorted(vars(app).items()):
        if isinstance(v, np.ndarray):
            h.update(k.encode())
            h.update(str(v.shape).encode())
            h.update(np.ascontiguousarray(v).tobytes())
    return h.hexdigest()[:12]


def measure(engine, state, source: str = "run") -> dict:
    """Build an occupancy record from a (finished) run's state. The
    occ_* entries are a handful of small per-shard arrays — fetching
    them costs microseconds, never the [H, E] heaps."""
    from shadow_tpu._jax import jax

    H = engine.config.n_hosts
    occ = {k: np.asarray(jax.device_get(state[k]))
           for k in ("occ_heap", "occ_ob", "occ_in", "occ_x",
                     "occ_trips", "occ_phases", "overflow",
                     "x_overflow")}
    eff = dict(engine.effective)
    # the full per-(src shard, dst shard) high-water matrix rides the
    # record (a few ints per shard pair): the exchange planner sizes
    # the direct per-pair CAP from its max and the two_phase per-PHASE
    # caps from its row/column aggregates, and choose_exchange
    # compares the variants' estimated ICI volumes from it
    pairs = np.asarray(occ["occ_x"], dtype=np.int64)
    if pairs.ndim > 2:          # ensemble stacks reduce to worst-case
        pairs = pairs.max(axis=tuple(range(pairs.ndim - 2)))
    measured = {
        "heap_rows_max": int(occ["occ_heap"][:H].max(initial=0)),
        "outbox_rows_max": int(occ["occ_ob"][:H].max(initial=0)),
        "arrivals_per_flush_max": int(occ["occ_in"][:H].max(initial=0)),
        "exchange_rows_max": int(occ["occ_x"].max(initial=0)),
        "exchange_pairs": [[int(v) for v in row] for row in pairs],
        "pop_trips_max": int(occ["occ_trips"].max(initial=0)),
        "phases": int(occ["occ_phases"].max(initial=0)),
        "overflow": int(occ["overflow"][:H].sum()),
        "x_overflow": int(occ["x_overflow"][:H].sum()),
    }
    return {
        "format": FORMAT,
        "source": source,
        "workload": {
            "app": type(engine.app).__name__,
            "app_fp": app_fingerprint(engine.app),
            "n_hosts": H,
            "seed": int(engine.config.seed),
            "stop_time": int(engine.config.stop_time),
        },
        "measured": measured,
        "effective": eff,
    }


def merged_measured(record: dict) -> dict:
    """The record's `measured` maxima merged with `final_measured`
    (elementwise for the pair matrix): a capacity_plan: <path> replay
    sizes for steady state, not just the warm-up prefix."""
    m = dict(record["measured"])
    for k, v in record.get("final_measured", {}).items():
        if k not in m:
            continue
        if k == "exchange_pairs":
            a = np.asarray(m[k], dtype=np.int64)
            b = np.asarray(v, dtype=np.int64)
            if a.shape == b.shape:
                m[k] = np.maximum(a, b).tolist()
        else:
            m[k] = max(m[k], v)
    return m


def pair_matrix(m: dict, n_shards: int) -> np.ndarray:
    """The per-(src shard, dst shard) high-water matrix of a merged
    `measured` dict. Records written before the matrix existed (or
    measured on a different shard count) fall back to the scalar
    per-pair max replicated everywhere off-diagonal — a safe upper
    bound that never undershoots what the scalar plan would have."""
    pairs = np.asarray(m.get("exchange_pairs", []), dtype=np.int64)
    if pairs.shape != (n_shards, n_shards):
        pairs = np.full((n_shards, n_shards),
                        int(m.get("exchange_rows_max", 0)),
                        dtype=np.int64)
        np.fill_diagonal(pairs, 0)
    return pairs


def two_phase_caps(pairs: np.ndarray, headroom: float = HEADROOM
                   ) -> tuple[int, int]:
    """Per-phase capacities of the hierarchical two_phase schedule
    from the pair high-water matrix. Shard s = (group a, rank b) with
    g = group_split(S)[0]:

    * phase 1 (intra-group): s ships ONE buffer per in-group rank r
      holding every row destined to rank r in ANY group, so CAP1 must
      hold max over (s, r) of sum_a pairs[s, a*g + r];
    * phase 2 (inter-group): intermediate (a, b) forwards its whole
      group's rows destined (a', b), so CAP2 must hold max over
      (a, b, a' != a) of sum_{s in group a} pairs[s, a'*g + b].

    Sums of per-pair high-water marks upper-bound the high-water of
    the sum, so a plan from these caps can only overshoot — an
    undershoot (the warm-up missed steady state) still fails loudly
    and re-plans, exactly like the direct CAP."""
    S = pairs.shape[0]
    g, ng = group_split(S)
    def pad(x: int) -> int:
        return int(math.ceil(int(x) * headroom)) + SLACK
    # [S, ng, g]: sender s -> (dst group a, dst rank r)
    by_dst = pairs.reshape(S, ng, g)
    cap1 = int(by_dst.sum(axis=1).max(initial=0))
    # [ng, g, ng, g]: (src group, src rank) -> (dst group, dst rank)
    by_both = pairs.reshape(ng, g, ng, g)
    # intermediate (a, b) -> dst group a': sum over src ranks in a of
    # rows destined (a', b); mask the a' == a diagonal (delivered in
    # phase 1, never forwarded)
    fwd = by_both.sum(axis=1)            # [a, a', b]
    eye = np.eye(ng, dtype=bool)[:, :, None]
    cap2 = int(np.where(eye, 0, fwd).max(initial=0))
    return max(8, pad(cap1)), max(8, pad(cap2))


def plan(record: dict, per_iter: int, floor_iters: int = 4,
         n_shards: int = 1, headroom: float = HEADROOM,
         exchange: str = "all_to_all") -> dict:
    """Measured occupancies -> EngineConfig capacity overrides.

    per_iter is the outbox row cost of one pop iteration (K_eff + T
    [+ READY]); outbox_capacity is planned in iterations so the
    engine's B = outbox // per_iter arithmetic lands exactly.

    Saved records carry both the warm-up slice maxima (`measured`)
    and, once the runner finishes, the full run's (`final_measured`)
    — plan from the elementwise max so a capacity_plan: <path> replay
    sizes for steady state, not just the warm-up prefix.

    `exchange` is the (resolved) exchange variant the engine will
    run: the direct all_to_all sizes one per-pair CAP from the occ_x
    high-water mark; two_phase sizes its two per-phase caps from the
    pair matrix aggregates (two_phase_caps); all_gather ships whole
    compacted outboxes and needs no CAP at all."""
    m = merged_measured(record)

    def pad(x: int) -> int:
        return int(math.ceil(x * headroom)) + SLACK

    event_capacity = max(2, pad(m["heap_rows_max"]))
    exchange_in = max(1, pad(m["arrivals_per_flush_max"]))
    # too few iterations per phase costs one collective exchange per
    # few events; too many only pads the (compactable) outbox
    iters = max(floor_iters, pad(m["pop_trips_max"]))
    outbox_capacity = iters * max(1, per_iter)
    # compaction wins only when the busiest host's real fan-out is
    # well under the outbox width (the lane sort must buy sort rows)
    cx = pad(m["outbox_rows_max"])
    outbox_compact = cx if cx < (3 * outbox_capacity) // 4 else 0
    # per shard-pair exchange rows: only meaningful multi-shard; 0
    # keeps the engine's own auto-sizing when nothing was measured
    exchange_capacity = 0
    exchange_capacity2 = 0
    if n_shards > 1 and m["exchange_rows_max"] > 0:
        if exchange == "two_phase":
            exchange_capacity, exchange_capacity2 = two_phase_caps(
                pair_matrix(m, n_shards), headroom)
        elif exchange != "all_gather":
            exchange_capacity = max(8, pad(m["exchange_rows_max"]))
    return {
        "event_capacity": event_capacity,
        "outbox_capacity": outbox_capacity,
        "exchange_capacity": exchange_capacity,
        "exchange_capacity2": exchange_capacity2,
        "exchange_in_capacity": exchange_in,
        "outbox_compact": outbox_compact,
    }


def estimate_ici_rows(record: dict, n_shards: int,
                      per_iter: int, floor_iters: int = 4,
                      headroom: float = HEADROOM) -> dict:
    """Estimated per-flush ICI rows each variant would ship per shard
    under a plan from this record (buffers ship at capacity — padding
    included — so the estimate is the planned cap times the peer
    count, exactly what the wire carries)."""
    m = merged_measured(record)
    S = n_shards
    if S <= 1:
        return {"all_to_all": 0, "two_phase": 0, "all_gather": 0}

    def pad(x: int) -> int:
        return int(math.ceil(x * headroom)) + SLACK

    pairs = pair_matrix(m, S)
    cap = max(8, pad(int(pairs.max(initial=0))))
    g, ng = group_split(S)
    cap1, cap2 = two_phase_caps(pairs, headroom)
    # all_gather replicates each shard's whole compacted outbox
    p = plan(record, per_iter, floor_iters, n_shards=S,
             headroom=headroom, exchange="all_gather")
    w = p["outbox_compact"] or p["outbox_capacity"]
    h_loc = -(-record["workload"]["n_hosts"] // S)
    return {
        "all_to_all": (S - 1) * cap,
        "two_phase": (g - 1) * cap1 + (ng - 1) * cap2,
        "all_gather": (S - 1) * h_loc * w,
    }


def choose_exchange(record: dict, n_shards: int, per_iter: int,
                    floor_iters: int = 4,
                    headroom: float = HEADROOM) -> tuple[str, dict]:
    """`exchange: auto` resolution from a measured occupancy record:
    compare the variants' estimated per-flush ICI rows and pick the
    cheapest. two_phase must beat the direct all_to_all by
    TWO_PHASE_MARGIN (its two collectives + extra on-device sort are
    only worth real bandwidth savings), and a degenerate group split
    (prime shard count) never qualifies. Returns (variant, info)."""
    est = estimate_ici_rows(record, n_shards, per_iter, floor_iters,
                            headroom)
    info = {"estimates": est, "n_shards": n_shards,
            "group_split": list(group_split(n_shards))}
    if n_shards <= 1:
        return "all_to_all", info
    choice = "all_to_all"
    if est["all_gather"] < est["all_to_all"]:
        choice = "all_gather"
    g, _ = group_split(n_shards)
    # two_phase must beat the DIRECT schedule by the margin (the
    # documented rule) and also be the overall minimum
    if g > 1 and est["two_phase"] < \
            TWO_PHASE_MARGIN * est["all_to_all"] and \
            est["two_phase"] < est[choice]:
        choice = "two_phase"
    info["chosen"] = choice
    return choice, info


def widen(knobs: dict, dims: tuple, effective: dict) -> dict:
    """Double the offending capacity dimension(s) after a loud
    overflow. `knobs` are the current EngineConfig overrides (may hold
    zeros meaning auto); `effective` supplies the auto-sized values so
    doubling always starts from what actually ran."""
    out = dict(knobs)
    for dim in dims:
        if dim == "event_capacity":
            out[dim] = 2 * max(out.get(dim) or 0, effective["E"])
        elif dim == "exchange_in_capacity":
            out[dim] = 2 * max(out.get(dim) or 0, effective["IN"])
        elif dim == "exchange_capacity":
            if effective["CAP"] > 0:
                out[dim] = 2 * max(out.get(dim) or 0, effective["CAP"])
        elif dim == "exchange_capacity2":
            # only live on the two_phase schedule (CAP2 > 0); the
            # x_overflow counter cannot tell which phase lost rows,
            # so both caps double together
            if effective.get("CAP2", 0) > 0:
                out[dim] = 2 * max(out.get(dim) or 0,
                                   effective["CAP2"])
        elif dim == "outbox_compact":
            # a compaction width that lost rows first doubles, then
            # turns off once it stops paying for itself
            cx, ob = effective["CX"], effective["OB"]
            if cx < ob:
                ncx = 2 * cx
                out[dim] = ncx if ncx < ob else 0
    return out


def overflow_dims(state) -> tuple:
    """Which capacity dimensions the state's loud counters implicate
    (empty tuple = clean). Costs two tiny device_gets."""
    from shadow_tpu._jax import jax

    dims = ()
    for counter, d in OVERFLOW_DIMS.items():
        if int(np.asarray(jax.device_get(state[counter])).sum()):
            dims += d
    return dims


def grow_heaps(host_state: dict, new_e: int) -> dict:
    """Pad the five [..., H, E] heap arrays of a host-side state
    snapshot to a larger event_capacity (rows are sorted; empty slots
    sort last, so tail padding preserves the heap invariant). Works
    on standalone [H, E] states and on ensemble [R, H, E] stacks —
    the slot axis is always last."""
    INF = np.int64(1) << np.int64(62)
    IMAX = np.int64(np.iinfo(np.int64).max)
    out = dict(host_state)
    *lead, e = host_state["ht"].shape
    if new_e < e:
        raise ValueError(f"cannot shrink event_capacity {e} -> {new_e} "
                         "on a live state")
    if new_e == e:
        return out
    fills = {"ht": INF, "hk": IMAX, "hm": 0, "hv": 0, "hw": 0}
    for k, fill in fills.items():
        pad = np.full(tuple(lead) + (new_e - e,), fill,
                      dtype=np.int64)
        out[k] = np.concatenate([np.asarray(host_state[k]), pad], -1)
    return out


# reshard_state's leaf classification: every key the engine may put
# in state must fall in exactly one class — an unregistered key fails
# loudly, so a new state leaf cannot be silently mis-resharded.
# (Per-host vector leaves — counters, seq/chk, occ_heap/ob/in, aud*,
# NIC scalars — are the residual class, shape-checked against the
# padded width.)
RESHARD_HOST_ROWS = ("ht", "hk", "hm", "hv", "hw", "app")
RESHARD_SHARD_ZERO = ("occ_x", "occ_trips", "occ_phases")
RESHARD_SHARD_SUM = ("path_cnt",)


def reshard_state(host_state: dict, n_hosts: int,
                  template_host: dict) -> dict:
    """Carry a host-side state snapshot across a mesh-geometry change
    (the elastic shrink failover's core transform): because
    ``H_pad = ceil(H / n_shards) * n_shards``, a different shard
    count means a different padded width, so every per-host leaf is
    re-padded row-for-row rather than transferred whole.

    ``template_host`` is a host-side copy of the TARGET engine's
    freshly initialized state (``device_get`` of ``init_state`` /
    ``init_ensemble_state``): its shapes define the new padded layout
    and its values supply the padding rows' contents (app init rows,
    INF/IMAX heap fills, zeroed counters) — exactly what an
    uninterrupted run on the target mesh holds for hosts that never
    execute. The first ``n_hosts`` rows along the host axis carry
    over verbatim, so per-host counters, event heaps, and trace
    checksums — the determinism surface — are untouched; combined
    with the engine's mesh-shape determinism contract, the resharded
    continuation is bit-identical to an uninterrupted run on the
    target mesh. Per-shard telemetry resets (high-water marks
    measured on the old geometry describe buffers that no longer
    exist) and the per-shard path histogram's partial sums
    re-aggregate onto shard 0 (row totals are the reported surface).
    Works on standalone ``[H, ...]`` states and ensemble
    ``[R, H, ...]`` stacks alike — the host axis position per leaf
    is fixed, only leading axes broadcast."""
    extra = set(host_state) - set(template_host)
    if any(not _aux_leaf(k) for k in extra):
        raise ValueError(
            "reshard_state: snapshot carries leaves the target "
            f"engine lacks: {sorted(extra)}")
    old_pad = np.asarray(host_state["ht"]).shape[-2]
    new_pad = np.asarray(template_host["ht"]).shape[-2]
    H = int(n_hosts)
    if not (0 < H <= old_pad and H <= new_pad):
        raise ValueError(
            f"reshard_state: n_hosts {H} does not fit the padded "
            f"widths (old {old_pad}, new {new_pad})")
    out = {}
    for k, tmpl in template_host.items():
        new = np.array(tmpl)        # the target padding, host-side
        if k not in host_state:
            if k == "aud_tx":
                # the snapshot predates the audit (a rotation entry
                # written with state_audit off): reseed the
                # conservation ledger from the saved counters, the
                # checkpoint.load_state rule — per-host, so the
                # global balance holds exactly at the resume point
                ht = np.asarray(host_state["ht"])
                head = np.asarray(host_state["head"])
                E = ht.shape[-1]
                live = ((np.arange(E) >= head[..., None]) &
                        (ht < (np.int64(1) << np.int64(62)))).sum(-1)
                recon = (np.asarray(host_state["n_exec"])
                         .astype(np.int64) + live
                         + np.asarray(host_state["overflow"])
                         .astype(np.int64)
                         + np.asarray(host_state["x_overflow"])
                         .astype(np.int64))
                new[..., :H] = recon[..., :H]
            elif not _aux_leaf(k):
                raise ValueError(
                    f"reshard_state: snapshot is missing leaf {k!r}")
            out[k] = new
            continue
        old = np.asarray(host_state[k])
        if k in RESHARD_HOST_ROWS:
            if old.shape[-1] != new.shape[-1] or \
                    old.shape[:-2] != new.shape[:-2] or \
                    old.shape[-2] != old_pad or \
                    new.shape[-2] != new_pad:
                raise ValueError(
                    f"reshard_state: leaf {k} is {old.shape}, target "
                    f"expects {new.shape} — reshard carries geometry "
                    "only, never capacity or replica changes")
            new[..., :H, :] = old[..., :H, :]
        elif k in RESHARD_SHARD_ZERO:
            new[...] = 0
        elif k in RESHARD_SHARD_SUM:
            new[...] = 0
            new[..., 0, :] = old.sum(axis=-2)
        elif old.shape[:-1] == new.shape[:-1] and \
                old.shape[-1] == old_pad and \
                new.shape[-1] == new_pad:
            new[..., :H] = old[..., :H]
        else:
            raise ValueError(
                f"reshard_state: leaf {k!r} ({old.shape} -> "
                f"{new.shape}) is not registered in any reshard "
                "class — classify it in capacity.RESHARD_* before "
                "adding state leaves")
        out[k] = new
    return out


def _aux_leaf(k: str) -> bool:
    """Auxiliary leaves that may differ between the saving and
    resuming engines without perturbing the trace (the
    checkpoint.load_state rule): occupancy telemetry and the
    invariant-audit word."""
    return k.startswith("occ_") or k.startswith("aud")


def transfer(engine, starts, host_state: dict,
             template: dict = None) -> dict:
    """Place a host-side state snapshot onto a (re-planned) engine:
    pads the heaps to the engine's event_capacity and device_puts
    every leaf with the sharding of a freshly built template state.
    `template` overrides the standalone init_state template (the
    ensemble runner passes its [R, ...] init_ensemble_state)."""
    from shadow_tpu._jax import jax

    host_state = grow_heaps(host_state, engine.config.event_capacity)
    if template is None:
        template = engine.init_state(starts)
    if set(template) != set(host_state):
        raise ValueError(
            "state keys changed across re-plan: "
            f"{sorted(set(template) ^ set(host_state))}")
    out = {}
    for k, tmpl in template.items():
        arr = np.asarray(host_state[k])
        if arr.shape != tmpl.shape or arr.dtype != np.dtype(tmpl.dtype):
            raise ValueError(
                f"state leaf {k} is {arr.shape}/{arr.dtype}, the "
                f"re-planned engine expects {tmpl.shape}/{tmpl.dtype}")
        out[k] = jax.device_put(arr, tmpl.sharding)
    return out


def record_path(engine, directory: str = "") -> str:
    """Canonical OCC record path for a workload: app class + host
    count + workload fingerprint (deterministic, so tune_10k.py and
    repeat runs find it; the fingerprint keeps two traffic-shape
    variants of the same app from clobbering each other's record).
    SHADOW_TPU_OCC_DIR overrides the directory (tests point it at a
    tmpdir so runs never litter the repo's artifacts/)."""
    directory = directory or os.environ.get("SHADOW_TPU_OCC_DIR",
                                            "artifacts")
    return os.path.join(
        directory,
        f"OCC_{type(engine.app).__name__}_{engine.config.n_hosts}"
        f"_{app_fingerprint(engine.app)}.json")


def save_record(record: dict, path: str) -> None:
    from shadow_tpu.obs import trace as obstrace
    from shadow_tpu.utils.artifacts import atomic_write_json

    atomic_write_json(record, path)
    # flight-recorder marker: OCC record writes are plan-phase
    # milestones worth a tick on the run timeline
    obstrace.current().instant("occ.save", "plan", path=path)


def load_record(path: str) -> dict:
    from shadow_tpu.obs import trace as obstrace

    with open(path) as f:
        record = json.load(f)
    if record.get("format") != FORMAT:
        raise ValueError(
            f"occupancy record {path}: format {record.get('format')} "
            f"(this build reads format {FORMAT})")
    for key in ("measured", "workload"):
        if key not in record:
            raise ValueError(f"occupancy record {path}: missing {key!r}")
    obstrace.current().instant("occ.load", "plan", path=path)
    return record


# ----------------------------------------------------------------------
# preflight admission: footprint estimate vs per-device budget
# ----------------------------------------------------------------------
# The byte model counts exactly what the engine pins on device: the
# sharded state pytree (state_structs), in-flight copies of it (the
# segment pipeline keeps up to `depth` issued segments plus the last
# validated snapshot alive), the replica axis R, the per-flush outbox
# and exchange buffers at their effective capacities, and the
# replicated world tables. XLA's transient workspace (sort scratch,
# fusion temporaries) is deliberately NOT modeled — the estimate is a
# floor on steady-state live bytes, and the honesty tests pin it to
# measured live bytes within FOOTPRINT_TOLERANCE.
FOOTPRINT_TOLERANCE = 4.0


def _nbytes(struct) -> int:
    """Bytes of one ShapeDtypeStruct (shape may be empty)."""
    n = 1
    for d in struct.shape:
        n *= int(d)
    return n * np.dtype(struct.dtype).itemsize


def footprint(engine, pipeline_depth: int = 0,
              replicas: int = None) -> dict:
    """Static per-device byte model of an engine's resident state —
    from the same resolved inputs program_facts reports, with zero
    device work (admission must run BEFORE any compile).

    ``replicas`` overrides the engine's ensemble width (the
    replica-batch rungs of the degradation ladder estimate a k-replica
    batch against the full-R engine before building it)."""
    eff = engine.effective
    S = max(1, int(eff["n_shards"]))
    ens = getattr(engine, "ensemble", None)
    R_full = int(ens.R) if ens is not None else 1
    R = max(1, int(replicas if replicas is not None else R_full))
    # one copy of one replica's sharded state, per device
    structs = engine.state_structs()
    state_total = sum(_nbytes(v) for v in structs.values())
    state_dev = -(-state_total // S)
    # the segment pipeline holds `depth` issued segment outputs plus
    # the last validated snapshot (rewind source) concurrently
    copies = max(1, int(pipeline_depth)) + 1
    # per-flush scratch: the 5 int64 outbox field arrays plus the
    # exchange send+receive buffers at the effective capacities
    H_pad, OB = engine._ob_shape_global
    outbox_dev = 5 * (-(-int(H_pad) // S)) * int(OB) * 8
    h_loc = -(-int(H_pad) // S)
    g, ng = (int(x) for x in eff["tp_groups"])
    if S <= 1:
        rows = 0
    elif eff["exchange"] == "two_phase":
        rows = g * int(eff["CAP"]) + ng * int(eff["CAP2"])
    elif eff["exchange"] == "all_gather":
        rows = S * h_loc * int(eff["CX"])
    else:
        rows = S * int(eff["CAP"])
    exchange_dev = 2 * rows * 6 * 8          # send + recv, ~6 fields
    scratch = (outbox_dev + exchange_dev) * R
    # world tables replicate on every device; ensemble stacks them
    # [R]. Under the hierarchical representation the latency /
    # reliability slots are TUPLES of factored leaves ([C,C] + [V]
    # vectors), so flatten the pytree and price the actual uploaded
    # arrays — the whole point of the representation is that this sum
    # is MBs where the dense [V,V] pair would be GBs.
    from shadow_tpu._jax import jax

    ws = engine.world_structs(ensemble=ens is not None)
    world_total = sum(_nbytes(s)
                     for s in jax.tree_util.tree_leaves(ws))
    if ens is not None and R_full:
        world_total = (world_total * R) // R_full
    hier = isinstance(getattr(engine, "latency", None), tuple)
    per_device = state_dev * copies * R + scratch + world_total
    return {
        "representation": "hierarchical" if hier else "dense",
        "per_device": int(per_device),
        "total": int(per_device * S),
        "state_bytes": int(state_dev),
        "scratch_bytes": int(scratch),
        "world_bytes": int(world_total),
        "copies": int(copies),
        "replicas": int(R),
        "pipeline_depth": int(pipeline_depth),
        "n_devices": int(S),
    }


def fmt_bytes(n) -> str:
    """Human-readable byte count for admission diagnostics."""
    n = float(int(n))
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return (f"{int(n)} B" if unit == "B"
                    else f"{n:.1f} {unit}")
        n /= 1024.0
    return f"{n:.1f} TiB"


def device_budget(engine, xp) -> tuple:
    """(per-device budget bytes, source). The backend's reported
    bytes_limit wins when it exposes one (TPU/GPU); else the
    operator's experimental.device_memory_budget; else (0, "") —
    no budget, admission: auto skips and strict refuses."""
    try:
        dev = list(engine.mesh.devices.flat)[0]
        ms = dev.memory_stats()
        if ms and int(ms.get("bytes_limit", 0) or 0) > 0:
            return int(ms["bytes_limit"]), "backend"
    except Exception:
        pass
    b = int(getattr(xp, "device_memory_budget", 0) or 0)
    if b > 0:
        return b, "config"
    return 0, ""


def admission_diagnostic(est: dict, budget: int, source: str) -> str:
    return (
        f"admission: needs {fmt_bytes(est['per_device'])} per device, "
        f"budget {fmt_bytes(budget)} ({source}) on "
        f"{est['n_devices']} device(s) — state "
        f"{fmt_bytes(est['state_bytes'])} x {est['copies']} copies x "
        f"R={est['replicas']}, scratch "
        f"{fmt_bytes(est['scratch_bytes'])}, world "
        f"{fmt_bytes(est['world_bytes'])} "
        f"({est.get('representation', 'dense')} tables); raise the "
        "budget or lower pipeline_depth / ensemble.replicas / "
        "capacities")


def admission_verdict(engine, xp, pipeline_depth: int = 0,
                      batchable: bool = False) -> dict:
    """The preflight admission gate, shared by both runners.

    * ``strict``  — refuse an over-budget estimate outright (raises
      ValueError with the readable diagnostic) before any compile.
    * ``auto``    — degrade statically along the same ladder the
      runtime walks (shrink pipeline_depth, then split the ensemble
      into replica batches); if the estimate still exceeds the
      budget, admit LOUDLY — the runtime degradation ladder in
      supervise.advance is the backstop for what the static model
      cannot shed (dispatch_segment halving, failover).
    * ``off``     — skip entirely.

    Returns the verdict dict the runners stash on ``runner.admission``
    (bench stamps it; supervise reads the imposed overrides)."""
    mode = str(getattr(xp, "admission", "auto"))
    ens = getattr(engine, "ensemble", None)
    R_full = int(ens.R) if ens is not None else 1
    est = footprint(engine, pipeline_depth=pipeline_depth)
    budget, source = device_budget(engine, xp)
    out = {"mode": mode, "budget": int(budget),
           "budget_source": source, "estimate": est,
           "action": "admit", "fits": True, "overrides": {}}
    if mode == "off":
        out["action"] = "off"
        return out
    if budget <= 0:
        if mode == "strict":
            raise ValueError(
                "experimental.admission: strict needs a per-device "
                "budget, but the backend reports none and "
                "experimental.device_memory_budget is unset")
        out["action"] = "no-budget"
        return out
    if est["per_device"] <= budget:
        log.info("admission: fits — %s per device of %s (%s)",
                 fmt_bytes(est["per_device"]), fmt_bytes(budget),
                 source)
        return out
    diag = admission_diagnostic(est, budget, source)
    if mode == "strict":
        raise ValueError(diag)
    # auto: statically walk the ladder's estimable rungs
    overrides = {}
    depth = max(1, int(pipeline_depth))
    while est["per_device"] > budget and depth > 1:
        depth //= 2
        overrides["pipeline_depth"] = depth
        est = footprint(engine, pipeline_depth=depth)
    batch = R_full
    while est["per_device"] > budget and batchable and batch > 1:
        batch = (batch + 1) // 2
        overrides["replica_batch"] = batch
        est = footprint(engine, pipeline_depth=depth,
                        replicas=batch)
    out["estimate"] = est
    out["overrides"] = overrides
    out["fits"] = est["per_device"] <= int(budget)
    if out["fits"]:
        out["action"] = "degrade"
        log.warning("%s — degraded preflight to %s (now %s per "
                    "device)", diag, overrides,
                    fmt_bytes(est["per_device"]))
    else:
        out["action"] = "over"
        log.warning("%s — admitting anyway (admission: auto); the "
                    "runtime degradation ladder is the backstop",
                    diag)
    return out
