"""The device simulation engine.

One jitted program advances the whole simulation: an outer while_loop
over conservative time windows (controller_run's round loop,
reference controller.c:392-424), an inner while_loop that pops and
executes at most one event per host per iteration (preserving each
host's serial (time, src, seq) order — the per-host sequentiality
invariant of event.c:109-152 — while all hosts advance in parallel),
and a per-round collective packet exchange:

  pop min event/host -> app handle (batched) -> counter-RNG drop rolls
  + latency gathers (worker_sendPacket semantics, worker.c:520-579) ->
  outbox -> all_gather over the mesh axis -> merge into destination
  heaps (causality bump, host_single.c:174-220) -> pmin next event time.

Determinism: every stochastic decision is keyed by stable integer ids
(threefry counters), per-host event heaps merge by full-key sort, and
incoming packets are ordered by (src_gid, outbox_slot) — so results are
bit-identical across mesh shapes AND match the CPU serial oracle's
per-host schedule (verified by trace checksums in tests).

The heap is a fixed-capacity unsorted slot array per host: pops are
two-stage argmins (O(E) vector work, no data-dependent shapes), and
per-round batch inserts are one lexicographic lax.sort of the
concatenated [heap | incoming] rows. Everything is static-shape; the
only dynamism is while_loop trip counts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

from shadow_tpu._jax import jax, jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shadow_tpu import simtime
from shadow_tpu.core.event import (
    KIND_BOOT,
    KIND_PACKET,
    KIND_STOP,
    KIND_TIMER,
)
from shadow_tpu.device import prng
from shadow_tpu.device.apps import DeviceApp
from shadow_tpu.device.netsem import packet_drop_mask
from shadow_tpu.utils.rng import PURPOSE_APP

from shadow_tpu.utils.checksum import (
    CHK_KIND,
    CHK_MUL,
    CHK_SEQ,
    CHK_SRC,
    MASK63,
)

INF = np.int64(1) << np.int64(62)
IMAX = np.int64(np.iinfo(np.int64).max)

AXIS = "hosts"

HEAP_FIELDS = ("t", "src", "seq", "kind", "size", "d0", "d1")
NIC_KEYS = ("tx_free", "rx_free", "cd_fa", "cd_next", "cd_cnt",
            "cd_last", "cd_drop")


@dataclass
class EngineConfig:
    n_hosts: int                 # real hosts
    event_capacity: int = 64
    outbox_capacity: int = 32
    lookahead: int = simtime.SIMTIME_ONE_MILLISECOND
    stop_time: int = simtime.SIMTIME_ONE_SECOND
    bootstrap_end: int = 0
    seed: int = 1
    max_rounds: int = 1 << 62    # safety valve
    # cross-shard packet exchange: "all_to_all" moves only each
    # (src shard, dst shard) pair's rows over ICI (two-phase: sort by
    # destination shard, then lax.all_to_all on [n_shards, CAP]
    # buffers); "all_gather" replicates every shard's whole outbox
    # (simple, bandwidth ∝ H_pad*OB per device)
    exchange: str = "all_to_all"
    # per (src shard, dst shard) row capacity; 0 = auto-size from the
    # outbox volume with 4x headroom for skewed traffic. Overflow is
    # counted per source host and fails the run, never silently lost.
    exchange_capacity: int = 0
    # bandwidth + CoDel for raw sends (host/model_nic.py's fluid NIC):
    # TX serialization at send, RX serialization + event-driven CoDel
    # at delivery via a KIND_PACKET -> KIND_PACKET_READY two-stage pop
    model_bandwidth: bool = False


class DeviceEngine:
    """Builds and runs the jitted sharded simulation program."""

    def __init__(self, config: EngineConfig, app: DeviceApp,
                 host_vertex: np.ndarray, latency_ns: np.ndarray,
                 reliability: np.ndarray,
                 mesh: Optional[Mesh] = None,
                 bw_up_bits: Optional[np.ndarray] = None,
                 bw_down_bits: Optional[np.ndarray] = None):
        self.config = config
        self.app = app
        if mesh is None:
            devs = jax.devices()
            mesh = Mesh(np.array(devs), (AXIS,))
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        H = config.n_hosts
        self.H_pad = int(math.ceil(H / self.n_shards) * self.n_shards)
        self.H_loc = self.H_pad // self.n_shards

        if (latency_ns > np.iinfo(np.int32).max).any():
            raise ValueError("path latencies above ~2.1 s don't fit the "
                             "i32 device latency matrix")
        self.host_vertex = np.zeros(self.H_pad, dtype=np.int32)
        self.host_vertex[:H] = host_vertex
        self.latency = latency_ns.astype(np.int32)
        self.reliability = reliability.astype(np.float32)
        self.seed_pair = prng.seed_key(config.seed)
        # model-NIC bandwidths (bits/s), padded; 1 Gbit default keeps
        # the padded hosts' arithmetic harmless
        self.bw_up = np.full(self.H_pad, 10**9, dtype=np.int64)
        self.bw_down = np.full(self.H_pad, 10**9, dtype=np.int64)
        if bw_up_bits is not None:
            self.bw_up[:H] = np.maximum(1, bw_up_bits)
        if bw_down_bits is not None:
            self.bw_down[:H] = np.maximum(1, bw_down_bits)

        self._shard_spec = P(AXIS)
        self._repl_spec = P()
        self._build_program()

    # ------------------------------------------------------------------
    # state construction (host side)
    # ------------------------------------------------------------------
    def init_state(self, starts: list[tuple]) -> dict:
        """starts: (host_id, start_time, stop_time|-1[, proc_idx]) per
        process, in registration order — seq consumption mirrors
        Manager.boot_hosts (device configs are single-process/host, so
        the index is ignored here)."""
        H, E = self.H_pad, self.config.event_capacity
        W = self.app.n_state_words
        t = np.full((H, E), INF, dtype=np.int64)
        src = np.zeros((H, E), dtype=np.int32)
        seq = np.zeros((H, E), dtype=np.int32)
        kind = np.zeros((H, E), dtype=np.int32)
        size = np.zeros((H, E), dtype=np.int32)
        d0 = np.zeros((H, E), dtype=np.int32)
        d1 = np.zeros((H, E), dtype=np.int32)
        event_seq = np.zeros(H, dtype=np.int32)
        fill = np.zeros(H, dtype=np.int32)

        def _push(h, when, k):
            slot = fill[h]
            if slot >= E:
                raise ValueError(f"host {h}: too many boot events for "
                                 f"event_capacity={E}")
            t[h, slot] = when
            src[h, slot] = h
            seq[h, slot] = event_seq[h]
            kind[h, slot] = k
            event_seq[h] += 1
            fill[h] += 1

        for entry in starts:
            host_id, t_start, t_stop = entry[0], entry[1], entry[2]
            _push(host_id, t_start, KIND_BOOT)
            if t_stop is not None and t_stop >= 0:
                _push(host_id, t_stop, KIND_STOP)

        zeros_i32 = np.zeros(H, dtype=np.int32)
        state = {
            "t": t, "src": src, "seq": seq, "kind": kind,
            "size": size, "d0": d0, "d1": d1,
            "event_seq": event_seq,
            "packet_seq": zeros_i32.copy(),
            "app_seq": zeros_i32.copy(),
            "app": np.asarray(self.app.init_state(H), dtype=np.int32),
            "n_exec": zeros_i32.copy(),
            "n_sent": zeros_i32.copy(),
            "n_drop": zeros_i32.copy(),
            "n_deliv": zeros_i32.copy(),
            "overflow": zeros_i32.copy(),
            "x_overflow": zeros_i32.copy(),
            "chk": np.zeros(H, dtype=np.int64),
        }
        if self.config.model_bandwidth:
            # model-NIC scalars (host/model_nic.py ModelNic twin)
            for k in NIC_KEYS:
                state[k] = np.zeros(H, dtype=np.int64)
        shard = NamedSharding(self.mesh, self._shard_spec)
        return {k: jax.device_put(jnp.asarray(v), shard)
                for k, v in state.items()}

    # ------------------------------------------------------------------
    # the jitted program
    # ------------------------------------------------------------------
    def _build_program(self):
        cfg = self.config
        app = self.app
        E = cfg.event_capacity
        OB = cfg.outbox_capacity
        IN = E                       # per-round incoming capacity
        K = app.max_sends
        T = app.max_timers
        D = max(1, app.max_draws)
        H_loc, H_pad = self.H_loc, self.H_pad
        n_shards = self.n_shards
        seed_pair = self.seed_pair
        LOOKAHEAD = np.int64(max(1, cfg.lookahead))
        BOOT_END = np.int64(cfg.bootstrap_end)

        if OB < K:
            raise ValueError(
                f"outbox_capacity ({OB}) must be >= the app's max "
                f"sends per event ({K}): one event's burst must fit "
                "or the flow-control phase loop cannot make progress")

        MB = bool(cfg.model_bandwidth)
        # model-NIC constants (host/model_nic.py twins; keep in
        # lockstep with its arithmetic — trace equality depends on it)
        from shadow_tpu.host.model_nic import (
            CODEL_INTERVAL_NS as CD_INT,
            CODEL_TARGET_NS as CD_TGT,
            LAW,
            LAW_SIZE,
            MAX_SER_BYTES as MAX_SER,
        )
        from shadow_tpu.core.event import KIND_PACKET_READY
        law_t = jnp.asarray(LAW)                       # [1024] i64
        bw_up_t = jnp.asarray(self.bw_up)              # [H_pad] i64
        bw_down_t = jnp.asarray(self.bw_down)
        NSx8 = np.int64(8) * np.int64(1_000_000_000)

        hidx = jnp.arange(H_loc)

        def key2_of(src, seq):
            return (src.astype(jnp.int64) << 32) | \
                (seq.astype(jnp.int64) & 0xFFFFFFFF)

        # ---------------- inner loop body: one event per host ----------
        def _step(carry, win_end, gid, host_vertex, lat, rel):
            state, ob, ob_cnt, _ = carry
            t = state["t"]
            min_t = t.min(axis=-1)                              # [H]
            tie = t == min_t[:, None]
            k2 = jnp.where(tie, key2_of(state["src"], state["seq"]), IMAX)
            slot = jnp.argmin(k2, axis=-1)                      # [H]
            # flow control: a host only pops while its outbox has
            # headroom for a full K-send burst; a blocked host's events
            # stay heaped and run in the next phase of the SAME window
            # (outer phase loop in _round), so bursty apps never lose
            # packets to a fixed outbox (SURVEY hard-part #2: ragged
            # all_to_all under static shapes)
            runnable = (min_t < win_end) & (ob_cnt + K <= OB)

            def g(f):
                return state[f][hidx, slot]

            pt = g("t")
            psrc, pseq, pkind = g("src"), g("seq"), g("kind")
            psize, pd0, pd1 = g("size"), g("d0"), g("d1")
            state["t"] = t.at[hidx, slot].set(jnp.where(runnable, INF, pt))

            state["n_exec"] = state["n_exec"] + runnable
            # with the model NIC, a packet pops twice: the RX stage
            # (KIND_PACKET: bandwidth+CoDel, no app) and the delivery
            # (KIND_PACKET_READY). Deliveries are the READY pops then.
            is_rx = runnable & (pkind == KIND_PACKET) if MB else \
                jnp.zeros_like(runnable)
            is_pkt = runnable & (pkind == (KIND_PACKET_READY if MB
                                           else KIND_PACKET))
            state["n_deliv"] = state["n_deliv"] + is_pkt
            mix = (pt ^ (psrc.astype(jnp.int64) * CHK_SRC)
                   ^ (pkind.astype(jnp.int64) * CHK_KIND)
                   ^ (pseq.astype(jnp.int64) * CHK_SEQ)) & MASK63
            state["chk"] = jnp.where(
                runnable, (state["chk"] * CHK_MUL + mix) & MASK63,
                state["chk"])

            # app dispatch (batched); masked hosts see kind=-1. Under
            # the model NIC the RX stage is engine-internal (app sees
            # -1) and READY pops present as KIND_PACKET to the app.
            draw_seqs = state["app_seq"][:, None] + jnp.arange(D,
                                                              dtype=jnp.int32)
            draws = prng.random_bits32(prng.chain_key(
                seed_pair, PURPOSE_APP, gid[:, None], draw_seqs))
            if MB:
                app_kind = jnp.where(pkind == KIND_PACKET_READY,
                                     jnp.int32(KIND_PACKET), pkind)
                app_kind = jnp.where(runnable & ~is_rx, app_kind, -1)
            else:
                app_kind = jnp.where(runnable, pkind, -1)
            out = app.handle(gid, pt, app_kind,
                             psrc, psize, pd0, pd1, state["app"], draws)
            # commit app outputs only for pops the app really handled:
            # RX-stage pops are engine-internal, and the engine (not
            # each app's kind=-1 behavior) enforces that their outputs
            # are discarded
            app_on = runnable & ~is_rx if MB else runnable
            # apps may return [H,1] columns that broadcast over K/T
            # (e.g. a role-constant dst); materialize full shapes once
            out = out._replace(
                send_dst=jnp.broadcast_to(out.send_dst, (H_loc, K)),
                send_size=jnp.broadcast_to(out.send_size, (H_loc, K)),
                send_d0=jnp.broadcast_to(out.send_d0, (H_loc, K)),
                send_d1=jnp.broadcast_to(out.send_d1, (H_loc, K)),
                send_valid=jnp.broadcast_to(out.send_valid, (H_loc, K)),
                timer_delay=jnp.broadcast_to(out.timer_delay,
                                             (H_loc, T)),
                timer_d0=jnp.broadcast_to(out.timer_d0, (H_loc, T)),
                timer_valid=jnp.broadcast_to(out.timer_valid,
                                             (H_loc, T)),
            )
            state["app"] = jnp.where(app_on[:, None], out.app_state,
                                     state["app"])
            state["app_seq"] = state["app_seq"] + \
                jnp.where(app_on, out.n_draws, 0)

            # sends -> network judgment (worker_sendPacket semantics)
            send_valid = out.send_valid & app_on[:, None]       # [H,K]
            vrank = jnp.cumsum(send_valid, axis=-1) - send_valid
            pkt_seq = state["packet_seq"][:, None] + vrank
            state["packet_seq"] = state["packet_seq"] + \
                send_valid.sum(-1).astype(jnp.int32)

            dst = out.send_dst                                   # [H,K]
            srcv = host_vertex[gid][:, None]
            dstv = host_vertex[jnp.clip(dst, 0, H_pad - 1)]
            latv = lat[srcv, dstv].astype(jnp.int64)             # [H,K]
            relv = rel[srcv, dstv]
            dropped = send_valid & packet_drop_mask(
                seed_pair, BOOT_END, pt[:, None], gid[:, None],
                pkt_seq, relv)
            if MB:
                # TX fluid bucket (ModelNic.tx_depart): a burst's sends
                # serialize in slot order; drop-rolled packets still
                # consume uplink time (the network drops them later)
                ser_up = jnp.where(
                    send_valid,
                    (jnp.clip(out.send_size, 1,
                              MAX_SER).astype(jnp.int64)
                     * NSx8) // bw_up_t[gid][:, None],
                    jnp.int64(0))                                # [H,K]
                tx_base = jnp.maximum(pt, state["tx_free"])      # [H]
                cum = jnp.cumsum(ser_up, axis=-1)
                depart = tx_base[:, None] + (cum - ser_up)
                state["tx_free"] = jnp.where(
                    runnable, tx_base + cum[:, -1], state["tx_free"])
            else:
                depart = pt[:, None]
            delivered = send_valid & ~dropped
            state["n_sent"] = state["n_sent"] + \
                send_valid.sum(-1).astype(jnp.int32)
            state["n_drop"] = state["n_drop"] + \
                dropped.sum(-1).astype(jnp.int32)

            # event seq consumed per SEND (delivered or dropped alike),
            # matching the CPU engines — lets the CPU side defer drop
            # judgment to a batched device call without perturbing seqs
            ev_seq = state["event_seq"][:, None] + vrank
            n_snt = send_valid.sum(-1).astype(jnp.int32)

            deliver_t = depart + latv
            cross = dst != gid[:, None]
            # cross-host causality bump (host_single.c:174-220); self
            # packets keep their true time — they may run this round
            deliver_t = jnp.where(cross,
                                  jnp.maximum(deliver_t, win_end),
                                  deliver_t)

            # cross-host sends -> outbox (slots beyond OB overflow)
            to_outbox = delivered & cross
            orank = jnp.cumsum(to_outbox, axis=-1) - to_outbox
            pos = ob_cnt[:, None] + orank
            ok = to_outbox & (pos < OB)
            state["overflow"] = state["overflow"] + \
                (to_outbox & (pos >= OB)).sum(-1).astype(jnp.int32)
            spos = jnp.where(ok, pos, OB)        # OB = out-of-bounds drop

            def scat(arr, val):
                return arr.at[hidx[:, None], spos].set(val, mode="drop")

            ob["t"] = scat(ob["t"], deliver_t)
            ob["dst"] = scat(ob["dst"], dst.astype(jnp.int32))
            ob["src"] = scat(ob["src"], jnp.broadcast_to(gid[:, None],
                                                         dst.shape))
            ob["seq"] = scat(ob["seq"], ev_seq.astype(jnp.int32))
            ob["size"] = scat(ob["size"], out.send_size)
            ob["d0"] = scat(ob["d0"], out.send_d0)
            ob["d1"] = scat(ob["d1"], out.send_d1)
            ob_cnt = ob_cnt + to_outbox.sum(-1).astype(jnp.int32)

            # model-NIC RX stage (ModelNic.rx_deliver twin): the popped
            # KIND_PACKET row passes the download bucket + event-driven
            # CoDel; survivors re-enter the local heap as READY rows at
            # their post-serialization delivery time (same src/seq)
            if MB:
                rxf = state["rx_free"]
                dq = jnp.maximum(pt, rxf)                       # [H]
                soj = dq - pt
                below = soj < CD_TGT
                fa = state["cd_fa"]
                fa0 = fa == 0
                above = ~below & ~fa0 & (dq >= fa)
                in_drop = state["cd_drop"] != 0
                drop_now = above & in_drop & (dq >= state["cd_next"])
                drop_first = above & ~in_drop
                rx_drop = is_rx & (drop_now | drop_first)
                rx_keep = is_rx & ~(drop_now | drop_first)

                delta = state["cd_cnt"] - state["cd_last"]
                first_cnt = jnp.where(
                    (dq - state["cd_next"] < CD_INT) & (delta > 1),
                    delta, jnp.int64(1))
                new_cnt = jnp.where(
                    drop_now, state["cd_cnt"] + 1,
                    jnp.where(drop_first, first_cnt, state["cd_cnt"]))
                law = law_t[jnp.clip(new_cnt, 0, LAW_SIZE - 1)]
                new_next = jnp.where(
                    drop_now, state["cd_next"] + law,
                    jnp.where(drop_first, dq + law, state["cd_next"]))
                new_last = jnp.where(drop_first, first_cnt,
                                     state["cd_last"])
                new_fa = jnp.where(below, jnp.int64(0),
                                   jnp.where(fa0, dq + CD_INT, fa))
                new_cd_drop = jnp.where(
                    below, jnp.int64(0),
                    jnp.where(fa0, state["cd_drop"],
                              jnp.where(above,
                                        jnp.where(in_drop,
                                                  state["cd_drop"],
                                                  jnp.int64(1)),
                                        jnp.int64(0))))

                ser_down = (jnp.clip(psize, 1, MAX_SER)
                            .astype(jnp.int64) * NSx8) \
                    // bw_down_t[gid]
                rx_deliver = dq + ser_down
                for f_, v_ in (("cd_cnt", new_cnt),
                               ("cd_next", new_next),
                               ("cd_last", new_last),
                               ("cd_fa", new_fa),
                               ("cd_drop", new_cd_drop)):
                    state[f_] = jnp.where(is_rx, v_, state[f_])
                state["rx_free"] = jnp.where(rx_keep, rx_deliver, rxf)
                state["n_drop"] = state["n_drop"] + rx_drop
            else:
                rx_keep = jnp.zeros_like(runnable)
                rx_deliver = pt

            # self-destined sends insert into the local heap immediately
            # (like the CPU engine's push): with a runahead override
            # larger than a self-path latency they must be runnable in
            # this same window, in timestamp order. Timers likewise.
            # Both go through ONE batched insert: rank the heap's free
            # slots once and scatter every item to its own slot —
            # O(E log E) instead of (K+T) sequential full-heap scans
            # (slot choice doesn't affect semantics; pops order by
            # (t, src, seq), never by slot index).
            to_self = delivered & ~cross
            timer_valid = out.timer_valid & app_on[:, None]     # [H,T]
            trank = jnp.cumsum(timer_valid, axis=-1) - timer_valid
            tseq = state["event_seq"][:, None] + n_snt[:, None] + trank
            state["event_seq"] = state["event_seq"] + n_snt + \
                timer_valid.sum(-1).astype(jnp.int32)

            # column layout: K sends | T timers | (MB only) 1 READY
            # reinsert, which keeps its ORIGINAL sender/seq
            def cols(*parts):
                return jnp.concatenate(
                    parts[:2 + (1 if MB else 0)], axis=1)

            ins_valid = cols(to_self, timer_valid, rx_keep[:, None])
            ins = {
                "t": cols(deliver_t, pt[:, None] + out.timer_delay,
                          rx_deliver[:, None]),
                "seq": cols(ev_seq, tseq,
                            pseq[:, None]).astype(jnp.int32),
                "kind": cols(
                    jnp.full((H_loc, K), KIND_PACKET, jnp.int32),
                    jnp.full((H_loc, T), KIND_TIMER, jnp.int32),
                    jnp.full((H_loc, 1), KIND_PACKET_READY,
                             jnp.int32)),
                "size": cols(out.send_size,
                             jnp.zeros((H_loc, T), jnp.int32),
                             psize[:, None]),
                "d0": cols(out.send_d0, out.timer_d0, pd0[:, None]),
                "d1": cols(out.send_d1,
                           jnp.zeros((H_loc, T), jnp.int32),
                           pd1[:, None]),
                "src": cols(
                    jnp.broadcast_to(gid[:, None], (H_loc, K)),
                    jnp.broadcast_to(gid[:, None], (H_loc, T)),
                    psrc[:, None]),
            }
            M = K + T + (1 if MB else 0)
            free = state["t"] == INF                            # [H,E]
            slot_order = jnp.argsort(
                jnp.where(free, 0, E) + jnp.arange(E)[None, :],
                axis=-1)                                        # [H,E]
            n_free = free.sum(-1)                               # [H]
            irank = jnp.cumsum(ins_valid, axis=-1) - ins_valid  # [H,M]
            ok = ins_valid & (irank < n_free[:, None]) & (irank < E)
            state["overflow"] = state["overflow"] + \
                (ins_valid & ~ok).sum(-1).astype(jnp.int32)
            dest = jnp.take_along_axis(
                slot_order, jnp.minimum(irank, E - 1), axis=1)  # [H,M]
            dest = jnp.where(ok, dest, E)       # E = out-of-bounds drop

            def bscat(f, vals):
                state[f] = state[f].at[hidx[:, None], dest].set(
                    vals, mode="drop")

            bscat("t", ins["t"])
            bscat("src", ins["src"])
            bscat("seq", ins["seq"])
            bscat("kind", ins["kind"])
            bscat("size", ins["size"])
            bscat("d0", ins["d0"])
            bscat("d1", ins["d1"])

            return state, ob, ob_cnt, runnable.any()

        # ---------------- end-of-round exchange + merge ----------------
        # Two exchange strategies produce the same multiset of rows in
        # the same deterministic arrival order — keyed by
        # (dst_local, okey) where okey = src_gid*OB + outbox slot:
        #
        # all_gather: every shard replicates its whole outbox
        # (bandwidth ∝ H_pad*OB rows per device, (n-1)/n discarded).
        #
        # all_to_all (default): two-phase — sort the local outbox by
        # destination shard, pack each shard's rows into a
        # [n_shards, CAP] buffer, and lax.all_to_all it so each pair
        # of shards exchanges only its own rows (bandwidth ∝ traffic).
        # CAP is derived from the outbox volume (4x headroom for skew);
        # rows beyond CAP are counted per source host in `overflow`
        # and fail the run — never silently lost (SURVEY hard-part #2).
        R = H_loc * OB
        SPAN = H_pad * OB              # exclusive upper bound on okey
        if cfg.exchange == "all_to_all":
            # auto-size for 4x-skewed traffic, floored at one full
            # event-capacity burst toward a single shard; hub-heavy
            # configs that concentrate a whole outbox on one shard
            # should set exchange_capacity (or exchange: all_gather) —
            # overflow is loud, counted separately, and names the knob
            CAP = cfg.exchange_capacity or \
                min(R, max(64, E, (4 * R + n_shards - 1) // n_shards))
        else:
            CAP = 0
        XFIELDS = ("t", "dst", "src", "seq", "size", "d0", "d1")

        def _rows_all_gather(state, ob):
            G = H_pad * OB
            rows = {f: lax.all_gather(ob[f], AXIS).reshape(G)
                    for f in XFIELDS}
            # gather order is gid-major: row index == src_gid*OB + slot
            return state, rows, jnp.arange(G, dtype=jnp.int64)

        def _rows_all_to_all(state, ob, my_shard):
            slot = jnp.broadcast_to(
                jnp.arange(OB, dtype=jnp.int64)[None, :], (H_loc, OB))
            flat = {f: ob[f].reshape(R) for f in XFIELDS}
            flat["okey"] = (ob["src"].astype(jnp.int64) * OB
                            + slot).reshape(R)
            valid = flat["t"] < INF
            ds = jnp.where(valid, flat["dst"] // H_loc, n_shards)
            perm = jnp.argsort(ds.astype(jnp.int64) * SPAN
                               + jnp.where(valid, flat["okey"], 0))
            sds = ds[perm]
            idx = jnp.arange(R, dtype=jnp.int64)
            is_new = jnp.concatenate([jnp.array([True]),
                                      sds[1:] != sds[:-1]])
            seg_start = lax.associative_scan(
                jnp.maximum, jnp.where(is_new, idx, 0))
            rank = idx - seg_start
            ok = (sds < n_shards) & (rank < CAP)
            lost = (sds < n_shards) & (rank >= CAP)
            # overflow attributed to the SENDING host (it owns sizing),
            # in its own counter so the failure names the right knob
            src_loc = (flat["okey"][perm] // OB).astype(jnp.int32) \
                - my_shard * H_loc
            state["x_overflow"] = state["x_overflow"] + \
                jnp.zeros((H_loc,), jnp.int32).at[
                    jnp.where(lost, src_loc, H_loc)].add(1, mode="drop")

            row = jnp.where(ok, sds, n_shards)   # n_shards = drop row
            col = jnp.where(ok, rank, 0).astype(jnp.int32)

            def pack(f, fillv, dtype):
                base = jnp.full((n_shards, CAP), fillv, dtype)
                return base.at[row, col].set(
                    flat[f][perm].astype(dtype), mode="drop")

            send = {"t": pack("t", INF, jnp.int64),
                    "okey": pack("okey", 0, jnp.int64)}
            for f in ("dst", "src", "seq", "size", "d0", "d1"):
                send[f] = pack(f, 0, jnp.int32)
            rows = {f: lax.all_to_all(v, AXIS, split_axis=0,
                                      concat_axis=0)
                    .reshape(n_shards * CAP)
                    for f, v in send.items()}
            return state, rows, rows.pop("okey")

        def _exchange(state, ob, my_shard):
            if cfg.exchange == "all_to_all":
                state, rows, okey = _rows_all_to_all(state, ob, my_shard)
                G = n_shards * CAP
            else:
                state, rows, okey = _rows_all_gather(state, ob)
                G = H_pad * OB

            gt = rows["t"]
            gdst = rows["dst"]
            valid = gt < INF
            dshard = gdst // H_loc
            mine = valid & (dshard == my_shard)
            dloc = gdst % H_loc

            # deterministic arrival order: (dst, src_gid*OB + slot) —
            # independent of mesh shape AND exchange strategy
            skey = jnp.where(mine,
                             dloc.astype(jnp.int64) * SPAN + okey, IMAX)
            perm = jnp.argsort(skey)
            sdloc = dloc[perm]
            smine = mine[perm]

            idx = jnp.arange(G, dtype=jnp.int64)
            is_new = jnp.concatenate([jnp.array([True]),
                                      sdloc[1:] != sdloc[:-1]])
            seg_start = lax.associative_scan(
                jnp.maximum, jnp.where(is_new, idx, 0))
            rank = idx - seg_start

            keep = smine & (rank < IN)
            # per-host overflow for arrivals beyond IN
            lost = smine & (rank >= IN)
            state["overflow"] = state["overflow"] + \
                jnp.zeros((H_loc,), jnp.int32).at[sdloc].add(
                    lost.astype(jnp.int32), mode="drop")

            row = jnp.where(keep, sdloc, H_loc)       # H_loc = drop row
            col = jnp.where(keep, rank, 0).astype(jnp.int32)

            def scatter_in(f, fill, dtype):
                base = jnp.full((H_loc, IN), fill, dtype)
                return base.at[row, col].set(
                    rows[f][perm].astype(dtype), mode="drop")

            inc_t = scatter_in("t", INF, jnp.int64)
            inc = {
                "t": inc_t,
                "src": scatter_in("src", 0, jnp.int32),
                "seq": scatter_in("seq", 0, jnp.int32),
                "kind": jnp.where(inc_t < INF, jnp.int32(KIND_PACKET),
                                  jnp.int32(0)),
                "size": scatter_in("size", 0, jnp.int32),
                "d0": scatter_in("d0", 0, jnp.int32),
                "d1": scatter_in("d1", 0, jnp.int32),
            }

            # merge: lexicographic sort of [heap | incoming] rows by
            # (time, src, seq); first E slots survive
            cat = {f: jnp.concatenate([state[f], inc[f]], axis=1)
                   for f in HEAP_FIELDS}
            k2 = key2_of(cat["src"], cat["seq"])
            sorted_ops = lax.sort(
                (cat["t"], k2, cat["src"], cat["seq"], cat["kind"],
                 cat["size"], cat["d0"], cat["d1"]),
                dimension=1, num_keys=2)
            (st, _, ssrc, sseq, skind, ssize, sd0, sd1) = sorted_ops
            state["overflow"] = state["overflow"] + \
                (st[:, E:] < INF).sum(-1).astype(jnp.int32)
            state["t"] = st[:, :E]
            state["src"] = ssrc[:, :E]
            state["seq"] = sseq[:, :E]
            state["kind"] = skind[:, :E]
            state["size"] = ssize[:, :E]
            state["d0"] = sd0[:, :E]
            state["d1"] = sd1[:, :E]
            return state

        # ---------------- one round (window) ---------------------------
        # A window may take several phases: each phase pops until every
        # host is drained below win_end OR outbox-blocked, exchanges,
        # and the window only advances when no host has events left
        # under the barrier. Phase count is data-dependent but the
        # predicate is a collective, so all shards agree.
        def _round(state, win_end, gid, my_shard, host_vertex, lat, rel):
            def _phase(state):
                ob = {
                    "t": jnp.full((H_loc, OB), INF, jnp.int64),
                    "dst": jnp.zeros((H_loc, OB), jnp.int32),
                    "src": jnp.zeros((H_loc, OB), jnp.int32),
                    "seq": jnp.zeros((H_loc, OB), jnp.int32),
                    "size": jnp.zeros((H_loc, OB), jnp.int32),
                    "d0": jnp.zeros((H_loc, OB), jnp.int32),
                    "d1": jnp.zeros((H_loc, OB), jnp.int32),
                }
                ob_cnt = jnp.zeros((H_loc,), jnp.int32)
                carry = (state, ob, ob_cnt,
                         (state["t"].min(axis=-1) < win_end).any())
                carry = lax.while_loop(
                    lambda c: c[3],
                    lambda c: _step(c, win_end, gid, host_vertex, lat,
                                    rel),
                    carry)
                state2, ob, _, _ = carry
                return _exchange(state2, ob, my_shard)

            def more(state):
                return _axis_min(
                    jnp.where(state["t"].min(axis=-1) < win_end,
                              jnp.int64(0), jnp.int64(1)).min()) == 0

            state = _phase(state)
            state, _ = lax.while_loop(
                lambda c: c[1],
                lambda c: (lambda s: (s, more(s)))(_phase(c[0])),
                (state, more(state)))
            return state

        # ---------------- full run ------------------------------------
        # cross-shard min via all_gather: some TPU AOT toolchains lower
        # only Sum all-reduces, so pmin is expressed as gather+min
        # (identical result; the gathered vector is tiny: one scalar
        # per device)
        def _axis_min(x):
            return lax.all_gather(jnp.reshape(x, (1,)), AXIS).min()

        def _run_shard(state, host_vertex, lat, rel, stop, final_stop):
            # `stop` is where THIS invocation pauses (a traced scalar,
            # so one compiled program serves every slice length);
            # `final_stop` is the simulation end that window boundaries
            # clamp to — pausing at heartbeat boundaries therefore
            # yields the EXACT window sequence of an unsegmented run
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)

            def next_time(state):
                return _axis_min(state["t"].min())

            def cond(c):
                state, nxt, rounds = c
                return (nxt < stop) & (rounds < cfg.max_rounds)

            def body(c):
                state, nxt, rounds = c
                win_end = jnp.minimum(nxt + LOOKAHEAD, final_stop)
                state = _round(state, win_end, gid, my_shard,
                               host_vertex, lat, rel)
                return state, next_time(state), rounds + 1

            state, _, rounds = lax.while_loop(
                cond, body, (state, next_time(state), jnp.int64(0)))
            return state, rounds

        # one window as a standalone jitted step (also used by
        # __graft_entry__; works on any mesh size including 1)
        def _one_round(state, win_end, host_vertex, lat, rel):
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)
            state = _round(state, win_end, gid, my_shard,
                           host_vertex, lat, rel)
            nxt = _axis_min(state["t"].min())
            return state, nxt

        spec_keys = ("t", "src", "seq", "kind", "size", "d0", "d1",
                     "event_seq", "packet_seq", "app_seq", "app",
                     "n_exec", "n_sent", "n_drop", "n_deliv",
                     "overflow", "x_overflow", "chk") + \
            (NIC_KEYS if MB else ())
        specs = {k: self._shard_spec for k in spec_keys}
        repl = self._repl_spec
        self._run = jax.jit(jax.shard_map(
            _run_shard, mesh=self.mesh,
            in_specs=(specs, repl, repl, repl, repl, repl),
            out_specs=(specs, repl),
            check_vma=False,
        ))
        self._round_step = jax.jit(jax.shard_map(
            _one_round, mesh=self.mesh,
            in_specs=(specs, repl, repl, repl, repl),
            out_specs=(specs, repl),
            check_vma=False,
        ))

    # ------------------------------------------------------------------
    def run(self, state: dict, stop: Optional[int] = None,
            final_stop: Optional[int] = None):
        """Run to `stop` (default config.stop_time); returns
        (final_state, rounds) on device. Both stops are runtime
        scalars — every slice length reuses one compiled program.
        `final_stop` (default = stop) is the window-clamping horizon:
        pass the simulation end when pausing at intermediate
        boundaries (heartbeats) so the window sequence — and thus the
        trace — is identical to an unsegmented run."""
        repl = NamedSharding(self.mesh, self._repl_spec)
        hv = jax.device_put(jnp.asarray(self.host_vertex), repl)
        lat = jax.device_put(jnp.asarray(self.latency), repl)
        rel = jax.device_put(jnp.asarray(self.reliability), repl)
        stop_v = jnp.int64(self.config.stop_time if stop is None
                           else stop)
        final_v = stop_v if final_stop is None else jnp.int64(final_stop)
        return self._run(state, hv, lat, rel, stop_v, final_v)
