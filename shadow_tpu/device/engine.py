"""The device simulation engine.

One jitted program advances the whole simulation: an outer while_loop
over conservative time windows (controller_run's round loop,
reference controller.c:392-424), an inner while_loop that pops and
executes at most one event per host per iteration (preserving each
host's serial (time, src, seq) order — the per-host sequentiality
invariant of event.c:109-152 — while all hosts advance in parallel),
and a per-round collective packet exchange:

  pop min event/host -> app handle (batched) -> counter-RNG drop rolls
  + latency gathers (worker_sendPacket semantics, worker.c:520-579) ->
  outbox -> collective exchange over the mesh axis -> merge into
  destination heaps (causality bump, host_single.c:174-220) -> pmin
  next event time.

Determinism: every stochastic decision is keyed by stable integer ids
(threefry counters), per-host event heaps merge by full-key sort, and
incoming packets are ordered by (src_gid, outbox column) — so results
are bit-identical across mesh shapes AND match the CPU serial oracle's
per-host schedule (verified by trace checksums in tests).

v2 data-structure design — NO SCATTERS. TPU scatters with computed
indices serialize per element and crash on multi-million-element
operands, so every hot-path op here is a sort, a contiguous
dynamic-slice, or a take: heaps are per-host SORTED rows popped by a
head cursor; each pop iteration appends its sends to a contiguous
per-iteration column block of the outbox; flushes regroup rows with
one flat sort by (dst, okey) + searchsorted segment starts + windowed
takes, and merge with one per-row lexicographic sort of
[live heap | incoming]. Everything is static-shape; the only dynamism
is while_loop trip counts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu._jax import jax, jnp, shard_map
from jax import lax
from jax.sharding import (
    Mesh,
    NamedSharding,
    PartitionSpec,
    PartitionSpec as P,
)

from shadow_tpu import simtime
from shadow_tpu.core.event import (
    KIND_BOOT,
    KIND_PACKET,
    KIND_STOP,
    KIND_TIMER,
)
from shadow_tpu.device import prng
from shadow_tpu.device.apps import DeviceApp
from shadow_tpu.device.netsem import packet_drop_mask
from shadow_tpu.topology import hierarchy
from shadow_tpu.utils.rng import PURPOSE_APP, PURPOSE_PACKET_DROP

from shadow_tpu.utils.checksum import (
    CHK_KIND,
    CHK_MUL,
    CHK_SEQ,
    CHK_SRC,
    MASK63,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("device")

INF = np.int64(1) << np.int64(62)
# reserved outbox time marker: a drop-rolled send carried only for the
# per-path packet histogram (never exchanged or delivered)
DROP_T = INF - 1
IMAX = np.int64(np.iinfo(np.int64).max)

AXIS = "hosts"

NIC_KEYS = ("tx_free", "rx_free", "cd_fa", "cd_next", "cd_cnt",
            "cd_last", "cd_drop")

# on-device invariant audit (EngineConfig.audit / experimental.
# state_audit): per-host "health word" bitmask accumulated by cheap
# reductions compiled into the round program. A nonzero word marks the
# state corrupted — the supervisor (device/supervise.py) refuses to
# checkpoint it, so a bad state is never the one a run resumes from.
AUD_HEAP = 1       # heap rows out of (t, key) order, or head out of
                   # [0, E] — the pop loop would replay/skip events
AUD_CLOCK = 2      # a host popped an event earlier than one it
                   # already executed (per-host clock monotonicity)
AUD_COUNTER = 4    # a cumulative counter went negative (i32 wrap or
                   # corrupted arithmetic)
AUD_CONSERVE = 8   # event-row conservation broke: rows produced !=
                   # rows executed + rows live in heaps + rows counted
                   # lost — the exchange dropped something silently
AUD_KEYS = ("aud", "aud_t", "aud_tx")


@dataclass
class EngineConfig:
    n_hosts: int                 # real hosts
    event_capacity: int = 64
    outbox_capacity: int = 32
    lookahead: int = simtime.SIMTIME_ONE_MILLISECOND
    stop_time: int = simtime.SIMTIME_ONE_SECOND
    bootstrap_end: int = 0
    seed: int = 1
    max_rounds: int = 1 << 62    # safety valve
    # cross-shard packet exchange: "all_to_all" moves only each
    # (src shard, dst shard) pair's rows over ICI (sort by
    # destination shard, then lax.all_to_all on [n_shards, CAP]
    # buffers); "all_gather" replicates every shard's whole outbox
    # (simple, bandwidth ∝ H_pad*OB per device); "two_phase" is the
    # hierarchical schedule (direct-connect style, arxiv 2309.13541):
    # shards factor into groups of g = capacity.group_split(S)[0],
    # phase 1 exchanges intra-group by destination RANK, phase 2
    # forwards inter-group — per-phase buffers aggregate over whole
    # rank/group sets, so one hot pair borrows headroom from quiet
    # pairs instead of padding every pair to the worst.
    exchange: str = "all_to_all"
    # per (src shard, dst shard) row capacity; 0 = auto-size from the
    # outbox volume with 4x headroom for skewed traffic. Overflow is
    # counted per source host and fails the run, never silently lost.
    # Under two_phase this is the PHASE-1 per-peer buffer (rows per
    # destination rank, summed over destination groups).
    exchange_capacity: int = 0
    # two_phase phase-2 per-peer buffer (rows one intermediate
    # forwards to one destination group); 0 = auto-size. Unused by
    # the other exchange variants. Overflow is counted against the
    # ORIGINAL sending host (cross-shard: one scalar collective
    # decides the loss branch, then a psum'd histogram lands each
    # lost row on its sender's shard) and fails the run.
    exchange_capacity2: int = 0
    # per-host arrivals accepted per flush (merge width = E + this);
    # 0 = event_capacity. Overflow is counted and fails the run.
    exchange_in_capacity: int = 0
    # per-host outbox rows that survive to the flush's flat sort:
    # the outbox is mostly empty (each of B iterations reserves its
    # own column block), so compacting each host's row to its first
    # `outbox_compact` valid entries before the GLOBAL sort shrinks
    # the sort from H*OB to H*compact rows. 0 = off. Too small is
    # LOUD (x_overflow, attributed to the sending host).
    outbox_compact: int = 0
    # bandwidth + CoDel for raw sends (host/model_nic.py's fluid NIC):
    # TX serialization at send, RX serialization + event-driven CoDel
    # at delivery via a KIND_PACKET -> KIND_PACKET_READY two-stage pop
    model_bandwidth: bool = False
    # per-path packet counters (topology_incrementPathPacketCounter,
    # ref topology.c:1983): a [V,V] histogram of SENT packets
    # (drop-rolled included) accumulated at flush time. Costs one
    # extra flat sort per flush; requires V*V <= 65536.
    count_paths: bool = False
    # network-judgment placement: True = judge the whole phase's
    # outbox once at flush (fewer ops in the serial pop loop — the
    # right trade on TPU, where per-op dispatch in the while body
    # dominates); False = judge each pop iteration in-step (the right
    # trade on one CPU core, where the loop is cheap and the batched
    # judge's extra memory traffic is not). None = auto by platform.
    # Traces are bit-identical either way (tests pin both).
    judge_hoist: Optional[bool] = None
    # flush merge strategy: True = ONE global double sort of
    # [outbox rows | heap rows] keyed by (dst host, time, src/seq)
    # lands every row at its [host, slot] heap position with zero
    # gathers — on TPU a 500k-element take costs ~10 ms while a
    # 6-operand 840k-row sort costs ~3 ms, so the window path's
    # seg_take + take_along_axis recovery (5 + 3 takes per flush) IS
    # the round cost there. False = the flat-sort + per-host window +
    # row-merge path (fewer/narrower sorts; the right trade on one
    # CPU core where sorts are the cost and takes are cheap).
    # None = auto by platform. Traces are bit-identical either way
    # (tests pin both).
    merge_global: Optional[bool] = None
    # pop head reads: True = one-hot masked reductions (compare a
    # column iota against head, select, reduce over E) — pure
    # elementwise+reduce VPU work, no gather; the pop loop's
    # take_along_axis head reads (5 operand takes + the loop-cond
    # take per iteration) are the same ~ms-class TPU gathers the
    # gatherless flush removed. False = take_along_axis (cheaper on
    # one CPU core, where gathers are a pointer chase and the E-wide
    # reduction is real work). None = auto by platform. Traces are
    # bit-identical either way (tests pin both).
    pop_onehot: Optional[bool] = None
    # topology-table lookups (lat[srcv,dstv] / rel[srcv,dstv] in the
    # hoisted judge): True = one-hot masked sums over the V*V table
    # (unrolled; only legal for V*V <= 128) — no gather; False =
    # indexed gather. None = False everywhere until the on-chip
    # micro (scripts/tpu_micro.py --variant 4) decides. Selection is exact
    # (single nonzero term), so traces are bit-identical either way.
    table_onehot: Optional[bool] = None
    # on-device invariant audit (experimental.state_audit): compile a
    # per-host health word of cheap reductions into the round program
    # — heap order, per-host clock monotonicity, counter
    # non-negativity, and event-row conservation across the exchange
    # (see the AUD_* bits above). Off by default: the audited program
    # carries three extra state leaves and one extra collective per
    # round; with audit off the compiled program is byte-identical to
    # an un-audited build. Traces are bit-identical either way (the
    # audit only reads existing values).
    audit: bool = False


class DeviceEngine:
    """Builds and runs the jitted sharded simulation program."""

    def __init__(self, config: EngineConfig, app: DeviceApp,
                 host_vertex: np.ndarray, latency_ns: np.ndarray,
                 reliability: np.ndarray,
                 mesh: Optional[Mesh] = None,
                 bw_up_bits: Optional[np.ndarray] = None,
                 bw_down_bits: Optional[np.ndarray] = None,
                 epoch_times: Optional[np.ndarray] = None,
                 ensemble=None):
        self.config = config
        self.app = app
        # ensemble worlds (shadow_tpu/ensemble/spec.py EnsembleWorlds,
        # duck-typed to avoid the import cycle): stacked per-replica
        # (latency, reliability, epoch_times, seed keys). When set,
        # replica 0 is the engine's base world (standard program,
        # fingerprints) and _build_program additionally compiles the
        # vmapped R-replica campaign program. Compile-time branch
        # flags (ALL_REL1, the i32 latency bound) are evaluated over
        # the WHOLE stack — one lossy replica must not let the
        # lossless fast path skip every replica's drop rolls.
        self.ensemble = ensemble
        if ensemble is not None:
            # the stacked tables arrive i32/f32 — build_worlds
            # (ensemble/spec.py) enforces the i32 latency bound over
            # every replica before the cast, so no re-check here.
            # Hierarchical worlds stack each factored leaf [R,...]
            # instead of one [R,(T,)V,V] matrix.
            if isinstance(ensemble.latency, tuple):
                latency_ns = tuple(np.asarray(p[0])
                                   for p in ensemble.latency)
                reliability = tuple(np.asarray(p[0])
                                    for p in ensemble.reliability)
            else:
                latency_ns = np.asarray(ensemble.latency[0])
                reliability = np.asarray(ensemble.reliability[0])
            epoch_times = np.asarray(ensemble.epoch_times[0])
        # d2 survivor bitmasks are one uint32 word: a larger train
        # would silently lose packets (ADVICE r3 #2 — fail loudly)
        assert getattr(app, "max_train", 1) <= 32, \
            f"app.max_train={app.max_train} exceeds the 32-bit " \
            "survivor mask"
        if mesh is None:
            devs = jax.devices()
            mesh = Mesh(np.array(devs), (AXIS,))
        self.mesh = mesh
        self.n_shards = mesh.devices.size
        H = config.n_hosts
        self.H_pad = int(math.ceil(H / self.n_shards) * self.n_shards)
        self.H_loc = self.H_pad // self.n_shards

        # topology matrices are stored STACKED per fault epoch
        # [T,V,V] (shadow_tpu/faults.py epoch table) when a fault
        # schedule exists; the fault-free single epoch keeps the
        # plain [V,V] matrices so the compiled program (and its
        # gathers) is byte-identical to the pre-fault engine.
        # Under `network.topology.representation: hierarchical` the
        # matrices are replaced by factored leaf TUPLES
        # (cluster [C,C], cluster-of [V], access [V], self [V]) —
        # hierarchy.HierTables lat_parts()/rel_parts() — with the
        # epoch stack as a leading [T] axis on every leaf; the
        # per-packet lookup becomes hierarchy.gather_parts.
        hier = isinstance(latency_ns, tuple)
        if hier:
            latency_ns = tuple(np.asarray(p) for p in latency_ns)
            reliability = tuple(np.asarray(p) for p in reliability)
            n_epochs = latency_ns[0].shape[0] \
                if latency_ns[0].ndim == 3 else 1
        else:
            latency_ns = np.asarray(latency_ns)
            reliability = np.asarray(reliability)
            n_epochs = latency_ns.shape[0] if latency_ns.ndim == 3 \
                else 1
        if epoch_times is None:
            epoch_times = np.zeros(n_epochs, dtype=np.int64)
        self.epoch_times = np.asarray(epoch_times, dtype=np.int64)
        if len(self.epoch_times) != n_epochs:
            raise ValueError(
                f"epoch_times has {len(self.epoch_times)} entries but "
                f"the latency table has {n_epochs} epochs")
        if n_epochs == 1:
            if hier and latency_ns[0].ndim == 3:
                latency_ns = tuple(p[0] for p in latency_ns)
                reliability = tuple(p[0] for p in reliability)
            elif not hier and latency_ns.ndim == 3:
                latency_ns = latency_ns[0]
                reliability = reliability[0]
        if hier:
            if latency_ns[0].ndim == 3:
                over = max(hierarchy.max_composed_latency(
                    tuple(p[e] for p in latency_ns))
                    for e in range(n_epochs))
            else:
                over = hierarchy.max_composed_latency(latency_ns)
            if over > np.iinfo(np.int32).max:
                raise ValueError(
                    "path latencies above ~2.1 s don't fit the "
                    "i32 device latency matrix")
        elif (latency_ns > np.iinfo(np.int32).max).any():
            raise ValueError("path latencies above ~2.1 s don't fit the "
                             "i32 device latency matrix")
        self.host_vertex = np.zeros(self.H_pad, dtype=np.int32)
        self.host_vertex[:H] = host_vertex
        if hier:
            # int leaves (cluster/access/self latency + the i32
            # cluster-of vector) ride i32; reliability leaves f32
            # except the shared cluster-of index vector
            self.latency = tuple(np.asarray(p).astype(np.int32)
                                 for p in latency_ns)
            self.reliability = tuple(
                np.asarray(p).astype(
                    np.int32 if i == 1 else np.float32)
                for i, p in enumerate(reliability))
            self.n_vertices = int(self.latency[1].shape[-1])
        else:
            self.latency = latency_ns.astype(np.int32)
            self.n_vertices = int(latency_ns.shape[-1])
            self.reliability = reliability.astype(np.float32)
        if config.count_paths and self.n_vertices ** 2 > 65536:
            raise ValueError(
                "count_paths needs V*V <= 65536 (histogram boundaries "
                f"scale with V^2; this graph has V={self.n_vertices})")
        self.seed_pair = prng.seed_key(config.seed)
        # model-NIC bandwidths (bits/s), padded; 1 Gbit default keeps
        # the padded hosts' arithmetic harmless
        self.bw_up = np.full(self.H_pad, 10**9, dtype=np.int64)
        self.bw_down = np.full(self.H_pad, 10**9, dtype=np.int64)
        if bw_up_bits is not None:
            self.bw_up[:H] = np.maximum(1, bw_up_bits)
        if bw_down_bits is not None:
            self.bw_down[:H] = np.maximum(1, bw_down_bits)

        self._shard_spec = P(AXIS)
        self._repl_spec = P()
        self._heap_builder = None       # jitted lazily by init_state
        # persistent AOT compile cache (device/aotcache.py): the
        # runner attaches one shared AotCache after construction;
        # run()/run_ensemble()/profile() then dispatch each program
        # through a cached (or freshly AOT-compiled + stored)
        # executable resolved on first use. The executables live in
        # _aot_exec — the _run/_pop_phase/... jit attributes stay
        # untouched so tooling (and tests) can still .lower() them.
        # None = plain lazy jit.
        self.aot_cache = None
        self._aot_exec: dict = {}
        self._build_program()

    # ------------------------------------------------------------------
    # state construction (host side)
    # ------------------------------------------------------------------
    def init_state(self, starts: list[tuple]) -> dict:
        """starts: (host_id, start_time, stop_time|-1[, proc_idx]) per
        process, in registration order — seq consumption mirrors
        Manager.boot_hosts (device configs are single-process/host, so
        the index is ignored here).

        v2 state layout (scatter-free engine): per-host event heaps are
        SORTED rows of five packed i64 arrays —
          ht [H,E] time (INF = empty slot),
          hk [H,E] src<<32|seq  (the deterministic tiebreak key),
          hm [H,E] kind<<32|size,
          hv [H,E] d0<<32|d1,
          hw [H,E] d2 (train survivor bitmask; 0 otherwise),
        plus a per-host `head` cursor: slots < head are consumed; the
        next event of host h is always column head[h]. Rows re-sort
        only at flush (one lax.sort per phase) — no scatters anywhere.

        The [H,E] heaps are BUILT ON DEVICE from [H] boot/stop vectors:
        over a tunneled TPU the heap upload would otherwise dominate
        small-slice wall time (~20 MB at the 10k rung, ~250 MB at
        tor_large; the vectors are a few hundred KB)."""
        H, E = self.H_pad, self.config.event_capacity
        if E < 2:
            raise ValueError("event_capacity must be >= 2 (boot+stop)")
        t0s = np.full(H, INF, dtype=np.int64)
        t1s = np.full(H, INF, dtype=np.int64)
        event_seq = np.zeros(H, dtype=np.int32)
        as_arrays = getattr(starts, "as_arrays", None)
        if as_arrays is not None:
            # columnar fast path (host/plane.py StartColumns): the
            # boot/stop vectors are already [n] aligned columns — fill
            # by slice instead of a million-iteration loop. One
            # process per host by construction.
            s0, s1 = as_arrays()
            n = s0.shape[0]
            bad = np.flatnonzero((s1 >= 0) & (s1 < s0))
            if bad.size:
                h = int(bad[0])
                raise ValueError(
                    f"host {h}: stop_time {int(s1[h])} precedes "
                    f"start_time {int(s0[h])}")
            has_stop = s1 >= 0
            t0s[:n] = s0
            t1s[:n] = np.where(has_stop, s1, INF)
            event_seq[:n] = np.where(has_stop, 2, 1).astype(np.int32)
        else:
            for entry in starts:
                h, t_start, t_stop = entry[0], entry[1], entry[2]
                if t0s[h] != INF:
                    raise ValueError(
                        f"host {h}: multiple processes per host are "
                        "not supported by the device engine")
                t0s[h] = t_start
                event_seq[h] = 1
                if t_stop is not None and t_stop >= 0:
                    if t_stop < t_start:
                        raise ValueError(
                            f"host {h}: stop_time {t_stop} precedes "
                            f"start_time {t_start}")
                    t1s[h] = t_stop
                    event_seq[h] = 2

        shard = NamedSharding(self.mesh, self._shard_spec)

        if self._heap_builder is None:
            def _build(t0, t1):
                hid = jnp.arange(H, dtype=jnp.int64)
                padt = jnp.full((H, E - 2), INF, jnp.int64)
                ht = jnp.concatenate([t0[:, None], t1[:, None], padt],
                                     1)
                # rows are (t, src, seq)-sorted by construction: boot
                # (seq 0) precedes stop (seq 1), validated host-side
                hk = jnp.concatenate([
                    jnp.where(t0 < INF, hid << 32, IMAX)[:, None],
                    jnp.where(t1 < INF, (hid << 32) | 1,
                              IMAX)[:, None],
                    jnp.full((H, E - 2), IMAX, jnp.int64)], 1)
                padz = jnp.zeros((H, E - 2), jnp.int64)
                hm = jnp.concatenate([
                    jnp.where(t0 < INF,
                              jnp.int64(KIND_BOOT) << 32, 0)[:, None],
                    jnp.where(t1 < INF,
                              jnp.int64(KIND_STOP) << 32, 0)[:, None],
                    padz], 1)
                z2 = jnp.zeros((H, E), jnp.int64)
                return ht, hk, hm, z2, z2

            self._heap_builder = jax.jit(_build,
                                         out_shardings=(shard,) * 5)

        ht, hk, hm, hv, hw = self._heap_builder(
            jax.device_put(jnp.asarray(t0s), shard),
            jax.device_put(jnp.asarray(t1s), shard))

        zeros_i32 = np.zeros(H, dtype=np.int32)
        small = {
            "head": zeros_i32.copy(),
            "event_seq": event_seq,
            "packet_seq": zeros_i32.copy(),
            "app_seq": zeros_i32.copy(),
            "app": np.asarray(self.app.init_state(H), dtype=np.int32),
            "n_exec": zeros_i32.copy(),
            "n_sent": zeros_i32.copy(),
            "n_drop": zeros_i32.copy(),
            "n_deliv": zeros_i32.copy(),
            "overflow": zeros_i32.copy(),
            "x_overflow": zeros_i32.copy(),
            "chk": np.zeros(H, dtype=np.int64),
            # occupancy telemetry (device/capacity.py consumes these):
            # per-segment high-water marks accumulated with reductions
            # only — never sorts — so they ride every run for free.
            #   occ_heap  [H]  max live heap rows per host (post-merge)
            #   occ_ob    [H]  max exchangeable outbox rows per phase
            #   occ_in    [H]  max arrivals accepted per flush
            "occ_heap": zeros_i32.copy(),
            "occ_ob": zeros_i32.copy(),
            "occ_in": zeros_i32.copy(),
            #   occ_x     [S,S] max rows per (src shard, dst shard)
            #   occ_trips [S]  max pop-loop iterations per phase
            #   occ_phases[S]  total flushes executed
            "occ_x": np.zeros((self.n_shards, self.n_shards),
                              dtype=np.int32),
            "occ_trips": np.zeros(self.n_shards, dtype=np.int32),
            "occ_phases": np.zeros(self.n_shards, dtype=np.int32),
        }
        if self.config.audit:
            # invariant-audit leaves (AUD_* bits above):
            #   aud    [H] the health word (0 = every invariant held)
            #   aud_t  [H] last popped event time (clock monotonicity)
            #   aud_tx [H] cumulative event rows this host produced —
            #              seeded with the boot/stop rows so the
            #              conservation identity holds from round 0
            small["aud"] = zeros_i32.copy()
            small["aud_t"] = np.zeros(H, dtype=np.int64)
            small["aud_tx"] = ((t0s != INF).astype(np.int64)
                               + (t1s != INF).astype(np.int64))
        if self.config.count_paths:
            V = self.n_vertices
            small["path_cnt"] = np.zeros((self.n_shards, V * V),
                                         dtype=np.int64)
        if self.config.model_bandwidth:
            # model-NIC scalars (host/model_nic.py ModelNic twin)
            for k in NIC_KEYS:
                small[k] = np.zeros(H, dtype=np.int64)
        state = {k: jax.device_put(jnp.asarray(v), shard)
                 for k, v in small.items()}
        state.update(ht=ht, hk=hk, hm=hm, hv=hv, hw=hw)
        return state

    # ------------------------------------------------------------------
    # the jitted program (v2: scatter-free)
    # ------------------------------------------------------------------
    # TPU scatters with computed indices serialize per element (~1.4 us
    # each) and crash outright on multi-million-element operands; v1's
    # per-step heap/outbox scatters made iteration cost scale with H
    # and the exchange scatters killed the 10k-host rung. v2 uses only
    # TPU-fast primitives, all O(H)-parallel:
    #   pops     — the heap rows are kept sorted; the next event is
    #              column head[h] (take_along_axis, no argmin);
    #   appends  — each iteration owns a CONTIGUOUS column block of
    #              the outbox (lax.dynamic_update_slice at blk*M);
    #   exchange — one flat lax.sort by dst*SPAN+okey, segment starts
    #              via searchsorted, arrivals via contiguous takes;
    #   merge    — one per-row lax.sort of [live heap | incoming].
    def _build_program(self):
        cfg = self.config
        app = self.app
        if cfg.exchange not in ("all_to_all", "all_gather",
                                "two_phase"):
            # "auto" resolves in the runner (capacity.choose_exchange
            # over the OCC record) — the engine only compiles concrete
            # schedules
            raise ValueError(
                f"EngineConfig.exchange={cfg.exchange!r}: the engine "
                "needs a concrete variant (all_to_all | all_gather | "
                "two_phase); 'auto' is resolved by the runner")
        E = cfg.event_capacity
        K = app.max_sends
        T = app.max_timers
        D = max(1, app.max_draws)
        H_loc, H_pad = self.H_loc, self.H_pad
        n_shards = self.n_shards
        LOOKAHEAD = np.int64(max(1, cfg.lookahead))
        BOOT_END = np.int64(cfg.bootstrap_end)
        MB = bool(cfg.model_bandwidth)

        # outbox layout: each pop iteration owns M_out columns (K sends
        # + T timers + the model-NIC READY reinsert); a phase runs at
        # most B iterations between flushes
        C = max(1, getattr(app, "max_train", 1))
        CP = bool(cfg.count_paths)
        V = self.n_vertices
        # burst de-skew: an app may declare that its STATELESS
        # responder hosts (app.burst_mask) can pop up to P consecutive
        # in-window KIND_PACKET events per iteration, each answered on
        # its own send lane — a busy hub no longer holds every lane
        # hostage for N serial iterations (BASELINE round-3 diagnosis)
        P = max(1, getattr(app, "burst_pops", 1))
        if P > 1 and MB:
            # the fluid-NIC CoDel/tx state is sequential per event:
            # degrade to single pops rather than failing a config that
            # worked without bursts
            log.info("burst_pops=%d disabled: model_bandwidth needs "
                     "sequential per-event NIC state", P)
            P = 1
        if P > 1 and K != 1:
            raise ValueError("burst_pops requires max_sends == 1")
        K_eff = P if P > 1 else K
        M_out = K_eff + T + (1 if MB else 0)
        B = max(1, cfg.outbox_capacity // M_out)
        OB = B * M_out
        # per-flush arrivals per host: the merge width is E + IN (x2
        # on the multi-shard bypass path), so a tight IN is a
        # first-order flush win; too small is LOUD (overflow counter)
        IN = cfg.exchange_in_capacity or E
        SPAN = np.int64(H_pad) * OB   # okey < SPAN
        from shadow_tpu.device.capacity import (
            dense_auto_cap,
            group_split,
        )
        TP_G, TP_NG = (group_split(n_shards)
                       if cfg.exchange == "two_phase" else
                       (1, n_shards))
        if cfg.exchange == "all_to_all" and n_shards > 1:
            CAP = cfg.exchange_capacity or \
                dense_auto_cap(H_loc, OB, E, n_shards)
            CAP2 = 0
        elif cfg.exchange == "two_phase" and n_shards > 1:
            # phase-1 buffers aggregate a sender's rows per dst RANK
            # (over all groups); phase-2 buffers aggregate a whole
            # group's forwards per dst group. The blind auto sizes
            # assume 4x-of-balanced skew exactly like the direct
            # CAP's; the planner replaces both with measured sums.
            R = H_loc * OB
            CAP = cfg.exchange_capacity or \
                min(R, max(64, E, (4 * R + TP_G - 1) // TP_G))
            CAP2 = cfg.exchange_capacity2 or \
                min(TP_G * CAP,
                    max(64, E,
                        (4 * R * TP_G + n_shards - 1) // n_shards))
        else:
            CAP = CAP2 = 0

        # Judgment hoist: without the fluid NIC, a send's network
        # judgment (latency gather + drop rolls + causality bump) does
        # not feed back into the pop loop — the only in-loop consumer
        # is the dirty bit, which needs just the host's SELF-latency.
        # So the while-body writes raw send rows (depart time, train
        # count, live mask) and the whole phase is judged ONCE over
        # the outbox at flush time (_judge_outbox): ~40% fewer ops in
        # the serial loop, identical keys and values, bit-identical
        # traces. The fluid NIC keeps the legacy in-step path (its
        # tx/rx buckets are sequential per event).
        platform = self.mesh.devices.flat[0].platform
        HOIST = (not MB) and (cfg.judge_hoist
                              if cfg.judge_hoist is not None
                              else platform == "tpu")
        # gatherless flush merge (see EngineConfig.merge_global)
        MERGE_GLOBAL = (cfg.merge_global
                        if cfg.merge_global is not None
                        else platform == "tpu")
        # gatherless pop head reads (see EngineConfig.pop_onehot)
        POP_ONEHOT = (cfg.pop_onehot
                      if cfg.pop_onehot is not None
                      else platform == "tpu")
        # on-device invariant audit (see the AUD_* bits): every audit
        # op sits behind this flag so the un-audited program is
        # byte-identical to a pre-audit build
        AUDIT = bool(cfg.audit)
        # fault epochs: the [T] epoch start times are part of the
        # compiled schedule exactly like the capacities, but ride the
        # program as a TRACED [T] vector (the `wrld` tuple below) so
        # the vmapped ensemble program can vary them per replica;
        # each lookup selects its epoch by SEND time with a
        # comparison count — the vectorized twin of the CPU model's
        # binary search (faults.FaultTable.epoch_of). T == 1 (no
        # faults) keeps the [V,V] matrices and the original 2-operand
        # gather, so the fault-free program is byte-identical.
        T_EP = len(self.epoch_times)

        def _ep_of(t, ept):
            return (t[..., None] >= ept).sum(-1).astype(jnp.int32) - 1

        # hierarchical representation: world tables are factored leaf
        # tuples; every lookup goes through the shared two-level
        # gather (topology/hierarchy.py gather_parts)
        HIER = isinstance(self.latency, tuple)

        def _tbl(tab, t, sv, dv, ept):
            """Topology-table gather at send time t; tab is [V,V]
            (single epoch) or [T,V,V] (fault schedule) — or, under
            the hierarchical representation, the factored leaf tuple
            with an optional leading [T] axis on every leaf."""
            if HIER:
                e = None if T_EP == 1 else _ep_of(t, ept)
                return hierarchy.gather_parts(tab, sv, dv, e=e)
            if T_EP == 1:
                return tab[sv, dv]
            return tab[_ep_of(t, ept), sv, dv]

        # one-hot topology-table lookups (see EngineConfig.table_onehot)
        TAB_ONEHOT = bool(cfg.table_onehot) and V * V <= 128 \
            and T_EP == 1 and not HIER
        if cfg.table_onehot and not TAB_ONEHOT:
            if T_EP > 1:
                log.info("table_onehot disabled: fault epoch table "
                         "(T=%d) uses the indexed gather", T_EP)
            elif HIER:
                log.info("table_onehot disabled: hierarchical "
                         "representation uses the factored gather")
            else:
                log.info("table_onehot disabled: V*V = %d > 128",
                         V * V)
        # statically lossless topologies (all reliability == 1) never
        # drop: packet_drop_mask is False for every row regardless of
        # the roll, so the threefry batch is skipped outright. Under
        # an ensemble the check spans every replica's table — one
        # lossy replica keeps the rolls for all.
        if HIER:
            _rel_tab = (self.ensemble.reliability
                        if self.ensemble is not None
                        else self.reliability)
            ALL_REL1 = hierarchy.all_rel1(_rel_tab)
        else:
            ALL_REL1 = bool((np.asarray(
                self.ensemble.reliability if self.ensemble is not None
                else self.reliability) >= 1.0).all())

        # model-NIC constants (host/model_nic.py twins; keep in
        # lockstep with its arithmetic — trace equality depends on it)
        from shadow_tpu.host.model_nic import (
            CODEL_INTERVAL_NS as CD_INT,
            CODEL_TARGET_NS as CD_TGT,
            LAW,
            LAW_SIZE,
            MAX_SER_BYTES as MAX_SER,
        )
        from shadow_tpu.core.event import KIND_PACKET_READY
        # shadowlint: const-ok(LAW is a constant table from
        # host/model_nic.py, a CODE_DIGEST_MODULES member — an edit
        # invalidates every cached executable via the code digest)
        law_t = jnp.asarray(LAW)                       # [1024] i64
        # shadowlint: const-ok(the per-host bandwidth vectors are
        # deliberately baked, not threaded through wrld — aotcache
        # keys entries on their bw_digest under model_bandwidth)
        bw_up_t = jnp.asarray(self.bw_up)              # [H_pad] i64
        bw_down_t = jnp.asarray(self.bw_down)
        NSx8 = np.int64(8) * np.int64(1_000_000_000)

        U32 = jnp.int64(0xFFFFFFFF)

        def pack2(hi, lo):
            return ((hi.astype(jnp.int64) & U32) << 32) | \
                (lo.astype(jnp.int64) & U32)

        def hi32(x):
            return (x >> 32).astype(jnp.int32)

        def lo32(x):
            return (x & U32).astype(jnp.int32)

        hidx = jnp.arange(H_loc)

        def _take_head(arr, head, fill):
            if POP_ONEHOT:
                m = jnp.arange(E)[None, :] == head[:, None]
                v = jnp.where(m, arr,
                              jnp.zeros((), arr.dtype)).sum(axis=1)
                return jnp.where(head < E, v, fill)
            v = jnp.take_along_axis(
                arr, jnp.minimum(head, E - 1)[:, None], axis=1)[:, 0]
            return jnp.where(head < E, v, fill)

        # ---------------- inner loop body: one event per host ----------
        # (up to P events for an app's declared burst hosts)
        # `wrld` is the traced per-world tuple (lat, rel, seed k1,
        # seed k2, epoch times): everything a replica may vary without
        # changing shapes — the ensemble program vmaps over a stacked
        # axis of exactly these plus the state.
        def _step(carry, win_end, gid, host_vertex, wrld):
            lat, rel, sk1, sk2, ept = wrld
            seed_pair = (sk1, sk2)
            state, ob, blk, dirty = carry
            head = state["head"]
            if P > 1:
                offs = jnp.arange(P, dtype=head.dtype)
                idxs = head[:, None] + offs

                def _take_heads(arr, fill):
                    if POP_ONEHOT:
                        m = jnp.arange(E)[None, None, :] == \
                            idxs[:, :, None]
                        v = jnp.where(m, arr[:, None, :],
                                      jnp.zeros((), arr.dtype)) \
                            .sum(axis=-1)
                        return jnp.where(idxs < E, v, fill)
                    v = jnp.take_along_axis(
                        arr, jnp.minimum(idxs, E - 1), axis=1)
                    return jnp.where(idxs < E, v, fill)

                ptP = _take_heads(state["ht"], INF)
                pk2P = _take_heads(state["hk"], IMAX)
                pmP = _take_heads(state["hm"], jnp.int64(0))
                pvP = _take_heads(state["hv"], jnp.int64(0))
                pwP = _take_heads(state["hw"], jnp.int64(0))
                pt, pk2 = ptP[:, 0], pk2P[:, 0]
                pm, pv, pw = pmP[:, 0], pvP[:, 0], pwP[:, 0]
            else:
                pt = _take_head(state["ht"], head, INF)
                pk2 = _take_head(state["hk"], head, IMAX)
                pm = _take_head(state["hm"], head, jnp.int64(0))
                pv = _take_head(state["hv"], head, jnp.int64(0))
                pw = _take_head(state["hw"], head, jnp.int64(0))
            psrc, pseq = hi32(pk2), lo32(pk2)
            pkind, psize = hi32(pm), lo32(pm)
            pd0, pd1 = hi32(pv), lo32(pv)
            pd2 = lo32(pw)

            # a host with a possibly-in-window insert pending in the
            # outbox (dirty) must stall until the flush lands it, or
            # it would pop later events first (order violation)
            runnable = (pt < win_end) & ~dirty
            if P > 1:
                # burst hosts pop their RUN of consecutive in-window
                # packet events (the stateless-responder contract:
                # handling order within the run cannot feed back into
                # the run); everyone else pops one event as usual
                bm = app.burst_mask(state["app"])
                kindP = hi32(pmP)
                eligP = (ptP < win_end) & (kindP == KIND_PACKET)
                run = jnp.cumprod(eligP.astype(jnp.int32), axis=1)
                popcnt = jnp.where(
                    runnable,
                    jnp.where(bm & eligP[:, 0], run.sum(-1), 1),
                    0).astype(head.dtype)
                activeP = offs[None, :] < popcnt[:, None]   # [H,P]
            else:
                popcnt = runnable.astype(head.dtype)
            state["head"] = head + popcnt

            state["n_exec"] = state["n_exec"] + \
                popcnt.astype(jnp.int32)
            if AUDIT:
                # per-host clock monotonicity: popping an event older
                # than one already executed means the heap (or a
                # resume) handed events out of order
                prev_t = state["aud_t"]
                state["aud"] = state["aud"] | jnp.where(
                    runnable & (pt < prev_t),
                    jnp.int32(AUD_CLOCK), jnp.int32(0))
                if P > 1:
                    last_t = jnp.where(activeP, ptP,
                                       jnp.int64(0)).max(-1)
                else:
                    last_t = pt
                state["aud_t"] = jnp.where(
                    runnable, jnp.maximum(prev_t, last_t), prev_t)
            # with the model NIC, a packet pops twice: the RX stage
            # (KIND_PACKET: bandwidth+CoDel, no app) and the delivery
            # (KIND_PACKET_READY). Deliveries are the READY pops then.
            is_rx = runnable & (pkind == KIND_PACKET) if MB else \
                jnp.zeros_like(runnable)
            if P > 1:
                # delivered PACKETS: popcount(d2) survivors per popped
                # packet row, summed over the burst
                is_pktP = activeP & (kindP == KIND_PACKET)
                state["n_deliv"] = state["n_deliv"] + jnp.where(
                    is_pktP,
                    lax.population_count(lo32(pwP).astype(jnp.uint32))
                    .astype(jnp.int32), 0).sum(-1, dtype=jnp.int32)
                # the trace checksum folds each popped event exactly
                # as the serial oracle does — stepwise (the inter-step
                # MASK63 truncation makes a closed-form fold wrong)
                chk = state["chk"]
                srcPa, seqPa = hi32(pk2P), lo32(pk2P)
                for j in range(P):
                    mix_j = (ptP[:, j]
                             ^ (srcPa[:, j].astype(jnp.int64)
                                * CHK_SRC)
                             ^ (kindP[:, j].astype(jnp.int64)
                                * CHK_KIND)
                             ^ (seqPa[:, j].astype(jnp.int64)
                                * CHK_SEQ)) & MASK63
                    chk = jnp.where(activeP[:, j],
                                    (chk * CHK_MUL + mix_j) & MASK63,
                                    chk)
                state["chk"] = chk
            else:
                is_pkt = runnable & (pkind == (KIND_PACKET_READY if MB
                                               else KIND_PACKET))
                # delivered PACKETS: a train row carries popcount(d2)
                # survivors (ordinary packets carry d2 == 1)
                state["n_deliv"] = state["n_deliv"] + jnp.where(
                    is_pkt,
                    lax.population_count(pd2.astype(jnp.uint32))
                    .astype(jnp.int32), 0)
                mix = (pt ^ (psrc.astype(jnp.int64) * CHK_SRC)
                       ^ (pkind.astype(jnp.int64) * CHK_KIND)
                       ^ (pseq.astype(jnp.int64) * CHK_SEQ)) & MASK63
                state["chk"] = jnp.where(
                    runnable, (state["chk"] * CHK_MUL + mix) & MASK63,
                    state["chk"])

            # app dispatch (batched); masked hosts see kind=-1. Under
            # the model NIC the RX stage is engine-internal (app sees
            # -1) and READY pops present as KIND_PACKET to the app.
            draw_seqs = state["app_seq"][:, None] + \
                jnp.arange(D, dtype=jnp.int32)
            draws = prng.random_bits32(prng.chain_key(
                seed_pair, PURPOSE_APP, gid[:, None], draw_seqs))
            if P > 1:
                # burst dispatch: the app sees all P popped columns
                # (inactive ones as kind=-1) and answers each on its
                # own send lane
                kindP_app = jnp.where(activeP, kindP, -1)
                out = app.handle_burst(
                    gid, ptP, kindP_app, srcPa, lo32(pmP),
                    hi32(pvP), lo32(pvP), lo32(pwP), state["app"],
                    draws)
                app_on = runnable
            else:
                if MB:
                    app_kind = jnp.where(pkind == KIND_PACKET_READY,
                                         jnp.int32(KIND_PACKET), pkind)
                    app_kind = jnp.where(runnable & ~is_rx, app_kind,
                                         -1)
                else:
                    app_kind = jnp.where(runnable, pkind, -1)
                out = app.handle(gid, pt, app_kind,
                                 psrc, psize, pd0, pd1, pd2,
                                 state["app"], draws)
                app_on = runnable & ~is_rx if MB else runnable
            # apps may return [H,1] columns that broadcast over K/T
            out = out._replace(
                send_dst=jnp.broadcast_to(out.send_dst,
                                          (H_loc, K_eff)),
                send_size=jnp.broadcast_to(out.send_size,
                                           (H_loc, K_eff)),
                send_d0=jnp.broadcast_to(out.send_d0, (H_loc, K_eff)),
                send_d1=jnp.broadcast_to(out.send_d1, (H_loc, K_eff)),
                send_valid=jnp.broadcast_to(out.send_valid,
                                            (H_loc, K_eff)),
                timer_delay=jnp.broadcast_to(out.timer_delay,
                                             (H_loc, T)),
                timer_d0=jnp.broadcast_to(out.timer_d0, (H_loc, T)),
                timer_valid=jnp.broadcast_to(out.timer_valid,
                                             (H_loc, T)),
            )
            # send lane j of a burst departs at ITS popped event's
            # time (bit-identical bootstrap gating + delivery times)
            lane_t = ptP if P > 1 else pt[:, None]
            state["app"] = jnp.where(app_on[:, None], out.app_state,
                                     state["app"])
            state["app_seq"] = state["app_seq"] + \
                jnp.where(app_on, out.n_draws, 0)

            # sends -> network judgment (worker_sendPacket semantics)
            send_valid = out.send_valid & app_on[:, None]       # [H,K]
            vrank = jnp.cumsum(send_valid, axis=-1) - send_valid
            if C > 1:
                counts = jnp.clip(
                    jnp.broadcast_to(out.send_count, (H_loc, K_eff))
                    if out.send_count is not None
                    else jnp.ones((H_loc, K_eff), jnp.int32), 1, C)
                vcnt = counts * send_valid
                state["packet_seq"] = state["packet_seq"] + \
                    vcnt.sum(-1).astype(jnp.int32)
            else:
                counts = jnp.ones((H_loc, K_eff), jnp.int32)
                vcnt = send_valid.astype(jnp.int32)
                state["packet_seq"] = state["packet_seq"] + \
                    send_valid.sum(-1).astype(jnp.int32)

            dst = out.send_dst                                   # [H,K]
            if HOIST:
                # raw rows only: depart time (== the popped event
                # time — also the drop-roll key time the judge
                # re-derives), train count, and the live-lane mask.
                # _judge_outbox settles drops/latency once per phase.
                depart = lane_t
                if out.send_mask is not None:
                    smask = jnp.broadcast_to(
                        out.send_mask, (H_loc, K_eff)).astype(jnp.int32)
                else:
                    smask = jnp.full((H_loc, K_eff), -1, jnp.int32)
            else:
                if C > 1:
                    ccum = jnp.cumsum(vcnt, axis=-1) - vcnt
                    pkt_seq = state["packet_seq"][:, None] - \
                        vcnt.sum(-1).astype(jnp.int32)[:, None] + ccum
                else:
                    pkt_seq = state["packet_seq"][:, None] - \
                        send_valid.sum(-1).astype(jnp.int32)[:, None] \
                        + vrank
                srcv = host_vertex[gid][:, None]
                dstv = host_vertex[jnp.clip(dst, 0, H_pad - 1)]
                # epoch keyed on the SEND time (lane_t), matching the
                # CPU model's judge(now=send time) under faults
                latv = _tbl(lat, lane_t, srcv, dstv,
                            ept).astype(jnp.int64)               # [H,K]
                relv = _tbl(rel, lane_t, srcv, dstv, ept)
            if not HOIST and C > 1:
                # packet TRAINS: one drop roll per packet, keyed by the
                # exact (src, pkt_seq0+j) sequence individual sends
                # would consume — loss statistics are bit-identical to
                # per-packet sends; survivors become the d2 bitmask
                js = jnp.arange(C, dtype=jnp.int32)              # [C]
                if ALL_REL1:
                    # statically lossless: the roll can never drop
                    drop3 = jnp.zeros((H_loc, K_eff, C), bool)
                else:
                    seqs3 = pkt_seq[..., None] + js              # [H,K,C]
                    drop3 = packet_drop_mask(
                        seed_pair, BOOT_END, lane_t[..., None],
                        gid[:, None, None], seqs3, relv[..., None])
                win3 = js[None, None, :] < counts[..., None]
                if out.send_mask is not None:
                    # forwarding a previous hop's survivors: only LIVE
                    # lanes are packets (seq consumption + roll keys
                    # still span all `counts` lanes — twin alignment)
                    smask = jnp.broadcast_to(
                        out.send_mask, (H_loc, K_eff)) \
                        .astype(jnp.uint32)
                    live3 = win3 & (jnp.right_shift(
                        smask[..., None],
                        js.astype(jnp.uint32)[None, None, :])
                        & jnp.uint32(1)).astype(bool)
                else:
                    live3 = win3
                lost3 = drop3 & live3 & send_valid[..., None]
                surv = jnp.where(
                    ~drop3 & live3,
                    jnp.left_shift(jnp.uint32(1),
                                   js.astype(jnp.uint32)),
                    jnp.uint32(0)).sum(-1, dtype=jnp.uint32)     # [H,K]
                surv = jnp.where(send_valid, surv, 0)
                dropped = send_valid & (surv == 0)
                n_lost = lost3.sum((-2, -1)).astype(jnp.int32)
                livecnt = (live3 & send_valid[..., None]).sum(
                    -1, dtype=jnp.int32)                         # [H,K]
            elif not HOIST:
                dropped = send_valid & (
                    jnp.zeros((H_loc, K_eff), bool) if ALL_REL1
                    else packet_drop_mask(
                        seed_pair, BOOT_END, lane_t, gid[:, None],
                        pkt_seq, relv))
                surv = jnp.where(send_valid & ~dropped,
                                 jnp.uint32(1), jnp.uint32(0))
                n_lost = dropped.sum(-1).astype(jnp.int32)
                livecnt = vcnt
            if MB:
                # TX fluid bucket (ModelNic.tx_depart): a burst's sends
                # serialize in slot order; drop-rolled packets still
                # consume uplink time (the network drops them later)
                ser_up = jnp.where(
                    send_valid,
                    (jnp.clip(out.send_size, 1,
                              MAX_SER).astype(jnp.int64)
                     * NSx8) // bw_up_t[gid][:, None],
                    jnp.int64(0))                                # [H,K]
                tx_base = jnp.maximum(pt, state["tx_free"])      # [H]
                cum = jnp.cumsum(ser_up, axis=-1)
                depart = tx_base[:, None] + (cum - ser_up)
                state["tx_free"] = jnp.where(
                    runnable, tx_base + cum[:, -1], state["tx_free"])
            elif not HOIST:
                depart = lane_t
            if not HOIST:
                delivered = send_valid & ~dropped
                state["n_sent"] = state["n_sent"] + \
                    livecnt.sum(-1).astype(jnp.int32)
                state["n_drop"] = state["n_drop"] + n_lost

            # event seq consumed per SEND (delivered or dropped alike),
            # matching the CPU engines — lets the CPU side defer drop
            # judgment to a batched device call without perturbing seqs
            ev_seq = state["event_seq"][:, None] + vrank
            n_snt = send_valid.sum(-1).astype(jnp.int32)

            if not HOIST:
                deliver_t = depart + latv
                cross = dst != gid[:, None]
                # cross-host causality bump (host_single.c:174-220);
                # self packets keep their true time — they may run this
                # window (the flush + another phase makes them
                # poppable)
                deliver_t = jnp.where(cross,
                                      jnp.maximum(deliver_t, win_end),
                                      deliver_t)

            # model-NIC RX stage (ModelNic.rx_deliver twin): the popped
            # KIND_PACKET row passes the download bucket + event-driven
            # CoDel; survivors re-enter via the outbox as READY rows at
            # their post-serialization delivery time (same src/seq)
            if MB:
                rxf = state["rx_free"]
                dq = jnp.maximum(pt, rxf)                       # [H]
                soj = dq - pt
                below = soj < CD_TGT
                fa = state["cd_fa"]
                fa0 = fa == 0
                above = ~below & ~fa0 & (dq >= fa)
                in_drop = state["cd_drop"] != 0
                drop_now = above & in_drop & (dq >= state["cd_next"])
                drop_first = above & ~in_drop
                rx_drop = is_rx & (drop_now | drop_first)
                rx_keep = is_rx & ~(drop_now | drop_first)

                delta = state["cd_cnt"] - state["cd_last"]
                first_cnt = jnp.where(
                    (dq - state["cd_next"] < CD_INT) & (delta > 1),
                    delta, jnp.int64(1))
                new_cnt = jnp.where(
                    drop_now, state["cd_cnt"] + 1,
                    jnp.where(drop_first, first_cnt, state["cd_cnt"]))
                law = law_t[jnp.clip(new_cnt, 0, LAW_SIZE - 1)]
                new_next = jnp.where(
                    drop_now, state["cd_next"] + law,
                    jnp.where(drop_first, dq + law, state["cd_next"]))
                new_last = jnp.where(drop_first, first_cnt,
                                     state["cd_last"])
                new_fa = jnp.where(below, jnp.int64(0),
                                   jnp.where(fa0, dq + CD_INT, fa))
                new_cd_drop = jnp.where(
                    below, jnp.int64(0),
                    jnp.where(fa0, state["cd_drop"],
                              jnp.where(above,
                                        jnp.where(in_drop,
                                                  state["cd_drop"],
                                                  jnp.int64(1)),
                                        jnp.int64(0))))

                ser_down = (jnp.clip(psize, 1, MAX_SER)
                            .astype(jnp.int64) * NSx8) \
                    // bw_down_t[gid]
                rx_deliver = dq + ser_down
                for f_, v_ in (("cd_cnt", new_cnt),
                               ("cd_next", new_next),
                               ("cd_last", new_last),
                               ("cd_fa", new_fa),
                               ("cd_drop", new_cd_drop)):
                    state[f_] = jnp.where(is_rx, v_, state[f_])
                state["rx_free"] = jnp.where(rx_keep, rx_deliver, rxf)
                state["n_drop"] = state["n_drop"] + rx_drop
            else:
                rx_keep = jnp.zeros_like(runnable)
                rx_deliver = pt

            # timers (self rows; may fire inside this window)
            timer_valid = out.timer_valid & app_on[:, None]     # [H,T]
            trank = jnp.cumsum(timer_valid, axis=-1) - timer_valid
            tseq = state["event_seq"][:, None] + n_snt[:, None] + trank
            state["event_seq"] = state["event_seq"] + n_snt + \
                timer_valid.sum(-1).astype(jnp.int32)
            timer_t = pt[:, None] + out.timer_delay

            # the iteration's outbox block: K sends | T timers | READY.
            # EVERY insert goes through the outbox (no heap scatters);
            # a self-destined row that could run inside this window
            # marks the host dirty so pop order is preserved.
            def cols(*parts):
                return jnp.concatenate(
                    parts[:2 + (1 if MB else 0)], axis=1)

            gcol = jnp.broadcast_to(gid[:, None], (H_loc, K_eff))
            gcolT = jnp.broadcast_to(gid[:, None], (H_loc, T))
            if HOIST:
                # raw rows: depart time, train COUNT in the kind field
                # (the judge rewrites it with the live count), and the
                # live-lane mask where the judge puts the survivors
                bvalid_send = send_valid
                send_t = depart
                kcnt = counts
                vhi = smask
            elif CP:
                # drop-rolled sends ride along under the reserved
                # DROP_T marker so the flush's path histogram counts
                # them (ref counts per SENT packet, worker.c:554)
                bvalid_send = send_valid
                send_t = jnp.where(delivered, deliver_t, DROP_T)
                kcnt = livecnt
                vhi = surv.astype(jnp.int32)
            else:
                bvalid_send = delivered
                send_t = deliver_t
                kcnt = livecnt
                vhi = surv.astype(jnp.int32)
            bvalid = cols(bvalid_send, timer_valid, rx_keep[:, None])
            bt = jnp.where(bvalid,
                           cols(send_t, timer_t,
                                rx_deliver[:, None]),
                           INF)
            bk = cols(pack2(gcol, ev_seq), pack2(gcolT, tseq),
                      pk2[:, None])
            bdst = cols(dst, gcolT, gid[:, None])
            # packet-kind rows carry their train count in bits 8+ of
            # the kind field (histogram weight; kind itself is <256)
            bkind = cols(
                jnp.full((H_loc, K_eff), KIND_PACKET, jnp.int32)
                | (kcnt << 8),
                jnp.full((H_loc, T), KIND_TIMER, jnp.int32),
                jnp.full((H_loc, 1), KIND_PACKET_READY, jnp.int32))
            bm = pack2(bdst, bkind)
            bs = pack2(cols(out.send_size,
                            jnp.zeros((H_loc, T), jnp.int32),
                            psize[:, None]),
                       cols(out.send_d0, out.timer_d0, pd0[:, None]))
            bv = pack2(cols(vhi,
                            jnp.zeros((H_loc, T), jnp.int32),
                            pd2[:, None]),
                       cols(out.send_d1,
                            jnp.zeros((H_loc, T), jnp.int32),
                            pd1[:, None]))

            col0 = blk * jnp.int32(M_out)
            for f, block in (("t", bt), ("k", bk), ("m", bm),
                             ("s", bs), ("v", bv)):
                ob[f] = lax.dynamic_update_slice(ob[f], block,
                                                 (jnp.int32(0), col0))

            if HOIST:
                # the judge hasn't run, so in-window detection uses the
                # host's SELF-latency (self rows never take the bump);
                # conservative over drop rolls — a later-dropped self
                # send still stalls the host one phase, which only
                # moves the phase boundary, never the per-host pop
                # order (the trace is bit-identical either way)
                hvg = host_vertex[gid][:, None]                  # [H,1]
                selflat = _tbl(lat, depart, hvg, hvg,
                               ept).astype(jnp.int64)
                self_in = send_valid & (dst == gid[:, None]) & \
                    (depart + selflat < win_end)
                tim_in = timer_valid & (timer_t < win_end)
                dirty = dirty | (runnable &
                                 (self_in.any(-1) | tim_in.any(-1)))
            else:
                in_win = bvalid & (bt < win_end) & \
                    (bdst == gid[:, None])
                dirty = dirty | (runnable & in_win.any(-1))

            return state, ob, blk + 1, dirty

        # ---------------- flush: exchange + merge ----------------------
        # Deterministic arrival order — keyed by skey = dst*SPAN + okey
        # with okey = src_gid*OB + column — independent of mesh shape
        # and exchange strategy. Rows beyond a dst host's IN window (or
        # a shard pair's CAP) are counted in overflow/x_overflow and
        # fail the run — never silently lost (SURVEY hard-part #2).
        XF = ("t", "k", "m", "s", "v")

        # Sorts move every operand through every bitonic pass, so the
        # flush sorts ONLY (key, iota) and recovers payload rows later
        # with gathers — the profiler showed the old 6-operand flat
        # sort + 5-operand merge dominating round cost (~85%).
        CX = min(cfg.outbox_compact or OB, OB)

        # effective (post-auto-sizing) capacities, for the occupancy
        # record and the planner's re-plan arithmetic. ICI_* is the
        # per-flush cross-chip traffic each shard SENDS (buffers ship
        # at capacity, padding included — that IS the wire cost), so
        # bench/tpu_micro report exchanged volume without touching
        # device state: rows/round = ICI_rows_per_flush * phases /
        # rounds.
        if n_shards <= 1:
            ici_rows, ici_arrays = 0, 0
        elif cfg.exchange == "all_to_all":
            # [n_shards, CAP] buffers; the self slot never crosses ICI
            ici_rows = (n_shards - 1) * int(CAP)
            # 5 field arrays, + the shipped sort keys on the window
            # merge path (the global merge re-derives order)
            ici_arrays = 5 if MERGE_GLOBAL else 6
        elif cfg.exchange == "two_phase":
            ici_rows = (TP_G - 1) * int(CAP) + \
                (TP_NG - 1) * int(CAP2)
            ici_arrays = 6          # keys route phase 2 on both paths
        else:                       # all_gather replicates everything
            ici_rows = (n_shards - 1) * H_loc * CX
            ici_arrays = 5 if MERGE_GLOBAL else 7   # + skey + perm
        self.effective = {"E": E, "B": B, "OB": OB, "IN": IN,
                          "CAP": int(CAP), "CAP2": int(CAP2),
                          "CX": CX, "M_out": M_out,
                          "n_shards": n_shards,
                          "exchange": cfg.exchange,
                          "tp_groups": [int(TP_G), int(TP_NG)],
                          "ICI_rows_per_flush": int(ici_rows),
                          "ICI_bytes_per_flush":
                              int(ici_rows) * ici_arrays * 8}
        # the resolved compile-time surface of the traced programs:
        # every value the trace bakes in as a constant (capacities,
        # platform-resolved strategy flags, lookahead/bootstrap,
        # fault epoch count, audit, ensemble width, ...). The AOT
        # compile cache (device/aotcache.py) keys serialized
        # executables on this dict — a knob that newly shapes the
        # program must join here or stale cache entries would load
        # for the wrong trace. Runtime-scalar inputs (stop,
        # final_stop, seeds, the world tables' VALUES) stay out:
        # they are traced, not baked.
        self.program_facts = {
            "n_hosts": int(cfg.n_hosts),
            "h_pad": int(H_pad), "h_loc": int(H_loc),
            "n_shards": int(n_shards),
            "capacities": {"E": int(E), "OB": int(OB), "IN": int(IN),
                           "CAP": int(CAP), "CAP2": int(CAP2),
                           "CX": int(CX)},
            "exchange": cfg.exchange,
            "tp_groups": [int(TP_G), int(TP_NG)],
            "lookahead": int(LOOKAHEAD),
            "bootstrap_end": int(BOOT_END),
            "max_rounds": int(cfg.max_rounds),
            "fault_epochs": int(T_EP),
            "audit": bool(AUDIT),
            "model_bandwidth": bool(MB),
            "count_paths": bool(CP),
            "judge_hoist": bool(HOIST),
            "merge_global": bool(MERGE_GLOBAL),
            "pop_onehot": bool(POP_ONEHOT),
            "table_onehot": bool(TAB_ONEHOT),
            "all_rel1": bool(ALL_REL1),
            "burst_pops": int(P),
            "lanes": {"K": int(K), "K_eff": int(K_eff), "T": int(T),
                      "D": int(D), "C": int(C), "M_out": int(M_out),
                      "B": int(B)},
            "n_vertices": int(V),
            # the factored-vs-dense world layout shapes the gather
            # trace, so two representations of the SAME topology must
            # never share a cached executable
            "representation": ("hierarchical" if HIER else "dense"),
            "n_clusters": (int(self.latency[0].shape[-1])
                           if HIER else 0),
            "ensemble_replicas": (int(self.ensemble.R)
                                  if self.ensemble is not None else 0),
        }

        def _flat_sorted(state, ob, gid):
            slot = jnp.arange(OB, dtype=jnp.int64)[None, :]
            okey2 = gid.astype(jnp.int64)[:, None] * OB + slot
            fdst2 = hi32(ob["m"]).astype(jnp.int64)
            # DROP_T rows exist only for the path histogram — they are
            # never exchanged or delivered
            valid2 = ob["t"] < DROP_T
            skey2 = jnp.where(valid2, fdst2 * SPAN + okey2, IMAX)
            if CX < OB:
                # two-level flush: each host's row compacts to its
                # first CX valid entries (a width-OB row sort — far
                # cheaper than pushing the ~98%-empty outbox through
                # the global sort), then the flat sort runs over
                # H*CX rows. Keys are unchanged, so the final order
                # is bit-identical whenever nothing overflows; the
                # loss is counted against the SENDING host.
                cols = jnp.broadcast_to(
                    slot, (H_loc, OB)).astype(jnp.int64)
                ssk, scol = lax.sort((skey2, cols), dimension=1,
                                     num_keys=1)
                state["x_overflow"] = state["x_overflow"] + \
                    (ssk[:, CX:] < IMAX).sum(-1).astype(jnp.int32)
                keep_col = scol[:, :CX].astype(jnp.int32)
                F = H_loc * CX
                flat = {f: jnp.take_along_axis(ob[f], keep_col,
                                               axis=1).reshape(F)
                        for f in XF}
                skey = ssk[:, :CX].reshape(F)
            else:
                F = H_loc * OB
                flat = {f: ob[f].reshape(F) for f in XF}
                skey = skey2.reshape(F)
            skey_s, perm = lax.sort(
                (skey, jnp.arange(F, dtype=jnp.int64)), num_keys=1)
            return state, skey_s, perm, flat

        def _count_paths(state, ob, host_vertex):
            """topology_incrementPathPacketCounter parity: a [V,V]
            histogram of SENT packets per (src_vertex, dst_vertex),
            drop-rolled packets included — scatter-free via one flat
            sort + prefix-sum segment totals."""
            F = H_loc * OB
            ft = ob["t"].reshape(F)
            fm = ob["m"].reshape(F)
            fk = ob["k"].reshape(F)
            kindf = lo32(fm)
            is_pkt = (ft < INF) & ((kindf & 0xFF) == KIND_PACKET)
            cnt = jnp.where(is_pkt, (kindf >> 8).astype(jnp.int64), 0)
            src = hi32(fk)
            dstf = hi32(fm)
            sv = host_vertex[jnp.clip(src, 0, H_pad - 1)]
            dv = host_vertex[jnp.clip(dstf, 0, H_pad - 1)]
            pair = jnp.where(is_pkt,
                             sv.astype(jnp.int64) * V + dv, V * V)
            spair, scnt = lax.sort((pair, cnt), num_keys=1)
            prefix = jnp.concatenate(
                [jnp.zeros((1,), jnp.int64), jnp.cumsum(scnt)])
            edges = jnp.searchsorted(
                spair, jnp.arange(V * V + 1, dtype=jnp.int64))
            state["path_cnt"] = state["path_cnt"] + \
                (prefix[edges[1:]] - prefix[edges[:-1]])[None, :]
            return state

        def _seg_take(perm, rows, starts, counts, width):
            """Contiguous per-segment windows of the SORTED order: row
            i of the result is sorted-rows[starts[i]:starts[i]+width],
            masked past counts — realized as a two-hop gather through
            the sort permutation (rows stay unsorted)."""
            G = perm.shape[0]
            idx = starts[:, None] + jnp.arange(width,
                                               dtype=starts.dtype)
            ok = jnp.arange(width)[None, :] < \
                jnp.minimum(counts, width)[:, None]
            cidx = jnp.clip(idx, 0, G - 1).reshape(-1)
            pidx = jnp.take(perm, cidx)
            out = {}
            for f in XF:
                v = jnp.take(rows[f], pidx).reshape(idx.shape)
                fillv = INF if f == "t" else (IMAX if f == "k" else 0)
                out[f] = jnp.where(ok, v, fillv)
            return out

        def _host_windows(state, skey, perm, rows, my_shard):
            """Per-host contiguous arrival segments -> [H_loc, IN]
            windows + overflow accounting (shared by the self-shard
            bypass and the post-exchange arrival step). Also returns
            the per-host arrival counts (occupancy telemetry)."""
            base = my_shard.astype(jnp.int64) * H_loc
            hb = (base + jnp.arange(H_loc + 1, dtype=jnp.int64)) \
                * SPAN
            edges = jnp.searchsorted(skey, hb)
            starts, counts = edges[:-1], edges[1:] - edges[:-1]
            state["overflow"] = state["overflow"] + \
                jnp.maximum(0, counts - IN).astype(jnp.int32)
            return state, _seg_take(perm, rows, starts, counts, IN), \
                counts.astype(jnp.int32)

        def _judge_outbox(state, ob, gid, host_vertex, wrld,
                          win_end):
            """Per-phase network judgment of the raw outbox — the
            worker_sendPacket semantics (ref worker.c:520-579) hoisted
            out of the pop loop: latency gather, per-packet drop rolls
            under EXACTLY the keys the in-step path would use (src,
            per-source packet seq, send time), causality bump, and the
            sent/dropped counters. Runs once per phase over [H, OB]
            instead of once per pop iteration over [H, K]."""
            lat, rel, sk1, sk2, ept = wrld
            seed_pair = (sk1, sk2)
            ft, fm, fv = ob["t"], ob["m"], ob["v"]
            kindrow = lo32(fm)
            is_send = (ft < INF) & ((kindrow & 0xFF) == KIND_PACKET)
            cnt = jnp.where(is_send, kindrow >> 8, 0)        # [H,OB]
            dst = hi32(fm)
            srcv = host_vertex[gid][:, None]
            dstv = host_vertex[jnp.clip(dst, 0, H_pad - 1)]
            if TAB_ONEHOT:
                # gatherless table lookup: unrolled one-hot masked
                # sums over the tiny [V,V] table (exact — a single
                # nonzero term per row); the indexed gather costs
                # ~ms-class on TPU for [H,OB] outputs
                pairv = srcv * jnp.int32(V) + dstv           # [H,OB]
                latf, relf = lat.reshape(-1), rel.reshape(-1)
                latv = jnp.zeros(pairv.shape, jnp.int64)
                relv = jnp.zeros(pairv.shape, rel.dtype)
                for j in range(V * V):
                    m = pairv == j
                    latv = latv + jnp.where(
                        m, latf[j].astype(jnp.int64), jnp.int64(0))
                    relv = relv + jnp.where(
                        m, relf[j], jnp.zeros((), rel.dtype))
            else:
                # epoch keyed on the row's depart time `ft` — equal to
                # the send time in the hoisted (no-fluid-NIC) path, so
                # the drop-roll reliability and the latency come from
                # the same epoch the CPU twin reads. Empty rows
                # (ft == INF) gather the last epoch harmlessly — they
                # are masked by is_send everywhere downstream.
                latv = _tbl(lat, ft, srcv, dstv,
                            ept).astype(jnp.int64)
                relv = _tbl(rel, ft, srcv, dstv, ept)

            # per-row packet-seq base: state["packet_seq"] is already
            # the END of the phase; outbox columns sit in consumption
            # order (iteration block, then send lane), so an exclusive
            # prefix over the train counts recovers each row's base
            tot = cnt.sum(-1)
            base = (state["packet_seq"] - tot)[:, None] + \
                (jnp.cumsum(cnt, axis=-1) - cnt)

            # live lanes are a 2D popcount (mask ∩ count window); the
            # ONLY [H,OB,C] reduce is the survivor bitmask, and it is
            # the single consumer of the threefry product — extra
            # reduce roots would each re-read (or recompute) the
            # materialized 3D tensor, which measured 3x the whole
            # judge's budget on CPU
            wbits = jnp.where(
                cnt >= 32, jnp.uint32(0xFFFFFFFF),
                jnp.left_shift(jnp.uint32(1),
                               jnp.clip(cnt, 0, 31).astype(jnp.uint32))
                - jnp.uint32(1))
            livemask = hi32(fv).astype(jnp.uint32) & wbits   # [H,OB]
            livecnt = lax.population_count(livemask) \
                .astype(jnp.int32)
            if ALL_REL1:
                # statically lossless: the roll can never drop
                surv = livemask
            else:
                js = jnp.arange(C, dtype=jnp.int32)
                live3 = (jnp.right_shift(
                    livemask[..., None],
                    js.astype(jnp.uint32)[None, None, :])
                    & jnp.uint32(1)).astype(bool)            # [H,OB,C]
                seqs3 = base[..., None] + js
                hk1, hk2 = prng.purpose_id_key(
                    seed_pair, PURPOSE_PACKET_DROP, gid)     # [H] each
                drop3 = packet_drop_mask(
                    seed_pair, BOOT_END, ft[..., None],
                    gid[:, None, None], seqs3, relv[..., None],
                    src_key=(hk1[:, None, None], hk2[:, None, None]))
                surv = jnp.where(
                    live3 & ~drop3,
                    jnp.left_shift(jnp.uint32(1),
                                   js.astype(jnp.uint32)),
                    jnp.uint32(0)).sum(-1, dtype=jnp.uint32)
            lost = livecnt - lax.population_count(surv) \
                .astype(jnp.int32)
            state["n_sent"] = state["n_sent"] + \
                livecnt.sum(-1).astype(jnp.int32)
            state["n_drop"] = state["n_drop"] + \
                lost.sum(-1).astype(jnp.int32)

            deliver_t = ft + latv
            cross = dst != gid[:, None]
            # cross-host causality bump (host_single.c:174-220); self
            # rows keep their true time
            deliver_t = jnp.where(cross,
                                  jnp.maximum(deliver_t, win_end),
                                  deliver_t)
            dead = is_send & (surv == 0)
            dead_t = DROP_T if CP else INF
            new_t = jnp.where(
                is_send, jnp.where(dead, dead_t, deliver_t), ft)
            new_m = jnp.where(
                is_send,
                pack2(dst, jnp.int32(KIND_PACKET) | (livecnt << 8)),
                fm)
            new_v = jnp.where(
                is_send, pack2(surv.astype(jnp.int32), lo32(fv)), fv)
            return state, {**ob, "t": new_t, "m": new_m, "v": new_v}

        # ---------------- gatherless flush (merge_global) --------------
        # TPU takes with computed indices cost ~10 ms per 500k
        # elements while multi-operand sorts of the same data cost
        # ~3 ms (bitonic passes are bandwidth-bound; gathers
        # serialize). So on TPU the flush is TWO stable sorts and
        # zero gathers: sort [outbox | heap] rows by (host, t, key),
        # rank rows within each host segment with segmented scans,
        # then re-sort by target slot host*E+rank — every host
        # contributes exactly E heap rows (consumed slots masked to
        # INF), so ranks 0..E-1 exist for every host and the kept
        # prefix reshapes straight into the [H, E] heaps. Rows
        # ranked >= E are the merge overflow; their per-host count
        # rides the second sort to slot [h, 0] on the rank-0 row.
        # Arrival order within a host is (t, src<<32|seq) — a total
        # order, so traces are bit-identical to the window path
        # whenever neither path overflows (both fail loudly).
        # (host, t) pack into one i64 sort key: host in the top bits,
        # time below. Real times at or above T_CAP would alias the
        # INF encoding — they are counted into `overflow` (loud run
        # failure) rather than silently reordered; sims needing
        # >2^T_BITS ns of horizon must pin merge_strategy: window.
        H_BITS = max(1, int(math.ceil(math.log2(H_loc + 2))))
        T_BITS = 63 - H_BITS
        T_CAP = np.int64((1 << T_BITS) - 1)

        def _henc(host, t):
            return (host.astype(jnp.int64) << T_BITS) | \
                jnp.minimum(t, T_CAP)

        def _ob_rows(ft, fk, fm, fs, fv, lo, hi):
            """Outbox-format flat rows -> merge-format
            (hostt key, k, hm, hv, hw, poison); rows outside [lo, hi)
            or not exchangeable (t >= DROP_T) mask to the sentinel
            segment H_loc (sorts after every real host, lands past
            the kept prefix)."""
            dst = hi32(fm)
            kindb = lo32(fm) & 0xFF        # strip the train count
            m2 = pack2(kindb, hi32(fs))
            v2 = pack2(lo32(fs), lo32(fv))
            w2 = (fv >> 32) & U32
            mine = (ft < DROP_T) & (dst >= lo) & (dst < hi)
            host = jnp.where(mine, dst - lo,
                             jnp.int32(H_loc)).astype(jnp.int32)
            t = jnp.where(mine, ft, INF)
            k = jnp.where(mine, fk, IMAX)
            poison = ((t >= T_CAP) & (t < INF)).sum() \
                .astype(jnp.int32)
            return _henc(host, t), k, m2, v2, w2, poison

        def _merge_rows(state, parts):
            """The double-sort merge: `parts` are (hostt, k, m, v, w,
            poison) flat row tuples (already in heap field format)."""
            live = jnp.arange(E)[None, :] >= state["head"][:, None]
            mt = jnp.where(live, state["ht"], INF)
            mk = jnp.where(live, state["hk"], IMAX).reshape(-1)
            hrow = jnp.broadcast_to(
                jnp.arange(H_loc, dtype=jnp.int32)[:, None],
                (H_loc, E))
            poison = (((mt >= T_CAP) & (mt < INF)).sum()
                      .astype(jnp.int32)
                      + sum(p[5] for p in parts))
            ghk = jnp.concatenate([_henc(hrow, mt).reshape(-1)]
                                  + [p[0] for p in parts])
            gk = jnp.concatenate([mk] + [p[1] for p in parts])
            gm = jnp.concatenate([state["hm"].reshape(-1)]
                                 + [p[2] for p in parts])
            gv = jnp.concatenate([state["hv"].reshape(-1)]
                                 + [p[3] for p in parts])
            gw = jnp.concatenate([state["hw"].reshape(-1)]
                                 + [p[4] for p in parts])
            N = ghk.shape[0]

            shk, sk_, sm_, sv_, sw_ = lax.sort(
                (ghk, gk, gm, gv, gw), num_keys=2)
            # occupancy: arrivals per host this flush — each host's
            # sorted segment holds exactly E heap rows plus arrivals
            # (masked heap slots encode t=T_CAP, staying in-segment)
            hb2 = jnp.arange(H_loc + 1, dtype=jnp.int64) << T_BITS
            seg_n = jnp.searchsorted(shk, hb2)
            state["occ_in"] = jnp.maximum(
                state["occ_in"],
                (seg_n[1:] - seg_n[:-1] - E).astype(jnp.int32))
            sh = (shk >> T_BITS).astype(jnp.int64)
            idx = jnp.arange(N, dtype=jnp.int64)
            is_new = jnp.concatenate(
                [jnp.ones((1,), bool), sh[1:] != sh[:-1]])
            seg0 = lax.associative_scan(
                jnp.maximum, jnp.where(is_new, idx, 0))
            rank = idx - seg0
            kept = rank < E
            is_real = (shk & T_CAP) < T_CAP
            dropped_real = (~kept) & is_real

            tgt = sh * E + rank
            key2 = jnp.where(kept, tgt,
                             INF + idx)
            _, t2k, k2, m2, v2, w2 = lax.sort(
                (key2, shk, sk_, sm_, sv_, sw_), num_keys=1)
            KEEP = H_loc * E
            enc = (t2k[:KEEP] & T_CAP).reshape(H_loc, E)
            state["ht"] = jnp.where(enc == T_CAP, INF, enc)
            state["hk"] = k2[:KEEP].reshape(H_loc, E)
            state["hm"] = m2[:KEEP].reshape(H_loc, E)
            state["hv"] = v2[:KEEP].reshape(H_loc, E)
            state["hw"] = w2[:KEEP].reshape(H_loc, E)

            # overflow: per-host attribution is a sort + searchsorted
            # we only pay when something actually dropped (never in a
            # healthy run); the poison count (times aliasing T_CAP)
            # lands on host 0 — both fail the run loudly either way
            n_drop_tot = dropped_real.sum()

            def _attr(_):
                dh = lax.sort(jnp.where(dropped_real, sh, IMAX))
                hb = jnp.searchsorted(
                    dh, jnp.arange(H_loc + 1, dtype=jnp.int64))
                return (hb[1:] - hb[:-1]).astype(jnp.int32)

            ov = lax.cond(
                (n_drop_tot + poison) > 0, _attr,
                lambda _: jnp.zeros(H_loc, jnp.int32), 0)
            state["overflow"] = state["overflow"] + ov + \
                jnp.zeros(H_loc, jnp.int32).at[0].add(poison)
            state["head"] = jnp.zeros_like(state["head"])
            state["occ_heap"] = jnp.maximum(
                state["occ_heap"],
                (state["ht"] < INF).sum(-1).astype(jnp.int32))
            return state

        # pack plumbing shared by the direct and two-phase schedules:
        # BOTH must account shard segments, occ_x demand, and loud
        # per-sender loss identically, or the cross-variant
        # determinism/planner contracts silently desynchronize — so
        # each piece exists exactly once.
        def _shard_edges(skey):
            """Per-destination-shard [start, count) segments of a
            sorted key array."""
            bound = (jnp.arange(n_shards + 1, dtype=jnp.int64)
                     * H_loc * SPAN)
            edges = jnp.searchsorted(skey, bound)
            return edges[:-1], edges[1:] - edges[:-1]

        def _shard_segments(state, skey, my_shard):
            """_shard_edges with the self shard's count zeroed (the
            bypass owns those rows) and the occ_x pair telemetry
            updated — what exchange_capacity must hold per pair."""
            starts, counts = _shard_edges(skey)
            counts = jnp.where(jnp.arange(n_shards) != my_shard,
                               counts, 0)
            state["occ_x"] = jnp.maximum(
                state["occ_x"], counts.astype(jnp.int32)[None, :])
            return state, starts, counts

        def _within_shard_rank(skey):
            """(dst shard, within-segment rank) per sorted row — the
            position a row competes for inside its destination
            segment. Empty rows (IMAX keys) share the n_shards
            sentinel segment."""
            idx = jnp.arange(skey.shape[0], dtype=jnp.int64)
            shard_of = jnp.minimum(skey // (H_loc * SPAN),
                                   jnp.int64(n_shards))
            is_new = jnp.concatenate(
                [jnp.array([True]), shard_of[1:] != shard_of[:-1]])
            seg0 = lax.associative_scan(
                jnp.maximum, jnp.where(is_new, idx, 0))
            return shard_of, idx - seg0

        def _lost_to_local(state, lost_mask, skey, my_shard):
            """Attribute lost rows to the LOCAL sending host (it owns
            the sizing knob): 1-key sort + searchsorted histogram,
            scatter-free like everything else."""
            src_loc = (skey % SPAN) // OB \
                - my_shard.astype(jnp.int64) * H_loc
            lk = lax.sort(jnp.where(lost_mask, src_loc, IMAX))
            hb = jnp.searchsorted(
                lk, jnp.arange(H_loc + 1, dtype=jnp.int64))
            state["x_overflow"] = state["x_overflow"] + \
                (hb[1:] - hb[:-1]).astype(jnp.int32)
            return state

        def _pack_remote(state, skey, perm, rows, my_shard,
                         ship_keys):
            """Pack genuinely remote rows into [n_shards, CAP] and
            move them with one all_to_all; self-shard rows never
            enter the pack (zero ICI, zero CAP). CAP overflow is
            attributed to the SENDING host. `ship_keys` additionally
            moves each row's skey (the window merge re-sorts arrivals
            by it; the global merge orders by (t, key) and skips the
            extra operand)."""
            G = H_loc * CX
            state, starts, counts = _shard_segments(state, skey,
                                                    my_shard)
            shard_of, rank = _within_shard_rank(skey)
            lost_mask = (skey < IMAX) & (rank >= CAP) & \
                (shard_of != my_shard.astype(jnp.int64))
            state = _lost_to_local(state, lost_mask, skey, my_shard)
            win = _seg_take(perm, rows, starts, counts, CAP)
            moved = {f: lax.all_to_all(
                win[f], AXIS, split_axis=0, concat_axis=0)
                .reshape(n_shards * CAP) for f in XF}
            kmoved = None
            if ship_keys:
                kidx = jnp.clip(
                    starts[:, None] + jnp.arange(CAP,
                                                 dtype=jnp.int64),
                    0, G - 1)
                kwin = jnp.where(
                    jnp.arange(CAP)[None, :] <
                    jnp.minimum(counts, CAP)[:, None],
                    jnp.take(skey, kidx.reshape(-1)).reshape(
                        n_shards, CAP),
                    IMAX)
                kmoved = lax.all_to_all(
                    kwin, AXIS, split_axis=0,
                    concat_axis=0).reshape(n_shards * CAP)
            return state, moved, kmoved

        # ---------------- two-phase hierarchical exchange --------------
        # (exchange: two_phase) shard s = (group a, rank b) with
        # g = TP_G intra-group shards. Phase 1 ships each remote row
        # to the IN-GROUP peer whose rank matches the destination's
        # rank (rows destined inside the group arrive final there);
        # phase 2 forwards across groups at fixed rank. Both phases
        # decompose into peer-offset ppermutes (neighbor schedules, in
        # the spirit of the direct-connect all-to-all schedules,
        # arxiv 2309.13541), and both buffers AGGREGATE many
        # destination pairs — a skewed pair borrows headroom from
        # quiet pairs instead of padding every [src, dst] slot to the
        # worst pair, which is where the ICI volume win comes from.
        # Determinism: rows carry their skey through both hops and the
        # merge orders arrivals by it (window path) or by (t, key)
        # (global path) — the route cannot reorder anything, so traces
        # are bit-identical to the direct all_to_all whenever neither
        # overflows (both fail loudly).
        TP_FIELDS = ("key",) + XF       # stacked ppermute channels

        def _tp_mask(ch, vals, ok):
            fill = IMAX if ch in ("key", "k") else \
                (INF if ch == "t" else 0)
            return jnp.where(ok, vals, fill)

        def _pack_two_phase(state, skey, perm, rows, my_shard):
            """Returns (state, keys, rows) of everything this shard
            received over both phases: phase-1 arrivals (deliveries
            AND forwards — callers mask non-local destinations) plus
            phase-2 arrivals (always local). CAP/CAP2 overflow is
            LOUD: phase-1 loss lands on the local sending host;
            phase-2 loss happens at the intermediate, so its count is
            psum'd home to the original sender's shard (behind a
            uniform-predicate cond — healthy flushes pay one scalar
            collective, nothing more)."""
            G = skey.shape[0]
            g, ng = TP_G, TP_NG
            my64 = my_shard.astype(jnp.int64)
            my_g, my_b = my64 // g, my64 % g
            state, starts, counts = _shard_segments(state, skey,
                                                    my_shard)

            counts2 = counts.reshape(ng, g)      # [dst group, rank]
            ends2 = jnp.cumsum(counts2, axis=0)
            off2 = ends2 - counts2               # exclusive, by group
            tot_rank = ends2[-1]                 # [g]

            # phase-1 overflow: within one RANK buffer, a row's slot
            # is its within-dst-shard rank plus the offset of earlier
            # groups' blocks; slots >= CAP never ship — counted
            # against the local sending host, like the direct pack
            shard_of, rank1 = _within_shard_rank(skey)
            d_clip = jnp.clip(shard_of, 0, n_shards - 1)
            pos1 = rank1 + off2.reshape(-1)[d_clip]
            lost1 = (skey < IMAX) & (shard_of != my64) & (pos1 >= CAP)
            state = _lost_to_local(state, lost1, skey, my_shard)

            # phase-1 buffers, keyed by peer OFFSET o (slot o goes to
            # in-group peer (a, (b+o) % g)): concatenated per-group
            # blocks of the rows destined that peer's rank
            ranks = (my_b + jnp.arange(g, dtype=jnp.int64)) % g
            ends_o = jnp.take(ends2, ranks, axis=1).T     # [g, ng]
            off_o = jnp.take(off2, ranks, axis=1).T       # [g, ng]
            starts_o = jnp.take(starts.reshape(ng, g), ranks,
                                axis=1).T                 # [g, ng]
            j1 = jnp.arange(CAP, dtype=jnp.int64)[None, :]
            a_star = jnp.clip(
                (ends_o[:, None, :] <= j1[..., None]).sum(-1),
                0, ng - 1)                                # [g, CAP]
            srcpos = jnp.take_along_axis(starts_o, a_star, axis=1) \
                + (j1 - jnp.take_along_axis(off_o, a_star, axis=1))
            ok1 = j1 < tot_rank[ranks][:, None]
            cidx = jnp.clip(srcpos, 0, G - 1).reshape(-1)
            pidx = jnp.take(perm, cidx)
            chans = []
            for ch in TP_FIELDS:
                # keys live in SORTED order (cidx); payload rows stay
                # unsorted and go through the sort permutation (pidx)
                v = jnp.take(skey, cidx) if ch == "key" \
                    else jnp.take(rows[ch], pidx)
                chans.append(_tp_mask(ch, v.reshape(g, CAP), ok1))
            sbuf = jnp.stack(chans)                       # [C, g, CAP]

            parts1 = [sbuf[:, 0]]
            for o in range(1, g):
                perm_o = [(s, (s // g) * g + ((s % g) + o) % g)
                          for s in range(n_shards)]
                parts1.append(lax.ppermute(sbuf[:, o], AXIS, perm_o))
            C = len(TP_FIELDS)
            recv1 = jnp.stack(parts1, axis=1).reshape(C, g * CAP)

            # phase 2: re-sort the received rows by skey (dst-shard
            # segments; every received row is destined rank my_b), my
            # own segment stays as deliveries, each other group's
            # segment forwards in one offset ppermute
            RK = g * CAP
            rkey_s, rperm = lax.sort(
                (recv1[0], jnp.arange(RK, dtype=jnp.int64)),
                num_keys=1)
            starts_r, counts_r = _shard_edges(rkey_s)
            shard_r, rank2 = _within_shard_rank(rkey_s)
            lost2 = (rkey_s < IMAX) & (shard_r != my64) & \
                (rank2 >= CAP2)
            n_lost2 = _axis_sum64(lost2.sum())

            def _attr2(_):
                # the lost rows' senders live on OTHER shards (this
                # shard is only the intermediate): histogram by
                # global source gid, psum over the mesh, and keep the
                # local window — each loss lands on its true sender
                sg = jnp.where(lost2, (rkey_s % SPAN) // OB, IMAX)
                sgs = lax.sort(sg)
                hbg = jnp.searchsorted(
                    sgs, jnp.arange(H_pad + 1, dtype=jnp.int64))
                hist = lax.psum(
                    (hbg[1:] - hbg[:-1]).astype(jnp.int32), AXIS)
                return lax.dynamic_slice(
                    hist, (my_shard * H_loc,), (H_loc,))

            state["x_overflow"] = state["x_overflow"] + lax.cond(
                n_lost2 > 0, _attr2,
                lambda _: jnp.zeros(H_loc, jnp.int32), 0)

            j2 = jnp.arange(CAP2, dtype=jnp.int64)
            parts2 = []
            for q in range(1, ng):
                dq = ((my_g + q) % ng) * g + my_b
                ok2 = j2 < jnp.minimum(counts_r[dq], CAP2)
                pidx2 = jnp.take(
                    rperm, jnp.clip(starts_r[dq] + j2, 0, RK - 1))
                buf2 = jnp.stack([
                    _tp_mask(ch, jnp.take(recv1[c], pidx2), ok2)
                    for c, ch in enumerate(TP_FIELDS)])
                perm_q = [(s, ((s // g + q) % ng) * g + s % g)
                          for s in range(n_shards)]
                parts2.append(lax.ppermute(buf2, AXIS, perm_q))

            out = jnp.concatenate([recv1] + parts2, axis=1)
            return state, out[0], \
                {f: out[c + 1] for c, f in enumerate(XF)}

        def _compact_flat(state, ob):
            """Gatherless outbox compaction for the GLOBAL merge
            (outbox_compact; the window path has its own in
            _flat_sorted): one 5-operand lane sort brings each
            host's exchangeable rows (t < DROP_T — they sort before
            judged-drop DROP_T markers and empty INF slots) to the
            front, then a STATIC slice keeps the first CX columns —
            zero gathers. Real rows beyond CX count loudly into
            x_overflow against the sending host. Shrinks the merge's
            double sort from H*(OB+E) to H*(CX+E) rows."""
            if CX >= OB:
                return state, \
                    {f: ob[f].reshape(H_loc * OB) for f in XF}
            st, sk, sm, ss, sv = lax.sort(
                (ob["t"], ob["k"], ob["m"], ob["s"], ob["v"]),
                dimension=1, num_keys=1)
            state["x_overflow"] = state["x_overflow"] + \
                (st[:, CX:] < DROP_T).sum(-1).astype(jnp.int32)
            comp = {"t": st, "k": sk, "m": sm, "s": ss, "v": sv}
            return state, {f: comp[f][:, :CX].reshape(H_loc * CX)
                           for f in XF}

        def _exchange_global(state, ob, gid, my_shard):
            lo = my_shard.astype(jnp.int32) * H_loc
            hi = lo + H_loc
            if n_shards > 1 and cfg.exchange == "all_to_all":
                # remote rows pack per (src shard, dst shard) for the
                # all_to_all (x_overflow accounting shared with the
                # window path); self-shard rows bypass the pack and
                # feed the merge directly. _flat_sorted already
                # compacts its returned rows to CX (and counts the
                # loss once) — reuse them for the self-shard part
                # instead of re-compacting ob
                state, skey, perm, rows = _flat_sorted(state, ob, gid)
                state, moved, _ = _pack_remote(
                    state, skey, perm, rows, my_shard,
                    ship_keys=False)
                parts = [
                    _ob_rows(rows["t"], rows["k"], rows["m"],
                             rows["s"], rows["v"], lo, hi),
                    _ob_rows(moved["t"], moved["k"], moved["m"],
                             moved["s"], moved["v"], lo, hi),
                ]
            elif n_shards > 1 and cfg.exchange == "two_phase":
                # hierarchical exchange; the received block still
                # holds the forwards this shard relayed (and any
                # phase-2 loss) — _ob_rows' [lo, hi) destination mask
                # drops them, so only true arrivals reach the merge
                state, skey, perm, rows = _flat_sorted(state, ob, gid)
                state, kout, rout = _pack_two_phase(
                    state, skey, perm, rows, my_shard)
                parts = [
                    _ob_rows(rows["t"], rows["k"], rows["m"],
                             rows["s"], rows["v"], lo, hi),
                    _ob_rows(rout["t"], rout["k"], rout["m"],
                             rout["s"], rout["v"], lo, hi),
                ]
            elif n_shards > 1:
                # all_gather fallback: replicate every shard's
                # (compacted) outbox rows — compaction also cuts the
                # replicated ICI volume OB -> CX; each shard keeps
                # its own via the [lo, hi) mask inside _ob_rows
                state, flat = _compact_flat(state, ob)
                W = flat["t"].shape[0]
                allf = {f: lax.all_gather(flat[f], AXIS)
                        .reshape(n_shards * W) for f in XF}
                parts = [_ob_rows(allf["t"], allf["k"], allf["m"],
                                  allf["s"], allf["v"], lo, hi)]
            else:
                state, flat = _compact_flat(state, ob)
                parts = [_ob_rows(flat["t"], flat["k"], flat["m"],
                                  flat["s"], flat["v"], lo, hi)]
            return _merge_rows(state, parts)

        def _exchange(state, ob, gid, my_shard, host_vertex, wrld,
                      win_end):
            if HOIST:
                state, ob = _judge_outbox(state, ob, gid, host_vertex,
                                          wrld, win_end)
            if CP:
                state = _count_paths(state, ob, host_vertex)
            # occupancy: exchangeable outbox rows per host this phase
            # (post-judge, the population outbox_compact must hold)
            state["occ_ob"] = jnp.maximum(
                state["occ_ob"],
                (ob["t"] < DROP_T).sum(-1).astype(jnp.int32))
            state["occ_phases"] = state["occ_phases"] + jnp.int32(1)
            if AUDIT:
                # conservation ledger: every exchangeable row
                # (post-judge t < DROP_T — sends, timers, READY
                # reinserts) must land in some host's heap or be
                # counted into overflow/x_overflow; _audit_round
                # balances this ledger against pops + live rows
                state["aud_tx"] = state["aud_tx"] + \
                    (ob["t"] < DROP_T).sum(-1).astype(jnp.int64)
            if MERGE_GLOBAL:
                return _exchange_global(state, ob, gid, my_shard)
            state, skey, perm, rows = _flat_sorted(state, ob, gid)
            G = H_loc * CX

            inc2 = None
            arr2 = jnp.zeros(H_loc, jnp.int32)
            if n_shards > 1 and cfg.exchange == "all_to_all":
                # SELF-SHARD rows (timers, model-NIC READY reinserts,
                # local sends — often half the outbox) never need to
                # move: they bypass the pack entirely (zero ICI, zero
                # CAP consumption) and reach the merge as a second
                # incoming block below. Only genuinely remote rows
                # pack into [n_shards, CAP] for the all_to_all.
                # my own range: straight per-host windows (IN each)
                state, inc2, arr2 = _host_windows(state, skey, perm,
                                                  rows, my_shard)

                state, moved, kmoved = _pack_remote(
                    state, skey, perm, rows, my_shard,
                    ship_keys=True)
                G = n_shards * CAP
                skey, perm = lax.sort(
                    (kmoved, jnp.arange(G, dtype=jnp.int64)),
                    num_keys=1)
                rows = moved
            elif n_shards > 1 and cfg.exchange == "two_phase":
                # self-shard bypass identical to the direct path;
                # the two-phase received block still holds relayed
                # forwards, whose skeys fall outside this shard's
                # host boundaries — _host_windows never takes them
                state, inc2, arr2 = _host_windows(state, skey, perm,
                                                  rows, my_shard)
                state, kout, rout = _pack_two_phase(
                    state, skey, perm, rows, my_shard)
                G = kout.shape[0]
                skey, perm = lax.sort(
                    (kout, jnp.arange(G, dtype=jnp.int64)),
                    num_keys=1)
                rows = rout
            elif n_shards > 1:
                # all_gather fallback: replicate every shard's rows,
                # then one global key re-sort (debug / hub-heavy)
                rows = {f: lax.all_gather(rows[f], AXIS)
                        .reshape(n_shards * G) for f in XF}
                kg = lax.all_gather(skey, AXIS).reshape(n_shards * G)
                pg = (lax.all_gather(perm, AXIS)
                      .reshape(n_shards, G)
                      + (jnp.arange(n_shards, dtype=jnp.int64)
                         * G)[:, None]).reshape(n_shards * G)
                skey, perm = lax.sort(
                    (kg, pg), num_keys=2)
                G = n_shards * G

            # my hosts' contiguous arrival segments -> [H_loc, IN]
            state, inc, arr = _host_windows(state, skey, perm, rows,
                                            my_shard)
            # occupancy: the self-shard bypass and the post-exchange
            # arrivals are windowed to IN separately, so the
            # capacity-relevant mark is the per-block max, not the sum
            state["occ_in"] = jnp.maximum(state["occ_in"],
                                          jnp.maximum(arr, arr2))

            # merge: one lexicographic row sort of [live heap | inc
            # (| self-shard inc)] by (time, src<<32|seq) — keys +
            # column iota only; payload columns follow via
            # take_along_axis
            def _inc_cols(b):
                kindb = lo32(b["m"]) & 0xFF    # strip the train count
                return (b["t"], b["k"],
                        pack2(kindb, hi32(b["s"])),
                        pack2(lo32(b["s"]), lo32(b["v"])),
                        (b["v"] >> 32) & U32)  # d2 (train survivors)

            blocks = [_inc_cols(inc)]
            if inc2 is not None:
                blocks.append(_inc_cols(inc2))
            live = jnp.arange(E)[None, :] >= state["head"][:, None]
            mt = jnp.where(live, state["ht"], INF)
            mk = jnp.where(live, state["hk"], IMAX)
            WID = E + IN * len(blocks)
            ct = jnp.concatenate([mt] + [b[0] for b in blocks], axis=1)
            ck = jnp.concatenate([mk] + [b[1] for b in blocks], axis=1)
            ci = jnp.broadcast_to(
                jnp.arange(WID, dtype=jnp.int32)[None, :],
                (H_loc, WID))
            st, sk, si = lax.sort((ct, ck, ci), dimension=1,
                                  num_keys=2)
            state["overflow"] = state["overflow"] + \
                (st[:, E:] < INF).sum(-1).astype(jnp.int32)
            sie = si[:, :E]
            cm = jnp.concatenate([state["hm"]] + [b[2] for b in blocks],
                                 axis=1)
            cv = jnp.concatenate([state["hv"]] + [b[3] for b in blocks],
                                 axis=1)
            cw = jnp.concatenate([state["hw"]] + [b[4] for b in blocks],
                                 axis=1)
            state["ht"] = st[:, :E]
            state["hk"] = sk[:, :E]
            state["hm"] = jnp.take_along_axis(cm, sie, axis=1)
            state["hv"] = jnp.take_along_axis(cv, sie, axis=1)
            state["hw"] = jnp.take_along_axis(cw, sie, axis=1)
            state["head"] = jnp.zeros_like(state["head"])
            # occupancy: live heap rows after the merge — the rows
            # event_capacity must hold
            state["occ_heap"] = jnp.maximum(
                state["occ_heap"],
                (state["ht"] < INF).sum(-1).astype(jnp.int32))
            return state

        # ---------------- round-end invariant audit --------------------
        # The health word: four cheap reduction-only checks folded
        # into each host's `aud` bitmask at the end of every round.
        # Reductions + one scalar all_gather only — no sorts, no
        # gathers — so an audited run costs a fraction of one flush.
        def _axis_sum64(x):
            return lax.all_gather(
                jnp.reshape(x.astype(jnp.int64), (1,)), AXIS).sum()

        def _audit_round(state):
            head, ht, hk = state["head"], state["ht"], state["hk"]
            # heap rows must be (t, key)-lexicographically sorted
            # (INF-padded tails sort last by construction) and the
            # head cursor in [0, E]
            ok_heap = ((ht[:, :-1] < ht[:, 1:]) |
                       ((ht[:, :-1] == ht[:, 1:]) &
                        (hk[:, :-1] <= hk[:, 1:]))).all(-1)
            ok_heap = ok_heap & (head >= 0) & (head <= E)
            neg = jnp.zeros(ht.shape[0], bool)
            for key in ("n_exec", "n_sent", "n_drop", "n_deliv",
                        "event_seq", "packet_seq", "app_seq"):
                neg = neg | (state[key] < 0)
            # event-row conservation: rows produced (boot/stop seed +
            # every exchanged outbox row) == rows popped + rows live
            # in heaps + rows loudly counted lost. The balance is
            # global (a packet leaves one shard and lands on
            # another), so the per-shard differences sum over the
            # mesh — a collective, uniform across shards exactly like
            # the round predicates around it.
            live = ((jnp.arange(E)[None, :] >= head[:, None]) &
                    (ht < INF)).sum()
            diff = state["aud_tx"].sum() - (
                state["n_exec"].astype(jnp.int64).sum()
                + live.astype(jnp.int64)
                + state["overflow"].astype(jnp.int64).sum()
                + state["x_overflow"].astype(jnp.int64).sum())
            conserved = _axis_sum64(diff) == 0
            aud = state["aud"]
            aud = aud | jnp.where(ok_heap, jnp.int32(0),
                                  jnp.int32(AUD_HEAP))
            aud = aud | jnp.where(neg, jnp.int32(AUD_COUNTER),
                                  jnp.int32(0))
            aud = aud | jnp.where(conserved, jnp.int32(0),
                                  jnp.int32(AUD_CONSERVE))
            state["aud"] = aud
            return state

        # ---------------- one round (window) ---------------------------
        # A window may take several phases: each phase pops up to B
        # events per host (or until every host is drained below
        # win_end / stalled on an in-window insert), then flushes. The
        # window advances only when no host has events under the
        # barrier; the predicate is a collective, so all shards agree.
        def _round(state, win_end, gid, my_shard, host_vertex, wrld):
            def _phase(state):
                ob = {"t": jnp.full((H_loc, OB), INF, jnp.int64)}
                for f in ("k", "m", "s", "v"):
                    ob[f] = jnp.zeros((H_loc, OB), jnp.int64)
                dirty = jnp.zeros((H_loc,), bool)

                def cond(c):
                    state_, _, blk, dirty_ = c
                    nt = _take_head(state_["ht"], state_["head"], INF)
                    return ((nt < win_end) & ~dirty_).any() & \
                        (blk < B)

                carry = lax.while_loop(
                    cond,
                    lambda c: _step(c, win_end, gid, host_vertex,
                                    wrld),
                    (state, ob, jnp.int32(0), dirty))
                state2, ob, blk, _ = carry
                state2["occ_trips"] = jnp.maximum(
                    state2["occ_trips"], jnp.reshape(blk, (1,)))
                # skip the whole exchange when nothing was sent and no
                # slots were consumed (idle windows). The predicate is
                # COLLECTIVE: the flush contains all_to_all, so every
                # shard must take the same branch
                any_work = (ob["t"] < INF).any() | \
                    (state2["head"] > 0).any()
                go = _axis_min(jnp.where(any_work, jnp.int64(0),
                                         jnp.int64(1))) == 0
                return lax.cond(
                    go,
                    lambda s: _exchange(s, ob, gid, my_shard,
                                        host_vertex, wrld,
                                        win_end),
                    lambda s: s,
                    state2)

            def more(state):
                return _axis_min(
                    jnp.where((state["ht"][:, 0] < win_end).any(),
                              jnp.int64(0), jnp.int64(1))) == 0

            state = _phase(state)
            state, _ = lax.while_loop(
                lambda c: c[1],
                lambda c: (lambda s: (s, more(s)))(_phase(c[0])),
                (state, more(state)))
            if AUDIT:
                state = _audit_round(state)
            return state

        # ---------------- full run ------------------------------------
        # cross-shard min via all_gather: some TPU AOT toolchains lower
        # only Sum all-reduces, so pmin is expressed as gather+min
        # (identical result; the gathered vector is tiny: one scalar
        # per device)
        def _axis_min(x):
            return lax.all_gather(jnp.reshape(x, (1,)), AXIS).min()

        def _run_shard(state, host_vertex, wrld, stop, final_stop):
            # `stop` is where THIS invocation pauses (a traced scalar,
            # so one compiled program serves every slice length);
            # `final_stop` is the simulation end that window boundaries
            # clamp to — pausing at heartbeat boundaries therefore
            # yields the EXACT window sequence of an unsegmented run
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)

            def next_time(state):
                # rows are sorted and slots < head are INF-free only
                # after a flush; take the per-host head element
                return _axis_min(
                    _take_head(state["ht"], state["head"], INF).min())

            def cond(c):
                state, nxt, rounds = c
                return (nxt < stop) & (rounds < cfg.max_rounds)

            def body(c):
                state, nxt, rounds = c
                win_end = jnp.minimum(nxt + LOOKAHEAD, final_stop)
                state = _round(state, win_end, gid, my_shard,
                               host_vertex, wrld)
                return state, next_time(state), rounds + 1

            state, _, rounds = lax.while_loop(
                cond, body, (state, next_time(state), jnp.int64(0)))
            return state, rounds

        # one window as a standalone jitted step (also used by
        # __graft_entry__; works on any mesh size including 1)
        def _one_round(state, win_end, host_vertex, wrld):
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)
            state = _round(state, win_end, gid, my_shard,
                           host_vertex, wrld)
            nxt = _axis_min(
                _take_head(state["ht"], state["head"], INF).min())
            return state, nxt

        # ---------------- phase-split profiling path -------------------
        # the per-round cost hunt (BASELINE.md's 181 ms/round budget)
        # needs pop-loop vs exchange vs merge attribution; these split
        # jits let a host-side driver time each piece. They are traced
        # lazily (first call), so the normal path pays nothing.
        def _pop_shard(state, ob, host_vertex, wrld, win_end):
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)
            dirty = jnp.zeros((H_loc,), bool)

            def cond(c):
                state_, _, blk, dirty_ = c
                nt = _take_head(state_["ht"], state_["head"], INF)
                return ((nt < win_end) & ~dirty_).any() & (blk < B)

            state, ob, blk, _ = lax.while_loop(
                cond,
                lambda c: _step(c, win_end, gid, host_vertex, wrld),
                (state, ob, jnp.int32(0), dirty))
            state["occ_trips"] = jnp.maximum(
                state["occ_trips"], jnp.reshape(blk, (1,)))
            return state, ob, jnp.reshape(blk, (1,))

        def _flush_shard(state, ob, host_vertex, wrld, win_end):
            my_shard = lax.axis_index(AXIS)
            gid = (my_shard * H_loc + hidx).astype(jnp.int32)
            return _exchange(state, ob, gid, my_shard, host_vertex,
                             wrld, win_end)

        spec_keys = ("ht", "hk", "hm", "hv", "hw", "head",
                     "event_seq", "packet_seq", "app_seq", "app",
                     "n_exec", "n_sent", "n_drop", "n_deliv",
                     "overflow", "x_overflow", "chk",
                     "occ_heap", "occ_ob", "occ_in", "occ_x",
                     "occ_trips", "occ_phases") + \
            (AUD_KEYS if AUDIT else ()) + \
            (NIC_KEYS if MB else ()) + \
            (("path_cnt",) if CP else ())
        specs = {k: self._shard_spec for k in spec_keys}
        ob_specs = {f: self._shard_spec for f in XF}
        repl = self._repl_spec
        wspec = (repl,) * 5          # (lat, rel, k1, k2, epoch_times)
        self._run = jax.jit(shard_map(
            _run_shard, mesh=self.mesh,
            in_specs=(specs, repl, wspec, repl, repl),
            out_specs=(specs, repl),
            check_vma=False,
        ))
        self._round_step = jax.jit(shard_map(
            _one_round, mesh=self.mesh,
            in_specs=(specs, repl, repl, wspec),
            out_specs=(specs, repl),
            check_vma=False,
        ))
        self._pop_phase = jax.jit(shard_map(
            _pop_shard, mesh=self.mesh,
            in_specs=(specs, ob_specs, repl, wspec, repl),
            out_specs=(specs, ob_specs, self._shard_spec),
            check_vma=False,
        ))
        self._flush_phase = jax.jit(shard_map(
            _flush_shard, mesh=self.mesh,
            in_specs=(specs, ob_specs, repl, wspec, repl),
            out_specs=specs,
            check_vma=False,
        ))
        self._ob_shape_global = (H_pad, OB)

        # ---------------- ensemble program -----------------------------
        # The R-replica campaign: the SAME per-shard round program,
        # vmapped over a leading replica axis of (state, world) INSIDE
        # the host shard_map — the replica axis composes outside the
        # mesh axis, so multichip exchange is untouched and each
        # replica's trace is the standalone program's, value for value
        # (vmap batches while_loops by freezing finished replicas'
        # carries with selects — it never re-executes their updates).
        # Only array VALUES vary per replica (seed keys, topology
        # tables, epoch times); every shape is shared.
        if self.ensemble is not None:
            # NB: `P` (the PartitionSpec alias) is shadowed by the
            # burst width in this scope — use the unaliased name
            ens_spec = PartitionSpec(None, *self._shard_spec)
            especs = {k: ens_spec for k in spec_keys}

            def _run_ens_shard(states, host_vertex, wrlds, stop,
                               final_stop):
                return jax.vmap(
                    lambda st, w: _run_shard(st, host_vertex, w,
                                             stop, final_stop),
                    in_axes=(0, 0))(states, wrlds)

            self._run_ens = jax.jit(shard_map(
                _run_ens_shard, mesh=self.mesh,
                in_specs=(especs, repl, wspec, repl, repl),
                out_specs=(especs, repl),
                check_vma=False,
            ))
            self._ens_spec = ens_spec

        def _probe(state):
            head = state["head"]
            nt = jnp.take_along_axis(
                state["ht"], jnp.minimum(head, E - 1)[:, None],
                axis=1)[:, 0]
            nt = jnp.where(head < E, nt, INF)
            return nt.min(), head.sum()

        self._probe = jax.jit(_probe)

    # ------------------------------------------------------------------
    def _aot(self, name: str, jit_fn, args):
        """Resolve program `name` through the AOT compile cache on
        first use (cached executable, or AOT-compile + store on a
        miss) and return the callable to dispatch — the original
        lazy jit when no cache is attached or the cache layer
        declined. One bookkeeping site for every cached program."""
        if self.aot_cache is not None and name not in self._aot_exec:
            self._aot_exec[name] = self.aot_cache.ensure(
                self, name, jit_fn, args)
        return self._aot_exec.get(name, jit_fn)

    def world(self):
        """The traced world tuple (lat, rel, seed k1, seed k2,
        epoch_times) for the engine's own base world, replicated over
        the mesh — everything a run may vary without changing shapes
        (the ensemble program stacks R of these). Cached: the arrays
        are fixed at construction, and run()/profile() call per
        segment — re-uploading the tables each dispatch would be pure
        waste over a tunneled TPU."""
        if getattr(self, "_world_dev", None) is None:
            repl = NamedSharding(self.mesh, self._repl_spec)
            k1, k2 = self.seed_pair

            def put(a):
                return jax.device_put(jnp.asarray(a), repl)

            self._world_dev = (
                jax.tree_util.tree_map(put, self.latency),
                jax.tree_util.tree_map(put, self.reliability),
                put(k1), put(k2), put(self.epoch_times))
        return self._world_dev

    # ------------------------------------------------------------------
    # static-analysis surface (shadow_tpu/analyze, scripts/analyze.py)
    # ------------------------------------------------------------------
    # The jaxpr audit needs to TRACE every dispatchable program
    # without touching a device: these methods export the lowerable-
    # program registry (name -> (jit fn, abstract args)) plus the
    # collective registry (which cross-shard collectives this build is
    # ALLOWED to contain, with the capacities their buffers are pinned
    # to). determinism_gate --analyze-consistency cross-checks the
    # registry against effective{} at runtime so the static allowlist
    # cannot drift from the real program.
    def state_structs(self) -> dict:
        """jax.ShapeDtypeStruct pytree mirroring init_state's output —
        the abstract argument surface for .trace()/.lower() with zero
        device work (the analyzer must perturb nothing)."""
        import numpy as _np

        H, E = self.H_pad, self.config.event_capacity
        S = self.n_shards

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype)

        out = {k: sds((H, E), _np.int64)
               for k in ("ht", "hk", "hm", "hv", "hw")}
        for k in ("head", "event_seq", "packet_seq", "app_seq",
                  "n_exec", "n_sent", "n_drop", "n_deliv",
                  "overflow", "x_overflow",
                  "occ_heap", "occ_ob", "occ_in"):
            out[k] = sds((H,), _np.int32)
        out["chk"] = sds((H,), _np.int64)
        out["app"] = sds((H, int(self.app.n_state_words)), _np.int32)
        out["occ_x"] = sds((S, S), _np.int32)
        out["occ_trips"] = sds((S,), _np.int32)
        out["occ_phases"] = sds((S,), _np.int32)
        if self.config.audit:
            out["aud"] = sds((H,), _np.int32)
            out["aud_t"] = sds((H,), _np.int64)
            out["aud_tx"] = sds((H,), _np.int64)
        if self.config.count_paths:
            out["path_cnt"] = sds((S, self.n_vertices ** 2),
                                  _np.int64)
        if self.config.model_bandwidth:
            for k in NIC_KEYS:
                out[k] = sds((H,), _np.int64)
        return out

    def world_structs(self, ensemble: bool = False) -> tuple:
        """Abstract twin of world() / ensemble_worlds_device()."""
        import numpy as _np

        def sds(p):
            p = _np.asarray(p)
            return jax.ShapeDtypeStruct(p.shape, p.dtype)

        if ensemble:
            ens = self.ensemble
            if isinstance(ens.latency, tuple):
                # hierarchical leaves arrive final-dtyped from
                # build_worlds (i32 int leaves / f32 reliability)
                lat = jax.tree_util.tree_map(sds, ens.latency)
                rel = jax.tree_util.tree_map(sds, ens.reliability)
            else:
                lat = sds(_np.asarray(ens.latency, _np.int32))
                rel = sds(_np.asarray(ens.reliability, _np.float32))
            parts = (lat, rel,
                     sds(_np.asarray(ens.seed_k1, _np.uint32)),
                     sds(_np.asarray(ens.seed_k2, _np.uint32)),
                     sds(_np.asarray(ens.epoch_times, _np.int64)))
            return parts
        k1, k2 = self.seed_pair
        return (jax.tree_util.tree_map(sds, self.latency),
                jax.tree_util.tree_map(sds, self.reliability),
                sds(k1), sds(k2), sds(self.epoch_times))

    def lowerable_programs(self) -> dict:
        """name -> (jit fn, abstract args) for every program the
        engine dispatches — the same names the AOT cache keys on
        ("run", "run_ens", "pop", "flush"), so the audit surface and
        the cached surface cannot drift apart."""
        import numpy as _np

        s = self.state_structs()
        hv = jax.ShapeDtypeStruct((self.H_pad,), _np.int32)
        t = jax.ShapeDtypeStruct((), _np.int64)
        ob = {f: jax.ShapeDtypeStruct(self._ob_shape_global,
                                      _np.int64)
              for f in ("t", "k", "m", "s", "v")}
        w = self.world_structs()
        progs = {
            "run": (self._run, (s, hv, w, t, t)),
            "pop": (self._pop_phase, (s, ob, hv, w, t)),
            "flush": (self._flush_phase, (s, ob, hv, w, t)),
        }
        if self.ensemble is not None:
            R = int(self.ensemble.R)
            es = {k: jax.ShapeDtypeStruct((R,) + v.shape, v.dtype)
                  for k, v in s.items()}
            progs["run_ens"] = (
                self._run_ens,
                (es, hv, self.world_structs(ensemble=True), t, t))
        return progs

    def collective_registry(self) -> dict:
        """The cross-shard collectives this build is allowed to lower
        to: primitive name -> {"axis", "caps"} where caps pins the
        trailing buffer dimension of the capacity-carrying movers
        (None = shape not capacity-pinned: scalar reductions and
        whole-outbox replication). Derived from the SAME resolved
        config effective{} reports, so the runtime cross-check
        (determinism_gate --analyze-consistency) is exact."""
        eff = self.effective
        reg = {
            # axis_index / scalar all_gather reductions (_axis_min,
            # the audit's _axis_sum64) exist on every mesh size
            "axis_index": {"axis": AXIS, "caps": None},
            "all_gather": {"axis": AXIS, "caps": None},
        }
        if self.n_shards > 1:
            if eff["exchange"] == "all_to_all":
                reg["all_to_all"] = {"axis": AXIS,
                                     "caps": (int(eff["CAP"]),)}
            elif eff["exchange"] == "two_phase":
                reg["ppermute"] = {"axis": AXIS,
                                   "caps": (int(eff["CAP"]),
                                            int(eff["CAP2"]))}
                # phase-2 loss attribution psum: the histogram is
                # [H_pad]; the loss predicate is a scalar
                reg["psum"] = {"axis": AXIS,
                               "caps": (1, int(self.H_pad))}
            # exchange == all_gather reuses the all_gather entry
        return reg

    def audit_consts(self) -> dict:
        """The closure constants the jaxpr audit ACCEPTS in this
        engine's traced programs, by value. Every entry must carry a
        `# shadowlint: const-ok(reason)` comment at its capture site
        in this file (the audit cross-checks), and its bytes must be
        covered by the AOT cache key — via the code digest for
        module-level tables, via bw_digest for the bandwidth
        vectors. Anything else non-scalar captured by a trace is a
        leaked world value (stale-cache + broken-ensemble hazard)."""
        import numpy as _np

        from shadow_tpu.host.model_nic import LAW

        out = {"model_nic.LAW": _np.asarray(LAW)}
        if self.config.model_bandwidth:
            out["bw_up"] = _np.asarray(self.bw_up)
            out["bw_down"] = _np.asarray(self.bw_down)
        # per-host parameter arrays the app bakes into its traced
        # handle() (tgen client count/pause/retry vectors, tor relay
        # tables): capacity.app_fingerprint hashes EXACTLY the
        # ndarray attributes of the app into the cache key's
        # workload_fp, so using the same selection rule here makes
        # the allowance fingerprint-covered by construction (a test
        # pins that each array flips the fingerprint).
        for k, v in sorted(vars(self.app).items()):
            if isinstance(v, _np.ndarray):
                out[f"app:{k}"] = v
        return out

    def host_vertex_device(self):
        """The host->vertex table on device, replicated over the
        mesh — cached like world(): run()/run_ensemble() dispatch
        once per pipeline segment, and re-uploading the table on
        every issue would tax each enqueue with a device_put for
        nothing. The table is fixed at construction."""
        if getattr(self, "_hv_dev", None) is None:
            repl = NamedSharding(self.mesh, self._repl_spec)
            self._hv_dev = jax.device_put(
                jnp.asarray(self.host_vertex), repl)
        return self._hv_dev

    def live_bytes(self) -> int:
        """Measured live device bytes across this engine's mesh,
        attributed per buffer by its sharding (a buffer spanning k
        devices contributes nbytes/k per device; the return is the
        MAX per-device total — what admission compares to a
        per-device budget). Uses jax.live_arrays(), which works on
        every backend including cpu — the estimator honesty tests
        run on the forced-multi-device cpu mesh."""
        mesh_ids = {d.id for d in self.mesh.devices.flat}
        per_dev: dict = {}
        for arr in jax.live_arrays():
            try:
                devs = [d for d in arr.sharding.device_set
                        if d.id in mesh_ids]
                if not devs:
                    continue
                share = arr.nbytes // max(1, len(arr.sharding
                                                 .device_set))
            except Exception:       # deleted/donated buffers race
                continue
            for d in devs:
                per_dev[d.id] = per_dev.get(d.id, 0) + share
        return max(per_dev.values(), default=0)

    def device_memory_stats(self):
        """(bytes_in_use, bytes_limit) from the backend's allocator
        when it exposes them (TPU/GPU memory_stats), else None — the
        heartbeat lines print `n/a` then."""
        try:
            dev = list(self.mesh.devices.flat)[0]
            ms = dev.memory_stats()
            if not ms:
                return None
            in_use = int(ms.get("bytes_in_use", 0) or 0)
            limit = int(ms.get("bytes_limit", 0) or 0)
            if in_use <= 0 and limit <= 0:
                return None
            return in_use, limit
        except Exception:
            return None

    def run(self, state: dict, stop: Optional[int] = None,
            final_stop: Optional[int] = None):
        """Run to `stop` (default config.stop_time); returns
        (final_state, rounds) on device. Both stops are runtime
        scalars — every slice length reuses one compiled program.
        `final_stop` (default = stop) is the window-clamping horizon:
        pass the simulation end when pausing at intermediate
        boundaries (heartbeats) so the window sequence — and thus the
        trace — is identical to an unsegmented run.

        This call never synchronizes: it enqueues the compiled
        program and returns asynchronous device arrays, so the
        segment pipeline (supervise.advance) can keep several
        segments in flight while the host drains earlier ones."""
        hv = self.host_vertex_device()
        stop_v = jnp.int64(self.config.stop_time if stop is None
                           else stop)
        final_v = stop_v if final_stop is None else jnp.int64(final_stop)
        # warm start via the AOT cache: stops are runtime scalars, so
        # the one executable serves every slice
        args = (state, hv, self.world(), stop_v, final_v)
        return self._aot("run", self._run, args)(*args)

    # ------------------------------------------------------------------
    # ensemble campaign (shadow_tpu/ensemble/): R replicas in one
    # compiled program
    # ------------------------------------------------------------------
    def init_ensemble_state(self, starts: list[tuple]) -> dict:
        """[R, ...]-stacked initial state: every replica starts from
        the identical boot/stop schedule (vary axes change values —
        seeds, tables — never the start layout), so the stack is one
        on-device broadcast of the standalone initial state."""
        if self.ensemble is None:
            raise ValueError("engine was built without ensemble "
                             "worlds")
        base = self.init_state(starts)
        if getattr(self, "_ens_broadcaster", None) is None:
            # one jitted whole-dict broadcast, cached on the engine:
            # a fresh jit per leaf per call would retrace every leaf
            # on every init (warm-up, re-plan retries, resume
            # templates all re-init)
            R = int(self.ensemble.R)
            ens_shard = NamedSharding(self.mesh, self._ens_spec)
            self._ens_broadcaster = jax.jit(
                lambda tree: {
                    k: jnp.broadcast_to(v[None], (R,) + v.shape)
                    for k, v in tree.items()},
                out_shardings=ens_shard)
        return self._ens_broadcaster(base)

    def ensemble_worlds_device(self):
        """The stacked per-replica world tuple, replicated over the
        mesh (the replica axis is vmapped, not sharded). Cached like
        world(): run_ensemble is called once per heartbeat/dispatch
        segment, and the stacked tables never change after build."""
        if getattr(self, "_ens_world_dev", None) is None:
            ens = self.ensemble
            repl = NamedSharding(self.mesh, self._repl_spec)

            def put(a):
                return jax.device_put(jnp.asarray(a), repl)

            if isinstance(ens.latency, tuple):
                # hierarchical leaves are final-dtyped by build_worlds
                lat = jax.tree_util.tree_map(put, ens.latency)
                rel = jax.tree_util.tree_map(put, ens.reliability)
            else:
                lat = put(np.asarray(ens.latency, dtype=np.int32))
                rel = put(np.asarray(ens.reliability,
                                     dtype=np.float32))
            self._ens_world_dev = (
                lat, rel,
                put(np.asarray(ens.seed_k1, dtype=np.uint32)),
                put(np.asarray(ens.seed_k2, dtype=np.uint32)),
                put(np.asarray(ens.epoch_times, dtype=np.int64)),
            )
        return self._ens_world_dev

    def run_ensemble(self, states: dict, stop: Optional[int] = None,
                     final_stop: Optional[int] = None):
        """Advance all R replicas to `stop` in one dispatch of the
        vmapped program; returns ([R, ...] states, [R] rounds).
        Window clamping stays on `final_stop` exactly as in `run`, so
        segmented campaigns (heartbeats, dispatch_segment) replay the
        unsegmented window sequence per replica. Like `run`, this is
        a pure asynchronous enqueue — campaigns pipeline too."""
        hv = self.host_vertex_device()
        stop_v = jnp.int64(self.config.stop_time if stop is None
                           else stop)
        final_v = stop_v if final_stop is None else jnp.int64(final_stop)
        args = (states, hv, self.ensemble_worlds_device(), stop_v,
                final_v)
        return self._aot("run_ens", self._run_ens, args)(*args)

    def profile(self, state: dict, stop: Optional[int] = None) -> dict:
        """Phase-split run with host-side wall timing: the same round
        structure as `run`, but each pop loop / flush executes as its
        own jitted call with a block_until_ready fence, attributing
        wall time to pop vs exchange+merge vs the host-sync probe.
        Numbers include per-call dispatch + sync overhead the fused
        `run` does not pay — use the breakdown for RATIOS and the
        fused run for totals. Single- or multi-shard."""
        import time as _time

        repl = NamedSharding(self.mesh, self._repl_spec)
        shard = NamedSharding(self.mesh, self._shard_spec)
        hv = jax.device_put(jnp.asarray(self.host_vertex), repl)
        wrld = self.world()
        stop_t = self.config.stop_time if stop is None else stop
        LA = max(1, self.config.lookahead)

        def _ob():
            ob = {"t": jax.device_put(
                jnp.full(self._ob_shape_global, INF, jnp.int64),
                shard)}
            for f in ("k", "m", "s", "v"):
                ob[f] = jax.device_put(
                    jnp.zeros(self._ob_shape_global, jnp.int64), shard)
            return ob

        prof = {"rounds": 0, "phases": 0, "events": 0,
                "pop_s": 0.0, "flush_s": 0.0, "probe_s": 0.0,
                "compile_s": 0.0}
        # compile both split programs up front so timings are steady;
        # the AOT cache turns repeat profiles into warm starts (the
        # split programs get their own cache keys)
        t0 = _time.perf_counter()
        win0 = jnp.int64(0)
        pop_fn = self._aot("pop", self._pop_phase,
                           (state, _ob(), hv, wrld, win0))
        s_w, ob_w, _ = pop_fn(state, _ob(), hv, wrld, win0)
        flush_fn = self._aot("flush", self._flush_phase,
                             (s_w, ob_w, hv, wrld, win0))
        jax.block_until_ready(flush_fn(s_w, ob_w, hv, wrld, win0))
        jax.block_until_ready(self._probe(state))
        prof["compile_s"] = _time.perf_counter() - t0

        # the phase-split programs are the one place EXCHANGE wall is
        # measured host-side (the fused run buries the flush inside
        # the dispatch span) — record the splits as flight-recorder
        # spans so a profiled run's trace shows pop vs flush lanes
        from shadow_tpu.obs import trace as obstrace
        tracer = obstrace.current()

        exec0 = int(jnp.sum(state["n_exec"]))
        t0 = _time.perf_counter()
        nxt, _ = map(int, self._probe(state))
        prof["probe_s"] += _time.perf_counter() - t0
        t_all = _time.perf_counter()
        while nxt < stop_t and prof["rounds"] < 10_000:
            win_end = jnp.int64(min(nxt + LA, stop_t))
            while True:
                t0 = _time.perf_counter()
                with tracer.span("profile.pop", "dispatch",
                                 sim_t0=nxt, sim_t1=int(win_end)):
                    state, ob, _ = pop_fn(state, _ob(), hv, wrld,
                                          win_end)
                    jax.block_until_ready(state)
                prof["pop_s"] += _time.perf_counter() - t0

                t0 = _time.perf_counter()
                with tracer.span("profile.flush", "exchange",
                                 sim_t0=nxt, sim_t1=int(win_end)):
                    state = flush_fn(state, ob, hv, wrld,
                                     win_end)
                    jax.block_until_ready(state)
                prof["flush_s"] += _time.perf_counter() - t0
                prof["phases"] += 1

                t0 = _time.perf_counter()
                nu, _ = map(int, self._probe(state))
                prof["probe_s"] += _time.perf_counter() - t0
                if nu >= int(win_end):
                    break
            prof["rounds"] += 1
            nxt = nu
        prof["wall_s"] = _time.perf_counter() - t_all
        prof["events"] = int(jnp.sum(state["n_exec"])) - exec0
        prof["final_state"] = state
        return prof
