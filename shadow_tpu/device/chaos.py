"""Deterministic chaos injection at the supervise/engine seams.

The failover machinery (dispatch retry, mesh-shrink, hybrid rerun —
device/supervise.py) exists for failure modes no CI box exhibits on
demand: a chip dying mid-campaign, a checkpoint write torn by the
filesystem, a cache store hitting a full disk. This module makes
those failures SCRIPTABLE and byte-for-byte reproducible, so the
recovery ladder is drilled in CI the same way determinism is gated:
``experimental.chaos`` declares a schedule of fault points, and the
injector fires each one at a deterministic seam counter — never from
a timer, a signal, or randomness — so the same schedule against the
same config reproduces the identical run, failures included.

Fault kinds (:data:`KINDS`):

* ``device_loss`` — at the ``segment``-th dispatch issue of the
  supervised advance loop, the mesh device at position ``shard``
  is marked DEAD. Every subsequent dispatch on a mesh containing a
  dead device raises the scripted ``error`` class — exactly the
  shape of a real chip loss (retries exhaust because the segment can
  never drain clean) — until a mesh shrink rebuilds the engine on
  the survivors, after which dispatches succeed again. The liveness
  probe (supervise.surviving_devices) consults :meth:`is_dead` so a
  scripted death fails the probe the way a real one would.
* ``dispatch_error`` — a ONE-SHOT error at the ``segment``-th
  dispatch issue (transient-retry drills; a non-transient ``error``
  class drills the abort path).
* ``checkpoint_corrupt`` — after the ``entry``-th rotating
  checkpoint save lands on disk, truncate the file mid-payload (the
  artifact a SIGKILL can leave) so the newest-readable rotation
  fallback (supervise.resolve_checkpoint) must engage on resume.
* ``cache_store_fail`` — the ``store``-th AOT compile-cache store
  is refused (full-disk drill); the cache must degrade loudly to an
  unpersisted fresh compile, never abort the run.
* ``oom`` — a scripted ``RESOURCE_EXHAUSTED`` at the ``segment``-th
  dispatch issue OR the ``compile``-th program compile (exactly one).
  Unlike ``dispatch_error`` the fault REPEATS at every later count —
  a real out-of-memory is deterministic: the same too-big program
  fails every time — until the degradation ladder engages a rung
  (:meth:`ChaosInjector.on_degrade_rung`), after which it clears the
  way a real OOM clears once the footprint shrinks. This is the CI
  drill for supervise.advance's degrade ladder
  (``determinism_gate --degrade``).

Counters are seam-local and monotonic: dispatch issues count every
``dispatch.issue`` of supervise.advance (replays after a recovery
included — control flow is deterministic, so the count sequence is
too), rotation saves count Checkpointer.save calls, cache stores
count AotCache.store calls. All injector state is lock-protected and
registered in the concurrency lint's LOCK_REGISTRY
(shadow_tpu/analyze/concurrency.py).

The injector is process-global per run (``set_current`` /
``current``), installed by DeviceRunner.__init__ from the validated
config — a run without a chaos schedule installs None, so schedules
never leak across in-process runs (gates, tests).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from shadow_tpu.utils.slog import get_logger

log = get_logger("chaos")

KINDS = ("device_loss", "dispatch_error", "checkpoint_corrupt",
         "cache_store_fail", "oom", "server_crash")

# transient by default: UNAVAILABLE matches supervise.TRANSIENT_MARKERS
# so the scripted loss walks the real retry -> escalate ladder
DEFAULT_ERROR = "UNAVAILABLE"
# oom events default to the class a real allocator failure raises, so
# supervise.is_oom routes them onto the degradation ladder
OOM_ERROR = "RESOURCE_EXHAUSTED"


class ChaosError(RuntimeError):
    """A scripted fault. The message leads with the event's error
    class so supervise.is_transient classifies it exactly like the
    real XlaRuntimeError it stands in for."""


@dataclass(frozen=True)
class ChaosEvent:
    """One validated ``experimental.chaos`` entry."""

    kind: str
    segment: int = -1      # device_loss/dispatch_error/oom: dispatch #
    shard: int = -1        # device_loss: mesh position of the dying chip
    error: str = DEFAULT_ERROR
    entry: int = -1        # checkpoint_corrupt: rotation save #
    store: int = -1        # cache_store_fail: cache store #
    compile: int = -1      # oom: program compile #
    tick: int = -1         # server_crash: campaign-server scheduler tick #


def event_from_dict(i: int, d: dict) -> ChaosEvent:
    """One ``experimental.chaos[i]`` mapping -> a validated
    ChaosEvent. Structural validation happens at config load (the
    network.faults rule): a typo'd schedule must fail in
    milliseconds, not as a run that silently never injects."""
    section = f"experimental.chaos[{i}]"
    if not isinstance(d, dict):
        raise ValueError(f"{section} must be a mapping")
    allowed = {"kind", "segment", "shard", "error", "entry", "store",
               "compile", "tick"}
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(f"unknown key(s) in {section}: "
                         f"{sorted(unknown)} (allowed: "
                         f"{sorted(allowed)})")
    kind = d.get("kind")
    if kind not in KINDS:
        raise ValueError(
            f"{section}.kind={kind!r} is not one of {list(KINDS)}")
    need = {"device_loss": ("segment", "shard"),
            "dispatch_error": ("segment",),
            "checkpoint_corrupt": ("entry",),
            "cache_store_fail": ("store",),
            "oom": (),
            "server_crash": ("tick",)}[kind]
    for key in need:
        if d.get(key) is None or int(d[key]) < 0:
            raise ValueError(
                f"{section}: {kind} needs {key!r} >= 0 (the "
                "deterministic seam counter the fault fires at)")
    if kind == "oom":
        has_seg = d.get("segment") is not None and \
            int(d["segment"]) >= 0
        has_cmp = d.get("compile") is not None and \
            int(d["compile"]) >= 0
        if has_seg == has_cmp:
            raise ValueError(
                f"{section}: oom needs exactly one of 'segment' "
                "(dispatch issue #) or 'compile' (program compile #) "
                ">= 0")
    scope = {"device_loss": ("segment", "shard", "error"),
             "dispatch_error": ("segment", "error"),
             "checkpoint_corrupt": ("entry",),
             "cache_store_fail": ("store",),
             "oom": ("segment", "compile", "error"),
             "server_crash": ("tick",)}[kind]
    for key in ("segment", "shard", "entry", "store", "compile",
                "tick", "error"):
        if key not in scope and d.get(key) is not None:
            raise ValueError(
                f"{section}: {key!r} is not valid for {kind}")
    return ChaosEvent(
        kind=kind,
        segment=int(d.get("segment", -1)),
        shard=int(d.get("shard", -1)),
        error=str(d.get("error",
                        OOM_ERROR if kind == "oom" else DEFAULT_ERROR)),
        entry=int(d.get("entry", -1)),
        store=int(d.get("store", -1)),
        compile=int(d.get("compile", -1)),
        tick=int(d.get("tick", -1)),
    )


def events_from_config(raw: list) -> list[ChaosEvent]:
    """Validate the whole ``experimental.chaos`` list (schema.py
    delegates here — the injector owns its schedule format). Already-
    validated ChaosEvent entries pass through (gate scripts build
    them directly)."""
    if not isinstance(raw, list):
        raise ValueError("experimental.chaos must be a list of fault "
                         "events")
    out = []
    for i, d in enumerate(raw):
        if isinstance(d, ChaosEvent):
            out.append(d)
            continue
        out.append(event_from_dict(i, d))
    return out


class ChaosInjector:
    """Fires a validated schedule at the supervise/engine seams.

    Every mutation of the shared counters/ledger holds ``_lock``:
    the dispatch seam runs on the advance loop's thread, but the
    checkpoint and cache seams are exactly the calls a future async
    drain worker would issue — same rationale as PipelineWindow, and
    the same LOCK_REGISTRY discipline."""

    def __init__(self, events: list[ChaosEvent]):
        self._lock = threading.Lock()
        self._events = tuple(events)
        self._dead: dict = {}          # jax device id -> error class
        self._issues = 0
        self._ck_saves = 0
        self._stores = 0
        self._compiles = 0
        # scripted ooms repeat until the degradation ladder engages a
        # rung — then they clear, the way a real OOM clears once the
        # footprint shrinks (on_degrade_rung)
        self._oom_cleared = False
        self._ticks = 0                # campaign-server scheduler ticks
        self.fired: list = []          # ledger of fired events

    # -- dispatch seam (supervise.advance issue half) ------------------
    def on_dispatch_issue(self, engine) -> None:
        """Count one dispatch issue; fire any event scheduled at this
        count, then raise if the engine's mesh contains a dead device
        (a real dead chip fails every dispatch that touches it)."""
        from shadow_tpu.obs import trace as obstrace

        devices = list(engine.mesh.devices.flat)
        with self._lock:
            k = self._issues
            self._issues += 1
            oneshot = None
            for ev in self._events:
                if ev.segment != k:
                    continue
                if ev.kind == "device_loss":
                    if ev.shard >= len(devices):
                        raise ValueError(
                            f"chaos: device_loss shard {ev.shard} is "
                            f"out of range for the {len(devices)}-"
                            "device mesh")
                    dev = devices[ev.shard]
                    self._dead[dev.id] = ev.error
                    self.fired.append(
                        {"kind": "device_loss", "segment": k,
                         "shard": ev.shard, "device_id": dev.id})
                    log.warning("chaos: device %s (mesh position %d) "
                                "marked DEAD at dispatch issue %d",
                                dev, ev.shard, k)
                elif ev.kind == "dispatch_error":
                    oneshot = ev
                    self.fired.append(
                        {"kind": "dispatch_error", "segment": k,
                         "error": ev.error})
            oom = None
            if not self._oom_cleared:
                for ev in self._events:
                    if ev.kind == "oom" and ev.segment >= 0 and \
                            k >= ev.segment:
                        oom = ev
                        self.fired.append(
                            {"kind": "oom", "seam": "dispatch",
                             "segment": k, "error": ev.error})
                        break
            down = sorted((d.id, self._dead[d.id]) for d in devices
                          if d.id in self._dead)
        if oom is not None:
            obstrace.current().instant(
                "chaos.oom", "chaos", segment=k, error=oom.error)
            raise ChaosError(
                f"{oom.error}: chaos: scripted oom at dispatch issue "
                f"{k} (repeats until a degrade rung engages)")
        if oneshot is not None:
            obstrace.current().instant(
                "chaos.dispatch_error", "chaos", segment=k,
                error=oneshot.error)
            raise ChaosError(
                f"{oneshot.error}: chaos: scripted dispatch error at "
                f"issue {k}")
        if down:
            obstrace.current().instant(
                "chaos.device_down", "chaos", segment=k,
                device_ids=[d for d, _ in down])
            raise ChaosError(
                f"{down[0][1]}: chaos: mesh device(s) "
                f"{[d for d, _ in down]} are down (scripted device "
                "loss)")

    def is_dead(self, device_id) -> bool:
        """The liveness probe's hook: a scripted death must fail the
        probe exactly like a real one."""
        with self._lock:
            return device_id in self._dead

    # -- compile seam (aotcache.AotCache.ensure) -----------------------
    def on_compile(self, name: str) -> None:
        """Count one program compile (before lower/compile); raise a
        scripted oom scheduled at this count. A compile-time
        RESOURCE_EXHAUSTED surfaces out of the dispatch that forced
        the compile, so the same supervise recovery path catches it."""
        from shadow_tpu.obs import trace as obstrace

        with self._lock:
            n = self._compiles
            self._compiles += 1
            hit = None
            if not self._oom_cleared:
                for ev in self._events:
                    if ev.kind == "oom" and ev.compile >= 0 and \
                            n >= ev.compile:
                        hit = ev
                        self.fired.append(
                            {"kind": "oom", "seam": "compile",
                             "compile": n, "program": name,
                             "error": ev.error})
                        break
        if hit is not None:
            obstrace.current().instant(
                "chaos.oom", "chaos", compile=n, program=name,
                error=hit.error)
            log.warning("chaos: scripted oom at compile %d (%s)", n,
                        name)
            raise ChaosError(
                f"{hit.error}: chaos: scripted oom at compile {n} "
                f"({name}; repeats until a degrade rung engages)")

    def on_degrade_rung(self, rung: str) -> None:
        """The degradation ladder engaged a rung: scripted ooms stop
        firing. Without this clear the drill could never converge —
        a real OOM clears because the rung genuinely shrank the
        footprint; the scripted one must honor the same contract."""
        with self._lock:
            if self._oom_cleared:
                return
            self._oom_cleared = True
            self.fired.append({"kind": "oom_cleared", "rung": rung})
        log.warning("chaos: scripted oom cleared by degrade rung %s",
                    rung)

    # -- checkpoint seam (supervise.Checkpointer.save) -----------------
    def on_checkpoint_saved(self, path: str) -> None:
        """Count one rotation save; corrupt the file on disk when an
        event is scheduled at this count (truncate mid-payload — the
        decoy a SIGKILL can leave). The RUN is untouched: the
        corruption is to the artifact, and the newest-readable
        rotation fallback must absorb it on resume."""
        import os

        from shadow_tpu.obs import trace as obstrace

        with self._lock:
            n = self._ck_saves
            self._ck_saves += 1
            hit = any(ev.kind == "checkpoint_corrupt" and
                      ev.entry == n for ev in self._events)
            if hit:
                self.fired.append({"kind": "checkpoint_corrupt",
                                   "entry": n, "path": path})
        if not hit:
            return
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(max(1, size // 3))
        obstrace.current().instant("chaos.checkpoint_corrupt",
                                   "chaos", entry=n, path=path)
        log.warning("chaos: rotation entry %d corrupted on disk "
                    "(truncated %s — the newest-readable fallback "
                    "must skip it on resume)", n, path)

    # -- compile-cache seam (aotcache.AotCache.store) ------------------
    def on_cache_store(self, key: str) -> bool:
        """Count one cache store; True = this store must fail (the
        cache degrades to an unpersisted fresh compile, loudly)."""
        from shadow_tpu.obs import trace as obstrace

        with self._lock:
            n = self._stores
            self._stores += 1
            hit = any(ev.kind == "cache_store_fail" and
                      ev.store == n for ev in self._events)
            if hit:
                self.fired.append({"kind": "cache_store_fail",
                                   "store": n, "key": key})
        if hit:
            obstrace.current().instant("chaos.cache_store_fail",
                                       "chaos", store=n, key=key)
            log.warning("chaos: cache store %d (key %s) refused by "
                        "schedule", n, key)
        return hit

    # -- server seam (shadow_tpu/serve/server.py scheduler loop) -------
    def on_server_tick(self) -> bool:
        """Count one campaign-server scheduler tick; True = a scripted
        ``server_crash`` fires here and the server must die the HARD
        way (its crash_fn defaults to os._exit — no drain, no journal
        flush beyond what append_line already fsync'd). The drill is
        the journal's crash-replay contract, not a graceful shutdown:
        the restarted server must requeue every non-terminal campaign
        and finish it bit-identical."""
        with self._lock:
            n = self._ticks
            self._ticks += 1
            hit = any(ev.kind == "server_crash" and ev.tick == n
                      for ev in self._events)
            if hit:
                self.fired.append({"kind": "server_crash", "tick": n})
        if hit:
            log.warning("chaos: scripted server crash at scheduler "
                        "tick %d", n)
        return hit


# -- module-global current injector ------------------------------------
# installed by DeviceRunner.__init__ for the run's lifetime (None when
# the config has no chaos schedule — schedules never leak across
# in-process runs); the checkpoint and cache seams read it here, the
# same ownership rule as obs.trace's current tracer.
_CURRENT: object = None


def current():
    return _CURRENT


def set_current(injector) -> None:
    global _CURRENT
    _CURRENT = injector


def from_config(xp) -> object:
    """The runner's injector factory from validated
    ``experimental.chaos`` (None without a schedule)."""
    events = getattr(xp, "chaos", None)
    if not events:
        return None
    return ChaosInjector(events_from_config(events))
