"""Persistent ahead-of-time compile cache for the device engine.

Every fresh process pays the engine's full XLA compile before the
first event executes — 40s+ at the bench headline rung (BENCH_r02:
41.4s compile vs 5.0s steady run) — and that cost is re-paid by
supervised restarts, hybrid failovers, ensemble campaigns, CI rungs,
and every bench iteration. Training/inference stacks solve exactly
this cold-start problem with a persistent executable cache; this
module is that cache for the simulation engine:

* the engine's jitted programs (``_run``, ``_run_ens``, and the
  profiling split programs) are lowered and compiled AHEAD OF TIME
  (``jax.jit(...).lower(args).compile()``), serialized via
  ``jax.experimental.serialize_executable`` (the ``jax.stages``
  ``Compiled`` round-trip), and written to a cache directory;
* entries are keyed by a **program fingerprint** composed of every
  input that shapes the traced program: the workload fingerprint
  (``capacity.app_fingerprint`` — app scalars + per-host arrays), all
  six capacity knobs, the exchange variant + mesh shape, the fault
  epoch count, the audit flag, the jax/jaxlib versions + backend
  platform, and a digest of the engine-side source modules — so any
  input that changes the traced program changes the key, and a stale
  entry can never be (mis)used;
* the cache is **corruption-tolerant**: an unreadable, truncated, or
  stale entry logs a warning, recompiles, and atomically overwrites
  the bad entry (``utils/artifacts.atomic_write``) — degradation is
  always to a fresh compile, never to a wrong trace;
* the cache is **bounded**: total entry bytes are capped
  (``experimental.compile_cache_cap_mb``) with LRU eviction — loads
  touch the entry mtime, stores evict the least-recently-used entries
  past the cap;
* hits/misses are **loud**: every ``ensure`` records an attribution
  event (lower/compile/serialize/load walls) that the runners surface
  through ``SimStats.compile_cache`` and bench stamps into every
  BENCH_*/MULTICHIP_* record.

Concurrent-writer safety rides the artifacts helper: tmp files carry
the writer's pid and land via ``os.replace``, so two processes racing
onto one entry each write a complete file and the loser's replace
simply lands second — readers always see a complete entry.

Backends whose PJRT client does not support executable serialization
(``serialize_executable`` raises) degrade to the plain jit path with
one warning; JAX's own persistent *tracing* cache
(``JAX_COMPILATION_CACHE_DIR`` / shadow_tpu/_jax.py) still covers
those environments.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import pickle
import threading
import time

from shadow_tpu.obs import trace as obstrace
from shadow_tpu.utils.slog import get_logger

log = get_logger("aotcache")

FORMAT = 1
ENTRY_SUFFIX = ".aotc"
DEFAULT_DIR = "~/.cache/shadow_tpu_aot"
DEFAULT_CAP_MB = 2048

# the engine-side source surface that shapes the traced programs: a
# code change in any of these must invalidate every cached executable
# (the fingerprint cannot see a rewritten flush or a new audit bit).
# Module paths, resolved lazily so importing this module stays cheap.
CODE_DIGEST_MODULES = (
    "shadow_tpu.device.engine",
    "shadow_tpu.device.apps",
    "shadow_tpu.device.netsem",
    "shadow_tpu.device.prng",
    # the two-level factored gather the traced program calls under
    # representation: hierarchical (compose order is trace semantics)
    "shadow_tpu.topology.hierarchy",
    "shadow_tpu.host.model_nic",
    # constant providers the trace bakes in: checksum fold constants
    # (CHK_*/MASK63), event kind ids (KIND_*), RNG purpose ids
    "shadow_tpu.utils.checksum",
    "shadow_tpu.core.event",
    "shadow_tpu.utils.rng",
)

# import-graph hook for the fingerprint-completeness pass
# (shadow_tpu/analyze/imports_audit.py): the walk starts at the trace
# roots, follows static imports, and requires every module it reaches
# to appear in CODE_DIGEST_MODULES — EXCEPT the declared boundary
# modules, whose trace-relevant outputs are fingerprinted BY VALUE
# elsewhere in the cache key (so their source need not be digested,
# and their own imports are not followed). Each boundary entry names
# where its value coverage lives; deleting a non-boundary module from
# CODE_DIGEST_MODULES fails the analyze CI rung loudly.
CODE_DIGEST_ROOTS = ("shadow_tpu.device.engine",)
CODE_DIGEST_BOUNDARY = {
    "shadow_tpu": "package namespace only (version/__init__ exports)",
    "shadow_tpu.device": "package namespace only",
    "shadow_tpu.topology":
        "package namespace only; builders never enter a traced "
        "program — the tables they produce join the cache key by "
        "value (world fingerprint + program_facts representation), "
        "and the traced gather itself is topology.hierarchy, digested",
    "shadow_tpu._jax":
        "import shim; jax/jaxlib versions join backend_signature",
    "shadow_tpu.simtime":
        "unit constants; the resolved values (lookahead, bootstrap, "
        "stops, MSS-derived app scalars) are fingerprinted by value "
        "via program_facts + app_fingerprint",
    "shadow_tpu.device.capacity":
        "its trace inputs (CAP/CAP2/CX, tp group split, exchange "
        "choice) are fingerprinted by value via program_facts",
    "shadow_tpu.models.tgen":
        "CPU-twin constants (CHUNK_PKTS) land in app scalars, "
        "fingerprinted by value via app_fingerprint",
    "shadow_tpu.models.tor":
        "CPU-twin constants land in app scalars, fingerprinted by "
        "value via app_fingerprint",
    "shadow_tpu.obs":
        "flight recorder: spans only read already-computed values "
        "(contract pinned by determinism_gate --telemetry)",
    "shadow_tpu.obs.trace":
        "flight recorder: spans only read already-computed values",
    "shadow_tpu.utils.slog": "logging only; no values enter a trace",
}

_code_digest_cache: str = ""


def _set_tracing_cache(enabled: bool) -> None:
    """Enable/disable JAX's persistent TRACING cache process-wide.

    The two caches do not compose on the CPU backend (verified
    empirically on jax 0.4.37): once any executable in the process
    came out of the tracing cache, later `serialize_executable` blobs
    (and loads) break with INTERNAL "Symbols not found" — the
    process-global JIT symbol state poisons the round-trip. So an
    enabled AOT cache turns the tracing cache OFF for the process
    (the engine executables land in THIS cache instead, which skips
    tracing too — strictly better), and a backend that turns out not
    to serialize turns it back ON so the documented fallback
    (JAX_COMPILATION_CACHE_DIR) still applies.

    jax latches `is_cache_used` per process at the first compile, so
    flipping the flag alone is not enough — reset_cache() drops the
    latch."""
    import jax

    try:
        jax.config.update("jax_enable_compilation_cache", enabled)
        from jax._src import compilation_cache as cc

        cc.reset_cache()
    except Exception as e:              # noqa: BLE001 — older jax
        log.info("could not %s jax's tracing cache (%s)",
                 "enable" if enabled else "disable", e)


_serialization_probe: bool | None = None


def serialization_supported() -> bool:
    """One cheap per-process probe: can this backend's PJRT client
    round a Compiled through serialize? Runs BEFORE the cache
    disables jax's tracing cache, so an unsupported backend (e.g. a
    relay that raises UNIMPLEMENTED) keeps the tracing cache as its
    persistence layer for the big engine compiles — not just for
    programs compiled after the first store failure. The probe
    compiles fresh (see _fresh_compile): a tracing-cache-hit
    executable would fail serialization for the wrong reason."""
    global _serialization_probe
    if _serialization_probe is None:
        try:
            import jax
            import jax.numpy as jnp
            from jax.experimental import serialize_executable as se

            with _fresh_compile():
                compiled = jax.jit(lambda x: x + 1).lower(
                    jnp.zeros((8,), jnp.int32)).compile()
            se.serialize(compiled)
            _serialization_probe = True
        except Exception as e:          # noqa: BLE001 — backend gap
            log.warning(
                "compile cache: executable serialization is "
                "unsupported on this backend (%s) — AOT entries "
                "disabled; JAX's built-in tracing cache remains the "
                "persistence layer (JAX_COMPILATION_CACHE_DIR)", e)
            _serialization_probe = False
    return _serialization_probe


@contextlib.contextmanager
def _fresh_compile():
    """Bypass JAX's persistent TRACING cache for one compile whose
    executable will be serialized (see _set_tracing_cache for why the
    caches must not mix). Standalone tooling (tpu_micro --variant 6)
    uses this; an enabled AotCache disables the tracing cache for the
    whole process instead."""
    import jax

    try:
        old = bool(jax.config.jax_enable_compilation_cache)
    except Exception:                   # noqa: BLE001 — older jax
        yield
        return
    _set_tracing_cache(False)
    try:
        yield
    finally:
        _set_tracing_cache(old)


def code_digest() -> str:
    """SHA-256 over the source of every program-shaping engine module
    (cached per process — the sources cannot change under a running
    interpreter)."""
    global _code_digest_cache
    if _code_digest_cache:
        return _code_digest_cache
    import importlib

    h = hashlib.sha256()
    for name in CODE_DIGEST_MODULES:
        mod = importlib.import_module(name)
        path = getattr(mod, "__file__", None)
        h.update(name.encode())
        if path and os.path.exists(path):
            with open(path, "rb") as f:
                h.update(f.read())
    _code_digest_cache = h.hexdigest()[:16]
    return _code_digest_cache


def backend_identity(devs) -> dict:
    """jax/jaxlib versions, platform, and device kinds for a device
    list — the ONE definition of "backend identity", shared by the
    cache key (backend_signature) and bench's record stamps, so the
    two surfaces cannot drift on what identifies a backend."""
    import jax
    import jaxlib

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": devs[0].platform,
        "device_kinds": sorted({d.device_kind for d in devs}),
    }


def backend_signature(mesh) -> dict:
    """The backend identity a serialized executable is only valid
    for, plus the mesh's device ordering (an executable compiled for
    devices [0..3] must not load onto a differently-ordered mesh)."""
    devs = list(mesh.devices.flat)
    sig = backend_identity(devs)
    sig["device_ids"] = [int(d.id) for d in devs]
    return sig


def program_signature(engine, program: str) -> dict:
    """Every input that shapes `program`'s traced computation, as one
    JSON-able dict. The engine's ``program_facts`` carries the resolved
    compile-time surface (capacities, strategy flags, lookahead,
    epoch count, audit, ensemble width, ...); the workload fingerprint
    covers the app's scalars + per-host arrays; the backend signature
    and code digest cover everything outside the config."""
    from shadow_tpu.device.capacity import app_fingerprint

    sig = {
        "format": FORMAT,
        "program": str(program),
        "app": type(engine.app).__name__,
        "workload_fp": app_fingerprint(engine.app),
        "facts": dict(engine.program_facts),
        "backend": backend_signature(engine.mesh),
        "code": code_digest(),
    }
    if engine.config.model_bandwidth:
        # the fluid NIC bakes the per-host bandwidth vectors into the
        # trace as closure constants (engine.py bw_up_t/bw_down_t) —
        # unlike the latency/reliability tables, which ride the traced
        # world tuple — so under model_bandwidth they must key the
        # entry. Fault-free model-app runs skip the digest: the
        # vectors are unused there and would only cost spurious
        # misses on irrelevant bandwidth edits.
        import numpy as np

        h = hashlib.sha256()
        for arr in (engine.bw_up, engine.bw_down):
            h.update(np.ascontiguousarray(np.asarray(arr)).tobytes())
        sig["bw_digest"] = h.hexdigest()[:16]
    return sig


def program_key(engine, program: str) -> str:
    sig = program_signature(engine, program)
    return hashlib.sha256(
        json.dumps(sig, sort_keys=True).encode()).hexdigest()[:24]


class AotCache:
    """One persistent executable cache directory.

    The runners create ONE instance per run (from
    ``experimental.compile_cache``) and attach it to every engine they
    build — warm-up engines, re-planned engines, and resumed engines
    all consult the same cache, and ``report()`` aggregates the whole
    run's attribution (the loud hit/miss surface)."""

    def __init__(self, directory: str,
                 cap_bytes: int = DEFAULT_CAP_MB * (1 << 20)):
        self.directory = os.path.expanduser(directory)
        self.cap_bytes = int(cap_bytes)
        self.events: list[dict] = []
        # two independent degradations, so neither forfeits the
        # other's warm starts:
        # * unsupported  — the backend cannot serialize/deserialize
        #   executables at all: both load and store are off, jax's
        #   tracing cache stays on as the fallback;
        # * store_disabled — the DIRECTORY cannot be written
        #   (read-only shared cache, disk full): new entries are not
        #   stored, but EXISTING entries still load — a prepopulated
        #   read-only cache remains a warm-start source.
        self.unsupported = not serialization_supported()
        self.store_disabled = (False if self.unsupported
                               else not self._dir_writable())
        # background entry pre-reads (prefetch): key -> (thread,
        # slot). A plan/re-plan names the next program before its
        # first dispatch, so the entry's disk read + pickle parse can
        # overlap the state-transfer work instead of blocking load()
        self._prefetched: dict = {}
        if not self.unsupported:
            # executable serialization and jax's tracing cache do
            # not compose (see _set_tracing_cache) — whenever this
            # cache may LOAD entries, the tracing cache must be off,
            # or a tracing-cache-hit executable earlier in the
            # process poisons the deserialize. This also applies in
            # store_disabled mode (loads are the whole point there).
            # Named cost: programs OUTSIDE the AOT side table (the
            # heap builder, _probe, transfer broadcasts) lose cross-
            # process tracing-cache persistence; the engine's heavy
            # programs — the ones worth persisting — all live here.
            _set_tracing_cache(False)

    def _dir_writable(self) -> bool:
        """Probe the cache directory for writability NOW — before the
        constructor trades jax's tracing cache away for a cache that
        could never store anything (read-only home, full disk)."""
        probe = os.path.join(self.directory,
                             f".probe.{os.getpid()}.tmp")
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(probe, "wb"):
                pass
            os.unlink(probe)
            return True
        except OSError as e:
            log.warning(
                "compile cache: directory %s is not writable (%s) — "
                "new AOT entries disabled; existing entries still "
                "load, but fresh compiles are not persisted this "
                "run (the tracing cache must stay off while AOT "
                "entries load — the two layers do not compose)",
                self.directory, e)
            return False

    # -- entry I/O ----------------------------------------------------
    def entry_path(self, key: str) -> str:
        return os.path.join(self.directory, key + ENTRY_SUFFIX)

    def _read_entry(self, key: str, path: str) -> dict:
        """Read + structurally validate one entry file (raises on any
        problem). Shared by the synchronous load path and the
        prefetch thread, so the two can never disagree on what a
        valid entry is."""
        with open(path, "rb") as f:
            entry = pickle.load(f)
        if not isinstance(entry, dict) or \
                entry.get("format") != FORMAT or \
                entry.get("key") != key:
            raise ValueError(
                f"format {entry.get('format')!r} / key "
                f"{entry.get('key')!r} (want {FORMAT}/{key})")
        return entry

    def prefetch(self, key: str, program: str = "") -> bool:
        """Start a BACKGROUND read+parse of `key`'s entry so a later
        :meth:`load` finds it in memory (supervise.prefetch_programs
        — a plan or re-plan names the next program while the current
        segment's work still runs). Purely a wall-time optimization:
        the thread only reads bytes and validates structure; the
        deserialize into a live executable stays on the calling
        thread, and any prefetch failure silently falls back to the
        synchronous path. Returns True when a read was started."""
        if self.unsupported or key in self._prefetched:
            return False
        path = self.entry_path(key)
        if not os.path.exists(path):
            return False
        # bound the prediction set: a re-plan that supersedes an
        # unconsumed prefetch (repeated widen() cycles) must not pin
        # each superseded entry's multi-MB payload until process
        # exit — keep only the newest few, oldest first out
        while len(self._prefetched) >= 4:
            self._prefetched.pop(next(iter(self._prefetched)))
        slot: dict = {"entry": None, "dur_s": 0.0}

        def _read():
            t0 = time.perf_counter()
            try:
                slot["entry"] = self._read_entry(key, path)
            except Exception:   # noqa: BLE001 — load() retries + warns
                pass
            slot["dur_s"] = time.perf_counter() - t0

        th = threading.Thread(target=_read, daemon=True,
                              name=f"aot-prefetch-{key[:8]}")
        self._prefetched[key] = (th, slot)
        th.start()
        # the instant is recorded from the CALLING thread (the
        # tracer's attribution stacks are per-thread; a worker-thread
        # span would misattribute nothing but also belongs nowhere)
        obstrace.current().instant(
            f"compile.prefetch:{program or key[:8]}", "compile",
            key=key)
        log.info("compile cache: prefetching %s entry %s in the "
                 "background", program or "program",
                 self.entry_path(key))
        return True

    def _take_prefetched(self, key: str):
        """Collect a finished (or in-flight — joined; it is a local
        file read) prefetch for `key`, or None."""
        item = self._prefetched.pop(key, None)
        if item is None:
            return None
        th, slot = item
        th.join(timeout=60.0)
        if th.is_alive():       # a wedged filesystem: fall back
            return None
        if slot["entry"] is not None:
            log.info("compile cache: prefetched entry served for "
                     "%s (%.3fs background read)", key,
                     slot["dur_s"])
        return slot["entry"]

    def load(self, key: str):
        """Deserialize-and-load the cached executable for `key`, or
        None on a miss. ANY failure on an existing entry (truncated
        pickle, format drift, a backend that cannot load the blob) is
        a warned miss — the caller recompiles and the store path
        atomically overwrites the bad entry. A background
        :meth:`prefetch` of the same key feeds this path its already-
        parsed entry."""
        path = self.entry_path(key)
        entry = self._take_prefetched(key)
        if entry is None and not os.path.exists(path):
            return None
        try:
            if entry is None:
                entry = self._read_entry(key, path)
            from jax.experimental import serialize_executable as se

            loaded = se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception as e:          # noqa: BLE001 — any bad entry
            log.warning(
                "compile cache: entry %s is unreadable/stale (%s) — "
                "recompiling and overwriting it", path, e)
            return None
        try:
            # LRU touch: loads refresh the entry's eviction clock
            os.utime(path, None)
        except OSError:
            pass
        return loaded

    def store(self, key: str, compiled, meta: dict) -> bool:
        """Serialize `compiled` (a jax.stages.Compiled) under `key`,
        atomically (tmp+rename via utils/artifacts — a mid-write kill
        or a concurrent writer can never leave a truncated entry),
        then evict LRU entries past the size cap."""
        from shadow_tpu.utils.artifacts import atomic_write

        from shadow_tpu.device import chaos as chaosmod

        inj = chaosmod.current()
        if inj is not None and inj.on_cache_store(key):
            # chaos seam (full-disk drill): this store is refused —
            # the run continues on the unpersisted fresh compile,
            # exactly the degradation contract a real write failure
            # gets below (store_disabled stays off: the scripted
            # failure is one store, not the directory)
            log.warning("compile cache: store of %s refused by the "
                        "chaos schedule — running on the unpersisted "
                        "fresh compile", key)
            return False

        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as e:          # noqa: BLE001 — backend gap
            self.unsupported = True
            # hand compile persistence back to jax's tracing cache —
            # the documented fallback for serialization-less backends
            _set_tracing_cache(True)
            log.warning(
                "compile cache: this backend cannot serialize "
                "executables (%s) — running without the AOT cache "
                "(JAX's built-in tracing cache re-enabled as the "
                "fallback; see JAX_COMPILATION_CACHE_DIR)", e)
            return False
        entry = {"format": FORMAT, "key": key, "meta": dict(meta),
                 "payload": payload, "in_tree": in_tree,
                 "out_tree": out_tree}
        path = self.entry_path(key)
        try:
            atomic_write(path, lambda f: pickle.dump(entry, f))
        except Exception as e:          # noqa: BLE001 — degrade, never crash
            # OSError: the directory turned unwritable after the
            # constructor probe (disk filled mid-run). Anything else
            # (a PyTreeDef that won't pickle on this jax version):
            # same remedy — stop STORING but keep LOADING, so valid
            # entries on disk still serve their warm starts. The
            # tracing cache stays OFF: re-enabling it mid-run would
            # poison every later AOT load in this process (the
            # non-compose rule), a worse trade than one run's
            # unpersisted fresh compiles. A cache-layer failure must
            # never abort the simulation.
            self.store_disabled = True
            log.warning("compile cache: could not write %s (%s) — "
                        "new entries disabled for this run (existing "
                        "entries still load)", path, e)
            return False
        self._evict()
        return True

    def _evict(self) -> None:
        """Drop least-recently-used entries until the directory fits
        the size cap. mtime is the LRU clock (stores write it, loads
        touch it); a racing sibling deleting the same file is fine."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        entries = []
        now = time.time()
        for n in names:
            p = os.path.join(self.directory, n)
            if not n.endswith(ENTRY_SUFFIX):
                # debris from a hard-killed writer (SIGKILL mid-write
                # skips atomic_write's cleanup): stale tmp files are
                # deleted outright — the size cap must bound what is
                # actually on disk, not just the finished entries
                if ".tmp" in n:
                    try:
                        if now - os.stat(p).st_mtime > 600:
                            os.unlink(p)
                    except OSError:
                        pass
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
        total = sum(s for _, s, _ in entries)
        if total <= self.cap_bytes:
            return
        entries.sort()                  # oldest first
        # the newest entry is never evicted: a cap smaller than one
        # executable would otherwise delete the entry just stored and
        # leave the cache permanently cold
        if len(entries) > 1 and entries[-1][1] > self.cap_bytes:
            log.warning(
                "compile cache: one entry (%d MB) exceeds the "
                "compile_cache_cap_mb cap (%d MB) — raise the cap, "
                "or only this newest entry will survive",
                entries[-1][1] >> 20, self.cap_bytes >> 20)
        for _, size, p in entries[:-1]:
            if total <= self.cap_bytes:
                break
            try:
                os.unlink(p)
                total -= size
                log.info("compile cache: evicted %s (LRU, cap %d MB)",
                         p, self.cap_bytes >> 20)
            except OSError:
                pass

    # -- the engine hook ----------------------------------------------
    def ensure(self, engine, program: str, jit_fn, args):
        """Return a ready-to-call executable for `program`:

        * cache hit  -> the deserialized Compiled (no trace, no
          compile);
        * cache miss -> ``jit_fn.lower(*args).compile()`` timed in its
          two stages, stored for the next process, returned;
        * any cache-layer failure -> the original ``jit_fn`` (the
          plain lazy-jit path — correctness never depends on the
          cache).

        The attribution event lands in ``self.events`` either way."""
        ev = {"program": program, "hit": False,
              "lower_s": 0.0, "compile_s": 0.0, "load_s": 0.0,
              "serialize_s": 0.0}
        try:
            key = program_key(engine, program)
        except Exception as e:          # noqa: BLE001
            log.warning("compile cache: could not fingerprint %s "
                        "(%s); compiling without the cache",
                        program, e)
            ev["error"] = str(e)
            self.events.append(ev)
            return jit_fn
        ev["key"] = key
        if not self.unsupported:
            t0 = time.perf_counter()
            loaded = self.load(key)
            if loaded is not None:
                ev["hit"] = True
                ev["load_s"] = round(time.perf_counter() - t0, 3)
                self.events.append(ev)
                # flight-recorder attribution (shadow_tpu/obs): the
                # cache's walls are already measured, the tracer only
                # needs them on the run's timeline
                obstrace.current().record(
                    f"aot.load:{program}", "compile", ev["load_s"],
                    hit=True, key=key)
                log.info("compile cache HIT: %s <- %s (%.2fs load; "
                         "compile skipped)", program,
                         self.entry_path(key), ev["load_s"])
                return loaded
        # chaos seam (device/chaos.py `oom`): a scripted compile-time
        # RESOURCE_EXHAUSTED fires HERE — after the cache-hit return
        # (a hit compiles nothing), before lower/compile, and OUTSIDE
        # the lazy-jit fallback below (the fallback absorbs backend
        # quirks, not allocator failures) — so it surfaces out of the
        # dispatch that forced the compile, exactly like a real one
        from shadow_tpu.device import chaos as chaosmod

        inj = chaosmod.current()
        if inj is not None and hasattr(inj, "on_compile"):
            inj.on_compile(program)
        # a blob destined for the cache must come from a FRESH
        # compile (see _fresh_compile); when nothing will be stored
        # (unsupported backend, unwritable directory) keep JAX's
        # tracing cache in play so the compile persists SOMEWHERE
        will_store = not self.unsupported and not self.store_disabled
        try:
            ctx = (_fresh_compile() if will_store
                   else contextlib.nullcontext())
            with ctx:
                t0 = time.perf_counter()
                lowered = jit_fn.lower(*args)
                t1 = time.perf_counter()
                compiled = lowered.compile()
                t2 = time.perf_counter()
            ev["lower_s"] = round(t1 - t0, 3)
            ev["compile_s"] = round(t2 - t1, 3)
            tr = obstrace.current()
            # lower ended compile_s ago — placed before the compile
            # on the timeline, not overlapping it on one track
            tr.record(f"aot.lower:{program}", "compile",
                      ev["lower_s"], ago_s=ev["compile_s"],
                      hit=False)
            tr.record(f"aot.compile:{program}", "compile",
                      ev["compile_s"], hit=False, key=key)
        except Exception as e:          # noqa: BLE001
            # AOT lowering failed (exotic arg structure, backend
            # quirk): fall back to the lazy jit path, which compiles
            # the identical program on first call. The tracing cache
            # deliberately stays OFF — re-enabling it mid-run would
            # poison later AOT loads in this process (non-compose
            # rule), so this one program simply recompiles per
            # process until the quirk is fixed.
            log.warning("compile cache: AOT lower/compile of %s "
                        "failed (%s); falling back to lazy jit",
                        program, e)
            ev["error"] = str(e)
            self.events.append(ev)
            return jit_fn
        if will_store:
            t0 = time.perf_counter()
            try:
                stored = self.store(key, compiled, meta={
                    "program": program,
                    "signature": program_signature(engine, program)})
                if stored:
                    # self-validation: an entry that cannot
                    # round-trip (backend serialization gap our
                    # probe missed) must not greet the next process
                    # as a poisoned hit
                    if self.load(key) is None:
                        log.warning("compile cache: stored entry "
                                    "for %s failed its round-trip "
                                    "check — removing it", program)
                        try:
                            os.unlink(self.entry_path(key))
                        except OSError:
                            pass
                        stored = False
            except Exception as e:      # noqa: BLE001 — never abort a run
                log.warning("compile cache: storing %s failed (%s); "
                            "continuing with the fresh compile",
                            program, e)
                stored = False
            ev["serialize_s"] = round(time.perf_counter() - t0, 3)
            ev["stored"] = stored
            obstrace.current().record(
                f"aot.serialize:{program}", "compile",
                ev["serialize_s"], stored=stored)
        self.events.append(ev)
        log.info("compile cache MISS: %s (lower %.2fs + compile "
                 "%.2fs%s) -> %s", program, ev["lower_s"],
                 ev["compile_s"],
                 "" if ev.get("stored") else "; entry NOT stored",
                 self.entry_path(key))
        return compiled

    # -- attribution --------------------------------------------------
    def publish(self, stats) -> None:
        """The runners' one summary site: set
        ``stats.compile_cache`` to this run's report and log the loud
        hit/miss line (DeviceRunner and EnsembleRunner both call
        here, so the surface cannot drift between them)."""
        stats.compile_cache = rep = self.report()
        log.info("compile cache: %d hit(s), %d miss(es) "
                 "(%.1fs compiling, %.1fs loading) in %s",
                 rep["hits"], rep["misses"], rep["compile_s"],
                 rep["load_s"], rep["dir"])

    def report(self) -> dict:
        """The run's loud hit/miss surface (SimStats.compile_cache /
        bench records): per-program events plus the totals a record
        reader needs without walking the event list."""
        hits = sum(1 for e in self.events if e.get("hit"))
        misses = sum(1 for e in self.events
                     if not e.get("hit") and "error" not in e)
        return {
            "dir": self.directory,
            "cap_mb": self.cap_bytes >> 20,
            "unsupported": self.unsupported,
            "store_disabled": self.store_disabled,
            "hits": hits,
            "misses": misses,
            "compile_s": round(sum(e["lower_s"] + e["compile_s"]
                                   for e in self.events), 3),
            "load_s": round(sum(e["load_s"] for e in self.events), 3),
            "events": list(self.events),
        }


def resolve_cache(experimental) -> AotCache | None:
    """The runners' cache factory, from the validated
    ``experimental.compile_cache`` knob: ``off`` -> None, ``auto`` ->
    the default directory ($SHADOW_TPU_AOT_DIR, else
    ~/.cache/shadow_tpu_aot), anything else is the (schema-validated)
    cache directory path."""
    mode = experimental.compile_cache
    if mode == "off":
        return None
    if mode == "auto":
        directory = os.environ.get("SHADOW_TPU_AOT_DIR",
                                   DEFAULT_DIR)
    else:
        directory = mode
    cap = int(experimental.compile_cache_cap_mb) * (1 << 20)
    return AotCache(directory, cap_bytes=cap)
