"""Host -> topology-vertex attachment.

Mirrors the reference's topology_attach (src/main/routing/topology.c:
2024-2272): an explicit vertex pin (`network_node_id`, the modern config
style) wins; otherwise candidates are filtered by city/country hints,
then an IP hint selects the vertex whose `ip_address` shares the longest
prefix; remaining ties (or no hints) resolve by a draw from the host's
deterministic RNG. The chosen vertex's bandwidths become the host's
defaults (host.c:170-183).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


from shadow_tpu.routing.address import ip_to_int
from shadow_tpu.topology.graph import Topology
from shadow_tpu.utils.rng import SeededRandom


def _ip_to_int(ip: str) -> Optional[int]:
    """Lenient variant of routing.address.ip_to_int: vertex/hint IPs in
    GML files may be malformed; an unparsable IP just disables
    prefix-matching for that vertex."""
    try:
        return ip_to_int(ip)
    except Exception:
        return None


def _common_prefix_bits(a: int, b: int) -> int:
    x = a ^ b
    bits = 0
    for shift in range(31, -1, -1):
        if x >> shift:
            break
        bits += 1
    return bits


@dataclass
class HostAttachment:
    """Resolved placement of one host on the topology."""

    vertex: int
    bw_down_bits: int
    bw_up_bits: int


class Attacher:
    def __init__(self, topology: Topology, rng: SeededRandom):
        self._top = topology
        self._rng = rng
        self._vertex_ips = [
            (_ip_to_int(ip) if ip else None) for ip in topology.ip_strs
        ]

    def attach(self,
               network_node_id: Optional[int] = None,
               ip_hint: Optional[str] = None,
               city_hint: Optional[str] = None,
               country_hint: Optional[str] = None,
               bw_down_override: Optional[int] = None,
               bw_up_override: Optional[int] = None) -> HostAttachment:
        top = self._top
        if network_node_id is not None:
            vertex = top.vertex_index_for_id(network_node_id)
        else:
            vertex = self._pick_vertex(ip_hint, city_hint, country_hint)

        bw_down = (bw_down_override if bw_down_override is not None
                   else int(top.bw_down_bits[vertex]))
        bw_up = (bw_up_override if bw_up_override is not None
                 else int(top.bw_up_bits[vertex]))
        return HostAttachment(vertex=vertex, bw_down_bits=bw_down,
                              bw_up_bits=bw_up)

    def _pick_vertex(self, ip_hint, city_hint, country_hint) -> int:
        top = self._top
        candidates = list(range(top.n_vertices))

        def _filtered(attr_list, want):
            hits = [v for v in candidates if attr_list[v] == want]
            return hits or candidates

        if country_hint:
            candidates = _filtered(top.country_codes, country_hint)
        if city_hint:
            candidates = _filtered(top.city_codes, city_hint)

        if ip_hint:
            want = _ip_to_int(ip_hint)
            if want is not None:
                best_bits, best = -1, None
                for v in candidates:
                    have = self._vertex_ips[v]
                    if have is None:
                        continue
                    bits = _common_prefix_bits(want, have)
                    if bits > best_bits:
                        best_bits, best = bits, v
                if best is not None:
                    return best

        if len(candidates) == 1:
            return candidates[0]
        return candidates[self._rng.randint(0, len(candidates))]
