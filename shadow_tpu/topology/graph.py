"""Network topology as dense arrays.

The reference wraps igraph and computes shortest paths lazily per source
with a RW-locked path cache (src/main/routing/topology.c:1166-1858). The
TPU-first design precomputes **all-pairs** latency and reliability
matrices once at load time: for the graph sizes Shadow-style topologies
use (vertices are network points-of-presence, not hosts — even the
full-consensus Tor atlas is a few thousand vertices), a dense [V,V]
int64/float32 pair is small, and it turns every per-packet
latency/reliability lookup into a device-side gather.

Semantics mirrored from the reference:

* vertices require `bandwidth_down`/`bandwidth_up` (unit strings, e.g.
  "1 Gbit"); optional ip_address/city_code/country_code/label
  (topology.c:87-104, 561-601).
* edges require `latency` (> 0) and `packet_loss` in [0,1]; optional
  jitter/label (topology.c:98-104, 612-640).
* the graph must be connected (strongly, if directed) as a single
  component (topology.c:659-716).
* `use_shortest_path=false` requires a complete graph and uses direct
  edges only (topology.c:1816-1858).
* self-paths: a self-loop edge is used as-is; otherwise the cheapest
  incident edge is used out-and-back (latency doubled, reliability
  squared) (topology.c:1431-1576).
* computed zero-latency paths are clamped to 1 ms (topology.c:1788).
* reliability of a multi-edge path is the product of per-edge
  (1 - packet_loss) (topology.c:1341).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu import simtime
from shadow_tpu.config.units import parse_bandwidth_bits, parse_time_ns
from shadow_tpu.topology.gml import GmlGraph, GmlError, parse_gml
from shadow_tpu.topology.hierarchy import (
    HIER_VERIFY_MAX_V,
    HierTables,
)
from shadow_tpu.utils.slog import get_logger

log = get_logger("topology")

REPRESENTATIONS = ("dense", "hierarchical", "auto")

# Builtin graph, byte-identical semantics to the reference's
# ONE_GBIT_SWITCH_GRAPH (configuration.rs:732-760).
ONE_GBIT_SWITCH_GML = """graph [
  directed 0
  node [
    id 0
    ip_address "0.0.0.0"
    bandwidth_up "1 Gbit"
    bandwidth_down "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
    packet_loss 0.0
  ]
]"""

_MIN_PATH_LATENCY_NS = simtime.SIMTIME_ONE_MILLISECOND  # 0-latency clamp


def dense_adjacency(n_vertices: int, directed: bool,
                    edge_src: np.ndarray, edge_dst: np.ndarray,
                    edge_latency_ns: np.ndarray,
                    edge_reliability: np.ndarray,
                    edge_alive: Optional[np.ndarray] = None
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Dense [V,V] direct-edge latency (ns; 0 = no edge) and
    reliability matrices, keeping the cheapest parallel edge.
    `edge_alive` (bool [E], default all-True) masks edges out — the
    fault layer (shadow_tpu/faults.py) removes downed links through
    it, so an epoch's adjacency is built by the SAME code path as the
    base topology's."""
    V = n_vertices
    lat = np.zeros((V, V), dtype=np.int64)
    rel = np.zeros((V, V), dtype=np.float32)

    def _store(s, d, l, r):
        if lat[s, d] == 0 or l < lat[s, d]:
            lat[s, d] = l
            rel[s, d] = r

    for k, (s, d, l, r) in enumerate(zip(edge_src, edge_dst,
                                         edge_latency_ns,
                                         edge_reliability)):
        if edge_alive is not None and not edge_alive[k]:
            continue
        _store(s, d, l, r)
        if not directed:
            _store(d, s, l, r)
    return lat, rel


def sparse_min_adjacency(n_vertices: int, directed: bool,
                         edge_src: np.ndarray, edge_dst: np.ndarray,
                         edge_latency_ns: np.ndarray,
                         edge_reliability: np.ndarray,
                         edge_alive: Optional[np.ndarray] = None
                         ) -> tuple[np.ndarray, np.ndarray,
                                    np.ndarray, np.ndarray]:
    """Sparse twin of dense_adjacency: the reduced (v, u, lat, rel)
    entry arrays — one row per ordered vertex pair that has at least
    one (alive) edge — with EXACTLY dense_adjacency's parallel-edge
    tie rule (the first edge reaching the minimal latency wins,
    in _store call order). O(E log E) and never materializes [V,V],
    so the hierarchical builder and its fault epochs can reduce a
    million-edge list."""
    esrc = np.asarray(edge_src, np.int64)
    edst = np.asarray(edge_dst, np.int64)
    elat = np.asarray(edge_latency_ns, np.int64)
    erel = np.asarray(edge_reliability, np.float32)
    if edge_alive is not None:
        keep = np.asarray(edge_alive, bool)
        # the ORIGINAL edge index keeps the tie rule stable under
        # fault-epoch masks (dense_adjacency skips dead edges without
        # renumbering the survivors)
        order = np.nonzero(keep)[0].astype(np.int64)
        esrc, edst = esrc[keep], edst[keep]
        elat, erel = elat[keep], erel[keep]
    else:
        order = np.arange(len(esrc), dtype=np.int64)
    if directed:
        v, u, l, r, o = esrc, edst, elat, erel, 2 * order
    else:
        # _store(s, d) runs before _store(d, s) within each edge
        v = np.concatenate([esrc, edst])
        u = np.concatenate([edst, esrc])
        l = np.concatenate([elat, elat])
        r = np.concatenate([erel, erel])
        o = np.concatenate([2 * order, 2 * order + 1])
    key = v * np.int64(n_vertices) + u
    idx = np.lexsort((o, l, key))
    key_s = key[idx]
    first = np.ones(len(key_s), dtype=bool)
    first[1:] = key_s[1:] != key_s[:-1]
    sel = idx[first]
    return v[sel], u[sel], l[sel], r[sel]


def build_hier_tables(top: "Topology") -> HierTables:
    """Factor a topology into cluster tables (hierarchy.HierTables).

    Structural form: *spokes* are vertices with exactly one distinct
    non-self neighbor (whose own degree exceeds one); everything else
    is a *hub* and becomes its own cluster. Spokes are dead ends, so
    every shortest path factors as access + inter-hub + access, and
    hub-to-hub shortest paths never detour through a spoke — the
    [C,C] cluster matrices are the dense pipeline run on the hub
    subgraph alone. Raises GmlError when the graph does not fit the
    factored form (directed, or direct-edge-only routing)."""
    if top.directed:
        raise GmlError("hierarchical representation requires an "
                       "undirected graph")
    if not top.use_shortest_path:
        raise GmlError("hierarchical representation requires "
                       "use_shortest_path: true (direct-edge-only "
                       "routing does not factor)")
    V = top.n_vertices
    av, au, alat, arel = sparse_min_adjacency(
        V, False, top.edge_src, top.edge_dst,
        top.edge_latency_ns, top.edge_reliability)

    off = av != au
    ov, ou = av[off], au[off]
    olat, orel = alat[off], arel[off]
    deg = np.bincount(ov, minlength=V)        # distinct neighbors
    nbr_of = np.full(V, 0, dtype=np.int64)
    nbr_of[ov] = ou                           # exact for deg==1 rows
    spoke = (deg == 1) & (deg[nbr_of] > 1)

    hub_vertex = np.nonzero(~spoke)[0].astype(np.int64)
    C = len(hub_vertex)
    hub_rank = np.full(V, -1, dtype=np.int64)
    hub_rank[hub_vertex] = np.arange(C, dtype=np.int64)
    cl = hub_rank.copy()
    cl[spoke] = hub_rank[nbr_of[spoke]]

    # access factors: the reduced spoke->hub entry (dense adjacency
    # semantics — cheapest parallel edge, first-minimal tie)
    acc_lat = np.zeros(V, dtype=np.int64)
    acc_rel = np.ones(V, dtype=np.float32)
    m = spoke[ov]
    acc_lat[ov[m]] = olat[m]
    acc_rel[ov[m]] = orel[m]

    # inter-cluster matrices: dense shortest paths over the hubs only
    if C == 1:
        cc_lat = np.zeros((1, 1), dtype=np.int64)
        cc_rel = np.ones((1, 1), dtype=np.float32)
    else:
        hub_edge = (~spoke)[top.edge_src] & (~spoke)[top.edge_dst]
        hsrc = hub_rank[np.asarray(top.edge_src)[hub_edge]]
        hdst = hub_rank[np.asarray(top.edge_dst)[hub_edge]]
        rv, ru, rl, rr = sparse_min_adjacency(
            C, False, hsrc, hdst,
            np.asarray(top.edge_latency_ns)[hub_edge],
            np.asarray(top.edge_reliability)[hub_edge])
        dlat = np.zeros((C, C), dtype=np.int64)
        drel = np.zeros((C, C), dtype=np.float32)
        dlat[rv, ru] = rl
        drel[rv, ru] = rr
        # a disconnected hub subgraph would contradict connectivity
        # of the full graph (spokes are dead ends) — _all_pairs
        # raises loudly if the structural argument is ever violated
        cc_lat, cc_rel = _all_pairs_shortest(dlat, drel, None)
    np.fill_diagonal(cc_lat, 0)               # transit identity —
    np.fill_diagonal(cc_rel, 1.0)             # true self paths below

    # self vectors: the dense self-path rule (self-loop as-is, else
    # cheapest incident edge out-and-back), tuple-lexicographic min
    cand_v = av
    cand_lat = np.where(av == au, alat, 2 * alat)
    cand_rel = np.where(av == au, arel,
                        (arel * arel).astype(np.float32))
    order = np.lexsort((cand_rel.astype(np.float64), cand_lat,
                        cand_v))
    sv_, sl_, sr_ = cand_v[order], cand_lat[order], cand_rel[order]
    firstv = np.ones(len(sv_), dtype=bool)
    firstv[1:] = sv_[1:] != sv_[:-1]
    # no incident edge at all: the dense zero-latency clamp value
    self_lat = np.full(V, _MIN_PATH_LATENCY_NS, dtype=np.int64)
    self_rel = np.ones(V, dtype=np.float32)
    self_lat[sv_[firstv]] = sl_[firstv]
    self_rel[sv_[firstv]] = sr_[firstv]

    return HierTables(
        cluster_lat=cc_lat.astype(np.int64),
        cluster_rel=cc_rel.astype(np.float32),
        cl=cl.astype(np.int32), hub_vertex=hub_vertex,
        acc_lat=acc_lat, acc_rel=acc_rel,
        self_lat=self_lat, self_rel=self_rel)


def compute_path_matrices(direct_lat: np.ndarray, direct_rel: np.ndarray,
                          use_shortest_path: bool,
                          unreachable_lat: Optional[np.ndarray] = None
                          ) -> tuple[np.ndarray, np.ndarray]:
    """All-pairs (latency, reliability) path matrices from a dense
    direct-edge adjacency — the core of Topology._compute_paths,
    reusable per fault epoch with a modified edge set.

    `unreachable_lat`: None = a disconnected pair raises GmlError (the
    base-topology contract, topology.c:659-716); otherwise a [V,V]
    latency matrix whose entries stand in for unreachable pairs (the
    fault layer passes the healthy base matrix) with reliability 0 —
    the pair is undeliverable (every drop roll fails) but the latency
    stays finite so lookahead windows and the i32 device matrices are
    unaffected."""
    V = direct_lat.shape[0]

    if not use_shortest_path:
        path_lat = direct_lat.copy()
        path_rel = direct_rel.copy()
        # fault epochs only (unreachable_lat set): a zero off-diagonal
        # entry means a downed link on this complete graph — mark it
        # unreachable instead of letting the zero-latency clamp below
        # resurrect it as a 1 ms lossless path. The base topology
        # (unreachable_lat None) keeps the legacy clamp semantics
        # byte for byte (completeness is enforced upstream anyway).
        if unreachable_lat is not None:
            miss = (path_lat <= 0) & ~np.eye(V, dtype=bool)
            if miss.any():
                path_rel = np.where(miss, 0.0, path_rel)
                path_lat = np.where(miss, unreachable_lat, path_lat)
    else:
        path_lat, path_rel = _all_pairs_shortest(direct_lat, direct_rel,
                                                 unreachable_lat)

    # Self paths (topology.c:1431-1576): self-loop edge as-is,
    # otherwise cheapest incident edge doubled.
    for v in range(V):
        options: list[tuple[int, float]] = []
        if direct_lat[v, v] > 0:
            options.append((int(direct_lat[v, v]),
                            float(direct_rel[v, v])))
        out = [(int(2 * direct_lat[v, u]), float(direct_rel[v, u] ** 2))
               for u in range(V) if u != v and direct_lat[v, u] > 0]
        options.extend(out)
        if options:
            path_lat[v, v], path_rel[v, v] = min(options)
        else:
            path_lat[v, v], path_rel[v, v] = 0, 1.0

    # Clamp only *zero*-latency paths to 1 ms like the reference
    # (topology.c:1788) — sub-millisecond edges are legitimate.
    zero = path_lat <= 0
    if zero.any():
        path_rel = np.where(zero, 1.0, path_rel)
        path_lat = np.where(zero, _MIN_PATH_LATENCY_NS, path_lat)

    return path_lat.astype(np.int64), path_rel.astype(np.float32)


def _all_pairs_shortest(direct_lat: np.ndarray, direct_rel: np.ndarray,
                        unreachable_lat: Optional[np.ndarray]
                        ) -> tuple[np.ndarray, np.ndarray]:
    """All-pairs Dijkstra by latency; reliability is accumulated
    along the chosen (latency-)shortest path via the predecessor
    tree, replacing the reference's lazy per-source
    igraph_get_shortest_paths_dijkstra (topology.c:1682-1701)."""
    V = direct_lat.shape[0]
    try:
        from scipy.sparse import csr_matrix
        from scipy.sparse.csgraph import dijkstra
    except ImportError:
        return _all_pairs_minplus(direct_lat, direct_rel,
                                  unreachable_lat)

    # Exclude self-loops from transit paths (the reference's Dijkstra
    # operates on the simple graph; self paths are computed separately).
    w = direct_lat.astype(np.float64)
    np.fill_diagonal(w, 0.0)
    graph = csr_matrix(w)
    dist, pred = dijkstra(graph, directed=True, return_predecessors=True)
    unreachable = np.isinf(dist)
    if unreachable.any() and unreachable_lat is None:
        raise GmlError("graph is not connected (no path between some "
                       "vertex pair)")

    # Walk the predecessor tree breadth-first from each source:
    # rel[s,d] = rel[s,pred[d]] * edge_rel[pred[d],d]. Hop levels are
    # found by fixpoint (hops[s,d] = hops[s,pred]+1), <= diameter
    # iterations of O(V^2) vectorized work.
    hops = np.full((V, V), -1, dtype=np.int64)
    np.fill_diagonal(hops, 0)
    for _ in range(V):
        pending = (pred >= 0) & (hops < 0)
        if not pending.any():
            break
        s_idx, d_idx = np.nonzero(pending)
        parent_hops = hops[s_idx, pred[s_idx, d_idx]]
        ready = parent_hops >= 0
        if not ready.any():
            break
        hops[s_idx[ready], d_idx[ready]] = parent_hops[ready] + 1

    rel = np.zeros((V, V), dtype=np.float64)
    np.fill_diagonal(rel, 1.0)
    for h in range(1, int(hops.max()) + 1):
        s_idx, d_idx = np.nonzero(hops == h)
        pr = pred[s_idx, d_idx]
        rel[s_idx, d_idx] = rel[s_idx, pr] * direct_rel[pr, d_idx]

    lat = np.rint(np.where(unreachable, 0.0, dist)).astype(np.int64)
    if unreachable.any():
        lat = np.where(unreachable, unreachable_lat, lat)
        rel = np.where(unreachable, 0.0, rel)
    return lat, rel.astype(np.float32)


def _all_pairs_minplus(direct_lat: np.ndarray, direct_rel: np.ndarray,
                       unreachable_lat: Optional[np.ndarray]
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Dense Floyd-Warshall carrying reliability, scipy-free."""
    V = direct_lat.shape[0]
    # float64 avoids int64 INF+INF overflow; ns latencies are far
    # below 2**53 so the arithmetic stays exact.
    lat = np.where(direct_lat > 0, direct_lat.astype(np.float64), np.inf)
    np.fill_diagonal(lat, 0.0)
    rel = np.where(direct_lat > 0, direct_rel.astype(np.float64), 0.0)
    np.fill_diagonal(rel, 1.0)
    for k in range(V):
        via = lat[:, k, None] + lat[None, k, :]
        better = via < lat
        lat = np.where(better, via, lat)
        rel = np.where(better, rel[:, k, None] * rel[None, k, :], rel)
    unreachable = np.isinf(lat)
    if unreachable.any():
        if unreachable_lat is None:
            raise GmlError("graph is not connected (no path between "
                           "some vertex pair)")
        lat = np.where(unreachable, unreachable_lat.astype(np.float64),
                       lat)
        rel = np.where(unreachable, 0.0, rel)
    return np.rint(lat).astype(np.int64), rel.astype(np.float32)


def _parse_edge_latency_ns(value) -> int:
    """Edge latency: unit string ("50 ms") per the reference's
    _topology_findEdgeAttributeStringTimeMs; bare numbers are taken as
    milliseconds for compatibility with older numeric GML files."""
    if isinstance(value, (int, float)):
        return int(round(value * simtime.SIMTIME_ONE_MILLISECOND))
    return parse_time_ns(value)


@dataclass
class Topology:
    directed: bool
    complete: bool
    use_shortest_path: bool
    vertex_ids: np.ndarray          # [V] original GML ids
    bw_down_bits: np.ndarray        # [V] int64 bits/s
    bw_up_bits: np.ndarray          # [V] int64 bits/s
    ip_strs: list[Optional[str]]
    country_codes: list[Optional[str]]
    city_codes: list[Optional[str]]
    labels: list[Optional[str]]
    edge_src: np.ndarray            # [E] vertex indices
    edge_dst: np.ndarray
    edge_latency_ns: np.ndarray     # [E] int64
    edge_reliability: np.ndarray    # [E] float32 (1 - packet_loss)
    # dense representation: [V,V] int64 / float32 path matrices.
    # Under representation == "hierarchical" BOTH are None and the
    # factored tables live in `hier` (hierarchy.HierTables) — every
    # consumer goes through path()/min_latency_ns or branches on
    # `hier`, so a stray dense read fails loudly instead of silently
    # reading stale zeros.
    latency_ns: Optional[np.ndarray]
    reliability: Optional[np.ndarray]
    representation: str = "dense"
    hier: Optional[HierTables] = None

    @property
    def n_vertices(self) -> int:
        return len(self.vertex_ids)

    @property
    def min_latency_ns(self) -> int:
        """Minimum path latency — the conservative lookahead window
        ("min time jump", controller.c:125-153)."""
        if self.hier is not None:
            return self.hier.min_latency_ns()
        return int(self.latency_ns.min())

    def path(self, src_vertex: int, dst_vertex: int
             ) -> tuple[int, float]:
        """(latency_ns, reliability) in whatever representation this
        topology holds — the single fault-free lookup seam."""
        if self.hier is not None:
            return self.hier.lookup(src_vertex, dst_vertex)
        return (int(self.latency_ns[src_vertex, dst_vertex]),
                float(self.reliability[src_vertex, dst_vertex]))

    def get_latency_ns(self, src_vertex: int, dst_vertex: int) -> int:
        return self.path(src_vertex, dst_vertex)[0]

    def get_reliability(self, src_vertex: int, dst_vertex: int) -> float:
        return self.path(src_vertex, dst_vertex)[1]

    def table_nbytes(self) -> int:
        """Bytes of the path tables this representation holds — what
        admission/bench report as the world table cost."""
        if self.hier is not None:
            return self.hier.nbytes()
        return int(self.latency_ns.nbytes + self.reliability.nbytes)

    def vertex_index_for_id(self, gml_id: int) -> int:
        idx = np.nonzero(self.vertex_ids == gml_id)[0]
        if len(idx) == 0:
            raise GmlError(f"no vertex with GML id {gml_id}")
        return int(idx[0])

    # ------------------------------------------------------------------
    @classmethod
    def from_gml(cls, text: str, use_shortest_path: bool = True,
                 representation: str = "dense") -> "Topology":
        g = parse_gml(text)
        return cls.from_parsed(g, use_shortest_path,
                               representation=representation)

    @classmethod
    def builtin_1_gbit_switch(cls,
                              representation: str = "dense"
                              ) -> "Topology":
        return cls.from_gml(ONE_GBIT_SWITCH_GML,
                            use_shortest_path=True,
                            representation=representation)

    @classmethod
    def from_parsed(cls, g: GmlGraph, use_shortest_path: bool,
                    representation: str = "dense") -> "Topology":
        V = len(g.nodes)
        if V == 0:
            raise GmlError("graph has no vertices")

        ids = np.array([int(n.get("id")) for n in g.nodes], dtype=np.int64)
        if len(set(ids.tolist())) != V:
            raise GmlError("duplicate vertex ids")
        id_to_idx = {int(i): k for k, i in enumerate(ids)}

        def _bw(node, key):
            v = node.get(key)
            if v is None:
                raise GmlError(f"vertex {node.get('id')} missing required "
                               f"attribute {key!r}")
            return parse_bandwidth_bits(v)

        bw_down = np.array([_bw(n, "bandwidth_down") for n in g.nodes],
                           dtype=np.int64)
        bw_up = np.array([_bw(n, "bandwidth_up") for n in g.nodes],
                         dtype=np.int64)
        ip_strs = [n.get("ip_address") for n in g.nodes]
        countries = [n.get("country_code") for n in g.nodes]
        cities = [n.get("city_code") for n in g.nodes]
        labels = [n.get("label") for n in g.nodes]

        E = len(g.edges)
        esrc = np.empty(E, dtype=np.int64)
        edst = np.empty(E, dtype=np.int64)
        elat = np.empty(E, dtype=np.int64)
        erel = np.empty(E, dtype=np.float32)
        for k, e in enumerate(g.edges):
            try:
                esrc[k] = id_to_idx[int(e.get("source"))]
                edst[k] = id_to_idx[int(e.get("target"))]
            except KeyError as bad:
                raise GmlError(
                    f"edge references unknown vertex id "
                    f"{bad}") from bad
            lat = e.get("latency")
            if lat is None:
                raise GmlError("edge missing required attribute 'latency'")
            elat[k] = _parse_edge_latency_ns(lat)
            if elat[k] <= 0:
                raise GmlError(f"edge {k} has latency <= 0")
            loss = e.get("packet_loss")
            if loss is None:
                raise GmlError("edge missing required attribute "
                               "'packet_loss'")
            loss = float(loss)
            if not (0.0 <= loss <= 1.0):
                raise GmlError(f"edge {k} packet_loss {loss} not in [0,1]")
            erel[k] = 1.0 - loss

        top = cls(
            directed=g.directed, complete=False,
            use_shortest_path=use_shortest_path,
            vertex_ids=ids, bw_down_bits=bw_down, bw_up_bits=bw_up,
            ip_strs=ip_strs, country_codes=countries, city_codes=cities,
            labels=labels,
            edge_src=esrc, edge_dst=edst, edge_latency_ns=elat,
            edge_reliability=erel,
            latency_ns=np.zeros((V, V), dtype=np.int64),
            reliability=np.zeros((V, V), dtype=np.float32),
        )
        top._check_connected()
        top.complete = top._detect_complete()
        if not use_shortest_path and not top.complete:
            raise GmlError("use_shortest_path=false requires a complete "
                           "graph (every ordered vertex pair needs a "
                           "direct edge)")
        top._compute_paths(representation)
        return top

    # ------------------------------------------------------------------
    def _adjacency(self) -> tuple[np.ndarray, np.ndarray]:
        return dense_adjacency(self.n_vertices, self.directed,
                               self.edge_src, self.edge_dst,
                               self.edge_latency_ns,
                               self.edge_reliability)

    def _check_connected(self) -> None:
        """Single (strongly-)connected component (topology.c:659-716)."""
        V = self.n_vertices
        adj = [[] for _ in range(V)]
        radj = [[] for _ in range(V)]
        for s, d in zip(self.edge_src, self.edge_dst):
            adj[s].append(int(d))
            radj[d].append(int(s))
            if not self.directed:
                adj[d].append(int(s))
                radj[s].append(int(d))

        def _bfs(start, neighbors):
            seen = np.zeros(V, dtype=bool)
            seen[start] = True
            stack = [start]
            while stack:
                u = stack.pop()
                for v in neighbors[u]:
                    if not seen[v]:
                        seen[v] = True
                        stack.append(v)
            return seen

        if not _bfs(0, adj).all():
            raise GmlError("graph is not connected")
        if self.directed and not _bfs(0, radj).all():
            raise GmlError("directed graph is not strongly connected")

    def _detect_complete(self) -> bool:
        """Every ordered pair of distinct vertices has a direct edge
        (topology.c:409-511)."""
        V = self.n_vertices
        if V == 1:
            return True
        lat, _ = self._adjacency()
        off_diag = ~np.eye(V, dtype=bool)
        return bool((lat[off_diag] > 0).all())

    # ------------------------------------------------------------------
    def _compute_dense(self) -> None:
        direct_lat, direct_rel = self._adjacency()
        self.latency_ns, self.reliability = compute_path_matrices(
            direct_lat, direct_rel, self.use_shortest_path)
        self.representation = "dense"
        self.hier = None

    def _compute_paths(self, representation: str = "dense") -> None:
        """Build the path tables in the requested representation.

        ``dense``        — the original [V,V] matrices, byte for byte.
        ``hierarchical`` — cluster-factored tables; a graph that does
                           not fit the factored form (directed,
                           direct-edge-only routing) or whose factored
                           float32 reliabilities fail the bit-exact
                           dense verification (V <= HIER_VERIFY_MAX_V)
                           is a HARD error.
        ``auto``         — hierarchical when it factors, verifies, and
                           actually shrinks the tables (C < V); dense
                           with a log line otherwise.
        """
        if representation not in REPRESENTATIONS:
            raise GmlError(
                f"network.topology.representation must be one of "
                f"{REPRESENTATIONS}, got {representation!r}")
        if representation == "dense":
            self._compute_dense()
            return
        try:
            ht = build_hier_tables(self)
        except GmlError as why:
            if representation == "hierarchical":
                raise GmlError(
                    "network.topology.representation: hierarchical, "
                    f"but this graph does not factor: {why}") from why
            log.info("topology representation auto: dense fallback "
                     "(%s)", why)
            self._compute_dense()
            return
        if representation == "auto" and \
                ht.n_clusters >= self.n_vertices:
            log.info("topology representation auto: dense (no spokes "
                     "— factoring would not shrink the tables, "
                     "C=%d == V=%d)", ht.n_clusters, self.n_vertices)
            self._compute_dense()
            return
        if self.n_vertices <= HIER_VERIFY_MAX_V:
            # bit-exact verification against the dense pipeline: the
            # loud contract that hierarchical traces match the dense
            # oracle on every backend
            direct_lat, direct_rel = self._adjacency()
            dlat, drel = compute_path_matrices(
                direct_lat, direct_rel, self.use_shortest_path)
            hlat, hrel = ht.dense()
            if not (np.array_equal(dlat, hlat)
                    and np.array_equal(drel, hrel)):
                if representation == "hierarchical":
                    raise GmlError(
                        "hierarchical tables do not reproduce the "
                        "dense path matrices bit for bit (equal-cost "
                        "multipath tie-break or a float32 "
                        "reliability product that does not factor) — "
                        "use representation: dense or auto")
                log.info("topology representation auto: dense "
                         "fallback (factored tables failed the "
                         "bit-exact verification)")
                self.latency_ns, self.reliability = dlat, drel
                self.representation = "dense"
                self.hier = None
                return
        self.hier = ht
        self.representation = "hierarchical"
        self.latency_ns = None
        self.reliability = None
        log.info("topology representation hierarchical: V=%d C=%d "
                 "table bytes %d (dense would be %d)",
                 self.n_vertices, ht.n_clusters, ht.nbytes(),
                 12 * self.n_vertices ** 2)
