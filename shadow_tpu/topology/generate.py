"""Programmatic topology generators (network.graph.type: star_clusters).

A million-host topology cannot arrive as a GML file: parsing a million
node stanzas costs minutes, and `Topology.from_parsed` runs two O(V^2)
scans (the completeness detector materializes the dense adjacency, the
connectivity check walks a Python adjacency list). Generators build the
edge arrays directly with numpy and skip both scans — the generated
structure is connected and non-complete *by construction* — then hand
off to the shared `_compute_paths` dispatch, so representation
semantics (dense / hierarchical / auto, verification, fallback) are
identical to a parsed graph's.

`star_clusters` is the canonical hierarchical shape: `clusters` hub
vertices forming a complete inter-hub graph, each with
`spokes_per_cluster` spoke vertices hanging off it. Vertex ids are the
indices: hubs 0..C-1, then the spokes of hub h at
C + h*S .. C + (h+1)*S - 1 — so a host group with `network_node_id: C`
and `network_node_stride: 1` tiles hosts across the spokes with O(1)
placement per host.
"""

from __future__ import annotations

import numpy as np

from shadow_tpu.config.units import parse_bandwidth_bits, parse_time_ns
from shadow_tpu.topology.graph import GmlError, Topology
from shadow_tpu.utils.slog import get_logger

log = get_logger("topology.generate")


def generate_star_clusters(params: dict,
                           use_shortest_path: bool = True,
                           representation: str = "dense") -> Topology:
    """Build the hub-and-spoke cluster topology from the
    `network.graph` generator keys (config/schema.py validates the key
    set; this validates the values)."""
    C = int(params.get("clusters", 1))
    S = int(params.get("spokes_per_cluster", 0))
    if C < 1:
        raise GmlError("star_clusters: clusters must be >= 1")
    if S < 0:
        raise GmlError("star_clusters: spokes_per_cluster must "
                       "be >= 0")
    hub_lat = parse_time_ns(params.get("hub_latency", "10 ms"))
    acc_lat = parse_time_ns(params.get("access_latency", "1 ms"))
    if hub_lat <= 0 or acc_lat <= 0:
        raise GmlError("star_clusters: latencies must be > 0")
    hub_loss = float(params.get("hub_packet_loss", 0.0))
    acc_loss = float(params.get("access_packet_loss", 0.0))
    for name, loss in (("hub_packet_loss", hub_loss),
                       ("access_packet_loss", acc_loss)):
        if not (0.0 <= loss <= 1.0):
            raise GmlError(f"star_clusters: {name} {loss} not in "
                           "[0,1]")
    bw_down = parse_bandwidth_bits(params.get("bandwidth_down",
                                              "1 Gbit"))
    bw_up = parse_bandwidth_bits(params.get("bandwidth_up", "1 Gbit"))

    V = C + C * S
    # complete inter-hub graph: one undirected edge per hub pair
    hi, hj = np.triu_indices(C, k=1)
    # spoke k of hub h sits at vertex C + h*S + k
    sp = np.arange(C * S, dtype=np.int64) + C
    sp_hub = (np.arange(C * S, dtype=np.int64) // max(1, S)) \
        if S else np.empty(0, dtype=np.int64)
    esrc = np.concatenate([hi.astype(np.int64), sp_hub])
    edst = np.concatenate([hj.astype(np.int64), sp])
    E_hub = len(hi)
    elat = np.concatenate([
        np.full(E_hub, hub_lat, dtype=np.int64),
        np.full(C * S, acc_lat, dtype=np.int64)])
    erel = np.concatenate([
        np.full(E_hub, np.float32(1.0 - hub_loss), dtype=np.float32),
        np.full(C * S, np.float32(1.0 - acc_loss), dtype=np.float32)])

    top = Topology(
        directed=False,
        # a star is complete only in the degenerate 1-vertex case —
        # set statically, never via the O(V^2) detector
        complete=(V == 1),
        use_shortest_path=use_shortest_path,
        vertex_ids=np.arange(V, dtype=np.int64),
        bw_down_bits=np.full(V, bw_down, dtype=np.int64),
        bw_up_bits=np.full(V, bw_up, dtype=np.int64),
        ip_strs=[None] * V, country_codes=[None] * V,
        city_codes=[None] * V, labels=[None] * V,
        edge_src=esrc, edge_dst=edst,
        edge_latency_ns=elat, edge_reliability=erel,
        latency_ns=None, reliability=None,
    )
    if not use_shortest_path and not top.complete:
        raise GmlError("use_shortest_path=false requires a complete "
                       "graph (every ordered vertex pair needs a "
                       "direct edge)")
    log.info("star_clusters: V=%d (C=%d hubs, %d spokes/hub), E=%d",
             V, C, S, len(esrc))
    top._compute_paths(representation)
    return top
