"""Cluster-factored topology tables (hierarchical representation).

The dense representation (topology/graph.py) stores all-pairs [V,V]
latency/reliability matrices — O(V^2) memory makes a million-host
topology infeasible (one int64 [V,V] at V=1e6 is ~8 TB). Real
internet-scale topologies are hierarchical: most vertices are *spokes*
(hosts / leaf PoPs) hanging off a much smaller core of *hubs*
(AS/PoP routers). On such a graph every shortest path factors exactly:

    lat[s,d] = acc_lat[s] + cluster_lat[c(s), c(d)] + acc_lat[d]
    rel[s,d] = (acc_rel[s] * cluster_rel[c(s), c(d)]) * acc_rel[d]

with s == d handled by an explicit self vector (the dense self-path
rule), because a spoke's only way in or out of the graph is its single
hub edge, and a shortest path between hubs never detours through a
spoke (it would re-enter through the same hub, adding two positive
edges). Memory drops to O(C^2 + V): a [C,C] inter-cluster pair over
the hubs, a [V] cluster assignment, [V] access-link factors, and [V]
self-path vectors.

Exactness contract (docs/topology.md has the full statement):

* latency is EXACT on every factorable graph — integer addition
  composes losslessly and the factored terms are the dense path sums;
* reliability is exact whenever every access link is lossless
  (multiplying by float32 1.0 is exact and the cluster entries are
  the dense hub-path values), and bit-verified against the dense
  pipeline at build time for V <= HIER_VERIFY_MAX_V. On larger lossy
  graphs the factored float32 product can differ from the dense
  float64-accumulate-then-round path by an ulp; the builder
  (graph.py) refuses / falls back per the representation knob.

This module is deliberately dependency-light — numpy plus the _jax
shim ONLY — because the device engine imports it (the two-level
gather lives here) and therefore it is a CODE_DIGEST_MODULES member
(device/aotcache.py): every transitive import would join the SL201
digest surface. The *builder* (hub/spoke detection against a parsed
graph, dense verification) lives in topology/graph.py, which imports
this module, never the reverse.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from shadow_tpu._jax import jnp

# Full elementwise dense-equality verification threshold: below this
# vertex count the builder materializes the dense matrices and proves
# the factored tables reproduce them bit for bit (cheap — [V,V] at
# V=2048 is 48 MB); above it the structural latency argument stands
# alone and reliability exactness needs lossless access links.
HIER_VERIFY_MAX_V = 2048


def compose_lat(acc_s, core, acc_d):
    """Factored latency composition — plain integer addition, exact in
    every integer dtype wide enough for the bound (see
    max_composed_latency)."""
    return acc_s + core + acc_d


def compose_rel(acc_s, core, acc_d):
    """Factored reliability composition with a FIXED association:
    (acc_s * core) * acc_d. Every consumer (CPU lookup, device
    gather, fault epochs, verification) uses this exact order so
    float32 non-associativity can never split the backends."""
    return (acc_s * core) * acc_d


@dataclass
class HierTables:
    """The factored tables. Hubs are their own cluster (acc terms 0
    latency / 1.0 reliability); cluster_lat/cluster_rel diagonals are
    the TRANSIT identity (0 ns / 1.0) — intra-cluster pairs compose
    through them — while true self paths come from the self vectors."""

    cluster_lat: np.ndarray        # [C,C] int64, diag 0
    cluster_rel: np.ndarray        # [C,C] float32, diag 1.0
    cl: np.ndarray                 # [V] int32 cluster of each vertex
    hub_vertex: np.ndarray         # [C] int64 vertex index of each hub
    acc_lat: np.ndarray            # [V] int64 access latency (hubs 0)
    acc_rel: np.ndarray            # [V] float32 access rel (hubs 1.0)
    self_lat: np.ndarray           # [V] int64 dense self-path rule
    self_rel: np.ndarray           # [V] float32

    @property
    def n_vertices(self) -> int:
        return len(self.cl)

    @property
    def n_clusters(self) -> int:
        return len(self.hub_vertex)

    def lat_parts(self) -> tuple:
        """The additive world leaves, in gather_parts order."""
        return (self.cluster_lat, self.cl, self.acc_lat, self.self_lat)

    def rel_parts(self) -> tuple:
        """The multiplicative world leaves, in gather_parts order."""
        return (self.cluster_rel, self.cl, self.acc_rel, self.self_rel)

    def lookup(self, sv: int, dv: int) -> tuple[int, float]:
        """(latency_ns, reliability) for one pair — the CPU twin of
        the device gather, float32 ops in the shared fixed order."""
        if sv == dv:
            return int(self.self_lat[sv]), float(self.self_rel[sv])
        cs, cd = int(self.cl[sv]), int(self.cl[dv])
        lat = compose_lat(int(self.acc_lat[sv]),
                          int(self.cluster_lat[cs, cd]),
                          int(self.acc_lat[dv]))
        rel = compose_rel(self.acc_rel[sv],
                          self.cluster_rel[cs, cd],
                          self.acc_rel[dv])
        return lat, float(rel)

    def dense(self) -> tuple[np.ndarray, np.ndarray]:
        """Materialize the full [V,V] matrices (verification/tests
        only — O(V^2)). Elementwise float32 ops in the shared order,
        so equality against this IS equality against every lookup."""
        lat, rel = dense_from_parts(self.lat_parts(), self.rel_parts())
        return lat, rel

    def min_latency_ns(self) -> int:
        return min_latency_from_parts(self.lat_parts())

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for a in
                   (self.cluster_lat, self.cluster_rel, self.cl,
                    self.acc_lat, self.acc_rel,
                    self.self_lat, self.self_rel))


def dense_from_parts(lat_parts, rel_parts
                     ) -> tuple[np.ndarray, np.ndarray]:
    """[V,V] materialization of single-epoch factored parts, with the
    same composition ops/order as the scalar and device lookups."""
    cc, cl, acc, slf = lat_parts
    ccr, _, accr, slfr = rel_parts
    cc = np.asarray(cc, np.int64)
    acc = np.asarray(acc, np.int64)
    core = cc[np.asarray(cl)[:, None], np.asarray(cl)[None, :]]
    lat = compose_lat(acc[:, None], core, acc[None, :])
    accr = np.asarray(accr, np.float32)
    corer = np.asarray(ccr, np.float32)[
        np.asarray(cl)[:, None], np.asarray(cl)[None, :]]
    rel = compose_rel(accr[:, None], corer, accr[None, :])
    np.fill_diagonal(lat, np.asarray(slf, np.int64))
    np.fill_diagonal(rel, np.asarray(slfr, np.float32))
    return lat.astype(np.int64), rel.astype(np.float32)


def min_latency_from_parts(lat_parts) -> int:
    """EXACT min over the implied dense [V,V] latency (diagonal
    included) in O(V + C^2): candidates are the min off-diagonal
    cluster entry (hubs compose with 0 access), the min spoke access
    latency (each spoke pairs with its own hub through the 0
    diagonal), and the min self path."""
    cc, cl, acc, slf = lat_parts
    cc = np.asarray(cc, np.int64)
    acc = np.asarray(acc, np.int64)
    cands = [int(np.asarray(slf, np.int64).min())]
    C = cc.shape[0]
    if C > 1:
        cands.append(int(cc[~np.eye(C, dtype=bool)].min()))
    spoke = acc > 0
    if spoke.any():
        cands.append(int(acc[spoke].min()))
    return min(cands)


def max_composed_latency(lat_parts) -> int:
    """Upper bound of every composed latency — what must fit the i32
    device matrices (the dense path checks latency_ns.max())."""
    cc, cl, acc, slf = lat_parts
    hi = 2 * int(np.asarray(acc, np.int64).max(initial=0)) + \
        int(np.asarray(cc, np.int64).max(initial=0))
    return max(hi, int(np.asarray(slf, np.int64).max(initial=0)))


def all_rel1(rel_parts) -> bool:
    """Statically-lossless check over the factored leaves — the hier
    twin of (reliability >= 1).all() on the dense matrix."""
    ccr, _, accr, slfr = rel_parts
    return bool(np.asarray(ccr).min(initial=1.0) >= 1.0
                and np.asarray(accr).min(initial=1.0) >= 1.0
                and np.asarray(slfr).min(initial=1.0) >= 1.0)


def gather_parts(parts, sv, dv, e=None):
    """The device-side two-level gather shared by the engine and the
    hybrid judge. `parts` = (cc, cl, acc, slf) as traced jax arrays; a
    floating cc selects the multiplicative (reliability) composition,
    an integer cc the additive (latency) one — both in the module's
    fixed order. `e` (same broadcast shape as sv/dv) indexes a leading
    per-epoch axis on every leaf; None = single epoch."""
    cc, cl, acc, slf = parts
    mul = jnp.issubdtype(cc.dtype, jnp.floating)
    if e is None:
        cs, cd = cl[sv], cl[dv]
        a_s, a_d, sf = acc[sv], acc[dv], slf[sv]
        core = cc[cs, cd]
    else:
        cs, cd = cl[e, sv], cl[e, dv]
        a_s, a_d, sf = acc[e, sv], acc[e, dv], slf[e, sv]
        core = cc[e, cs, cd]
    comp = compose_rel(a_s, core, a_d) if mul \
        else compose_lat(a_s, core, a_d)
    return jnp.where(sv == dv, sf, comp)


def world_tables(topology, fault_table):
    """(latency, reliability, epoch_times) in whatever representation
    the topology selected — dense ndarrays, or factored part tuples
    under `representation: hierarchical` (fault schedules stack a
    leading [T] axis on every leaf). The single resolver the device
    runner and the hybrid judge share, so the two cannot disagree on
    what rides the world tuple. Duck-typed on purpose: fault tables
    live in shadow_tpu/faults.py, which must stay OUT of this
    module's import graph (see the module docstring)."""
    hier = getattr(topology, "hier", None)
    if fault_table is None:
        if hier is not None:
            return hier.lat_parts(), hier.rel_parts(), None
        return (np.asarray(topology.latency_ns, np.int64),
                np.asarray(topology.reliability, np.float32),
                None)
    times = np.asarray(fault_table.times, np.int64)
    if getattr(fault_table, "is_hierarchical", False):
        return (fault_table.lat_parts_stacked(),
                fault_table.rel_parts_stacked(), times)
    return (np.asarray(fault_table.latency_ns, np.int64),
            np.asarray(fault_table.reliability, np.float32), times)
