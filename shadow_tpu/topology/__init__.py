from shadow_tpu.topology.gml import parse_gml, GmlGraph
from shadow_tpu.topology.graph import Topology, ONE_GBIT_SWITCH_GML
from shadow_tpu.topology.attach import HostAttachment

__all__ = [
    "parse_gml",
    "GmlGraph",
    "Topology",
    "ONE_GBIT_SWITCH_GML",
    "HostAttachment",
]
