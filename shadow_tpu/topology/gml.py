"""GML (Graph Modelling Language) parsing.

The reference loads network graphs with igraph's GML reader
(src/main/routing/topology.c:326-360). We parse the same dialect
ourselves — the format is a simple recursive `key value` / `key [ ... ]`
structure — so the framework has no external graph-library dependency.

Supported value types: integers, floats, double-quoted strings (with
backslash escapes), and nested lists. Comments start with `#` outside
strings. Keys can repeat (e.g. many `node [...]` blocks).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Iterator, Union

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<lbracket>\[)
      | (?P<rbracket>\])
      | (?P<number>[+-]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_]*)
    )
    """,
    re.VERBOSE,
)

Value = Union[int, float, str, "GmlRecord"]


class GmlError(ValueError):
    pass


class GmlRecord:
    """An ordered multimap of key -> values (keys may repeat)."""

    def __init__(self):
        self._items: list[tuple[str, Value]] = []

    def add(self, key: str, value: Value) -> None:
        self._items.append((key, value))

    def get(self, key: str, default=None) -> Value:
        for k, v in self._items:
            if k == key:
                return v
        return default

    def get_all(self, key: str) -> list[Value]:
        return [v for k, v in self._items if k == key]

    def __contains__(self, key: str) -> bool:
        return any(k == key for k, _ in self._items)

    def items(self) -> Iterator[tuple[str, Value]]:
        return iter(self._items)

    def __repr__(self) -> str:
        return f"GmlRecord({self._items!r})"


def _tokenize(text: str) -> Iterator[tuple[str, str]]:
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            if text[pos:].strip() == "":
                return
            raise GmlError(f"bad GML syntax at offset {pos}: "
                           f"{text[pos:pos+40]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "comment":
            continue
        yield kind, m.group(kind)
    return


def _parse_record(tokens: Iterator[tuple[str, str]], depth: int) -> GmlRecord:
    rec = GmlRecord()
    for kind, tok in tokens:
        if kind == "rbracket":
            if depth == 0:
                raise GmlError("unbalanced ']'")
            return rec
        if kind != "key":
            raise GmlError(f"expected key, got {tok!r}")
        key = tok
        try:
            vkind, vtok = next(tokens)
        except StopIteration:
            raise GmlError(f"key {key!r} has no value") from None
        if vkind == "lbracket":
            rec.add(key, _parse_record(tokens, depth + 1))
        elif vkind == "string":
            rec.add(key, vtok[1:-1].replace('\\"', '"').replace("\\\\", "\\"))
        elif vkind == "number":
            try:
                rec.add(key, int(vtok))
            except ValueError:
                rec.add(key, float(vtok))
        elif vkind == "key":
            # bare words (GML allows unquoted constants like `directed 0`
            # only as numbers, but be permissive and keep the word)
            rec.add(key, vtok)
        else:
            raise GmlError(f"unexpected value token {vtok!r} for key {key!r}")
    if depth != 0:
        raise GmlError("unbalanced '['")
    return rec


@dataclass
class GmlGraph:
    directed: bool = False
    nodes: list[GmlRecord] = field(default_factory=list)
    edges: list[GmlRecord] = field(default_factory=list)
    attrs: GmlRecord = field(default_factory=GmlRecord)


def parse_gml(text: str) -> GmlGraph:
    top = _parse_record(_tokenize(text), 0)
    graph = top.get("graph")
    if not isinstance(graph, GmlRecord):
        raise GmlError("no 'graph [...]' block found")
    out = GmlGraph(attrs=graph)
    out.directed = bool(graph.get("directed", 0))
    for node in graph.get_all("node"):
        if not isinstance(node, GmlRecord):
            raise GmlError("'node' must be a [...] block")
        if "id" not in node:
            raise GmlError("node missing required 'id'")
        out.nodes.append(node)
    for edge in graph.get_all("edge"):
        if not isinstance(edge, GmlRecord):
            raise GmlError("'edge' must be a [...] block")
        if "source" not in edge or "target" not in edge:
            raise GmlError("edge missing required 'source'/'target'")
        out.edges.append(edge)
    return out
