"""Replica worlds: the value-only parameter stacks an ensemble varies.

An ensemble campaign runs R replicas of one device-twin workload in a
single compiled program (device/engine.py vmaps the fused round step
over a leading replica axis, outside the host shard axis). The ONLY
things a replica may vary are array *values* the engine already takes
as traced inputs — the seed key pair, the topology latency/reliability
tables, and the fault-epoch start times. Shapes are shared: every
replica sees the same hosts, capacities, stop time, and epoch count
(shorter fault schedules pad with never-reached epochs that repeat
their last real matrices).

This module turns the validated ``ensemble:`` config block
(config/schema.py EnsembleOptions) into an :class:`EnsembleWorlds` —
the stacked numpy arrays the engine consumes — plus the campaign
fingerprint that stamps checkpoints and the ENSEMBLE_*.json record.

Determinism contract: replica *i*'s slice of the stacked world is
value-identical to the world a standalone run with replica *i*'s
parameters would build, so replica *i*'s trace is bit-identical to
that standalone run (determinism_gate.py --ensemble enforces it in
CI). The one shared scalar is the lookahead window: the campaign uses
the MIN over all replicas' tables (conservative for every replica); a
standalone comparison run pins ``experimental.runahead`` to it when
its own floor differs.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

# pad value for never-reached fault epochs: the engine's INF sentinel,
# far above any reachable sim time, so the epoch select can never pick
# a padded epoch for a real send (empty outbox rows gather it
# harmlessly — they are masked downstream)
FAR_EPOCH = np.int64(1) << np.int64(62)


@dataclass
class EnsembleWorlds:
    """Stacked per-replica world arrays (engine constructor input).

    latency/reliability are ``[R, V, V]`` when no replica has a fault
    schedule, else ``[R, T, V, V]`` with the shared padded epoch count
    T; epoch_times is ``[R, T]``; the seed key halves are ``[R]``
    uint32 (prng.seed_key split per replica).
    """

    R: int
    latency: np.ndarray
    reliability: np.ndarray
    epoch_times: np.ndarray
    seed_k1: np.ndarray
    seed_k2: np.ndarray
    seeds: np.ndarray              # [R] engine seeds
    lookahead: int                 # min latency over every replica
    descriptors: list = field(default_factory=list)
    campaign_fp: str = ""


def slice_worlds(w: EnsembleWorlds, lo: int, hi: int) -> EnsembleWorlds:
    """A replica-contiguous slice ``[lo, hi)`` of a stacked world, for
    sequential replica batches (``ensemble.replica_batch`` / the OOM
    degradation ladder's replica-batch rung in campaign.py). Every
    ``[R, ...]``-leading array is sliced; the shared scalars are kept
    VERBATIM — in particular the FULL campaign's lookahead (the min
    over ALL replicas: a batch-local min could differ and change
    round boundaries, breaking the batch == full-vmap bit-identity)
    and the full campaign fingerprint (records must name the
    campaign, not the batch)."""
    lo, hi = int(lo), int(hi)
    if not (0 <= lo < hi <= w.R):
        raise ValueError(
            f"slice_worlds: replica window [{lo}, {hi}) is outside "
            f"[0, {w.R})")
    return EnsembleWorlds(
        R=hi - lo,
        latency=w.latency[lo:hi],
        reliability=w.reliability[lo:hi],
        epoch_times=w.epoch_times[lo:hi],
        seed_k1=w.seed_k1[lo:hi],
        seed_k2=w.seed_k2[lo:hi],
        seeds=w.seeds[lo:hi],
        lookahead=w.lookahead,
        descriptors=list(w.descriptors[lo:hi]),
        campaign_fp=w.campaign_fp,
    )


def seed_key_np(seed: int) -> tuple[np.uint32, np.uint32]:
    """numpy twin of device/prng.seed_key — the same 64-bit mask and
    split, so the traced per-replica keys are bit-identical to the
    scalars a standalone engine would close over."""
    s = int(seed) & 0xFFFF_FFFF_FFFF_FFFF
    return np.uint32(s >> 32), np.uint32(s & 0xFFFF_FFFF)


def campaign_fingerprint(R: int, seeds, descriptors,
                         latency: np.ndarray, reliability: np.ndarray,
                         epoch_times: np.ndarray) -> str:
    """Digest of everything that defines the campaign's replica set.
    Checkpoints stamp it (resuming a campaign against an edited vary
    block must fail loudly) and the ENSEMBLE record carries it."""
    h = hashlib.sha256()
    h.update(f"R={R}".encode())
    h.update(np.asarray(seeds, np.int64).tobytes())
    for d in descriptors:
        h.update(repr(sorted(d.items())).encode())
    for a in (latency, reliability, epoch_times):
        a = np.ascontiguousarray(a)
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:12]


def build_worlds(sim, eopts) -> EnsembleWorlds:
    """Compile the ``ensemble:`` block against a built simulation into
    the stacked world arrays. `sim` is a BuiltSimulation (topology +
    base fault table already compiled); `eopts` the validated
    EnsembleOptions."""
    from shadow_tpu import faults as faultmod

    cfg = sim.cfg
    R = int(eopts.replicas)
    vary = eopts.vary
    seeds = [int(s) for s in vary.get("seed",
                                      [cfg.general.seed] * R)]
    scales = [float(x) for x in vary.get("latency_scale", [1.0] * R)]
    deltas = [float(x) for x in vary.get("packet_loss_delta",
                                         [0.0] * R)]
    names = [str(n) for n in vary.get("fault_schedule", ["base"] * R)]

    # compile each distinct named schedule once against the topology
    # (the same dense_adjacency + shortest-path pipeline the base
    # network.faults schedule went through at build time)
    tables: dict = {}

    def table_for(name: str):
        if name not in tables:
            if name == "base":
                tables[name] = sim.fault_table
            elif name == "none":
                tables[name] = None
            else:
                tables[name] = faultmod.compile_link_faults(
                    sim.topology, eopts.fault_schedules[name])
        return tables[name]

    base_lat = np.asarray(sim.topology.latency_ns, np.int64)
    base_rel = np.asarray(sim.topology.reliability, np.float32)
    per = []
    T_max = 1
    for r in range(R):
        tab = table_for(names[r])
        if tab is None:
            times = np.zeros(1, np.int64)
            lat = base_lat[None]
            rel = base_rel[None].astype(np.float64)
        else:
            times = np.asarray(tab.times, np.int64)
            lat = np.asarray(tab.latency_ns, np.int64)
            rel = np.asarray(tab.reliability,
                             np.float32).astype(np.float64)
        if scales[r] != 1.0:
            lat = np.maximum(1, np.rint(
                lat.astype(np.float64) * scales[r])).astype(np.int64)
        if deltas[r] != 0.0:
            rel = np.clip(rel - deltas[r], 0.0, 1.0)
        per.append((times, lat, rel.astype(np.float32)))
        T_max = max(T_max, len(times))

    lats, rels, eps = [], [], []
    for times, lat, rel in per:
        pad = T_max - len(times)
        if pad:
            # never-reached epochs repeating the last real matrices:
            # value-identical lookups for every reachable send time
            times = np.concatenate(
                [times, np.full(pad, FAR_EPOCH, np.int64)])
            lat = np.concatenate([lat, np.repeat(lat[-1:], pad, 0)])
            rel = np.concatenate([rel, np.repeat(rel[-1:], pad, 0)])
        eps.append(times)
        lats.append(lat)
        rels.append(rel)
    latency = np.stack(lats)               # [R, T, V, V]
    reliability = np.stack(rels)
    epoch_times = np.stack(eps)            # [R, T]
    if T_max == 1:
        # fault-free campaigns keep the plain [R, V, V] matrices so
        # each replica's program matches the pre-fault-layer engine
        # byte for byte (the same squeeze the standalone engine does)
        latency = latency[:, 0]
        reliability = reliability[:, 0]

    if (latency > np.iinfo(np.int32).max).any():
        bad = [r for r in range(R)
               if (latency[r] > np.iinfo(np.int32).max).any()]
        raise ValueError(
            f"ensemble: replica(s) {bad} have scaled path latencies "
            "above ~2.1 s — they do not fit the i32 device latency "
            "matrix (lower vary.latency_scale)")

    k1 = np.empty(R, np.uint32)
    k2 = np.empty(R, np.uint32)
    for r, s in enumerate(seeds):
        k1[r], k2[r] = seed_key_np(s)

    descriptors = [
        {"replica": r, "seed": seeds[r], "latency_scale": scales[r],
         "packet_loss_delta": deltas[r], "fault_schedule": names[r]}
        for r in range(R)]
    return EnsembleWorlds(
        R=R,
        latency=latency.astype(np.int32),
        reliability=reliability.astype(np.float32),
        epoch_times=epoch_times,
        seed_k1=k1, seed_k2=k2,
        seeds=np.asarray(seeds, np.int64),
        lookahead=int(latency.min()),
        descriptors=descriptors,
        campaign_fp=campaign_fingerprint(
            R, seeds, descriptors, latency, reliability, epoch_times),
    )
