"""Replica worlds: the value-only parameter stacks an ensemble varies.

An ensemble campaign runs R replicas of one device-twin workload in a
single compiled program (device/engine.py vmaps the fused round step
over a leading replica axis, outside the host shard axis). The ONLY
things a replica may vary are array *values* the engine already takes
as traced inputs — the seed key pair, the topology latency/reliability
tables, and the fault-epoch start times. Shapes are shared: every
replica sees the same hosts, capacities, stop time, and epoch count
(shorter fault schedules pad with never-reached epochs that repeat
their last real matrices).

This module turns the validated ``ensemble:`` config block
(config/schema.py EnsembleOptions) into an :class:`EnsembleWorlds` —
the stacked numpy arrays the engine consumes — plus the campaign
fingerprint that stamps checkpoints and the ENSEMBLE_*.json record.

Determinism contract: replica *i*'s slice of the stacked world is
value-identical to the world a standalone run with replica *i*'s
parameters would build, so replica *i*'s trace is bit-identical to
that standalone run (determinism_gate.py --ensemble enforces it in
CI). The one shared scalar is the lookahead window: the campaign uses
the MIN over all replicas' tables (conservative for every replica); a
standalone comparison run pins ``experimental.runahead`` to it when
its own floor differs.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

import numpy as np

from shadow_tpu.topology import hierarchy

# pad value for never-reached fault epochs: the engine's INF sentinel,
# far above any reachable sim time, so the epoch select can never pick
# a padded epoch for a real send (empty outbox rows gather it
# harmlessly — they are masked downstream)
FAR_EPOCH = np.int64(1) << np.int64(62)


@dataclass
class EnsembleWorlds:
    """Stacked per-replica world arrays (engine constructor input).

    latency/reliability are ``[R, V, V]`` when no replica has a fault
    schedule, else ``[R, T, V, V]`` with the shared padded epoch count
    T; epoch_times is ``[R, T]``; the seed key halves are ``[R]``
    uint32 (prng.seed_key split per replica).

    Under ``network.topology.representation: hierarchical``,
    latency/reliability are instead TUPLES of factored leaves
    (topology/hierarchy.py parts order), each stacked ``[R, ...]``
    (with the shared ``[T]`` epoch axis after R when any replica has
    a fault schedule).
    """

    R: int
    latency: np.ndarray
    reliability: np.ndarray
    epoch_times: np.ndarray
    seed_k1: np.ndarray
    seed_k2: np.ndarray
    seeds: np.ndarray              # [R] engine seeds
    lookahead: int                 # min latency over every replica
    descriptors: list = field(default_factory=list)
    campaign_fp: str = ""


def slice_worlds(w: EnsembleWorlds, lo: int, hi: int) -> EnsembleWorlds:
    """A replica-contiguous slice ``[lo, hi)`` of a stacked world, for
    sequential replica batches (``ensemble.replica_batch`` / the OOM
    degradation ladder's replica-batch rung in campaign.py). Every
    ``[R, ...]``-leading array is sliced; the shared scalars are kept
    VERBATIM — in particular the FULL campaign's lookahead (the min
    over ALL replicas: a batch-local min could differ and change
    round boundaries, breaking the batch == full-vmap bit-identity)
    and the full campaign fingerprint (records must name the
    campaign, not the batch)."""
    lo, hi = int(lo), int(hi)
    if not (0 <= lo < hi <= w.R):
        raise ValueError(
            f"slice_worlds: replica window [{lo}, {hi}) is outside "
            f"[0, {w.R})")
    def _sl(x):
        # hierarchical worlds are tuples of [R, ...] leaves
        if isinstance(x, tuple):
            return tuple(a[lo:hi] for a in x)
        return x[lo:hi]

    return EnsembleWorlds(
        R=hi - lo,
        latency=_sl(w.latency),
        reliability=_sl(w.reliability),
        epoch_times=w.epoch_times[lo:hi],
        seed_k1=w.seed_k1[lo:hi],
        seed_k2=w.seed_k2[lo:hi],
        seeds=w.seeds[lo:hi],
        lookahead=w.lookahead,
        descriptors=list(w.descriptors[lo:hi]),
        campaign_fp=w.campaign_fp,
    )


def seed_key_np(seed: int) -> tuple[np.uint32, np.uint32]:
    """numpy twin of device/prng.seed_key — the same 64-bit mask and
    split, so the traced per-replica keys are bit-identical to the
    scalars a standalone engine would close over."""
    s = int(seed) & 0xFFFF_FFFF_FFFF_FFFF
    return np.uint32(s >> 32), np.uint32(s & 0xFFFF_FFFF)


def campaign_fingerprint(R: int, seeds, descriptors,
                         latency: np.ndarray, reliability: np.ndarray,
                         epoch_times: np.ndarray) -> str:
    """Digest of everything that defines the campaign's replica set.
    Checkpoints stamp it (resuming a campaign against an edited vary
    block must fail loudly) and the ENSEMBLE record carries it."""
    h = hashlib.sha256()
    h.update(f"R={R}".encode())
    h.update(np.asarray(seeds, np.int64).tobytes())
    for d in descriptors:
        h.update(repr(sorted(d.items())).encode())
    for t in (latency, reliability, epoch_times):
        # hierarchical worlds are leaf tuples; the dense byte
        # sequence is unchanged (one leaf per table)
        for a in (t if isinstance(t, tuple) else (t,)):
            a = np.ascontiguousarray(a)
            h.update(str(a.shape).encode())
            h.update(a.tobytes())
    return h.hexdigest()[:12]


def build_worlds(sim, eopts) -> EnsembleWorlds:
    """Compile the ``ensemble:`` block against a built simulation into
    the stacked world arrays. `sim` is a BuiltSimulation (topology +
    base fault table already compiled); `eopts` the validated
    EnsembleOptions."""
    from shadow_tpu import faults as faultmod

    cfg = sim.cfg
    R = int(eopts.replicas)
    vary = eopts.vary
    seeds = [int(s) for s in vary.get("seed",
                                      [cfg.general.seed] * R)]
    scales = [float(x) for x in vary.get("latency_scale", [1.0] * R)]
    deltas = [float(x) for x in vary.get("packet_loss_delta",
                                         [0.0] * R)]
    names = [str(n) for n in vary.get("fault_schedule", ["base"] * R)]

    # compile each distinct named schedule once against the topology
    # (the same dense_adjacency + shortest-path pipeline the base
    # network.faults schedule went through at build time)
    tables: dict = {}

    def table_for(name: str):
        if name not in tables:
            if name == "base":
                tables[name] = sim.fault_table
            elif name == "none":
                tables[name] = None
            else:
                tables[name] = faultmod.compile_link_faults(
                    sim.topology, eopts.fault_schedules[name])
        return tables[name]

    if sim.topology.hier is not None:
        return _build_worlds_hier(sim, R, seeds, scales, deltas,
                                  names, table_for)

    base_lat = np.asarray(sim.topology.latency_ns, np.int64)
    base_rel = np.asarray(sim.topology.reliability, np.float32)
    per = []
    T_max = 1
    for r in range(R):
        tab = table_for(names[r])
        if tab is None:
            times = np.zeros(1, np.int64)
            lat = base_lat[None]
            rel = base_rel[None].astype(np.float64)
        else:
            times = np.asarray(tab.times, np.int64)
            lat = np.asarray(tab.latency_ns, np.int64)
            rel = np.asarray(tab.reliability,
                             np.float32).astype(np.float64)
        if scales[r] != 1.0:
            lat = np.maximum(1, np.rint(
                lat.astype(np.float64) * scales[r])).astype(np.int64)
        if deltas[r] != 0.0:
            rel = np.clip(rel - deltas[r], 0.0, 1.0)
        per.append((times, lat, rel.astype(np.float32)))
        T_max = max(T_max, len(times))

    lats, rels, eps = [], [], []
    for times, lat, rel in per:
        pad = T_max - len(times)
        if pad:
            # never-reached epochs repeating the last real matrices:
            # value-identical lookups for every reachable send time
            times = np.concatenate(
                [times, np.full(pad, FAR_EPOCH, np.int64)])
            lat = np.concatenate([lat, np.repeat(lat[-1:], pad, 0)])
            rel = np.concatenate([rel, np.repeat(rel[-1:], pad, 0)])
        eps.append(times)
        lats.append(lat)
        rels.append(rel)
    latency = np.stack(lats)               # [R, T, V, V]
    reliability = np.stack(rels)
    epoch_times = np.stack(eps)            # [R, T]
    if T_max == 1:
        # fault-free campaigns keep the plain [R, V, V] matrices so
        # each replica's program matches the pre-fault-layer engine
        # byte for byte (the same squeeze the standalone engine does)
        latency = latency[:, 0]
        reliability = reliability[:, 0]

    if (latency > np.iinfo(np.int32).max).any():
        bad = [r for r in range(R)
               if (latency[r] > np.iinfo(np.int32).max).any()]
        raise ValueError(
            f"ensemble: replica(s) {bad} have scaled path latencies "
            "above ~2.1 s — they do not fit the i32 device latency "
            "matrix (lower vary.latency_scale)")

    k1 = np.empty(R, np.uint32)
    k2 = np.empty(R, np.uint32)
    for r, s in enumerate(seeds):
        k1[r], k2[r] = seed_key_np(s)

    descriptors = [
        {"replica": r, "seed": seeds[r], "latency_scale": scales[r],
         "packet_loss_delta": deltas[r], "fault_schedule": names[r]}
        for r in range(R)]
    return EnsembleWorlds(
        R=R,
        latency=latency.astype(np.int32),
        reliability=reliability.astype(np.float32),
        epoch_times=epoch_times,
        seed_k1=k1, seed_k2=k2,
        seeds=np.asarray(seeds, np.int64),
        lookahead=int(latency.min()),
        descriptors=descriptors,
        campaign_fp=campaign_fingerprint(
            R, seeds, descriptors, latency, reliability, epoch_times),
    )


def _build_worlds_hier(sim, R, seeds, scales, deltas, names,
                       table_for) -> EnsembleWorlds:
    """Hierarchical twin of the build_worlds table stacking: each
    replica varies the FACTORED leaves, so the stacked world stays
    O(R * (T*C^2 + T*V)) instead of O(R*T*V^2).

    Exactness vs the dense stacking: latency_scale multiplies every
    positive latency factor (composition then distributes —
    bit-identical to scaling the dense matrix for integer scale
    factors, where rint is exact per factor); packet_loss_delta
    subtracts from the cluster (diagonal included — intra-cluster
    pairs compose through it) and self reliabilities, which equals
    the dense clip exactly when every access link is lossless, and
    is refused loudly otherwise."""
    ht = sim.topology.hier
    if any(d != 0.0 for d in deltas) and \
            not bool((np.asarray(ht.acc_rel) >= 1.0).all()):
        raise ValueError(
            "ensemble: vary.packet_loss_delta under the hierarchical "
            "representation requires lossless access links (the "
            "dense clip does not factor through lossy access terms) "
            "— use network.topology.representation: dense")

    def scale_int(x, s):
        if s == 1.0:
            return np.asarray(x, np.int64)
        x = np.asarray(x, np.int64)
        # zero factors are structural (hub access terms, the cluster
        # transit diagonal), never latencies — they must stay zero
        return np.where(
            x > 0,
            np.maximum(1, np.rint(x.astype(np.float64) * s))
            .astype(np.int64), np.int64(0))

    def delta_rel(x, d):
        x = np.asarray(x, np.float32)
        if d == 0.0:
            return x
        return np.clip(x.astype(np.float64) - d,
                       0.0, 1.0).astype(np.float32)

    def parts_for(tab):
        if tab is None:
            lat = tuple(np.asarray(p)[None] for p in ht.lat_parts())
            rel = tuple(np.asarray(p)[None] for p in ht.rel_parts())
            return np.zeros(1, np.int64), lat, rel
        return (np.asarray(tab.times, np.int64),
                tuple(np.asarray(p) for p in tab.lat_parts_stacked()),
                tuple(np.asarray(p) for p in tab.rel_parts_stacked()))

    per = []
    T_max = 1
    for r in range(R):
        times, lat, rel = parts_for(table_for(names[r]))
        cc, cl, acc, slf = lat
        ccr, _, accr, slfr = rel
        lat = (scale_int(cc, scales[r]), cl,
               scale_int(acc, scales[r]), scale_int(slf, scales[r]))
        rel = (delta_rel(ccr, deltas[r]), cl,
               np.asarray(accr, np.float32),
               delta_rel(slfr, deltas[r]))
        per.append((times, lat, rel))
        T_max = max(T_max, len(times))

    lats, rels, eps = [], [], []
    for times, lat, rel in per:
        pad = T_max - len(times)
        if pad:
            times = np.concatenate(
                [times, np.full(pad, FAR_EPOCH, np.int64)])
            lat = tuple(np.concatenate([p, np.repeat(p[-1:], pad, 0)])
                        for p in lat)
            rel = tuple(np.concatenate([p, np.repeat(p[-1:], pad, 0)])
                        for p in rel)
        eps.append(times)
        lats.append(lat)
        rels.append(rel)
    latency = tuple(np.stack([l[i] for l in lats]) for i in range(4))
    reliability = tuple(np.stack([x[i] for x in rels])
                        for i in range(4))
    epoch_times = np.stack(eps)
    if T_max == 1:
        latency = tuple(p[:, 0] for p in latency)
        reliability = tuple(p[:, 0] for p in reliability)

    def replica_epochs(r):
        parts = tuple(p[r] for p in latency)
        if parts[0].ndim == 3:
            return [tuple(p[e] for p in parts)
                    for e in range(parts[0].shape[0])]
        return [parts]

    bad = [r for r in range(R)
           if max(hierarchy.max_composed_latency(ep)
                  for ep in replica_epochs(r))
           > np.iinfo(np.int32).max]
    if bad:
        raise ValueError(
            f"ensemble: replica(s) {bad} have scaled path latencies "
            "above ~2.1 s — they do not fit the i32 device latency "
            "matrix (lower vary.latency_scale)")
    lookahead = min(hierarchy.min_latency_from_parts(ep)
                    for r in range(R) for ep in replica_epochs(r))

    k1 = np.empty(R, np.uint32)
    k2 = np.empty(R, np.uint32)
    for r, s in enumerate(seeds):
        k1[r], k2[r] = seed_key_np(s)

    descriptors = [
        {"replica": r, "seed": seeds[r], "latency_scale": scales[r],
         "packet_loss_delta": deltas[r], "fault_schedule": names[r]}
        for r in range(R)]
    latency = tuple(p.astype(np.int32) for p in latency)
    reliability = tuple(
        p.astype(np.int32) if i == 1 else p.astype(np.float32)
        for i, p in enumerate(reliability))
    return EnsembleWorlds(
        R=R,
        latency=latency,
        reliability=reliability,
        epoch_times=epoch_times,
        seed_k1=k1, seed_k2=k2,
        seeds=np.asarray(seeds, np.int64),
        lookahead=lookahead,
        descriptors=descriptors,
        campaign_fp=campaign_fingerprint(
            R, seeds, descriptors, latency, reliability, epoch_times),
    )
