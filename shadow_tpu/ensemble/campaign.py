"""EnsembleRunner: R-replica simulation campaigns in one program.

The ensemble twin of device/runner.py's DeviceRunner: maps the config
to its vectorized device app, builds ONE engine whose program carries
a leading replica axis (vmapped outside the mesh shard axis), plans
capacities once from the worst-case replica, advances all replicas in
heartbeat/dispatch segments with per-replica heartbeat lines, and
emits an ``artifacts/ENSEMBLE_*.json`` campaign record with
per-replica checksums plus aggregate statistics.

Why one program: a seed/loss/fault sweep as N serial processes pays
the XLA compile and every dispatch N times; as one vmapped program it
pays them once, and the replica axis rides the vector units the small
per-host shapes leave idle. Replica *i* stays bit-identical to a
standalone run with replica *i*'s parameters (spec.py's contract), so
campaign aggregates are statistics over *real* runs, not
approximations.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from shadow_tpu import simtime
from shadow_tpu._jax import jax
from shadow_tpu.core.manager import SimStats
from shadow_tpu.device import capacity
from shadow_tpu.device.runner import DeviceRunner, NoDeviceTwin
from shadow_tpu.ensemble.spec import EnsembleWorlds, build_worlds
from shadow_tpu.utils.artifacts import atomic_write_json
from shadow_tpu.utils.slog import get_logger

log = get_logger("ensemble")

RECORD_FORMAT = 1
# per-replica per-host checksum lists stay inline below this host
# count; larger campaigns keep the sha256 digest only
CHK_INLINE_HOSTS = 64

_AGG_OPS = {
    "mean": np.mean,
    "min": np.min,
    "max": np.max,
    "p5": lambda v: np.percentile(v, 5),
    "p95": lambda v: np.percentile(v, 95),
}


def aggregate(values, which) -> dict:
    """Aggregate one per-replica metric vector with the configured
    statistics (mean/p5/p95/min/max)."""
    v = np.asarray(values, np.float64)
    return {k: float(_AGG_OPS[k](v)) for k in which}


class EnsembleRunner:
    """Runs the ``ensemble:`` campaign of a built simulation. Raises
    NoDeviceTwin when the config's apps have no fully-vectorized
    device twin — there is no hybrid fallback for campaigns (CPU host
    emulation cannot vmap), so the Controller surfaces that loudly
    instead of silently running one replica."""

    def __init__(self, sim, trace: Optional[list] = None, mesh=None):
        eopts = sim.cfg.ensemble
        if eopts is None:
            raise ValueError("EnsembleRunner needs an ensemble: "
                             "config block")
        if trace is not None:
            raise ValueError(
                "ensemble campaigns do not record python event "
                "traces; use the per-replica checksums in the "
                "ENSEMBLE record")
        if getattr(sim, "host_faults", None):
            raise ValueError(
                "ensemble: host_crash/host_restart faults are "
                "manager-side events — the campaign engine cannot "
                "run them (vary link faults via "
                "ensemble.fault_schedules instead)")
        # reuse DeviceRunner wholesale for the single-replica twin
        # mapping, knob plumbing, and engine construction — the
        # campaign engine is the same engine with ensemble worlds
        # (defer_engine: the standalone engine it would build is dead
        # weight here)
        self._base = DeviceRunner(sim, trace=None, mesh=mesh,
                                  defer_engine=True)
        self.app = self._base.app
        # the campaign engine consults the same AOT compile cache the
        # base runner resolved (one instance, one report)
        self.aot_cache = self._base.aot_cache
        self.sim = sim
        self.worlds: EnsembleWorlds = build_worlds(sim, eopts)
        if hasattr(self.app, "seed_pair") and \
                len(set(int(s) for s in self.worlds.seeds)) > 1:
            # TorDevice bakes its route seed into the program as a
            # compile constant — a seed sweep would leave every
            # replica's routes identical and silently break the
            # replica-i == standalone-i contract
            raise ValueError(
                "ensemble: vary.seed is not supported for "
                f"{type(self.app).__name__} (it derives app-internal "
                "RNG from the seed at build time); sweep "
                "latency/loss/faults instead")
        self.engine = self._build_engine()
        self.replans = 0
        self.retries = 0
        self.reshards = 0
        self.degrades = 0
        self._planned = False
        # preflight admission verdict (capacity.admission_verdict),
        # set per run(); the shared advance loop reads its overrides
        # and the ENSEMBLE/bench records stamp it
        self.admission = None
        # nonzero = the OOM ladder may degrade this campaign to
        # sequential replica batches of this size (set per run();
        # zero while batching is impossible or already engaged)
        self._replica_batchable = 0
        # replica-index offset of the batch currently running, so
        # batched heartbeat lines keep campaign-global replica labels
        self._replica_offset = 0
        # chaos injection + shrink failover ride the base runner's
        # plumbing (one injector, one mesh owner); the shared advance
        # loop reads runner.chaos
        self.chaos = self._base.chaos
        self.occ_record: Optional[dict] = None
        self.record: Optional[dict] = None
        self.final_state: Optional[dict] = None
        # supervision plumbing (device/supervise.py), set per run();
        # campaign checkpoints carry the campaign stamp so standalone
        # runs refuse them
        self.checkpointer = None
        self.guard = None
        # wall-clock heartbeat staleness monitor (supervise.
        # HeartbeatMonitor), created per run() when
        # experimental.heartbeat_stale_after is set; the campaign
        # server's watchdog polls it cross-thread
        self.hb_monitor = None
        self._ck_extra_meta = {"campaign": self.worlds.campaign_fp,
                               "replicas": int(self.worlds.R)}
        # flight recorder (shadow_tpu/obs): attached by the
        # Controller; the shared advance loop records the spans
        self.tracer = None
        # ensemble-heartbeat rate mark: (wall, per-replica sent) at
        # the last heartbeat, for the pkts/s-since-last column
        self._hb_mark = None

    # ------------------------------------------------------------------
    @property
    def lookahead(self) -> int:
        """The campaign's shared lookahead window: the min over every
        replica's table (each replica's standalone floor is >= it, so
        it is conservative for all). determinism_gate --ensemble pins
        standalone comparison runs to this value via
        experimental.runahead."""
        xp = self.sim.cfg.experimental
        if xp.runahead is not None:
            return max(1, xp.runahead)
        return max(1, min(self.worlds.lookahead, self.sim.lookahead))

    def _build_engine(self):
        """The DeviceRunner's engine builder with the ensemble worlds
        attached: the engine swaps in replica 0's tables as its base
        world and additionally compiles the vmapped campaign program.
        One builder serves both runners — knob plumbing, outbox
        floors, and strategy tristates cannot drift apart."""
        return self._base._build_engine(
            ensemble=self.worlds,
            lookahead=self.lookahead,
            seed=int(self.worlds.seeds[0]))

    @property
    def _capacity_overrides(self) -> dict:
        return self._base._capacity_overrides

    @_capacity_overrides.setter
    def _capacity_overrides(self, value: dict) -> None:
        self._base._capacity_overrides = value

    def _shrink_to(self, alive, host_state: dict,
                   ensemble: bool = True):
        """The shrink failover's campaign path: mesh + capacity
        re-plan route through the base runner (the one owner of
        both), then the CAMPAIGN engine — vmapped replica axis
        outside the new, smaller mesh axis — rebuilds and the
        [R, ...] snapshot re-shards leaf-for-leaf. The replica axis
        survives intact: shrink is the one failover campaigns have
        (hybrid cannot vmap replicas). Transactional like the base
        runner's: a failed reshard rolls everything back so the
        escalation still sees the old-geometry engine."""
        from jax.sharding import Mesh

        from shadow_tpu.device import supervise
        from shadow_tpu.device.engine import AXIS
        from shadow_tpu.device.runner import DeviceRunner

        base = self._base
        rollback = (base._mesh, self.engine,
                    dict(base._capacity_overrides),
                    base._exchange_choice, base.strategy_plan)
        try:
            base._mesh = Mesh(np.array(list(alive)), (AXIS,))
            base._replan_for_shrink(
                len(alive), record=self.occ_record,
                per_iter=self.engine.effective["M_out"])
            self.engine = self._build_engine()
            supervise.prefetch_programs(self, ensemble=True)
            return DeviceRunner._place_resharded(self, host_state,
                                                 ensemble=True)
        except Exception:
            (base._mesh, self.engine, base._capacity_overrides,
             base._exchange_choice, base.strategy_plan) = rollback
            raise

    # ------------------------------------------------------------------
    def _worst_case_view(self, states) -> dict:
        """Reduce the [R, ...] occupancy/overflow leaves to the
        standalone shapes capacity.measure expects: elementwise MAX
        over the replica axis for high-water marks (the worst-case
        replica sizes the shared capacities), SUM for the loud
        overflow counters (any replica's loss fails the campaign)."""
        view = {}
        for k in ("occ_heap", "occ_ob", "occ_in", "occ_x",
                  "occ_trips", "occ_phases"):
            view[k] = np.asarray(jax.device_get(states[k])).max(0)
        for k in ("overflow", "x_overflow"):
            view[k] = np.asarray(jax.device_get(states[k])).sum(0)
        return view

    def _plan_capacities(self, stop: int,
                         load_path: Optional[str] = None) -> None:
        """capacity_plan on the campaign: the warm-up slice runs the
        ENSEMBLE program, so the plan sizes every capacity from the
        worst-case replica's measured occupancy — one replica with a
        hot hub cannot overflow the others' tight plan."""
        xp = self.sim.cfg.experimental
        mode = xp.capacity_plan
        if load_path is None:
            load_path = xp.checkpoint_load
        if load_path:
            # same contract as DeviceRunner._plan_capacities: the
            # fingerprint pins the SAVING engine's capacities, so a
            # resume adopts them instead of re-planning (a fresh
            # warm-up could plan smaller sizes and reject a valid
            # campaign checkpoint — and would pay the warm-up compile
            # on every resume for nothing). ONE shared adopt path.
            self._base._adopt_checkpoint_caps(load_path)
            self.engine = self._build_engine()
            self._planned = True
            log.warning("capacity_plan: %s skipped — checkpoint_load "
                        "resumes the campaign with the saved "
                        "engine's capacities %s", mode,
                        self._capacity_overrides)
            return
        static_knobs = {k: getattr(self.engine.config, k)
                        for k in capacity.CAPACITY_KNOBS}
        if mode == "auto":
            warm = xp.capacity_warmup or max(1, stop // 8)
            warm = min(warm, stop)
            seg = xp.dispatch_segment
            states = self.engine.init_ensemble_state(self.sim.starts)
            for attempt in range(capacity.MAX_REPLANS + 1):
                t = 0
                dims = ()
                while t < warm:
                    nxt = min(warm, t + seg) if seg else warm
                    states, _ = self.engine.run_ensemble(
                        states, stop=nxt, final_stop=stop)
                    t = nxt
                    dims = capacity.overflow_dims(states)
                    if dims:
                        break
                if not dims:
                    break
                if attempt == capacity.MAX_REPLANS:
                    raise RuntimeError(
                        f"ensemble capacity warm-up still overflows "
                        f"after {capacity.MAX_REPLANS} doublings on "
                        f"{dims}")
                self._capacity_overrides = capacity.widen(
                    self._capacity_overrides, dims,
                    self.engine.effective)
                log.warning("ensemble capacity warm-up overflowed on "
                            "%s; retrying with %s", dims,
                            self._capacity_overrides)
                self.engine = self._build_engine()
                states = self.engine.init_ensemble_state(
                    self.sim.starts)
            record = capacity.measure(
                self.engine, self._worst_case_view(states),
                source=f"ensemble-warmup:{warm}ns")
        else:
            record = capacity.load_record(mode)
            want = {"app": type(self.app).__name__,
                    "app_fp": capacity.app_fingerprint(self.app),
                    "n_hosts": len(self.sim.hosts)}
            got = {k: record["workload"].get(k) for k in want}
            if got != want:
                raise ValueError(
                    f"occupancy record {mode} was measured on {got}; "
                    f"this campaign is {want} — re-measure with "
                    "capacity_plan: auto")
        # the worst-case view reduced occ_x over replicas, so the
        # auto choice (and the per-phase caps) cover every replica
        exchange = self._base._resolve_exchange(record,
                                                engine=self.engine)
        planned = capacity.plan(
            record,
            per_iter=self.engine.effective["M_out"],
            floor_iters=4 if self._base._burst > 1 else 8,
            n_shards=self.engine.n_shards,
            headroom=self._base._headroom(),
            exchange=exchange)
        record["planned"] = planned
        record["static"] = static_knobs
        self.occ_record = record
        self._capacity_overrides = dict(planned)
        self.engine = self._build_engine()
        self._planned = True
        # overlap the planned program's AOT entry read with the
        # ensemble init/load work that follows
        from shadow_tpu.device import supervise
        supervise.prefetch_programs(self, ensemble=True)
        log.info("ensemble capacity plan (%s, exchange %s): %s  "
                 "[measured %s]", mode, exchange, planned,
                 record["measured"])

    # ------------------------------------------------------------------
    def _emit_heartbeats(self, now: int, states) -> None:
        """Per-replica heartbeat lines at a segment boundary: replica
        totals from the device counters (the [R, H] arrays are a few
        KB — never the heaps). Each line carries the wall-clock
        pkts/s since the previous heartbeat and the campaign's
        cumulative retry/replan counts, so a stalled or thrashing
        replica is visible from the log stream alone."""
        from shadow_tpu.device.supervise import heartbeat_rates

        # getattr: obs tests drive this method on a bare stub runner
        mon = getattr(self, "hb_monitor", None)
        if mon is not None:
            mon.beat()
        H = len(self.sim.hosts)
        n_exec = np.asarray(jax.device_get(states["n_exec"]))[:, :H]
        n_sent = np.asarray(jax.device_get(states["n_sent"]))[:, :H]
        n_drop = np.asarray(jax.device_get(states["n_drop"]))[:, :H]
        n_deliv = np.asarray(jax.device_get(states["n_deliv"]))[:, :H]
        self._hb_mark, rates = heartbeat_rates(self._hb_mark,
                                               n_sent.sum(1))
        # live device memory, when the backend exposes allocator
        # stats (TPU/GPU); "n/a" on CPU or before the engine exists —
        # the operator can tell an approaching OOM from the log
        # stream alone
        eng = getattr(self, "engine", None)
        mem = eng.device_memory_stats() if eng is not None else None
        mem_s = (f"{capacity.fmt_bytes(mem[0])}/"
                 f"{capacity.fmt_bytes(mem[1])}"
                 if mem is not None else "n/a")
        for r in range(self.worlds.R):
            log.info("[ensemble-heartbeat] t=%s replica=%d events=%d "
                     "sent=%d dropped=%d delivered=%d pkts/s=%s "
                     "retries=%d replans=%d mem=%s",
                     simtime.format_time(now),
                     r + getattr(self, "_replica_offset", 0),
                     int(n_exec[r].sum()), int(n_sent[r].sum()),
                     int(n_drop[r].sum()), int(n_deliv[r].sum()),
                     rates[r], self.retries, self.replans, mem_s)

    # ------------------------------------------------------------------
    def record_path(self) -> str:
        """Canonical campaign record path (ensemble.record_path
        overrides; experimental.artifacts_dir namespaces the
        directory — the campaign server's per-tenant seam;
        SHADOW_TPU_OCC_DIR redirects the default artifacts dir, the
        same env tests already use to keep runs out of the repo)."""
        eopts = self.sim.cfg.ensemble
        if eopts.record_path:
            return eopts.record_path
        directory = (
            getattr(self.sim.cfg.experimental, "artifacts_dir", "")
            or os.environ.get("SHADOW_TPU_OCC_DIR", "artifacts"))
        return os.path.join(
            directory,
            f"ENSEMBLE_{type(self.app).__name__}"
            f"_{len(self.sim.hosts)}_{self.worlds.campaign_fp}.json")

    def _build_record(self, final: dict, rounds_r, wall: float,
                      ok: bool) -> dict:
        import hashlib

        H = len(self.sim.hosts)
        w = self.worlds
        eopts = self.sim.cfg.ensemble
        metrics = {
            "events_executed": final["n_exec"][:, :H].sum(1),
            "packets_sent": final["n_sent"][:, :H].sum(1),
            "packets_dropped": final["n_drop"][:, :H].sum(1),
            "packets_delivered": final["n_deliv"][:, :H].sum(1),
            "rounds": np.asarray(rounds_r),
        }
        replicas = []
        for r in range(w.R):
            chk = np.ascontiguousarray(final["chk"][r, :H])
            entry = dict(w.descriptors[r])
            entry.update({
                "events_executed": int(metrics["events_executed"][r]),
                "packets_sent": int(metrics["packets_sent"][r]),
                "packets_dropped": int(metrics["packets_dropped"][r]),
                "packets_delivered": int(
                    metrics["packets_delivered"][r]),
                "host_checksums_sha256": hashlib.sha256(
                    chk.tobytes()).hexdigest()[:16],
            })
            if H <= CHK_INLINE_HOSTS:
                entry["host_checksums"] = [int(c) for c in chk]
            replicas.append(entry)
        return {
            "format": RECORD_FORMAT,
            "campaign": w.campaign_fp,
            "workload": {
                "app": type(self.app).__name__,
                "n_hosts": H,
                "stop_time": int(self.sim.cfg.general.stop_time),
                "replicas": w.R,
                "lookahead": self.lookahead,
            },
            "vary": w.descriptors,
            "replicas": replicas,
            "aggregates": {
                name: aggregate(vals, eopts.aggregate)
                for name, vals in metrics.items()},
            "wall_s": round(wall, 3),
            "replans": self.replans,
            "ok": bool(ok),
        }

    # ------------------------------------------------------------------
    def _run_batched(self, t_start: int, pause: int, stop: int,
                     batch: int, tracer, resume=None):
        """Sequential replica batches: vmap over <= ``batch`` replicas
        at a time, then merge the per-batch host-side finals over the
        replica axis. Bit-identical to the full-R vmap — each
        replica's trace is a pure function of its own world row
        (spec.py's contract), and every batch keeps the FULL
        campaign's lookahead, so batch boundaries cannot move round
        boundaries. Engaged by ``ensemble.replica_batch``, a
        preflight admission override, or the OOM ladder's
        :class:`supervise.DegradeToReplicaBatch` rung. Returns
        ``(merged_final, combined AdvanceResult, per-replica
        rounds)``; the merged final is host-side (the point is never
        holding all R replicas of device state at once), which the
        downstream record/stats path consumes unchanged.

        Supervision: with ``checkpoint_every`` set each batch writes
        its OWN rotation series (``<save>.b<k>.t<ns>``, stamped with
        the batch's replica window) — every batch restarts sim time
        at 0, so a shared base would collide and cross-prune. A
        preemption drain saves the running batch's entry and stops
        the loop; the completed batches' finals are DISCARDED, and
        ``merged_final`` comes back None. ``resume=(path,
        replica_lo)`` replays batches before the stamped one fresh
        from t=0 (pure functions — bit-identical), loads the stamped
        batch from its entry, and runs the rest fresh, so the
        resumed campaign's record equals the uninterrupted one."""
        from shadow_tpu.device import checkpoint, supervise
        from shadow_tpu.ensemble import spec

        xp = self.sim.cfg.experimental
        w_full = self.worlds
        R = int(w_full.R)
        batch = max(1, min(int(batch), R))
        n_batches = -(-R // batch)
        log.warning(
            "replica batching: running %d replica(s) as %d "
            "sequential batch(es) of <= %d (one vmapped program per "
            "batch, finals merged — bit-identical to the full vmap)",
            R, n_batches, batch)
        # already batched: the ladder's replica-batch rung must not
        # re-trigger (an OOM inside a batch walks the next rung)
        self._replica_batchable = 0
        heaps = ("ht", "hk", "hm", "hv", "hw")
        b_resume = int(resume[1]) // batch if resume is not None else -1
        engine_full, finals, rounds_parts = self.engine, [], []
        ck_full = self.checkpointer
        combined = supervise.AdvanceResult()
        try:
            for b in range(n_batches):
                lo, hi = b * batch, min(R, (b + 1) * batch)
                part = spec.slice_worlds(w_full, lo, hi)
                self.worlds = part
                self._replica_offset = lo
                # per-replica heartbeat rate vectors change length
                # across batches — a stale mark would mis-zip
                self._hb_mark = None
                if xp.checkpoint_every:
                    self.checkpointer = supervise.Checkpointer(
                        f"{xp.checkpoint_save}.b{b}",
                        xp.checkpoint_every, xp.checkpoint_keep,
                        final_stop=stop,
                        extra_meta={**self._ck_extra_meta,
                                    "replica_lo": lo,
                                    "replica_hi": hi,
                                    "replica_batch": batch},
                        audit_enabled=xp.state_audit)
                with tracer.span("replica_batch", "host",
                                 sim_t0=t_start, lo=lo, hi=hi,
                                 batch_index=b):
                    self.engine = self._build_engine()
                    supervise.prefetch_programs(self, ensemble=True)
                    if b == b_resume:
                        states, t0 = checkpoint.load_state(
                            self.engine, self.sim.starts, resume[0],
                            final_stop=stop,
                            template=self.engine.init_ensemble_state(
                                self.sim.starts))
                        log.info("resumed replica batch %d "
                                 "(replicas [%d, %d)) from %s at "
                                 "t=%d ns", b, lo, hi, resume[0], t0)
                    else:
                        states = self.engine.init_ensemble_state(
                            self.sim.starts)
                        t0 = t_start
                    states, adv = supervise.advance(
                        self, states, t0, pause, stop,
                        ensemble=True)
                    if not adv.preempted:
                        finals.append(jax.device_get(
                            {k: v for k, v in states.items()
                             if k not in heaps}))
                combined.t_end = adv.t_end
                combined.retries += adv.retries
                combined.reshards += adv.reshards
                combined.degrades += adv.degrades
                combined.budget_hit |= adv.budget_hit
                combined.overflowed |= adv.overflowed
                combined.pipeline = adv.pipeline
                if adv.preempted:
                    # the drain already saved THIS batch's rotation
                    # entry; stop the loop — later batches never
                    # started, and the completed ones replay
                    # bit-identically on resume (pure functions of
                    # their world slices)
                    combined.preempted = True
                    combined.resume_path = adv.resume_path
                    break
                rounds_parts.append(np.broadcast_to(
                    np.asarray(adv.rounds), (hi - lo,)).copy())
        finally:
            self.worlds = w_full
            self._replica_offset = 0
            self.engine = engine_full
            self.checkpointer = ck_full
        pl = dict(combined.pipeline or {})
        pl["replica_batches"] = int(n_batches)
        pl["replica_batch"] = int(batch)
        combined.pipeline = pl
        if isinstance(self.admission, dict):
            self.admission["replica_batch"] = int(batch)
        if combined.preempted:
            rounds_r = (np.concatenate(rounds_parts)
                        if rounds_parts else np.zeros(0, np.int64))
            combined.rounds = np.int64(
                rounds_r.max() if rounds_r.size else 0)
            return None, combined, rounds_r
        merged = {k: np.concatenate([f[k] for f in finals], axis=0)
                  for k in finals[0]}
        rounds_r = np.concatenate(rounds_parts)
        combined.rounds = np.int64(rounds_r.max())
        return merged, combined, rounds_r

    # ------------------------------------------------------------------
    def run(self, stop: int) -> SimStats:
        from shadow_tpu.device import checkpoint, supervise

        from shadow_tpu.obs import trace as obstrace

        xp = self.sim.cfg.experimental
        tracer = self.tracer or obstrace.current()
        self.replans = 0
        self.retries = 0
        self.reshards = 0
        self.degrades = 0
        self._hb_mark = None
        self._replica_offset = 0
        w = self.worlds
        if xp.checkpoint_save:
            checkpoint.probe_writable(xp.checkpoint_save)
        eopts = self.sim.cfg.ensemble
        knob_batch = int(getattr(eopts, "replica_batch", 0) or 0)
        load_path = ""
        resume_batch = None
        if xp.checkpoint_load:
            load_path = supervise.resolve_checkpoint(
                xp.checkpoint_load)
            meta = checkpoint.peek_meta(load_path)
            ens_meta = meta.get("ensemble") or {}
            camp = ens_meta.get("campaign")
            if camp is None:
                raise ValueError(
                    f"checkpoint {load_path} was saved by a "
                    "standalone run — an ensemble campaign cannot "
                    "resume it")
            if camp != w.campaign_fp:
                raise ValueError(
                    f"checkpoint {load_path} belongs to "
                    f"campaign {camp}; this config builds "
                    f"{w.campaign_fp} — the vary block or schedules "
                    "changed, so the saved replicas would diverge")
            saved_lo = ens_meta.get("replica_lo")
            if saved_lo is not None:
                # a replica-batch rotation entry: it stamps ONE
                # batch's sliced state, so only a campaign batched
                # the same way can place it
                saved_batch = int(ens_meta.get("replica_batch") or 0)
                if knob_batch != saved_batch:
                    have = (f"uses replica_batch: {knob_batch}"
                            if knob_batch else
                            "expects the full-R stacked state")
                    raise ValueError(
                        f"checkpoint {load_path} was saved by "
                        f"replica batch [{saved_lo}, "
                        f"{ens_meta.get('replica_hi')}) of a "
                        f"replica_batch={saved_batch} campaign — "
                        f"set ensemble.replica_batch: {saved_batch} "
                        f"to resume it (this config {have})")
                resume_batch = (load_path, int(saved_lo))
            elif knob_batch:
                raise ValueError(
                    f"checkpoint {load_path} stamps the full-R "
                    "stacked state — a replica_batch campaign "
                    "cannot resume it (drop ensemble.replica_batch "
                    "or resume without the checkpoint)")
            checkpoint.prevalidate_resume(
                load_path, stop,
                save_path=xp.checkpoint_save,
                save_time=xp.checkpoint_save_time)
            # a post-shrink campaign checkpoint stamps the shrunken
            # geometry: the base runner adopts the mesh (one adopt
            # path), then the CAMPAIGN engine rebuilds on it
            if self._base._adopt_checkpoint_geometry(load_path):
                self.engine = self._build_engine()
        # preflight admission (capacity.py): the campaign footprint —
        # per-replica state x R, exchange scratch, pipeline copies —
        # against the per-device budget, BEFORE any compile (the
        # first compile happens lazily at the first dispatch, which
        # the capacity warm-up below would trigger). strict refuses
        # over-budget here; auto may statically degrade the pipeline
        # depth or pre-split the sweep into replica batches.
        batch = knob_batch
        ck_on = bool(xp.checkpoint_save or xp.checkpoint_load
                     or xp.checkpoint_every)
        can_batch = w.R > 1 and not batch and not ck_on
        self.admission = capacity.admission_verdict(
            self.engine, xp,
            pipeline_depth=getattr(xp, "pipeline_depth", 0),
            batchable=can_batch)
        adm_ov = self.admission.get("overrides") or {}
        if not batch and adm_ov.get("replica_batch"):
            batch = int(adm_ov["replica_batch"])
        # the OOM ladder may still degrade an unbatched campaign at
        # runtime (supervise.DegradeToReplicaBatch); a checkpointed
        # unbatched campaign stays unbatched — its checkpoints stamp
        # the full-R stacked state, which a mid-run batch switch
        # would orphan (explicit ensemble.replica_batch opts into
        # per-batch rotation series instead)
        self._replica_batchable = (max(1, w.R // 2)
                                   if can_batch and not batch else 0)
        if xp.capacity_plan != "static" and not self._planned:
            with tracer.span("capacity.plan", "plan",
                             mode=xp.capacity_plan, ensemble=True):
                self._plan_capacities(stop, load_path=load_path)
        if batch:
            # the whole point of batching is never materializing the
            # full-R state — _run_batched inits (or loads) each
            # batch's slice itself
            states = None
            t_start = 0
        elif load_path:
            with tracer.span("checkpoint.load", "checkpoint",
                             path=load_path):
                states, t_start = checkpoint.load_state(
                    self.engine, self.sim.starts, load_path,
                    final_stop=stop,
                    template=self.engine.init_ensemble_state(
                        self.sim.starts))
            log.info("resumed campaign checkpoint %s at t=%d ns",
                     load_path, t_start)
        else:
            states = self.engine.init_ensemble_state(self.sim.starts)
            t_start = 0
        pause = stop
        if xp.checkpoint_save:
            if xp.checkpoint_save_time:
                pause = min(stop, xp.checkpoint_save_time)
            if pause <= t_start:
                raise ValueError(
                    f"checkpoint_save_time {pause} ns is not after "
                    f"the campaign's start time {t_start} ns")
        self.checkpointer = None
        if xp.checkpoint_every and not batch:
            # batched campaigns rotate per-batch checkpointers inside
            # _run_batched (each batch restarts sim time at 0, so one
            # shared base would collide and cross-prune)
            self.checkpointer = supervise.Checkpointer(
                xp.checkpoint_save, xp.checkpoint_every,
                xp.checkpoint_keep, final_stop=stop,
                extra_meta=self._ck_extra_meta,
                audit_enabled=xp.state_audit)
        self.guard = supervise.make_guard(self.sim.cfg)
        self.hb_monitor = (
            supervise.HeartbeatMonitor(xp.heartbeat_stale_after)
            if getattr(xp, "heartbeat_stale_after", 0) else None)
        import contextlib
        t0 = time.perf_counter()
        rounds_r = None
        with (self.guard if self.guard is not None
              else contextlib.nullcontext()):
            if batch:
                states, adv, rounds_r = self._run_batched(
                    t_start, pause, stop, batch, tracer,
                    resume=resume_batch)
            else:
                try:
                    states, adv = supervise.advance(
                        self, states, t_start, pause, stop,
                        ensemble=True)
                except supervise.DegradeToReplicaBatch as dg:
                    # the ladder's replica-batch rung: the full-R
                    # vmap exhausted device memory deterministically
                    # — re-run the sweep from t=0 in sequential
                    # batches (bit-identical; no checkpointer exists
                    # on this path, so nothing was saved to rewind)
                    batch = dg.batch
                    states, adv, rounds_r = self._run_batched(
                        t_start, pause, stop, batch, tracer)
                    adv.degrades += 1   # the rung that engaged it
        if states is None:
            # batched campaign preempted mid-batch: there is no
            # merged final to record (and the completed batches'
            # finals were discarded — the resume replays them
            # bit-identically); surface the resumable outcome the
            # way a standalone preempted run does
            self.retries = adv.retries
            self.degrades = adv.degrades
            stats = SimStats()
            stats.end_time = adv.t_end
            stats.rounds = int(np.asarray(adv.rounds).max())
            stats.strategy_plan = self._base.strategy_plan
            if self.aot_cache is not None:
                self.aot_cache.publish(stats)
            stats.replans = self.replans
            stats.retries = adv.retries
            stats.reshards = adv.reshards
            stats.degrades = adv.degrades
            stats.admission = self.admission
            stats.preempted = True
            stats.resume_path = adv.resume_path
            stats.pipeline = adv.pipeline or None
            if self.hb_monitor is not None:
                stats.stale_heartbeats = self.hb_monitor.stale_events
            log.info("ensemble record not written (batched campaign "
                     "preempted; resume from %s)", adv.resume_path)
            return stats
        if rounds_r is None:
            rounds_r = np.broadcast_to(np.asarray(adv.rounds),
                                       (self.worlds.R,))
        t_end = adv.t_end
        budget_hit, overflowed = adv.budget_hit, adv.overflowed
        self.retries = adv.retries
        rounds = int(np.asarray(rounds_r).max())
        if xp.checkpoint_save and batch:
            # the merged final is host-side and heap-less — there is
            # no full-R stacked device state to save; the per-batch
            # rotation entries written during the run are the
            # campaign's checkpoints (schema.py requires
            # checkpoint_every alongside replica_batch+save for
            # exactly this reason)
            log.info("end-of-run campaign checkpoint skipped "
                     "(replica_batch: the rotation entries "
                     "%s.b<k>.t<ns> are the resumable artifacts)",
                     xp.checkpoint_save)
        elif xp.checkpoint_save:
            if budget_hit or overflowed:
                log.error("%s before the checkpoint boundary — NOT "
                          "saving %s",
                          "max_rounds exhausted" if budget_hit
                          else "capacity overflow (events lost)",
                          xp.checkpoint_save)
            elif adv.preempted:
                # the drain already saved the resume checkpoint
                pass
            else:
                with tracer.span("checkpoint.save", "checkpoint",
                                 sim_t0=t_end,
                                 path=xp.checkpoint_save):
                    checkpoint.save_state(
                        self.engine, states, xp.checkpoint_save,
                        t_end, final_stop=stop,
                        extra_meta=self._ck_extra_meta,
                        audit_meta=({"enabled": True, "violations": 0}
                                    if xp.state_audit else None))
                log.info("campaign checkpoint saved at t=%d ns -> %s",
                         t_end, xp.checkpoint_save)
        stat_keys = [k for k in states
                     if k not in ("ht", "hk", "hm", "hv", "hw")]
        with tracer.span("state.fetch", "host", sim_t0=t_end):
            final = {k: np.asarray(v) for k, v in jax.device_get(
                {k: states[k] for k in stat_keys}).items()}
        wall = time.perf_counter() - t0
        self.final_state = final
        H = len(self.sim.hosts)

        # `final` already holds every counter host-side — the
        # worst-case reduction reuses it rather than re-fetching the
        # same [R, ...] arrays from device
        occ = capacity.measure(self.engine,
                               self._worst_case_view(final),
                               source="ensemble-run")
        occ["workload"]["replicas"] = int(w.R)
        if self.occ_record is not None:
            self.occ_record["final_measured"] = occ["measured"]
            self.occ_record["effective"] = occ["effective"]
            self.occ_record["replans"] = self.replans
            self.occ_record["applied"] = dict(
                self._capacity_overrides)
        else:
            self.occ_record = occ

        overflow = int(final["overflow"][:, :H].sum())
        x_overflow = int(final["x_overflow"][:, :H].sum())
        ok = overflow == 0 and x_overflow == 0 and not budget_hit
        self.degrades = adv.degrades
        self.record = self._build_record(final, rounds_r, wall, ok)
        if self.admission is not None:
            # the preflight verdict (and any replica-batch split)
            # rides the campaign record — bench.py stamps it into
            # the ensemble BENCH records from here
            self.record["admission"] = self.admission
        if batch:
            self.record["replica_batch"] = int(batch)
        if adv.degrades:
            self.record["degrades"] = int(adv.degrades)
        if adv.preempted:
            # a preempted campaign's counters cover only the executed
            # prefix — the resumed run writes the real record
            log.info("ensemble record not written (campaign "
                     "preempted; resume from %s)", adv.resume_path)
        else:
            path = self.record_path()
            try:
                atomic_write_json(self.record, path)
                log.info("ensemble record -> %s", path)
            except OSError as e:
                log.warning("could not write ensemble record %s: %s",
                            path, e)

        n_exec_total = int(final["n_exec"][:, :H].sum())
        log.info("ensemble perf: %d replicas, %d rounds in %.2fs "
                 "wall (%.0f events/s aggregate)", w.R, rounds, wall,
                 n_exec_total / wall if wall > 0 else 0.0)

        stats = SimStats()
        stats.end_time = t_end
        stats.rounds = int(rounds)
        stats.occupancy = self.occ_record
        # the campaign shares the base runner's plan adoption (the
        # one mutation site, before any engine was built)
        stats.strategy_plan = self._base.strategy_plan
        if self.aot_cache is not None:
            self.aot_cache.publish(stats)
        stats.replans = self.replans
        stats.retries = self.retries
        stats.reshards = adv.reshards
        stats.degrades = adv.degrades
        stats.admission = self.admission
        mem = self.engine.device_memory_stats()
        if mem is not None:
            stats.mem_bytes_in_use, stats.mem_budget = mem
        stats.preempted = adv.preempted
        stats.resume_path = adv.resume_path
        if self.hb_monitor is not None:
            stats.stale_heartbeats = self.hb_monitor.stale_events
        # campaigns ride the same segment pipeline as standalone runs
        # (supervise.advance is shared) — report its telemetry too
        stats.pipeline = adv.pipeline or None
        stats.ensemble = self.record
        # campaign totals (all replicas) — the aggregate view; the
        # per-replica breakdown lives in the record
        stats.events_executed = n_exec_total
        stats.packets_sent = int(final["n_sent"][:, :H].sum())
        stats.packets_dropped = int(final["n_drop"][:, :H].sum())
        stats.packets_delivered = int(final["n_deliv"][:, :H].sum())
        if overflow:
            stats.ok = False
            log.error("ensemble engine overflow: %d events lost — "
                      "raise experimental.event_capacity/"
                      "outbox_capacity, or set capacity_plan: auto",
                      overflow)
        if x_overflow:
            stats.ok = False
            log.error("ensemble exchange overflow: %d rows exceeded "
                      "the per-shard-pair capacity — raise "
                      "experimental.exchange_capacity or use "
                      "capacity_plan: auto", x_overflow)

        # replica 0's per-host results reflect onto the Host objects:
        # the determinism gate's signature path (and any tooling that
        # reads hosts) sees the base replica, which must bit-match a
        # standalone run with replica 0's parameters. A columnar build
        # adopts the row as plane columns instead — no host
        # materialization just to carry counters.
        plane = getattr(self.sim, "plane", None)
        if plane is not None:
            plane.adopt_final(final, replica=0)
        else:
            for h in self.sim.hosts:
                i = h.host_id
                h.events_executed = int(final["n_exec"][0, i])
                h.packets_sent = int(final["n_sent"][0, i])
                h.packets_dropped = int(final["n_drop"][0, i])
                h.packets_delivered = int(final["n_deliv"][0, i])
                h.trace_checksum = int(final["chk"][0, i])
        return stats
