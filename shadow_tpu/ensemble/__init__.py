"""Ensemble engine: vmapped multi-replica simulation campaigns.

``ensemble:`` configs run R independent replicas of a device-twin
workload in ONE compiled program — the engine vmaps the fused round
step over a replica axis composed outside the mesh shard axis, so a
seed/latency/loss/fault sweep pays one compile and one dispatch
stream instead of N. See spec.py (replica worlds + the determinism
contract) and campaign.py (the runner + ENSEMBLE_*.json record).
"""

from shadow_tpu.ensemble.spec import (
    EnsembleWorlds,
    build_worlds,
    campaign_fingerprint,
    seed_key_np,
)
from shadow_tpu.ensemble.campaign import EnsembleRunner, aggregate

__all__ = [
    "EnsembleWorlds",
    "EnsembleRunner",
    "aggregate",
    "build_worlds",
    "campaign_fingerprint",
    "seed_key_np",
]
