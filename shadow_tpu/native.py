"""ctypes binding to the native C++ runtime (native/).

Loads (building on first use if needed) libshadowtpu_native.so: the
shared-memory arena with buddy allocation + serializable handles, and
the spinning-semaphore IPC channel — the substrate the managed-process
runtime (syscall interposition) is built on, mirroring the role of the
reference's shmem allocator + shim IPC.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libshadowtpu_native.so")

_lib: Optional[ctypes.CDLL] = None


class IpcMessage(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_uint32),
        ("_pad", ctypes.c_uint32),
        ("number", ctypes.c_int64),
        ("args", ctypes.c_uint64 * 6),
        ("inline_bytes", ctypes.c_uint8 * 64),
    ]


IPC_NONE = 0
IPC_START = 1
IPC_SYSCALL = 2
IPC_SYSCALL_DONE = 3
IPC_SYSCALL_NATIVE = 4
IPC_STOP = 5
IPC_CLONE_GO = 6       # sim->plugin: clone approved (vtid + chan offset)
IPC_EXEC_DONE = 12     # plugin->sim: post-execve image live on channel
IPC_THREAD_START = 7   # child thread announcing itself on its channel
IPC_THREAD_FAIL = 8    # native clone failed after approval
IPC_FORK_RESULT = 9    # parent->sim: real child pid (or -errno)
IPC_SIGNAL = 10        # sim->plugin: run handler args[0] for signal
IPC_SIGNAL_DONE = 11   # plugin->sim: handler returned


def load(build_if_missing: bool = True) -> ctypes.CDLL:
    """Load the native library, building it on first use."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH) and build_if_missing:
        subprocess.run(["make", "-C", _NATIVE_DIR,
                        "build/libshadowtpu_native.so"],
                       check=True, capture_output=True)
    lib = ctypes.CDLL(_LIB_PATH)
    lib.shadowtpu_arena_create.restype = ctypes.c_void_p
    lib.shadowtpu_arena_create.argtypes = [ctypes.c_char_p,
                                           ctypes.c_uint64]
    lib.shadowtpu_arena_open.restype = ctypes.c_void_p
    lib.shadowtpu_arena_open.argtypes = [ctypes.c_char_p]
    lib.shadowtpu_arena_close.argtypes = [ctypes.c_void_p]
    lib.shadowtpu_arena_unlink.argtypes = [ctypes.c_void_p]
    lib.shadowtpu_arena_alloc.restype = ctypes.c_void_p
    lib.shadowtpu_arena_alloc.argtypes = [ctypes.c_void_p,
                                          ctypes.c_uint64]
    lib.shadowtpu_arena_free.argtypes = [ctypes.c_void_p,
                                         ctypes.c_void_p]
    lib.shadowtpu_arena_allocated.restype = ctypes.c_uint64
    lib.shadowtpu_arena_allocated.argtypes = [ctypes.c_void_p]
    lib.shadowtpu_arena_offset.restype = ctypes.c_uint64
    lib.shadowtpu_arena_offset.argtypes = [ctypes.c_void_p,
                                           ctypes.c_void_p]
    lib.shadowtpu_arena_at.restype = ctypes.c_void_p
    lib.shadowtpu_arena_at.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.shadowtpu_cleanup_orphans.restype = ctypes.c_int
    lib.shadowtpu_cleanup_orphans.argtypes = [ctypes.c_char_p]
    lib.shadowtpu_ipc_sizeof.restype = ctypes.c_uint64
    lib.shadowtpu_ipc_init.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.shadowtpu_ipc_send_to_plugin.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IpcMessage)]
    lib.shadowtpu_ipc_set_sim_now.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]
    lib.shadowtpu_ipc_recv_from_plugin.restype = ctypes.c_int
    lib.shadowtpu_ipc_recv_from_plugin.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IpcMessage)]
    lib.shadowtpu_ipc_recv_from_plugin_timed.restype = ctypes.c_int
    lib.shadowtpu_ipc_recv_from_plugin_timed.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IpcMessage), ctypes.c_uint32]
    lib.shadowtpu_ipc_send_to_simulator.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IpcMessage)]
    lib.shadowtpu_ipc_recv_from_simulator.restype = ctypes.c_int
    lib.shadowtpu_ipc_recv_from_simulator.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(IpcMessage)]
    lib.shadowtpu_ipc_mark_plugin_exited.argtypes = [ctypes.c_void_p]
    lib.shadowtpu_ipc_native_thread_alive.restype = ctypes.c_uint32
    lib.shadowtpu_ipc_native_thread_alive.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class ShmArena:
    """Python handle to a native shared-memory arena."""

    def __init__(self, name: str, size: int = 0, create: bool = True):
        self._lib = load()
        self.name = name
        if create:
            self._h = self._lib.shadowtpu_arena_create(
                name.encode(), size)
        else:
            self._h = self._lib.shadowtpu_arena_open(name.encode())
        if not self._h:
            raise OSError(f"cannot {'create' if create else 'open'} "
                          f"arena {name}")

    def alloc(self, nbytes: int) -> int:
        p = self._lib.shadowtpu_arena_alloc(self._h, nbytes)
        if not p:
            raise MemoryError(f"arena {self.name} exhausted")
        return p

    def free(self, p: int) -> None:
        self._lib.shadowtpu_arena_free(self._h, p)

    @property
    def allocated(self) -> int:
        return self._lib.shadowtpu_arena_allocated(self._h)

    def offset_of(self, p: int) -> int:
        return self._lib.shadowtpu_arena_offset(self._h, p)

    def at_offset(self, off: int) -> int:
        return self._lib.shadowtpu_arena_at(self._h, off)

    def unlink(self) -> None:
        self._lib.shadowtpu_arena_unlink(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.shadowtpu_arena_close(self._h)
            self._h = None


class IpcChannel:
    """An IPC channel living inside an arena at a known offset."""

    def __init__(self, arena: ShmArena, ptr: Optional[int] = None,
                 spin_max: int = 8096):
        self._lib = load()
        self.arena = arena
        if ptr is None:
            ptr = arena.alloc(self._lib.shadowtpu_ipc_sizeof())
            self._lib.shadowtpu_ipc_init(ptr, spin_max)
        self.ptr = ptr

    @property
    def offset(self) -> int:
        return self.arena.offset_of(self.ptr)

    def send_to_plugin(self, msg: IpcMessage) -> None:
        self._lib.shadowtpu_ipc_send_to_plugin(self.ptr,
                                               ctypes.byref(msg))

    def set_sim_now(self, now_ns: int) -> None:
        """Publish simulated time for the shim's passive readers
        (log timestamps; ref shim_event.h:17-22 sim_time block)."""
        self._lib.shadowtpu_ipc_set_sim_now(self.ptr, now_ns)

    def recv_from_plugin(self) -> Optional[IpcMessage]:
        out = IpcMessage()
        ok = self._lib.shadowtpu_ipc_recv_from_plugin(
            self.ptr, ctypes.byref(out))
        return out if ok else None

    def recv_from_plugin_timed(self, timeout_ms: int
                               ) -> tuple[int, Optional[IpcMessage]]:
        """-> (status, msg): 1 = received, 0 = plugin exited,
        -1 = timed out."""
        out = IpcMessage()
        status = self._lib.shadowtpu_ipc_recv_from_plugin_timed(
            self.ptr, ctypes.byref(out), timeout_ms)
        return status, (out if status == 1 else None)

    def send_to_simulator(self, msg: IpcMessage) -> None:
        self._lib.shadowtpu_ipc_send_to_simulator(self.ptr,
                                                  ctypes.byref(msg))

    def recv_from_simulator(self) -> Optional[IpcMessage]:
        out = IpcMessage()
        ok = self._lib.shadowtpu_ipc_recv_from_simulator(
            self.ptr, ctypes.byref(out))
        return out if ok else None

    def mark_plugin_exited(self) -> None:
        self._lib.shadowtpu_ipc_mark_plugin_exited(self.ptr)

    def native_thread_alive(self) -> bool:
        """True while the cloned native thread behind this channel is
        alive (kernel-cleared CLEARTID guard; see spinsem.hpp)."""
        return bool(self._lib.shadowtpu_ipc_native_thread_alive(self.ptr))


def cleanup_orphans(prefix: str = "shadowtpu_shm_") -> int:
    return load().shadowtpu_cleanup_orphans(prefix.encode())


_SHIM_PATH = os.path.join(_NATIVE_DIR, "build", "libshadowtpu_shim.so")


def shim_path(build_if_missing: bool = True) -> str:
    """Path to the preload shim injected into managed processes."""
    if not os.path.exists(_SHIM_PATH) and build_if_missing:
        subprocess.run(["make", "-C", _NATIVE_DIR,
                        "build/libshadowtpu_shim.so"],
                       check=True, capture_output=True)
    return _SHIM_PATH


_LAUNCHER_PATH = os.path.join(_NATIVE_DIR, "build",
                              "shadowtpu_launcher")


def launcher_path(build_if_missing: bool = True) -> str:
    """Path to the ptrace-backend tracee launcher stub."""
    if not os.path.exists(_LAUNCHER_PATH) and build_if_missing:
        subprocess.run(["make", "-C", _NATIVE_DIR,
                        "build/shadowtpu_launcher"],
                       check=True, capture_output=True)
    return _LAUNCHER_PATH


_LAUNCHER_STATIC_PATH = os.path.join(_NATIVE_DIR, "build",
                                     "shadowtpu_launcher_static")
_LAUNCHER_STATIC_RESULT = [False, None]     # [attempted, path|None]


def launcher_static_path(build_if_missing: bool = True):
    """Path to the STATIC launcher stub (preload backend's --run
    mode: rlimit cap + ASLR off + exec, with LD_PRELOAD inert in the
    stub itself), or None when no static libc exists on this machine
    (callers fall back to a preexec_fn). The build attempt is
    memoized — a machine without static libc must not pay a failing
    make per process spawn."""
    if os.path.exists(_LAUNCHER_STATIC_PATH):
        return _LAUNCHER_STATIC_PATH
    if not build_if_missing or _LAUNCHER_STATIC_RESULT[0]:
        return _LAUNCHER_STATIC_RESULT[1]
    _LAUNCHER_STATIC_RESULT[0] = True
    r = subprocess.run(["make", "-C", _NATIVE_DIR,
                        "build/shadowtpu_launcher_static"],
                       capture_output=True)
    if r.returncode == 0 and os.path.exists(_LAUNCHER_STATIC_PATH):
        _LAUNCHER_STATIC_RESULT[1] = _LAUNCHER_STATIC_PATH
    return _LAUNCHER_STATIC_RESULT[1]
