"""Model-application interface (CPU form).

A ModelApp is the scripted stand-in for a managed process. Its hooks
receive a SimContext (core/worker.py) exposing:

* ``ctx.now`` — current sim time (ns)
* ``ctx.host_id`` / ``ctx.n_hosts``
* ``ctx.send(dst_host, size_bytes, data)`` — send a packet through the
  network model (may be dropped); delivery fires the destination app's
  ``on_packet``
* ``ctx.schedule(delay_ns, data)`` — self timer -> ``on_timer``
* ``ctx.app_bits()`` — 32 deterministic random bits from the counter
  RNG (bit-identical on CPU and device, so vectorized twins of an app
  make the same decisions)

Apps that also have a device (vectorized JAX) twin must restrict their
decision-making to integer arithmetic on ``app_bits()`` draws so traces
match bit-for-bit across engines.
"""

from __future__ import annotations

import shlex
from functools import lru_cache
from typing import Any


@lru_cache(maxsize=4096)
def _split_cached(args: str) -> tuple[str, ...]:
    # every host of a quantity-N group carries the identical args
    # string; shlex dominates the 100k-host build without this memo
    return tuple(shlex.split(args))


def parse_kv_args(args: Any) -> dict[str, str]:
    """Process args come as "k=v k=v" strings or lists (schema.py);
    model apps use k=v pairs like the reference's phold test driver."""
    if isinstance(args, dict):
        return {str(k): str(v) for k, v in args.items()}
    if isinstance(args, (list, tuple)):
        parts = [str(p) for p in args]
    else:
        parts = _split_cached(str(args or ""))
    out = {}
    for p in parts:
        k, eq, v = p.partition("=")
        if eq:
            out[k.strip("-")] = v
    return out


class ModelApp:
    def __init__(self, args: dict[str, str], host_id: int, n_hosts: int):
        self.args = args
        self.host_id = host_id
        self.n_hosts = n_hosts

    def boot(self, ctx) -> None:
        """Process start (the _process_start analogue)."""

    def on_timer(self, ctx, data: tuple) -> None:
        """A ctx.schedule()'d timer fired."""

    def on_packet(self, ctx, src_host: int, size: int,
                  data: tuple) -> None:
        """A packet from src_host was delivered to this host."""

    def on_stop(self, ctx) -> None:
        """Process stop_time reached."""
