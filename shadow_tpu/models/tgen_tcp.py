"""tgen-like bulk transfer over the in-simulator TCP stack.

The TCP-fidelity twin of models/tgen.py: the client opens a real
(simulated) TCP connection — three-way handshake, Reno congestion
control, token-bucket bandwidth, CoDel router queues, retransmissions —
sends a 64-byte request, and the server streams `size` bytes back.
This is the shape of the reference's flagship tgen workload (BASELINE
configs 1-3) running over its in-Shadow TCP (descriptor/tcp.c).

server args: size=bytes (per-request response size)
client args: server=<hostname>, port=, size= (expected; for accounting
only — the server's own size config governs), count=, pause=.
"""

from __future__ import annotations

from shadow_tpu.config.units import parse_size_bytes, parse_time_ns
from shadow_tpu.models.base import ModelApp

REQUEST_BYTES = 64


class TgenTcpServerApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.size = parse_size_bytes(args.get("size", "1 MiB"))
        self.port = int(args.get("port", 80))
        self.requests_served = 0
        self._pending: dict[int, int] = {}   # conn_id -> request bytes

    def boot(self, ctx) -> None:
        ctx.tcp_listen(self.port, on_accept=self._on_accept,
                       on_data=self._on_data)

    def _on_accept(self, ctx, conn, now) -> None:
        self._pending[conn.conn_id] = 0

    def _on_data(self, ctx, conn, nbytes, now) -> None:
        got = self._pending.get(conn.conn_id, 0) + nbytes
        self._pending[conn.conn_id] = got
        if got >= REQUEST_BYTES:
            self._pending.pop(conn.conn_id, None)
            self.requests_served += 1
            conn.send(now, self.size)
            # one response per connection: FIN rides after the last
            # data segment, so the client sees data then close
            conn.close(now)


class TgenTcpClientApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.server_name = args.get("server", "server")
        self.port = int(args.get("port", 80))
        self.size = parse_size_bytes(args.get("size", "1 MiB"))
        self.count = int(args.get("count", 1))
        self.pause_ns = parse_time_ns(args.get("pause", "1 s"))
        self.downloads_done = 0
        self.bytes_received = 0
        self._conn_bytes = 0
        self._last_download_ns = 0
        self._started_at = 0

    def boot(self, ctx) -> None:
        if self.count > 0:
            self._connect(ctx)

    def on_timer(self, ctx, data) -> None:
        self._connect(ctx)

    def _connect(self, ctx) -> None:
        self._conn_bytes = 0
        self._started_at = ctx.now
        ctx.tcp_connect(ctx.resolve(self.server_name), self.port,
                        on_connected=self._on_connected,
                        on_data=self._on_data)

    def _on_connected(self, ctx, conn, now) -> None:
        conn.send(now, REQUEST_BYTES)

    def _on_data(self, ctx, conn, nbytes, now) -> None:
        self.bytes_received += nbytes
        self._conn_bytes += nbytes
        if self._conn_bytes >= self.size:
            self.downloads_done += 1
            self._last_download_ns = now - self._started_at
            conn.close(now)
            if self.downloads_done < self.count:
                ctx.schedule(self.pause_ns)
