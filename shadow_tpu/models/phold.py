"""PHOLD: the classic parallel-discrete-event benchmark workload.

Equivalent of the reference's src/test/phold (test_phold.c + phold.yaml):
N peers bounce messages around — each received message triggers one new
message to a pseudo-random peer. The steady-state message population
equals ``msgload`` x hosts, and throughput (events/sec wall) is the
scheduler's figure of merit.

args: msgload=K (initial messages per host, default 1), size=bytes
(payload size, default 64), selfloop=0/1 (allow sending to self,
default 0).

Decisions use only integer ops on ``app_bits()`` so the device twin
(shadow_tpu/device/apps.py) reproduces them exactly.
"""

from __future__ import annotations

from shadow_tpu.models.base import ModelApp


class PholdApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.msgload = int(args.get("msgload", 1))
        self.size = int(args.get("size", 64))
        self.selfloop = int(args.get("selfloop", 0))
        # virtual CPU milliseconds burned per received message (the
        # reference phold's cpuload knob); CPU engines only — keep 0
        # for device-twin trace parity until the device CPU model lands
        self.cpuload_ms = int(args.get("cpuload", 0))
        self.received = 0

    def _pick_peer(self, ctx) -> int:
        bits = ctx.app_bits()
        if self.selfloop or self.n_hosts == 1:
            return bits % self.n_hosts
        # exclude self without biasing the draw
        return (self.host_id + 1 + bits % (self.n_hosts - 1)) % self.n_hosts

    def boot(self, ctx) -> None:
        for _ in range(self.msgload):
            ctx.send(self._pick_peer(ctx), self.size)

    def on_packet(self, ctx, src_host, size, data) -> None:
        self.received += 1
        if self.cpuload_ms:
            ctx.consume_cpu(self.cpuload_ms * 1_000_000)
        ctx.send(self._pick_peer(ctx), self.size)
