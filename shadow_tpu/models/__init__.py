"""Application models.

With `interpose_method: model` (the default), processes are *application
models*: scripted behaviors with two interchangeable implementations —
a per-host Python class for the CPU engines (this package) and a
vectorized JAX form for the device engine (shadow_tpu/device/apps.py).
The `path` of a process config selects one as "model:<name>".

Real-program execution (interpose_method preload/ptrace), where `path`
is an actual executable run under syscall interposition, is the native
runtime's job (native/), mirroring the reference's managed processes.
"""

from __future__ import annotations

from shadow_tpu.models.base import ModelApp, parse_kv_args
from shadow_tpu.models.phold import PholdApp
from shadow_tpu.models.tgen import TgenClientApp, TgenServerApp
from shadow_tpu.models.tgen_tcp import TgenTcpClientApp, TgenTcpServerApp
from shadow_tpu.models.tor import TorClientApp, TorRelayApp

_REGISTRY = {
    "phold": PholdApp,
    "tgen_client": TgenClientApp,
    "tgen_server": TgenServerApp,
    "tgen_tcp_client": TgenTcpClientApp,
    "tgen_tcp_server": TgenTcpServerApp,
    "tor_relay": TorRelayApp,
    "tor_client": TorClientApp,
}


# models the columnar host plane (host/plane.py) can build without
# per-host app objects: arg parsing happens once per GROUP (a
# prototype app) and the device twin's arrays fill from group slices.
# tor stays out (relay lists + route state want real per-host apps);
# extension models register here only if their parsed fields are pure
# functions of the args string (never of host_id).
COLUMNAR_MODELS = {"phold", "tgen_client", "tgen_server"}


def is_model_path(path: str) -> bool:
    return path.startswith("model:")


def make_app(path: str, args, host_id: int, n_hosts: int) -> ModelApp:
    if not is_model_path(path):
        raise ValueError(
            f"process path {path!r} is not a model: real-executable "
            "processes require the native interposition runtime")
    name = path[len("model:"):]
    if name not in _REGISTRY:
        raise ValueError(f"unknown model app {name!r} "
                         f"(have: {sorted(_REGISTRY)})")
    return _REGISTRY[name](parse_kv_args(args), host_id, n_hosts)


def register_model(name: str, cls) -> None:
    """Extension point for user-defined application models."""
    _REGISTRY[name] = cls


__all__ = ["ModelApp", "make_app", "register_model", "is_model_path",
           "parse_kv_args", "COLUMNAR_MODELS",
           "PholdApp", "TgenClientApp", "TgenServerApp"]
