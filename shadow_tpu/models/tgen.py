"""tgen-like traffic generator models (client/server bulk transfer).

Models the reference's flagship benchmark workload (tgen bulk downloads,
docs/getting_started_tgen.md and BASELINE configs 1-3): a client asks a
server for `size` bytes; the server streams them back as MTU-sized
packets; the client counts arrivals, and after receiving everything
pauses and repeats, `count` times total.

The transfer is *pull-based and chunked*: the client requests a window
of at most CHUNK_PKTS packets at a time and the server answers each
request statelessly (REQ carries the starting packet index; the total
size is a static client arg). This bounds the per-event send fan-out to
a compile-time constant, which is exactly what the vectorized device
twin (device/apps.py TgenDevice) needs — and both twins therefore
produce identical event traces.

This packet-granularity form runs on the raw network model (latency,
loss, drops). When the in-simulator TCP stack is selected the tgen_tcp
variants run over real TCP flows with congestion control instead.

client args: server=<hostname>, size=bytes, count=N, pause=ns between
downloads, retry=timeout for re-requesting a chunk on packet loss
(0 = no retries; leave 0 on lossless paths). server args: none.

Message tags (integers, for device-twin parity):
  1=REQ(d0=start packet index, d1=total bytes)   2=DATA(d0=seq_no)
Timer payload d0: -1 = pause expired (start next download);
  gen >= 0 = chunk retry, valid only if gen still current.
"""

from __future__ import annotations

from shadow_tpu import simtime
from shadow_tpu.config.units import parse_size_bytes, parse_time_ns
from shadow_tpu.models.base import ModelApp

TAG_REQ = 1
TAG_DATA = 2

MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE
CHUNK_PKTS = 32                  # window: packets per REQ round trip


def n_packets(total_bytes: int) -> int:
    return (total_bytes + MSS - 1) // MSS


class TgenServerApp(ModelApp):
    """Stateless chunk server: REQ(start, total) -> ONE packet-train
    event carrying up to CHUNK_PKTS DATA packets [start, ...). The
    train is the classic DES bulk-flow optimization: one event per
    chunk instead of one per packet, while the network still rolls a
    drop per packet (SimContext.send_train) with the identical keys —
    so loss behavior matches per-packet sends bit-for-bit."""

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_REQ:
            return
        start, total = data[1], data[2]
        npkts = n_packets(total)
        cnt = min(CHUNK_PKTS, npkts - start)
        if cnt <= 0:
            return
        last = total % MSS or MSS
        nbytes = cnt * MSS if start + cnt < npkts \
            else (cnt - 1) * MSS + last
        ctx.send_train(src_host, nbytes, (TAG_DATA, start), count=cnt)


class TgenClientApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.server_name = args.get("server", "server")
        self.size = parse_size_bytes(args.get("size", "1 MiB"))
        self.count = int(args.get("count", 1))
        self.pause_ns = parse_time_ns(args.get("pause", "1 s"))
        self.retry_ns = parse_time_ns(args.get("retry", 0))
        self.downloads_done = 0
        self.bytes_received = 0
        self._chunk_start = 0          # first packet index of the chunk
        self._got = 0                  # packets received in the chunk
        self._mask = 0                 # bitmask of chunk seqs received
        self._req_gen = 0              # stale-retry guard
        self._server: int | None = None

    @property
    def _npkts(self) -> int:
        return n_packets(self.size)

    def _request_chunk(self, ctx) -> None:
        if self._server is None:
            self._server = ctx.resolve(self.server_name)
        self._got = 0
        self._mask = 0
        self._req_gen += 1
        ctx.send(self._server, 64, (TAG_REQ, self._chunk_start,
                                    self.size))
        if self.retry_ns > 0:
            ctx.schedule(self.retry_ns, data=(self._req_gen,))

    def boot(self, ctx) -> None:
        if self.count > 0:
            self._request_chunk(ctx)

    def on_timer(self, ctx, data) -> None:
        d0 = data[0] if data else -1
        if d0 >= 0:
            if d0 == self._req_gen:            # chunk still outstanding
                self._request_chunk(ctx)       # re-request (lost DATA)
            return
        self._chunk_start = 0
        self._request_chunk(ctx)

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_DATA:
            return
        # a train event: data = (start, survivor_bitmask). Only fresh
        # in-window bits advance the window — duplicates from a
        # premature retry must not complete a chunk
        start = data[1] if len(data) > 1 else -1
        surv = data[2] if len(data) > 2 else 0
        chunk_len = min(CHUNK_PKTS, self._npkts - self._chunk_start)
        shift = start - self._chunk_start
        if shift > 0:
            window = (surv << shift) & ((1 << chunk_len) - 1)
        else:
            window = (surv >> -shift) & ((1 << chunk_len) - 1)
        fresh = window & ~self._mask
        if not fresh:
            return                     # stale chunk / all duplicates
        self._mask |= fresh
        for off in range(chunk_len):
            if fresh & (1 << off):
                seq = self._chunk_start + off
                self.bytes_received += MSS if seq < self._npkts - 1 \
                    else (self.size % MSS or MSS)
                self._got += 1
        if self._got < chunk_len:
            return
        self._chunk_start += chunk_len
        if self._chunk_start < self._npkts:
            self._request_chunk(ctx)
            return
        # download complete
        self.downloads_done += 1
        self._chunk_start = 0
        self._req_gen += 1                     # invalidate pending retry
        if self.downloads_done < self.count:
            ctx.schedule(self.pause_ns, data=(-1,))
