"""tgen-like traffic generator models (client/server bulk transfer).

Models the reference's flagship benchmark workload (tgen bulk downloads,
docs/getting_started_tgen.md and BASELINE configs 1-3): a client asks a
server for `size` bytes; the server streams them back as MTU-sized
packets; the client counts arrivals, and after receiving everything
pauses and repeats, `count` times total.

The transfer is *pull-based and chunked*: the client requests a window
of at most CHUNK_PKTS packets at a time and the server answers each
request statelessly (REQ carries the starting packet index; the total
size is a static client arg). This bounds the per-event send fan-out to
a compile-time constant, which is exactly what the vectorized device
twin (device/apps.py TgenDevice) needs — and both twins therefore
produce identical event traces.

This packet-granularity form runs on the raw network model (latency,
loss, drops). When the in-simulator TCP stack is selected the tgen_tcp
variants run over real TCP flows with congestion control instead.

client args: server=<hostname>, size=bytes, count=N, pause=ns between
downloads, retry=timeout for re-requesting a chunk on packet loss
(0 = no retries; leave 0 on lossless paths). server args: none.

Message tags (integers, for device-twin parity):
  1=REQ(d0=start packet index, d1=total bytes)   2=DATA(d0=seq_no)
Timer payload d0: -1 = pause expired (start next download);
  gen >= 0 = chunk retry, valid only if gen still current.
"""

from __future__ import annotations

from shadow_tpu import simtime
from shadow_tpu.config.units import parse_size_bytes, parse_time_ns
from shadow_tpu.models.base import ModelApp

TAG_REQ = 1
TAG_DATA = 2

MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE
CHUNK_PKTS = 32                  # window: packets per REQ round trip


def n_packets(total_bytes: int) -> int:
    return (total_bytes + MSS - 1) // MSS


class TgenServerApp(ModelApp):
    """Stateless chunk server: REQ(start, total) -> up to CHUNK_PKTS
    DATA packets [start, ...), sizes MSS except the final remainder."""

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_REQ:
            return
        start, total = data[1], data[2]
        npkts = n_packets(total)
        for k in range(CHUNK_PKTS):
            seq = start + k
            if seq >= npkts:
                break
            sz = MSS if seq < npkts - 1 or total % MSS == 0 \
                else total % MSS
            ctx.send(src_host, sz, (TAG_DATA, seq))


class TgenClientApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.server_name = args.get("server", "server")
        self.size = parse_size_bytes(args.get("size", "1 MiB"))
        self.count = int(args.get("count", 1))
        self.pause_ns = parse_time_ns(args.get("pause", "1 s"))
        self.retry_ns = parse_time_ns(args.get("retry", 0))
        self.downloads_done = 0
        self.bytes_received = 0
        self._chunk_start = 0          # first packet index of the chunk
        self._got = 0                  # packets received in the chunk
        self._mask = 0                 # bitmask of chunk seqs received
        self._req_gen = 0              # stale-retry guard
        self._server: int | None = None

    @property
    def _npkts(self) -> int:
        return n_packets(self.size)

    def _request_chunk(self, ctx) -> None:
        if self._server is None:
            self._server = ctx.resolve(self.server_name)
        self._got = 0
        self._mask = 0
        self._req_gen += 1
        ctx.send(self._server, 64, (TAG_REQ, self._chunk_start,
                                    self.size))
        if self.retry_ns > 0:
            ctx.schedule(self.retry_ns, data=(self._req_gen,))

    def boot(self, ctx) -> None:
        if self.count > 0:
            self._request_chunk(ctx)

    def on_timer(self, ctx, data) -> None:
        d0 = data[0] if data else -1
        if d0 >= 0:
            if d0 == self._req_gen:            # chunk still outstanding
                self._request_chunk(ctx)       # re-request (lost DATA)
            return
        self._chunk_start = 0
        self._request_chunk(ctx)

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_DATA:
            return
        # count only fresh in-window packets: a premature retry can put
        # duplicate DATA in flight, which must not advance the window
        seq = data[1] if len(data) > 1 else -1
        chunk_len = min(CHUNK_PKTS, self._npkts - self._chunk_start)
        off = seq - self._chunk_start
        if off < 0 or off >= chunk_len:
            return                     # stale chunk / out of window
        bit = 1 << off
        if self._mask & bit:
            return                     # duplicate within the window
        self._mask |= bit
        self.bytes_received += size
        self._got += 1
        if self._got < chunk_len:
            return
        self._chunk_start += chunk_len
        if self._chunk_start < self._npkts:
            self._request_chunk(ctx)
            return
        # download complete
        self.downloads_done += 1
        self._chunk_start = 0
        self._req_gen += 1                     # invalidate pending retry
        if self.downloads_done < self.count:
            ctx.schedule(self.pause_ns, data=(-1,))
