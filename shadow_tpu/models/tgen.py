"""tgen-like traffic generator models (client/server bulk transfer).

Models the reference's flagship benchmark workload (tgen bulk downloads,
docs/getting_started_tgen.md and BASELINE configs 1-3): a client asks a
server for `size` bytes; the server streams them back as MTU-sized
packets; the client counts arrivals, and after receiving everything
pauses and repeats, `count` times total.

This packet-granularity form runs on the raw network model (latency,
loss, drops). When the in-simulator TCP stack is selected
(experimental.transport=tcp, shadow_tpu/host/tcp.py), the same apps run
over real TCP flows with congestion control and retransmission instead.

client args: server=<hostname>, size=bytes, count=N, pause=ns between
downloads. server args: none.

Message tags (integers, for device-twin parity):
  1=REQ(total_size)  2=DATA(seq_no)  3=FIN
"""

from __future__ import annotations

from shadow_tpu import simtime
from shadow_tpu.config.units import parse_size_bytes, parse_time_ns
from shadow_tpu.models.base import ModelApp

TAG_REQ = 1
TAG_DATA = 2
TAG_FIN = 3

MSS = simtime.CONFIG_TCP_MAX_SEGMENT_SIZE


class TgenServerApp(ModelApp):
    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_REQ:
            return
        total = data[1]
        n_full, last = divmod(total, MSS)
        for seq in range(n_full):
            ctx.send(src_host, MSS, (TAG_DATA, seq))
        if last:
            ctx.send(src_host, last, (TAG_DATA, n_full))
        ctx.send(src_host, 1, (TAG_FIN, n_full + (1 if last else 0)))


class TgenClientApp(ModelApp):
    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.server_name = args.get("server", "server")
        self.size = parse_size_bytes(args.get("size", "1 MiB"))
        self.count = int(args.get("count", 1))
        self.pause_ns = parse_time_ns(args.get("pause", "1 s"))
        self.downloads_done = 0
        self.bytes_received = 0
        self._expect_packets = 0
        self._got_packets = 0
        self._server: int | None = None

    def _request(self, ctx) -> None:
        if self._server is None:
            self._server = ctx.resolve(self.server_name)
        self._got_packets = 0
        self._expect_packets = 0
        ctx.send(self._server, 64, (TAG_REQ, self.size))

    def boot(self, ctx) -> None:
        if self.count > 0:
            self._request(ctx)

    def on_timer(self, ctx, data) -> None:
        self._request(ctx)

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag == TAG_DATA:
            self.bytes_received += size
            self._got_packets += 1
        elif tag == TAG_FIN:
            self._expect_packets = data[1]
        if (self._expect_packets and
                self._got_packets >= self._expect_packets):
            self.downloads_done += 1
            self._expect_packets = 0
            if self.downloads_done < self.count:
                ctx.schedule(self.pause_ns)
