"""Onion-routing (Tor-like) workload: clients stream cells through
3-hop relay circuits.

The reference's flagship scale target is Tor simulation (README.md:66-69,
run_tor.yml CI; BASELINE configs #4/#5: 50 relays + 200 clients and
~6k relays + 50k clients). Real Tor runs as managed processes; this
model is the scripted twin of its *traffic shape* — guard/middle/exit
forwarding, cell quantization, chunked end-to-end pulls — built
TPU-first:

**Relays are stateless.** A circuit is a pure function of the client id
(three distinct relays drawn from counter-RNG keyed by
(TOR_ROUTE, client, hop)), so any relay can recompute route position
and next hop from the cell's circuit id alone — no per-relay circuit
tables, which is exactly what lets the device twin (TorDevice) run
every relay as one vectorized branch with zero dynamic state.

Cells: REQ (64 B) travels client -> guard -> middle -> exit carrying a
chunk-start index; the exit answers with up to CHUNK_CELLS DATA cells
(CELL_BYTES each) flowing exit -> middle -> guard -> client. The client
windows chunks exactly like the tgen model (received-mask, retry
generation, pause between downloads).

client args: cells=N per download, count=downloads, pause=ns,
retry=ns (0 disables). relay args: none.

Tags (device-twin parity): 3=TOR_REQ, 4=TOR_DATA. d1 packs
(circ << SEQ_BITS) | seq for DATA and (circ << SEQ_BITS) | chunk_start
for REQ; circuits are client gids.
"""

from __future__ import annotations

from shadow_tpu.config.units import parse_time_ns
from shadow_tpu.models.base import ModelApp
from shadow_tpu.utils.rng import PURPOSE_TOR_ROUTE

TAG_TOR_REQ = 3
TAG_TOR_DATA = 4

CELL_BYTES = 512                # Tor cell payload quantum
CHUNK_CELLS = 16                # cells per REQ round trip (window)
SEQ_BITS = 12                   # seq field width inside d1
SEQ_MASK = (1 << SEQ_BITS) - 1


def pick_route(bits3: tuple[int, int, int], n_relays: int
               ) -> tuple[int, int, int]:
    """Three DISTINCT relay indices from three independent draws —
    pure integer math shared verbatim with the device twin."""
    r = n_relays
    g = bits3[0] % r
    m = bits3[1] % (r - 1)
    if m >= g:
        m += 1
    lo, hi = (g, m) if g < m else (m, g)
    e = bits3[2] % (r - 2)
    if e >= lo:
        e += 1
    if e >= hi:
        e += 1
    return g, m, e


class TorMixin:
    """Shared route computation over the config's relay group."""

    def _relay_gids(self, ctx) -> list[int]:
        if getattr(self, "_relays", None) is None:
            # every host whose app is a relay, in gid order — the
            # device twin derives the identical list from roles
            self._relays = [h.host_id for h in ctx._m.hosts
                            if isinstance(h.app, TorRelayApp)]
            if len(self._relays) < 3:
                raise ValueError("tor model needs >= 3 relays")
        return self._relays

    def _route(self, ctx, circ: int) -> tuple[int, int, int]:
        relays = self._relay_gids(ctx)
        bits = tuple(ctx.pure_bits(PURPOSE_TOR_ROUTE, circ, j)
                     for j in range(3))
        g, m, e = pick_route(bits, len(relays))
        return relays[g], relays[m], relays[e]


class TorRelayApp(ModelApp, TorMixin):
    """Stateless onion relay: recomputes the circuit route from the
    cell's circuit id and forwards one hop; the exit answers REQ chunks
    itself (the 'server' role is folded into the exit hop)."""

    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.cells_relayed = 0
        self.cells_served = 0

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag == TAG_TOR_REQ:
            circ, start = data[1], data[2]
            g, m, e = self._route(ctx, circ)
            me = ctx.host_id
            if me == g:
                self.cells_relayed += 1
                ctx.send(m, size, tuple(data))
            elif me == m:
                self.cells_relayed += 1
                ctx.send(e, size, tuple(data))
            elif me == e:
                # exit: serve the chunk back toward the client as ONE
                # packet TRAIN (per-cell drop rolls, survivor bitmask
                # — the tgen chunk optimization applied to cells)
                n_cells = data[3]
                cnt = min(CHUNK_CELLS, n_cells - start)
                if cnt > 0:
                    self.cells_served += cnt
                    ctx.send_train(
                        m, CELL_BYTES * cnt,
                        (TAG_TOR_DATA, circ, start),
                        count=CHUNK_CELLS, mask=(1 << cnt) - 1)
        elif tag == TAG_TOR_DATA:
            # a DATA train: (circ, chunk start, survivor mask). Each
            # hop forwards the SURVIVORS as a new masked train — roll
            # keys still span all CHUNK_CELLS lanes (device parity)
            circ, start, surv = data[1], data[2], data[3]
            g, m, e = self._route(ctx, circ)
            me = ctx.host_id
            live = surv.bit_count()
            if live == 0:
                return
            if me == m:
                self.cells_relayed += live
                ctx.send_train(g, CELL_BYTES * live,
                               (TAG_TOR_DATA, circ, start),
                               count=CHUNK_CELLS, mask=surv)
            elif me == g:
                self.cells_relayed += live
                ctx.send_train(circ, CELL_BYTES * live,
                               (TAG_TOR_DATA, circ, start),
                               count=CHUNK_CELLS, mask=surv)


class TorClientApp(ModelApp, TorMixin):
    """Chunked cell puller through its circuit (window/mask/retry state
    identical in shape to the tgen client, so the device twin reuses
    the proven dedup rules)."""

    def __init__(self, args, host_id, n_hosts):
        super().__init__(args, host_id, n_hosts)
        self.cells = int(args.get("cells", 64))
        if self.cells > SEQ_MASK:
            raise ValueError(f"cells > {SEQ_MASK} not encodable")
        self.count = int(args.get("count", 1))
        self.pause_ns = parse_time_ns(args.get("pause", "1 s"))
        self.retry_ns = parse_time_ns(args.get("retry", 0))
        self.downloads_done = 0
        self.cells_received = 0
        self._chunk_start = 0
        self._got = 0
        self._mask = 0
        self._gen = 0

    def _request_chunk(self, ctx) -> None:
        g, _m, _e = self._route(ctx, ctx.host_id)
        self._got = 0
        self._mask = 0
        self._gen += 1
        ctx.send(g, 64, (TAG_TOR_REQ, ctx.host_id, self._chunk_start,
                         self.cells))
        if self.retry_ns > 0:
            ctx.schedule(self.retry_ns, data=(self._gen,))

    def boot(self, ctx) -> None:
        if self.count > 0:
            self._request_chunk(ctx)

    def on_timer(self, ctx, data) -> None:
        d0 = data[0] if data else -1
        if d0 >= 0:
            if d0 == self._gen:           # chunk still outstanding
                self._request_chunk(ctx)
            return
        self._chunk_start = 0
        self._request_chunk(ctx)

    def on_packet(self, ctx, src_host, size, data) -> None:
        tag = data[0] if data else 0
        if tag != TAG_TOR_DATA:
            return
        # a DATA train: (circ, start, survivor mask). Only fresh
        # in-window bits advance the window — duplicates from a
        # premature retry must not complete a chunk (tgen rules)
        start, surv = data[2], data[3]
        chunk_len = min(CHUNK_CELLS, self.cells - self._chunk_start)
        shift = start - self._chunk_start
        if shift > 0:
            window = (surv << shift) & ((1 << chunk_len) - 1)
        else:
            window = (surv >> -shift) & ((1 << chunk_len) - 1)
        fresh = window & ~self._mask
        if not fresh:
            return                        # stale chunk / duplicates
        self._mask |= fresh
        got_add = fresh.bit_count()
        self._got += got_add
        self.cells_received += got_add
        if self._got < chunk_len:
            return
        nxt = self._chunk_start + chunk_len
        if nxt < self.cells:
            self._chunk_start = nxt
            self._request_chunk(ctx)
            return
        self.downloads_done += 1
        self._chunk_start = 0
        self._got = 0
        self._mask = 0
        self._gen += 1                    # invalidate pending retries
        if self.downloads_done < self.count:
            ctx.schedule(self.pause_ns, data=(-1,))
