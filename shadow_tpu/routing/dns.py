"""DNS: the simulation-wide name <-> address registry.

Equivalent of src/main/routing/dns.c: hosts register a unique name and
get a unique virtual IP — assigned sequentially while skipping reserved
CIDR ranges (dns.c:40-60) — or keep an explicitly requested IP if it is
valid and free. `write_hosts_file` emits the /etc/hosts-style file that
managed (real) processes resolve against.

Two registration paths share one allocator contract:

* ``register`` — the scalar path: one name, one Address object, dict
  entries for every lookup direction.
* ``register_block`` — the bulk path for model-only host groups
  (host/plane.py and the object build's model groups): ONE vectorized
  allocation grants the group's whole IP column and records a compact
  block (prefix, base id, count, ips) instead of ``count`` dict
  entries. Addresses materialize lazily on lookup. The block draws
  exactly the IPs ``count`` scalar calls would have drawn — both take
  the first assignable addresses at/after ``_next_ip`` in increasing
  order, then advance past the last grant — so mixing the two paths
  in one build stays bit-identical to an all-scalar build.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from shadow_tpu.routing.address import Address, int_to_ip, ip_to_int
from shadow_tpu.utils.slog import get_logger

log = get_logger("dns")

_RESERVED = [
    # (base, mask-bits): loopback, rfc1918, link-local, multicast+
    (ip_to_int("0.0.0.0"), 8),
    (ip_to_int("10.0.0.0"), 8),
    (ip_to_int("100.64.0.0"), 10),
    (ip_to_int("127.0.0.0"), 8),
    (ip_to_int("169.254.0.0"), 16),
    (ip_to_int("172.16.0.0"), 12),
    (ip_to_int("192.168.0.0"), 16),
    (ip_to_int("224.0.0.0"), 3),
]


def _is_reserved(ip: int) -> bool:
    for base, bits in _RESERVED:
        if (ip >> (32 - bits)) == (base >> (32 - bits)):
            return True
    return ip & 0xFF in (0, 255)          # network/broadcast-looking


def _reserved_mask(ips: np.ndarray) -> np.ndarray:
    """Vectorized ``_is_reserved`` over an int64 candidate window."""
    low = ips & 0xFF
    m = (low == 0) | (low == 255)
    for base, bits in _RESERVED:
        m |= (ips >> (32 - bits)) == (base >> (32 - bits))
    return m


@dataclass
class _Block:
    """One bulk-registered host group: names are ``{prefix}{i}`` for
    i in [0, count), ids are base_id + i, ips[i] is host i's address
    (strictly increasing — searchsorted resolves reverse lookups)."""

    prefix: str
    base_id: int
    count: int
    ips: np.ndarray


class Dns:
    def __init__(self):
        self._by_name: dict[str, Address] = {}
        self._by_ip: dict[int, Address] = {}
        self._by_id: dict[int, Address] = {}
        self._blocks: list[_Block] = []
        self._next_ip = ip_to_int("11.0.0.1")

    def _alloc_ip(self) -> int:
        ip = self._next_ip
        while _is_reserved(ip) or ip in self._by_ip:
            ip += 1
        self._next_ip = ip + 1
        return ip

    def _alloc_ips(self, n: int) -> np.ndarray:
        """The first ``n`` assignable IPs at/after ``_next_ip``, in
        increasing order — provably the sequence ``n`` scalar
        ``_alloc_ip`` calls produce, vectorized. Block IPs are always
        below ``_next_ip`` (allocation advances past them), so only
        explicitly-requested scalar IPs can occupy the window."""
        parts: list[np.ndarray] = []
        got = 0
        nxt = self._next_ip
        requested = np.array(
            [ip for ip in self._by_ip if ip >= nxt], dtype=np.int64)
        while got < n:
            # window with slack for the reserved skips (2 per /24 in
            # the unreserved space, plus whole reserved ranges)
            width = max(4096, (n - got) * 258 // 254 + 512)
            cand = np.arange(nxt, nxt + width, dtype=np.int64)
            ok = ~_reserved_mask(cand)
            if requested.size:
                ok &= ~np.isin(cand, requested)
            free = cand[ok][: n - got]
            parts.append(free)
            got += free.size
            nxt += width
        ips = parts[0] if len(parts) == 1 else np.concatenate(parts)
        self._next_ip = int(ips[-1]) + 1
        return ips

    def _block_entry(self, name: str) -> Optional[Address]:
        for b in self._blocks:
            if name.startswith(b.prefix):
                suf = name[len(b.prefix):]
                # generated names never carry leading zeros
                if suf.isdigit() and str(int(suf)) == suf \
                        and int(suf) < b.count:
                    i = int(suf)
                    return Address(host_id=b.base_id + i, name=name,
                                   ip=int(b.ips[i]))
        return None

    def _ip_in_blocks(self, ip: int) -> bool:
        for b in self._blocks:
            j = int(np.searchsorted(b.ips, ip))
            if j < b.count and int(b.ips[j]) == ip:
                return True
        return False

    def register(self, host_id: int, name: str,
                 requested_ip: Optional[str] = None) -> Address:
        if name in self._by_name or self._block_entry(name) is not None:
            raise ValueError(f"duplicate host name {name!r}")
        ip = None
        if requested_ip:
            try:
                cand = ip_to_int(requested_ip)
            except Exception:
                raise ValueError(
                    f"host {name!r}: invalid ip_address_hint "
                    f"{requested_ip!r}") from None
            if not _is_reserved(cand) and cand not in self._by_ip \
                    and not self._ip_in_blocks(cand):
                ip = cand
            else:
                log.warning("host %s: requested IP %s is reserved or "
                            "taken; auto-assigning", name, requested_ip)
        if ip is None:
            ip = self._alloc_ip()
        addr = Address(host_id=host_id, name=name, ip=ip)
        self._by_name[name] = addr
        self._by_ip[ip] = addr
        self._by_id[host_id] = addr
        return addr

    def register_block(self, base_id: int, prefix: str,
                       count: int) -> np.ndarray:
        """Bulk registration for a model-only host group: hosts
        ``{prefix}0 .. {prefix}{count-1}`` with ids ``base_id ..``
        get the next ``count`` sequential IPs in one vectorized
        allocation. Returns the [count] int64 IP column; Address
        objects materialize lazily on lookup."""
        if count == 1:
            # a single-host group's name has no index suffix: the
            # scalar path is both correct and just as cheap
            return np.array([self.register(base_id, prefix).ip],
                            dtype=np.int64)
        # scalar names are few: parse each against this prefix rather
        # than probing all `count` generated names
        risky = any(n.startswith(prefix) for n in self._by_name)
        for b in self._blocks:
            if b.prefix == prefix:
                raise ValueError(f"duplicate host group {prefix!r}")
            lo, hi = sorted((prefix, b.prefix), key=len)
            if hi.startswith(lo):
                # nested prefixes ("web" / "web1") CAN collide
                # ("web10"); only an exact probe settles it
                risky = True
        if risky:
            for i in range(count):
                probe = f"{prefix}{i}"
                if probe in self._by_name or \
                        self._block_entry(probe) is not None:
                    raise ValueError(f"duplicate host name {probe!r}")
        ips = self._alloc_ips(count)
        self._blocks.append(_Block(prefix=prefix, base_id=base_id,
                                   count=count, ips=ips))
        return ips

    def resolve_name(self, name: str) -> Optional[Address]:
        addr = self._by_name.get(name)
        return addr if addr is not None else self._block_entry(name)

    def resolve_ip(self, ip) -> Optional[Address]:
        if isinstance(ip, str):
            ip = ip_to_int(ip)
        addr = self._by_ip.get(ip)
        if addr is not None:
            return addr
        for b in self._blocks:
            j = int(np.searchsorted(b.ips, ip))
            if j < b.count and int(b.ips[j]) == ip:
                return Address(host_id=b.base_id + j,
                               name=f"{b.prefix}{j}", ip=ip)
        return None

    def address_of(self, host_id: int) -> Optional[Address]:
        addr = self._by_id.get(host_id)
        if addr is not None:
            return addr
        for b in self._blocks:
            if b.base_id <= host_id < b.base_id + b.count:
                i = host_id - b.base_id
                return Address(host_id=host_id,
                               name=f"{b.prefix}{i}",
                               ip=int(b.ips[i]))
        return None

    def write_hosts_file(self, path: str) -> None:
        entries = [(name, addr.ip)
                   for name, addr in self._by_name.items()]
        for b in self._blocks:
            entries.extend((f"{b.prefix}{i}", int(b.ips[i]))
                           for i in range(b.count))
        with open(path, "w") as f:
            f.write("127.0.0.1 localhost\n")
            for name, ip in sorted(entries):
                f.write(f"{int_to_ip(ip)} {name}\n")
