"""DNS: the simulation-wide name <-> address registry.

Equivalent of src/main/routing/dns.c: hosts register a unique name and
get a unique virtual IP — assigned sequentially while skipping reserved
CIDR ranges (dns.c:40-60) — or keep an explicitly requested IP if it is
valid and free. `write_hosts_file` emits the /etc/hosts-style file that
managed (real) processes resolve against.
"""

from __future__ import annotations

from typing import Optional

from shadow_tpu.routing.address import Address, int_to_ip, ip_to_int

_RESERVED = [
    # (base, mask-bits): loopback, rfc1918, link-local, multicast+
    (ip_to_int("0.0.0.0"), 8),
    (ip_to_int("10.0.0.0"), 8),
    (ip_to_int("100.64.0.0"), 10),
    (ip_to_int("127.0.0.0"), 8),
    (ip_to_int("169.254.0.0"), 16),
    (ip_to_int("172.16.0.0"), 12),
    (ip_to_int("192.168.0.0"), 16),
    (ip_to_int("224.0.0.0"), 3),
]


def _is_reserved(ip: int) -> bool:
    for base, bits in _RESERVED:
        if (ip >> (32 - bits)) == (base >> (32 - bits)):
            return True
    return ip & 0xFF in (0, 255)          # network/broadcast-looking


class Dns:
    def __init__(self):
        self._by_name: dict[str, Address] = {}
        self._by_ip: dict[int, Address] = {}
        self._by_id: dict[int, Address] = {}
        self._next_ip = ip_to_int("11.0.0.1")

    def _alloc_ip(self) -> int:
        ip = self._next_ip
        while _is_reserved(ip) or ip in self._by_ip:
            ip += 1
        self._next_ip = ip + 1
        return ip

    def register(self, host_id: int, name: str,
                 requested_ip: Optional[str] = None) -> Address:
        from shadow_tpu.utils.slog import get_logger
        log = get_logger("dns")

        if name in self._by_name:
            raise ValueError(f"duplicate host name {name!r}")
        ip = None
        if requested_ip:
            try:
                cand = ip_to_int(requested_ip)
            except Exception:
                raise ValueError(
                    f"host {name!r}: invalid ip_address_hint "
                    f"{requested_ip!r}") from None
            if not _is_reserved(cand) and cand not in self._by_ip:
                ip = cand
            else:
                log.warning("host %s: requested IP %s is reserved or "
                            "taken; auto-assigning", name, requested_ip)
        if ip is None:
            ip = self._alloc_ip()
        addr = Address(host_id=host_id, name=name, ip=ip)
        self._by_name[name] = addr
        self._by_ip[ip] = addr
        self._by_id[host_id] = addr
        return addr

    def resolve_name(self, name: str) -> Optional[Address]:
        return self._by_name.get(name)

    def resolve_ip(self, ip) -> Optional[Address]:
        if isinstance(ip, str):
            ip = ip_to_int(ip)
        return self._by_ip.get(ip)

    def address_of(self, host_id: int) -> Optional[Address]:
        return self._by_id.get(host_id)

    def write_hosts_file(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("127.0.0.1 localhost\n")
            for name, addr in sorted(self._by_name.items()):
                f.write(f"{int_to_ip(addr.ip)} {name}\n")
