from shadow_tpu.routing.packet import Packet, PacketStatus, Protocol
from shadow_tpu.routing.router import Router
from shadow_tpu.routing.queues import (
    CoDelQueue,
    SingleQueue,
    StaticQueue,
    make_router_queue,
)

__all__ = [
    "Packet", "PacketStatus", "Protocol",
    "Router", "CoDelQueue", "SingleQueue", "StaticQueue",
    "make_router_queue",
]
