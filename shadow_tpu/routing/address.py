"""Addresses: (ip, name, host id) tuples with order helpers.

Equivalent of src/main/routing/address.c: an immutable identity record
the DNS hands out; IPs are stored as host-order ints with dotted-quad
helpers (the reference keeps both byte orders cached).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass


def ip_to_int(ip: str) -> int:
    return int(ipaddress.IPv4Address(ip))


def int_to_ip(v: int) -> str:
    return str(ipaddress.IPv4Address(v))


@dataclass(frozen=True)
class Address:
    host_id: int
    name: str
    ip: int               # host byte order

    @property
    def ip_str(self) -> str:
        return int_to_ip(self.ip)

    def __str__(self) -> str:
        return f"{self.name}({self.ip_str})"
