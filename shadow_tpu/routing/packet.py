"""Packets.

Equivalent of the reference's refcounted Packet (src/main/routing/
packet.c/h): identity (src host + per-source packet id), protocol,
header fields, payload length, a priority for qdisc ordering, and a
delivery-status trail for debugging (packet.h:37-81, PDS_* flags).

Payload bytes: the device network model never needs them (latency,
loss, and ordering depend only on metadata), and model apps usually
count bytes rather than inspect them — so `payload` is optional bytes
kept host-side only, with `size` the authoritative length (mirroring
the reference's decision to copy payloads out of plugin memory lazily,
payload.c:25-48).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional


class Protocol(enum.IntEnum):
    LOCAL = 0
    UDP = 1
    TCP = 2


class PacketStatus(enum.IntFlag):
    """Delivery-status trail (packet.h PDS_* equivalents)."""

    NONE = 0
    SND_CREATED = 1 << 0
    SND_TCP_ENQUEUE_THROTTLED = 1 << 1
    SND_SOCKET_BUFFERED = 1 << 2
    SND_INTERFACE_SENT = 1 << 3
    INET_SENT = 1 << 4
    INET_DROPPED = 1 << 5
    ROUTER_ENQUEUED = 1 << 6
    ROUTER_DEQUEUED = 1 << 7
    ROUTER_DROPPED = 1 << 8
    RCV_INTERFACE_RECEIVED = 1 << 9
    RCV_INTERFACE_DROPPED = 1 << 10
    RCV_SOCKET_PROCESSED = 1 << 11
    RCV_SOCKET_DELIVERED = 1 << 12
    DESTROYED = 1 << 13
    RELAY_CACHED = 1 << 14
    RELAY_FORWARDED = 1 << 15


@dataclass
class TcpHeader:
    """TCP header fields the simulated stack uses (packet.h:20-33)."""

    flags: int = 0            # TcpFlags bitmask
    seq: int = 0              # sequence number of first payload byte
    ack: int = 0              # cumulative acknowledgement
    window: int = 0           # advertised receive window (bytes)
    src_port: int = 0
    dst_port: int = 0
    sack: tuple = ()          # selective-ack blocks ((start, end), ...)
    ts_val: int = 0
    ts_echo: int = 0


class TcpFlags(enum.IntFlag):
    NONE = 0
    RST = 1 << 0
    SYN = 1 << 1
    ACK = 1 << 2
    FIN = 1 << 3


@dataclass
class Packet:
    src_host: int
    packet_id: int            # per-source counter: (src_host, packet_id)
    dst_host: int
    protocol: Protocol
    size: int                 # payload bytes
    src_port: int = 0
    dst_port: int = 0
    priority: int = 0         # FIFO qdisc ordering (send time surrogate)
    tcp: Optional[TcpHeader] = None
    payload: Optional[bytes] = None
    status: PacketStatus = PacketStatus.NONE
    enqueue_time: int = -1    # set by router queues (CoDel sojourn)

    def add_status(self, s: PacketStatus) -> None:
        self.status |= s

    @property
    def header_size(self) -> int:
        from shadow_tpu import simtime
        if self.protocol == Protocol.TCP:
            return simtime.CONFIG_HEADER_SIZE_TCPIPETH
        if self.protocol == Protocol.UDP:
            return simtime.CONFIG_HEADER_SIZE_UDPIPETH
        return 0

    @property
    def total_size(self) -> int:
        return self.size + self.header_size
