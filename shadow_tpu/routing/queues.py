"""Router queue management: CoDel (default), single, static drop-tail.

Equivalents of the reference's three router-queue implementations
(src/main/routing/router_queue_codel.c, _single.c, _static.c). CoDel
follows RFC 8289 with the reference's parameters: 10 ms target sojourn,
100 ms interval, unbounded hard limit, and the inverse-sqrt control law
(router_queue_codel.c:36-48, 198-267).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Optional

from shadow_tpu import simtime
from shadow_tpu.routing.packet import Packet, PacketStatus

CODEL_TARGET_NS = 10 * simtime.SIMTIME_ONE_MILLISECOND
CODEL_INTERVAL_NS = 100 * simtime.SIMTIME_ONE_MILLISECOND


class RouterQueue:
    """vtable equivalent (router.h queue hooks)."""

    def enqueue(self, packet: Packet, now: int) -> bool:
        raise NotImplementedError

    def dequeue(self, now: int) -> Optional[Packet]:
        raise NotImplementedError

    def peek(self) -> Optional[Packet]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class SingleQueue(RouterQueue):
    """One-packet buffer (router_queue_single.c): a new arrival while
    occupied is dropped."""

    def __init__(self):
        self._slot: Optional[Packet] = None

    def enqueue(self, packet: Packet, now: int) -> bool:
        if self._slot is not None:
            packet.add_status(PacketStatus.ROUTER_DROPPED)
            return False
        packet.enqueue_time = now
        packet.add_status(PacketStatus.ROUTER_ENQUEUED)
        self._slot = packet
        return True

    def dequeue(self, now: int) -> Optional[Packet]:
        p, self._slot = self._slot, None
        if p is not None:
            p.add_status(PacketStatus.ROUTER_DEQUEUED)
        return p

    def peek(self) -> Optional[Packet]:
        return self._slot

    def __len__(self) -> int:
        return 0 if self._slot is None else 1


class StaticQueue(RouterQueue):
    """Fixed-capacity drop-tail FIFO (router_queue_static.c)."""

    def __init__(self, capacity: int = 1024):
        self._q: deque[Packet] = deque()
        self._capacity = capacity

    def enqueue(self, packet: Packet, now: int) -> bool:
        if len(self._q) >= self._capacity:
            packet.add_status(PacketStatus.ROUTER_DROPPED)
            return False
        packet.enqueue_time = now
        packet.add_status(PacketStatus.ROUTER_ENQUEUED)
        self._q.append(packet)
        return True

    def dequeue(self, now: int) -> Optional[Packet]:
        if not self._q:
            return None
        p = self._q.popleft()
        p.add_status(PacketStatus.ROUTER_DEQUEUED)
        return p

    def peek(self) -> Optional[Packet]:
        return self._q[0] if self._q else None

    def __len__(self) -> int:
        return len(self._q)


class CoDelQueue(RouterQueue):
    """Controlled Delay AQM, RFC 8289 (router_queue_codel.c)."""

    def __init__(self, target_ns: int = CODEL_TARGET_NS,
                 interval_ns: int = CODEL_INTERVAL_NS):
        self._q: deque[Packet] = deque()
        self.target = target_ns
        self.interval = interval_ns
        # control-law state (5 scalars — the device twin mirrors these,
        # shadow_tpu/device/netstate.py)
        self.first_above_time = 0
        self.drop_next = 0
        self.count = 0
        self.lastcount = 0
        self.dropping = False
        self.total_dropped = 0
        self._bytes = 0          # running backlog byte count

    def enqueue(self, packet: Packet, now: int) -> bool:
        packet.enqueue_time = now
        packet.add_status(PacketStatus.ROUTER_ENQUEUED)
        self._q.append(packet)       # infinite hard limit
        self._bytes += packet.total_size
        return True

    def _control_law(self, t: int, count: int) -> int:
        return t + int(self.interval / math.sqrt(max(1, count)))

    def _do_dequeue(self, now: int):
        """Returns (packet, ok_to_stay_in_drop_state)."""
        if not self._q:
            self.first_above_time = 0
            return None, False
        p = self._q.popleft()
        self._bytes -= p.total_size
        sojourn = now - p.enqueue_time
        if sojourn < self.target or not self._q_has_backlog():
            self.first_above_time = 0
            return p, False
        if self.first_above_time == 0:
            self.first_above_time = now + self.interval
            return p, False
        return p, now >= self.first_above_time

    def _q_has_backlog(self) -> bool:
        # the reference checks bytes > MTU; a single small packet
        # shouldn't hold the queue in the above-target state
        return self._bytes >= simtime.CONFIG_MTU

    def dequeue(self, now: int) -> Optional[Packet]:
        p, above = self._do_dequeue(now)
        if p is None:
            self.dropping = False
            return None
        if self.dropping:
            if not above:
                self.dropping = False
            elif now >= self.drop_next:
                while now >= self.drop_next and self.dropping:
                    p.add_status(PacketStatus.ROUTER_DROPPED)
                    self.total_dropped += 1
                    self.count += 1
                    p, above = self._do_dequeue(now)
                    if p is None:
                        self.dropping = False
                        return None
                    if not above:
                        self.dropping = False
                    else:
                        self.drop_next = self._control_law(
                            self.drop_next, self.count)
        elif above and (now - self.drop_next < self.interval
                        or now - self.first_above_time >= self.interval):
            p.add_status(PacketStatus.ROUTER_DROPPED)
            self.total_dropped += 1
            p, _ = self._do_dequeue(now)
            if p is None:
                self.dropping = False
                return None
            self.dropping = True
            if now - self.drop_next < self.interval:
                self.count = self.count - self.lastcount \
                    if self.count - self.lastcount > 1 else 1
            else:
                self.count = 1
            self.lastcount = self.count
            self.drop_next = self._control_law(now, self.count)
        if p is not None:
            p.add_status(PacketStatus.ROUTER_DEQUEUED)
        return p

    def peek(self) -> Optional[Packet]:
        return self._q[0] if self._q else None

    def __len__(self) -> int:
        return len(self._q)


def make_router_queue(kind: str, static_capacity: int = 1024
                      ) -> RouterQueue:
    if kind == "codel":
        return CoDelQueue()
    if kind == "single":
        return SingleQueue()
    if kind == "static":
        return StaticQueue(static_capacity)
    raise ValueError(f"unknown router queue {kind!r}")
