"""Router: the upstream-ISP buffer in front of a host's interface.

Equivalent of src/main/routing/router.c: arriving packets (after the
network model's latency/drop decision) enter the router's queue-
management discipline; the NetworkInterface drains it at the host's
download bandwidth. `forward` on the egress side hands packets to the
network model (the reference delegates to worker_sendPacket,
router.c:95-132).
"""

from __future__ import annotations

from typing import Callable, Optional

from shadow_tpu.routing.packet import Packet
from shadow_tpu.routing.queues import RouterQueue, make_router_queue


class Router:
    def __init__(self, queue: Optional[RouterQueue] = None,
                 kind: str = "codel", static_capacity: int = 1024):
        self.queue = queue or make_router_queue(kind, static_capacity)
        # NIC callback: poked on enqueue so an idle interface starts
        # its receive loop (router.c:103-121)
        self.on_enqueue: Optional[Callable[[int], None]] = None

    def enqueue(self, packet: Packet, now: int) -> bool:
        ok = self.queue.enqueue(packet, now)
        if ok and self.on_enqueue is not None:
            self.on_enqueue(now)
        return ok

    def dequeue(self, now: int) -> Optional[Packet]:
        return self.queue.dequeue(now)

    def peek(self) -> Optional[Packet]:
        return self.queue.peek()

    def __len__(self) -> int:
        return len(self.queue)
