"""Typed unit parsing for config values: "10 Mbit", "50 ms", "16 MiB".

Mirrors the semantics of the reference's units module (reference
src/main/core/support/units.rs:51-580): values are an integer (or decimal)
followed by an optional SI/IEC prefix and a base unit, with whitespace
allowed between number and unit. Bandwidth normalizes to bits/second, sizes
to bytes, times to nanoseconds.
"""

from __future__ import annotations

import re
from typing import Union

from shadow_tpu import simtime

_SI = {
    "": 1,
    "k": 10**3, "K": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
}
_IEC = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
}

_NS = simtime.SIMTIME_ONE_NANOSECOND
_US = simtime.SIMTIME_ONE_MICROSECOND
_MS = simtime.SIMTIME_ONE_MILLISECOND
_S = simtime.SIMTIME_ONE_SECOND
_MIN = simtime.SIMTIME_ONE_MINUTE
_H = simtime.SIMTIME_ONE_HOUR

_TIME_UNITS = {
    "ns": _NS,
    "nanosecond": _NS, "nanoseconds": _NS,
    "us": _US, "μs": _US,
    "microsecond": _US, "microseconds": _US,
    "ms": _MS,
    "millisecond": _MS, "milliseconds": _MS,
    "s": _S, "sec": _S, "secs": _S,
    "second": _S, "seconds": _S,
    "m": _MIN, "min": _MIN, "mins": _MIN,
    "minute": _MIN, "minutes": _MIN,
    "h": _H, "hr": _H, "hrs": _H,
    "hour": _H, "hours": _H,
}

_NUM_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([A-Za-zμ]*)\s*$")


def _split(value: str) -> tuple[float, str]:
    m = _NUM_RE.match(value)
    if not m:
        raise ValueError(f"cannot parse unit value: {value!r}")
    return float(m.group(1)), m.group(2)


def parse_time_ns(value: Union[str, int, float]) -> int:
    """Parse a time value to integer nanoseconds.

    Bare numbers are interpreted as seconds (matching the reference's
    config fields like stop_time, which default to seconds when unitless).
    """
    if isinstance(value, (int, float)):
        return int(round(value * _S))
    num, unit = _split(value)
    if unit == "":
        return int(round(num * _S))
    if unit not in _TIME_UNITS:
        raise ValueError(f"unknown time unit {unit!r} in {value!r}")
    return int(round(num * _TIME_UNITS[unit]))


def _parse_prefixed(value: str, bases: dict[str, int], kind: str) -> int:
    num, unit = _split(value)
    for base, scale in bases.items():
        if unit == base:
            return int(round(num * scale))
        for prefix, mult in _IEC.items():
            if unit == prefix + base:
                return int(round(num * mult * scale))
        for prefix, mult in _SI.items():
            if prefix and unit == prefix + base:
                return int(round(num * mult * scale))
    raise ValueError(f"cannot parse {kind} value: {value!r}")


def parse_size_bytes(value: Union[str, int, float]) -> int:
    """Parse a size value to bytes. Bare numbers are bytes."""
    if isinstance(value, (int, float)):
        return int(round(value))
    num, unit = _split(value)
    if unit == "":
        return int(num)
    return _parse_prefixed(value, {"B": 1, "byte": 1, "bytes": 1}, "size")


def parse_bandwidth_bits(value: Union[str, int, float]) -> int:
    """Parse a bandwidth value to bits/second. Bare numbers are bits/s.

    Accepts bit-based ("10 Mbit", "1 Gbit") and byte-based ("10 MB")
    spellings like the reference's units.rs (bandwidth is stored
    bit-normalized, units.rs:776-830).
    """
    if isinstance(value, (int, float)):
        return int(round(value))
    num, unit = _split(value)
    if unit == "":
        return int(num)
    try:
        return _parse_prefixed(
            value, {"bit": 1, "bits": 1, "bps": 1}, "bandwidth"
        )
    except ValueError:
        pass
    return 8 * _parse_prefixed(value, {"B": 1, "byte": 1, "bytes": 1}, "bandwidth")
