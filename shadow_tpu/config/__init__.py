from shadow_tpu.config.units import (
    parse_bandwidth_bits,
    parse_size_bytes,
    parse_time_ns,
)
from shadow_tpu.config.schema import (
    ConfigOptions,
    GeneralOptions,
    NetworkOptions,
    ExperimentalOptions,
    HostOptions,
    ProcessOptions,
)
from shadow_tpu.config.loader import load_config, load_config_str

__all__ = [
    "parse_bandwidth_bits",
    "parse_size_bytes",
    "parse_time_ns",
    "ConfigOptions",
    "GeneralOptions",
    "NetworkOptions",
    "ExperimentalOptions",
    "HostOptions",
    "ProcessOptions",
    "load_config",
    "load_config_str",
]
