"""Configuration schema.

YAML-compatible with the reference's config format (reference
src/main/core/support/configuration.rs:27-760 and
docs/shadow_config_spec.md): sections `general`, `network`, `experimental`,
and `hosts.<name>` with nested `processes`. New TPU-specific knobs live
under `experimental` (the reference's escape-hatch section) so existing
configs parse unchanged.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from shadow_tpu.config.units import (
    parse_bandwidth_bits,
    parse_time_ns,
    parse_size_bytes,
)

LOG_LEVELS = ("error", "warning", "info", "debug", "trace")

# Scheduler policies: the five CPU policies of the reference
# (scheduler_policy_type.h:26, configuration.rs:575) plus the new `tpu`
# policy that runs the network model on device.
SCHEDULER_POLICIES = (
    "host",          # thread-per-host set, per-host queues (host_single)
    "steal",         # work stealing (host_steal)
    "thread",        # thread_single
    "threadXthread",  # thread_perthread
    "threadXhost",   # thread_perhost
    "serial",        # single-threaded reference oracle (new)
    "tpu",           # JAX device engine; falls back to hybrid when the
                     # apps have no vectorized twin (new)
    "hybrid",        # CPU host emulation + device network judgment (new)
)

INTERPOSE_METHODS = ("preload", "ptrace", "model")


def _check_keys(section: str, d: dict, allowed: set[str]) -> None:
    """Reject unknown keys, like the reference's serde
    `deny_unknown_fields` on every config struct — a typo'd option must
    fail loudly, not silently keep its default."""
    unknown = set(d) - allowed
    if unknown:
        raise ValueError(
            f"unknown key(s) in {section}: {sorted(unknown)} "
            f"(allowed: {sorted(allowed)})"
        )


def _check_choice(section: str, name: str, value: str, choices) -> None:
    if value not in choices:
        raise ValueError(
            f"{section}.{name}={value!r} is not one of {list(choices)}"
        )


def _keyword_or_path(name: str, value, keywords: tuple,
                     path_hint: str, json_record: bool = False,
                     bool_words: tuple = ()) -> str:
    """The ONE keyword-vs-path validation for experimental knobs that
    accept a mode keyword OR a filesystem path (capacity_plan,
    compile_cache, strategy_plan — a new such knob joins here, not as
    a fourth copy of the typo-rejection logic): normalize YAML 1.1
    bare ``on``/``off`` booleans back to the knob's keywords
    (`bool_words` = (off_word, on_word)), reject non-string scalars
    with the knob's own message (never a TypeError from a path check),
    pass keywords through, and require anything else to LOOK like the
    kind of path the knob documents — ``.json`` record paths
    (`json_record`) or directory-ish paths (a separator or a leading
    ``./``/``~``/``/``). A typo'd keyword must fail at config load,
    not minutes later as a raw FileNotFoundError deep inside the
    run."""
    if bool_words and isinstance(value, bool):
        value = bool_words[1] if value else bool_words[0]
    kws = " / ".join(repr(k) for k in keywords)
    if not isinstance(value, str):
        raise ValueError(
            f"experimental.{name}: {value!r} is neither {kws} nor "
            f"{path_hint}")
    if value in keywords:
        return value
    looks_like_path = (value.endswith(".json") if json_record else
                       (os.sep in value
                        or value.startswith((".", "~", "/"))))
    if not looks_like_path:
        raise ValueError(
            f"experimental.{name}: {value!r} is neither {kws} nor "
            f"{path_hint}")
    return value


@dataclass
class ProcessOptions:
    """One virtual process (configuration.rs:478-503)."""

    path: str
    args: Any = ""
    environment: str = ""
    quantity: int = 1
    start_time: int = 0            # sim ns
    stop_time: Optional[int] = None

    @classmethod
    def from_dict(cls, d: dict) -> "ProcessOptions":
        _check_keys("process", d, {"path", "args", "environment", "quantity",
                                   "start_time", "stop_time"})
        return cls(
            path=d["path"],
            args=d.get("args", ""),
            environment=d.get("environment", ""),
            quantity=int(d.get("quantity", 1)),
            start_time=parse_time_ns(d.get("start_time", 0)),
            stop_time=(parse_time_ns(d["stop_time"])
                       if d.get("stop_time") is not None else None),
        )


@dataclass
class HostOptions:
    """One host group (configuration.rs:505+)."""

    name: str = ""
    quantity: int = 1
    bandwidth_down: Optional[int] = None   # bits/s; default from topology vertex
    bandwidth_up: Optional[int] = None
    network_node_id: Optional[int] = None  # pin to a topology vertex id
    # with network_node_id: host i of the group attaches at vertex
    # network_node_id + i * stride — O(1) placement for generated
    # million-vertex topologies (no per-host vertex scan)
    network_node_stride: int = 0
    ip_address_hint: Optional[str] = None
    country_code_hint: Optional[str] = None
    city_code_hint: Optional[str] = None
    log_level: Optional[str] = None
    pcap_directory: Optional[str] = None
    options: dict = field(default_factory=dict)
    processes: list[ProcessOptions] = field(default_factory=list)

    @classmethod
    def from_dict(cls, name: str, d: dict) -> "HostOptions":
        _check_keys(f"hosts.{name}", d, {
            "quantity", "bandwidth_down", "bandwidth_up", "network_node_id",
            "network_node_stride",
            "ip_address_hint", "ip_addr", "country_code_hint",
            "city_code_hint", "log_level", "pcap_directory", "options",
            "processes",
        })
        stride = int(d.get("network_node_stride", 0))
        if stride < 0:
            raise ValueError(
                f"hosts.{name}: network_node_stride must be >= 0")
        if stride > 0 and d.get("network_node_id") is None:
            raise ValueError(
                f"hosts.{name}: network_node_stride needs "
                "network_node_id (the stride's base vertex)")
        return cls(
            name=name,
            quantity=int(d.get("quantity", 1)),
            network_node_id=(int(d["network_node_id"])
                             if d.get("network_node_id") is not None
                             else None),
            network_node_stride=stride,
            bandwidth_down=(parse_bandwidth_bits(d["bandwidth_down"])
                            if d.get("bandwidth_down") is not None else None),
            bandwidth_up=(parse_bandwidth_bits(d["bandwidth_up"])
                          if d.get("bandwidth_up") is not None else None),
            ip_address_hint=d.get("ip_address_hint") or d.get("ip_addr"),
            country_code_hint=d.get("country_code_hint"),
            city_code_hint=d.get("city_code_hint"),
            log_level=d.get("log_level"),
            pcap_directory=d.get("pcap_directory"),
            options=dict(d.get("options", {})),
            processes=[ProcessOptions.from_dict(p)
                       for p in d.get("processes", [])],
        )


@dataclass
class GeneralOptions:
    """`general` section (configuration.rs:129-195)."""

    stop_time: int = 0                      # sim ns; required in practice
    seed: int = 1
    parallelism: int = 0                    # 0 => use all cores/devices
    bootstrap_end_time: int = 0             # unlimited bandwidth until here
    log_level: str = "info"
    heartbeat_interval: Optional[int] = None
    data_directory: str = "shadow.data"
    template_directory: Optional[str] = None
    progress: bool = False
    model_unblocked_syscall_latency: bool = False

    @classmethod
    def from_dict(cls, d: dict) -> "GeneralOptions":
        _check_keys("general", d, {
            "stop_time", "seed", "parallelism", "bootstrap_end_time",
            "log_level", "heartbeat_interval", "data_directory",
            "template_directory", "progress",
            "model_unblocked_syscall_latency",
        })
        return cls(
            stop_time=parse_time_ns(d.get("stop_time", 0)),
            seed=int(d.get("seed", 1)),
            parallelism=int(d.get("parallelism", 0)),
            bootstrap_end_time=parse_time_ns(d.get("bootstrap_end_time", 0)),
            log_level=d.get("log_level", "info"),
            heartbeat_interval=(parse_time_ns(d["heartbeat_interval"])
                                if d.get("heartbeat_interval") is not None
                                else None),
            data_directory=d.get("data_directory", "shadow.data"),
            template_directory=d.get("template_directory"),
            progress=bool(d.get("progress", False)),
            model_unblocked_syscall_latency=bool(
                d.get("model_unblocked_syscall_latency", False)),
        )


def _fault_from_dict(i: int, d: dict):
    """One `network.faults` entry -> a validated FaultEvent
    (shadow_tpu/faults.py). Structural validation happens here at
    config load; topology-dependent checks (the edge exists, down/up
    pairing, host names) happen at build time when the graph and host
    list exist."""
    from shadow_tpu.faults import (
        FAULT_KINDS,
        FaultEvent,
        HOST_KINDS,
        LINK_KINDS,
    )

    section = f"network.faults[{i}]"
    if not isinstance(d, dict):
        raise ValueError(f"{section} must be a mapping")
    _check_keys(section, d, {"kind", "time", "source", "target",
                             "duration", "latency_multiplier",
                             "extra_packet_loss", "host"})
    kind = d.get("kind")
    if kind not in FAULT_KINDS:
        raise ValueError(
            f"{section}.kind={kind!r} is not one of {list(FAULT_KINDS)}")
    if "time" not in d:
        raise ValueError(f"{section}: missing required key 'time'")
    if kind in LINK_KINDS:
        if d.get("source") is None or d.get("target") is None:
            raise ValueError(
                f"{section}: {kind} needs 'source' and 'target' "
                "topology vertex ids")
        if d.get("host") is not None:
            raise ValueError(
                f"{section}: 'host' is only valid for "
                f"{list(HOST_KINDS)}")
    else:
        if not d.get("host"):
            raise ValueError(
                f"{section}: {kind} needs 'host' (a configured host "
                "name, group-expanded like client0)")
        for bad in ("source", "target", "duration",
                    "latency_multiplier", "extra_packet_loss"):
            if d.get(bad) is not None:
                raise ValueError(
                    f"{section}: {bad!r} is only valid for link "
                    "faults")
    if kind != "degrade":
        for bad in ("duration", "latency_multiplier",
                    "extra_packet_loss"):
            if d.get(bad) is not None:
                raise ValueError(
                    f"{section}: {bad!r} is only valid for degrade")
    return FaultEvent(
        kind=kind,
        time=parse_time_ns(d["time"]),
        source=int(d["source"]) if d.get("source") is not None else -1,
        target=int(d["target"]) if d.get("target") is not None else -1,
        duration=(parse_time_ns(d["duration"])
                  if d.get("duration") is not None else 0),
        latency_multiplier=float(d.get("latency_multiplier", 1.0)),
        extra_packet_loss=float(d.get("extra_packet_loss", 0.0)),
        host=str(d.get("host", "")),
    )


@dataclass
class NetworkOptions:
    """`network` section (configuration.rs:199-213).

    graph.type is "gml" (with `file.path` or `inline`) or the builtin
    "1_gbit_switch" (configuration.rs:732-760). `faults` is the
    deterministic fault-injection schedule (shadow_tpu/faults.py):
    timed link_down/link_up/degrade edge events compiled into an
    epoch table at load, plus manager-side host_crash/host_restart.
    """

    graph_type: str = "1_gbit_switch"
    graph_file: Optional[str] = None
    graph_inline: Optional[str] = None
    # generator knobs (graph.type: star_clusters — topology/generate.py)
    graph_params: dict = field(default_factory=dict)
    use_shortest_path: bool = True
    # network.topology.representation: dense | hierarchical | auto —
    # how the all-pairs tables are stored (topology/graph.py; see
    # docs/topology.md). dense is the exact [V,V] baseline;
    # hierarchical factors through clusters (O(C^2 + V), required
    # beyond ~100k hosts) and REFUSES non-factorable graphs; auto
    # tries hierarchical and falls back to dense with a log line.
    representation: str = "dense"
    faults: list = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkOptions":
        _check_keys("network", d, {"graph", "use_shortest_path",
                                   "topology", "faults"})
        graph = d.get("graph", {}) or {}
        _check_keys("network.graph", graph, {
            "type", "file", "inline",
            # star_clusters generator surface
            "clusters", "spokes_per_cluster", "hub_latency",
            "access_latency", "hub_packet_loss", "access_packet_loss",
            "bandwidth_down", "bandwidth_up"})
        gtype = graph.get("type", "1_gbit_switch")
        gfile = None
        if isinstance(graph.get("file"), dict):
            gfile = graph["file"].get("path")
        elif isinstance(graph.get("file"), str):
            gfile = graph["file"]
        params = {k: graph[k] for k in (
            "clusters", "spokes_per_cluster", "hub_latency",
            "access_latency", "hub_packet_loss", "access_packet_loss",
            "bandwidth_down", "bandwidth_up") if k in graph}
        if params and gtype != "star_clusters":
            raise ValueError(
                "network.graph: generator keys "
                f"{sorted(params)} are only valid with "
                "type: star_clusters")
        topo = d.get("topology", {}) or {}
        _check_keys("network.topology", topo, {"representation"})
        rep = str(topo.get("representation", "dense"))
        if rep not in ("dense", "hierarchical", "auto"):
            raise ValueError(
                "network.topology.representation must be dense, "
                f"hierarchical or auto (got {rep!r})")
        raw_faults = d.get("faults") or []
        if not isinstance(raw_faults, list):
            raise ValueError("network.faults must be a list of fault "
                             "events")
        return cls(
            graph_type=gtype,
            graph_file=gfile,
            graph_inline=graph.get("inline"),
            graph_params=params,
            use_shortest_path=bool(d.get("use_shortest_path", True)),
            representation=rep,
            faults=[_fault_from_dict(i, f)
                    for i, f in enumerate(raw_faults)],
        )


@dataclass
class ExperimentalOptions:
    """`experimental` escape hatches (configuration.rs:230-392) plus the
    TPU engine's capacity/layout knobs (new)."""

    interpose_method: str = "model"
    # default flips to "tpu" once a config opts in; serial is the safe
    # universal default (the device engine requires jax devices)
    scheduler_policy: str = "serial"
    runahead: Optional[int] = None          # override lookahead window, ns
    use_cpu_pinning: bool = True
    # worker CONTEXTS for threaded policies; 0 = one per LP. When
    # workers > general.parallelism, the LogicalProcessors layer
    # multiplexes them (logical_processor.rs analogue)
    workers: int = 0
    use_memory_manager: bool = True
    use_seccomp: bool = True
    use_shim_syscall_handler: bool = True
    preload_spin_max: int = 8096
    interface_qdisc: str = "fifo"           # fifo | roundrobin
    interface_buffer: int = 1024 * 1024     # bytes
    socket_recv_buffer: int = 174760
    socket_send_buffer: int = 131072
    socket_recv_autotune: bool = True
    socket_send_autotune: bool = True
    tcp_congestion: str = "reno"            # tcp_cong.h algorithm name
    router_queue: str = "codel"             # codel | single | static
    router_static_capacity: int = 1024      # packets, for `static` queue
    # bandwidth + CoDel for RAW model-app sends (the socket path always
    # models bandwidth): the vectorizable fluid NIC that exists on both
    # the CPU and device engines (host/model_nic.py)
    model_bandwidth: bool = False
    # per-path packet counters (topology_incrementPathPacketCounter):
    # tracked by the CPU NetworkModel always; on the device engine
    # this opts into the flush-time [V,V] histogram (V^2 <= 65536)
    count_paths: bool = False

    # --- TPU engine knobs (new; absent from the reference) ---
    event_capacity: int = 64        # device event slots per host
    outbox_capacity: int = 32       # device packet sends per host per round
    # cross-shard exchange schedule: "all_to_all" (direct per-pair
    # buffers), "all_gather" (replicate whole outboxes; hub-heavy
    # traffic), "two_phase" (hierarchical intra-group then
    # inter-group schedule with aggregated per-phase buffers; skewed
    # sparse traffic), or "auto" (pick per workload from the measured
    # occupancy record — needs capacity_plan auto/<path> on a
    # multi-chip mesh, otherwise resolves to all_to_all). Traces are
    # bit-identical across variants (docs/exchange.md).
    exchange: str = "all_to_all"
    exchange_capacity: int = 0      # per shard-pair rows; 0 = auto-size
    # two_phase phase-2 (inter-group forward) buffer rows; 0 =
    # auto-size. Ignored by the other exchange variants.
    exchange_capacity2: int = 0
    # per-host arrivals accepted per flush (the merge-sort width is
    # event_capacity + this, so it is a first-order term of flush
    # cost); 0 = event_capacity. Too small fails LOUDLY via the
    # overflow counter — size it to the worst per-window fan-in
    # (e.g. every client of one server requesting in the same window)
    exchange_in_capacity: int = 0
    # per-host outbox rows surviving to the flush's global sort (the
    # outbox is mostly empty; compaction shrinks the flat sort from
    # H*outbox to H*this). 0 = off; too small fails loudly
    # (x_overflow). Size to the busiest host's sends+timers per phase.
    outbox_compact: int = 0
    # occupancy-driven capacity planning (device/capacity.py):
    # "static" keeps the hand-tuned knobs above; "auto" measures a
    # short warm-up slice and sizes every capacity from its occupancy
    # high-water marks; any other value is a path to a previously
    # written artifacts/OCC_*.json record. Non-static runs also
    # re-plan with doubled headroom and retry from the last
    # known-good state on a loud capacity overflow instead of
    # failing the run. Traces are bit-identical across capacity
    # choices whenever nothing overflows (tests pin it).
    capacity_plan: str = "static"
    # warm-up slice length for capacity_plan: auto (sim time;
    # 0 = stop_time / 8). It must reach real traffic — a slice that
    # ends before the first client start_time measures only boot.
    capacity_warmup: int = 0
    # network-judgment placement on the device engine: "auto" judges
    # the phase's outbox at flush on TPU (fewer ops in the pop loop)
    # and in-step on CPU; "flush"/"step" pin it. Bit-identical traces
    # either way.
    judge_placement: str = "auto"   # auto | flush | step
    # flush merge strategy: "global" regroups arrivals and re-sorts
    # the heaps in ONE double sort over [outbox | heap] rows keyed by
    # (dst host, time, src/seq) — no gathers, the right trade on TPU
    # where takes cost ~10 ms and multi-operand sorts ~3 ms; "window"
    # is the flat-sort + per-host window + row-merge path (the right
    # trade on one CPU core). "auto" picks by platform. Bit-identical
    # traces either way.
    merge_strategy: str = "auto"    # auto | global | window
    # pop head reads on the device engine: "onehot" replaces the pop
    # loop's take_along_axis head reads with one-hot masked
    # reductions (no gathers — the same trade as merge_strategy:
    # global, applied to the pop side); "gather" keeps
    # take_along_axis (cheaper on one CPU core). "auto" picks by
    # platform. Bit-identical traces either way.
    pop_strategy: str = "auto"      # auto | onehot | gather
    # topology-table lookups in the hoisted judge: "onehot" unrolls
    # the [V,V] lat/rel lookups into masked sums (no gather; V*V <=
    # 128 only), "gather" keeps indexed lookups. "auto" = gather
    # until the on-chip micro decides. Bit-identical either way.
    table_strategy: str = "auto"    # auto | onehot | gather
    # burst-pop lane width override (0 = the app's own declaration):
    # burst apps (tgen servers, tor relays) pop up to this many
    # consecutive in-window packet events per iteration, one send
    # lane each. Traces are width-invariant; the knob trades
    # per-iteration vector width (nearly free on TPU) against
    # iteration count (the serial cost). 1 disables bursting.
    burst_pops: int = 0
    # max simulated time per device dispatch (ns; 0 = unbounded):
    # long runs split into several invocations of the one compiled
    # program with identical traces (window clamping stays on the
    # global stop). Tunneled TPU relays kill executions that run for
    # minutes, so bench full runs bound each dispatch to a few
    # wall-seconds of work.
    dispatch_segment: int = 0
    # pipelined segment dispatch (device/supervise.py): how many
    # dispatch segments may be in flight on the device at once.
    # 0/1 = the serial issue-then-sync loop (byte-identical
    # behavior); N >= 2 = the issue half enqueues up to N segments
    # back-to-back while the drain half performs the blocking syncs,
    # validation, checkpoints, and heartbeats for the oldest — so
    # host-side boundary work overlaps device execution. The
    # compiled device program is untouched at ANY depth (pipelining
    # is pure host-side orchestration) and traces are bit-identical
    # to the serial loop (determinism_gate --pipelined pins depths
    # 1/2/4 against the serial oracle). Each in-flight segment pins
    # one state copy on device — memory scales with depth. Requires
    # scheduler_policy: tpu; recovery (overflow re-plan, transient
    # retry, audit, SIGTERM drain) discards the speculative window
    # and replays from the last validated state.
    pipeline_depth: int = 0
    # device-state checkpoint / resume (device/checkpoint.py; the
    # reference has no checkpoint at all — SURVEY §5). checkpoint_save
    # writes the full simulation state at checkpoint_save_time
    # (0 = at stop_time) and pauses the run there; checkpoint_load
    # resumes a saved state and runs on to stop_time. A paused+resumed
    # pair bit-matches the uninterrupted run (window clamping stays on
    # the global stop — the heartbeat-segmentation contract).
    checkpoint_save: str = ""
    checkpoint_save_time: int = 0
    checkpoint_load: str = ""
    # --- supervised runs (device/supervise.py) ---
    # periodic validated checkpointing: every `checkpoint_every` sim
    # ns of progress the run writes a rotating checkpoint
    # (<checkpoint_save>.t<ns>, atomic tmp+rename, last
    # `checkpoint_keep` retained), validated by the fingerprint/meta
    # machinery plus the state_audit health word when enabled — so a
    # corrupted checkpoint is never the one a crash-restart resumes
    # from. 0 = off (the end-of-run checkpoint_save semantics are
    # unchanged). checkpoint_load accepts the base path and resolves
    # to the newest readable rotation entry.
    checkpoint_every: int = 0
    checkpoint_keep: int = 3
    # compile the on-device invariant audit (engine.py AUD_* bits:
    # heap order, clock monotonicity, counter non-negativity, packet
    # conservation across exchange) into the round program. Cheap
    # (reductions + one scalar collective per round); off by default
    # — the un-audited program is byte-identical to before.
    state_audit: bool = False
    # persistent AOT compile cache (device/aotcache.py): "auto"
    # serializes the engine's compiled executables under
    # $SHADOW_TPU_AOT_DIR (default ~/.cache/shadow_tpu_aot) keyed by
    # the full program fingerprint, so repeat processes (supervised
    # restarts, failover re-runs, ensemble campaigns, CI rungs,
    # bench iterations) skip the 40s+ XLA compile; "off" disables;
    # any other value is the cache DIRECTORY path (it must look like
    # a path — contain a separator or start with ./ ~ / — so a
    # typo'd keyword fails at load, like capacity_plan). A cache hit
    # is bit-identical to a fresh compile, and an unreadable/stale
    # entry recompiles loudly (determinism_gate --compile-cache pins
    # both). Backends without executable serialization fall back to
    # JAX's built-in tracing cache (JAX_COMPILATION_CACHE_DIR).
    compile_cache: str = "auto"
    # total size cap for the cache directory, in MB; least-recently-
    # used entries are evicted past it
    compile_cache_cap_mb: int = 2048
    # transient-dispatch recovery: a device error matching the
    # transient markers (RESOURCE_EXHAUSTED, device unavailable, ...)
    # retries the failed segment from the last validated state up to
    # `dispatch_retries` CONSECUTIVE times (the counter resets when a
    # segment completes) with capped exponential backoff
    # (`dispatch_retry_backoff` seconds base, doubling, 30 s cap).
    dispatch_retries: int = 0
    dispatch_retry_backoff: float = 0.5
    # after exhausting retries — the failover LADDER
    # (docs/operations.md#failover): "abort" fails the run; "shrink"
    # probes the mesh for dead devices, re-shards the last validated
    # state onto the M survivors, re-plans exchange capacities for
    # the new geometry, and continues ON-DEVICE at M/N throughput
    # (bit-identical to the uninterrupted run — the mesh-shape
    # determinism contract), escalating to the hybrid rung only when
    # no shrink is possible (no dead device found, no survivor, or
    # the state is unrecoverable); "hybrid" saves the last validated
    # state to <checkpoint_save>.failover (kept for a device-side
    # resume) and re-runs on the hybrid backend with a loud
    # diagnostic — CPU host state is rebuilt from t=0 (device arrays
    # are not importable into CPU hosts), so the run finishes at the
    # cost of replaying the lost prefix. Ensemble campaigns may use
    # "shrink" (the replica axis vmaps outside the mesh axis and
    # survives intact); "hybrid" stays rejected for them (CPU host
    # emulation cannot vmap replicas).
    failover: str = "abort"
    # deterministic chaos injection (device/chaos.py,
    # docs/operations.md#chaos): a list of scripted fault points —
    # device_loss / dispatch_error at the k-th dispatch issue,
    # checkpoint_corrupt after the k-th rotation save,
    # cache_store_fail at the k-th cache store — fired at
    # deterministic seam counters so the same schedule reproduces
    # the identical run, failures included. This is how the failover
    # ladder is drilled in CI (determinism_gate --chaos) without
    # real hardware dying on cue.
    chaos: list = field(default_factory=list)
    # pin the device mesh to the first N available devices (0 = all):
    # the chaos gate's uninterrupted M-shard comparison runs, and any
    # workload that wants a submesh (a shrunken-geometry resume on a
    # healthy pool, capacity experiments), build their mesh here
    # instead of via XLA_FLAGS process-global forcing.
    mesh_shards: int = 0
    mesh_axis: str = "hosts"
    device_batch_rounds: int = 64   # rounds fused into one device while_loop
    # hybrid mode: which CPU policy drives host emulation while the
    # network model runs on device
    hybrid_cpu_policy: str = "serial"
    # adaptive judge: rounds with fewer pending packets than this are
    # judged synchronously on the CPU (one device dispatch costs
    # ~1-2 ms over a tunneled TPU; a CPU judgment costs ~10 us/pkt,
    # so small batches never pay for the trip). 0 = always device.
    hybrid_judge_min_batch: int = 192
    # wall-clock round watchdog (core/manager.py RoundWatchdog),
    # seconds; 0 = off. If a scheduling round makes no progress for
    # this long, dump per-host/per-process state (current blocked
    # syscall, quarantine counts) and abort with a diagnostic instead
    # of hanging forever. CPU policies only (the device engine's
    # rounds are bounded by max_rounds). Size the interval ABOVE any
    # legitimate in-round pause — in particular hybrid mode's first
    # device flush includes its XLA compile (tens of seconds on a
    # tunneled TPU), during which no event executes.
    round_watchdog: int = 0
    # where the watchdog ALSO writes its per-host/per-process stall
    # dump (atomic tmp+rename) when it fires — log lines scroll away
    # or get truncated by supervisors; the file survives for
    # post-mortem. "" = log only.
    round_watchdog_dump: str = ""
    # flight recorder (shadow_tpu/obs, docs/observability.md): "off"
    # records nothing (zero per-round work), "summary" (default)
    # accumulates per-phase wall attribution into SimStats.telemetry
    # (plus a recent-span ring for watchdog stall dumps), "trace"
    # additionally streams a JSONL span log and writes a
    # Perfetto-loadable TRACE_*.trace.json + METRICS_*.json record.
    # Tracing never perturbs the simulation: traces are bit-identical
    # across all three modes (determinism_gate --telemetry pins it).
    telemetry: str = "summary"
    # output DIRECTORY for the telemetry artifacts ("" = the
    # artifacts dir, honoring $SHADOW_TPU_OCC_DIR like OCC/ENSEMBLE
    # records). Setting it also makes `summary` mode write its
    # METRICS_*.json (by default only `trace` writes files).
    telemetry_path: str = ""
    # per-run artifacts DIRECTORY override for every record the run
    # writes by label/fingerprint-derived name — OCC occupancy
    # records, ENSEMBLE campaign records, METRICS/TRACE telemetry
    # ("" = "artifacts", honoring $SHADOW_TPU_OCC_DIR; an explicit
    # telemetry_path / ensemble.record_path still wins for its own
    # artifact). This is the multi-tenant namespacing seam: two
    # concurrent runs of the same workload derive the SAME canonical
    # filenames, so the campaign server points each tenant at
    # <spool>/campaigns/<cid>/artifacts and they can never clobber
    # each other's records.
    artifacts_dir: str = ""
    # loud wall-clock staleness detection on the supervise/ensemble
    # heartbeat cadence (device/supervise.py HeartbeatMonitor): a
    # gap wider than this many times the expected cadence (EWMA of
    # healthy gaps) warns loudly and counts into
    # SimStats.stale_heartbeats; the campaign server's watchdog
    # polls the same monitor to turn a wedged campaign into a
    # supervised kill + requeue instead of a wedged slot. 0 = off.
    heartbeat_stale_after: int = 0
    # telemetry-driven strategy plans (shadow_tpu/tune/,
    # docs/autotune.md): "off" ignores stored plans; "auto" adopts
    # the workload's PLAN_<app>_<H>_<fp>.json record (written by
    # scripts/tune.py next to the OCC records) when one exists; any
    # other value is an explicit plan path (must end in .json — a
    # typo'd keyword fails at load, like capacity_plan) whose
    # workload fingerprint must match this simulation (loud mismatch
    # refusal, never a silently wrong plan). Adoption changes WALL
    # time only: every knob in the plan space is individually
    # bit-identity-pinned, so a tuned run's traces equal the
    # default-knob run's (determinism_gate --tuned pins the
    # composition).
    strategy_plan: str = "off"
    # capacity-plan headroom factor override for capacity.plan's pad
    # rule (planned = ceil(measured * headroom) + slack): 0 keeps the
    # planner default (capacity.HEADROOM, 1.5). A tunable trade:
    # more headroom buys fewer overflow re-plans at the cost of
    # wider sorts and more ICI padding. Requires capacity_plan
    # auto/<path> (there is nothing to pad on a static run).
    capacity_headroom: float = 0.0
    # preflight resource admission (device/capacity.py footprint +
    # admission_verdict; docs/operations.md#admission): before any
    # compile, both runners estimate the per-device byte footprint
    # and compare it to the per-device budget. "auto" (default)
    # admits, statically degrades (pipeline_depth shrink, ensemble
    # replica batching), or admits loudly over budget — the runtime
    # degradation ladder is the backstop; "strict" refuses an
    # over-budget config with a readable diagnostic; "off" skips.
    admission: str = "auto"
    # per-device memory budget in bytes (size suffixes accepted:
    # "7.5 GiB") for backends that report none (cpu meshes, some
    # tunneled relays). A backend-reported bytes_limit wins when
    # present. 0 = no budget: admission auto skips, strict refuses.
    device_memory_budget: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "ExperimentalOptions":
        _check_keys("experimental", d,
                    {f.name for f in dataclasses.fields(cls)})
        out = cls()
        for f in dataclasses.fields(cls):
            if f.name in d:
                v = d[f.name]
                if f.name in ("runahead", "dispatch_segment",
                              "checkpoint_save_time",
                              "checkpoint_every",
                              "capacity_warmup"):
                    v = parse_time_ns(v)
                elif f.name in ("interface_buffer", "socket_recv_buffer",
                                "socket_send_buffer",
                                "device_memory_budget"):
                    v = parse_size_bytes(v)
                elif f.type == "int":
                    v = int(v)
                elif f.type == "float":
                    v = float(v)
                elif f.type == "bool":
                    v = bool(v)
                setattr(out, f.name, v)
        _check_choice("experimental", "scheduler_policy",
                      out.scheduler_policy, SCHEDULER_POLICIES)
        _check_choice("experimental", "interpose_method",
                      out.interpose_method, INTERPOSE_METHODS)
        _check_choice("experimental", "interface_qdisc",
                      out.interface_qdisc, ("fifo", "roundrobin"))
        _check_choice("experimental", "router_queue",
                      out.router_queue, ("codel", "single", "static"))
        _check_choice("experimental", "exchange",
                      out.exchange, ("all_gather", "all_to_all",
                                     "two_phase", "auto"))
        _check_choice("experimental", "judge_placement",
                      out.judge_placement, ("auto", "flush", "step"))
        _check_choice("experimental", "merge_strategy",
                      out.merge_strategy, ("auto", "global", "window"))
        _check_choice("experimental", "pop_strategy",
                      out.pop_strategy, ("auto", "onehot", "gather"))
        _check_choice("experimental", "table_strategy",
                      out.table_strategy, ("auto", "onehot", "gather"))
        if isinstance(out.telemetry, bool):
            # YAML 1.1 reads bare `off`/`on` as booleans — map them
            # back to the knob's keywords (the compile_cache rule);
            # `on` means the default-on mode, summary
            out.telemetry = "summary" if out.telemetry else "off"
        from shadow_tpu.obs.trace import MODES as TELEMETRY_MODES
        _check_choice("experimental", "telemetry",
                      out.telemetry, TELEMETRY_MODES)
        if not isinstance(out.telemetry_path, str):
            raise ValueError(
                f"experimental.telemetry_path: {out.telemetry_path!r} "
                "must be a directory path string")
        if not isinstance(out.artifacts_dir, str):
            raise ValueError(
                f"experimental.artifacts_dir: {out.artifacts_dir!r} "
                "must be a directory path string")
        if out.heartbeat_stale_after < 0:
            raise ValueError(
                "experimental.heartbeat_stale_after must be >= 0 "
                "(0 = staleness detection off; k = warn when a "
                "heartbeat gap exceeds k x the expected cadence)")
        from shadow_tpu.host.tcp import CONGESTION_ALGORITHMS
        _check_choice("experimental", "tcp_congestion",
                      out.tcp_congestion,
                      sorted(CONGESTION_ALGORITHMS))
        _check_choice("experimental", "hybrid_cpu_policy",
                      out.hybrid_cpu_policy,
                      [p for p in SCHEDULER_POLICIES
                       if p not in ("tpu", "hybrid")])
        if out.checkpoint_save_time and not out.checkpoint_save:
            raise ValueError(
                "experimental.checkpoint_save_time is set but "
                "checkpoint_save (the output path) is not — the "
                "pause time would be silently ignored")
        if out.capacity_plan != "static" and \
                out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.capacity_plan: occupancy-driven "
                "capacity planning sizes the DEVICE engine's buffers "
                "and requires scheduler_policy: tpu (CPU policies "
                "have no static capacities to plan)")
        if out.capacity_warmup < 0:
            raise ValueError(
                "experimental.capacity_warmup must be >= 0")
        # record paths always end in .json (capacity.record_path
        # writes OCC_*.json); the shared helper owns the typo
        # rejection
        out.capacity_plan = _keyword_or_path(
            "capacity_plan", out.capacity_plan, ("static", "auto"),
            "a path to a saved OCC_*.json occupancy record",
            json_record=True)
        if out.capacity_warmup and out.capacity_plan != "auto":
            raise ValueError(
                "experimental.capacity_warmup is set but "
                f"capacity_plan is {out.capacity_plan!r} — the "
                "warm-up slice only runs under capacity_plan: auto, "
                "so the knob would be silently ignored")
        # cache directories always look like paths — anything else
        # ("atuo", a bare number) is a typo'd mode that would
        # otherwise silently become a directory named after the typo;
        # YAML 1.1 bare off/on booleans normalize to the keywords
        out.compile_cache = _keyword_or_path(
            "compile_cache", out.compile_cache, ("auto", "off"),
            "a cache directory path (paths must contain a separator "
            "or start with './', '~', or '/')",
            bool_words=("off", "auto"))
        # strategy plans are .json records next to the OCC records
        # (tune/plan.py); same bool normalization as compile_cache
        out.strategy_plan = _keyword_or_path(
            "strategy_plan", out.strategy_plan, ("auto", "off"),
            "a path to a saved PLAN_*.json strategy record",
            json_record=True, bool_words=("off", "auto"))
        if out.capacity_headroom and out.capacity_headroom < 1.0:
            raise ValueError(
                "experimental.capacity_headroom must be 0 (planner "
                "default) or >= 1.0 — padding below the measured "
                "high-water mark would guarantee overflow re-plans")
        if out.capacity_headroom and out.capacity_plan == "static":
            raise ValueError(
                "experimental.capacity_headroom is set but "
                "capacity_plan is 'static' — the headroom factor "
                "only shapes planned capacities, so the knob would "
                "be silently ignored")
        if out.compile_cache_cap_mb < 1:
            raise ValueError(
                "experimental.compile_cache_cap_mb must be >= 1")
        if (out.checkpoint_save or out.checkpoint_load) and \
                out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.checkpoint_save/load: device-state "
                "checkpointing requires scheduler_policy: tpu (CPU "
                "policies execute managed OS processes, whose state "
                "is not checkpointable — the reference has the same "
                "limitation, i.e. no checkpoint at all)")
        _check_choice("experimental", "failover", out.failover,
                      ("abort", "shrink", "hybrid"))
        if isinstance(out.admission, bool):
            # YAML 1.1 reads bare `off`/`on` as booleans — map them
            # back to the knob's keywords (the telemetry rule); `on`
            # means the default-on mode, auto
            out.admission = "auto" if out.admission else "off"
        _check_choice("experimental", "admission", out.admission,
                      ("auto", "off", "strict"))
        if out.admission == "strict" and \
                out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.admission: strict gates DEVICE engine "
                "footprints and requires scheduler_policy: tpu (CPU "
                "policies have no device budget to admit against)")
        if out.device_memory_budget and out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.device_memory_budget bounds the DEVICE "
                "engine's footprint and requires scheduler_policy: "
                "tpu")
        if out.chaos:
            # the injector owns its schedule format — validate every
            # entry at load (the network.faults rule: a typo'd
            # schedule fails in milliseconds, never as a run that
            # silently injects nothing)
            from shadow_tpu.device.chaos import events_from_config
            out.chaos = events_from_config(out.chaos)
            if out.scheduler_policy != "tpu":
                raise ValueError(
                    "experimental.chaos injects faults at the DEVICE "
                    "supervise/engine seams and requires "
                    "scheduler_policy: tpu")
        if out.mesh_shards and out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.mesh_shards pins the DEVICE mesh and "
                "requires scheduler_policy: tpu (CPU policies have "
                "no mesh to pin)")
        if out.checkpoint_every:
            if not out.checkpoint_save:
                raise ValueError(
                    "experimental.checkpoint_every is set but "
                    "checkpoint_save (the rotation base path) is not "
                    "— periodic checkpoints would have nowhere to go")
            if out.checkpoint_save_time:
                raise ValueError(
                    "experimental.checkpoint_every cannot combine "
                    "with checkpoint_save_time: periodic supervision "
                    "runs to stop_time writing rotating checkpoints, "
                    "while checkpoint_save_time pauses the run at one "
                    "boundary — pick one")
        if out.state_audit and out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.state_audit compiles the invariant "
                "audit into the DEVICE round program and requires "
                "scheduler_policy: tpu")
        if (out.dispatch_retries or out.failover != "abort") and \
                out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.dispatch_retries/failover supervise "
                "DEVICE dispatches and require scheduler_policy: tpu")
        if out.pipeline_depth >= 2 and out.scheduler_policy != "tpu":
            raise ValueError(
                "experimental.pipeline_depth >= 2 pipelines DEVICE "
                "dispatch segments and requires scheduler_policy: "
                "tpu (CPU policies have no asynchronous dispatch to "
                "overlap)")
        if out.pipeline_depth > 64:
            raise ValueError(
                "experimental.pipeline_depth must be <= 64 — every "
                "in-flight segment pins a full device state copy, "
                "and depths past the segment count buy nothing")
        if out.dispatch_retry_backoff < 0:
            raise ValueError(
                "experimental.dispatch_retry_backoff must be >= 0")
        if out.model_bandwidth and out.judge_placement == "flush":
            raise ValueError(
                "experimental.judge_placement: flush cannot combine "
                "with model_bandwidth (the fluid NIC's tx/rx state "
                "is sequential per event; judgment stays in-step)")
        for name, minimum in (("event_capacity", 2),
                              ("dispatch_segment", 0),
                              ("pipeline_depth", 0),
                              ("checkpoint_save_time", 0),
                              ("checkpoint_every", 0),
                              ("checkpoint_keep", 1),
                              ("dispatch_retries", 0),
                              ("mesh_shards", 0),
                              ("outbox_capacity", 1),
                              ("exchange_capacity", 0),
                              ("exchange_capacity2", 0),
                              ("exchange_in_capacity", 0),
                              ("outbox_compact", 0),
                              ("burst_pops", 0),
                              ("device_batch_rounds", 1),
                              ("hybrid_judge_min_batch", 0),
                              ("round_watchdog", 0),
                              ("preload_spin_max", 0),
                              ("device_memory_budget", 0)):
            if getattr(out, name) < minimum:
                raise ValueError(
                    f"experimental.{name} must be >= {minimum}")
        if out.burst_pops > 32:
            raise ValueError(
                "experimental.burst_pops must be <= 32 (the per-lane "
                "checksum fold unrolls P-wide in the compiled step)")
        if out.burst_pops > 1 and out.model_bandwidth:
            raise ValueError(
                "experimental.burst_pops > 1 cannot combine with "
                "model_bandwidth (the fluid NIC's tx/rx state is "
                "sequential per event — the engine would silently "
                "degrade the requested width to 1)")
        return out


# ensemble vary axes: per-replica values that change array VALUES on
# device (seeds, topology tables, epoch times) — never shapes. Axes
# that would change shapes (host counts, capacities, stop_time) are
# deliberately not offered.
ENSEMBLE_VARY_AXES = ("seed", "latency_scale", "packet_loss_delta",
                      "fault_schedule")
ENSEMBLE_AGGREGATES = ("mean", "p5", "p95", "min", "max")


@dataclass
class EnsembleOptions:
    """`ensemble` section (new; no reference analogue): run R
    independent replicas of the device-twin workload in ONE compiled
    program (shadow_tpu/ensemble/), varying only array values per
    replica. Replica i is bit-identical to a standalone run with
    replica i's parameters (the campaign determinism contract,
    enforced by determinism_gate.py --ensemble)."""

    replicas: int = 1
    vary: dict = field(default_factory=dict)
    # named alternative link-fault schedules for vary.fault_schedule
    # (each a list of validated FaultEvents; "base" = the config's
    # network.faults schedule, "none" = fault-free)
    fault_schedules: dict = field(default_factory=dict)
    aggregate: tuple = ENSEMBLE_AGGREGATES
    record_path: str = ""        # "" = artifacts/ENSEMBLE_*.json
    # sequential replica batching (the ensembles' out-of-memory
    # story, and the degradation ladder's rung 2): 0 = the full
    # R-replica vmap in one program; k = run ceil(R/k) sequential
    # batches of <= k replicas each and merge the results — pinned
    # bit-identical to the full vmap (each replica's trace is the
    # standalone program's regardless of which batch carries it,
    # determinism_gate --degrade). Combines with supervised
    # checkpointing via checkpoint_save + checkpoint_every only:
    # each batch writes its own rotation series
    # (<save>.b<k>.t<ns>, stamped with the batch's replica window)
    # and a preempted campaign resumes by replaying completed
    # batches fresh (pure functions — bit-identical) and loading
    # the stamped batch's entry. checkpoint_save_time is rejected
    # (batches replay the full time range, so there is no single
    # campaign pause point).
    replica_batch: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "EnsembleOptions":
        from shadow_tpu.faults import LINK_KINDS

        _check_keys("ensemble", d, {"replicas", "vary",
                                    "fault_schedules", "aggregate",
                                    "record_path", "replica_batch"})
        if "replicas" not in d:
            raise ValueError("ensemble: missing required key "
                             "'replicas'")
        replicas = int(d["replicas"])
        if replicas < 1:
            raise ValueError("ensemble.replicas must be >= 1")
        raw_vary = d.get("vary") or {}
        if not isinstance(raw_vary, dict):
            raise ValueError("ensemble.vary must be a mapping of "
                             "axis -> per-replica value list")
        _check_keys("ensemble.vary", raw_vary, set(ENSEMBLE_VARY_AXES))
        if replicas > 1 and not raw_vary:
            raise ValueError(
                "ensemble: replicas > 1 with an empty vary block "
                "would run identical replicas — declare at least one "
                f"vary axis ({list(ENSEMBLE_VARY_AXES)})")
        vary: dict = {}
        for axis, vals in raw_vary.items():
            if not isinstance(vals, list) or len(vals) != replicas:
                raise ValueError(
                    f"ensemble.vary.{axis} must list exactly one "
                    f"value per replica ({replicas})")
            if axis == "seed":
                vary[axis] = [int(v) for v in vals]
            elif axis == "latency_scale":
                vary[axis] = [float(v) for v in vals]
                if any(v <= 0 for v in vary[axis]):
                    raise ValueError(
                        "ensemble.vary.latency_scale values must be "
                        "> 0")
            elif axis == "packet_loss_delta":
                vary[axis] = [float(v) for v in vals]
                if any(not (0.0 <= v <= 1.0) for v in vary[axis]):
                    raise ValueError(
                        "ensemble.vary.packet_loss_delta values must "
                        "be in [0, 1]")
            else:                        # fault_schedule
                vary[axis] = [str(v) for v in vals]
        raw_scheds = d.get("fault_schedules") or {}
        if not isinstance(raw_scheds, dict):
            raise ValueError("ensemble.fault_schedules must be a "
                             "mapping of name -> fault event list")
        schedules: dict = {}
        for name, evs in raw_scheds.items():
            if name in ("base", "none"):
                raise ValueError(
                    f"ensemble.fault_schedules: {name!r} is reserved "
                    "('base' = network.faults, 'none' = fault-free)")
            if not isinstance(evs, list):
                raise ValueError(
                    f"ensemble.fault_schedules.{name} must be a list "
                    "of fault events")
            events = [_fault_from_dict(i, e) for i, e in enumerate(evs)]
            bad = [e.kind for e in events if e.kind not in LINK_KINDS]
            if bad:
                raise ValueError(
                    f"ensemble.fault_schedules.{name}: {bad} are "
                    "manager-side host faults — ensemble campaigns "
                    "run on the device engine and only vary link "
                    f"faults ({list(LINK_KINDS)})")
            schedules[name] = events
        for name in vary.get("fault_schedule", ()):
            if name not in ("base", "none") and name not in schedules:
                raise ValueError(
                    f"ensemble.vary.fault_schedule names unknown "
                    f"schedule {name!r} (declare it under "
                    "ensemble.fault_schedules, or use 'base'/'none')")
        agg = d.get("aggregate")
        if agg is None:
            aggregate = ENSEMBLE_AGGREGATES
        else:
            if not isinstance(agg, list) or not agg:
                raise ValueError("ensemble.aggregate must be a "
                                 "non-empty list")
            for a in agg:
                _check_choice("ensemble", "aggregate", a,
                              ENSEMBLE_AGGREGATES)
            aggregate = tuple(agg)
        replica_batch = int(d.get("replica_batch", 0) or 0)
        if replica_batch < 0 or replica_batch > replicas:
            raise ValueError(
                f"ensemble.replica_batch must be in [0, replicas="
                f"{replicas}] (0 = full vmap; k = sequential batches "
                "of <= k replicas)")
        return cls(replicas=replicas, vary=vary,
                   fault_schedules=schedules, aggregate=aggregate,
                   record_path=str(d.get("record_path", "") or ""),
                   replica_batch=replica_batch)


@dataclass
class ConfigOptions:
    general: GeneralOptions = field(default_factory=GeneralOptions)
    network: NetworkOptions = field(default_factory=NetworkOptions)
    experimental: ExperimentalOptions = field(default_factory=ExperimentalOptions)
    hosts: list[HostOptions] = field(default_factory=list)
    ensemble: Optional[EnsembleOptions] = None

    @classmethod
    def from_dict(cls, d: dict) -> "ConfigOptions":
        _check_keys("config", d, {"general", "network", "experimental",
                                  "hosts", "host_option_defaults",
                                  "host_defaults", "ensemble"})
        hosts = [HostOptions.from_dict(name, hd or {})
                 for name, hd in (d.get("hosts", {}) or {}).items()]
        ensemble = (EnsembleOptions.from_dict(d["ensemble"])
                    if d.get("ensemble") else None)
        out = cls(
            general=GeneralOptions.from_dict(d.get("general", {}) or {}),
            network=NetworkOptions.from_dict(d.get("network", {}) or {}),
            experimental=ExperimentalOptions.from_dict(
                d.get("experimental", {}) or {}),
            hosts=hosts,
            ensemble=ensemble,
        )
        if ensemble is not None and \
                out.experimental.scheduler_policy != "tpu":
            raise ValueError(
                "ensemble: multi-replica campaigns run as one vmapped "
                "device program and require "
                "experimental.scheduler_policy: tpu (run replicas as "
                "separate processes on CPU policies)")
        if ensemble is not None and \
                out.experimental.failover == "hybrid":
            raise ValueError(
                "ensemble: experimental.failover: hybrid is not "
                "available for campaigns (CPU host emulation cannot "
                "vmap replicas) — use failover: shrink (campaigns "
                "survive device loss on-device; the replica axis "
                "vmaps outside the mesh axis), or let exhausted "
                "retries fail loudly with the last validated "
                "checkpoint on disk")
        if ensemble is not None and ensemble.replica_batch and \
                out.experimental.checkpoint_save_time:
            raise ValueError(
                "ensemble.replica_batch cannot combine with "
                "checkpoint_save_time: every sequential batch replays "
                "the full time range, so there is no single campaign "
                "pause point to save at — use checkpoint_every for "
                "supervised/preemptible batched campaigns")
        if ensemble is not None and ensemble.replica_batch and \
                out.experimental.checkpoint_save and \
                not out.experimental.checkpoint_every:
            raise ValueError(
                "ensemble.replica_batch with checkpoint_save needs "
                "checkpoint_every: a batched campaign never "
                "materializes the full-R stacked state, so the only "
                "checkpoints it can write are the per-batch rotation "
                "entries (<save>.b<k>.t<ns>) the supervised drain "
                "produces — without checkpoint_every the end-of-run "
                "save would be silently skipped")
        if out.experimental.heartbeat_stale_after and \
                not out.general.heartbeat_interval:
            raise ValueError(
                "experimental.heartbeat_stale_after is set but "
                "general.heartbeat_interval is 0 — staleness is "
                "measured on the [supervise-heartbeat] boundaries, "
                "so without a heartbeat cadence the knob would be "
                "silently ignored")
        return out

    def total_hosts(self) -> int:
        return sum(h.quantity for h in self.hosts)
