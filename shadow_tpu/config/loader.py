"""YAML config loading with CLI-style overrides.

Mirrors ConfigOptions::new merging of file + CLI values (reference
src/main/core/support/configuration.rs:81-124): the YAML file is parsed
first, then dotted-path overrides ("general.stop_time=10s") are applied
on the raw dict before schema conversion.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

import yaml

from shadow_tpu.config.schema import ConfigOptions


def _apply_override(raw: dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    node = raw
    for i, p in enumerate(parts[:-1]):
        node = node.setdefault(p, {})
        if not isinstance(node, dict):
            prefix = ".".join(parts[: i + 1])
            raise ValueError(
                f"override path {dotted!r}: {prefix!r} is not a section"
            )
    node[parts[-1]] = value


def load_config_str(text: str,
                    overrides: Optional[Iterable[str]] = None) -> ConfigOptions:
    raw = yaml.safe_load(text) or {}
    for ov in overrides or ():
        key, eq, val = ov.partition("=")
        if not eq:
            raise ValueError(f"override {ov!r} is not of the form KEY=VALUE")
        _apply_override(raw, key.strip(), yaml.safe_load(val))
    return ConfigOptions.from_dict(raw)


def load_config(path: str,
                overrides: Optional[Iterable[str]] = None) -> ConfigOptions:
    with open(path) as f:
        cfg = load_config_str(f.read(), overrides)
    plan = cfg.experimental.capacity_plan
    if plan not in ("static", "auto") and not os.path.isabs(plan):
        # a path-valued capacity_plan (a saved OCC_*.json occupancy
        # record) resolves relative to the config file; a value that
        # came in as a CLI override was typed against the launching
        # cwd, so when only the cwd candidate exists, use it
        cand = os.path.normpath(
            os.path.join(os.path.dirname(os.path.abspath(path)), plan))
        if not os.path.exists(cand) and os.path.exists(plan):
            cand = os.path.abspath(plan)
        cfg.experimental.capacity_plan = cand
    return cfg
