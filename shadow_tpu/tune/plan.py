"""Strategy-plan persistence and adoption.

A PLAN record is the tuner's durable output: the winning knob
assignment for one workload fingerprint, plus the trial ledger that
chose it, written atomically to ``PLAN_<app>_<H>_<fp>.json`` next to
the OCC records (same directory, same ``$SHADOW_TPU_OCC_DIR``
override, same fingerprint discipline — two traffic-shape variants of
one app never share a plan).

Adoption (``experimental.strategy_plan``):

* ``off``   — stored plans are ignored;
* ``auto``  — the workload's canonical plan path is consulted; no
  file, no change (production runs self-tune once a plan exists);
* ``<path>``— an explicit record; a missing file is a loud error.

Either way the record's workload stamp must match the simulation
(app class + fingerprint + host count — the OCC-record rule) or
adoption REFUSES loudly: a plan tuned for different traffic must
never silently steer this run. Knobs the operator hand-set (config
value differs from the schema default) win over the plan, logged per
knob — a plan assists defaults, it does not fight explicit
configuration.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from shadow_tpu.tune import space
from shadow_tpu.utils.slog import get_logger

log = get_logger("tune")

FORMAT = 1


def plan_path(app, n_hosts: int, directory: str = "") -> str:
    """Canonical PLAN record path for a workload: app class + host
    count + workload fingerprint, beside the OCC records."""
    from shadow_tpu.device import capacity

    directory = directory or os.environ.get("SHADOW_TPU_OCC_DIR",
                                            "artifacts")
    return os.path.join(
        directory,
        f"PLAN_{type(app).__name__}_{int(n_hosts)}"
        f"_{capacity.app_fingerprint(app)}.json")


def save_plan(record: dict, path: str) -> None:
    from shadow_tpu.obs import trace as obstrace
    from shadow_tpu.utils.artifacts import atomic_write_json

    atomic_write_json(record, path)
    obstrace.current().instant("plan.save", "plan", path=path)


def load_plan(path: str) -> dict:
    with open(path) as f:
        record = json.load(f)
    if record.get("format") != FORMAT:
        raise ValueError(
            f"strategy plan {path}: format {record.get('format')!r} "
            f"(this build reads format {FORMAT})")
    for key in ("workload", "knobs"):
        if key not in record:
            raise ValueError(f"strategy plan {path}: missing {key!r}")
    return record


def workload_stamp(app, n_hosts: int) -> dict:
    """The identity a plan is valid for — the OCC record's
    fingerprint discipline, reused verbatim."""
    from shadow_tpu.device import capacity

    return {"app": type(app).__name__,
            "app_fp": capacity.app_fingerprint(app),
            "n_hosts": int(n_hosts)}


def verify_workload(record: dict, app, n_hosts: int,
                    path: str = "") -> None:
    """Loud mismatch refusal: the record's workload stamp must match
    this simulation exactly. Shared by runner adoption AND bench's
    provenance stamping (bench must never stamp plan provenance from
    a fingerprint-mismatched file), so the two checks cannot
    drift."""
    want = workload_stamp(app, n_hosts)
    got = {k: record.get("workload", {}).get(k) for k in want}
    if got != want:
        raise ValueError(
            f"strategy plan {path or '<record>'} was tuned for "
            f"{got}; this simulation is {want} — re-tune with "
            "scripts/tune.py (plans never transfer across workload "
            "fingerprints)")


def resolve_plan(mode: str, app, n_hosts: int
                 ) -> tuple[Optional[dict], str]:
    """``experimental.strategy_plan`` -> (record, path) or
    (None, ""). ``auto`` with no canonical file is a silent no-op
    (the self-tuning default must not nag un-tuned workloads); an
    explicit path that is missing or mismatched is a loud error."""
    if mode == "off":
        return None, ""
    if mode == "auto":
        path = plan_path(app, n_hosts)
        if not os.path.exists(path):
            return None, ""
    else:
        path = mode
        if not os.path.exists(path):
            raise ValueError(
                f"experimental.strategy_plan: {path!r} does not "
                "exist (write one with scripts/tune.py, or use "
                "'auto' to adopt the canonical record only when "
                "present)")
    record = load_plan(path)
    verify_workload(record, app, n_hosts, path=path)
    return record, path


def adopt(cfg, app, n_hosts: int, n_shards: int = 0,
          policy: str = "") -> Optional[dict]:
    """Apply a stored plan onto a validated config (the runners call
    this before building their engine; the Controller's hybrid
    branch calls it with ``policy="hybrid"`` so the judge knob's
    gate sees the policy actually RUNNING, not the config's pre-
    fallback one). Returns the provenance dict
    (``SimStats.strategy_plan``) or None when nothing was adopted.

    Skip rules, each logged: a knob whose config value differs from
    the plan's tuned-from baseline (its recorded default, else the
    schema default) is hand-set and wins over the plan; a knob
    whose applicability gate fails on this run shape (plan tuned on
    a mesh, adopted on one chip) is dropped rather than misapplied.
    """
    record, path = resolve_plan(cfg.experimental.strategy_plan, app,
                                n_hosts)
    if record is None:
        return None
    ctx = space.context(cfg, n_shards=n_shards)
    if policy:
        ctx["policy"] = policy
    plan_defaults = record.get("default") or {}
    assignment, skipped = {}, {}
    for name, value in record["knobs"].items():
        knob = space.KNOB_BY_NAME.get(name)
        if knob is None:
            skipped[name] = "unknown knob (newer/older plan space)"
            continue
        if not knob.applies(cfg, ctx):
            skipped[name] = "not applicable to this run shape"
            continue
        section = cfg.experimental if knob.section == "experimental" \
            else cfg.general
        cur = getattr(section, knob.name)
        # "hand-set wins": the reference is the baseline the plan
        # was tuned FROM (its recorded default) when the record
        # carries one, else the schema default — cadence knobs only
        # exist on configs that set them, so their tuned-from value,
        # not the schema's zero, is what "untouched since tuning"
        # means
        ref = space.schema_default(knob)
        if name in plan_defaults:
            try:
                ref = knob.coerce(plan_defaults[name])
            except (TypeError, ValueError):
                pass
        if cur != ref:
            skipped[name] = (f"hand-set to {cur!r} in the config "
                             f"(the plan tuned from {ref!r})")
            continue
        assignment[name] = value
    applied = space.apply_assignment(cfg, assignment)
    for name, why in skipped.items():
        log.info("strategy plan: knob %s=%r skipped (%s)", name,
                 record["knobs"][name], why)
    prov = {
        "path": path,
        "workload": dict(record["workload"]),
        "knobs": applied,
        "skipped": skipped,
        "score": record.get("score"),
    }
    if applied:
        log.info("strategy plan adopted from %s: %s (tuned %s)",
                 path, applied, record.get("score") or "un-scored")
    else:
        log.info("strategy plan %s matched but every knob was "
                 "skipped (%s)", path, skipped or "empty plan")
    return prov


def revalidate_after_reshard(cfg, provenance, n_shards: int):
    """A mesh-shrink failover changed the run shape the adopted plan
    was tuned and gate-validated against (plans are fingerprinted
    per shard count). Every plan-space knob is individually
    bit-identity-pinned, so nothing already applied can corrupt the
    trace — but knobs whose applicability gate fails under the NEW
    shard count (an exchange schedule tuned for a wider mesh, a
    pipeline depth sized to the old segment cost) are now merely
    inherited, not tuned. Re-run each applied knob's gate under the
    new geometry and stamp the survivors/stale ones into the
    provenance (``SimStats.strategy_plan``), so post-shrink records
    never read as 'tuned for this mesh'. The exchange geometry
    itself is re-planned for real by the runner
    (DeviceRunner._replan_for_shrink) — this is the audit trail."""
    if not provenance:
        return provenance
    ctx = space.context(cfg, n_shards=n_shards)
    ctx["policy"] = "tpu"
    stale = {}
    for name in (provenance.get("knobs") or {}):
        knob = space.KNOB_BY_NAME.get(name)
        if knob is not None and not knob.applies(cfg, ctx):
            stale[name] = (f"tuned for the pre-shrink mesh; gate "
                           f"fails at n_shards={n_shards}")
    out = dict(provenance)
    out["resharded_to"] = int(n_shards)
    if stale:
        out["stale_after_reshard"] = stale
        log.warning(
            "strategy plan: knob(s) %s were tuned for the pre-shrink "
            "mesh and no longer pass their applicability gate at %d "
            "shard(s) — values stay (each is bit-identity-pinned) "
            "but the plan should be re-tuned for the new geometry "
            "(scripts/tune.py)", sorted(stale), n_shards)
    else:
        log.info("strategy plan re-validated after the mesh shrink: "
                 "every adopted knob still applies at %d shard(s)",
                 n_shards)
    return out
