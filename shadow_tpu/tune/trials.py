"""Trial harness: bounded-window runs that score a knob assignment.

Each trial is one REAL run of the workload through the normal
Controller path (device runs go through the supervise segmented-
advance loop, hybrid runs through the Manager) with a bounded
sim-time window, the candidate assignment applied via tune/space, and
every artifact redirected into a scratch directory — a trial must
never clobber the workload's production OCC/ENSEMBLE records or
checkpoints. Trials are WARM via the persistent AOT compile cache
(every trial process shares it), and the score subtracts the
compile/plan walls the flight recorder attributes — a reshaping
candidate must win on steady-state throughput, not lose on a one-time
compile the cache amortizes away.

Score: packets routed per second of scored wall. Diagnostics: the
tracer's per-phase walls ride every ledger entry, so a losing
candidate's record says WHERE it lost (dispatch vs judge vs exchange
vs checkpoint).

Safety: every trial's per-host signature must bit-match the
default-assignment run of the same window — the knobs are all
individually bit-identity-pinned, and this guard catches a
compositional violation before a plan can be written from it. A
diverging trial is disqualified loudly, never selected.

Search strategies:

* ``coordinate_descent`` — one knob at a time from the defaults,
  free runtime knobs first, repeated passes until a pass yields no
  improvement (early stopping) or the trial budget runs out;
* ``successive_halving`` — the assignment grid raced on a short
  window, top half survives to a doubled window, repeated to the
  full window (the budget-allowing mode: many candidates, few long
  runs).

Either way the winner must beat the full-window default baseline by
``min_gain`` or the plan keeps the defaults — a tuned plan is
no-slower-than-defaults by construction.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import shutil
import tempfile
import time
from dataclasses import dataclass, field

from shadow_tpu.tune import space
from shadow_tpu.utils.slog import get_logger

log = get_logger("tune")

# candidate-grid cap for successive halving: past this the harness
# falls back to single-knob variants (the grid is exponential in
# knob count; the ladder is not)
MAX_GRID = 48
# minimum relative throughput gain before a candidate unseats the
# incumbent (small windows are noisy; chasing <2% on them overfits)
MIN_GAIN = 0.02


@dataclass
class TrialResult:
    """One ledger entry: the assignment, its walls, and its score."""

    knobs: dict
    window_ns: int
    ok: bool = False
    wall_s: float = 0.0
    score_wall_s: float = 0.0
    packets: int = 0
    pkts_per_s: float = 0.0
    phases: dict = field(default_factory=dict)
    signature: str = ""
    error: str = ""

    def ledger(self) -> dict:
        """JSON-able trial record for the PLAN file."""
        out = {"knobs": dict(self.knobs),
               "window_ns": int(self.window_ns),
               "ok": bool(self.ok),
               "wall_s": round(self.wall_s, 3),
               "score_wall_s": round(self.score_wall_s, 3),
               "packets": int(self.packets),
               "pkts_per_s": round(self.pkts_per_s, 1)}
        if self.phases:
            out["phases"] = self.phases
        if self.error:
            out["error"] = self.error
        return out


@contextlib.contextmanager
def _scratch_artifacts(directory: str):
    """Redirect every artifact a trial writes (OCC records, ENSEMBLE
    records, telemetry files — all honor $SHADOW_TPU_OCC_DIR) into
    the trial's scratch directory."""
    prev = os.environ.get("SHADOW_TPU_OCC_DIR")
    os.environ["SHADOW_TPU_OCC_DIR"] = directory
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("SHADOW_TPU_OCC_DIR", None)
        else:
            os.environ["SHADOW_TPU_OCC_DIR"] = prev


def _signature(hosts) -> str:
    """One digest over the per-host signature tuple — the same
    surface the determinism gate compares."""
    import hashlib

    h = hashlib.sha256()
    for hh in hosts:
        h.update(repr((hh.name, hh.trace_checksum, hh.events_executed,
                       hh.packets_sent, hh.packets_dropped,
                       hh.packets_delivered)).encode())
    return h.hexdigest()[:16]


def run_trial(config_path: str, assignment: dict, window_ns: int,
              policy: str = "", workdir: str = "") -> TrialResult:
    """One scored run. `assignment` covers EVERY tuned knob (the
    harness always passes full assignments, so a ledger entry is
    self-describing); `policy` overrides the config's scheduler
    policy when set; `workdir` hosts the trial's data directory and
    redirected artifacts (a private tmpdir when empty)."""
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    res = TrialResult(knobs=dict(assignment), window_ns=int(window_ns))
    own_tmp = not workdir
    if own_tmp:
        workdir = tempfile.mkdtemp(prefix="shadow_tpu_trial_")
    try:
        cfg = load_config(config_path)
        if policy:
            cfg.experimental.scheduler_policy = policy
        cfg.general.stop_time = int(window_ns)
        cfg.general.data_directory = os.path.join(workdir,
                                                  "shadow.data")
        # trials measure candidates, they never consume a plan (a
        # stored plan would silently shift every trial's baseline)
        cfg.experimental.strategy_plan = "off"
        if cfg.experimental.telemetry == "off":
            # the per-phase walls are the score's input and the
            # ledger's diagnostic surface; summary mode adds no
            # device work, so forcing it cannot perturb the trial
            cfg.experimental.telemetry = "summary"
        if cfg.experimental.checkpoint_save:
            # checkpoint cadence is tunable, so supervision stays ON
            # in trials — but pointed at scratch, never at the
            # production rotation the config names
            cfg.experimental.checkpoint_save = os.path.join(
                workdir, "trial_ck.npz")
        if cfg.experimental.checkpoint_load:
            cfg.experimental.checkpoint_load = ""
        space.apply_assignment(cfg, assignment)
        t0 = time.perf_counter()
        with _scratch_artifacts(workdir):
            c = Controller(cfg)
            stats = c.run()
        res.wall_s = time.perf_counter() - t0
        res.packets = int(stats.packets_sent)
        res.signature = _signature(c.sim.hosts)
        tel = stats.telemetry or {}
        res.phases = dict(tel.get("phases") or {})
        total = float(tel.get("total_wall_s") or res.wall_s)
        # score on the steady-state wall: the compile and plan walls
        # are one-time costs the AOT cache / saved OCC record
        # amortize in production, and counting them would punish
        # every reshaping candidate for being new
        res.score_wall_s = max(
            1e-9, total - res.phases.get("compile_s", 0.0)
            - res.phases.get("plan_s", 0.0))
        res.pkts_per_s = res.packets / res.score_wall_s
        res.ok = bool(stats.ok) and not stats.preempted
        if not stats.ok:
            res.error = "run reported not-ok (overflow?)"
    except Exception as e:      # noqa: BLE001 — a failed candidate is
        # a disqualified ledger entry, never the end of the search
        res.error = f"{type(e).__name__}: {e}"
        log.warning("trial %s failed: %s", assignment, res.error)
    finally:
        if own_tmp:
            shutil.rmtree(workdir, ignore_errors=True)
    return res


class Tuner:
    """One search over one workload's plan space. Collects the full
    trial ledger; ``search()`` returns the pieces tune/plan.py
    persists."""

    def __init__(self, config_path: str, window_ns: int = 0,
                 budget: int = 24, min_gain: float = MIN_GAIN,
                 policy: str = ""):
        from shadow_tpu.config import load_config

        self.config_path = config_path
        self.cfg = load_config(config_path)
        self.policy = policy or self.cfg.experimental.scheduler_policy
        if self.policy not in ("tpu", "hybrid"):
            # the plan space is device-side; serial/thread configs
            # tune their device twin
            self.policy = "tpu"
        self.cfg.experimental.scheduler_policy = self.policy
        self.stop = int(self.cfg.general.stop_time)
        self.window = int(window_ns) or self.stop
        self.window = min(self.window, self.stop)
        self.budget = int(budget)
        self.min_gain = float(min_gain)
        self.ledger: list[TrialResult] = []
        self._baselines: dict[int, TrialResult] = {}
        n_shards = 0
        if self.policy == "tpu":
            from shadow_tpu._jax import jax
            n_shards = len(jax.devices())
        self.ctx = space.context(self.cfg, n_shards=n_shards)
        self.ctx["stop"] = self.window
        self.knobs = space.applicable(self.cfg, self.ctx)
        self.base = space.current(self.cfg, self.knobs)

    # -- bookkeeping ---------------------------------------------------
    @property
    def trials_run(self) -> int:
        return len(self.ledger)

    def _exhausted(self) -> bool:
        return self.trials_run >= self.budget

    def trial(self, assignment: dict, window_ns: int) -> TrialResult:
        t = run_trial(self.config_path, assignment, window_ns,
                      policy=self.policy)
        base = self._baselines.get(window_ns)
        if base is not None and t.ok and base.ok and \
                t.signature != base.signature:
            # the compositional bit-identity guard: every knob is
            # individually trace-invariant, so a diverging combo is a
            # bug — disqualify it loudly and keep searching
            t.ok = False
            t.error = ("trace diverged from the default-knob run — "
                       "disqualified (a strategy knob must never "
                       "change the simulation)")
            log.error("trial %s DIVERGED from the default-knob "
                      "signature at window %d ns", assignment,
                      window_ns)
        self.ledger.append(t)
        log.info("trial %d/%d window=%.3gs %s -> %s",
                 self.trials_run, self.budget, window_ns / 1e9,
                 assignment,
                 f"{t.pkts_per_s:,.0f} pkts/s" if t.ok else
                 f"FAILED ({t.error})")
        return t

    def baseline(self, window_ns: int) -> TrialResult:
        """The default-assignment reference for a window: the score
        to beat AND the signature every candidate must reproduce."""
        if window_ns not in self._baselines:
            t = run_trial(self.config_path, dict(self.base),
                          window_ns, policy=self.policy)
            self._baselines[window_ns] = t
            self.ledger.append(t)
            log.info("baseline window=%.3gs %s -> %s",
                     window_ns / 1e9, self.base,
                     f"{t.pkts_per_s:,.0f} pkts/s" if t.ok else
                     f"FAILED ({t.error})")
        return self._baselines[window_ns]

    # -- strategies ----------------------------------------------------
    def grid(self) -> list[dict]:
        """Deterministic candidate grid for successive halving: the
        cross product of every applicable knob's ladder, or (past
        MAX_GRID) the single-knob variants."""
        ladders = [(k, k.candidates(self.cfg, self.ctx))
                   for k in self.knobs]
        n = 1
        for _, cands in ladders:
            n *= max(1, len(cands))
        out = []
        if n <= MAX_GRID:
            names = [k.name for k, _ in ladders]
            for combo in itertools.product(
                    *[c for _, c in ladders]):
                out.append(dict(zip(names, combo)))
        else:
            for k, cands in ladders:
                for c in cands:
                    if c != self.base[k.name]:
                        out.append({**self.base, k.name: c})
        # the defaults always race too (they are the floor)
        if dict(self.base) not in out:
            out.insert(0, dict(self.base))
        return out

    def coordinate_descent(self) -> dict:
        """One knob at a time from the defaults (free knobs first),
        best candidate per knob, repeated passes until no pass
        improves (early stopping) or the budget is spent."""
        current = dict(self.base)
        best = self.baseline(self.window)
        if not best.ok:
            raise RuntimeError(
                f"default-knob baseline failed: {best.error}")
        for _ in range(3):                  # passes
            improved = False
            for knob in self.knobs:
                if self._exhausted():
                    break
                cands = [c for c in knob.candidates(self.cfg, self.ctx)
                         if c != current[knob.name]]
                knob_best = None
                for cand in cands:
                    if self._exhausted():
                        break
                    t = self.trial({**current, knob.name: cand},
                                   self.window)
                    if t.ok and (knob_best is None
                                 or t.pkts_per_s >
                                 knob_best.pkts_per_s):
                        knob_best = t
                if knob_best is not None and knob_best.pkts_per_s > \
                        best.pkts_per_s * (1 + self.min_gain):
                    best = knob_best
                    current = dict(knob_best.knobs)
                    improved = True
            if not improved or self._exhausted():
                break
        return current

    def successive_halving(self, grid: list = None) -> dict:
        """Race the grid on a quarter window, halve the field, double
        the window, repeat to the full window."""
        survivors = self.grid() if grid is None else grid
        windows = [w for w in (self.window // 4, self.window // 2,
                               self.window)
                   if w >= 1]
        if not windows:
            windows = [self.window]
        windows[-1] = self.window
        ranked: list[tuple[dict, TrialResult]] = []
        for i, w in enumerate(dict.fromkeys(windows)):
            # the rung's signature reference AND score floor — a
            # failed baseline would silently disable the divergence
            # guard for the whole rung, so it is fatal here exactly
            # as in coordinate_descent
            if not self.baseline(w).ok:
                raise RuntimeError(
                    f"default-knob baseline failed at window "
                    f"{w} ns: {self.baseline(w).error}")
            ranked = []
            for a in survivors:
                if self._exhausted():
                    break
                t = (self._baselines[w] if a == self.base
                     else self.trial(a, w))
                if t.ok:
                    ranked.append((a, t))
            if not ranked:
                break
            ranked.sort(key=lambda at: -at[1].pkts_per_s)
            keep = max(1, (len(ranked) + 1) // 2)
            survivors = [a for a, _ in ranked[:keep]]
            log.info("halving rung %d (window %.3gs): %d -> %d "
                     "candidate(s)", i + 1, w / 1e9, len(ranked),
                     len(survivors))
        return survivors[0] if survivors else dict(self.base)

    # -- entry ---------------------------------------------------------
    def search(self, strategy: str = "auto") -> dict:
        """Run the search; returns the PLAN record body (un-persisted
        — scripts/tune.py and the gate add the workload stamp and
        write it via tune/plan.py)."""
        # one discarded warm-up run before any scored trial: the
        # first run in a process pays backend init and other one-time
        # costs the per-phase subtraction cannot see, and the
        # baseline always runs first — without this it would lose to
        # every later candidate by exactly that bias
        run_trial(self.config_path, dict(self.base),
                  max(1, self.window // 4), policy=self.policy)
        if not self.knobs:
            log.warning("plan space is empty for this run shape "
                        "(policy %s, %d shard(s)) — writing a "
                        "defaults-only plan", self.policy,
                        self.ctx.get("n_shards", 0))
            chosen, strategy_used = dict(self.base), "none"
        else:
            grid = self.grid()
            if strategy == "auto":
                # halving pays off when the budget can race a real
                # grid through three rungs; otherwise descend
                strategy = ("successive_halving"
                            if self.budget >= 2 * len(grid)
                            and len(grid) > 3
                            else "coordinate_descent")
            if strategy == "coordinate_descent":
                chosen = self.coordinate_descent()
            elif strategy == "successive_halving":
                chosen = self.successive_halving(grid)
            else:
                raise ValueError(f"unknown search strategy "
                                 f"{strategy!r}")
            strategy_used = strategy
        base_t = self.baseline(self.window)
        if not base_t.ok:
            # without a good full-window baseline there is no score
            # floor and no signature reference — a plan must never
            # be written from an unguarded search
            raise RuntimeError(
                f"default-knob baseline failed: {base_t.error}")
        if chosen != self.base:
            final = next((t for t in reversed(self.ledger)
                          if t.ok and t.knobs == chosen
                          and t.window_ns == self.window), None)
            if final is None:
                final = self.trial(dict(chosen), self.window)
            if not final.ok or final.pkts_per_s <= \
                    base_t.pkts_per_s * (1 + self.min_gain):
                # no-slower-than-defaults by construction: a winner
                # that cannot beat the full-window baseline by the
                # margin is not a winner
                log.info("tuned candidate %s did not beat the "
                         "defaults at the full window (%.0f vs "
                         "%.0f pkts/s) — keeping the defaults",
                         chosen, final.pkts_per_s,
                         base_t.pkts_per_s)
                chosen, final = dict(self.base), base_t
        else:
            final = base_t
        return {
            "policy": self.policy,
            "strategy": strategy_used,
            "space": [k.name for k in self.knobs],
            "default": dict(self.base),
            "knobs": dict(chosen),
            "improved": chosen != self.base,
            "score": {
                "pkts_per_s": round(final.pkts_per_s, 1),
                "baseline_pkts_per_s": round(base_t.pkts_per_s, 1),
                "speedup": round(
                    final.pkts_per_s / base_t.pkts_per_s, 3)
                if base_t.pkts_per_s else None,
                "window_ns": self.window,
                "trials": self.trials_run,
            },
            "trials": [t.ledger() for t in self.ledger],
        }
