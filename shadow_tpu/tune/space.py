"""The strategy-plan space: a declared registry of tunable knobs.

Every knob the tuner may move is declared HERE, with its valid
candidate ladder, an applicability gate, and a flag for whether a
change reshapes the compiled program (a reshaping knob costs an XLA
compile — or an AOT cache load — per distinct value, so the trial
harness orders and budgets them differently from free runtime knobs).

The registry is the single source of truth three consumers share:

* the trial harness (tune/trials.py) enumerates candidates from it;
* plan adoption (tune/plan.py) applies a stored assignment through
  it — a knob absent from the registry can never enter a config via
  a PLAN file, and every value is re-coerced/validated on the way in
  (plan files are hand-editable JSON);
* the determinism gate's ``--tuned`` rung composes the most
  adversarial assignment from it to pin compositional bit-identity.

Inclusion rule: a knob joins the space only if it is individually
bit-identity-pinned (traces do not depend on it) — the tuner's
contract is that a plan changes WALL time only. Knobs that trade
identity for speed (burst_pops needs app support, capacities are the
capacity planner's job) stay out.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from shadow_tpu.utils.slog import get_logger

log = get_logger("tune")


@dataclass(frozen=True)
class Knob:
    """One tunable execution knob.

    ``candidates(cfg, ctx)`` returns the ordered value ladder to try
    (deduplicated, current value included); ``applies(cfg, ctx)``
    gates the knob on the run shape (policy, mesh size, whether the
    feature is on at all); ``coerce`` re-validates a stored value at
    adoption time. ``reshapes`` marks knobs whose change recompiles
    the device program (exchange schedule, planned capacities)."""

    name: str                   # config field name
    section: str                # "experimental" | "general"
    reshapes: bool
    description: str
    candidates: Callable        # (cfg, ctx) -> tuple
    applies: Callable           # (cfg, ctx) -> bool
    coerce: Callable            # raw -> validated value (raises)


def _coerce_time_ns(v) -> int:
    n = int(v)
    if n < 0:
        raise ValueError(f"negative time {v!r}")
    return n


def _coerce_nonneg_int(v) -> int:
    n = int(v)
    if n < 0:
        raise ValueError(f"negative count {v!r}")
    return n


def _coerce_exchange(v) -> str:
    # "auto" is never a CANDIDATE (a searched plan is the resolved
    # choice) but it must round-trip as a value: the base assignment
    # mirrors the config, and `exchange: auto` is a valid config —
    # a defaults-keeping plan for such a config stores "auto" and
    # adoption re-applies it unchanged
    valid = ("all_to_all", "all_gather", "two_phase", "auto")
    if v not in valid:
        raise ValueError(f"exchange {v!r} is not one of {list(valid)}")
    return v


def _coerce_headroom(v) -> float:
    f = float(v)
    if f != 0.0 and f < 1.0:
        raise ValueError(f"capacity_headroom {v!r} must be 0 or >= 1")
    return f


def _seg_candidates(cfg, ctx) -> tuple:
    """Dispatch-segment ladder relative to the workload's stop time:
    unbounded (one mega-dispatch), plus halves/quarters/eighths —
    the trade is per-dispatch host latency (fewer, longer segments)
    vs dispatch overlap with host-side work and checkpoint/retry
    granularity (more, shorter segments)."""
    stop = int(ctx["stop"])
    cur = int(cfg.experimental.dispatch_segment)
    ladder = [0, stop // 2, stop // 4, stop // 8]
    out = [cur] + [s for s in ladder if s > 0 or cur != 0]
    seen, uniq = set(), []
    for s in out:
        if s not in seen:
            seen.add(s)
            uniq.append(s)
    return tuple(uniq)


def _coerce_depth(v) -> int:
    n = int(v)
    if n < 0:
        raise ValueError(f"negative pipeline_depth {v!r}")
    if n > 64:
        raise ValueError(f"pipeline_depth {v!r} must be <= 64 "
                         "(each in-flight segment pins a device "
                         "state copy)")
    return n


def _pipeline_candidates(cfg, ctx) -> tuple:
    """Pipeline-depth ladder: serial (1), double-buffered (2), and a
    deep window (4). The trade is overlap of host-side boundary work
    with device rounds (deeper hides more) against device memory
    (each in-flight segment pins a state copy) and recovery replay
    distance. Depth never reshapes the compiled program and is
    bit-identity-pinned at every value (determinism_gate
    --pipelined), so it joins the space as a free runtime knob — the
    autotuner's biggest new lever on sync-bound meshes. A hand-set
    0 normalizes to 1 — advance() runs both as the identical serial
    loop, and two byte-identical trials would waste a full
    bounded-sim run per descent pass."""
    cur = max(1, int(cfg.experimental.pipeline_depth))
    return tuple(dict.fromkeys((cur, 1, 2, 4)))


def _judge_candidates(cfg, ctx) -> tuple:
    cur = int(cfg.experimental.hybrid_judge_min_batch)
    ladder = (0, 64, 192, 512, 1024)
    return tuple(dict.fromkeys((cur,) + ladder))


def _exchange_candidates(cfg, ctx) -> tuple:
    # the concrete schedules only — never "auto": candidates are the
    # things the search RESOLVES between. The config's current value
    # (possibly "auto") leads so the baseline assignment mirrors the
    # config exactly.
    cur = cfg.experimental.exchange
    return tuple(dict.fromkeys(
        (cur, "all_to_all", "all_gather", "two_phase"))) \
        if cur == "auto" else ("all_to_all", "all_gather",
                               "two_phase")


def _headroom_candidates(cfg, ctx) -> tuple:
    cur = float(cfg.experimental.capacity_headroom)
    return tuple(dict.fromkeys((cur, 0.0, 1.25, 2.0)))


def _ckpt_candidates(cfg, ctx) -> tuple:
    """Checkpoint cadence ladder: multiples of the configured
    interval (never below it — the configured cadence is the
    operator's durability floor, so the tuner may only trade MORE
    progress-at-risk for less checkpoint wall, explicitly)."""
    cur = int(cfg.experimental.checkpoint_every)
    stop = int(ctx["stop"])
    out = [cur]
    for m in (2, 4):
        c = cur * m
        if c < stop:
            out.append(c)
    return tuple(dict.fromkeys(out))


def _hb_candidates(cfg, ctx) -> tuple:
    """Heartbeat cadence: the configured interval and coarser
    multiples (each boundary costs per-host device_gets + log I/O).
    Never finer, and never off — the lines are the operator's live
    surface, the tuner only thins them."""
    cur = int(cfg.general.heartbeat_interval)
    stop = int(ctx["stop"])
    out = [cur]
    for m in (2, 4):
        c = cur * m
        if c < stop:
            out.append(c)
    return tuple(dict.fromkeys(out))


KNOBS: tuple[Knob, ...] = (
    Knob("dispatch_segment", "experimental", False,
         "max sim-time per device dispatch (ns; 0 = unbounded)",
         _seg_candidates,
         lambda cfg, ctx: ctx["policy"] == "tpu",
         _coerce_time_ns),
    Knob("pipeline_depth", "experimental", False,
         "in-flight dispatch segments (0/1 = serial issue+sync)",
         _pipeline_candidates,
         # device policies only: the pipeline lives in the device
         # runners' shared segmented-advance loop — the hybrid
         # policy's judge flushes have no segment window to overlap
         lambda cfg, ctx: ctx["policy"] == "tpu",
         _coerce_depth),
    Knob("hybrid_judge_min_batch", "experimental", False,
         "rounds smaller than this judge on the CPU, not the device",
         _judge_candidates,
         lambda cfg, ctx: ctx["policy"] == "hybrid",
         _coerce_nonneg_int),
    Knob("exchange", "experimental", True,
         "cross-shard exchange schedule",
         _exchange_candidates,
         lambda cfg, ctx: ctx["policy"] == "tpu"
         and ctx.get("n_shards", 1) > 1,
         _coerce_exchange),
    Knob("capacity_headroom", "experimental", True,
         "capacity-plan pad factor (0 = planner default 1.5)",
         _headroom_candidates,
         lambda cfg, ctx: ctx["policy"] == "tpu"
         and cfg.experimental.capacity_plan != "static",
         _coerce_headroom),
    Knob("checkpoint_every", "experimental", False,
         "rotating-checkpoint cadence (ns; only coarsened)",
         _ckpt_candidates,
         lambda cfg, ctx: ctx["policy"] == "tpu"
         and bool(cfg.experimental.checkpoint_every),
         _coerce_time_ns),
    Knob("heartbeat_interval", "general", False,
         "heartbeat cadence (ns; only coarsened)",
         _hb_candidates,
         lambda cfg, ctx: ctx["policy"] == "tpu"
         and bool(cfg.general.heartbeat_interval),
         _coerce_time_ns),
)

KNOB_BY_NAME = {k.name: k for k in KNOBS}


def context(cfg, n_shards: int = 0) -> dict:
    """The applicability context the gates read. ``n_shards`` comes
    from the caller (the runner knows its mesh; scripts/tune.py asks
    jax) — the space itself never touches a backend."""
    return {
        "policy": cfg.experimental.scheduler_policy,
        "stop": int(cfg.general.stop_time),
        "n_shards": int(n_shards),
    }


def applicable(cfg, ctx) -> list[Knob]:
    """The knobs this run shape can move, in registry order (free
    runtime knobs before reshaping ones — the coordinate-descent
    order that front-loads the cheap wins)."""
    free = [k for k in KNOBS if not k.reshapes and k.applies(cfg, ctx)]
    shaped = [k for k in KNOBS if k.reshapes and k.applies(cfg, ctx)]
    return free + shaped


def current(cfg, knobs) -> dict:
    """The config's current assignment over `knobs` — the hand-set /
    default baseline every trial and every adoption compares
    against."""
    out = {}
    for k in knobs:
        section = cfg.experimental if k.section == "experimental" \
            else cfg.general
        out[k.name] = getattr(section, k.name)
    return out


def schema_default(knob: Knob):
    """The knob's schema default (what an untouched config carries) —
    adoption uses it to tell hand-set values from defaults."""
    from shadow_tpu.config.schema import (
        ExperimentalOptions,
        GeneralOptions,
    )

    blank = (ExperimentalOptions() if knob.section == "experimental"
             else GeneralOptions())
    return getattr(blank, knob.name)


def apply_assignment(cfg, assignment: dict) -> dict:
    """Set an assignment's knobs onto a config (trial harness and
    plan adoption both funnel through here). Unknown knob names and
    invalid values fail loudly — PLAN files are hand-editable JSON
    and must never smuggle an unvalidated value into the engine.
    Returns the validated {name: value} actually applied."""
    applied = {}
    for name, raw in assignment.items():
        knob = KNOB_BY_NAME.get(name)
        if knob is None:
            raise ValueError(
                f"strategy plan names unknown knob {name!r} "
                f"(the plan space is {sorted(KNOB_BY_NAME)})")
        try:
            value = knob.coerce(raw)
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"strategy plan: invalid value for {name}: "
                f"{e}") from e
        section = cfg.experimental if knob.section == "experimental" \
            else cfg.general
        setattr(section, knob.name, value)
        applied[name] = value
    return applied


def reshaping(names) -> list[str]:
    """Which of `names` recompile the program when changed."""
    return [n for n in names
            if n in KNOB_BY_NAME and KNOB_BY_NAME[n].reshapes]
