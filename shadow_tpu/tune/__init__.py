"""Strategy autotuner (docs/autotune.md).

Telemetry-driven search over the engine's execution-strategy knobs,
per workload fingerprint, with the winner persisted next to the OCC
records so production runs self-tune:

* :mod:`shadow_tpu.tune.space`  — the declared registry of tunable
  knobs (valid ranges, whether each reshapes the compiled program);
* :mod:`shadow_tpu.tune.trials` — short bounded-sim-window trials
  through the normal Controller/supervise path, warm via the AOT
  cache, scored on pkts/s with the flight recorder's per-phase walls
  as the diagnostic surface; coordinate descent with early stopping,
  successive halving when the budget allows;
* :mod:`shadow_tpu.tune.plan`   — ``PLAN_<app>_<H>_<fp>.json``
  persistence and fingerprint-verified adoption
  (``experimental.strategy_plan: auto|off|<path>``).

The hard contract: a tuned plan changes WALL time only — every knob
in the space is individually bit-identity-pinned, and the tuner
preserves that compositionally (determinism_gate --tuned).
"""
