"""Command-line entry point.

Equivalent of the reference's CLI layer (src/main/core/main.c:133
main_runShadow + the clap-based CliOptions, configuration.rs:27-80):
parse CLI args, load + merge the YAML config, initialize logging, and
hand off to the Controller. `show-config` mirrors the reference's
--show-config debugging aid.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from shadow_tpu import simtime
from shadow_tpu.config import load_config
from shadow_tpu.utils import slog


def _config_to_jsonable(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _config_to_jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, list):
        return [_config_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _config_to_jsonable(v) for k, v in obj.items()}
    return obj


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="shadow-tpu",
        description="TPU-native discrete-event network simulator",
    )
    parser.add_argument("config", help="simulation config (YAML)")
    parser.add_argument("--show-config", action="store_true",
                        help="print the parsed config as JSON and exit")
    parser.add_argument("-o", "--option", action="append", default=[],
                        metavar="KEY=VALUE",
                        help="override a config value by dotted path, "
                             "e.g. -o general.stop_time=10s")
    parser.add_argument("--log-level", default=None,
                        choices=["error", "warning", "info", "debug", "trace"])
    args = parser.parse_args(argv)

    try:
        cfg = load_config(args.config, overrides=args.option)
    except (OSError, ValueError, KeyError) as e:
        print(f"shadow-tpu: failed to load config: {e}", file=sys.stderr)
        return 1

    if args.log_level:
        cfg.general.log_level = args.log_level
    slog.init_logging(cfg.general.log_level)

    if args.show_config:
        json.dump(_config_to_jsonable(cfg), sys.stdout, indent=2)
        print()
        return 0

    if cfg.general.stop_time <= 0:
        print("shadow-tpu: general.stop_time must be > 0", file=sys.stderr)
        return 1

    # Defer the heavy imports so `--show-config` stays fast.
    from shadow_tpu.core.controller import Controller

    controller = Controller(cfg)
    stats = controller.run()
    log = slog.get_logger("cli")
    log.info("simulation finished at %s: %s",
             simtime.format_time(stats.end_time), stats.summary())
    if stats.telemetry:
        # the flight recorder's where-did-the-wall-go pointer: the
        # detailed one-table breakdown is scripts/trace_report.py's
        # job; the log line names the dominant phase and artifacts
        files = stats.telemetry.get("files") or {}
        if files.get("metrics"):
            log.info("telemetry: dominant phase %s — full breakdown: "
                     "python scripts/trace_report.py %s",
                     stats.telemetry["dominant_phase"],
                     files["metrics"])
    if stats.ensemble is not None:
        # campaign summary: the per-replica breakdown + aggregates
        # live in the ENSEMBLE record (ensemble/campaign.py)
        rec = stats.ensemble
        log.info("ensemble campaign %s: %d replicas, aggregate "
                 "packets %d; per-replica checksums + "
                 "mean/p5/p95/min/max in the ENSEMBLE record",
                 rec["campaign"], rec["workload"]["replicas"],
                 stats.packets_sent)
    if stats.stale_heartbeats:
        # staleness detection (experimental.heartbeat_stale_after):
        # the run COMPLETED, but some heartbeat gaps blew past the
        # threshold — under the campaign server the watchdog would
        # have preempted + requeued; standalone, the operator should
        # know the run stalled even though it finished
        log.warning("%d stale heartbeat gap(s) during the run "
                    "(gaps > %dx the expected cadence) — the run "
                    "stalled between segment boundaries; see the "
                    "STALE HEARTBEAT warnings above",
                    stats.stale_heartbeats,
                    cfg.experimental.heartbeat_stale_after)
    if stats.preempted:
        # graceful preemption (device/supervise.py): the run is
        # incomplete but resumable — a DISTINCT rc so schedulers can
        # tell "resume me" (75, EX_TEMPFAIL) apart from success and
        # failure
        from shadow_tpu.device.supervise import EXIT_PREEMPTED
        log.warning("preempted at %s — resume with "
                    "experimental.checkpoint_load: %s (rc %d)",
                    simtime.format_time(stats.end_time),
                    stats.resume_path, EXIT_PREEMPTED)
        return EXIT_PREEMPTED
    return 0 if stats.ok else 1


if __name__ == "__main__":
    sys.exit(main())
