"""Candidate gatherless flush (double-sort merge) timed end-to-end.

Composes the full replacement for flat-sort + seg_take + merge at the
10k-rung shapes: one global sort of [outbox F | heap H*E] rows by
(host, t, k), segmented-scan ranks, stable re-sort by target slot,
reshape to [H, E]. Compares against the judge + the old path's
measured pieces. Also validates the construction against a numpy
oracle at a small shape.

Usage: python scripts/tpu_micro3.py [reps]
"""

from __future__ import annotations

import json
import signal
import sys
import time

sys.path.insert(0, ".")

H = 10000
OB = 36
E = 48
F = H * OB
N = F + H * E
BIG = (1 << 62)


def timed(label, fn, reps):
    from shadow_tpu._jax import jax
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"  [{label}] {1e3 * dt:.3f} ms/call", file=sys.stderr,
          flush=True)
    return round(1e3 * dt, 3)


def build(jnp, lax):
    INF = jnp.int64(1) << jnp.int64(62)

    def seg_scan_sum(flags_new, vals):
        """Segmented cumsum: resets at rows where flags_new is True."""
        def comb(a, b):
            af, av = a
            bf, bv = b
            return af | bf, jnp.where(bf, bv, av + bv)
        _, out = lax.associative_scan(comb, (flags_new, vals))
        return out

    def flush(ob_t, ob_host, ob_k, ob_m, ob_v, ob_w,
              ht, hk, hm, hv, hw, head):
        # heap rows: consumed slots (col < head) present as INF
        live = jnp.arange(E)[None, :] >= head[:, None]
        mt = jnp.where(live, ht, INF).reshape(-1)
        mk = jnp.where(live, hk, (1 << 62) - 1).reshape(-1)
        hrow = jnp.broadcast_to(
            jnp.arange(H, dtype=jnp.int32)[:, None], (H, E)) \
            .reshape(-1)
        gt = jnp.concatenate([ob_t, mt])
        gk = jnp.concatenate([ob_k, mk])
        gm = jnp.concatenate([ob_m, hm.reshape(-1)])
        gv = jnp.concatenate([ob_v, hv.reshape(-1)])
        gw = jnp.concatenate([ob_w, hw.reshape(-1)])
        ghost = jnp.concatenate([ob_host, hrow])

        # sort1: (host, t, k) — 3 keys, payload rides
        sh, st_, sk_, sm_, sv_, sw_ = lax.sort(
            (ghost, gt, gk, gm, gv, gw), num_keys=3)

        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), sh[1:] != sh[:-1]])
        rank = seg_scan_sum(is_new, jnp.ones(N, jnp.int32)) - 1
        kept = rank < E
        is_real = st_ < INF
        dropped_real = (~kept) & is_real
        # per-host dropped count rides to slot [h, 0] on the rank-0 row
        rev_new = jnp.concatenate(
            [(sh[1:] != sh[:-1]), jnp.ones((1,), bool)])
        rdrop = seg_scan_sum(rev_new[::-1],
                             dropped_real[::-1].astype(jnp.int32))[::-1]
        ov_carry = jnp.where(rank == 0, rdrop, 0)

        tgt = sh.astype(jnp.int64) * E + rank
        key2 = jnp.where(kept, tgt, BIG + jnp.arange(N,
                                                     dtype=jnp.int64))
        _, t2, k2, m2, v2, w2, ov2 = lax.sort(
            (key2, st_, sk_, sm_, sv_, sw_, ov_carry), num_keys=1)
        KEEP = H * E
        new_ht = t2[:KEEP].reshape(H, E)
        new_hk = k2[:KEEP].reshape(H, E)
        new_hm = m2[:KEEP].reshape(H, E)
        new_hv = v2[:KEEP].reshape(H, E)
        new_hw = w2[:KEEP].reshape(H, E)
        overflow = ov2[:KEEP].reshape(H, E)[:, 0]
        return new_ht, new_hk, new_hm, new_hv, new_hw, overflow

    return flush


def main() -> int:
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    signal.signal(signal.SIGALRM, lambda *a: sys.exit(9))
    signal.alarm(20 * 60)

    import numpy as np
    from shadow_tpu._jax import jax, jnp
    from jax import lax

    res = {"platform": jax.devices()[0].platform, "reps": reps}
    flush = jax.jit(build(jnp, lax))
    rng = np.random.default_rng(0)
    INF = np.int64(1) << np.int64(62)

    # realistic sparsity: ~2% of outbox rows valid
    valid = rng.random(F) < 0.02
    ob_t = np.where(valid, rng.integers(0, 1 << 40, F), INF) \
        .astype(np.int64)
    ob_host = np.where(valid, rng.integers(0, H, F),
                       np.int64(1 << 31)).astype(np.int64)
    ob_k = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_m = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_v = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_w = rng.integers(0, 1 << 30, F).astype(np.int64)
    # heap ~25% full
    ht = np.where(rng.random((H, E)) < 0.25,
                  rng.integers(0, 1 << 40, (H, E)), INF) \
        .astype(np.int64)
    ht = np.sort(ht, axis=1)
    hk = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hm = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hv = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hw = rng.integers(0, 1 << 30, (H, E)).astype(np.int64)
    head = rng.integers(0, 4, H).astype(np.int32)

    args = [jax.device_put(jnp.asarray(a)) for a in
            (ob_t, ob_host, ob_k, ob_m, ob_v, ob_w,
             ht, hk, hm, hv, hw, head)]
    res["gatherless_flush_ms"] = timed(
        "gatherless flush @10k", lambda: flush(*args), reps)

    # numpy oracle check at a tiny shape
    import importlib
    ok = check_small()
    res["small_oracle_ok"] = ok
    print(json.dumps(res), flush=True)
    return 0 if ok else 1


def check_small() -> bool:
    global H, OB, E, F, N
    H_, OB_, E_ = H, OB, E
    H, OB, E = 7, 5, 4
    F = H * OB
    N = F + H * E
    try:
        import numpy as np
        from shadow_tpu._jax import jax, jnp
        from jax import lax
        flush = jax.jit(build(jnp, lax))
        rng = np.random.default_rng(7)
        INF = np.int64(1) << np.int64(62)
        valid = rng.random(F) < 0.4
        ob_t = np.where(valid, rng.integers(0, 100, F), INF) \
            .astype(np.int64)
        ob_host = np.where(valid, rng.integers(0, H, F),
                           np.int64(1 << 31)).astype(np.int64)
        ob_k = rng.integers(0, 1 << 20, F).astype(np.int64)
        ht = np.where(rng.random((H, E)) < 0.6,
                      rng.integers(0, 100, (H, E)), INF) \
            .astype(np.int64)
        ht = np.sort(ht, axis=1)
        hk = rng.integers(0, 1 << 20, (H, E)).astype(np.int64)
        head = rng.integers(0, 2, H).astype(np.int32)
        z = np.zeros(F, np.int64)
        zh = np.zeros((H, E), np.int64)
        out = flush(*[jnp.asarray(a) for a in
                      (ob_t, ob_host, ob_k, z, z, z,
                       ht, hk, zh, zh, zh, head)])
        new_ht, new_hk = np.asarray(out[0]), np.asarray(out[1])
        ovf = np.asarray(out[5])
        # oracle
        for h in range(H):
            rows = []
            for j in range(E):
                if j >= head[h] and ht[h, j] < INF:
                    rows.append((int(ht[h, j]), int(hk[h, j])))
                elif j >= head[h]:
                    rows.append((int(INF), int(hk[h, j])))
            for i in range(F):
                if ob_host[i] == h:
                    rows.append((int(ob_t[i]), int(ob_k[i])))
            rows.sort()
            exp_drop = sum(1 for (t, _) in rows[E:] if t < INF)
            rows = rows[:E]
            got = [(int(new_ht[h, j]), int(new_hk[h, j]))
                   for j in range(len(rows))]
            if [r[0] for r in rows] != [g[0] for g in got]:
                print(f"host {h}: time mismatch {rows} vs {got}",
                      file=sys.stderr)
                return False
            if exp_drop != int(ovf[h]):
                print(f"host {h}: overflow {exp_drop} vs {ovf[h]}",
                      file=sys.stderr)
                return False
        return True
    finally:
        H, OB, E = H_, OB_, E_
        F = H * OB
        N = F + H * E


if __name__ == "__main__":
    sys.exit(main())
