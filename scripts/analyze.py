#!/usr/bin/env python
"""shadowlint driver — run the static determinism & cache-soundness
passes (shadow_tpu/analyze) and enforce the suppression baseline.

    python scripts/analyze.py                 # all three passes
    python scripts/analyze.py --pass jaxpr    # one pass
    python scripts/analyze.py --json out.json # machine-readable record
    python scripts/analyze.py --fix-hints     # name the repair per finding
    python scripts/analyze.py --write-baseline --reason "PR NN staging"

Exit codes: 0 = clean (no non-baselined error findings),
1 = new error findings (or stale suppressions under --strict-baseline),
2 = analyzer crash.

The jaxpr audit only TRACES programs (no compile, no dispatch), so
the driver is safe to run anywhere; it forces a 4-device CPU mesh by
default so cross-shard collectives actually lower (set XLA_FLAGS
yourself to override). docs/static_analysis.md documents the pass
taxonomy and the baseline workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# env before ANY jax import: the collective audit needs a multi-device
# mesh, and this tool must never dial a real TPU just to trace
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from shadow_tpu import analyze
    from shadow_tpu.analyze import findings as F

    ap = argparse.ArgumentParser(
        description="shadowlint: static determinism analysis")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=list(analyze.PASS_NAMES),
                    help="run only this pass (repeatable); default "
                         "all three")
    ap.add_argument("--json", metavar="PATH",
                    help="write the machine-readable findings record "
                         "(the CI workflow artifact)")
    ap.add_argument("--baseline", default=F.DEFAULT_BASELINE,
                    help="suppression baseline file (default: the "
                         "checked-in shadow_tpu/analyze/baseline.json)")
    ap.add_argument("--fix-hints", action="store_true",
                    help="print the named repair under each finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather the current findings into "
                         "--baseline instead of failing on them")
    ap.add_argument("--reason", default="",
                    help="reason recorded with --write-baseline "
                         "suppressions (required with it)")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="also fail on stale suppressions (baseline "
                         "hygiene for CI)")
    args = ap.parse_args()
    passes = args.passes or list(analyze.PASS_NAMES)

    findings, walls = [], {}
    for name in passes:
        t0 = time.perf_counter()
        found = analyze.run_pass(name)
        walls[name] = time.perf_counter() - t0
        print(f"pass {name}: {len(found)} finding(s) in "
              f"{walls[name]:.1f}s")
        findings.extend(found)

    if args.write_baseline:
        if not args.reason:
            print("FAIL: --write-baseline requires --reason")
            return 1
        F.write_baseline(args.baseline, findings, args.reason)
        print(f"baseline written: {args.baseline} "
              f"({len(findings)} suppression(s))")
        return 0

    baseline = F.load_baseline(args.baseline)
    new, suppressed, stale = F.apply_baseline(findings, baseline)
    # a --pass subset run cannot judge the other passes' suppressions
    # stale — their findings were never computed
    ran = tuple(analyze.PASS_CODE_PREFIX[p] for p in passes)
    stale = [s for s in stale if s["key"].startswith(ran)]
    rec = F.record(findings, new, suppressed, stale, passes, walls)
    if args.json:
        d = os.path.dirname(os.path.abspath(args.json))
        os.makedirs(d, exist_ok=True)
        with open(args.json, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"findings record: {args.json}")

    for f_ in new:
        print(f_.format(fix_hints=args.fix_hints))
    for s in suppressed:
        print(f"suppressed: {s['key']} (reason: {s['reason']})")
    for s in stale:
        print(f"stale suppression: {s['key']} — the finding is gone; "
              "remove it from the baseline")

    errors = [f_ for f_ in new if f_.severity == F.SEV_ERROR]
    rc = 0
    if errors:
        print(f"shadowlint: FAIL — {len(errors)} new error "
              f"finding(s) ({len(new) - len(errors)} warning(s), "
              f"{len(suppressed)} suppressed)")
        rc = 1
    elif stale and args.strict_baseline:
        print(f"shadowlint: FAIL — {len(stale)} stale "
              "suppression(s) under --strict-baseline")
        rc = 1
    else:
        print(f"shadowlint: OK — {len(findings)} finding(s), "
              f"{len(new)} new (warnings only), "
              f"{len(suppressed)} suppressed, {len(stale)} stale")
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:              # noqa: BLE001 — CLI boundary
        print(f"shadowlint: analyzer crash: {e}")
        raise SystemExit(2) from e
