"""On-chip micro #4: the round's REMAINING gathers + one-hot pop.

After the gatherless flush (micro3) and one-hot pop head reads, the
per-round gathers left on the fused path are the judge's topology
lookups (once per flush):
  a. host_vertex[dst]      — [H,OB] take from an [H_pad] i32 vector
  b. lat[srcv, dstv]       — [H,OB] take from a [V,V] table (V=6)
  c. one-hot alternative to (b): sum_j table[j] * (pair == j) over
     V*V=36 — pure VPU, no gather
  d. pop head reads at exact [H,E] shapes: take_along_axis vs the
     one-hot masked reduction (pop_strategy), P=1 and P=8
Times each with pipelined dispatches (amortized per-call overhead),
prints ONE JSON line. Shapes default to the 10k rung's.

Usage: python scripts/tpu_micro4.py [reps]
"""

from __future__ import annotations

import json
import signal
import sys
import time

sys.path.insert(0, ".")

H = 10000
OB = 40
E = 48
V = 6
P = 8
REPS = 30


def timed(label, fn, reps=None):
    from shadow_tpu._jax import jax
    if reps is None:
        reps = REPS         # read at call time: main() overrides it
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"  [{label}] {1e3 * dt:.3f} ms/call", file=sys.stderr,
          flush=True)
    return round(1e3 * dt, 3)


def main() -> int:
    global REPS
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else REPS
    REPS = reps
    signal.signal(signal.SIGALRM, lambda *a: sys.exit(9))
    signal.alarm(20 * 60)

    import numpy as np
    from shadow_tpu._jax import jax, jnp

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(7)
    host_vertex = jnp.asarray(rng.randint(0, V, H).astype(np.int32))
    lat = jnp.asarray(rng.randint(5e6, 1.4e8, (V, V)).astype(np.int64))
    dst = jnp.asarray(rng.randint(0, H, (H, OB)).astype(np.int32))
    srcv = jnp.asarray(rng.randint(0, V, H).astype(np.int32))[:, None]

    r = {"platform": platform, "H": H, "OB": OB, "E": E, "reps": reps}

    f_dstv = jax.jit(lambda d: host_vertex[jnp.clip(d, 0, H - 1)])
    r["a_hostvertex_gather"] = timed("a host_vertex[dst]",
                                     lambda: f_dstv(dst))
    dstv = f_dstv(dst)

    f_lat = jax.jit(lambda s, d: lat[s, d])
    r["b_table_gather"] = timed("b lat[srcv,dstv]",
                                lambda: f_lat(srcv, dstv))

    lat_flat = lat.reshape(-1)

    def onehot_lookup(s, d):
        pair = s * V + d                              # [H,OB]
        acc = jnp.zeros(pair.shape, jnp.int64)
        for j in range(V * V):
            acc = acc + jnp.where(pair == j, lat_flat[j],
                                  jnp.int64(0))
        return acc

    f_oh = jax.jit(onehot_lookup)
    r["c_table_onehot"] = timed("c one-hot table", lambda: f_oh(srcv,
                                                               dstv))
    assert bool(jnp.all(f_oh(srcv, dstv) == f_lat(srcv, dstv)))

    ht = jnp.asarray(
        np.sort(rng.randint(0, 1 << 40, (H, E)).astype(np.int64), 1))
    head = jnp.asarray(rng.randint(0, 4, H).astype(np.int64))
    INF = jnp.int64(1) << jnp.int64(62)

    def take_gather(arr, hd):
        v = jnp.take_along_axis(arr, jnp.minimum(hd, E - 1)[:, None],
                                axis=1)[:, 0]
        return jnp.where(hd < E, v, INF)

    def take_onehot(arr, hd):
        m = jnp.arange(E)[None, :] == hd[:, None]
        v = jnp.where(m, arr, jnp.zeros((), arr.dtype)).sum(axis=1)
        return jnp.where(hd < E, v, INF)

    fg, fo = jax.jit(take_gather), jax.jit(take_onehot)
    r["d_pop1_gather"] = timed("d pop P=1 gather", lambda: fg(ht, head))
    r["d_pop1_onehot"] = timed("d pop P=1 onehot", lambda: fo(ht, head))
    assert bool(jnp.all(fg(ht, head) == fo(ht, head)))

    offs = jnp.arange(P, dtype=head.dtype)

    def takeP_gather(arr, hd):
        idxs = hd[:, None] + offs
        v = jnp.take_along_axis(arr, jnp.minimum(idxs, E - 1), axis=1)
        return jnp.where(idxs < E, v, INF)

    def takeP_onehot(arr, hd):
        idxs = hd[:, None] + offs
        m = jnp.arange(E)[None, None, :] == idxs[:, :, None]
        v = jnp.where(m, arr[:, None, :],
                      jnp.zeros((), arr.dtype)).sum(axis=-1)
        return jnp.where(idxs < E, v, INF)

    fgP, foP = jax.jit(takeP_gather), jax.jit(takeP_onehot)
    r["d_pop8_gather"] = timed("d pop P=8 gather",
                               lambda: fgP(ht, head))
    r["d_pop8_onehot"] = timed("d pop P=8 onehot",
                               lambda: foP(ht, head))
    assert bool(jnp.all(fgP(ht, head) == foP(ht, head)))

    print(json.dumps(r))
    return 0


if __name__ == "__main__":
    sys.exit(main())
