"""Per-phase device-engine profile (VERDICT r3 next-step #2).

Runs a config's device twin with the engine's phase-split profiler and
prints ONE JSON line attributing wall time to pop-loop vs
exchange+merge vs host-probe sync, plus the fused-run rate for the
same slice for calibration (the split path pays per-call dispatch +
sync the fused while_loop does not).

Usage:
  python scripts/profile_device.py examples/tgen_10000.yaml [stop_s]
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")


def main() -> int:
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else \
        "examples/tgen_10000.yaml"
    stop_s = float(sys.argv[2]) if len(sys.argv) > 2 else 0.5

    from shadow_tpu import simtime
    from shadow_tpu._jax import jax
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config(cfg_path)
    cfg.experimental.scheduler_policy = "tpu"
    cfg.general.stop_time = simtime.from_seconds(stop_s)
    placement = sys.argv[3] if len(sys.argv) > 3 else None
    if placement:
        cfg.experimental.judge_placement = placement
    c = Controller(cfg)
    eng = c.runner.engine
    stop = simtime.from_seconds(stop_s)

    # fused-run calibration on the identical slice (compile + run)
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    fused_first = time.perf_counter() - t0
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    fused_s = time.perf_counter() - t0

    st = eng.init_state(c.sim.starts)
    prof = eng.profile(st, stop=stop)
    prof.pop("final_state")

    r = max(1, prof["rounds"])
    out = {
        "config": cfg_path,
        "platform": jax.devices()[0].platform,
        "slice_sim_s": stop_s,
        "fused_run_s": round(fused_s, 3),
        "fused_compile_plus_run_s": round(fused_first, 3),
        "fused_rounds": int(rounds),
        "split": {k: (round(v, 4) if isinstance(v, float) else v)
                  for k, v in prof.items()},
        "per_round_ms": {
            "pop": round(1e3 * prof["pop_s"] / r, 3),
            "flush": round(1e3 * prof["flush_s"] / r, 3),
            "probe": round(1e3 * prof["probe_s"] / r, 3),
            "fused_total": round(1e3 * fused_s / max(1, int(rounds)),
                                 3),
        },
        "phases_per_round": round(prof["phases"] / r, 2),
        "events_per_round": round(prof["events"] / r, 1),
    }
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
