#!/usr/bin/env python
"""Where did the wall go: one-table breakdown of a flight-recorder
run (shadow_tpu/obs, docs/observability.md).

Reads a ``METRICS_*.json`` summary (or a ``TRACE_*.jsonl`` span log,
aggregated on the fly) and prints the per-phase wall attribution —
host / judge / dispatch / exchange / checkpoint / retry / compile /
plan — with span counts, flags the dominant phase, and names the
lever it implicates. This is the concrete evidence the pipelining
and auto-tuning work cite: e.g. a dispatch-dominant tgen_100 run is
the per-round-dispatch-latency bottleneck MPMD overlap attacks.

Usage:
  python scripts/trace_report.py artifacts/METRICS_tpu_1000.json
  python scripts/trace_report.py artifacts/TRACE_tpu_1000.jsonl
  python scripts/trace_report.py --top 10 <file>   # slowest spans too
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from shadow_tpu.obs.trace import PHASES          # noqa: E402

# dominant phase -> the lever it implicates (the ROADMAP's open
# items), printed under the table so the report ends with an action
LEVERS = {
    "dispatch": "per-round dispatch latency dominates - the "
                "pipelined/MPMD-overlap dispatch lever (ROADMAP)",
    "host": "host-side Python dominates - batch more work per "
            "dispatch (dispatch_segment), or move the workload to "
            "the device twin",
    "judge": "hybrid judge batching dominates - raise "
             "hybrid_judge_min_batch or move hosts to a device twin",
    "exchange": "cross-shard exchange dominates - try exchange: auto "
                "/ two_phase with a capacity plan (docs/exchange.md)",
    "checkpoint": "checkpointing dominates - raise checkpoint_every "
                  "or shrink the state (docs/operations.md)",
    "retry": "retry/backoff waits dominate - the device/relay is "
             "unhealthy; see the dispatch error spans",
    "compile": "XLA compile dominates - warm the AOT cache "
               "(docs/compile_cache.md); repeat runs should hit",
    "plan": "capacity warm-up/re-plan dominates - save and reuse the "
            "OCC record (capacity_plan: <path>)",
}


def load_metrics(path: str) -> dict:
    """A METRICS_*.json summary, or one synthesized from a
    TRACE_*.jsonl span log (works on a hung run's .partial file
    too — the whole point of a streamed log)."""
    if path.endswith(".json"):
        with open(path) as f:
            m = json.load(f)
        if "phases" not in m:
            raise ValueError(
                f"{path} has no 'phases' key - not a METRICS record")
        return m
    walls: dict = {}
    counts: dict = {}
    spans = []
    n = 0
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # a SIGKILL/OOM tears the streamed log mid-line (the
                # writer's stdio buffer flushes on its own schedule
                # between explicit flushes) — the intact prefix IS
                # the post-mortem; a torn line must not abort it
                torn += 1
                continue
            n += 1
            # self_s where present: a span's bucket must not also
            # count the nested spans recorded inside it (the
            # tracer's own attribution rule)
            walls[rec["phase"]] = (walls.get(rec["phase"], 0.0)
                                   + rec.get("self_s", rec["dur_s"]))
            counts[rec["phase"]] = counts.get(rec["phase"], 0) + 1
            spans.append(rec)
    if not spans:
        raise ValueError(f"{path} holds no spans")
    if torn:
        print(f"note: {torn} unparseable line(s) skipped "
              "(truncated stream from a killed run?)",
              file=sys.stderr)
    # total = the last span's end offset (the log is stream-ordered);
    # host_s is the residual, exactly as the tracer computes it
    total = max(r["t0_s"] + r["dur_s"] for r in spans)
    phases = {f"{p}_s": round(walls.get(p, 0.0), 3)
              for p in PHASES if p != "host"}
    attributed = sum(phases.values())
    phases["host_s"] = round(max(0.0, total - attributed), 3)
    return {"mode": "jsonl", "total_wall_s": round(total, 3),
            "phases": phases, "spans": n,
            "span_counts": counts,
            "dominant_phase": max(phases, key=phases.get)[:-2],
            "_spans": spans}


def print_report(m: dict, top: int = 0) -> None:
    total = m["total_wall_s"] or 1e-12
    phases = m["phases"]
    counts = m.get("span_counts", {})
    run = m.get("run") or {}
    title = " ".join(f"{k}={v}" for k, v in run.items())
    print(f"flight-recorder report ({m.get('mode', '?')} mode"
          f"{', ' + title if title else ''})")
    print(f"total wall: {m['total_wall_s']:.3f}s over "
          f"{m.get('spans', '?')} span(s)")
    print()
    print(f"  {'phase':<12} {'wall_s':>10} {'share':>7} {'spans':>7}")
    print(f"  {'-' * 12} {'-' * 10} {'-' * 7} {'-' * 7}")
    rows = sorted(phases.items(), key=lambda kv: -kv[1])
    for key, wall in rows:
        phase = key[:-2]
        print(f"  {phase:<12} {wall:>10.3f} {wall / total:>6.1%} "
              f"{counts.get(phase, '-'):>7}")
    print(f"  {'-' * 12} {'-' * 10} {'-' * 7} {'-' * 7}")
    print(f"  {'sum':<12} {sum(phases.values()):>10.3f} "
          f"{sum(phases.values()) / total:>6.1%}")
    dom = m.get("dominant_phase") or rows[0][0][:-2]
    print()
    print(f"dominant phase: {dom} "
          f"({phases.get(dom + '_s', 0.0):.3f}s, "
          f"{phases.get(dom + '_s', 0.0) / total:.1%} of wall)")
    lever = LEVERS.get(dom)
    if lever:
        print(f"  -> {lever}")
    if m.get("dropped_spans"):
        print(f"note: {m['dropped_spans']} span(s) dropped from the "
              "in-memory list (JSONL log is complete)")
    if top and m.get("_spans"):
        slow = sorted(m["_spans"], key=lambda r: -r["dur_s"])[:top]
        print()
        print(f"slowest {len(slow)} span(s):")
        for r in slow:
            window = ""
            if "sim_t0" in r:
                window = (f"  sim=({r['sim_t0']}, "
                          f"{r.get('sim_t1', '?')}] ns")
            print(f"  {r['dur_s']:8.3f}s  {r['phase']:<10} "
                  f"{r['name']}{window}")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="per-phase wall breakdown of a flight-recorder "
                    "run")
    ap.add_argument("path", help="METRICS_*.json or TRACE_*.jsonl "
                                 "(.partial accepted)")
    ap.add_argument("--top", type=int, default=0,
                    help="also list the N slowest spans (jsonl input "
                         "only)")
    args = ap.parse_args()
    try:
        m = load_metrics(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_report: cannot read {args.path}: {e}",
              file=sys.stderr)
        return 1
    print_report(m, top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
