#!/usr/bin/env python
"""Where did the wall go: one-table breakdown of a flight-recorder
run (shadow_tpu/obs, docs/observability.md).

Reads a ``METRICS_*.json`` summary (or a ``TRACE_*.jsonl`` span log,
aggregated on the fly) and prints the per-phase wall attribution —
host / judge / dispatch / exchange / checkpoint / retry / compile /
plan / reshard / chaos / failover — with span counts, flags the
dominant phase, and names the lever it implicates. This is the
concrete evidence the pipelining
and auto-tuning work cite: e.g. a dispatch-dominant tgen_100 run is
the per-round-dispatch-latency bottleneck MPMD overlap attacks.

``--compare A B`` diffs two records phase-by-phase (delta walls +
pkts/s) — the one-command before/after surface tuner trials and
A/B runs use: run A is the baseline, run B the candidate, negative
deltas mean B is cheaper.

Usage:
  python scripts/trace_report.py artifacts/METRICS_tpu_1000.json
  python scripts/trace_report.py artifacts/TRACE_tpu_1000.jsonl
  python scripts/trace_report.py --top 10 <file>   # slowest spans too
  python scripts/trace_report.py --compare METRICS_a.json METRICS_b.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from shadow_tpu.obs.trace import PHASES          # noqa: E402

# dominant phase -> the lever it implicates (the ROADMAP's open
# items), printed under the table so the report ends with an action
LEVERS = {
    "dispatch": "per-round dispatch latency dominates - the "
                "pipelined/MPMD-overlap dispatch lever (ROADMAP)",
    "dispatch.sync": "blocking waits for device results dominate - "
                     "the run is device-bound; raise pipeline_depth "
                     "so host-side boundary work overlaps device "
                     "rounds (docs/operations.md#pipelining), or "
                     "attack the round program itself",
    "dispatch.issue": "host-side dispatch enqueue dominates - raise "
                      "dispatch_segment (fewer, longer segments) or "
                      "device_batch_rounds to batch more work per "
                      "dispatch",
    "host": "host-side Python dominates - batch more work per "
            "dispatch (dispatch_segment), or move the workload to "
            "the device twin",
    "judge": "hybrid judge batching dominates - raise "
             "hybrid_judge_min_batch or move hosts to a device twin",
    "exchange": "cross-shard exchange dominates - try exchange: auto "
                "/ two_phase with a capacity plan (docs/exchange.md)",
    "checkpoint": "checkpointing dominates - raise checkpoint_every "
                  "or shrink the state (docs/operations.md)",
    "retry": "retry/backoff waits dominate - the device/relay is "
             "unhealthy; see the dispatch error spans",
    "compile": "XLA compile dominates - warm the AOT cache "
               "(docs/compile_cache.md); repeat runs should hit",
    "plan": "capacity warm-up/re-plan dominates - save and reuse the "
            "OCC record (capacity_plan: <path>)",
    "reshard": "mesh-shrink failover cost dominates - devices died "
               "mid-run (drain + re-shard + recompile per shrink); "
               "fix the pool, or warm the AOT cache so the rebuilt "
               "program loads instead of recompiling",
    "chaos": "scripted fault injections (experimental.chaos) - this "
             "is a failover drill, not a production run",
    "failover": "hybrid-failover rerun overhead dominates - the "
                "device run died and replayed on CPU from t=0; "
                "failover: shrink keeps the survivors on-device "
                "(docs/operations.md#failover)",
}


def load_metrics(path: str) -> dict:
    """A METRICS_*.json summary, or one synthesized from a
    TRACE_*.jsonl span log (works on a hung run's .partial file
    too — the whole point of a streamed log)."""
    if path.endswith(".json"):
        with open(path) as f:
            m = json.load(f)
        if "phases" not in m:
            raise ValueError(
                f"{path} has no 'phases' key - not a METRICS record")
        return m
    walls: dict = {}
    counts: dict = {}
    spans = []
    n = 0
    torn = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                # a SIGKILL/OOM tears the streamed log mid-line (the
                # writer's stdio buffer flushes on its own schedule
                # between explicit flushes) — the intact prefix IS
                # the post-mortem; a torn line must not abort it
                torn += 1
                continue
            n += 1
            # self_s where present: a span's bucket must not also
            # count the nested spans recorded inside it (the
            # tracer's own attribution rule)
            walls[rec["phase"]] = (walls.get(rec["phase"], 0.0)
                                   + rec.get("self_s", rec["dur_s"]))
            counts[rec["phase"]] = counts.get(rec["phase"], 0) + 1
            spans.append(rec)
    if not spans:
        raise ValueError(f"{path} holds no spans")
    if torn:
        print(f"note: {torn} unparseable line(s) skipped "
              "(truncated stream from a killed run?)",
              file=sys.stderr)
    # total = the last span's end offset (the log is stream-ordered);
    # host_s is the residual, exactly as the tracer computes it
    total = max(r["t0_s"] + r["dur_s"] for r in spans)
    phases = {f"{p}_s": round(walls.get(p, 0.0), 3)
              for p in PHASES if p != "host"}
    attributed = sum(phases.values())
    phases["host_s"] = round(max(0.0, total - attributed), 3)
    return {"mode": "jsonl", "total_wall_s": round(total, 3),
            "phases": phases, "spans": n,
            "span_counts": counts,
            "dominant_phase": max(phases, key=phases.get)[:-2],
            "_spans": spans}


def print_report(m: dict, top: int = 0) -> None:
    total = m["total_wall_s"] or 1e-12
    phases = m["phases"]
    counts = m.get("span_counts", {})
    run = m.get("run") or {}
    title = " ".join(f"{k}={v}" for k, v in run.items())
    print(f"flight-recorder report ({m.get('mode', '?')} mode"
          f"{', ' + title if title else ''})")
    print(f"total wall: {m['total_wall_s']:.3f}s over "
          f"{m.get('spans', '?')} span(s)")
    print()
    print(f"  {'phase':<14} {'wall_s':>10} {'share':>7} {'spans':>7}")
    print(f"  {'-' * 14} {'-' * 10} {'-' * 7} {'-' * 7}")
    rows = sorted(phases.items(), key=lambda kv: -kv[1])
    for key, wall in rows:
        phase = key[:-2]
        print(f"  {phase:<14} {wall:>10.3f} {wall / total:>6.1%} "
              f"{counts.get(phase, '-'):>7}")
    print(f"  {'-' * 14} {'-' * 10} {'-' * 7} {'-' * 7}")
    print(f"  {'sum':<14} {sum(phases.values()):>10.3f} "
          f"{sum(phases.values()) / total:>6.1%}")
    dom = m.get("dominant_phase") or rows[0][0][:-2]
    print()
    print(f"dominant phase: {dom} "
          f"({phases.get(dom + '_s', 0.0):.3f}s, "
          f"{phases.get(dom + '_s', 0.0) / total:.1%} of wall)")
    lever = LEVERS.get(dom)
    if lever:
        print(f"  -> {lever}")
    if (dom == "plan" and run.get("representation") == "dense"
            and int(run.get("n_hosts") or 0) >= 100_000):
        # a plan-dominant dense run at >=100k hosts is almost always
        # paying the [V,V] table build/upload — the factored tables
        # are the lever (docs/topology.md)
        print(f"  -> dense path tables at {run['n_hosts']} hosts: "
              "if the topology is hub-and-spoke, set "
              "network.topology.representation: hierarchical "
              "(docs/topology.md)")
    pipe = (m.get("counters") or {}).get("pipeline")
    if pipe:
        # the pipelined-dispatch summary: how deep the window ran
        # and how much host wall the in-flight segments overlapped
        print(f"pipeline: depth {pipe.get('depth')}, "
              f"{pipe.get('issued', '?')} issued / "
              f"{pipe.get('drained', '?')} drained"
              + (f" / {pipe['discarded']} discarded"
                 if pipe.get("discarded") else "")
              + f"; sync {pipe.get('sync_wall_s', 0.0):.3f}s, "
              f"overlapped host {pipe.get('overlapped_host_s', 0.0):.3f}s "
              f"-> overlap efficiency "
              f"{pipe.get('overlap_efficiency', 0.0):.0%}")
    reshards = (m.get("counters") or {}).get("reshards")
    if reshards or phases.get("reshard_s"):
        # the shrink's degradation cost as a first-class line: wall
        # lost to the drain + re-shard + re-place (the rebuilt
        # program's compile wall lands in compile_s)
        print(f"mesh shrinks: {reshards or '?'} absorbed; reshard "
              f"wall {phases.get('reshard_s', 0.0):.3f}s "
              "(+ rebuild compile in compile_s)")
    if m.get("dropped_spans"):
        print(f"note: {m['dropped_spans']} span(s) dropped from the "
              "in-memory list (JSONL log is complete)")
    if top and m.get("_spans"):
        slow = sorted(m["_spans"], key=lambda r: -r["dur_s"])[:top]
        print()
        print(f"slowest {len(slow)} span(s):")
        for r in slow:
            window = ""
            if "sim_t0" in r:
                window = (f"  sim=({r['sim_t0']}, "
                          f"{r.get('sim_t1', '?')}] ns")
            print(f"  {r['dur_s']:8.3f}s  {r['phase']:<10} "
                  f"{r['name']}{window}")


def _pkts_per_s(m: dict):
    """packets/s of a record, when its counters carry packets (the
    Controller stamps events/packets/rounds into METRICS summaries);
    None otherwise — the compare table then shows walls only."""
    pkts = (m.get("counters") or {}).get("packets")
    total = m.get("total_wall_s") or 0.0
    if pkts is None or total <= 0:
        return None
    return pkts / total


def print_compare(a: dict, b: dict, name_a: str, name_b: str) -> None:
    """Phase-by-phase diff of two records: A is the baseline, B the
    candidate; delta = B - A (negative = B cheaper)."""
    pa, pb = a["phases"], b["phases"]
    keys = [f"{p}_s" for p in PHASES if f"{p}_s" in pa
            or f"{p}_s" in pb]
    keys += sorted((set(pa) | set(pb)) - set(keys))
    print("flight-recorder comparison")
    print(f"  A: {name_a}")
    print(f"  B: {name_b}")
    print()
    print(f"  {'phase':<14} {'A_s':>10} {'B_s':>10} {'delta_s':>10} "
          f"{'delta':>8}")
    print(f"  {'-' * 14} {'-' * 10} {'-' * 10} {'-' * 10} {'-' * 8}")
    rows = sorted(keys, key=lambda k: -(pa.get(k, 0.0)
                                        + pb.get(k, 0.0)))
    for key in rows:
        wa, wb = pa.get(key, 0.0), pb.get(key, 0.0)
        d = wb - wa
        rel = f"{d / wa:+.1%}" if wa > 0 else ("new" if wb else "-")
        print(f"  {key[:-2]:<14} {wa:>10.3f} {wb:>10.3f} {d:>+10.3f} "
              f"{rel:>8}")
    ta = a.get("total_wall_s", 0.0)
    tb = b.get("total_wall_s", 0.0)
    print(f"  {'-' * 14} {'-' * 10} {'-' * 10} {'-' * 10} {'-' * 8}")
    rel = f"{(tb - ta) / ta:+.1%}" if ta > 0 else "-"
    print(f"  {'total':<14} {ta:>10.3f} {tb:>10.3f} "
          f"{tb - ta:>+10.3f} {rel:>8}")
    ra, rb = _pkts_per_s(a), _pkts_per_s(b)
    print()
    if ra is not None and rb is not None:
        speed = f" ({rb / ra:.2f}x)" if ra > 0 else ""
        print(f"pkts/s: A {ra:,.0f} -> B {rb:,.0f}{speed}")
    elif ra is None and rb is None:
        print("pkts/s: n/a (no packet counters in either record)")
    else:
        # one-sided counters (e.g. a METRICS summary vs a raw JSONL
        # aggregation): show the known side, never silently drop the
        # throughput row
        fmt = ("n/a" if ra is None else f"{ra:,.0f}",
               "n/a" if rb is None else f"{rb:,.0f}")
        print(f"pkts/s: A {fmt[0]} -> B {fmt[1]} (one record has no "
              "packet counters)")
    dom_a, dom_b = a.get("dominant_phase"), b.get("dominant_phase")
    if dom_a and dom_b:
        print(f"dominant phase: A {dom_a} -> B {dom_b}"
              + ("" if dom_a == dom_b else "  <- shifted"))
    pipe_a = (a.get("counters") or {}).get("pipeline")
    pipe_b = (b.get("counters") or {}).get("pipeline")
    if pipe_a or pipe_b:
        def _pfmt(p):
            return (f"depth {p.get('depth')} overlap "
                    f"{p.get('overlap_efficiency', 0.0):.0%}"
                    if p else "n/a")
        print(f"pipeline: A {_pfmt(pipe_a)} -> B {_pfmt(pipe_b)}")
    rsh_a = (a.get("counters") or {}).get("reshards", 0)
    rsh_b = (b.get("counters") or {}).get("reshards", 0)
    if rsh_a or rsh_b or pa.get("reshard_s") or pb.get("reshard_s"):
        # the one-line answer to "what did the shrink cost": wall
        # lost to drain + re-shard + recompile, side by side
        wa = pa.get("reshard_s", 0.0)
        wb = pb.get("reshard_s", 0.0)
        print(f"shrink cost: A {rsh_a} shrink(s) / {wa:.3f}s -> "
              f"B {rsh_b} shrink(s) / {wb:.3f}s (drain + reshard + "
              "recompile; rebuild compile rides compile_s)")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="per-phase wall breakdown of a flight-recorder "
                    "run")
    ap.add_argument("path", nargs="?",
                    help="METRICS_*.json or TRACE_*.jsonl "
                         "(.partial accepted)")
    ap.add_argument("--top", type=int, default=0,
                    help="also list the N slowest spans (jsonl input "
                         "only)")
    ap.add_argument("--compare", nargs=2, metavar=("A", "B"),
                    help="diff two METRICS/JSONL records phase-by-"
                         "phase (A = baseline, B = candidate)")
    args = ap.parse_args()
    if args.compare:
        if args.path:
            print("trace_report: --compare takes exactly its two "
                  "records (drop the positional path)",
                  file=sys.stderr)
            return 1
        try:
            a = load_metrics(args.compare[0])
            b = load_metrics(args.compare[1])
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"trace_report: cannot read comparison input: {e}",
                  file=sys.stderr)
            return 1
        print_compare(a, b, args.compare[0], args.compare[1])
        return 0
    if not args.path:
        print("trace_report: need a METRICS/TRACE path (or "
              "--compare A B)", file=sys.stderr)
        return 1
    try:
        m = load_metrics(args.path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"trace_report: cannot read {args.path}: {e}",
              file=sys.stderr)
        return 1
    print_report(m, top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
