"""On-chip cost attribution for the device engine's round step.

The phase-split profiler (scripts/profile_device.py) syncs after every
call, so over the tunneled TPU each number carries a full dispatch+sync
RTT — fine for CPU ratios, useless for on-chip math. This script times
each piece with N pipelined (async) dispatches of identical work and
one final block, so per-call overhead amortizes away, and times the
hot flush primitives (flat sort, merge sort, judge threefry, segment
gathers) standalone at the engine's exact shapes.

Usage:
  python scripts/tpu_micro.py [config] [stop_s] [reps]

Prints ONE JSON line.
"""

from __future__ import annotations

import json
import signal
import sys
import time

sys.path.insert(0, ".")

REPS = 30


def timed(label, fn, reps=REPS):
    """Pipelined repeat: dispatch `reps` identical calls, block once.
    Returns seconds per call."""
    from shadow_tpu._jax import jax
    out = fn()
    jax.block_until_ready(out)          # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"  [{label}] {1e3 * dt:.3f} ms/call", file=sys.stderr,
          flush=True)
    return dt


def main() -> int:
    cfg_path = sys.argv[1] if len(sys.argv) > 1 else \
        "examples/tgen_10000.yaml"
    stop_s = float(sys.argv[2]) if len(sys.argv) > 2 else 2.5
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else REPS

    signal.signal(signal.SIGALRM, lambda *a: sys.exit(9))
    signal.alarm(30 * 60)

    from shadow_tpu import simtime
    from shadow_tpu._jax import jax, jnp
    from jax import lax
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device import prng
    from shadow_tpu.device.netsem import packet_drop_mask
    from shadow_tpu.device.engine import INF

    cfg = load_config(cfg_path)
    cfg.experimental.scheduler_policy = "tpu"
    cfg.general.stop_time = simtime.from_seconds(stop_s)
    c = Controller(cfg)
    eng = c.runner.engine
    ec = eng.config
    stop = simtime.from_seconds(stop_s)
    res = {"config": cfg_path,
           "platform": jax.devices()[0].platform,
           "slice_sim_s": stop_s, "reps": reps}

    # ---- fused baseline --------------------------------------------
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    res["fused_compile_plus_run_s"] = round(time.perf_counter() - t0, 3)
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    fused_s = time.perf_counter() - t0
    rounds = int(rounds)
    res["fused_run_s"] = round(fused_s, 3)
    res["fused_rounds"] = rounds
    res["fused_ms_per_round"] = round(1e3 * fused_s / max(1, rounds), 3)
    print(f"fused: {fused_s:.3f}s / {rounds} rounds = "
          f"{res['fused_ms_per_round']:.1f} ms/round", file=sys.stderr,
          flush=True)

    # ---- mid-run state + a filled outbox for phase timing ----------
    st = eng.init_state(c.sim.starts)
    st_mid, _ = eng.run(st, stop=stop // 2, final_stop=stop)
    jax.block_until_ready(st_mid)
    from jax.sharding import NamedSharding
    repl = NamedSharding(eng.mesh, eng._repl_spec)
    shard = NamedSharding(eng.mesh, eng._shard_spec)
    hv = jax.device_put(jnp.asarray(eng.host_vertex), repl)
    lat = jax.device_put(jnp.asarray(eng.latency), repl)
    rel = jax.device_put(jnp.asarray(eng.reliability), repl)
    nxt, _ = map(int, eng._probe(st_mid))
    win_end = jnp.int64(min(nxt + max(1, ec.lookahead), stop))

    def fresh_ob():
        ob = {"t": jax.device_put(
            jnp.full(eng._ob_shape_global, INF, jnp.int64), shard)}
        for f in ("k", "m", "s", "v"):
            ob[f] = jax.device_put(
                jnp.zeros(eng._ob_shape_global, jnp.int64), shard)
        return ob

    ob0 = fresh_ob()
    st_pop, ob_full, _ = eng._pop_phase(st_mid, ob0, hv, lat, rel,
                                        win_end)
    jax.block_until_ready((st_pop, ob_full))

    # calibration: per-dispatch overhead of a trivial jitted call
    noop = jax.jit(lambda x: x + 1)
    res["noop_ms"] = round(1e3 * timed(
        "noop", lambda: noop(jnp.int64(1)), reps), 3)

    res["pop_ms"] = round(1e3 * timed(
        "pop_phase", lambda: eng._pop_phase(
            st_mid, ob0, hv, lat, rel, win_end), reps), 3)
    res["flush_ms"] = round(1e3 * timed(
        "flush_phase", lambda: eng._flush_phase(
            st_pop, ob_full, hv, lat, rel, win_end), reps), 3)

    # ---- flush primitives at the engine's exact shapes -------------
    H_loc = eng.H_loc
    E = ec.event_capacity
    IN = ec.exchange_in_capacity or E
    app = eng.app
    K_eff = max(1, getattr(app, "burst_pops", 1)) \
        if getattr(app, "burst_pops", 1) > 1 else app.max_sends
    M_out = K_eff + app.max_timers
    B = max(1, ec.outbox_capacity // max(1, M_out))
    OB = B * M_out
    C = max(1, getattr(app, "max_train", 1))
    F = H_loc * OB
    res["shapes"] = {"H_loc": H_loc, "E": E, "IN": IN, "OB": OB,
                     "C": C, "F": F, "B": B}

    key = jax.random.key(0)
    import numpy as np
    skey = jax.device_put(jnp.asarray(
        np.random.default_rng(0).integers(0, 1 << 60, F)
        .astype(np.int64)))
    iota = jnp.arange(F, dtype=jnp.int64)
    flat_sort = jax.jit(
        lambda k: lax.sort((k, iota), num_keys=1))
    res["flat_sort_ms"] = round(1e3 * timed(
        f"flat_sort F={F}", lambda: flat_sort(skey), reps), 3)

    W = E + IN
    ct = jax.device_put(jnp.asarray(
        np.random.default_rng(1).integers(0, 1 << 60, (H_loc, W))
        .astype(np.int64)))
    ck = jax.device_put(jnp.asarray(
        np.random.default_rng(2).integers(0, 1 << 60, (H_loc, W))
        .astype(np.int64)))
    ci = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :],
                          (H_loc, W))
    merge_sort = jax.jit(
        lambda a, b: lax.sort((a, b, ci), dimension=1, num_keys=2))
    res["merge_sort_ms"] = round(1e3 * timed(
        f"merge_sort [{H_loc},{W}]x3", lambda: merge_sort(ct, ck),
        reps), 3)

    # payload recovery gathers (3x take_along_axis at merge width)
    cm = ck
    sie = jnp.asarray(
        np.random.default_rng(3).integers(0, W, (H_loc, E))
        .astype(np.int32))
    gat = jax.jit(lambda m: jnp.take_along_axis(m, sie, axis=1))
    res["merge_gather_ms"] = round(1e3 * timed(
        "merge_gather x1", lambda: gat(cm), reps), 3)

    # seg_take: 5 fields, [H_loc*IN] random takes from F rows
    pidx = jnp.asarray(
        np.random.default_rng(4).integers(0, F, H_loc * IN)
        .astype(np.int64))
    segtake = jax.jit(lambda v: jnp.take(v, pidx))
    res["seg_take_ms_x1"] = round(1e3 * timed(
        "seg_take x1 field", lambda: segtake(skey), reps), 3)

    # judge threefry: drop mask at [H_loc, OB, C]
    seed_pair = eng.seed_pair
    ft = jax.device_put(jnp.asarray(
        np.random.default_rng(5).integers(0, 1 << 40, (H_loc, OB))
        .astype(np.int64)))
    gid = jnp.arange(H_loc, dtype=jnp.int32)
    seqs3 = jnp.asarray(
        np.random.default_rng(6).integers(0, 1 << 30, (H_loc, OB, C))
        .astype(np.int32))
    relv = jnp.full((H_loc, OB, 1), 0.999, jnp.float32)

    def judge():
        from shadow_tpu.utils.rng import PURPOSE_PACKET_DROP
        hk1, hk2 = prng.purpose_id_key(seed_pair, PURPOSE_PACKET_DROP,
                                       gid)
        return packet_drop_mask(
            seed_pair, jnp.int64(0), ft[..., None],
            gid[:, None, None], seqs3, relv,
            src_key=(hk1[:, None, None], hk2[:, None, None]))

    judge_j = jax.jit(judge)
    res["judge_threefry_ms"] = round(1e3 * timed(
        f"judge [{H_loc},{OB},{C}]", judge_j, reps), 3)

    # searchsorted over F at H_loc+1 boundaries
    hb = jnp.arange(H_loc + 1, dtype=jnp.int64) * (F // H_loc)
    ss = jax.jit(lambda k: jnp.searchsorted(k, hb))
    skey_sorted = jnp.sort(skey)
    res["searchsorted_ms"] = round(1e3 * timed(
        "searchsorted", lambda: ss(skey_sorted), reps), 3)

    print(json.dumps(res), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
