"""On-chip microbenchmarks for the device engine, one parameterized
driver (the former tpu_micro.py / tpu_micro2.py / tpu_micro3.py /
tpu_micro4.py clones, consolidated):

  python scripts/tpu_micro.py [--variant N] [variant args...]

variant 1 (default) — round-step cost attribution at a real config's
  shapes: fused run baseline, pipelined pop/flush phase timings, and
  the hot flush primitives (flat sort, merge sort, judge threefry,
  segment gathers) standalone. Args: [config] [stop_s] [reps].
variant 2 — multi-operand sorts vs gather recovery (the flush's
  ~10 ms-per-gather takes vs 1.6-2.6 ms sorts): 6-operand flat sort,
  5-operand merge sort, window takes, row-stacked gathers, the
  filler-sort expand. Args: [reps].
variant 3 — the candidate gatherless flush (double-sort merge) timed
  end-to-end at the 10k-rung shapes + a numpy oracle check at a small
  shape. Args: [reps].
variant 4 — the round's remaining gathers + one-hot pop head reads:
  host_vertex/table gathers vs unrolled one-hot sums, P=1 and P=8 pop
  reads. Args: [reps].
variant 5 — the cross-shard exchange in isolation (IPU-dissection
  style attribution): the flush phase timed per exchange schedule —
  dense auto-sized all_to_all, occ_x-planned (compacted) all_to_all,
  two_phase, all_gather — at a real config's shapes on the visible
  mesh, with per-flush ICI rows/bytes from the engine's static
  accounting. Args: [config] [stop_s] [reps].
variant 6 — compile/dispatch attribution (IPU-dissection style,
  arxiv 1912.03413): per-program lower / compile / AOT-cache
  serialize+load / first-dispatch / steady walls for the round
  program and each profiling split (pop, flush), printed as ONE
  table — the cold-start budget the persistent AOT compile cache
  (device/aotcache.py) collapses, measured piece by piece.
  Args: [config] [stop_s] [reps].

Every variant prints ONE JSON line. Timings use pipelined (async)
dispatches with one final block so per-call overhead amortizes away —
the numbers are on-chip costs, not dispatch RTTs (contrast
scripts/profile_device.py, which syncs per call).
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time

sys.path.insert(0, ".")

REPS = 30


def timed(label, fn, reps):
    """Pipelined repeat: dispatch `reps` identical calls, block once.
    Returns seconds per call."""
    from shadow_tpu._jax import jax
    out = fn()
    jax.block_until_ready(out)          # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"  [{label}] {1e3 * dt:.3f} ms/call", file=sys.stderr,
          flush=True)
    return dt


def timed_ms(label, fn, reps):
    return round(1e3 * timed(label, fn, reps), 3)


# ---------------------------------------------------------------------
# variant 1: round-step cost attribution at a real config's shapes
# ---------------------------------------------------------------------
def variant1(args: list[str]) -> int:
    cfg_path = args[0] if len(args) > 0 else "examples/tgen_10000.yaml"
    stop_s = float(args[1]) if len(args) > 1 else 2.5
    reps = int(args[2]) if len(args) > 2 else REPS

    from shadow_tpu import simtime
    from shadow_tpu._jax import jax, jnp
    from jax import lax
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device import prng
    from shadow_tpu.device.netsem import packet_drop_mask
    from shadow_tpu.device.engine import INF

    cfg = load_config(cfg_path)
    cfg.experimental.scheduler_policy = "tpu"
    cfg.general.stop_time = simtime.from_seconds(stop_s)
    c = Controller(cfg)
    eng = c.runner.engine
    ec = eng.config
    stop = simtime.from_seconds(stop_s)
    res = {"variant": 1, "config": cfg_path,
           "platform": jax.devices()[0].platform,
           "slice_sim_s": stop_s, "reps": reps}

    # ---- fused baseline --------------------------------------------
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    res["fused_compile_plus_run_s"] = round(time.perf_counter() - t0, 3)
    st = eng.init_state(c.sim.starts)
    t0 = time.perf_counter()
    st_out, rounds = eng.run(st, stop=stop)
    jax.block_until_ready(st_out)
    fused_s = time.perf_counter() - t0
    rounds = int(rounds)
    res["fused_run_s"] = round(fused_s, 3)
    res["fused_rounds"] = rounds
    res["fused_ms_per_round"] = round(1e3 * fused_s / max(1, rounds), 3)
    print(f"fused: {fused_s:.3f}s / {rounds} rounds = "
          f"{res['fused_ms_per_round']:.1f} ms/round", file=sys.stderr,
          flush=True)

    # ---- mid-run state + a filled outbox for phase timing ----------
    st = eng.init_state(c.sim.starts)
    st_mid, _ = eng.run(st, stop=stop // 2, final_stop=stop)
    jax.block_until_ready(st_mid)
    from jax.sharding import NamedSharding
    repl = NamedSharding(eng.mesh, eng._repl_spec)
    shard = NamedSharding(eng.mesh, eng._shard_spec)
    hv = jax.device_put(jnp.asarray(eng.host_vertex), repl)
    wrld = eng.world()
    nxt, _ = map(int, eng._probe(st_mid))
    win_end = jnp.int64(min(nxt + max(1, ec.lookahead), stop))

    def fresh_ob():
        ob = {"t": jax.device_put(
            jnp.full(eng._ob_shape_global, INF, jnp.int64), shard)}
        for f in ("k", "m", "s", "v"):
            ob[f] = jax.device_put(
                jnp.zeros(eng._ob_shape_global, jnp.int64), shard)
        return ob

    ob0 = fresh_ob()
    st_pop, ob_full, _ = eng._pop_phase(st_mid, ob0, hv, wrld,
                                        win_end)
    jax.block_until_ready((st_pop, ob_full))

    # calibration: per-dispatch overhead of a trivial jitted call
    noop = jax.jit(lambda x: x + 1)
    res["noop_ms"] = timed_ms("noop", lambda: noop(jnp.int64(1)),
                              reps)

    res["pop_ms"] = timed_ms(
        "pop_phase", lambda: eng._pop_phase(
            st_mid, ob0, hv, wrld, win_end), reps)
    res["flush_ms"] = timed_ms(
        "flush_phase", lambda: eng._flush_phase(
            st_pop, ob_full, hv, wrld, win_end), reps)

    # ---- flush primitives at the engine's exact shapes -------------
    H_loc = eng.H_loc
    E = ec.event_capacity
    IN = ec.exchange_in_capacity or E
    app = eng.app
    K_eff = max(1, getattr(app, "burst_pops", 1)) \
        if getattr(app, "burst_pops", 1) > 1 else app.max_sends
    M_out = K_eff + app.max_timers
    B = max(1, ec.outbox_capacity // max(1, M_out))
    OB = B * M_out
    C = max(1, getattr(app, "max_train", 1))
    F = H_loc * OB
    res["shapes"] = {"H_loc": H_loc, "E": E, "IN": IN, "OB": OB,
                     "C": C, "F": F, "B": B}

    import numpy as np
    skey = jax.device_put(jnp.asarray(
        np.random.default_rng(0).integers(0, 1 << 60, F)
        .astype(np.int64)))
    iota = jnp.arange(F, dtype=jnp.int64)
    flat_sort = jax.jit(
        lambda k: lax.sort((k, iota), num_keys=1))
    res["flat_sort_ms"] = timed_ms(
        f"flat_sort F={F}", lambda: flat_sort(skey), reps)

    W = E + IN
    ct = jax.device_put(jnp.asarray(
        np.random.default_rng(1).integers(0, 1 << 60, (H_loc, W))
        .astype(np.int64)))
    ck = jax.device_put(jnp.asarray(
        np.random.default_rng(2).integers(0, 1 << 60, (H_loc, W))
        .astype(np.int64)))
    ci = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :],
                          (H_loc, W))
    merge_sort = jax.jit(
        lambda a, b: lax.sort((a, b, ci), dimension=1, num_keys=2))
    res["merge_sort_ms"] = timed_ms(
        f"merge_sort [{H_loc},{W}]x3", lambda: merge_sort(ct, ck),
        reps)

    # payload recovery gathers (3x take_along_axis at merge width)
    cm = ck
    sie = jnp.asarray(
        np.random.default_rng(3).integers(0, W, (H_loc, E))
        .astype(np.int32))
    gat = jax.jit(lambda m: jnp.take_along_axis(m, sie, axis=1))
    res["merge_gather_ms"] = timed_ms(
        "merge_gather x1", lambda: gat(cm), reps)

    # seg_take: 5 fields, [H_loc*IN] random takes from F rows
    pidx = jnp.asarray(
        np.random.default_rng(4).integers(0, F, H_loc * IN)
        .astype(np.int64))
    segtake = jax.jit(lambda v: jnp.take(v, pidx))
    res["seg_take_ms_x1"] = timed_ms(
        "seg_take x1 field", lambda: segtake(skey), reps)

    # judge threefry: drop mask at [H_loc, OB, C]
    seed_pair = eng.seed_pair
    ft = jax.device_put(jnp.asarray(
        np.random.default_rng(5).integers(0, 1 << 40, (H_loc, OB))
        .astype(np.int64)))
    gid = jnp.arange(H_loc, dtype=jnp.int32)
    seqs3 = jnp.asarray(
        np.random.default_rng(6).integers(0, 1 << 30, (H_loc, OB, C))
        .astype(np.int32))
    relv = jnp.full((H_loc, OB, 1), 0.999, jnp.float32)

    def judge():
        from shadow_tpu.utils.rng import PURPOSE_PACKET_DROP
        hk1, hk2 = prng.purpose_id_key(seed_pair, PURPOSE_PACKET_DROP,
                                       gid)
        return packet_drop_mask(
            seed_pair, jnp.int64(0), ft[..., None],
            gid[:, None, None], seqs3, relv,
            src_key=(hk1[:, None, None], hk2[:, None, None]))

    judge_j = jax.jit(judge)
    res["judge_threefry_ms"] = timed_ms(
        f"judge [{H_loc},{OB},{C}]", judge_j, reps)

    # searchsorted over F at H_loc+1 boundaries
    hb = jnp.arange(H_loc + 1, dtype=jnp.int64) * (F // H_loc)
    ss = jax.jit(lambda k: jnp.searchsorted(k, hb))
    skey_sorted = jnp.sort(skey)
    res["searchsorted_ms"] = timed_ms(
        "searchsorted", lambda: ss(skey_sorted), reps)

    print(json.dumps(res), flush=True)
    return 0


# ---------------------------------------------------------------------
# variant 2: multi-operand sorts vs gather recovery
# ---------------------------------------------------------------------
def variant2(args: list[str]) -> int:
    reps = int(args[0]) if args else REPS
    H, OB = 10000, 36
    F = H * OB
    E = IN = 48
    W = E + IN

    import numpy as np
    from shadow_tpu._jax import jax, jnp
    from jax import lax

    res = {"variant": 2, "platform": jax.devices()[0].platform,
           "reps": reps}
    rng = np.random.default_rng(0)

    def arr64(shape, hi=1 << 60):
        return jax.device_put(jnp.asarray(
            rng.integers(0, hi, shape).astype(np.int64)))

    skey = arr64(F)
    p1, p2, p3, p4, p5 = (arr64(F) for _ in range(5))

    # 6-operand flat sort: payload rides through the bitonic passes
    sort6 = jax.jit(lambda k, a, b, c, d, e:
                    lax.sort((k, a, b, c, d, e), num_keys=1))
    res["flat_sort6_ms"] = timed_ms(
        "flat sort 6-op F=360k",
        lambda: sort6(skey, p1, p2, p3, p4, p5), reps)

    # 2-operand for reference at same F
    sort2 = jax.jit(lambda k, a: lax.sort((k, a), num_keys=1))
    res["flat_sort2_ms"] = timed_ms(
        "flat sort 2-op F=360k", lambda: sort2(skey, p1), reps)

    # 5-operand merge sort [H, W]
    ct = arr64((H, W))
    ck = arr64((H, W))
    cm = arr64((H, W))
    cv = arr64((H, W))
    cw = arr64((H, W))
    msort5 = jax.jit(lambda t, k, m, v, w: lax.sort(
        (t, k, m, v, w), dimension=1, num_keys=2))
    res["merge_sort5_ms"] = timed_ms(
        "merge sort 5-op [10k,96]",
        lambda: msort5(ct, ck, cm, cv, cw), reps)

    # contiguous-window takes (1-hop, from sorted payload)
    starts = jnp.sort(arr64(H, hi=F - IN))
    idx = starts[:, None] + jnp.arange(IN, dtype=jnp.int64)[None, :]
    cidx = jnp.clip(idx, 0, F - 1).reshape(-1)
    win_take = jax.jit(lambda v: jnp.take(v, cidx).reshape(H, IN))
    res["window_take_ms_x1"] = timed_ms(
        "contiguous window take x1", lambda: win_take(p1), reps)

    # row-stacked gather: [F, 8] i64, gather H*IN rows
    mat = arr64((F, 8))
    ridx = jnp.asarray(rng.integers(0, F, H * IN).astype(np.int32))
    row_gather = jax.jit(lambda m: jnp.take(m, ridx, axis=0))
    res["row_gather_f8_ms"] = timed_ms(
        "row gather [F,8] x H*IN rows", lambda: row_gather(mat), reps)

    # row-stacked CONTIGUOUS window rows
    crow = jax.jit(lambda m: jnp.take(m, cidx.astype(jnp.int32),
                                      axis=0))
    res["row_gather_f8_contig_ms"] = timed_ms(
        "row gather [F,8] contiguous windows", lambda: crow(mat), reps)

    # dynamic_slice-per-row via vmap (windows)
    def _dsl(m, s):
        return lax.dynamic_slice(m, (s,), (IN,))
    vds = jax.jit(lambda v: jax.vmap(_dsl, (None, 0))(v, starts))
    res["vmap_dynslice_ms_x1"] = timed_ms(
        "vmap dynamic_slice windows x1", lambda: vds(p1), reps)

    # filler-sort expand: 2 stable sorts of (F + H*IN) x 6 operands
    FE = F + H * IN
    dkey = arr64(FE, hi=2 * H)
    q1, q2, q3, q4, q5 = (arr64(FE) for _ in range(5))
    sort6e = jax.jit(lambda k, a, b, c, d, e:
                     lax.sort((k, a, b, c, d, e), num_keys=1))

    def expand():
        r = sort6e(dkey, q1, q2, q3, q4, q5)
        return sort6e(r[1], r[0], r[2], r[3], r[4], r[5])

    res["filler_expand_2sorts_ms"] = timed_ms(
        "filler expand 2x sort6 @840k", expand, reps)

    # one-hot matmul take_along_axis [H, W] -> [H, E]
    sie = jnp.asarray(rng.integers(0, W, (H, E)).astype(np.int32))

    def onehot_gather(m):
        oh = (sie[:, :, None] ==
              jnp.arange(W, dtype=jnp.int32)[None, None, :]) \
            .astype(jnp.float32)                      # [H, E, W]
        lo = (m & 0xFFFFF).astype(jnp.float32)
        mid = ((m >> 20) & 0xFFFFF).astype(jnp.float32)
        hi = ((m >> 40) & 0xFFFFFF).astype(jnp.float32)
        parts = jnp.stack([lo, mid, hi], axis=-1)     # [H, W, 3]
        got = jnp.einsum("hew,hwc->hec", oh, parts,
                         preferred_element_type=jnp.float32)
        lo_, mid_, hi_ = (got[..., i].astype(jnp.int64)
                          for i in range(3))
        return lo_ | (mid_ << 20) | (hi_ << 40)

    ohg = jax.jit(onehot_gather)
    res["onehot_gather_ms_x1"] = timed_ms(
        "one-hot matmul take_along x1", lambda: ohg(cm), reps)

    # searchsorted at F for the window starts
    hb = jnp.arange(H + 1, dtype=jnp.int64) * OB
    skey_sorted = jnp.sort(skey)
    ss = jax.jit(lambda k: jnp.searchsorted(k, hb))
    res["searchsorted_ms"] = timed_ms(
        "searchsorted F@10k+1", lambda: ss(skey_sorted), reps)

    print(json.dumps(res), flush=True)
    return 0


# ---------------------------------------------------------------------
# variant 3: candidate gatherless flush (double-sort merge)
# ---------------------------------------------------------------------
def _build_gatherless_flush(jnp, lax, H, OB, E):
    INF = jnp.int64(1) << jnp.int64(62)
    F = H * OB
    N = F + H * E
    BIG = 1 << 62

    def seg_scan_sum(flags_new, vals):
        """Segmented cumsum: resets at rows where flags_new is True."""
        def comb(a, b):
            af, av = a
            bf, bv = b
            return af | bf, jnp.where(bf, bv, av + bv)
        _, out = lax.associative_scan(comb, (flags_new, vals))
        return out

    def flush(ob_t, ob_host, ob_k, ob_m, ob_v, ob_w,
              ht, hk, hm, hv, hw, head):
        # heap rows: consumed slots (col < head) present as INF
        live = jnp.arange(E)[None, :] >= head[:, None]
        mt = jnp.where(live, ht, INF).reshape(-1)
        mk = jnp.where(live, hk, (1 << 62) - 1).reshape(-1)
        hrow = jnp.broadcast_to(
            jnp.arange(H, dtype=jnp.int32)[:, None], (H, E)) \
            .reshape(-1)
        gt = jnp.concatenate([ob_t, mt])
        gk = jnp.concatenate([ob_k, mk])
        gm = jnp.concatenate([ob_m, hm.reshape(-1)])
        gv = jnp.concatenate([ob_v, hv.reshape(-1)])
        gw = jnp.concatenate([ob_w, hw.reshape(-1)])
        ghost = jnp.concatenate([ob_host, hrow])

        # sort1: (host, t, k) — 3 keys, payload rides
        sh, st_, sk_, sm_, sv_, sw_ = lax.sort(
            (ghost, gt, gk, gm, gv, gw), num_keys=3)

        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), sh[1:] != sh[:-1]])
        rank = seg_scan_sum(is_new, jnp.ones(N, jnp.int32)) - 1
        kept = rank < E
        is_real = st_ < INF
        dropped_real = (~kept) & is_real
        # per-host dropped count rides to slot [h, 0] on the rank-0 row
        rev_new = jnp.concatenate(
            [(sh[1:] != sh[:-1]), jnp.ones((1,), bool)])
        rdrop = seg_scan_sum(rev_new[::-1],
                             dropped_real[::-1].astype(jnp.int32))[::-1]
        ov_carry = jnp.where(rank == 0, rdrop, 0)

        tgt = sh.astype(jnp.int64) * E + rank
        key2 = jnp.where(kept, tgt, BIG + jnp.arange(N,
                                                     dtype=jnp.int64))
        _, t2, k2, m2, v2, w2, ov2 = lax.sort(
            (key2, st_, sk_, sm_, sv_, sw_, ov_carry), num_keys=1)
        KEEP = H * E
        new_ht = t2[:KEEP].reshape(H, E)
        new_hk = k2[:KEEP].reshape(H, E)
        new_hm = m2[:KEEP].reshape(H, E)
        new_hv = v2[:KEEP].reshape(H, E)
        new_hw = w2[:KEEP].reshape(H, E)
        overflow = ov2[:KEEP].reshape(H, E)[:, 0]
        return new_ht, new_hk, new_hm, new_hv, new_hw, overflow

    return flush


def _variant3_oracle_check() -> bool:
    """The gatherless flush vs a per-host numpy sort at a tiny shape."""
    import numpy as np
    from shadow_tpu._jax import jax, jnp
    from jax import lax

    H, OB, E = 7, 5, 4
    F = H * OB
    flush = jax.jit(_build_gatherless_flush(jnp, lax, H, OB, E))
    rng = np.random.default_rng(7)
    INF = np.int64(1) << np.int64(62)
    valid = rng.random(F) < 0.4
    ob_t = np.where(valid, rng.integers(0, 100, F), INF) \
        .astype(np.int64)
    ob_host = np.where(valid, rng.integers(0, H, F),
                       np.int64(1 << 31)).astype(np.int64)
    ob_k = rng.integers(0, 1 << 20, F).astype(np.int64)
    ht = np.where(rng.random((H, E)) < 0.6,
                  rng.integers(0, 100, (H, E)), INF) \
        .astype(np.int64)
    ht = np.sort(ht, axis=1)
    hk = rng.integers(0, 1 << 20, (H, E)).astype(np.int64)
    head = rng.integers(0, 2, H).astype(np.int32)
    z = np.zeros(F, np.int64)
    zh = np.zeros((H, E), np.int64)
    out = flush(*[jnp.asarray(a) for a in
                  (ob_t, ob_host, ob_k, z, z, z,
                   ht, hk, zh, zh, zh, head)])
    new_ht, new_hk = np.asarray(out[0]), np.asarray(out[1])
    ovf = np.asarray(out[5])
    for h in range(H):
        rows = []
        for j in range(E):
            if j >= head[h] and ht[h, j] < INF:
                rows.append((int(ht[h, j]), int(hk[h, j])))
            elif j >= head[h]:
                rows.append((int(INF), int(hk[h, j])))
        for i in range(F):
            if ob_host[i] == h:
                rows.append((int(ob_t[i]), int(ob_k[i])))
        rows.sort()
        exp_drop = sum(1 for (t, _) in rows[E:] if t < INF)
        rows = rows[:E]
        got = [(int(new_ht[h, j]), int(new_hk[h, j]))
               for j in range(len(rows))]
        if [r[0] for r in rows] != [g[0] for g in got]:
            print(f"host {h}: time mismatch {rows} vs {got}",
                  file=sys.stderr)
            return False
        if exp_drop != int(ovf[h]):
            print(f"host {h}: overflow {exp_drop} vs {ovf[h]}",
                  file=sys.stderr)
            return False
    return True


def variant3(args: list[str]) -> int:
    reps = int(args[0]) if args else REPS
    H, OB, E = 10000, 36, 48
    F = H * OB

    import numpy as np
    from shadow_tpu._jax import jax, jnp
    from jax import lax

    res = {"variant": 3, "platform": jax.devices()[0].platform,
           "reps": reps}
    flush = jax.jit(_build_gatherless_flush(jnp, lax, H, OB, E))
    rng = np.random.default_rng(0)
    INF = np.int64(1) << np.int64(62)

    # realistic sparsity: ~2% of outbox rows valid
    valid = rng.random(F) < 0.02
    ob_t = np.where(valid, rng.integers(0, 1 << 40, F), INF) \
        .astype(np.int64)
    ob_host = np.where(valid, rng.integers(0, H, F),
                       np.int64(1 << 31)).astype(np.int64)
    ob_k = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_m = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_v = rng.integers(0, 1 << 60, F).astype(np.int64)
    ob_w = rng.integers(0, 1 << 30, F).astype(np.int64)
    # heap ~25% full
    ht = np.where(rng.random((H, E)) < 0.25,
                  rng.integers(0, 1 << 40, (H, E)), INF) \
        .astype(np.int64)
    ht = np.sort(ht, axis=1)
    hk = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hm = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hv = rng.integers(0, 1 << 60, (H, E)).astype(np.int64)
    hw = rng.integers(0, 1 << 30, (H, E)).astype(np.int64)
    head = rng.integers(0, 4, H).astype(np.int32)

    fargs = [jax.device_put(jnp.asarray(a)) for a in
             (ob_t, ob_host, ob_k, ob_m, ob_v, ob_w,
              ht, hk, hm, hv, hw, head)]
    res["gatherless_flush_ms"] = timed_ms(
        "gatherless flush @10k", lambda: flush(*fargs), reps)

    ok = _variant3_oracle_check()
    res["small_oracle_ok"] = ok
    print(json.dumps(res), flush=True)
    return 0 if ok else 1


# ---------------------------------------------------------------------
# variant 4: remaining gathers + one-hot pop head reads
# ---------------------------------------------------------------------
def variant4(args: list[str]) -> int:
    reps = int(args[0]) if args else REPS
    H, OB, E, V, Pw = 10000, 40, 48, 6, 8

    import numpy as np
    from shadow_tpu._jax import jax, jnp

    platform = jax.devices()[0].platform
    rng = np.random.RandomState(7)
    host_vertex = jnp.asarray(rng.randint(0, V, H).astype(np.int32))
    lat = jnp.asarray(rng.randint(5e6, 1.4e8, (V, V)).astype(np.int64))
    dst = jnp.asarray(rng.randint(0, H, (H, OB)).astype(np.int32))
    srcv = jnp.asarray(rng.randint(0, V, H).astype(np.int32))[:, None]

    r = {"variant": 4, "platform": platform, "H": H, "OB": OB,
         "E": E, "reps": reps}

    f_dstv = jax.jit(lambda d: host_vertex[jnp.clip(d, 0, H - 1)])
    r["a_hostvertex_gather"] = timed_ms("a host_vertex[dst]",
                                        lambda: f_dstv(dst), reps)
    dstv = f_dstv(dst)

    f_lat = jax.jit(lambda s, d: lat[s, d])
    r["b_table_gather"] = timed_ms("b lat[srcv,dstv]",
                                   lambda: f_lat(srcv, dstv), reps)

    lat_flat = lat.reshape(-1)

    def onehot_lookup(s, d):
        pair = s * V + d                              # [H,OB]
        acc = jnp.zeros(pair.shape, jnp.int64)
        for j in range(V * V):
            acc = acc + jnp.where(pair == j, lat_flat[j],
                                  jnp.int64(0))
        return acc

    f_oh = jax.jit(onehot_lookup)
    r["c_table_onehot"] = timed_ms("c one-hot table",
                                   lambda: f_oh(srcv, dstv), reps)
    assert bool(jnp.all(f_oh(srcv, dstv) == f_lat(srcv, dstv)))

    ht = jnp.asarray(
        np.sort(rng.randint(0, 1 << 40, (H, E)).astype(np.int64), 1))
    head = jnp.asarray(rng.randint(0, 4, H).astype(np.int64))
    INF = jnp.int64(1) << jnp.int64(62)

    def take_gather(arr, hd):
        v = jnp.take_along_axis(arr, jnp.minimum(hd, E - 1)[:, None],
                                axis=1)[:, 0]
        return jnp.where(hd < E, v, INF)

    def take_onehot(arr, hd):
        m = jnp.arange(E)[None, :] == hd[:, None]
        v = jnp.where(m, arr, jnp.zeros((), arr.dtype)).sum(axis=1)
        return jnp.where(hd < E, v, INF)

    fg, fo = jax.jit(take_gather), jax.jit(take_onehot)
    r["d_pop1_gather"] = timed_ms("d pop P=1 gather",
                                  lambda: fg(ht, head), reps)
    r["d_pop1_onehot"] = timed_ms("d pop P=1 onehot",
                                  lambda: fo(ht, head), reps)
    assert bool(jnp.all(fg(ht, head) == fo(ht, head)))

    offs = jnp.arange(Pw, dtype=head.dtype)

    def takeP_gather(arr, hd):
        idxs = hd[:, None] + offs
        v = jnp.take_along_axis(arr, jnp.minimum(idxs, E - 1), axis=1)
        return jnp.where(idxs < E, v, INF)

    def takeP_onehot(arr, hd):
        idxs = hd[:, None] + offs
        m = jnp.arange(E)[None, None, :] == idxs[:, :, None]
        v = jnp.where(m, arr[:, None, :],
                      jnp.zeros((), arr.dtype)).sum(axis=-1)
        return jnp.where(idxs < E, v, INF)

    fgP, foP = jax.jit(takeP_gather), jax.jit(takeP_onehot)
    r["d_pop8_gather"] = timed_ms("d pop P=8 gather",
                                  lambda: fgP(ht, head), reps)
    r["d_pop8_onehot"] = timed_ms("d pop P=8 onehot",
                                  lambda: foP(ht, head), reps)
    assert bool(jnp.all(fgP(ht, head) == foP(ht, head)))

    print(json.dumps(r))
    return 0


# ---------------------------------------------------------------------
# variant 5: the cross-shard exchange in isolation
# ---------------------------------------------------------------------
def variant5(args: list[str]) -> int:
    """Flush-phase wall + per-flush ICI volume per exchange schedule
    at a real config's shapes. Each schedule gets its own engine:
    `dense` is the blind 4x auto-sized all_to_all pack (the
    pre-planner baseline), `planned` sizes every capacity (CAP
    included) from a measured warm-up record, `two_phase` and
    `all_gather` run the alternative schedules under the same plan.
    Single-shard meshes still time the flush (sort/merge work), with
    ICI volume 0."""
    cfg_path = args[0] if len(args) > 0 else "examples/tgen_1000.yaml"
    stop_s = float(args[1]) if len(args) > 1 else 3.0
    reps = int(args[2]) if len(args) > 2 else REPS

    from shadow_tpu import simtime
    from shadow_tpu._jax import jax, jnp
    from jax.sharding import NamedSharding
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.engine import INF

    stop = simtime.from_seconds(stop_s)
    res = {"variant": 5, "config": cfg_path,
           "platform": jax.devices()[0].platform,
           "n_devices": len(jax.devices()),
           "slice_sim_s": stop_s, "reps": reps, "schedules": {}}

    def build(label, exchange, planned):
        cfg = load_config(cfg_path)
        cfg.experimental.scheduler_policy = "tpu"
        cfg.general.stop_time = stop
        cfg.experimental.exchange = exchange
        if planned:
            cfg.experimental.capacity_plan = "auto"
            cfg.experimental.capacity_warmup = min(
                stop, simtime.from_seconds(3.0))
        else:
            # the dense baseline: blind auto CAP, no compaction
            cfg.experimental.outbox_compact = 0
            cfg.experimental.exchange_capacity = 0
        c = Controller(cfg)
        if planned:
            c.runner._plan_capacities(stop)
        return c

    for label, exchange, planned in (
            ("dense_all_to_all", "all_to_all", False),
            ("planned_all_to_all", "all_to_all", True),
            ("planned_two_phase", "two_phase", True),
            ("planned_all_gather", "all_gather", True)):
        t_build = time.perf_counter()
        c = build(label, exchange, planned)
        eng = c.runner.engine
        eff = dict(eng.effective)
        # mid-run state + one popped phase's outbox, flush timed alone
        st = eng.init_state(c.sim.starts)
        st_mid, _ = eng.run(st, stop=stop // 2, final_stop=stop)
        jax.block_until_ready(st_mid)
        repl = NamedSharding(eng.mesh, eng._repl_spec)
        shard = NamedSharding(eng.mesh, eng._shard_spec)
        hv = jax.device_put(jnp.asarray(eng.host_vertex), repl)
        wrld = eng.world()
        nxt, _ = map(int, eng._probe(st_mid))
        win_end = jnp.int64(min(nxt + max(1, eng.config.lookahead),
                                stop))
        ob = {"t": jax.device_put(
            jnp.full(eng._ob_shape_global, INF, jnp.int64), shard)}
        for f in ("k", "m", "s", "v"):
            ob[f] = jax.device_put(
                jnp.zeros(eng._ob_shape_global, jnp.int64), shard)
        st_pop, ob_full, _ = eng._pop_phase(st_mid, ob, hv, wrld,
                                            win_end)
        jax.block_until_ready(ob_full)
        ms = timed_ms(
            f"flush {label}", lambda: eng._flush_phase(
                st_pop, ob_full, hv, wrld, win_end), reps)
        res["schedules"][label] = {
            "flush_ms": ms,
            "build_s": round(time.perf_counter() - t_build, 1),
            "ici_rows_per_flush": eff["ICI_rows_per_flush"],
            "ici_bytes_per_flush": eff["ICI_bytes_per_flush"],
            "CAP": eff["CAP"], "CAP2": eff["CAP2"],
            "CX": eff["CX"], "OB": eff["OB"],
            "tp_groups": eff["tp_groups"],
        }
    dense = res["schedules"]["dense_all_to_all"]
    plan = res["schedules"]["planned_all_to_all"]
    if plan["ici_rows_per_flush"]:
        res["ici_reduction_planned_vs_dense"] = round(
            dense["ici_rows_per_flush"] / plan["ici_rows_per_flush"],
            2)
    print(json.dumps(res), flush=True)
    return 0


# ---------------------------------------------------------------------
# variant 6: compile/dispatch attribution (arxiv 1912.03413 style)
# ---------------------------------------------------------------------
def variant6(args: list[str]) -> int:
    """Where does the cold-start budget actually go? For the round
    program and each profiling split: jax tracing+lowering
    (``.lower()``), XLA compilation (``.compile()``), the AOT cache's
    serialize and deserialize-load walls (what a warm start pays
    instead of lower+compile), the first real dispatch, and the
    steady per-call dispatch — one table. Compiles are FRESH (the
    engine is built with the compile cache off and JAX's tracing
    cache bypassed), so the numbers are true cold costs."""
    cfg_path = args[0] if len(args) > 0 else "examples/tgen_1000.yaml"
    stop_s = float(args[1]) if len(args) > 1 else 3.0
    reps = int(args[2]) if len(args) > 2 else REPS

    import tempfile

    from shadow_tpu import simtime
    from shadow_tpu._jax import jax, jnp
    from jax.sharding import NamedSharding
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device import aotcache
    from shadow_tpu.device.engine import INF

    stop = simtime.from_seconds(stop_s)
    cfg = load_config(cfg_path)
    cfg.experimental.scheduler_policy = "tpu"
    cfg.experimental.compile_cache = "off"      # cold costs, measured
    cfg.general.stop_time = stop
    c = Controller(cfg)
    eng = c.runner.engine
    # the scratch cache for the serialize/load columns — constructing
    # it also disables jax's tracing cache for this process, so every
    # compile below is a TRUE cold compile
    cache = aotcache.AotCache(tempfile.mkdtemp(prefix="tpu_micro6_"))
    res = {"variant": 6, "config": cfg_path,
           "platform": jax.devices()[0].platform,
           "n_devices": len(jax.devices()),
           "slice_sim_s": stop_s, "reps": reps, "programs": {}}

    repl = NamedSharding(eng.mesh, eng._repl_spec)
    shard = NamedSharding(eng.mesh, eng._shard_spec)
    hv = jax.device_put(jnp.asarray(eng.host_vertex), repl)
    wrld = eng.world()
    st0 = eng.init_state(c.sim.starts)

    def fresh_ob():
        ob = {"t": jax.device_put(
            jnp.full(eng._ob_shape_global, INF, jnp.int64), shard)}
        for f in ("k", "m", "s", "v"):
            ob[f] = jax.device_put(
                jnp.zeros(eng._ob_shape_global, jnp.int64), shard)
        return ob

    win0 = jnp.int64(0)
    # per program: the jitted fn, its example args, and the
    # steady-state args (for `run`, the FINISHED state — the steady
    # number is the pure dispatch+probe floor, not a re-simulation)
    programs = [
        ("run", eng._run,
         (st0, hv, wrld, jnp.int64(stop), jnp.int64(stop))),
        ("pop_phase", eng._pop_phase,
         (st0, fresh_ob(), hv, wrld, win0)),
        ("flush_phase", None, None),      # args built from pop's out
    ]

    pop_out = None
    for name, jf, pargs in programs:
        if name == "flush_phase":
            jf = eng._flush_phase
            s_w, ob_w, _ = pop_out
            pargs = (s_w, ob_w, hv, wrld, win0)
        row = {}
        # _fresh_compile guards the cold-cost contract on EVERY
        # backend: when serialization is unsupported the AotCache
        # constructor leaves jax's tracing cache on, and a repeat
        # invocation would report a warm hit as "compile_s"
        with aotcache._fresh_compile():
            t0 = time.perf_counter()
            lowered = jf.lower(*pargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        row["lower_s"] = round(t1 - t0, 3)
        row["compile_s"] = round(t2 - t1, 3)
        # the AOT cache's side of the ledger: what a warm start pays
        # (deserialize+load) vs what it skips (lower+compile)
        key = f"micro6_{name}"
        t0 = time.perf_counter()
        stored = cache.store(key, compiled, meta={"program": name})
        row["aot_serialize_s"] = round(time.perf_counter() - t0, 3)
        loaded = cache.load(key) if stored else None
        if loaded is not None:
            t0 = time.perf_counter()
            cache.load(key)
            row["aot_load_s"] = round(time.perf_counter() - t0, 3)
            row["warm_vs_cold"] = round(
                (row["lower_s"] + row["compile_s"])
                / max(1e-9, row["aot_load_s"]), 1)
        else:
            # backend cannot round-trip this program — stamped, so
            # the table never reports a load wall that failed
            row["aot_load_s"] = None
            row["warm_vs_cold"] = None
        t0 = time.perf_counter()
        out = compiled(*pargs)
        jax.block_until_ready(out)
        row["first_dispatch_s"] = round(time.perf_counter() - t0, 3)
        if name == "pop_phase":
            pop_out = out
        if name == "run":
            # steady = re-dispatch on the FINISHED state: the program
            # runs zero rounds, so this is the dispatch+loop floor
            steady_args = (out[0], hv, wrld, jnp.int64(stop),
                           jnp.int64(stop))
        else:
            steady_args = pargs
        row["steady_ms"] = timed_ms(
            f"{name} steady", lambda: compiled(*steady_args), reps)
        res["programs"][name] = row

    # the one table (1912.03413-style dissection)
    cols = ("lower_s", "compile_s", "aot_serialize_s", "aot_load_s",
            "first_dispatch_s", "steady_ms", "warm_vs_cold")
    hdr = f"{'program':<14}" + "".join(f"{h:>18}" for h in cols)
    print(hdr, file=sys.stderr)
    for name, row in res["programs"].items():
        line = f"{name:<14}" + "".join(
            f"{row[h] if row[h] is not None else '-':>18}"
            for h in cols)
        print(line, file=sys.stderr)
    cold = sum(r["lower_s"] + r["compile_s"]
               for r in res["programs"].values())
    loads = [r["aot_load_s"] for r in res["programs"].values()]
    warm_ok = all(v is not None for v in loads)
    warm = sum(v or 0 for v in loads)
    res["cold_start_s"] = round(cold, 3)
    res["warm_start_s"] = round(warm, 3) if warm_ok else None
    warm_txt = (f"warm start (AOT load): {warm:.2f}s" if warm_ok
                else "warm start: unsupported on this backend")
    print(f"cold start (lower+compile, all programs): {cold:.2f}s; "
          f"{warm_txt}", file=sys.stderr)
    import shutil
    shutil.rmtree(cache.directory, ignore_errors=True)
    print(json.dumps(res), flush=True)
    return 0


VARIANTS = {1: variant1, 2: variant2, 3: variant3, 4: variant4,
            5: variant5, 6: variant6}


def main() -> int:
    ap = argparse.ArgumentParser(
        description="on-chip device-engine microbenchmarks")
    ap.add_argument("--variant", type=int, default=1,
                    choices=sorted(VARIANTS),
                    help="1 round-step attribution (default), "
                         "2 sorts-vs-gathers, 3 gatherless flush, "
                         "4 remaining gathers + one-hot pop, "
                         "5 exchange-in-isolation, "
                         "6 compile/dispatch attribution")
    ap.add_argument("args", nargs="*",
                    help="variant args (v1/v5/v6: [config] [stop_s] "
                         "[reps]; v2-4: [reps])")
    ns = ap.parse_args()

    signal.signal(signal.SIGALRM, lambda *a: sys.exit(9))
    signal.alarm(30 * 60 if ns.variant in (1, 5, 6) else 20 * 60)
    return VARIANTS[ns.variant](ns.args)


if __name__ == "__main__":
    sys.exit(main())
