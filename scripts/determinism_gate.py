#!/usr/bin/env python
"""Determinism CI gate (ref src/test/determinism/ +
determinism1_compare.cmake): run the same config twice and
byte-compare every host's outputs.

Two layers of comparison, mirroring the reference's diff loop:
  1. per-host trace checksums + packet counters from the engine;
  2. every file under each host's data directory (managed-process
     stdout/stderr), byte for byte.

Exit 0 = bit-identical; 1 = divergence (the reproducibility bar the
reference enforces in CI).

Usage: python scripts/determinism_gate.py [config.yaml] [--policy P]
Defaults to examples/minimal.yaml with the serial policy.

`--policy` also takes a comma list ("serial,thread,tpu"): the gate
then runs the config once per policy and additionally requires every
policy's per-host signature to be bit-identical to the first's — the
cross-policy determinism matrix (the fault-injection CI rung pins
serial/thread/tpu on examples/tgen_faults.yaml this way).

`--ensemble` switches to the CAMPAIGN gate (shadow_tpu/ensemble/):
the config must carry an `ensemble:` block. The gate runs the
campaign twice (run-to-run bit-identity over every replica), then
extracts replica `--replica` (default 0) and requires its per-host
signature to bit-match a STANDALONE run with that replica's
parameters under each `--policy` entry (default serial,tpu) — the
replica-i == standalone-i contract the ensemble engine guarantees.
Standalone runs pin experimental.runahead to the campaign's shared
lookahead (the min over all replicas' tables), since the window
sequence is part of the trace.
"""

from __future__ import annotations

import argparse
import filecmp
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_once(config: str, policy: str, data_dir: str):
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config(config)
    cfg.experimental.scheduler_policy = policy
    cfg.general.data_directory = data_dir
    c = Controller(cfg)
    stats = c.run()
    if not stats.ok:
        print(f"FAIL: run reported not-ok ({policy})")
        sys.exit(1)
    sig = [(h.name, h.trace_checksum, h.events_executed,
            h.packets_sent, h.packets_dropped, h.packets_delivered)
           for h in c.sim.hosts]
    return sig, stats


def compare_trees(a: str, b: str) -> list[str]:
    """Byte-compare every file under both trees; return differences."""
    diffs = []
    for root, _, files in os.walk(a):
        rel = os.path.relpath(root, a)
        for f in files:
            fa = os.path.join(root, f)
            fb = os.path.join(b, rel, f)
            if not os.path.exists(fb):
                diffs.append(f"only in run 1: {os.path.join(rel, f)}")
            elif not filecmp.cmp(fa, fb, shallow=False):
                diffs.append(f"differs: {os.path.join(rel, f)}")
    for root, _, files in os.walk(b):
        rel = os.path.relpath(root, b)
        for f in files:
            if not os.path.exists(os.path.join(a, rel, f)):
                diffs.append(f"only in run 2: {os.path.join(rel, f)}")
    return diffs


def run_ensemble_gate(config: str, policies: list[str],
                      replica: int) -> int:
    """Campaign determinism gate: run-to-run bit-identity of the whole
    ensemble, plus replica-`replica` == standalone bit-identity under
    each policy."""
    import numpy as np

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg0 = load_config(config)
    if cfg0.ensemble is None:
        print(f"FAIL: {config} has no ensemble: block "
              "(--ensemble needs a campaign config)")
        return 1
    R = cfg0.ensemble.replicas
    if not (0 <= replica < R):
        print(f"FAIL: --replica {replica} out of range (campaign has "
              f"{R} replicas)")
        return 1

    def run_campaign(data_dir: str):
        cfg = load_config(config)
        cfg.general.data_directory = data_dir
        # keep the campaign record out of the repo's artifacts/ (two
        # gate runs would also race onto one fingerprint-derived path)
        cfg.ensemble.record_path = os.path.join(data_dir,
                                                "ENSEMBLE.json")
        c = Controller(cfg)
        stats = c.run()
        if not stats.ok:
            print("FAIL: campaign run reported not-ok")
            sys.exit(1)
        return c, c.runner.final_state

    with tempfile.TemporaryDirectory() as tmp:
        c1, f1 = run_campaign(os.path.join(tmp, "e1", "shadow.data"))
        c2, f2 = run_campaign(os.path.join(tmp, "e2", "shadow.data"))
        rc = 0
        H = len(c1.sim.hosts)
        for key in ("chk", "n_exec", "n_sent", "n_drop", "n_deliv"):
            if not np.array_equal(np.asarray(f1[key]),
                                  np.asarray(f2[key])):
                rc = 1
                print(f"DETERMINISM FAILURE: campaign {key} differs "
                      "between two identical runs")
        desc = c1.runner.worlds.descriptors[replica]
        if desc["latency_scale"] != 1.0 or \
                desc["packet_loss_delta"] != 0.0:
            print(f"FAIL: replica {replica} varies "
                  "latency_scale/packet_loss_delta, which no "
                  "standalone config can reproduce — gate a replica "
                  "with the base tables (typically replica 0)")
            return 1
        ens_la = c1.runner.lookahead
        names = [h.name for h in c1.sim.hosts]
        sig_e = [(names[i], int(f1["chk"][replica, i]),
                  int(f1["n_exec"][replica, i]),
                  int(f1["n_sent"][replica, i]),
                  int(f1["n_drop"][replica, i]),
                  int(f1["n_deliv"][replica, i]))
                 for i in range(H)]
        for policy in policies:
            cfg = load_config(config)
            scheds = cfg.ensemble.fault_schedules
            sched = desc["fault_schedule"]
            cfg.ensemble = None
            cfg.experimental.scheduler_policy = policy
            cfg.experimental.runahead = ens_la
            cfg.general.seed = desc["seed"]
            if sched == "none":
                cfg.network.faults = []
            elif sched != "base":
                cfg.network.faults = list(scheds[sched])
            cfg.general.data_directory = os.path.join(
                tmp, f"alone_{policy}", "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: standalone {policy} run reported "
                      "not-ok")
                return 1
            sig_a = [(h.name, h.trace_checksum, h.events_executed,
                      h.packets_sent, h.packets_dropped,
                      h.packets_delivered) for h in c.sim.hosts]
            if sig_a != sig_e:
                rc = 1
                print(f"DETERMINISM FAILURE: campaign replica "
                      f"{replica} diverges from the standalone "
                      f"{policy} run with its parameters ({desc})")
                for a, b in zip(sig_e, sig_a):
                    if a != b:
                        print(f"  {a[0]}: ensemble {a[1:]} != "
                              f"standalone {b[1:]}")
        if rc == 0:
            print(f"ensemble determinism OK: {config} ({R} replicas "
                  f"bit-identical across 2 campaign runs; replica "
                  f"{replica} {desc} bit-matches standalone "
                  f"{','.join(policies)})")
        return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default="examples/minimal.yaml")
    ap.add_argument("--policy", default=None)
    ap.add_argument("--ensemble", action="store_true",
                    help="campaign gate: replica bit-identity vs "
                         "standalone runs (config needs ensemble:)")
    ap.add_argument("--replica", type=int, default=0,
                    help="which replica to compare standalone "
                         "(--ensemble only; default 0)")
    args = ap.parse_args()

    default_policy = "serial,tpu" if args.ensemble else "serial"
    policies = [p.strip()
                for p in (args.policy or default_policy).split(",")
                if p.strip()]

    if args.ensemble:
        return run_ensemble_gate(args.config, policies, args.replica)

    with tempfile.TemporaryDirectory() as tmp:
        d1 = os.path.join(tmp, "run1", "shadow.data")
        d2 = os.path.join(tmp, "run2", "shadow.data")
        sig1, stats1 = run_once(args.config, policies[0], d1)
        sig2, stats2 = run_once(args.config, policies[0], d2)

        rc = 0
        if sig1 != sig2:
            rc = 1
            print("DETERMINISM FAILURE: per-host signatures differ")
            for a, b in zip(sig1, sig2):
                if a != b:
                    print(f"  {a[0]}: {a[1:]} != {b[1:]}")
        diffs = compare_trees(d1, d2)
        if diffs:
            rc = 1
            print("DETERMINISM FAILURE: host files differ")
            for d in diffs[:20]:
                print(f"  {d}")

        # cross-policy matrix: every additional policy must reproduce
        # the first policy's per-host signature bit for bit
        for policy in policies[1:]:
            dp = os.path.join(tmp, f"run_{policy}", "shadow.data")
            sigp, _ = run_once(args.config, policy, dp)
            if sigp != sig1:
                rc = 1
                print(f"DETERMINISM FAILURE: policy {policy} diverges "
                      f"from {policies[0]}")
                for a, b in zip(sig1, sigp):
                    if a != b:
                        print(f"  {a[0]}: {a[1:]} != {b[1:]}")
            diffs = compare_trees(d1, dp)
            if diffs:
                rc = 1
                print(f"DETERMINISM FAILURE: host files differ "
                      f"({policies[0]} vs {policy})")
                for d in diffs[:20]:
                    print(f"  {d}")

        if rc == 0:
            across = f"across 2 runs of {policies[0]}"
            if len(policies) > 1:
                across += f" and policies {','.join(policies[1:])}"
            print(f"determinism OK: {args.config} "
                  f"({stats1.events_executed} events, "
                  f"{stats1.packets_sent} packets, bit-identical "
                  f"signatures and host files {across})")
        return rc


if __name__ == "__main__":
    sys.exit(main())
