#!/usr/bin/env python
"""Determinism CI gate (ref src/test/determinism/ +
determinism1_compare.cmake): run the same config twice and
byte-compare every host's outputs.

Two layers of comparison, mirroring the reference's diff loop:
  1. per-host trace checksums + packet counters from the engine;
  2. every file under each host's data directory (managed-process
     stdout/stderr), byte for byte.

Exit 0 = bit-identical; 1 = divergence (the reproducibility bar the
reference enforces in CI).

Usage: python scripts/determinism_gate.py [config.yaml] [--policy P]
Defaults to examples/minimal.yaml with the serial policy.

`--policy` also takes a comma list ("serial,thread,tpu"): the gate
then runs the config once per policy and additionally requires every
policy's per-host signature to be bit-identical to the first's — the
cross-policy determinism matrix (the fault-injection CI rung pins
serial/thread/tpu on examples/tgen_faults.yaml this way).
"""

from __future__ import annotations

import argparse
import filecmp
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_once(config: str, policy: str, data_dir: str):
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg = load_config(config)
    cfg.experimental.scheduler_policy = policy
    cfg.general.data_directory = data_dir
    c = Controller(cfg)
    stats = c.run()
    if not stats.ok:
        print(f"FAIL: run reported not-ok ({policy})")
        sys.exit(1)
    sig = [(h.name, h.trace_checksum, h.events_executed,
            h.packets_sent, h.packets_dropped, h.packets_delivered)
           for h in c.sim.hosts]
    return sig, stats


def compare_trees(a: str, b: str) -> list[str]:
    """Byte-compare every file under both trees; return differences."""
    diffs = []
    for root, _, files in os.walk(a):
        rel = os.path.relpath(root, a)
        for f in files:
            fa = os.path.join(root, f)
            fb = os.path.join(b, rel, f)
            if not os.path.exists(fb):
                diffs.append(f"only in run 1: {os.path.join(rel, f)}")
            elif not filecmp.cmp(fa, fb, shallow=False):
                diffs.append(f"differs: {os.path.join(rel, f)}")
    for root, _, files in os.walk(b):
        rel = os.path.relpath(root, b)
        for f in files:
            if not os.path.exists(os.path.join(a, rel, f)):
                diffs.append(f"only in run 2: {os.path.join(rel, f)}")
    return diffs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default="examples/minimal.yaml")
    ap.add_argument("--policy", default="serial")
    args = ap.parse_args()

    policies = [p.strip() for p in args.policy.split(",") if p.strip()]

    with tempfile.TemporaryDirectory() as tmp:
        d1 = os.path.join(tmp, "run1", "shadow.data")
        d2 = os.path.join(tmp, "run2", "shadow.data")
        sig1, stats1 = run_once(args.config, policies[0], d1)
        sig2, stats2 = run_once(args.config, policies[0], d2)

        rc = 0
        if sig1 != sig2:
            rc = 1
            print("DETERMINISM FAILURE: per-host signatures differ")
            for a, b in zip(sig1, sig2):
                if a != b:
                    print(f"  {a[0]}: {a[1:]} != {b[1:]}")
        diffs = compare_trees(d1, d2)
        if diffs:
            rc = 1
            print("DETERMINISM FAILURE: host files differ")
            for d in diffs[:20]:
                print(f"  {d}")

        # cross-policy matrix: every additional policy must reproduce
        # the first policy's per-host signature bit for bit
        for policy in policies[1:]:
            dp = os.path.join(tmp, f"run_{policy}", "shadow.data")
            sigp, _ = run_once(args.config, policy, dp)
            if sigp != sig1:
                rc = 1
                print(f"DETERMINISM FAILURE: policy {policy} diverges "
                      f"from {policies[0]}")
                for a, b in zip(sig1, sigp):
                    if a != b:
                        print(f"  {a[0]}: {a[1:]} != {b[1:]}")
            diffs = compare_trees(d1, dp)
            if diffs:
                rc = 1
                print(f"DETERMINISM FAILURE: host files differ "
                      f"({policies[0]} vs {policy})")
                for d in diffs[:20]:
                    print(f"  {d}")

        if rc == 0:
            across = f"across 2 runs of {policies[0]}"
            if len(policies) > 1:
                across += f" and policies {','.join(policies[1:])}"
            print(f"determinism OK: {args.config} "
                  f"({stats1.events_executed} events, "
                  f"{stats1.packets_sent} packets, bit-identical "
                  f"signatures and host files {across})")
        return rc


if __name__ == "__main__":
    sys.exit(main())
