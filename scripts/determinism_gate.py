#!/usr/bin/env python
"""Determinism CI gate (ref src/test/determinism/ +
determinism1_compare.cmake): run the same config twice and
byte-compare every host's outputs.

Two layers of comparison, mirroring the reference's diff loop:
  1. per-host trace checksums + packet counters from the engine;
  2. every file under each host's data directory (managed-process
     stdout/stderr), byte for byte.

Exit 0 = bit-identical; 1 = divergence (the reproducibility bar the
reference enforces in CI).

Usage: python scripts/determinism_gate.py [config.yaml] [--policy P]
Defaults to examples/minimal.yaml with the serial policy.

`--policy` also takes a comma list ("serial,thread,tpu"): the gate
then runs the config once per policy and additionally requires every
policy's per-host signature to be bit-identical to the first's — the
cross-policy determinism matrix (the fault-injection CI rung pins
serial/thread/tpu on examples/tgen_faults.yaml this way). A tpu
entry may pin the exchange variant with a ":" suffix
("tpu:all_to_all,tpu:all_gather,tpu:two_phase,tpu:auto") — the
forced-multichip CI rung runs this matrix under
XLA_FLAGS=--xla_force_host_platform_device_count=4, pinning every
cross-shard exchange schedule bit-identical to the serial oracle;
"tpu:auto" turns on capacity_plan: auto so the choice resolves from
a measured occ_x record.

`--preempt` switches to the PREEMPTION gate (device/supervise.py):
run the config uninterrupted (tpu policy), then run it supervised in
a subprocess (periodic validated checkpoints + state audit), SIGTERM
it as soon as the first rotating checkpoint lands, require the
distinct preemption rc (75, EX_TEMPFAIL), resume from the rotation
base, and require the resumed trace to bit-match the uninterrupted
run. Combine with `--ensemble` to preempt a campaign mid-flight
instead (the resumed replica stack must bit-match the uninterrupted
campaign's).

`--compile-cache` switches to the WARM-START gate (the persistent
AOT compile cache, device/aotcache.py): run the config (tpu policy)
three times against one shared cache directory — cold (must miss and
store), warm (must HIT, skipping the compile), and with every cache
entry deliberately corrupted (must degrade to a loud recompile) —
and require all three runs bit-identical. This pins the cache
correctness contract: a cache hit is bit-identical to a fresh
compile, and a bad entry recompiles, never loads a wrong trace. On
backends without executable serialization the bit-identity legs
still run (stamped unsupported; the hit/miss pattern is waived).

`--telemetry` switches to the FLIGHT-RECORDER gate (shadow_tpu/obs):
run the config (tpu policy) under telemetry off / summary / trace
and require bit-identical per-host signatures — tracing must never
perturb the simulation. The trace run must leave a Perfetto-loadable
TRACE_*.trace.json, the streamed TRACE_*.jsonl span log, and a
METRICS_*.json whose per-phase walls sum to within 10% of the
recorded total; $TELEMETRY_TRACE_OUT receives a copy of the
.trace.json for CI artifact upload.

`--tuned` switches to the STRATEGY-AUTOTUNER gate (shadow_tpu/tune/):
a real mini-tune writes a PLAN record through the full
produce-persist-adopt pipeline; the adopted run and a COMPOSED
adversarial plan (every applicable knob at its most aggressive
candidate at once, reshaping ones included) must both bit-match the
default-knob run — a tuned plan changes wall time only, and the
composition of individually-pinned knobs stays pinned.

`--pipelined` switches to the PIPELINED-DISPATCH gate
(device/supervise.py segment pipeline): pipeline_depth {1,2,4} —
dispatch-segmented with the state-audit word compiled in — must be
bit-identical to the serial oracle; a supervised child with a
depth-4 window in flight is SIGTERM'd (the drain must complete the
window and exit rc 75), and its checkpoint must resume at depth 1
bit-identically (cross-depth resume: depth is host orchestration,
never part of the checkpoint contract).

`--chaos` switches to the ELASTIC MESH-SHRINK gate (device/chaos.py
+ failover: shrink): on a forced >= 4-device mesh, a scripted device
loss (deterministic chaos injector) kills mesh device 1 at the 2nd
dispatch issue; retries exhaust, the run re-shards the last
validated state onto the 3 survivors and continues on-device under
the state-audit word. The shrunk run must bit-match BOTH the serial
oracle and an uninterrupted 3-shard run, for a standalone run AND
an ensemble campaign (`--chaos-ensemble` names the campaign
config); a post-shrink rotating checkpoint must stamp the shrunken
geometry and resume bit-identically on the full pool; and a
scripted corrupted-rotation-entry schedule must engage the
newest-readable fallback.

`--degrade` switches to the ADMISSION + DEGRADATION-LADDER gate
(device/capacity.py admission + device/supervise.py oom ladder): a
run must never OOM blind. `admission: strict` under a deliberately
tiny `device_memory_budget` must refuse with the readable "needs X,
budget Y on N devices" diagnostic before ANY compile; a scripted
RESOURCE_EXHAUSTED (chaos oom) at the 0th program compile (cold AOT
cache, so the compile really runs) and at the 2nd dispatch issue of
a depth-4 pipelined run must each walk the degradation ladder —
degrade >= 1, the retry budget NOT exhausted — and finish
bit-identical to the serial oracle; and `--chaos-ensemble`'s
campaign run in sequential replica batches
(ensemble.replica_batch=2) must bit-match the full-vmap campaign
and a standalone run of replica 0 (needs >= 4 devices).

`--ensemble` switches to the CAMPAIGN gate (shadow_tpu/ensemble/):
the config must carry an `ensemble:` block. The gate runs the
campaign twice (run-to-run bit-identity over every replica), then
extracts replica `--replica` (default 0) and requires its per-host
signature to bit-match a STANDALONE run with that replica's
parameters under each `--policy` entry (default serial,tpu) — the
replica-i == standalone-i contract the ensemble engine guarantees.
Standalone runs pin experimental.runahead to the campaign's shared
lookahead (the min over all replicas' tables), since the window
sequence is part of the trace.
"""

from __future__ import annotations

import argparse
import filecmp
import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_once(config: str, policy: str, data_dir: str):
    """One gated run. `policy` may carry an exchange-variant suffix
    for the device engine — "tpu:two_phase", "tpu:all_gather",
    "tpu:auto", ... — the forced-multichip CI rung pins every
    exchange schedule bit-identical to the serial oracle this way.
    "tpu:auto" additionally turns on capacity_plan: auto so the
    choice actually resolves from a measured occ_x record."""
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    policy, _, exchange = policy.partition(":")
    cfg = load_config(config)
    cfg.experimental.scheduler_policy = policy
    if exchange:
        if policy != "tpu":
            print(f"FAIL: exchange suffix {exchange!r} only applies "
                  "to the tpu policy")
            sys.exit(1)
        # the suffix lands after load_config's schema validation, so
        # re-check it here — a typo must FAIL cleanly, not surface as
        # a deep engine traceback after the build work
        valid = ("all_to_all", "all_gather", "two_phase", "auto")
        if exchange not in valid:
            print(f"FAIL: exchange suffix {exchange!r} is not one of "
                  f"{list(valid)}")
            sys.exit(1)
        cfg.experimental.exchange = exchange
        if exchange == "auto" and \
                cfg.experimental.capacity_plan == "static":
            cfg.experimental.capacity_plan = "auto"
    cfg.general.data_directory = data_dir
    c = Controller(cfg)
    stats = c.run()
    if not stats.ok:
        print(f"FAIL: run reported not-ok ({policy})")
        sys.exit(1)
    sig = [(h.name, h.trace_checksum, h.events_executed,
            h.packets_sent, h.packets_dropped, h.packets_delivered)
           for h in c.sim.hosts]
    return sig, stats


def compare_trees(a: str, b: str) -> list[str]:
    """Byte-compare every file under both trees; return differences."""
    diffs = []
    for root, _, files in os.walk(a):
        rel = os.path.relpath(root, a)
        for f in files:
            fa = os.path.join(root, f)
            fb = os.path.join(b, rel, f)
            if not os.path.exists(fb):
                diffs.append(f"only in run 1: {os.path.join(rel, f)}")
            elif not filecmp.cmp(fa, fb, shallow=False):
                diffs.append(f"differs: {os.path.join(rel, f)}")
    for root, _, files in os.walk(b):
        rel = os.path.relpath(root, b)
        for f in files:
            if not os.path.exists(os.path.join(a, rel, f)):
                diffs.append(f"only in run 2: {os.path.join(rel, f)}")
    return diffs


def run_ensemble_gate(config: str, policies: list[str],
                      replica: int) -> int:
    """Campaign determinism gate: run-to-run bit-identity of the whole
    ensemble, plus replica-`replica` == standalone bit-identity under
    each policy."""
    import numpy as np

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    cfg0 = load_config(config)
    if cfg0.ensemble is None:
        print(f"FAIL: {config} has no ensemble: block "
              "(--ensemble needs a campaign config)")
        return 1
    R = cfg0.ensemble.replicas
    if not (0 <= replica < R):
        print(f"FAIL: --replica {replica} out of range (campaign has "
              f"{R} replicas)")
        return 1

    def run_campaign(data_dir: str):
        cfg = load_config(config)
        cfg.general.data_directory = data_dir
        # keep the campaign record out of the repo's artifacts/ (two
        # gate runs would also race onto one fingerprint-derived path)
        cfg.ensemble.record_path = os.path.join(data_dir,
                                                "ENSEMBLE.json")
        c = Controller(cfg)
        stats = c.run()
        if not stats.ok:
            print("FAIL: campaign run reported not-ok")
            sys.exit(1)
        return c, c.runner.final_state

    with tempfile.TemporaryDirectory() as tmp:
        c1, f1 = run_campaign(os.path.join(tmp, "e1", "shadow.data"))
        c2, f2 = run_campaign(os.path.join(tmp, "e2", "shadow.data"))
        rc = 0
        H = len(c1.sim.hosts)
        for key in ("chk", "n_exec", "n_sent", "n_drop", "n_deliv"):
            if not np.array_equal(np.asarray(f1[key]),
                                  np.asarray(f2[key])):
                rc = 1
                print(f"DETERMINISM FAILURE: campaign {key} differs "
                      "between two identical runs")
        desc = c1.runner.worlds.descriptors[replica]
        if desc["latency_scale"] != 1.0 or \
                desc["packet_loss_delta"] != 0.0:
            print(f"FAIL: replica {replica} varies "
                  "latency_scale/packet_loss_delta, which no "
                  "standalone config can reproduce — gate a replica "
                  "with the base tables (typically replica 0)")
            return 1
        ens_la = c1.runner.lookahead
        names = [h.name for h in c1.sim.hosts]
        sig_e = [(names[i], int(f1["chk"][replica, i]),
                  int(f1["n_exec"][replica, i]),
                  int(f1["n_sent"][replica, i]),
                  int(f1["n_drop"][replica, i]),
                  int(f1["n_deliv"][replica, i]))
                 for i in range(H)]
        for policy in policies:
            cfg = load_config(config)
            scheds = cfg.ensemble.fault_schedules
            sched = desc["fault_schedule"]
            cfg.ensemble = None
            cfg.experimental.scheduler_policy = policy
            cfg.experimental.runahead = ens_la
            cfg.general.seed = desc["seed"]
            if sched == "none":
                cfg.network.faults = []
            elif sched != "base":
                cfg.network.faults = list(scheds[sched])
            cfg.general.data_directory = os.path.join(
                tmp, f"alone_{policy}", "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: standalone {policy} run reported "
                      "not-ok")
                return 1
            sig_a = [(h.name, h.trace_checksum, h.events_executed,
                      h.packets_sent, h.packets_dropped,
                      h.packets_delivered) for h in c.sim.hosts]
            if sig_a != sig_e:
                rc = 1
                print(f"DETERMINISM FAILURE: campaign replica "
                      f"{replica} diverges from the standalone "
                      f"{policy} run with its parameters ({desc})")
                for a, b in zip(sig_e, sig_a):
                    if a != b:
                        print(f"  {a[0]}: ensemble {a[1:]} != "
                              f"standalone {b[1:]}")
        if rc == 0:
            print(f"ensemble determinism OK: {config} ({R} replicas "
                  f"bit-identical across 2 campaign runs; replica "
                  f"{replica} {desc} bit-matches standalone "
                  f"{','.join(policies)})")
        return rc


def _preempt_child(config: str, base: str, every_ns: int,
                   data_dir: str, ensemble: bool, extra=None):
    """Launch the supervised run as a child CLI process (the gate
    needs a real SIGTERM against a real process, not an in-process
    flag), SIGTERM it once the first rotating checkpoint exists, and
    return its exit code. `extra` appends raw -o override pairs (the
    pipelined gate preempts a child with a depth-4 window in
    flight)."""
    import signal
    import subprocess
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    overrides = [
        "-o", f"experimental.checkpoint_save={base}",
        "-o", f"experimental.checkpoint_every={every_ns}ns",
        "-o", "experimental.state_audit=true",
        "-o", f"general.data_directory={data_dir}",
    ]
    for o in (extra or []):
        overrides += ["-o", o]
    if not ensemble:
        overrides += ["-o", "experimental.scheduler_policy=tpu"]
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; from shadow_tpu.cli import main; "
         "sys.exit(main(sys.argv[1:]))", config] + overrides,
        env=env, cwd=repo)
    import glob as _glob
    deadline = time.monotonic() + 900
    signaled = False
    while proc.poll() is None and time.monotonic() < deadline:
        if not signaled and _glob.glob(_glob.escape(base) + ".t*"):
            proc.send_signal(signal.SIGTERM)
            signaled = True
        time.sleep(0.05)
    if proc.poll() is None:
        proc.kill()
        proc.wait()
        print("FAIL: supervised run hung past the gate deadline")
        return -1
    if not signaled:
        print("FAIL: the run finished before the first rotating "
              "checkpoint appeared — shrink checkpoint_every or grow "
              "stop_time so the gate can preempt mid-flight")
        return -1
    return proc.returncode


def run_preempt_gate(config: str, ensemble: bool) -> int:
    """SIGTERM mid-run -> resume must bit-match the uninterrupted
    run, and the preempted process must exit with the distinct
    preemption rc."""
    import numpy as np

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.supervise import EXIT_PREEMPTED

    cfg0 = load_config(config)
    if ensemble and cfg0.ensemble is None:
        print(f"FAIL: {config} has no ensemble: block")
        return 1
    every_ns = max(1, cfg0.general.stop_time // 8)

    def run_full(data_dir: str, extra=None):
        # the policy override must ride load_config's override list:
        # schema validation (checkpoint knobs require the tpu policy)
        # runs during parsing, before any post-hoc attribute edit
        extra = list(extra or [])
        if not ensemble:
            extra.append("experimental.scheduler_policy=tpu")
        cfg = load_config(config, overrides=extra)
        cfg.general.data_directory = data_dir
        if ensemble:
            cfg.ensemble.record_path = os.path.join(data_dir,
                                                    "ENSEMBLE.json")
        c = Controller(cfg)
        stats = c.run()
        if not stats.ok:
            print("FAIL: run reported not-ok")
            sys.exit(1)
        if ensemble:
            f = c.runner.final_state
            return {k: np.asarray(f[k])
                    for k in ("chk", "n_exec", "n_sent", "n_drop",
                              "n_deliv")}
        return [(h.name, h.trace_checksum, h.events_executed,
                 h.packets_sent, h.packets_dropped,
                 h.packets_delivered) for h in c.sim.hosts]

    with tempfile.TemporaryDirectory() as tmp:
        sig_full = run_full(os.path.join(tmp, "full", "shadow.data"))
        base = os.path.join(tmp, "ck.npz")
        rc = _preempt_child(config, base, every_ns,
                            os.path.join(tmp, "pre", "shadow.data"),
                            ensemble)
        if rc != EXIT_PREEMPTED:
            print(f"FAIL: preempted run exited rc {rc}, expected "
                  f"the distinct preemption rc {EXIT_PREEMPTED}")
            return 1
        sig_res = run_full(
            os.path.join(tmp, "res", "shadow.data"),
            extra=[f"experimental.checkpoint_load={base}"])
        if ensemble:
            bad = [k for k in sig_full
                   if not np.array_equal(sig_full[k], sig_res[k])]
            if bad:
                print(f"DETERMINISM FAILURE: resumed campaign {bad} "
                      "diverge from the uninterrupted campaign")
                return 1
        elif sig_res != sig_full:
            print("DETERMINISM FAILURE: resumed run diverges from "
                  "the uninterrupted run")
            for a, b in zip(sig_full, sig_res):
                if a != b:
                    print(f"  {a[0]}: {a[1:]} != {b[1:]}")
            return 1
        kind = "ensemble campaign" if ensemble else "standalone tpu"
        print(f"preemption OK: {config} ({kind}: SIGTERM mid-run -> "
              f"rc {EXIT_PREEMPTED}, resume from the checkpoint "
              "rotation bit-matches the uninterrupted run)")
        return 0


def run_compile_cache_gate(config: str) -> int:
    """Warm-start gate (device/aotcache.py): cold run populates the
    cache, warm run must HIT and bit-match, a deliberately corrupted
    cache must degrade to a recompile that still bit-matches."""
    import glob as _glob

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.aotcache import ENTRY_SUFFIX

    with tempfile.TemporaryDirectory() as tmp:
        cache_dir = os.path.join(tmp, "aot")

        def once(tag: str):
            cfg = load_config(config)
            cfg.experimental.scheduler_policy = "tpu"
            cfg.experimental.compile_cache = cache_dir
            cfg.general.data_directory = os.path.join(
                tmp, tag, "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: {tag} run reported not-ok")
                sys.exit(1)
            sig = [(h.name, h.trace_checksum, h.events_executed,
                    h.packets_sent, h.packets_dropped,
                    h.packets_delivered) for h in c.sim.hosts]
            return sig, (stats.compile_cache or {})

        sig_cold, rep_cold = once("cold")
        unsupported = rep_cold.get("unsupported", False)
        if not unsupported and not rep_cold.get("misses"):
            print("FAIL: cold run against an empty cache directory "
                  f"reported no compile miss ({rep_cold})")
            return 1

        sig_warm, rep_warm = once("warm")
        rc = 0
        if sig_warm != sig_cold:
            rc = 1
            print("DETERMINISM FAILURE: cache-hit run diverges from "
                  "the fresh-compile run")
            for a, b in zip(sig_cold, sig_warm):
                if a != b:
                    print(f"  {a[0]}: cold {a[1:]} != warm {b[1:]}")
        if not unsupported:
            if not rep_warm.get("hits") or rep_warm.get("misses"):
                rc = 1
                print("FAIL: warm run did not hit the populated "
                      f"cache (hits={rep_warm.get('hits')}, "
                      f"misses={rep_warm.get('misses')})")
            if rep_warm.get("compile_s", 0) != 0:
                rc = 1
                print("FAIL: warm run still paid "
                      f"{rep_warm['compile_s']}s of compile")

        # corrupt every entry mid-payload: the next run must warn,
        # recompile, and stay bit-identical — degradation is always
        # to a fresh compile, never to a wrong trace
        entries = _glob.glob(os.path.join(
            cache_dir, "*" + ENTRY_SUFFIX))
        if not unsupported and not entries:
            print("FAIL: no cache entries on disk after two runs")
            return 1
        for p in entries:
            size = os.path.getsize(p)
            with open(p, "r+b") as f:
                f.truncate(max(1, size // 3))
        sig_corrupt, rep_corrupt = once("corrupt")
        if sig_corrupt != sig_cold:
            rc = 1
            print("DETERMINISM FAILURE: the corrupted-cache run "
                  "diverges from the fresh-compile run")
        if not unsupported and rep_corrupt.get("hits"):
            rc = 1
            print("FAIL: a corrupted entry was reported as a cache "
                  "hit — the corruption check is not firing")

        if rc == 0:
            mode = ("bit-identity only; executable serialization "
                    "unsupported on this backend" if unsupported
                    else f"cold miss {rep_cold.get('compile_s')}s "
                         f"compile -> warm hit "
                         f"{rep_warm.get('load_s')}s load -> "
                         "corrupted entries recompiled")
            print(f"compile-cache OK: {config} (3 runs bit-identical"
                  f"; {mode})")
        return rc


def run_telemetry_gate(config: str) -> int:
    """Flight-recorder gate (shadow_tpu/obs): the same config under
    telemetry off / summary / trace (tpu policy) must produce
    bit-identical per-host signatures — tracing must never perturb
    the simulation. The trace run must additionally leave a
    Perfetto-loadable TRACE_*.trace.json, a streamed TRACE_*.jsonl,
    and a METRICS_*.json whose per-phase walls sum to within 10% of
    the recorded total. $TELEMETRY_TRACE_OUT (a file path) receives a
    copy of the .trace.json so CI can upload it as an artifact."""
    import glob as _glob
    import json
    import shutil

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    with tempfile.TemporaryDirectory() as tmp:
        sigs, summaries, tel_dirs = {}, {}, {}
        for mode in ("off", "summary", "trace"):
            cfg = load_config(config)
            cfg.experimental.scheduler_policy = "tpu"
            cfg.experimental.telemetry = mode
            tel_dirs[mode] = os.path.join(tmp, f"tel_{mode}")
            cfg.experimental.telemetry_path = tel_dirs[mode]
            cfg.general.data_directory = os.path.join(
                tmp, mode, "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: telemetry={mode} run reported not-ok")
                return 1
            sigs[mode] = [(h.name, h.trace_checksum,
                           h.events_executed, h.packets_sent,
                           h.packets_dropped, h.packets_delivered)
                          for h in c.sim.hosts]
            summaries[mode] = stats.telemetry
        rc = 0
        for mode in ("summary", "trace"):
            if sigs[mode] != sigs["off"]:
                rc = 1
                print(f"DETERMINISM FAILURE: telemetry={mode} "
                      "diverges from telemetry=off — tracing "
                      "perturbed the simulation")
                for a, b in zip(sigs["off"], sigs[mode]):
                    if a != b:
                        print(f"  {a[0]}: off {a[1:]} != {mode} "
                              f"{b[1:]}")
        if summaries["off"] is not None:
            rc = 1
            print("FAIL: telemetry=off still published a summary "
                  "(SimStats.telemetry must be None)")
        if not summaries["summary"] or \
                "phases" not in (summaries["summary"] or {}):
            rc = 1
            print("FAIL: telemetry=summary published no phase walls")
        traces = _glob.glob(os.path.join(tel_dirs["trace"],
                                         "TRACE_*.trace.json"))
        jsonls = _glob.glob(os.path.join(tel_dirs["trace"],
                                         "TRACE_*.jsonl"))
        metrics = _glob.glob(os.path.join(tel_dirs["trace"],
                                          "METRICS_*.json"))
        if not (traces and jsonls and metrics):
            print(f"FAIL: trace run left trace.json={traces} "
                  f"jsonl={jsonls} metrics={metrics} — expected all "
                  "three artifacts")
            return 1
        with open(traces[0]) as f:
            tr = json.load(f)
        if not tr.get("traceEvents"):
            rc = 1
            print(f"FAIL: {traces[0]} has no traceEvents — not a "
                  "loadable Chrome/Perfetto trace")
        with open(metrics[0]) as f:
            m = json.load(f)
        total = m.get("total_wall_s", 0.0)
        ssum = sum(m.get("phases", {}).values())
        if total <= 0 or abs(ssum - total) > 0.1 * total:
            rc = 1
            print(f"FAIL: METRICS phase walls sum to {ssum:.3f}s vs "
                  f"total {total:.3f}s — attribution is off by more "
                  "than 10%")
        out = os.environ.get("TELEMETRY_TRACE_OUT")
        if out:
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            shutil.copyfile(traces[0], out)
            print(f"trace artifact copied -> {out}")
        if rc == 0:
            dom = m.get("dominant_phase")
            print(f"telemetry OK: {config} (off/summary/trace "
                  "bit-identical; trace run wrote "
                  f"{os.path.basename(traces[0])} + "
                  f"{os.path.basename(metrics[0])}, phase walls sum "
                  f"{ssum:.3f}s of {total:.3f}s total, dominant "
                  f"phase {dom})")
        return rc


def run_analyze_consistency_gate(config: str) -> int:
    """Static-analysis consistency gate (shadow_tpu/analyze): the
    collective registry Pass 1 audits against must match what the
    RUNTIME engine reports, so the static allowlist can never
    silently drift from the real program. Three cheap checks on the
    config's device engine:

    1. registry-vs-effective: ``engine.collective_registry()`` must
       pin exactly the exchange variant and capacities
       ``engine.effective{}`` resolved (mover primitive per variant,
       CAP/CAP2 buffer dims);
    2. the Pass-1 jaxpr audit of the built engine must come up clean
       (and, on a multi-shard mesh, must SEE the registered mover in
       the lowered program — registry says ppermute, program must
       contain ppermute);
    3. analyzer-perturbs-nothing: the config runs once, the audit
       traces every program in-process, the config runs again — both
       runs' per-host signatures must be bit-identical (the
       --telemetry-style spot check; the audit only lowers, never
       executes).
    """
    from shadow_tpu.analyze import jaxpr_audit
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    with tempfile.TemporaryDirectory() as tmp:
        os.environ.setdefault("SHADOW_TPU_OCC_DIR",
                              os.path.join(tmp, "occ"))
        cfg = load_config(config)
        cfg.experimental.scheduler_policy = "tpu"
        cfg.general.data_directory = os.path.join(
            tmp, "probe", "shadow.data")
        c = Controller(cfg)
        if c.runner is None or c.runner.engine is None:
            print("FAIL: config did not build a device engine "
                  "(--analyze-consistency needs a tpu-policy device "
                  "config)")
            return 1
        engine = c.runner.engine
        eff = engine.effective
        reg = engine.collective_registry()
        rc = 0

        # 1. registry <-> effective{}
        mover = jaxpr_audit.EXCHANGE_MOVER.get(eff["exchange"])
        if mover is None:
            print(f"FAIL: effective exchange {eff['exchange']!r} has "
                  "no registered mover mapping")
            rc = 1
        elif engine.n_shards > 1 and mover not in reg:
            print(f"FAIL: effective exchange {eff['exchange']!r} "
                  f"needs mover {mover!r} but the collective "
                  f"registry only pins {sorted(reg)}")
            rc = 1
        caps_want = {"all_to_all": (eff["CAP"],),
                     "two_phase": (eff["CAP"], eff["CAP2"])}
        want = caps_want.get(eff["exchange"])
        if engine.n_shards > 1 and want is not None:
            got = tuple(reg.get(mover, {}).get("caps") or ())
            if got != tuple(int(x) for x in want):
                print(f"FAIL: registry pins {mover} caps {got}, "
                      f"effective says {want}")
                rc = 1

        # 2. the static audit of the real engine (traces only)
        found = jaxpr_audit.audit_engine(engine, "gate")
        errors = [f for f in found if f.severity == "error"]
        for f in errors:
            print(f"FAIL: {f.format()}")
        rc = rc or (1 if errors else 0)

        # 3. bit-identity across an in-process audit: run, audit,
        # run again — the analyzer must perturb nothing
        d1 = os.path.join(tmp, "run1", "shadow.data")
        d2 = os.path.join(tmp, "run2", "shadow.data")
        sig1, stats1 = run_once(config, "tpu", d1)
        jaxpr_audit.audit_engine(engine, "gate-again")
        sig2, _ = run_once(config, "tpu", d2)
        if sig1 != sig2:
            rc = 1
            print("FAIL: per-host signatures differ across an "
                  "in-process jaxpr audit — the analyzer perturbed "
                  "the run")
            for a, b in zip(sig1, sig2):
                if a != b:
                    print(f"  {a[0]}: {a[1:]} != {b[1:]}")

        if rc == 0:
            print(f"analyze-consistency OK: {config} (exchange "
                  f"{eff['exchange']}, registry caps match "
                  f"CAP={eff['CAP']}/CAP2={eff['CAP2']}, engine "
                  f"audit clean, {stats1.events_executed} events "
                  "bit-identical across an in-process audit)")
        return rc


def run_tuned_gate(config: str) -> int:
    """Strategy-autotuner gate (shadow_tpu/tune/): a tuned plan must
    change WALL time only. Three legs against one config (tpu
    policy):

    1. a real mini-tune (tune/trials.py coordinate descent, small
       budget, quarter window) writes a PLAN record through
       tune/plan.py — the full produce-persist-adopt pipeline runs,
       and the record must carry the chosen knobs and the trial
       ledger;
    2. the adopted run (``strategy_plan: <plan>``) must bit-match
       the default-knob run and surface adoption provenance;
    3. a COMPOSED adversarial plan — every applicable knob moved to
       its most aggressive candidate at once, including the
       program-reshaping ones — must also bit-match: each knob is
       individually bit-identity-pinned, and this leg pins the
       composition the tuner relies on.
    """
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller, build
    from shadow_tpu.device.runner import device_twin
    from shadow_tpu.tune import plan as planmod
    from shadow_tpu.tune import space
    from shadow_tpu.tune.trials import Tuner

    cfg0 = load_config(config)
    stop = cfg0.general.stop_time
    sim = build(cfg0)
    twin = device_twin(sim)
    n_hosts = len(sim.hosts)
    del sim

    with tempfile.TemporaryDirectory() as tmp:
        os.environ.setdefault("SHADOW_TPU_OCC_DIR",
                              os.path.join(tmp, "occ"))

        def once(tag: str, strategy_plan: str):
            cfg = load_config(config)
            cfg.experimental.scheduler_policy = "tpu"
            cfg.experimental.strategy_plan = strategy_plan
            cfg.general.data_directory = os.path.join(
                tmp, tag, "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: {tag} run reported not-ok")
                sys.exit(1)
            sig = [(h.name, h.trace_checksum, h.events_executed,
                    h.packets_sent, h.packets_dropped,
                    h.packets_delivered) for h in c.sim.hosts]
            return sig, stats

        # leg 1: the real pipeline — tune, persist, reload
        tuner = Tuner(config, window_ns=max(1, stop // 4), budget=6)
        body = tuner.search("coordinate_descent")
        plan_file = os.path.join(tmp, "PLAN_gate.json")
        planmod.save_plan({
            "format": planmod.FORMAT,
            "workload": {**planmod.workload_stamp(twin, n_hosts),
                         "stop_time": int(stop),
                         "seed": int(cfg0.general.seed)},
            "source": "determinism_gate --tuned",
            **body,
        }, plan_file)
        rec = planmod.load_plan(plan_file)
        if "trials" not in rec or not rec["trials"]:
            print("FAIL: the PLAN record carries no trial ledger")
            return 1
        diverged = [t for t in rec["trials"]
                    if "diverged" in t.get("error", "")]
        if diverged:
            print(f"DETERMINISM FAILURE: {len(diverged)} trial(s) "
                  "diverged from the default-knob signature during "
                  "the mini-tune")
            return 1

        sig_def, _ = once("default", "off")
        sig_tuned, stats_tuned = once("tuned", plan_file)
        rc = 0
        if sig_tuned != sig_def:
            rc = 1
            print("DETERMINISM FAILURE: the tuned-plan run diverges "
                  "from the default-knob run")
            for a, b in zip(sig_def, sig_tuned):
                if a != b:
                    print(f"  {a[0]}: default {a[1:]} != tuned "
                          f"{b[1:]}")
        if stats_tuned.strategy_plan is None:
            rc = 1
            print("FAIL: the adopted run surfaced no strategy-plan "
                  "provenance (SimStats.strategy_plan is None)")

        # leg 3: the composed adversarial plan — every applicable
        # knob at its most aggressive candidate at once
        ctx = space.context(cfg0, n_shards=tuner.ctx["n_shards"])
        ctx["policy"] = "tpu"
        adversarial, adv_defaults = {}, {}
        for knob in space.applicable(cfg0, ctx):
            cur = space.current(cfg0, [knob])[knob.name]
            cands = [c for c in knob.candidates(cfg0, ctx)
                     if c != cur]
            if cands:
                adversarial[knob.name] = cands[-1]
                # the tuned-from baseline: without it, adoption's
                # hand-set check compares cadence knobs against the
                # SCHEMA default (0/None) and would spuriously skip
                # them on any config that enables supervision or
                # heartbeats
                adv_defaults[knob.name] = cur
        adv_file = os.path.join(tmp, "PLAN_adversarial.json")
        planmod.save_plan({
            "format": planmod.FORMAT,
            "workload": {**planmod.workload_stamp(twin, n_hosts),
                         "stop_time": int(stop),
                         "seed": int(cfg0.general.seed)},
            "default": adv_defaults,
            "knobs": adversarial,
            "source": "determinism_gate --tuned (composed)",
        }, adv_file)
        sig_adv, stats_adv = once("adversarial", adv_file)
        if sig_adv != sig_def:
            rc = 1
            print("DETERMINISM FAILURE: the composed adversarial "
                  f"plan {adversarial} diverges from the "
                  "default-knob run — a strategy-knob composition "
                  "changes the simulation")
            for a, b in zip(sig_def, sig_adv):
                if a != b:
                    print(f"  {a[0]}: default {a[1:]} != composed "
                          f"{b[1:]}")
        applied = (stats_adv.strategy_plan or {}).get("knobs", {})
        missing = sorted(set(adversarial) - set(applied))
        if missing:
            rc = 1
            print(f"FAIL: composed plan knobs {missing} were not "
                  f"applied (provenance: {stats_adv.strategy_plan})")
        if rc == 0:
            print(f"tuned-plan OK: {config} (mini-tune "
                  f"{rec['score']['trials']} trial(s) -> "
                  f"{rec['knobs']}; adopted run and composed "
                  f"adversarial plan {adversarial} both bit-match "
                  "the default-knob run)")
        return rc


def run_chaos_gate(config: str, ensemble_config: str) -> int:
    """Elastic mesh-shrink failover gate (device/chaos.py +
    failover: shrink): device loss must cost throughput, never the
    run — or the trace. Driven end to end by the deterministic chaos
    injector on a forced >= 4-device CPU mesh. Legs:

    1. oracle + uninterrupted M-shard: the serial oracle, then the
       tpu policy pinned to 3 shards (experimental.mesh_shards) —
       bit-identical, the baseline pair every shrink compares to;
    2. scripted device loss: a 4-shard run whose mesh device 1 dies
       at the 2nd dispatch issue (chaos device_loss), retries
       exhaust, the mesh shrinks 4 -> 3 and continues on-device
       under the state-audit word — the final signature must
       bit-match BOTH the serial oracle and the uninterrupted
       3-shard run, with >= 1 reshard reported and the engine left
       on 3 shards;
    3. post-shrink checkpoint resume: the shrink run writes rotating
       checkpoints; the newest entry must stamp the SHRUNKEN
       geometry (meta["geometry"].n_shards == 3), and resuming it on
       the full device pool must auto-adopt that geometry and
       bit-match the oracle;
    4. corrupted-rotation chaos: a supervised run whose LAST rotation
       entry is corrupted on disk by the schedule
       (chaos checkpoint_corrupt) — resolve_checkpoint must skip the
       decoy (newest-READABLE fallback) and the resume must
       bit-match;
    5. ensemble campaign shrink: the same 4 -> 3 device loss against
       `ensemble_config`'s campaign — every replica's counters and
       checksums must bit-match the uninterrupted 3-shard campaign
       (shrink keeps the vmapped replica axis intact; it is the one
       failover campaigns have).
    """
    import numpy as np

    from shadow_tpu._jax import jax
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device import checkpoint, supervise
    from shadow_tpu.device.chaos import ChaosEvent

    ndev = len(jax.devices())
    if ndev < 4:
        print(f"FAIL: --chaos needs >= 4 devices for the 4 -> 3 "
              f"shrink (run under XLA_FLAGS=--xla_force_host_"
              f"platform_device_count=4); found {ndev}")
        return 1
    cfg0 = load_config(config)
    stop = cfg0.general.stop_time
    seg_ns = max(1, stop // 8)

    def run_tpu(tag: str, tmp: str, shards: int, mutate=None,
                ensemble: bool = False, want_ok: bool = True):
        cfg = load_config(ensemble_config if ensemble else config)
        cfg.experimental.scheduler_policy = "tpu"
        cfg.experimental.mesh_shards = shards
        cfg.experimental.state_audit = True
        cfg.experimental.dispatch_segment = seg_ns
        cfg.general.data_directory = os.path.join(
            tmp, tag, "shadow.data")
        if ensemble:
            cfg.ensemble.record_path = os.path.join(
                tmp, tag, "ENSEMBLE.json")
            # the campaign config's own stop drives its segments
            cfg.experimental.dispatch_segment = max(
                1, cfg.general.stop_time // 8)
        if mutate:
            mutate(cfg)
        c = Controller(cfg)
        stats = c.run()
        if want_ok and not stats.ok:
            print(f"FAIL: {tag} run reported not-ok")
            sys.exit(1)
        if ensemble:
            f = c.runner.final_state
            sig = {k: np.asarray(f[k])
                   for k in ("chk", "n_exec", "n_sent", "n_drop",
                             "n_deliv")}
        else:
            sig = [(h.name, h.trace_checksum, h.events_executed,
                    h.packets_sent, h.packets_dropped,
                    h.packets_delivered) for h in c.sim.hosts]
        return sig, stats, c

    def loss_schedule(cfg):
        cfg.experimental.failover = "shrink"
        cfg.experimental.dispatch_retries = 1
        cfg.experimental.dispatch_retry_backoff = 0.0
        cfg.experimental.chaos = [
            ChaosEvent(kind="device_loss", segment=1, shard=1)]

    with tempfile.TemporaryDirectory() as tmp:
        os.environ.setdefault("SHADOW_TPU_OCC_DIR",
                              os.path.join(tmp, "occ"))
        rc = 0
        # leg 1: the baseline pair
        sig_oracle, stats_oracle = run_once(
            config, "serial", os.path.join(tmp, "oracle",
                                           "shadow.data"))
        sig_m, _, _ = run_tpu("alone3", tmp, shards=3)
        if sig_m != sig_oracle:
            print("DETERMINISM FAILURE: the uninterrupted 3-shard "
                  "run diverges from the serial oracle")
            return 1

        # leg 2 + 3: scripted device loss with rotating checkpoints
        base = os.path.join(tmp, "ck.npz")

        def shrink_mutate(cfg):
            loss_schedule(cfg)
            cfg.experimental.checkpoint_save = base
            cfg.experimental.checkpoint_every = seg_ns
            cfg.experimental.checkpoint_keep = 8

        sig_s, stats_s, c_s = run_tpu("shrink", tmp, shards=4,
                                      mutate=shrink_mutate)
        if sig_s != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the 4 -> 3 shrunk run "
                  "diverges from the serial oracle")
            for a, b in zip(sig_oracle, sig_s):
                if a != b:
                    print(f"  {a[0]}: oracle {a[1:]} != shrunk "
                          f"{b[1:]}")
        if stats_s.reshards < 1:
            rc = 1
            print(f"FAIL: the shrink run reported "
                  f"{stats_s.reshards} reshards — the scripted "
                  "device loss did not trigger a mesh shrink")
        if c_s.runner.engine.n_shards != 3:
            rc = 1
            print(f"FAIL: the shrink run finished on "
                  f"{c_s.runner.engine.n_shards} shard(s), "
                  "expected 3")

        entries = supervise.rotation_entries(base)
        post = [(t, p) for t, p in entries if t < stop]
        if not post:
            print("FAIL: the shrink run left no rotation entry "
                  "before stop — nothing to resume")
            return 1
        last_t, last_p = post[-1]
        geom = checkpoint.peek_geometry(checkpoint.peek_meta(last_p))
        if geom.get("n_shards") != 3:
            rc = 1
            print(f"FAIL: the post-shrink rotation entry {last_p} "
                  f"stamps geometry {geom}, expected n_shards=3")

        def resume_mutate(cfg):
            cfg.experimental.checkpoint_load = last_p

        # shards=0: the full pool — the runner must ADOPT the saved
        # shrunken geometry from the stamp
        sig_r, _, c_r = run_tpu("resume", tmp, shards=0,
                                mutate=resume_mutate)
        if sig_r != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the post-shrink checkpoint "
                  "resumed on the full pool diverges from the "
                  "oracle")
        if c_r.runner.engine.n_shards != 3:
            rc = 1
            print(f"FAIL: the resume rebuilt "
                  f"{c_r.runner.engine.n_shards} shard(s) — the "
                  "saved shrunken geometry was not adopted")

        # leg 4: corrupted-rotation chaos -> newest-readable fallback
        base2 = os.path.join(tmp, "ck2.npz")
        n_saves = (stop - 1) // seg_ns     # rotation saves at t<stop

        def corrupt_mutate(cfg):
            cfg.experimental.checkpoint_save = base2
            cfg.experimental.checkpoint_every = seg_ns
            cfg.experimental.checkpoint_keep = 8
            cfg.experimental.chaos = [
                ChaosEvent(kind="checkpoint_corrupt",
                           entry=n_saves - 1)]

        run_tpu("corrupt", tmp, shards=4, mutate=corrupt_mutate)
        # drop the end-of-run base save (simulating the crash the
        # rotation exists for) so resolution exercises the rotation
        os.unlink(base2)
        newest = supervise.rotation_entries(base2)[-1][1]
        resolved = supervise.resolve_checkpoint(base2)
        if resolved == newest:
            rc = 1
            print(f"FAIL: resolve_checkpoint returned the corrupted "
                  f"newest entry {newest} — the newest-readable "
                  "fallback did not engage")

        def resume2_mutate(cfg):
            cfg.experimental.checkpoint_load = base2

        sig_r2, _, _ = run_tpu("resume2", tmp, shards=4,
                               mutate=resume2_mutate)
        if sig_r2 != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the resume past the "
                  "corrupted rotation entry diverges from the "
                  "oracle")

        # leg 5: the ensemble campaign survives the same device loss
        ens_ref, _, _ = run_tpu("ens3", tmp, shards=3, ensemble=True)
        ens_s, ens_stats, ens_c = run_tpu(
            "ens_shrink", tmp, shards=4, mutate=loss_schedule,
            ensemble=True)
        bad = [k for k in ens_ref
               if not np.array_equal(ens_ref[k], ens_s[k])]
        if bad:
            rc = 1
            print(f"DETERMINISM FAILURE: the shrunk campaign's {bad} "
                  "diverge from the uninterrupted 3-shard campaign")
        if ens_stats.reshards < 1 or \
                ens_c.runner.engine.n_shards != 3:
            rc = 1
            print(f"FAIL: campaign shrink reported "
                  f"{ens_stats.reshards} reshards on "
                  f"{ens_c.runner.engine.n_shards} final shard(s) — "
                  "expected >= 1 on 3")

        if rc == 0:
            print(f"chaos OK: {config} (scripted 4 -> 3 device loss "
                  f"bit-matches the serial oracle "
                  f"[{stats_oracle.events_executed} events] and the "
                  "uninterrupted 3-shard run, standalone AND "
                  f"ensemble [{ensemble_config}]; post-shrink "
                  "checkpoint stamps n_shards=3 and resumes "
                  "bit-identically on the full pool; the corrupted "
                  "rotation entry fell back to the newest readable "
                  "one; audit word clean throughout)")
        return rc


def run_degrade_gate(config: str, ensemble_config: str) -> int:
    """Preflight-admission + degradation-ladder gate
    (device/capacity.py admission + device/supervise.py recover_oom):
    a run must never OOM blind — over-budget estimates are refused or
    degraded BEFORE any compile, and real allocator failures walk a
    bit-identical degradation ladder instead of burning the retry
    budget. Driven end to end by the deterministic chaos injector's
    oom seam on a forced >= 4-device CPU mesh. Legs:

    1. oracle: the serial run every degraded run compares to;
    2. strict refusal: ``admission: strict`` under a deliberately
       tiny ``device_memory_budget`` must raise the readable
       "needs X, budget Y on N devices" diagnostic before ANY
       compile — the leg's private cold AOT cache directory must
       stay empty;
    3. compile-seam oom: a scripted RESOURCE_EXHAUSTED at the 0th
       program compile (chaos oom against a COLD cache, so the
       compile actually runs — a warm hit compiles nothing and the
       seam never fires) repeats until the ladder engages a rung;
       the finished run must bit-match the oracle with degrade >= 1
       and the retry budget unexhausted;
    4. dispatch-seam oom: the same scripted oom at the 2nd dispatch
       issue of a depth-4 pipelined run — the FIRST failure charges
       one normal retry, the second consecutive identical one routes
       to the ladder (deterministic OOMs must never exhaust
       dispatch_retries), and the run bit-matches the oracle;
    5. replica batches: `ensemble_config`'s campaign run with
       ``ensemble.replica_batch: 2`` (sequential halves of the
       replica axis, each its own engine) must bit-match the
       full-vmap campaign over every replica's counters and
       checksums, stamp the admission verdict + batch split, and
       replica 0 must still bit-match a standalone serial run with
       its parameters (the batch never weakens the replica-i ==
       standalone-i contract).
    """
    import numpy as np

    from shadow_tpu._jax import jax
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.chaos import OOM_ERROR, ChaosEvent

    ndev = len(jax.devices())
    if ndev < 4:
        print(f"FAIL: --degrade needs >= 4 devices for the forced "
              f"CPU mesh (run under XLA_FLAGS=--xla_force_host_"
              f"platform_device_count=4); found {ndev}")
        return 1
    cfg0 = load_config(config)
    stop = cfg0.general.stop_time
    seg_ns = max(1, stop // 8)

    def run_tpu(tag: str, tmp: str, mutate=None):
        cfg = load_config(config)
        cfg.experimental.scheduler_policy = "tpu"
        cfg.experimental.state_audit = True
        cfg.experimental.dispatch_segment = seg_ns
        cfg.experimental.compile_cache = os.path.join(tmp, "aot")
        cfg.general.data_directory = os.path.join(
            tmp, tag, "shadow.data")
        if mutate:
            mutate(cfg)
        c = Controller(cfg)
        stats = c.run()
        if not stats.ok:
            print(f"FAIL: {tag} run reported not-ok")
            sys.exit(1)
        sig = [(h.name, h.trace_checksum, h.events_executed,
                h.packets_sent, h.packets_dropped,
                h.packets_delivered) for h in c.sim.hosts]
        return sig, stats

    with tempfile.TemporaryDirectory() as tmp:
        os.environ.setdefault("SHADOW_TPU_OCC_DIR",
                              os.path.join(tmp, "occ"))
        rc = 0
        # leg 1: the serial oracle
        sig_oracle, stats_oracle = run_once(
            config, "serial", os.path.join(tmp, "oracle",
                                           "shadow.data"))

        # leg 2: strict refusal, before any compile
        strict_aot = os.path.join(tmp, "aot_strict")
        cfg = load_config(config)
        cfg.experimental.scheduler_policy = "tpu"
        cfg.experimental.admission = "strict"
        cfg.experimental.device_memory_budget = 4096   # 4 KiB: absurd
        cfg.experimental.compile_cache = strict_aot
        cfg.general.data_directory = os.path.join(
            tmp, "strict", "shadow.data")
        try:
            Controller(cfg).run()
        except ValueError as e:
            msg = str(e)
            for frag in ("admission", "needs", "budget", "device"):
                if frag not in msg:
                    rc = 1
                    print(f"FAIL: strict refusal diagnostic lacks "
                          f"{frag!r}: {msg}")
        else:
            rc = 1
            print("FAIL: admission: strict ADMITTED a run whose "
                  "footprint dwarfs a 4 KiB device budget")
        if os.path.isdir(strict_aot) and os.listdir(strict_aot):
            rc = 1
            print("FAIL: the strict refusal leg left entries in its "
                  "cold AOT cache — something compiled BEFORE the "
                  "admission decision")

        # leg 3: scripted oom at the 0th program compile (cold cache)
        def oom_compile(cfg):
            cfg.experimental.pipeline_depth = 2
            cfg.experimental.dispatch_retries = 3
            cfg.experimental.dispatch_retry_backoff = 0.0
            cfg.experimental.compile_cache = os.path.join(
                tmp, "aot_cold")
            cfg.experimental.chaos = [
                ChaosEvent(kind="oom", compile=0, error=OOM_ERROR)]

        sig_c, stats_c = run_tpu("oom_compile", tmp,
                                 mutate=oom_compile)
        if sig_c != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the compile-seam oom run "
                  "diverges from the serial oracle")
        if stats_c.degrades < 1:
            rc = 1
            print(f"FAIL: the scripted compile oom reported "
                  f"{stats_c.degrades} degrades — the ladder never "
                  "engaged")
        if stats_c.retries >= 3:
            rc = 1
            print(f"FAIL: the compile-seam oom burned "
                  f"{stats_c.retries} retries — the ladder must "
                  "engage before the budget of 3 exhausts")

        # leg 4: scripted oom at the 2nd dispatch issue, depth 4
        def oom_dispatch(cfg):
            cfg.experimental.pipeline_depth = 4
            cfg.experimental.dispatch_retries = 3
            cfg.experimental.dispatch_retry_backoff = 0.0
            cfg.experimental.chaos = [
                ChaosEvent(kind="oom", segment=2, error=OOM_ERROR)]

        sig_d, stats_d = run_tpu("oom_dispatch", tmp,
                                 mutate=oom_dispatch)
        if sig_d != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the dispatch-seam oom run "
                  "diverges from the serial oracle")
            for a, b in zip(sig_oracle, sig_d):
                if a != b:
                    print(f"  {a[0]}: oracle {a[1:]} != degraded "
                          f"{b[1:]}")
        if stats_d.degrades < 1:
            rc = 1
            print(f"FAIL: the scripted dispatch oom reported "
                  f"{stats_d.degrades} degrades — the ladder never "
                  "engaged")
        if stats_d.retries > 1:
            rc = 1
            print(f"FAIL: the deterministic dispatch oom charged "
                  f"{stats_d.retries} retries — the second "
                  "consecutive identical failure must route to the "
                  "ladder after ONE charged retry, not drain "
                  "dispatch_retries")

        # leg 5: replica batches bit-match the full-vmap campaign
        def run_campaign(tag: str, batch: int = 0):
            cfg = load_config(ensemble_config)
            cfg.experimental.scheduler_policy = "tpu"
            cfg.experimental.state_audit = True
            cfg.experimental.dispatch_segment = max(
                1, cfg.general.stop_time // 8)
            cfg.experimental.compile_cache = os.path.join(
                tmp, "aot_ens")
            cfg.general.data_directory = os.path.join(
                tmp, tag, "shadow.data")
            cfg.ensemble.record_path = os.path.join(
                tmp, tag, "ENSEMBLE.json")
            if batch:
                cfg.ensemble.replica_batch = batch
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: {tag} campaign reported not-ok")
                sys.exit(1)
            f = c.runner.final_state
            sig = {k: np.asarray(f[k])
                   for k in ("chk", "n_exec", "n_sent", "n_drop",
                             "n_deliv")}
            return sig, stats, c

        ens_full, _, _ = run_campaign("ens_full")
        ens_b, stats_b, c_b = run_campaign("ens_batch", batch=2)
        bad = [k for k in ens_full
               if not np.array_equal(ens_full[k], ens_b[k])]
        if bad:
            rc = 1
            print(f"DETERMINISM FAILURE: the replica-batched "
                  f"campaign's {bad} diverge from the full-vmap "
                  "campaign")
        pipe = stats_b.pipeline or {}
        if pipe.get("replica_batches") != 2 or \
                pipe.get("replica_batch") != 2:
            rc = 1
            print(f"FAIL: the batched campaign stamped pipeline "
                  f"{pipe} — expected replica_batch=2 over "
                  "replica_batches=2")
        adm = stats_b.admission
        if not isinstance(adm, dict) or \
                adm.get("replica_batch") != 2:
            rc = 1
            print(f"FAIL: the batched campaign's admission verdict "
                  f"{adm} does not stamp replica_batch=2")

        # ... and replica 0 still bit-matches a standalone serial run
        desc = c_b.runner.worlds.descriptors[0]
        names = [h.name for h in c_b.sim.hosts]
        sig_e = [(names[i], int(ens_b["chk"][0, i]),
                  int(ens_b["n_exec"][0, i]),
                  int(ens_b["n_sent"][0, i]),
                  int(ens_b["n_drop"][0, i]),
                  int(ens_b["n_deliv"][0, i]))
                 for i in range(len(names))]
        cfg = load_config(ensemble_config)
        cfg.ensemble = None
        cfg.experimental.scheduler_policy = "serial"
        cfg.experimental.runahead = c_b.runner.lookahead
        cfg.general.seed = desc["seed"]
        cfg.general.data_directory = os.path.join(
            tmp, "alone", "shadow.data")
        c_a = Controller(cfg)
        stats_a = c_a.run()
        if not stats_a.ok:
            print("FAIL: standalone replica-0 run reported not-ok")
            return 1
        sig_a = [(h.name, h.trace_checksum, h.events_executed,
                  h.packets_sent, h.packets_dropped,
                  h.packets_delivered) for h in c_a.sim.hosts]
        if sig_a != sig_e:
            rc = 1
            print(f"DETERMINISM FAILURE: replica 0 of the batched "
                  f"campaign diverges from the standalone serial "
                  f"run with its parameters ({desc})")

        if rc == 0:
            print(f"degrade OK: {config} (strict admission refused "
                  f"a 4 KiB budget before any compile; scripted "
                  f"RESOURCE_EXHAUSTED at compile 0 and dispatch 2 "
                  f"walked the ladder bit-identical to the serial "
                  f"oracle [{stats_oracle.events_executed} events, "
                  f"{stats_c.degrades}+{stats_d.degrades} degrades, "
                  f"retry budget intact]; {ensemble_config} in "
                  "replica batches of 2 bit-matches the full-vmap "
                  "campaign and standalone replica 0)")
        return rc


def run_pipelined_gate(config: str) -> int:
    """Pipelined-dispatch gate (device/supervise.py segment
    pipeline): overlap must never change the simulation. Three legs
    against one config:

    1. depth sweep: the tpu policy at pipeline_depth {1, 2, 4} —
       dispatch-segmented so real windows are in flight, with the
       state-audit word compiled in — must be bit-identical to the
       SERIAL ORACLE (not merely to each other);
    2. recovery composition: a supervised child running with a
       depth-4 window in flight is SIGTERM'd mid-run — the
       preemption drain must complete the window, land a resume
       checkpoint, and exit with the distinct preemption rc;
    3. cross-depth resume: the checkpoint saved under depth 4 is
       resumed at depth 1 (depth is host-side orchestration, never
       part of the checkpoint contract) and the resumed run must
       bit-match the uninterrupted oracle.
    """
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.supervise import EXIT_PREEMPTED

    cfg0 = load_config(config)
    stop = cfg0.general.stop_time
    seg_ns = max(1, stop // 8)

    with tempfile.TemporaryDirectory() as tmp:
        sig_oracle, stats_oracle = run_once(
            config, "serial", os.path.join(tmp, "oracle",
                                           "shadow.data"))

        def run_depth(depth: int, tag: str, load: str = ""):
            cfg = load_config(config)
            cfg.experimental.scheduler_policy = "tpu"
            cfg.experimental.pipeline_depth = depth
            cfg.experimental.dispatch_segment = seg_ns
            cfg.experimental.state_audit = True
            if load:
                cfg.experimental.checkpoint_load = load
            cfg.general.data_directory = os.path.join(
                tmp, tag, "shadow.data")
            c = Controller(cfg)
            stats = c.run()
            if not stats.ok:
                print(f"FAIL: {tag} run reported not-ok")
                sys.exit(1)
            sig = [(h.name, h.trace_checksum, h.events_executed,
                    h.packets_sent, h.packets_dropped,
                    h.packets_delivered) for h in c.sim.hosts]
            return sig, stats

        rc = 0
        pipe_stats = {}
        for depth in (1, 2, 4):
            sig_d, stats_d = run_depth(depth, f"depth{depth}")
            pipe_stats[depth] = stats_d.pipeline or {}
            if sig_d != sig_oracle:
                rc = 1
                print(f"DETERMINISM FAILURE: pipeline_depth={depth} "
                      "diverges from the serial oracle")
                for a, b in zip(sig_oracle, sig_d):
                    if a != b:
                        print(f"  {a[0]}: oracle {a[1:]} != depth"
                              f"{depth} {b[1:]}")
            want_flight = min(depth, max(1, stop // seg_ns))
            got_flight = pipe_stats[depth].get("max_in_flight", 0)
            if got_flight < min(2, want_flight):
                rc = 1
                print(f"FAIL: pipeline_depth={depth} never held "
                      f"{min(2, want_flight)} segments in flight "
                      f"(max_in_flight={got_flight}) — the window "
                      "is not actually pipelining")

        # leg 2: SIGTERM with a depth-4 window in flight. The child
        # gets a much finer boundary cadence (stop//64, vs the depth
        # sweep's stop//8): at depth 4 the first rotation entry — the
        # parent's SIGTERM trigger — lags issue progress by a full
        # window, so with 8 coarse segments the signal would race the
        # run's tail; 64 boundaries leave ~90% of the run as runway.
        base = os.path.join(tmp, "ck.npz")
        pre_ns = max(1, stop // 64)
        child_rc = _preempt_child(
            config, base, pre_ns,
            os.path.join(tmp, "pre", "shadow.data"), False,
            extra=["experimental.pipeline_depth=4",
                   f"experimental.dispatch_segment={pre_ns}ns"])
        if child_rc != EXIT_PREEMPTED:
            print(f"FAIL: preempted depth-4 run exited rc {child_rc}"
                  f", expected the preemption rc {EXIT_PREEMPTED}")
            return 1

        # leg 3: resume the depth-4 checkpoint at depth 1
        sig_res, _ = run_depth(1, "resume", load=base)
        if sig_res != sig_oracle:
            rc = 1
            print("DETERMINISM FAILURE: the depth-4 checkpoint "
                  "resumed at depth 1 diverges from the "
                  "uninterrupted oracle")
            for a, b in zip(sig_oracle, sig_res):
                if a != b:
                    print(f"  {a[0]}: oracle {a[1:]} != resumed "
                          f"{b[1:]}")

        if rc == 0:
            flights = {d: p.get("max_in_flight")
                       for d, p in pipe_stats.items()}
            print(f"pipelined OK: {config} (depths 1/2/4 "
                  f"bit-identical to the serial oracle "
                  f"[{stats_oracle.events_executed} events], "
                  f"max_in_flight {flights}; SIGTERM with a depth-4 "
                  f"window drained to rc {EXIT_PREEMPTED} and the "
                  "checkpoint resumed at depth 1 bit-matches)")
        return rc


def run_host_plane_gate(config: str) -> int:
    """Columnar host plane gate (host/plane.py, docs/host_plane.md):
    on the forced multi-device mesh, the columnar build, the object-
    path build (SHADOW_TPU_HOST_PLANE=0), and the serial CPU oracle
    must produce bit-identical per-host signatures, and the two tpu
    legs' engines must carry identical checkpoint fingerprints.
    Vacuity-guarded: the columnar leg must actually have used the
    plane, and the object leg must not have."""
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device import checkpoint

    def leg(policy: str, data_dir: str, columnar: bool):
        old = os.environ.pop("SHADOW_TPU_HOST_PLANE", None)
        try:
            if not columnar:
                os.environ["SHADOW_TPU_HOST_PLANE"] = "0"
            cfg = load_config(config)
            cfg.experimental.scheduler_policy = policy
            cfg.general.data_directory = data_dir
            c = Controller(cfg)
            stats = c.run()
        finally:
            os.environ.pop("SHADOW_TPU_HOST_PLANE", None)
            if old is not None:
                os.environ["SHADOW_TPU_HOST_PLANE"] = old
        if not stats.ok:
            print(f"FAIL: {policy} leg reported not-ok")
            sys.exit(1)
        sig = [(h.name, h.trace_checksum, h.events_executed,
                h.packets_sent, h.packets_dropped,
                h.packets_delivered) for h in c.sim.hosts]
        return c, sig

    def diff(tag: str, a, b) -> None:
        print(f"HOST-PLANE FAILURE: {tag} signatures diverge")
        for x, y in zip(a, b):
            if x != y:
                print(f"  {x[0]}: {x[1:]} != {y[1:]}")

    with tempfile.TemporaryDirectory() as tmp:
        col, sig_col = leg("tpu", os.path.join(tmp, "columnar"), True)
        if col.sim.plane is None:
            print("FAIL: the columnar leg did not use the host plane "
                  "(eligibility refused this config, so the gate "
                  "would compare object vs object — fix the config "
                  "or the eligibility rule)")
            return 1
        obj, sig_obj = leg("tpu", os.path.join(tmp, "object"), False)
        if obj.sim.plane is not None:
            print("FAIL: SHADOW_TPU_HOST_PLANE=0 did not force the "
                  "object build")
            return 1
        _, sig_ser = leg("serial", os.path.join(tmp, "serial"), True)

        rc = 0
        if sig_col != sig_obj:
            rc = 1
            diff("columnar vs object", sig_col, sig_obj)
        if sig_col != sig_ser:
            rc = 1
            diff("columnar vs serial oracle", sig_col, sig_ser)
        fp_col = checkpoint._fingerprint(col.runner.engine)
        fp_obj = checkpoint._fingerprint(obj.runner.engine)
        if fp_col != fp_obj:
            rc = 1
            print("HOST-PLANE FAILURE: checkpoint fingerprints "
                  "diverge between the columnar and object engines")
            for k in fp_col:
                if fp_col.get(k) != fp_obj.get(k):
                    print(f"  {k}: {fp_col.get(k)} != {fp_obj.get(k)}")
        if rc == 0:
            import jax
            print(f"host-plane OK: {config} ({len(sig_col)} hosts, "
                  f"{len(jax.devices())} devices) — columnar, "
                  "object, and serial legs bit-identical; "
                  "checkpoint fingerprints match")
        return rc


def run_server_gate(config: str) -> int:
    """Campaign-server robustness gate (shadow_tpu/serve/), two legs
    on the forced multi-device mesh:

    1. kill -9 drill: submit two campaigns, run the daemon as a real
       child process, SIGKILL it once the first rotation checkpoint
       lands, restart with --idle-exit — journal replay must requeue
       the mid-flight campaign, BOTH must reach DONE, and every
       RESULT.json signature must bit-match an uninterrupted
       standalone run of the same config.
    2. priority drill: a higher-priority arrival preempts the running
       campaign through the rc-75 drain; the preempted campaign
       resumes after it and still bit-matches standalone.
    """
    import json as _json
    import signal as _signal
    import subprocess
    import time as _time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def daemon(spool, *extra):
        return subprocess.Popen(
            [sys.executable, "-m", "shadow_tpu.serve", "start", spool,
             "--poll", "0.05", "--log-level", "warning"] + list(extra),
            env=env, cwd=repo)

    def submit(spool, priority=0):
        rc = subprocess.run(
            [sys.executable, "-m", "shadow_tpu.serve", "submit",
             spool, config, "--priority", str(priority)],
            env=env, cwd=repo).returncode
        if rc != 0:
            raise RuntimeError(f"submit failed (rc {rc})")

    def journal_rows(spool):
        path = os.path.join(spool, "journal.jsonl")
        rows = []
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    try:
                        rows.append(_json.loads(line))
                    except ValueError:
                        pass
        return rows

    def wait_for(pred, what, timeout_s=900):
        deadline = _time.monotonic() + timeout_s
        while _time.monotonic() < deadline:
            if pred():
                return True
            _time.sleep(0.05)
        print(f"FAIL: timed out waiting for {what}")
        return False

    def results(spool, n):
        out = {}
        for i in range(n):
            cid = f"c{i:04d}"
            path = os.path.join(spool, "campaigns", cid,
                                "RESULT.json")
            if not os.path.exists(path):
                print(f"FAIL: {path} missing")
                return None
            with open(path, "r", encoding="utf-8") as f:
                out[cid] = _json.load(f)
        return out

    with tempfile.TemporaryDirectory() as tmp:
        ref_sig, _ = run_once(config, "tpu",
                              os.path.join(tmp, "ref.data"))
        ref = [list(s) for s in ref_sig]

        # -- leg 1: SIGKILL mid-campaign, restart, both complete ----
        spool = os.path.join(tmp, "spool_kill")
        submit(spool)
        submit(spool)
        proc = daemon(spool)
        ck_glob = os.path.join(spool, "campaigns", "*", "ck.npz.t*")
        if not wait_for(lambda: glob.glob(ck_glob),
                        "the first rotation checkpoint"):
            proc.kill()
            return 1
        proc.send_signal(_signal.SIGKILL)   # the crash drill IS kill -9
        proc.wait()
        proc = daemon(spool, "--idle-exit")
        rc = proc.wait(timeout=900)
        if rc != 0:
            print(f"FAIL: restarted server exited rc {rc}")
            return 1
        res = results(spool, 2)
        if res is None:
            return 1
        starts = sum(1 for r in journal_rows(spool)
                     if r.get("event") == "server_start")
        if starts != 2:
            print(f"FAIL: journal replayed {starts} server starts, "
                  "want 2 (one per daemon leg)")
            return 1
        for cid, r in res.items():
            if r.get("state") != "DONE":
                print(f"FAIL: {cid} ended {r.get('state')} "
                      f"({r.get('diagnostic', '')})")
                return 1
            if r.get("signature") != ref:
                print(f"FAIL: {cid} signature diverges from the "
                      "standalone run after the kill -9 restart")
                return 1
        requeued = any(r.get("state") == "PREEMPTED" and "restart"
                       in r.get("diagnostic", "")
                       for r in journal_rows(spool))
        if not requeued:
            print("FAIL: journal replay never requeued the "
                  "mid-flight campaign (the kill missed the RUNNING "
                  "window — shrink checkpoint cadence)")
            return 1
        print(f"server kill -9 drill OK: {config} — 2 campaigns "
              "DONE across a restart, signatures bit-match "
              "standalone")

        # -- leg 2: higher priority preempts via the rc-75 drain ----
        spool = os.path.join(tmp, "spool_prio")
        submit(spool, priority=0)       # before the daemon, so
        proc = daemon(spool, "--idle-exit")   # idle-exit cannot race
        try:
            if not wait_for(
                    lambda: any(r.get("cid") == "c0000"
                                and r.get("state") == "RUNNING"
                                for r in journal_rows(spool)),
                    "c0000 to start running"):
                return 1
            submit(spool, priority=5)
            rc = proc.wait(timeout=900)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
        if rc != 0:
            print(f"FAIL: priority-leg server exited rc {rc}")
            return 1
        res = results(spool, 2)
        if res is None:
            return 1
        rows = journal_rows(spool)
        states = [(r.get("cid"), r.get("state"))
                  for r in rows if r.get("state")]
        if ("c0000", "PREEMPTED") not in states:
            print("FAIL: the low-priority campaign was never "
                  "preempted (the high-priority submission lost the "
                  "race — grow stop_time)")
            return 1
        dones = [cid for cid, s in states if s == "DONE"]
        if dones and dones[0] != "c0001":
            print(f"FAIL: completion order {dones} — the "
                  "high-priority campaign must finish first")
            return 1
        for cid, r in res.items():
            if r.get("state") != "DONE" or r.get("signature") != ref:
                print(f"FAIL: {cid} ended {r.get('state')} or "
                      "diverged from standalone after the "
                      "preempt/resume cycle")
                return 1
        print(f"server priority drill OK: {config} — preempted "
              "campaign resumed bit-identical behind the "
              "high-priority one")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default="examples/minimal.yaml")
    ap.add_argument("--policy", default=None)
    ap.add_argument("--ensemble", action="store_true",
                    help="campaign gate: replica bit-identity vs "
                         "standalone runs (config needs ensemble:)")
    ap.add_argument("--replica", type=int, default=0,
                    help="which replica to compare standalone "
                         "(--ensemble only; default 0)")
    ap.add_argument("--preempt", action="store_true",
                    help="preemption gate: SIGTERM a supervised run "
                         "mid-flight, resume, require bit-identity "
                         "with the uninterrupted run")
    ap.add_argument("--compile-cache", action="store_true",
                    help="warm-start gate: cold/warm/corrupted runs "
                         "against one shared AOT compile cache must "
                         "be bit-identical, with the warm run a "
                         "cache hit and the corrupted run a loud "
                         "recompile")
    ap.add_argument("--telemetry", action="store_true",
                    help="flight-recorder gate: telemetry off vs "
                         "summary vs trace must be bit-identical, "
                         "and the trace run must leave a Perfetto-"
                         "loadable trace + a METRICS record whose "
                         "phase walls sum to the total")
    ap.add_argument("--tuned", action="store_true",
                    help="strategy-autotuner gate: a mini-tuned PLAN "
                         "record and a composed adversarial plan "
                         "must both bit-match the default-knob run "
                         "(a tuned plan changes wall time only)")
    ap.add_argument("--pipelined", action="store_true",
                    help="pipelined-dispatch gate: pipeline_depth "
                         "1/2/4 (segmented, state-audited) must be "
                         "bit-identical to the serial oracle; a "
                         "SIGTERM with a depth-4 window in flight "
                         "must drain to a resume checkpoint that a "
                         "depth-1 run resumes bit-identically")
    ap.add_argument("--chaos", action="store_true",
                    help="elastic mesh-shrink gate: scripted 4 -> 3 "
                         "device loss (deterministic chaos injector) "
                         "must bit-match the serial oracle and the "
                         "uninterrupted 3-shard run, standalone and "
                         "ensemble; post-shrink checkpoints stamp "
                         "the shrunken geometry and resume; a "
                         "corrupted rotation entry falls back to "
                         "the newest readable one (needs >= 4 "
                         "devices)")
    ap.add_argument("--chaos-ensemble",
                    default="examples/ensemble_seed_sweep.yaml",
                    help="campaign config for the --chaos / "
                         "--degrade ensemble legs (default "
                         "examples/ensemble_seed_sweep.yaml)")
    ap.add_argument("--degrade", action="store_true",
                    help="admission + degradation-ladder gate: "
                         "admission: strict must refuse a tiny "
                         "device_memory_budget with a readable "
                         "diagnostic before any compile; scripted "
                         "RESOURCE_EXHAUSTED at the 0th compile and "
                         "the 2nd dispatch issue must walk the "
                         "degradation ladder bit-identical to the "
                         "serial oracle without exhausting "
                         "dispatch_retries; the --chaos-ensemble "
                         "campaign in replica batches of 2 must "
                         "bit-match the full-vmap campaign and "
                         "standalone replica 0 (needs >= 4 devices)")
    ap.add_argument("--host-plane", action="store_true",
                    help="columnar host-plane gate: the vectorized "
                         "columnar build, the object-path build "
                         "(SHADOW_TPU_HOST_PLANE=0), and the serial "
                         "CPU oracle must be bit-identical on the "
                         "forced multi-device mesh, with matching "
                         "checkpoint fingerprints between the two "
                         "tpu legs")
    ap.add_argument("--analyze-consistency", action="store_true",
                    help="static-analysis consistency gate: the "
                         "collective registry shadowlint audits "
                         "against must match engine.effective{} at "
                         "runtime, the engine's jaxpr audit must be "
                         "clean, and an in-process audit must leave "
                         "run signatures bit-identical")
    ap.add_argument("--server", action="store_true",
                    help="campaign-server gate (shadow_tpu/serve/): "
                         "kill -9 the daemon mid-campaign and "
                         "restart — journal replay must complete "
                         "both campaigns bit-identical to standalone "
                         "runs; then a priority arrival must preempt "
                         "and the drained campaign resume "
                         "bit-identical (needs >= 4 devices)")
    args = ap.parse_args()

    default_policy = "serial,tpu" if args.ensemble else "serial"
    policies = [p.strip()
                for p in (args.policy or default_policy).split(",")
                if p.strip()]

    if args.server:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned \
                or args.analyze_consistency or args.pipelined or \
                args.chaos or args.degrade:
            # the server gate drives whole daemon processes; the
            # standalone reference runs are baked into its legs
            print("FAIL: --server does not combine with other gate "
                  "flags (it runs its own standalone reference plus "
                  "the kill -9 and priority-preemption daemon legs)")
            return 1
        return run_server_gate(args.config)

    if args.degrade:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned \
                or args.analyze_consistency or args.pipelined or \
                args.chaos:
            # the degrade gate runs the serial oracle, both oom
            # seams, the strict refusal, and its own replica-batch
            # ensemble leg by construction
            print("FAIL: --degrade does not combine with other gate "
                  "flags (it runs serial + tpu oom/strict legs plus "
                  "its own replica-batch ensemble leg)")
            return 1
        return run_degrade_gate(args.config, args.chaos_ensemble)

    if args.chaos:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned \
                or args.analyze_consistency or args.pipelined:
            # the chaos gate runs the serial oracle, the M-shard
            # comparison, the shrink/resume legs, and its own
            # ensemble leg by construction
            print("FAIL: --chaos does not combine with other gate "
                  "flags (it runs serial + tpu mesh_shards 3/4 plus "
                  "its own checkpoint/ensemble legs)")
            return 1
        return run_chaos_gate(args.config, args.chaos_ensemble)

    if args.pipelined:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned \
                or args.analyze_consistency:
            # the pipelined gate composes its own preemption leg and
            # runs the serial oracle + depth sweep by construction
            print("FAIL: --pipelined does not combine with other "
                  "gate flags (it runs serial + tpu depths 1/2/4 "
                  "plus its own preemption/resume legs)")
            return 1
        return run_pipelined_gate(args.config)

    if args.host_plane:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned \
                or args.analyze_consistency:
            # the host-plane gate runs its own three legs (columnar
            # tpu, object tpu, serial oracle) by construction
            print("FAIL: --host-plane does not combine with other "
                  "gate flags (it runs columnar tpu + object tpu + "
                  "serial legs by construction)")
            return 1
        return run_host_plane_gate(args.config)

    if args.analyze_consistency:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry or args.tuned:
            # this gate runs the standalone tpu policy around an
            # in-process audit by construction
            print("FAIL: --analyze-consistency does not combine "
                  "with --ensemble/--preempt/--policy/"
                  "--compile-cache/--telemetry/--tuned")
            return 1
        return run_analyze_consistency_gate(args.config)

    if args.tuned:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache or args.telemetry:
            # the tuned gate runs the standalone tpu policy against
            # its three plan legs by construction
            print("FAIL: --tuned does not combine with --ensemble/"
                  "--preempt/--policy/--compile-cache/--telemetry "
                  "(it runs the standalone tpu policy per plan leg)")
            return 1
        return run_tuned_gate(args.config)

    if args.telemetry:
        if args.ensemble or args.preempt or args.policy or \
                args.compile_cache:
            # the telemetry gate runs the standalone tpu policy under
            # its three modes by construction — dropping another
            # gate's flag silently would test the wrong thing
            print("FAIL: --telemetry does not combine with "
                  "--ensemble/--preempt/--policy/--compile-cache "
                  "(it runs the standalone tpu policy once per "
                  "telemetry mode)")
            return 1
        return run_telemetry_gate(args.config)

    if args.compile_cache:
        if args.ensemble or args.preempt or args.policy:
            # the warm-start gate runs the standalone tpu policy by
            # construction — dropping a composability flag silently
            # would test the wrong thing
            print("FAIL: --compile-cache does not combine with "
                  "--ensemble/--preempt/--policy (it runs the "
                  "standalone tpu policy three times against one "
                  "shared cache directory)")
            return 1
        return run_compile_cache_gate(args.config)

    if args.preempt:
        return run_preempt_gate(args.config, args.ensemble)

    if args.ensemble:
        return run_ensemble_gate(args.config, policies, args.replica)

    with tempfile.TemporaryDirectory() as tmp:
        d1 = os.path.join(tmp, "run1", "shadow.data")
        d2 = os.path.join(tmp, "run2", "shadow.data")
        sig1, stats1 = run_once(args.config, policies[0], d1)
        sig2, stats2 = run_once(args.config, policies[0], d2)

        rc = 0
        if sig1 != sig2:
            rc = 1
            print("DETERMINISM FAILURE: per-host signatures differ")
            for a, b in zip(sig1, sig2):
                if a != b:
                    print(f"  {a[0]}: {a[1:]} != {b[1:]}")
        diffs = compare_trees(d1, d2)
        if diffs:
            rc = 1
            print("DETERMINISM FAILURE: host files differ")
            for d in diffs[:20]:
                print(f"  {d}")

        # cross-policy matrix: every additional policy must reproduce
        # the first policy's per-host signature bit for bit
        for policy in policies[1:]:
            dp = os.path.join(tmp, f"run_{policy}", "shadow.data")
            sigp, _ = run_once(args.config, policy, dp)
            if sigp != sig1:
                rc = 1
                print(f"DETERMINISM FAILURE: policy {policy} diverges "
                      f"from {policies[0]}")
                for a, b in zip(sig1, sigp):
                    if a != b:
                        print(f"  {a[0]}: {a[1:]} != {b[1:]}")
            diffs = compare_trees(d1, dp)
            if diffs:
                rc = 1
                print(f"DETERMINISM FAILURE: host files differ "
                      f"({policies[0]} vs {policy})")
                for d in diffs[:20]:
                    print(f"  {d}")

        if rc == 0:
            across = f"across 2 runs of {policies[0]}"
            if len(policies) > 1:
                across += f" and policies {','.join(policies[1:])}"
            print(f"determinism OK: {args.config} "
                  f"({stats1.events_executed} events, "
                  f"{stats1.packets_sent} packets, bit-identical "
                  f"signatures and host files {across})")
        return rc


if __name__ == "__main__":
    sys.exit(main())
