"""Wait for the TPU relay to recover, then run the bench + profile.

The axon relay admits one client; a wedged claim makes jax.devices()
hang for hours. This watcher probes gently on a long cycle — each
probe subprocess gets a generous natural window and a SIGTERM + grace
shutdown (never a bare SIGKILL on a possibly-mid-claim client) — and
the moment a probe sees a real accelerator it runs, in order:

  1. scripts/tune_10k.py 2.5            -> artifacts/TUNE_tpu.json
     (pop_strategy x burst_pops sweep; bench.py reads the best combo)
  2. python bench.py                    -> artifacts/BENCH_tpu.json
  3. scripts/profile_device.py 10k rung -> artifacts/PROFILE_tpu.json
  4. scripts/tor_large_run.py 12        -> artifacts/TORLARGE_tpu.json
     (the longest step: a full-state 56k-host execution; the watcher
     holds the single-client relay for its duration)

Usage: python scripts/tpu_watch.py [max_hours]
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

PROBE_WINDOW_S = 2400       # one probe may legitimately sit this long
SLEEP_BETWEEN_S = 600
ART = "artifacts"


def log(msg: str) -> None:
    print(f"[tpu_watch {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def probe_once() -> bool:
    p = subprocess.Popen(
        [sys.executable, "-c",
         "import signal, sys; "
         "signal.signal(signal.SIGTERM, lambda *a: sys.exit(3)); "
         "import jax; print(jax.devices()[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        out, _ = p.communicate(timeout=PROBE_WINDOW_S)
        ok = p.returncode == 0 and "cpu" not in (out or "")
        log(f"probe -> rc={p.returncode} out={out!r}")
        return ok
    except subprocess.TimeoutExpired:
        log(f"probe still hung after {PROBE_WINDOW_S}s; "
            "SIGTERM + grace")
        p.terminate()
        try:
            p.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
        return False


def run_and_save(cmd: list[str], out_path: str, log_path: str) -> int:
    with open(out_path, "wb") as out, open(log_path, "wb") as err:
        r = subprocess.run(cmd, stdout=out, stderr=err)
    log(f"{' '.join(cmd[:2])} -> rc={r.returncode} ({out_path})")
    return r.returncode


def main() -> int:
    max_hours = float(sys.argv[1]) if len(sys.argv) > 1 else 9.0
    os.makedirs(ART, exist_ok=True)
    deadline = time.monotonic() + max_hours * 3600
    while time.monotonic() < deadline:
        if probe_once():
            log("TPU is back — running the 10k knob sweep")
            run_and_save([sys.executable, "scripts/tune_10k.py",
                          "2.5"],
                         f"{ART}/TUNE_tpu.json",
                         f"{ART}/TUNE_tpu.log")
            log("sweep done — running bench (tuned knobs apply)")
            run_and_save([sys.executable, "bench.py"],
                         f"{ART}/BENCH_tpu.json",
                         f"{ART}/BENCH_tpu.log")
            log("bench done — running 10k profile")
            run_and_save([sys.executable, "scripts/profile_device.py",
                          "examples/tgen_10000.yaml", "2.5"],
                         f"{ART}/PROFILE_tpu.json",
                         f"{ART}/PROFILE_tpu.log")
            log("profile done — running micro4 (gather attribution)")
            run_and_save([sys.executable, "scripts/tpu_micro.py",
                          "--variant", "4"],
                         f"{ART}/MICRO4_tpu.json",
                         f"{ART}/MICRO4_tpu.log")
            log("micro4 done — running full-state tor_large")
            run_and_save([sys.executable, "scripts/tor_large_run.py",
                          "12"],
                         f"{ART}/TORLARGE_tpu.json",
                         f"{ART}/TORLARGE_tpu.log")
            return 0
        time.sleep(SLEEP_BETWEEN_S)
    log("gave up: TPU never recovered inside the window")
    return 1


if __name__ == "__main__":
    signal.signal(signal.SIGTERM, lambda *a: sys.exit(3))
    sys.exit(main())
