#!/usr/bin/env python
"""Campaign server launcher — thin wrapper over
``python -m shadow_tpu.serve`` for checkouts without an installed
package.

  python scripts/serve.py start  /var/spool/shadow
  python scripts/serve.py submit /var/spool/shadow run.yaml --priority 5
  python scripts/serve.py status /var/spool/shadow
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from shadow_tpu.serve.__main__ import main   # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
