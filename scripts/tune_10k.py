"""On-chip knob sweep for the 10k north-star rung.

Runs the fused 2.5 sim-s tgen_10000 slice across the perf knobs that
cannot be chosen off-chip (TPU gather/sort/VPU cost ratios differ from
CPU by >10x): pop_strategy x burst_pops x outbox_compact, printing
wall seconds + derived ms/round per combo and ONE final JSON line
with the best combo. pop/burst are trace-invariant by contract; a
combo that diverges anyway is flagged loudly and disqualified.
outbox_compact is CAPACITY-sensitive: too small fails loudly
(x_overflow) and is disqualified here, and because the sweep slice
may not cover steady state, bench.py re-guards it (workload match +
retry-without on overflow).

When a measured occupancy record (artifacts/OCC_*.json, written by
bench.py or any capacity_plan run — see device/capacity.py) exists
for a workload with this host count, compact widths below the
measured busiest-host outbox fill are PRUNED from the grid up front:
they can only overflow loudly, so sweeping them burns chip time to
learn what the record already says.

Usage: python scripts/tune_10k.py [stop_s] [config]
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

POPS = ("onehot", "gather")
BURSTS = (8, 16)
# outbox compaction shrinks the global merge's outbox block at the
# price of one per-host lane sort; too small fails LOUDLY
# (x_overflow) and the sweep just disqualifies that combo. The width
# is uniform and thus bounded by the BUSIEST host — on the hub-shaped
# 10k config a burst server legitimately fills its whole 40-row
# outbox (measured: compact=16 overflows 3k+ rows in the first
# traffic window), so the axis defaults OFF here; pass extra compact
# widths as trailing args for flatter workloads.
COMPACTS = (0,)


def prune_compacts(compacts: tuple, config: str, stop_ns: int) -> tuple:
    """Drop compact widths a measured occupancy record proves too
    small: the busiest host's outbox fill is a hard floor (a smaller
    compaction width x_overflows loudly and the combo is disqualified
    anyway — sweeping it just burns chip time). Records match on the
    device app class, host count, AND the workload fingerprint (app
    scalars + per-host parameter arrays) — a 10k-host phold record
    must never size a 10k-host tgen sweep, nor a heavy-traffic tgen
    record a light-traffic variant; among matches the longest
    measured window wins. A record covering a PREFIX of the sweep
    slice (stop_time <= `stop_ns`) proves the width overflows in the
    sweep itself; a longer record (e.g. bench.py's full-run headline)
    proves it overflows at the real rung even if the shorter slice
    survives it — either way the width is not worth chip time.
    Outbox fill per phase is a property of the event windows, which
    are pop/burst-invariant (the knobs this sweep varies), so the
    floor transfers across combos. No record means no pruning."""
    import glob

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import build
    from shadow_tpu.device import capacity
    from shadow_tpu.device.runner import NoDeviceTwin, device_twin

    if all(c == 0 for c in compacts):
        return compacts                 # nothing prunable on the axis
    try:
        sim = build(load_config(config))
        twin = device_twin(sim)
    except NoDeviceTwin:
        return compacts                 # sweep will fail loudly anyway
    app = type(twin).__name__
    app_fp = capacity.app_fingerprint(twin)
    n_hosts = len(sim.hosts)
    occ_dir = os.environ.get("SHADOW_TPU_OCC_DIR", "artifacts")
    best = None
    for path in sorted(glob.glob(os.path.join(occ_dir, "OCC_*.json"))):
        try:
            rec = capacity.load_record(path)
        except (OSError, ValueError):
            continue
        rec_stop = rec["workload"].get("stop_time", 0)
        if rec["workload"].get("n_hosts") == n_hosts and \
                rec["workload"].get("app") == app and \
                rec["workload"].get("app_fp") == app_fp \
                and rec_stop > 0 \
                and (best is None or rec_stop > best[2]):
            best = (path, rec, rec_stop)
    if best is None:
        return compacts
    path, rec, rec_stop = best
    floor = max(rec["measured"]["outbox_rows_max"],
                rec.get("final_measured", {}).get("outbox_rows_max", 0))
    keep = tuple(c for c in compacts if c == 0 or c >= floor)
    dropped = [c for c in compacts if c not in keep]
    if dropped:
        why = "they can only x_overflow in this sweep" \
            if rec_stop <= stop_ns else \
            (f"they x_overflow by {rec_stop / 1e9:g} sim-s even if "
             "this shorter slice survives them")
        print(f"  occupancy record {path}: busiest host fills {floor} "
              f"outbox rows — pruning compact widths {dropped} from "
              f"the sweep ({why})",
              file=sys.stderr, flush=True)
    return keep or (0,)


def main() -> int:
    stop_s = float(sys.argv[1]) if len(sys.argv) > 1 else 2.5
    config = sys.argv[2] if len(sys.argv) > 2 else \
        "examples/tgen_10000.yaml"
    compacts = tuple(int(a) for a in sys.argv[3:]) or COMPACTS

    from shadow_tpu._jax import jax
    from shadow_tpu import simtime
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    compacts = prune_compacts(compacts, config,
                              simtime.from_seconds(stop_s))

    platform = jax.devices()[0].platform
    results = []
    all_counts = []
    for pop, bp, cx in itertools.product(POPS, BURSTS, compacts):
        cfg = load_config(config)
        cfg.general.stop_time = simtime.from_seconds(stop_s)
        cfg.experimental.pop_strategy = pop
        cfg.experimental.burst_pops = bp
        cfg.experimental.outbox_compact = cx
        c = Controller(cfg)
        compile_s = 0.0
        try:
            # warm the compile BEFORE timing (bench.py does the
            # same): with the persistent compilation cache a
            # previously-compiled combo would otherwise skip ~50 s
            # of compile inside its timed window and win on that
            # alone, crowning a combo by cache state, not runtime
            t0 = time.perf_counter()
            st = c.runner.engine.init_state(c.sim.starts)
            c.runner.engine.run(
                st, stop=simtime.from_seconds(0.001))
            compile_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            stats = c.run()
            ok = bool(stats.ok)
            counts = (stats.events_executed, stats.packets_sent,
                      stats.packets_delivered, stats.packets_dropped)
            rounds = stats.rounds
        except Exception as e:          # noqa: BLE001
            print(f"  pop={pop} burst={bp} compact={cx}: "
                  f"RAISED {e}", file=sys.stderr, flush=True)
            ok, counts, rounds = False, None, 0
        wall = time.perf_counter() - t0
        row = {"pop": pop, "burst": bp, "compact": cx,
               "wall_s": round(wall, 2), "rounds": rounds,
               "compile_s": round(compile_s, 1),
               "ms_per_round": round(1e3 * wall / max(1, rounds), 2),
               "ok": ok}
        results.append(row)
        all_counts.append(counts)
        print(f"  pop={pop:7s} burst={bp:2d} compact={cx:2d}: "
              f"{wall:6.2f}s {row['ms_per_round']:7.2f} ms/round "
              f"{'' if ok else ' <== FAILED'}",
              file=sys.stderr, flush=True)

    # divergence is judged against the first SUCCESSFUL run — a
    # failed first combo must neither disqualify every good one nor
    # crown a divergent one (the knobs are trace-invariant, so every
    # ok run must agree)
    ref = next((c for r, c in zip(results, all_counts) if r["ok"]),
               None)
    for r, c in zip(results, all_counts):
        r["counts_match"] = bool(r["ok"] and c == ref)
        if r["ok"] and not r["counts_match"]:
            print(f"  DIVERGED: pop={r['pop']} burst={r['burst']} "
                  f"compact={r['compact']} {c} != {ref}",
                  file=sys.stderr, flush=True)
    good = [r for r in results if r["counts_match"]]
    best = min(good, key=lambda r: r["wall_s"]) if good else None
    print(json.dumps({"workload": config, "platform": platform,
                      "slice_sim_s": stop_s, "results": results,
                      "best": best}))
    return 0 if good else 1


if __name__ == "__main__":
    sys.exit(main())
