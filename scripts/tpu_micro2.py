"""Follow-up on-chip micro: multi-operand sorts vs gather recovery.

tpu_micro.py showed the flush's ~10 ms-per-gather takes dominate the
round (sorts are 1.6-2.6 ms). This measures the alternatives:
  - 6-operand flat sort (payload rides the sort, no perm gathers)
  - 5-operand merge sort (no take_along_axis recovery)
  - contiguous-window takes from sorted payload (1-hop)
  - row-stacked gather layouts ([F, 8] lanes)
  - the filler-sort "expand to fixed stride" construction

Usage: python scripts/tpu_micro2.py [reps]
"""

from __future__ import annotations

import json
import signal
import sys
import time

sys.path.insert(0, ".")

H = 10000
OB = 36
F = H * OB
E = 48
IN = 48
W = E + IN


def timed(label, fn, reps):
    from shadow_tpu._jax import jax
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"  [{label}] {1e3 * dt:.3f} ms/call", file=sys.stderr,
          flush=True)
    return round(1e3 * dt, 3)


def main() -> int:
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    signal.signal(signal.SIGALRM, lambda *a: sys.exit(9))
    signal.alarm(20 * 60)

    import numpy as np
    from shadow_tpu._jax import jax, jnp
    from jax import lax

    res = {"platform": jax.devices()[0].platform, "reps": reps}
    rng = np.random.default_rng(0)

    def arr64(shape, hi=1 << 60):
        return jax.device_put(jnp.asarray(
            rng.integers(0, hi, shape).astype(np.int64)))

    skey = arr64(F)
    p1, p2, p3, p4, p5 = (arr64(F) for _ in range(5))

    # 6-operand flat sort: payload rides through the bitonic passes
    sort6 = jax.jit(lambda k, a, b, c, d, e:
                    lax.sort((k, a, b, c, d, e), num_keys=1))
    res["flat_sort6_ms"] = timed(
        "flat sort 6-op F=360k",
        lambda: sort6(skey, p1, p2, p3, p4, p5), reps)

    # 2-operand for reference at same F
    sort2 = jax.jit(lambda k, a: lax.sort((k, a), num_keys=1))
    res["flat_sort2_ms"] = timed(
        "flat sort 2-op F=360k", lambda: sort2(skey, p1), reps)

    # 5-operand merge sort [H, W]
    ct = arr64((H, W))
    ck = arr64((H, W))
    cm = arr64((H, W))
    cv = arr64((H, W))
    cw = arr64((H, W))
    msort5 = jax.jit(lambda t, k, m, v, w: lax.sort(
        (t, k, m, v, w), dimension=1, num_keys=2))
    res["merge_sort5_ms"] = timed(
        "merge sort 5-op [10k,96]",
        lambda: msort5(ct, ck, cm, cv, cw), reps)

    # contiguous-window takes (1-hop, from sorted payload)
    starts = jnp.sort(arr64(H, hi=F - IN))
    idx = starts[:, None] + jnp.arange(IN, dtype=jnp.int64)[None, :]
    cidx = jnp.clip(idx, 0, F - 1).reshape(-1)
    win_take = jax.jit(lambda v: jnp.take(v, cidx).reshape(H, IN))
    res["window_take_ms_x1"] = timed(
        "contiguous window take x1", lambda: win_take(p1), reps)

    # row-stacked gather: [F, 8] i64, gather H*IN rows
    mat = arr64((F, 8))
    ridx = jnp.asarray(rng.integers(0, F, H * IN).astype(np.int32))
    row_gather = jax.jit(lambda m: jnp.take(m, ridx, axis=0))
    res["row_gather_f8_ms"] = timed(
        "row gather [F,8] x H*IN rows", lambda: row_gather(mat), reps)

    # row-stacked CONTIGUOUS window rows
    crow = jax.jit(lambda m: jnp.take(m, cidx.astype(jnp.int32),
                                      axis=0))
    res["row_gather_f8_contig_ms"] = timed(
        "row gather [F,8] contiguous windows", lambda: crow(mat), reps)

    # dynamic_slice-per-row via vmap (windows)
    def _dsl(m, s):
        return lax.dynamic_slice(m, (s,), (IN,))
    vds = jax.jit(lambda v: jax.vmap(_dsl, (None, 0))(v, starts))
    res["vmap_dynslice_ms_x1"] = timed(
        "vmap dynamic_slice windows x1", lambda: vds(p1), reps)

    # filler-sort expand: 2 stable sorts of (F + H*IN) x 6 operands
    FE = F + H * IN
    dkey = arr64(FE, hi=2 * H)
    q1, q2, q3, q4, q5 = (arr64(FE) for _ in range(5))
    sort6e = jax.jit(lambda k, a, b, c, d, e:
                     lax.sort((k, a, b, c, d, e), num_keys=1))

    def expand():
        r = sort6e(dkey, q1, q2, q3, q4, q5)
        return sort6e(r[1], r[0], r[2], r[3], r[4], r[5])

    res["filler_expand_2sorts_ms"] = timed(
        "filler expand 2x sort6 @840k", expand, reps)

    # one-hot matmul take_along_axis [H, W] -> [H, E]
    sie = jnp.asarray(rng.integers(0, W, (H, E)).astype(np.int32))

    def onehot_gather(m):
        oh = (sie[:, :, None] ==
              jnp.arange(W, dtype=jnp.int32)[None, None, :]) \
            .astype(jnp.float32)                      # [H, E, W]
        lo = (m & 0xFFFFF).astype(jnp.float32)
        mid = ((m >> 20) & 0xFFFFF).astype(jnp.float32)
        hi = ((m >> 40) & 0xFFFFFF).astype(jnp.float32)
        parts = jnp.stack([lo, mid, hi], axis=-1)     # [H, W, 3]
        got = jnp.einsum("hew,hwc->hec", oh, parts,
                         preferred_element_type=jnp.float32)
        lo_, mid_, hi_ = (got[..., i].astype(jnp.int64)
                          for i in range(3))
        return lo_ | (mid_ << 20) | (hi_ << 40)

    ohg = jax.jit(onehot_gather)
    res["onehot_gather_ms_x1"] = timed(
        "one-hot matmul take_along x1", lambda: ohg(cm), reps)

    # searchsorted at F for the window starts
    hb = jnp.arange(H + 1, dtype=jnp.int64) * OB
    skey_sorted = jnp.sort(skey)
    ss = jax.jit(lambda k: jnp.searchsorted(k, hb))
    res["searchsorted_ms"] = timed(
        "searchsorted F@10k+1", lambda: ss(skey_sorted), reps)

    print(json.dumps(res), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
