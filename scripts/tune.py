#!/usr/bin/env python
"""Strategy autotuner CLI (shadow_tpu/tune/, docs/autotune.md).

Searches the execution-strategy plan space for one workload — short
bounded-sim-window trials through the normal Controller path, warm
via the AOT compile cache, scored on pkts/s with the flight
recorder's per-phase walls as the diagnostic — and persists the
winner as ``PLAN_<app>_<H>_<fp>.json`` next to the OCC records.
Production runs then adopt it with
``experimental.strategy_plan: auto``.

The plan is guaranteed no-slower-than-defaults (a candidate that
cannot beat the full-window default baseline keeps the defaults) and
bit-identical to the default-knob run (every trial's per-host
signature is checked against the default run's; a diverging combo is
disqualified loudly).

Usage:
  python scripts/tune.py examples/tgen_1000.yaml
  python scripts/tune.py CONFIG --window 4 --budget 16
  python scripts/tune.py CONFIG --strategy successive_halving
  python scripts/tune.py CONFIG --out artifacts/PLAN_custom.json

Prints a human trial log on stderr and ONE final JSON line (the plan
summary) on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the tuner drives many short runs; the XLA machine-feature WARNING
# spam would drown the trial log (bench.py's rule)
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="search the execution-strategy plan space and "
                    "persist the winner per workload fingerprint")
    ap.add_argument("config", help="simulation config (YAML)")
    ap.add_argument("--window", type=float, default=0.0,
                    help="trial sim window in seconds (default: the "
                         "config's stop_time; shorter windows = "
                         "cheaper trials, noisier scores — make sure "
                         "the window reaches real traffic)")
    ap.add_argument("--budget", type=int, default=24,
                    help="max scored trials (default 24)")
    ap.add_argument("--strategy", default="auto",
                    choices=["auto", "coordinate_descent",
                             "successive_halving"],
                    help="search strategy (auto: halving when the "
                         "budget can race the grid, else descent)")
    ap.add_argument("--min-gain", type=float, default=0.02,
                    help="relative throughput gain a candidate must "
                         "show to unseat the incumbent (default "
                         "0.02)")
    ap.add_argument("--policy", default="",
                    help="scheduler policy for the trials (default: "
                         "the config's, coerced to tpu for CPU "
                         "policies; 'hybrid' tunes the judge knobs)")
    ap.add_argument("--out", default="",
                    help="PLAN record path (default: the canonical "
                         "PLAN_<app>_<H>_<fp>.json beside the OCC "
                         "records)")
    args = ap.parse_args()

    from shadow_tpu import simtime
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import build
    from shadow_tpu.device.aotcache import backend_identity
    from shadow_tpu.device.runner import NoDeviceTwin, device_twin
    from shadow_tpu.tune import plan as planmod
    from shadow_tpu.tune.trials import Tuner
    from shadow_tpu.utils import slog

    slog.init_logging("info")

    # the workload fingerprint comes from the device twin — a config
    # without one has no fingerprint to key a plan on
    sim = build(load_config(args.config))
    try:
        twin = device_twin(sim)
    except NoDeviceTwin as e:
        print(f"tune: {args.config} has no device twin ({e}) — "
              "nothing to fingerprint a plan against", file=sys.stderr)
        return 1
    n_hosts = len(sim.hosts)
    del sim

    window_ns = (simtime.from_seconds(args.window) if args.window
                 else 0)
    tuner = Tuner(args.config, window_ns=window_ns,
                  budget=args.budget, min_gain=args.min_gain,
                  policy=args.policy)
    body = tuner.search(args.strategy)

    from shadow_tpu._jax import jax
    record = {
        "format": planmod.FORMAT,
        "workload": {
            **planmod.workload_stamp(twin, n_hosts),
            "stop_time": tuner.stop,
            "seed": int(tuner.cfg.general.seed),
        },
        "config": os.path.normpath(args.config),
        "backend": backend_identity(jax.devices()),
        "source": "scripts/tune.py",
        **body,
    }
    path = args.out or planmod.plan_path(twin, n_hosts)
    planmod.save_plan(record, path)
    print(f"tune: plan -> {path}", file=sys.stderr)

    summary = {k: record[k] for k in
               ("workload", "policy", "strategy", "space", "default",
                "knobs", "improved", "score")}
    summary["plan"] = path
    print(json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
