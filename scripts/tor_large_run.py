#!/usr/bin/env python
"""Full-state tor_large execution (BASELINE row 5 evidence).

Runs examples/tor_large.yaml — ALL 56,000 hosts, full event/outbox
capacities, the real device program — for a bounded sim interval, and
prints one JSON line with sim-s/wall-s so the committed artifact
records an actual full-state execution (not a slice). On a machine
without the TPU relay, run with JAX_PLATFORMS=cpu; the platform is
recorded in the line either way.

Usage: python scripts/tor_large_run.py [stop_sim_s] [config]
Default stop: 12 s (past the 10 s bootstrap window so steady-state
onion cells flow).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    stop_s = float(sys.argv[1]) if len(sys.argv) > 1 else 12.0
    config = sys.argv[2] if len(sys.argv) > 2 else \
        "examples/tor_large.yaml"

    from shadow_tpu._jax import jax
    from shadow_tpu import simtime
    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import Controller

    platform = jax.devices()[0].platform
    cfg = load_config(config)
    cfg.general.stop_time = simtime.from_seconds(stop_s)

    t0 = time.perf_counter()
    c = Controller(cfg)
    build_wall = time.perf_counter() - t0
    n_hosts = len(c.sim.hosts)
    print(f"tor_large: state built for {n_hosts} hosts in "
          f"{build_wall:.1f}s", file=sys.stderr, flush=True)

    t1 = time.perf_counter()
    stats = c.run()
    run_wall = time.perf_counter() - t1

    out = {
        "workload": config,
        "platform": platform,
        "n_hosts": n_hosts,
        "sim_s": stop_s,
        "build_wall_s": round(build_wall, 1),
        "run_wall_s": round(run_wall, 1),
        "sim_s_per_wall_s": round(stop_s / run_wall, 4),
        "ok": bool(stats.ok),
        "rounds": stats.rounds,
        "events_executed": stats.events_executed,
        "packets_sent": stats.packets_sent,
        "packets_delivered": stats.packets_delivered,
        "packets_dropped": stats.packets_dropped,
    }
    print(json.dumps(out), flush=True)
    return 0 if stats.ok else 1


if __name__ == "__main__":
    sys.exit(main())
