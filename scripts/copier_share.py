#!/usr/bin/env python
"""Copier-share measurement (host/memory.py's revisit threshold).

Runs the 3-hop relay circuit — the most syscall/iovec-dense managed
workload in the repo — with SHADOWTPU_COPY_TIMING=1 and reports what
fraction of simulation wall time the ProcessMemory copier spent in
process_vm_readv/writev. memory.py documents "revisit the zero-copy
mapper if a profile shows the copier past ~10%": this script IS that
profile, runnable any time.

Usage: python scripts/copier_share.py
Prints one JSON line: {"wall_s": W, "copy_ms": C, "copy_share": S,
"copy_ops": N, "copy_bytes": B}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

os.environ["SHADOWTPU_COPY_TIMING"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests"))
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller
    from test_relay import _circuit_cfg

    tmp = tempfile.mkdtemp(prefix="copier_share_")
    plug = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests", "plugins")
    bins = {}
    for name in ("tcp_server", "relay", "onion_client"):
        exe = os.path.join(tmp, name)
        subprocess.run(["cc", "-O1", "-o", exe,
                        os.path.join(plug, f"{name}.c")], check=True,
                       capture_output=True)
        bins[name] = exe

    data = os.path.join(tmp, "shadow.data")
    cfg = load_config_str(_circuit_cfg("serial", data, bins))
    c = Controller(cfg)
    t0 = time.perf_counter()
    stats = c.run()
    wall = time.perf_counter() - t0
    assert stats.ok

    ops = by = ns = 0
    for h in c.sim.hosts:
        for app in h.apps:
            stack = [app]
            while stack:
                p = stack.pop()
                stack.extend(getattr(p, "children", {}).values())
                mem = getattr(p, "mem", None)
                if mem is not None:
                    ops += mem.copy_ops
                    by += mem.copy_bytes
                    ns += mem.copy_ns
    print(json.dumps({
        "workload": "relay_circuit(3 hops, 60 KB, serial policy)",
        "wall_s": round(wall, 3),
        "copy_ms": round(ns / 1e6, 1),
        "copy_share": round(ns / 1e9 / wall, 4),
        "copy_ops": ops,
        "copy_bytes": by,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
