// Binary spinning semaphore + simulator<->plugin IPC channel.
//
// The native runtime's equivalent of the reference's shim IPC
// (src/lib/shim/binary_spinning_sem.cc, ipc.cc, shadow_sem.c): the
// simulator and a managed process ping-pong strictly (one side runs at
// a time), so the wake path is a short adaptive spin on a shared
// atomic (cheap when the partner responds within a few microseconds —
// the common case for emulated syscalls) followed by a futex sleep.
//
// The channel struct lives inside a shared-memory arena; both sides
// map it at (possibly) different addresses, so everything is
// position-independent plain data.

#pragma once

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace shadow_tpu {

inline long futex_call(std::atomic<uint32_t>* uaddr, int op,
                       uint32_t val) {
  return syscall(SYS_futex, reinterpret_cast<uint32_t*>(uaddr), op, val,
                 nullptr, nullptr, 0);
}

struct SpinSem {
  std::atomic<uint32_t> value;
  uint32_t spin_max;      // preload_spin_max equivalent (default 8096)

  void init(uint32_t spins) {
    value.store(0, std::memory_order_relaxed);
    spin_max = spins;
  }

  void post() {
    value.store(1, std::memory_order_release);
    futex_call(&value, FUTEX_WAKE, 1);
  }

  // Returns false if `abort_flag` (e.g. plugin-exited) became set.
  bool wait(const std::atomic<uint32_t>* abort_flag = nullptr) {
    for (;;) {
      for (uint32_t i = 0; i < spin_max; ++i) {
        uint32_t one = 1;
        if (value.compare_exchange_weak(one, 0,
                                        std::memory_order_acquire))
          return true;
        if (abort_flag &&
            abort_flag->load(std::memory_order_relaxed))
          return false;
#if defined(__x86_64__)
        __builtin_ia32_pause();
#endif
      }
      // sleep until posted (value != 0), then loop to claim it
      futex_call(&value, FUTEX_WAIT, 0);
    }
  }

  // Timed variant: 1 = acquired, 0 = abort_flag set, -1 = timed out.
  int wait_timed(const std::atomic<uint32_t>* abort_flag,
                 uint32_t timeout_ms) {
    timespec start;
    clock_gettime(CLOCK_MONOTONIC, &start);
    for (;;) {
      for (uint32_t i = 0; i < spin_max; ++i) {
        uint32_t one = 1;
        if (value.compare_exchange_weak(one, 0,
                                        std::memory_order_acquire))
          return 1;
        if (abort_flag &&
            abort_flag->load(std::memory_order_relaxed))
          return 0;
#if defined(__x86_64__)
        __builtin_ia32_pause();
#endif
      }
      timespec now;
      clock_gettime(CLOCK_MONOTONIC, &now);
      uint64_t elapsed_ms =
          (uint64_t)(now.tv_sec - start.tv_sec) * 1000 +
          (now.tv_nsec - start.tv_nsec) / 1000000;
      if (elapsed_ms >= timeout_ms) return -1;
      // sleep in short slices so abort/timeout stay responsive
      uint64_t slice = timeout_ms - elapsed_ms;
      if (slice > 100) slice = 100;
      timespec ts{(time_t)(slice / 1000),
                  (long)((slice % 1000) * 1000000)};
      syscall(SYS_futex, reinterpret_cast<uint32_t*>(&value),
              FUTEX_WAIT, 0, &ts, nullptr, 0);
    }
  }
};

// Fixed-size message slots: enough for a syscall request (number + 6
// args + 64 inline bytes) or a response (retval + flags).
constexpr size_t kIpcMsgBytes = 128;

enum IpcMsgKind : uint32_t {
  IPC_NONE = 0,
  IPC_START = 1,          // simulator -> plugin: begin execution
  IPC_SYSCALL = 2,        // plugin -> simulator: syscall request
  IPC_SYSCALL_DONE = 3,   // simulator -> plugin: emulated result
  IPC_SYSCALL_NATIVE = 4, // simulator -> plugin: execute natively
  IPC_STOP = 5,
  IPC_CLONE_GO = 6,       // simulator -> plugin: clone approved;
                          // number = child vtid, args[0] = channel off
  IPC_THREAD_START = 7,   // child thread -> simulator on its channel
  IPC_THREAD_FAIL = 8,    // child channel: native clone failed
};

struct IpcMessage {
  uint32_t kind;
  uint32_t _pad;
  int64_t number;         // syscall number / return value
  uint64_t args[6];
  uint8_t inline_bytes[kIpcMsgBytes - 64];
};
static_assert(sizeof(IpcMessage) == kIpcMsgBytes, "message size");

struct IpcChannel {
  SpinSem to_plugin;
  SpinSem to_simulator;
  std::atomic<uint32_t> plugin_exited;
  // Thread-death guard (was implicit struct padding, so the ABI is
  // unchanged): the shim arms it to 1 before the native clone and
  // passes its address as CLONE_CHILD_CLEARTID, so the KERNEL clears
  // it when the native thread has truly died. The simulator polls it
  // before waking pthread_join'ers (glibc frees the joined thread's
  // stack on join return; waking early would let it free a stack the
  // dying thread still runs its signal epilogue on).
  std::atomic<uint32_t> native_thread_alive;
  IpcMessage msg_to_plugin;
  IpcMessage msg_to_simulator;
  // Simulated CLOCK_MONOTONIC ns, published by the simulator at every
  // syscall dispatch; read passively by the shim (log timestamps).
  std::atomic<uint64_t> sim_now;

  void init(uint32_t spin_max) {
    to_plugin.init(spin_max);
    to_simulator.init(spin_max);
    plugin_exited.store(0, std::memory_order_relaxed);
    native_thread_alive.store(0, std::memory_order_relaxed);
    memset(&msg_to_plugin, 0, sizeof(msg_to_plugin));
    memset(&msg_to_simulator, 0, sizeof(msg_to_simulator));
    sim_now.store(0, std::memory_order_relaxed);
  }

  // simulator side
  void send_to_plugin(const IpcMessage& m) {
    msg_to_plugin = m;
    to_plugin.post();
  }
  bool recv_from_plugin(IpcMessage* out) {
    if (!to_simulator.wait(&plugin_exited)) return false;
    *out = msg_to_simulator;
    return true;
  }
  // 1 = message received, 0 = plugin exited, -1 = timed out
  int recv_from_plugin_timed(IpcMessage* out, uint32_t timeout_ms) {
    int r = to_simulator.wait_timed(&plugin_exited, timeout_ms);
    if (r == 1) *out = msg_to_simulator;
    return r;
  }

  // plugin side
  void send_to_simulator(const IpcMessage& m) {
    msg_to_simulator = m;
    to_simulator.post();
  }
  bool recv_from_simulator(IpcMessage* out) {
    if (!to_plugin.wait()) return false;
    *out = msg_to_plugin;
    return true;
  }

  void mark_plugin_exited() {
    plugin_exited.store(1, std::memory_order_release);
    futex_call(&to_simulator.value, FUTEX_WAKE, 1);
  }
};

}  // namespace shadow_tpu
