/* Tracee launcher for the ptrace backend.
 *
 * The tracer must not os.fork() the Python simulator (JAX's runtime
 * threads make a non-exec fork a deadlock risk); instead the child is
 * posix_spawn'd running THIS stub, which applies the pre-exec
 * settings the old fork path did inline — deterministic-TSC trapping
 * (PR_SET_TSC survives execve) and ASLR off — then stops itself so
 * the tracer can PTRACE_SEIZE before a single app instruction runs,
 * and finally execs the real program (the tracer resumes it and
 * catches the PTRACE_EVENT_EXEC stop).
 *
 * Reference analogue: utility/fork_proxy.c isolates the same hazard
 * with a dedicated early fork thread. */
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/personality.h>
#include <sys/prctl.h>
#include <sys/resource.h>
#include <unistd.h>

#ifndef PR_SET_TSC
#define PR_SET_TSC 26
#endif
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif
#define ADDR_NO_RANDOMIZE 0x0040000

int main(int argc, char **argv) {
  int argi = 1;
  int tsc = 1;
  int run_mode = 0;
  for (; argi < argc; argi++) {
    if (strcmp(argv[argi], "--no-tsc") == 0) {
      tsc = 0;
    } else if (strcmp(argv[argi], "--run") == 0) {
      /* preload-backend mode (built -static so LD_PRELOAD is inert
       * in the stub itself): apply the pre-exec settings, no
       * SIGSTOP (nothing seizes us) and no TSC trap (the preload
       * shim manages PR_SET_TSC in its own constructor). The win
       * over a preexec_fn: no Python ever runs in the forked child
       * of the JAX-threaded simulator (CPython's documented
       * post-fork hazard) and _posixsubprocess may use vfork. */
      run_mode = 1;
    } else {
      break;
    }
  }
  if (argi >= argc) {
    fprintf(stderr,
            "usage: launcher [--no-tsc] [--run] <prog> [args...]\n");
    return 2;
  }
  personality(ADDR_NO_RANDOMIZE);
  if (tsc && !run_mode)
    prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);
  /* native fds must stay below the virtual-fd floor (600) so the
   * fd-range classification can never be wrong; libc callers see
   * VIRTUAL rlimits via the emulated getrlimit/prlimit64. A hard
   * limit already below 600 is fine as-is (fds stay below the
   * window); a FAILED setrlimit is not — a native fd landing in
   * [600,1024) would be classified as virtual, so fail loudly
   * instead of silently running uncapped. */
  struct rlimit nof = {600, 600};
  struct rlimit cur;
  if (getrlimit(RLIMIT_NOFILE, &cur) == 0 && cur.rlim_max < 600)
    nof.rlim_cur = nof.rlim_max = cur.rlim_max;
  if (setrlimit(RLIMIT_NOFILE, &nof) != 0) {
    perror("launcher: setrlimit(RLIMIT_NOFILE) failed - native fds "
           "could reach the virtual-fd window [600,1024)");
    return 126;
  }
  if (!run_mode)
    raise(SIGSTOP); /* tracer seizes here */
  if (run_mode)
    execvp(argv[argi], argv + argi); /* PATH semantics like Popen */
  else
    execv(argv[argi], argv + argi);
  perror("execv");
  return 127;
}
