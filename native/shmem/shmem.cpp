#include "shmem.hpp"

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <stdexcept>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace shadow_tpu {

namespace {
constexpr uint32_t kMagicUsed = 0x5D10C8ED;
constexpr uint32_t kMagicFree = 0xF2EEB10C;
constexpr uint32_t kMinOrder = 6;      // 64-byte smallest block
constexpr uint64_t kNil = ~0ull;

inline uint32_t order_for(size_t n) {
  uint32_t o = kMinOrder;
  while ((1ull << o) < n) ++o;
  return o;
}
}  // namespace

// Every block (free or used) starts with this 24-byte header; the
// buddy of block at offset `off` (order o) sits at `off ^ (1<<o)`.
struct BlockHdr {
  uint32_t magic;
  uint32_t order;
  uint64_t next;    // free-list links (offsets; kNil = end)
  uint64_t prev;
};

struct ShmArena::BuddyHeader {
  uint32_t magic;
  uint32_t top_order;
  uint64_t data_off;
  std::atomic_flag lock;
  uint64_t free_heads[64];   // per-order free lists (offsets)
  uint64_t allocated;
};

ShmArena::ShmArena(const std::string& name, size_t size, bool create)
    : name_(name), size_(size), owner_(create) {
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  fd_ = shm_open(name.c_str(), flags, 0600);
  if (fd_ < 0) throw std::runtime_error("shm_open failed: " + name);
  if (create && ftruncate(fd_, size) != 0) {
    ::close(fd_);
    shm_unlink(name.c_str());
    throw std::runtime_error("ftruncate failed");
  }
  if (!create) {
    struct stat st;
    fstat(fd_, &st);
    size_ = size = st.st_size;
  }
  base_ = static_cast<uint8_t*>(mmap(nullptr, size,
                                     PROT_READ | PROT_WRITE,
                                     MAP_SHARED, fd_, 0));
  if (base_ == MAP_FAILED) {
    ::close(fd_);
    throw std::runtime_error("mmap failed");
  }
  hdr_ = reinterpret_cast<BuddyHeader*>(base_);

  if (create) {
    memset(static_cast<void*>(hdr_), 0, sizeof(BuddyHeader));
    hdr_->data_off = 4096;
    // largest power-of-two region that fits after the header page
    uint32_t top = kMinOrder;
    while ((1ull << (top + 1)) <= size - hdr_->data_off) ++top;
    hdr_->top_order = top;
    for (auto& h : hdr_->free_heads) h = kNil;
    auto* blk = reinterpret_cast<BlockHdr*>(base_ + hdr_->data_off);
    blk->magic = kMagicFree;
    blk->order = top;
    blk->next = kNil;
    blk->prev = kNil;
    hdr_->free_heads[top] = 0;
    hdr_->magic = kMagicUsed;
  } else if (hdr_->magic != kMagicUsed) {
    throw std::runtime_error("arena not initialized: " + name);
  }
}

ShmArena::~ShmArena() {
  if (base_ && base_ != MAP_FAILED) munmap(base_, size_);
  if (fd_ >= 0) ::close(fd_);
}

void ShmArena::unlink() { shm_unlink(name_.c_str()); }

size_t ShmArena::allocated_bytes() const { return hdr_->allocated; }

namespace {
struct SpinGuard {
  std::atomic_flag& f;
  explicit SpinGuard(std::atomic_flag& fl) : f(fl) {
    while (f.test_and_set(std::memory_order_acquire)) {
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
    }
  }
  ~SpinGuard() { f.clear(std::memory_order_release); }
};
}  // namespace

void* ShmArena::alloc(size_t nbytes) {
  uint32_t want = order_for(nbytes + sizeof(BlockHdr));
  if (want > hdr_->top_order) return nullptr;
  SpinGuard g(hdr_->lock);

  auto blk_at = [&](uint64_t off) {
    return reinterpret_cast<BlockHdr*>(base_ + hdr_->data_off + off);
  };
  auto pop_head = [&](uint32_t o) -> uint64_t {
    uint64_t off = hdr_->free_heads[o];
    if (off == kNil) return kNil;
    BlockHdr* b = blk_at(off);
    hdr_->free_heads[o] = b->next;
    if (b->next != kNil) blk_at(b->next)->prev = kNil;
    return off;
  };
  auto push_head = [&](uint32_t o, uint64_t off) {
    BlockHdr* b = blk_at(off);
    b->magic = kMagicFree;
    b->order = o;
    b->prev = kNil;
    b->next = hdr_->free_heads[o];
    if (b->next != kNil) blk_at(b->next)->prev = off;
    hdr_->free_heads[o] = off;
  };

  // find the smallest order with a free block, splitting downward
  uint32_t o = want;
  while (o <= hdr_->top_order && hdr_->free_heads[o] == kNil) ++o;
  if (o > hdr_->top_order) return nullptr;
  uint64_t off = pop_head(o);
  while (o > want) {
    --o;
    push_head(o, off ^ (1ull << o));   // give back the upper half
  }
  BlockHdr* b = blk_at(off);
  b->magic = kMagicUsed;
  b->order = want;
  hdr_->allocated += (1ull << want);
  return reinterpret_cast<uint8_t*>(b) + sizeof(BlockHdr);
}

void ShmArena::free(void* p) {
  if (p == nullptr) return;
  auto* b = reinterpret_cast<BlockHdr*>(
      static_cast<uint8_t*>(p) - sizeof(BlockHdr));
  if (b->magic != kMagicUsed) throw std::runtime_error("bad free");
  SpinGuard g(hdr_->lock);

  auto blk_at = [&](uint64_t off) {
    return reinterpret_cast<BlockHdr*>(base_ + hdr_->data_off + off);
  };
  auto unlink_blk = [&](BlockHdr* fb) {
    if (fb->prev != kNil) blk_at(fb->prev)->next = fb->next;
    else hdr_->free_heads[fb->order] = fb->next;
    if (fb->next != kNil) blk_at(fb->next)->prev = fb->prev;
  };

  uint64_t off = reinterpret_cast<uint8_t*>(b)
      - (base_ + hdr_->data_off);
  uint32_t o = b->order;
  hdr_->allocated -= (1ull << o);

  // coalesce upward while the buddy is free and the same order
  while (o < hdr_->top_order) {
    uint64_t buddy = off ^ (1ull << o);
    BlockHdr* bb = blk_at(buddy);
    if (bb->magic != kMagicFree || bb->order != o) break;
    unlink_blk(bb);
    off = off < buddy ? off : buddy;
    ++o;
  }
  BlockHdr* fb = blk_at(off);
  fb->magic = kMagicFree;
  fb->order = o;
  fb->prev = kNil;
  fb->next = hdr_->free_heads[o];
  if (fb->next != kNil) blk_at(fb->next)->prev = off;
  hdr_->free_heads[o] = off;
}

ShmBlockHandle ShmArena::handle_of(void* p, size_t size) const {
  ShmBlockHandle h;
  memset(&h, 0, sizeof(h));
  snprintf(h.file_name, sizeof(h.file_name), "%s", name_.c_str());
  h.offset = static_cast<uint8_t*>(p) - base_;
  h.size = size;
  return h;
}

void* ShmArena::resolve(const ShmBlockHandle& h) const {
  if (h.offset + h.size > size_) return nullptr;
  return base_ + h.offset;
}

int ShmArena::cleanup_orphans(const char* prefix) {
  DIR* d = opendir("/dev/shm");
  if (!d) return 0;
  int removed = 0;
  struct dirent* e;
  size_t plen = strlen(prefix);
  while ((e = readdir(d)) != nullptr) {
    if (strncmp(e->d_name, prefix, plen) != 0) continue;
    // name format: <prefix><pid>_<n>; remove if the pid is dead
    long pid = atol(e->d_name + plen);
    if (pid > 0 && kill(static_cast<pid_t>(pid), 0) != 0
        && errno == ESRCH) {
      std::string path = "/";
      path += e->d_name;
      if (shm_unlink(path.c_str()) == 0) ++removed;
    }
  }
  closedir(d);
  return removed;
}

}  // namespace shadow_tpu
