// Shared-memory arena with buddy allocation and serializable handles.
//
// The native runtime's equivalent of the reference's shmem subsystem
// (src/main/shmem/: shmem_allocator.c, buddy.c, shmem_file.c) —
// redesigned as a C++ arena object rather than a global singleton, so a
// simulator process can host several independent arenas (one per
// managed-process pool). Blocks are identified by serializable handles
// (file name + offset) that cross process boundaries: the simulator
// allocates, the shim maps the file and resolves offsets.
//
// Used by the syscall-interposition IPC (native/ipc/) and, later, the
// shim preload library.

#pragma once

#include <atomic>
#include <cstddef>
#include <cstdint>
#include <string>

namespace shadow_tpu {

// Serialized block handle: enough for any process to find the bytes.
struct ShmBlockHandle {
  char file_name[64];
  uint64_t offset;
  uint64_t size;
};

class ShmArena {
 public:
  // Creates (create=true) or maps (create=false) a POSIX shared-memory
  // file of `size` bytes. `name` must start with '/'.
  ShmArena(const std::string& name, size_t size, bool create);
  ~ShmArena();

  ShmArena(const ShmArena&) = delete;
  ShmArena& operator=(const ShmArena&) = delete;

  // Buddy allocation inside the arena. Returns nullptr when exhausted.
  void* alloc(size_t nbytes);
  void free(void* p);

  // Handles for cross-process transport.
  ShmBlockHandle handle_of(void* p, size_t size) const;
  void* resolve(const ShmBlockHandle& h) const;

  const std::string& name() const { return name_; }
  uint8_t* base() const { return base_; }
  size_t size() const { return size_; }
  size_t allocated_bytes() const;

  // Unlink the backing file (owner side).
  void unlink();

  // Remove orphaned arenas from crashed runs (shmem_cleanup.c).
  static int cleanup_orphans(const char* prefix);

 private:
  std::string name_;
  uint8_t* base_ = nullptr;
  size_t size_ = 0;
  int fd_ = -1;
  bool owner_ = false;

  // Buddy state lives at the start of the arena so every mapping
  // process shares it. Guarded by a process-shared mutex word.
  struct BuddyHeader;
  BuddyHeader* hdr_ = nullptr;
};

}  // namespace shadow_tpu
