// Native unit tests (the reference keeps shmem_test.c, 656 LoC; same
// idea): buddy allocator invariants, cross-mapping handle resolution,
// and a forked-process IPC ping-pong over the spinning semaphores.

#include <cassert>
#include <cstdio>
#include <cstring>
#include <sys/wait.h>
#include <unistd.h>
#include <vector>

#include "ipc/spinsem.hpp"
#include "shmem/shmem.hpp"

using namespace shadow_tpu;

static std::string arena_name() {
  char buf[64];
  snprintf(buf, sizeof(buf), "/shadowtpu_test_%d_0", getpid());
  return buf;
}

static void test_alloc_free() {
  auto name = arena_name();
  ShmArena a(name, 1 << 20, true);
  std::vector<void*> ptrs;
  for (int i = 0; i < 100; ++i) {
    void* p = a.alloc(100 + i * 7);
    assert(p != nullptr);
    memset(p, i, 100 + i * 7);
    ptrs.push_back(p);
  }
  size_t mid = a.allocated_bytes();
  assert(mid > 0);
  for (size_t i = 0; i < ptrs.size(); i += 2) a.free(ptrs[i]);
  for (size_t i = 1; i < ptrs.size(); i += 2) a.free(ptrs[i]);
  assert(a.allocated_bytes() == 0);

  // after freeing everything, a huge block must be allocatable again
  // (coalescing happened)
  void* big = a.alloc(1 << 18);
  assert(big != nullptr);
  a.free(big);
  a.unlink();
  printf("alloc/free ok\n");
}

static void test_exhaustion() {
  auto name = arena_name() + "x";
  ShmArena a(name, 1 << 16, true);
  std::vector<void*> ptrs;
  for (;;) {
    void* p = a.alloc(1000);
    if (!p) break;
    ptrs.push_back(p);
  }
  assert(!ptrs.empty());
  for (void* p : ptrs) a.free(p);
  assert(a.allocated_bytes() == 0);
  a.unlink();
  printf("exhaustion ok (%zu blocks)\n", ptrs.size());
}

static void test_cross_process_ipc() {
  auto name = arena_name() + "ipc";
  ShmArena a(name, 1 << 20, true);
  void* mem = a.alloc(sizeof(IpcChannel));
  assert(mem);
  auto* ch = new (mem) IpcChannel();
  ch->init(1000);
  uint64_t off = reinterpret_cast<uint8_t*>(mem)
      - a.base();

  pid_t pid = fork();
  if (pid == 0) {
    // plugin side: re-map the arena like a separate process would
    ShmArena b(name, 0, false);
    auto* pch = reinterpret_cast<IpcChannel*>(b.base() + off);
    IpcMessage m;
    if (!pch->recv_from_simulator(&m)) _exit(1);
    if (m.kind != IPC_START) _exit(2);
    for (int i = 0; i < 1000; ++i) {
      IpcMessage sc{};
      sc.kind = IPC_SYSCALL;
      sc.number = 39;  // getpid
      sc.args[0] = static_cast<uint64_t>(i);
      pch->send_to_simulator(sc);
      IpcMessage r;
      if (!pch->recv_from_simulator(&r)) _exit(3);
      if (r.kind != IPC_SYSCALL_DONE ||
          r.number != static_cast<int64_t>(i * 2))
        _exit(4);
    }
    pch->mark_plugin_exited();
    _exit(0);
  }

  IpcMessage start{};
  start.kind = IPC_START;
  ch->send_to_plugin(start);
  int handled = 0;
  for (;;) {
    IpcMessage m;
    if (!ch->recv_from_plugin(&m)) break;   // plugin exited
    assert(m.kind == IPC_SYSCALL);
    IpcMessage r{};
    r.kind = IPC_SYSCALL_DONE;
    r.number = static_cast<int64_t>(m.args[0] * 2);
    ch->send_to_plugin(r);
    ++handled;
  }
  int status = 0;
  waitpid(pid, &status, 0);
  assert(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  assert(handled == 1000);
  a.free(mem);
  a.unlink();
  printf("cross-process ipc ok (%d round trips)\n", handled);
}

int main() {
  test_alloc_free();
  test_exhaustion();
  test_cross_process_ipc();
  printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
