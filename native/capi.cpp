// C API over the native runtime, for ctypes binding
// (shadow_tpu/native.py). Mirrors the surface the reference exports
// from its shmem allocator (shmemallocator_globalAlloc/Free,
// shmemserializer_globalBlockDeserialize) plus the IPC channel ops.

#include <cstring>
#include <new>

#include "ipc/spinsem.hpp"
#include "shmem/shmem.hpp"

using shadow_tpu::IpcChannel;
using shadow_tpu::IpcMessage;
using shadow_tpu::ShmArena;
using shadow_tpu::ShmBlockHandle;

// Pin the channel ABI that the shim (native/shim/shim.c) mirrors with
// plain C structs; a layout drift here must fail the build, not the
// plugin at runtime.
static_assert(sizeof(IpcMessage) == 128, "ipc message abi");
static_assert(sizeof(IpcChannel) == 288, "ipc channel abi");
static_assert(offsetof(IpcChannel, plugin_exited) == 16, "ipc abi");
static_assert(offsetof(IpcChannel, msg_to_plugin) == 24, "ipc abi");
static_assert(offsetof(IpcChannel, msg_to_simulator) == 152, "ipc abi");
static_assert(offsetof(IpcChannel, sim_now) == 280, "ipc abi");

extern "C" {

void* shadowtpu_arena_create(const char* name, uint64_t size) {
  try {
    return new ShmArena(name, size, /*create=*/true);
  } catch (...) {
    return nullptr;
  }
}

void* shadowtpu_arena_open(const char* name) {
  try {
    return new ShmArena(name, 0, /*create=*/false);
  } catch (...) {
    return nullptr;
  }
}

void shadowtpu_arena_close(void* arena) {
  delete static_cast<ShmArena*>(arena);
}

void shadowtpu_arena_unlink(void* arena) {
  static_cast<ShmArena*>(arena)->unlink();
}

void* shadowtpu_arena_alloc(void* arena, uint64_t nbytes) {
  return static_cast<ShmArena*>(arena)->alloc(nbytes);
}

void shadowtpu_arena_free(void* arena, void* p) {
  static_cast<ShmArena*>(arena)->free(p);
}

uint64_t shadowtpu_arena_allocated(void* arena) {
  return static_cast<ShmArena*>(arena)->allocated_bytes();
}

uint64_t shadowtpu_arena_offset(void* arena, void* p) {
  auto* a = static_cast<ShmArena*>(arena);
  return static_cast<uint8_t*>(p) - a->base();
}

void* shadowtpu_arena_at(void* arena, uint64_t offset) {
  auto* a = static_cast<ShmArena*>(arena);
  return a->base() + offset;
}

int shadowtpu_cleanup_orphans(const char* prefix) {
  return ShmArena::cleanup_orphans(prefix);
}

// ---- IPC channel (lives inside an arena block) ----------------------

uint64_t shadowtpu_ipc_sizeof() { return sizeof(IpcChannel); }

void shadowtpu_ipc_init(void* mem, uint32_t spin_max) {
  static_cast<IpcChannel*>(mem)->init(spin_max);
}

void shadowtpu_ipc_send_to_plugin(void* ch, const IpcMessage* m) {
  static_cast<IpcChannel*>(ch)->send_to_plugin(*m);
}

void shadowtpu_ipc_set_sim_now(void* ch, uint64_t now_ns) {
  static_cast<IpcChannel*>(ch)->sim_now.store(
      now_ns, std::memory_order_relaxed);
}

int shadowtpu_ipc_recv_from_plugin(void* ch, IpcMessage* out) {
  return static_cast<IpcChannel*>(ch)->recv_from_plugin(out) ? 1 : 0;
}

int shadowtpu_ipc_recv_from_plugin_timed(void* ch, IpcMessage* out,
                                         uint32_t timeout_ms) {
  return static_cast<IpcChannel*>(ch)->recv_from_plugin_timed(
      out, timeout_ms);
}

void shadowtpu_ipc_send_to_simulator(void* ch, const IpcMessage* m) {
  static_cast<IpcChannel*>(ch)->send_to_simulator(*m);
}

int shadowtpu_ipc_recv_from_simulator(void* ch, IpcMessage* out) {
  return static_cast<IpcChannel*>(ch)->recv_from_simulator(out) ? 1 : 0;
}

void shadowtpu_ipc_mark_plugin_exited(void* ch) {
  static_cast<IpcChannel*>(ch)->mark_plugin_exited();
}

// 1 while the cloned native thread is alive (shim arms the guard before
// its raw clone; the kernel clears it via CLONE_CHILD_CLEARTID at true
// thread death). 0 once dead or never armed.
uint32_t shadowtpu_ipc_native_thread_alive(void* ch) {
  return static_cast<IpcChannel*>(ch)->native_thread_alive.load(
      std::memory_order_acquire);
}

}  // extern "C"
