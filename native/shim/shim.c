/* Shim preload library: runs inside managed (real) processes.
 *
 * The rebuild of the reference's shim layer (src/lib/shim/shim.c:393-506
 * seccomp install, preload_syscall.c syscall funnel, ipc.cc spinning-sem
 * IPC, preload_libraries.c:30-120 libc overrides): LD_PRELOADed into a
 * real Linux program spawned by the simulator, it
 *
 *   1. maps the simulator's shared-memory arena and locates its IPC
 *      channel (env SHADOWTPU_SHM / SHADOWTPU_IPC_OFFSET),
 *   2. installs a seccomp filter that TRAPs the simulation-relevant
 *      syscalls (network, time, sleep, epoll/poll/select, random, pid,
 *      exit) and fd-gated syscalls whose fd argument is in the virtual
 *      descriptor range, while syscalls issued from the shim's own
 *      raw-syscall instruction are allowed through (instruction-pointer
 *      range check, like the reference's shadow_vreal_raw_syscall
 *      escape),
 *   3. forwards each trapped syscall over the spinning-semaphore IPC
 *      channel to the simulator and applies the verdict: DONE (return
 *      the emulated result) or NATIVE (re-execute through the allowed
 *      raw-syscall instruction).
 *
 * Virtual descriptors live at fd >= SHADOWTPU_VFD_BASE so native kernel
 * fds (files opened by the plugin, stdio) never collide and their
 * read/write/close run natively with zero interposition cost — the BPF
 * filter itself checks the fd argument, so the common file-I/O path
 * does not even take a signal.
 *
 * Single-threaded plugins only for now: clone/fork are trapped and
 * refused by the simulator (ENOSYS).  All plugin<->simulator execution
 * is strictly ping-pong, one side runs at a time.
 */

#define _GNU_SOURCE
#include <dlfcn.h>
#include <errno.h>
#include <ifaddrs.h>
#include <stdarg.h>
#include <net/if.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <sys/socket.h>
#include <sys/utsname.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/futex.h>
#include <linux/seccomp.h>
#include <signal.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/prctl.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/ucontext.h>

#ifndef SYS_SECCOMP
#define SYS_SECCOMP 1 /* siginfo si_code for seccomp SIGSYS traps */
#endif
#include <time.h>
#include <unistd.h>
#include <fcntl.h>
#include <sys/mman.h>

/* ---- constants shared with the Python side ------------------------- */

/* Virtual fds live in [600, 1024): BELOW FD_SETSIZE so select()'s
 * fd_set can express them (glibc's FD_SET writes bit fd into a
 * 1024-bit array — a giant vfd number would smash memory in APP code
 * before any syscall is made), and above every native fd the plugin
 * can hold (the spawn path caps RLIMIT_NOFILE at 600, so the kernel
 * never hands out a native fd >= 600 and the fd-range gate stays
 * airtight). Values outside the window (e.g. AT_FDCWD as u32) are
 * not virtual fds and stay native. */
#define SHADOWTPU_VFD_BASE 600u  /* virtual descriptor fd floor */
#define SHADOWTPU_VFD_END 1024u  /* exclusive ceiling (FD_SETSIZE) */

enum {
  IPC_NONE = 0,
  IPC_START = 1,
  IPC_SYSCALL = 2,
  IPC_SYSCALL_DONE = 3,
  IPC_SYSCALL_NATIVE = 4,
  IPC_STOP = 5,
  IPC_CLONE_GO = 6,     /* sim -> plugin: clone approved; number = child
                         * vtid, args[0] = child channel arena offset */
  IPC_THREAD_START = 7, /* child -> sim on its own channel: alive */
  IPC_THREAD_FAIL = 8,  /* child channel: native clone failed */
  IPC_FORK_RESULT = 9,  /* parent -> sim: real child pid (or -errno) */
  IPC_EXEC_DONE = 12,   /* plugin -> sim: new image after execve is
                           live on the same channel (constructor) */
  IPC_SIGNAL = 10,      /* sim -> plugin: run handler args[0] for
                         * signal `number` (args[1] = sa_flags) */
  IPC_SIGNAL_DONE = 11, /* plugin -> sim: handler returned */
};

/* ---- IPC ABI: byte-compatible with native/ipc/spinsem.hpp ---------- */

typedef struct {
  volatile uint32_t value;
  uint32_t spin_max;
} ShimSem;

typedef struct {
  uint32_t kind;
  uint32_t _pad;
  int64_t number; /* syscall number / return value */
  uint64_t args[6];
  uint8_t inline_bytes[64];
} ShimMsg;

typedef struct {
  ShimSem to_plugin;
  ShimSem to_simulator;
  volatile uint32_t plugin_exited;
  /* Armed to 1 before the native clone; the KERNEL clears it to 0 when
   * the native thread truly dies (CLONE_CHILD_CLEARTID pointed here).
   * The simulator polls it before waking pthread_join'ers, so glibc
   * never reuses a stack the dying thread is still running on. */
  volatile uint32_t native_thread_alive;
  ShimMsg msg_to_plugin;
  ShimMsg msg_to_simulator;
  /* Simulated CLOCK_MONOTONIC ns, published by the simulator at every
   * syscall dispatch (ref shim_event.h:17-22 sim_time block): lets the
   * shim timestamp logs — and potentially fast-path time reads —
   * without an IPC round trip. */
  volatile uint64_t sim_now;
} ShimChannel;

_Static_assert(sizeof(ShimMsg) == 128, "msg abi");
_Static_assert(sizeof(ShimChannel) == 288, "channel abi");
_Static_assert(__builtin_offsetof(ShimChannel, sim_now) == 280, "abi");
_Static_assert(__builtin_offsetof(ShimChannel, plugin_exited) == 16, "abi");
_Static_assert(__builtin_offsetof(ShimChannel, msg_to_plugin) == 24, "abi");
_Static_assert(__builtin_offsetof(ShimChannel, msg_to_simulator) == 152,
               "abi");

/* ---- state --------------------------------------------------------- */

static int g_enabled = 0;
static int g_trace_traps = 0;
static void shim_logf(const char *fmt, ...)
    __attribute__((format(printf, 1, 2)));
static ShimChannel *g_ch = NULL;     /* main thread's channel */
static char *g_arena_base = NULL;

/* Per-thread IPC channel: the main thread uses g_ch; clone children
 * get their own channel from the simulator (one thread of a process
 * runs at a time, each in strict ping-pong on its own channel).
 * initial-exec TLS so accessing it never allocates (signal context). */
static __thread ShimChannel *t_ch
    __attribute__((tls_model("initial-exec"))) = NULL;

static inline ShimChannel *cur_ch(void) { return t_ch ? t_ch : g_ch; }

#define SHIM_CLONE_SCRATCH (64 * 1024)
/* this thread's clone scratch stack (freed at thread exit, once we're
 * running on the app's pthread stack) */
static __thread void *t_scratch
    __attribute__((tls_model("initial-exec"))) = NULL;

/* ---- the natively-allowed syscall instructions --------------------- */
/* All raw syscall insns live between shim_syscall_insn_start/end; the
 * seccomp filter allows any syscall whose post-insn ip falls in that
 * range (the reference's shadow_vreal_raw_syscall escape).
 * shim_rawsyscall: (long nr, a, b, c, d, e, f) — args map SysV->kernel
 * registers; the 7th argument arrives on the stack.
 * shim_clone_raw: raw clone where the CHILD starts on a scratch stack
 * whose top word is a CloneBoot pointer; the child pops it and enters
 * shim_child_start (never returns), while the parent returns the
 * kernel result. */

typedef long (*shim_raw_fn)(long nr, long a, long b, long c, long d,
                            long e, long f);
typedef long (*shim_clone_fn)(long flags, long child_sp, long ptid,
                              long ctid, long tls);
long shim_rawsyscall_tmpl(long nr, long a, long b, long c, long d,
                          long e, long f);
long shim_clone_raw_tmpl(long flags, long child_sp, long ptid,
                         long ctid, long tls);
void shim_child_start(void *boot);
extern const char shim_syscall_insn_start[];
extern const char shim_syscall_insn_end[];
extern const char shim_child_slot[];
extern const char shim_sigreturn_tmpl[];

/* The template is POSITION-INDEPENDENT as a block (the one external
 * reference, shim_child_start, goes through shim_child_slot inside
 * the block) so it can be copied to SHIM_TRAMP_ADDR — a FIXED page
 * shared by every shim generation. Why: seccomp filters survive
 * execve, and a stale filter's instruction-pointer escape would
 * otherwise point at the OLD image's shim mapping, force-killing the
 * new image's raw syscalls. With the escape range at a fixed address,
 * arbitrarily many stacked generations all allow the same page. */
__asm__(".text\n"
        ".globl shim_syscall_insn_start\n"
        "shim_syscall_insn_start:\n"
        ".globl shim_rawsyscall_tmpl\n"
        ".type shim_rawsyscall_tmpl,@function\n"
        "shim_rawsyscall_tmpl:\n"
        "  mov %rdi,%rax\n"
        "  mov %rsi,%rdi\n"
        "  mov %rdx,%rsi\n"
        "  mov %rcx,%rdx\n"
        "  mov %r8,%r10\n"
        "  mov %r9,%r8\n"
        "  mov 8(%rsp),%r9\n"
        "  syscall\n"
        "  ret\n"
        ".size shim_rawsyscall_tmpl,.-shim_rawsyscall_tmpl\n"
        ".globl shim_clone_raw_tmpl\n"
        ".type shim_clone_raw_tmpl,@function\n"
        "shim_clone_raw_tmpl:\n"
        "  mov %rcx,%r10\n"          /* ctid: SysV rcx -> kernel r10 */
        "  mov $56,%eax\n"           /* SYS_clone */
        "  syscall\n"
        "  test %rax,%rax\n"
        "  jnz 1f\n"
        "  pop %rdi\n"               /* child: scratch top = CloneBoot* */
        "  call *shim_child_slot(%rip)\n"  /* never returns */
        "1: ret\n"
        ".size shim_clone_raw_tmpl,.-shim_clone_raw_tmpl\n"
        ".globl shim_sigreturn_tmpl\n"
        ".type shim_sigreturn_tmpl,@function\n"
        "shim_sigreturn_tmpl:\n"
        "  mov $15,%eax\n"            /* SYS_rt_sigreturn */
        "  syscall\n"
        ".size shim_sigreturn_tmpl,.-shim_sigreturn_tmpl\n"
        ".balign 8\n"
        ".globl shim_child_slot\n"
        ".hidden shim_child_slot\n"
        "shim_child_slot:\n"
        "  .quad 0\n"
        ".globl shim_restore_context\n"
        ".type shim_restore_context,@function\n"
        "shim_restore_context:\n"    /* (CloneBoot*) — jump into app */
        "  mov %rdi,%rax\n"
        "  mov 8(%rax),%rsp\n"       /* app child_stack */
        "  mov 16(%rax),%rcx\n"      /* app rip (post-syscall insn) */
        "  push %rcx\n"
        "  mov 24(%rax),%rbx\n"
        "  mov 32(%rax),%rbp\n"
        "  mov 40(%rax),%r12\n"
        "  mov 48(%rax),%r13\n"
        "  mov 56(%rax),%r14\n"
        "  mov 64(%rax),%r15\n"
        "  mov 72(%rax),%rsi\n"
        "  mov 80(%rax),%rdx\n"
        "  mov 88(%rax),%r8\n"
        "  mov 96(%rax),%r9\n"
        "  mov 104(%rax),%r10\n"
        "  mov 112(%rax),%r11\n"
        "  mov 120(%rax),%rcx\n"
        "  mov 128(%rax),%rdi\n"
        "  xor %eax,%eax\n"          /* child's clone() returns 0 */
        "  ret\n"
        ".size shim_restore_context,.-shim_restore_context\n"
        ".globl shim_syscall_insn_end\n"
        "shim_syscall_insn_end:\n");

/* Fixed-address trampoline page (see the template comment). All raw
 * syscalls route through these pointers; the seccomp escape range is
 * [active base, +template size). */
#define SHIM_TRAMP_ADDR ((void *)0x6fff00000000UL)

static shim_raw_fn shim_rawsyscall = shim_rawsyscall_tmpl;
static shim_clone_fn shim_clone_raw = shim_clone_raw_tmpl;
static void *g_sigreturn = NULL;
static uintptr_t g_escape_lo, g_escape_hi;

/* Raw rt_sigaction through the trampoline, with the trampoline's own
 * rt_sigreturn restorer: a post-execve constructor runs under the OLD
 * image's stacked seccomp filter, which traps rt_sigaction — glibc's
 * sigaction would be force-killed before our SIGSYS handler exists. */
struct shim_ksigaction {
  void *handler;
  unsigned long flags;
  void *restorer;
  uint64_t mask;
};

#define SHIM_SA_RESTORER 0x04000000UL

static int shim_raw_sigaction(int sig, void *fn, unsigned long flags) {
  struct shim_ksigaction ks;
  ks.handler = fn;
  ks.flags = flags | SHIM_SA_RESTORER;
  ks.restorer = g_sigreturn;
  ks.mask = 0;
  return (int)shim_rawsyscall(SYS_rt_sigaction, sig, (long)&ks, 0, 8,
                              0, 0);
}

static void shim_setup_trampoline(void) {
  size_t len = (size_t)(shim_syscall_insn_end - shim_syscall_insn_start);
  size_t plen = (len + 4095) & ~(size_t)4095;
  long slot_off = shim_child_slot - shim_syscall_insn_start;
  long raw_off = (const char *)shim_rawsyscall_tmpl
      - shim_syscall_insn_start;
  long clone_off = (const char *)shim_clone_raw_tmpl
      - shim_syscall_insn_start;
  long sr_off = shim_sigreturn_tmpl - shim_syscall_insn_start;
  char *page = mmap(SHIM_TRAMP_ADDR, plen, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS | MAP_FIXED_NOREPLACE,
                    -1, 0);
  if (page == SHIM_TRAMP_ADDR) {
    memcpy(page, shim_syscall_insn_start, len);
    *(void **)(page + slot_off) = (void *)shim_child_start;
    if (mprotect(page, plen, PROT_READ | PROT_EXEC) == 0) {
      shim_rawsyscall = (shim_raw_fn)(page + raw_off);
      shim_clone_raw = (shim_clone_fn)(page + clone_off);
      g_sigreturn = page + sr_off;
      g_escape_lo = (uintptr_t)page;
      g_escape_hi = (uintptr_t)page + len;
      return;
    }
    munmap(page, plen);
  } else if (page != MAP_FAILED) {
    munmap(page, plen);
  }
  /* fallback: stay in the .so image (execve into a differently-laid-
   * out image is then unsupported); patch the slot in place */
  /* the slot is 8-aligned so it cannot straddle a page: one page */
  uintptr_t sbase = ((uintptr_t)shim_child_slot) & ~(uintptr_t)4095;
  if (mprotect((void *)sbase, 4096,
               PROT_READ | PROT_WRITE | PROT_EXEC) == 0) {
    *(void **)shim_child_slot = (void *)shim_child_start;
    mprotect((void *)sbase, 4096, PROT_READ | PROT_EXEC);
  }
  g_sigreturn = (void *)shim_sigreturn_tmpl;
  g_escape_lo = (uintptr_t)shim_syscall_insn_start;
  g_escape_hi = (uintptr_t)shim_syscall_insn_end;
}

/* ---- spinning semaphore (plugin side) ------------------------------ */

static void sem_post(volatile uint32_t *v) {
  __atomic_store_n(v, 1, __ATOMIC_RELEASE);
  shim_rawsyscall(SYS_futex, (long)v, FUTEX_WAKE, 1, 0, 0, 0);
}

static void sem_wait(ShimSem *s) {
  uint32_t spins = s->spin_max ? s->spin_max : 8096;
  for (;;) {
    for (uint32_t i = 0; i < spins; i++) {
      uint32_t one = 1;
      if (__atomic_compare_exchange_n(&s->value, &one, 0, 1,
                                      __ATOMIC_ACQUIRE,
                                      __ATOMIC_RELAXED))
        return;
#if defined(__x86_64__)
      __builtin_ia32_pause();
#endif
    }
    shim_rawsyscall(SYS_futex, (long)&s->value, FUTEX_WAIT, 0, 0, 0, 0);
  }
}

/* ---- syscall funnel ------------------------------------------------ */

/* fd-gated syscalls: interposed only when the fd argument addresses a
 * virtual descriptor. Keep in sync with the BPF filter below and the
 * Python handler (shadow_tpu/host/syscalls.py). */
static int is_fd_gated(long nr) {
  switch (nr) {
  case SYS_read:
  case SYS_write:
  case SYS_readv:
  case SYS_writev:
  case SYS_close:
  case SYS_fstat:
  case SYS_lseek:
  case SYS_ioctl:
  case SYS_fcntl:
  case SYS_dup:
  case SYS_dup2:
  case SYS_dup3:
  case SYS_pread64:
  case SYS_pwrite64:
  case SYS_newfstatat: /* glibc's fstat(fd) path; dirfd-gated */
  case SYS_statx:
  case SYS_sendfile:   /* out_fd-gated: emulated only toward our sockets */
    return 1;
  default:
    return 0;
  }
}

/* Virtual signal delivery: the simulator may interleave IPC_SIGNAL
 * messages before any reply; the shim runs the app's handler (in the
 * app's own address space — this IS the signal frame, delivered at a
 * syscall boundary exactly like the kernel would) and acks. */
/* forward decls: the handler-nesting state lives with the SIGSYS
 * handler below */
static __thread volatile int g_in_handler
    __attribute__((tls_model("initial-exec")));
static __thread ucontext_t *t_trap_ctx
    __attribute__((tls_model("initial-exec")));

static void shim_invoke_signal(const ShimMsg *m) {
  int signum = (int)m->number;
  void *h = (void *)(uintptr_t)m->args[0];
  uint64_t sa_flags = m->args[1];
  if (!h)
    return;
  /* the handler is APP code: it may legitimately make trapped
   * syscalls, which nest another SIGSYS while we might already be
   * inside one — suspend the nested-trap diagnostics and the trap
   * context for the duration */
  int saved_in = g_in_handler;
  ucontext_t *saved_ctx = t_trap_ctx;
  g_in_handler = 0;
  if (sa_flags & 4 /* SA_SIGINFO */) {
    siginfo_t si;
    memset(&si, 0, sizeof si);
    si.si_signo = signum;
    ucontext_t uc;
    memset(&uc, 0, sizeof uc);
    ((void (*)(int, siginfo_t *, void *))h)(signum, &si, &uc);
  } else {
    ((void (*)(int))h)(signum);
  }
  g_in_handler = saved_in;
  t_trap_ctx = saved_ctx;
}

/* Wait for a simulator reply on `ch`, servicing any interleaved
 * IPC_SIGNAL deliveries. */
static ShimMsg *shim_wait_reply(ShimChannel *ch) {
  for (;;) {
    sem_wait(&ch->to_plugin);
    ShimMsg *in = (ShimMsg *)&ch->msg_to_plugin;
    if (in->kind != IPC_SIGNAL)
      return in;
    shim_invoke_signal(in);
    ShimMsg *out = (ShimMsg *)&ch->msg_to_simulator;
    out->kind = IPC_SIGNAL_DONE;
    out->number = 0;
    sem_post(&ch->to_simulator.value);
  }
}

/* Forward one syscall to the simulator over the calling thread's
 * channel; returns the kernel-convention result (negative errno on
 * failure) or the raw reply message for multi-step protocols (clone).
 * Safe in signal context: only futexes + the raw syscall instruction. */
static ShimMsg *shim_roundtrip(long nr, const long args[6]) {
  ShimChannel *ch = cur_ch();
  ShimMsg *out = (ShimMsg *)&ch->msg_to_simulator;
  out->kind = IPC_SYSCALL;
  out->number = nr;
  for (int i = 0; i < 6; i++)
    out->args[i] = (uint64_t)args[i];
  sem_post(&ch->to_simulator.value);
  return shim_wait_reply(ch);
}

static long shim_emulated_syscall(long nr, const long args[6]) {
  ShimMsg *in = shim_roundtrip(nr, args);
  switch (in->kind) {
  case IPC_SYSCALL_DONE:
    return (long)in->number;
  case IPC_SYSCALL_NATIVE:
    if (nr == SYS_exit || nr == SYS_exit_group) {
      /* die HERE, not by unwinding through glibc: it keeps the
       * window between the simulator's joiner wakeup and this
       * thread's true death to a handful of instructions, and lets
       * us free the clone scratch stack (we run on the app stack) */
      if (nr == SYS_exit && t_scratch) {
        void *sc = t_scratch;
        t_scratch = NULL;
        shim_rawsyscall(SYS_munmap, (long)sc, SHIM_CLONE_SCRATCH, 0, 0,
                        0, 0);
      }
      shim_rawsyscall(nr, args[0], 0, 0, 0, 0, 0);
    }
    return shim_rawsyscall(nr, args[0], args[1], args[2], args[3],
                           args[4], args[5]);
  case IPC_STOP:
    shim_rawsyscall(SYS_exit_group, (long)in->number, 0, 0, 0, 0, 0);
    return -ENOSYS; /* unreachable */
  default:
    return -ENOSYS;
  }
}

/* ---- clone: managed thread creation -------------------------------- */
/* The simulator approves the clone and hands us a fresh IPC channel
 * for the child (IPC_CLONE_GO). We then execute the REAL clone, but
 * point the child at a scratch stack running shim_child_start: it
 * adopts its channel, announces itself, waits for the simulator to
 * schedule it, and only then restores the app's register context
 * (kernel clone child semantics: parent's registers, RAX=0, RSP=the
 * app's child_stack) and resumes app code. One thread runs at a time,
 * controlled by the simulator (reference thread model: clone.c +
 * shim.c's clone handshake). */

typedef struct {
  ShimChannel *ch;        /* 0  */
  uint64_t rsp;           /* 8  — app child_stack */
  uint64_t rip;           /* 16 — post-syscall-insn ip */
  uint64_t rbx, rbp, r12, r13, r14, r15;  /* 24..64 */
  uint64_t rsi, rdx, r8, r9, r10, r11;    /* 72..112 */
  uint64_t rcx, rdi;      /* 120, 128 */
} CloneBoot;

void shim_restore_context(CloneBoot *b);

static __thread ucontext_t *t_trap_ctx
    __attribute__((tls_model("initial-exec"))) = NULL;

#ifndef CLONE_PARENT_SETTID
#define CLONE_PARENT_SETTID 0x00100000
#endif
#ifndef CLONE_CHILD_CLEARTID
#define CLONE_CHILD_CLEARTID 0x00200000
#endif
#ifndef CLONE_CHILD_SETTID
#define CLONE_CHILD_SETTID 0x01000000
#endif

void shim_child_start(void *bootv) {
  CloneBoot *b = (CloneBoot *)bootv;
  t_ch = b->ch;
  t_scratch = (void *)b;
  /* make sure SIGSYS is deliverable in this thread no matter what
   * mask the clone inherited */
  uint64_t unblock = 1ULL << (SIGSYS - 1);
  shim_rawsyscall(SYS_rt_sigprocmask, 1 /* SIG_UNBLOCK */,
                  (long)&unblock, 0, 8, 0, 0);
  ShimMsg *out = (ShimMsg *)&t_ch->msg_to_simulator;
  out->kind = IPC_THREAD_START;
  out->number = 0;
  sem_post(&t_ch->to_simulator.value);
  sem_wait(&t_ch->to_plugin);   /* IPC_START: simulator scheduled us */
  shim_restore_context(b);      /* never returns */
}

static long shim_handle_clone(const long args[6]) {
  ShimMsg *in = shim_roundtrip(SYS_clone, args);
  if (in->kind == IPC_SYSCALL_DONE)
    return (long)in->number;    /* refused (-errno) */
  if (in->kind != IPC_CLONE_GO)
    return -ENOSYS;
  long vtid = (long)in->number;
  uint64_t ch_off = in->args[0];

  void *scratch = (void *)shim_rawsyscall(
      SYS_mmap, 0, SHIM_CLONE_SCRATCH, 0x3 /* RW */,
      0x22 /* PRIVATE|ANON */, -1, 0);
  if ((long)scratch < 0)
    return (long)scratch;
  CloneBoot *b = (CloneBoot *)scratch;
  b->ch = (ShimChannel *)(g_arena_base + ch_off);
  b->rsp = (uint64_t)args[1];
  ucontext_t *uc = t_trap_ctx;
  greg_t *g = uc->uc_mcontext.gregs;
  b->rip = (uint64_t)g[REG_RIP];
  b->rbx = (uint64_t)g[REG_RBX];
  b->rbp = (uint64_t)g[REG_RBP];
  b->r12 = (uint64_t)g[REG_R12];
  b->r13 = (uint64_t)g[REG_R13];
  b->r14 = (uint64_t)g[REG_R14];
  b->r15 = (uint64_t)g[REG_R15];
  b->rsi = (uint64_t)g[REG_RSI];
  b->rdx = (uint64_t)g[REG_RDX];
  b->r8 = (uint64_t)g[REG_R8];
  b->r9 = (uint64_t)g[REG_R9];
  b->r10 = (uint64_t)g[REG_R10];
  b->r11 = (uint64_t)g[REG_R11];
  b->rcx = (uint64_t)g[REG_RCX];
  b->rdi = (uint64_t)g[REG_RDI];

  /* child scratch stack: 16-aligned top holding the boot pointer */
  uint64_t top = ((uint64_t)scratch + SHIM_CLONE_SCRATCH - 64) & ~15ULL;
  *(uint64_t *)(top - 8) = (uint64_t)b;

  /* tid bookkeeping is emulated with VIRTUAL ids (below + simulator
   * exit handling), so the kernel must not write real tids into the
   * app's words. CLEARTID is retargeted — not stripped — at the
   * channel's native_thread_alive guard, so the kernel itself reports
   * true thread death to the simulator (which must not wake joiners
   * before then: glibc reuses the joined thread's stack). */
  b->ch->native_thread_alive = 1;
  long nflags = (args[0] &
      ~(long)(CLONE_PARENT_SETTID | CLONE_CHILD_SETTID)) |
      CLONE_CHILD_CLEARTID;
  long r = shim_clone_raw(nflags, (long)(top - 8), args[2],
                          (long)&b->ch->native_thread_alive, args[4]);
  if (r < 0) {
    ShimMsg *fm = (ShimMsg *)&b->ch->msg_to_simulator;
    fm->kind = IPC_THREAD_FAIL;
    fm->number = r;
    sem_post(&b->ch->to_simulator.value);
    return r;
  }
  if ((args[0] & CLONE_PARENT_SETTID) && args[2])
    *(int *)args[2] = (int)vtid;
  if ((args[0] & CLONE_CHILD_SETTID) && args[3])
    *(int *)args[3] = (int)vtid;    /* shared VM: child sees it */
  return vtid;
}

/* rt_sigprocmask with SIGSYS stripped from block requests: if the app
 * (glibc blocks ALL signals around pthread_create's clone) could mask
 * SIGSYS, the next seccomp trap would be force-killed instead of
 * handled. Runs entirely shim-side — no simulator round trip.
 *
 * Subtlety: this executes INSIDE the SIGSYS handler, and the trap
 * frame's sigreturn will restore the PRE-trap mask afterwards —
 * silently undoing the app's request (e.g. siglongjmp's mask restore
 * out of a signal handler would leave SIGSEGV blocked forever and the
 * next TSC trap would force-kill). So the resulting mask is mirrored
 * into the trap frame's uc_sigmask: sigreturn then installs exactly
 * what the app asked for. */
static long shim_sigprocmask(const long a[6]) {
  const uint64_t *set = (const uint64_t *)a[1];
  long r;
  if (set && a[0] != 1 /* != SIG_UNBLOCK */ && a[3] == 8) {
    uint64_t copy = *set & ~(1ULL << (SIGSYS - 1));
    r = shim_rawsyscall(SYS_rt_sigprocmask, a[0], (long)&copy, a[2],
                        8, 0, 0);
  } else {
    r = shim_rawsyscall(SYS_rt_sigprocmask, a[0], a[1], a[2], a[3],
                        0, 0);
  }
  if (r == 0 && set && t_trap_ctx) {
    uint64_t cur = 0;
    if (shim_rawsyscall(SYS_rt_sigprocmask, 0 /* SIG_BLOCK */, 0,
                        (long)&cur, 8, 0, 0) == 0) {
      /* kernel sigsets are 8 bytes; uc_sigmask's first word is what
       * sigreturn installs */
      uint64_t *frame = (uint64_t *)&t_trap_ctx->uc_sigmask;
      *frame = cur & ~(1ULL << (SIGSYS - 1));
    }
  }
  return r;
}

#ifndef CLONE_VM
#define CLONE_VM 0x00000100
#endif

/* fork / vfork / fork-style clone: the simulator allocates the child's
 * virtual pid + IPC channel (IPC_CLONE_GO), the shim performs a real
 * COW fork, the child adopts the new channel and announces itself,
 * and the parent reports the real child pid (IPC_FORK_RESULT) so the
 * simulator can watch for its death. vfork degrades to fork semantics
 * (the child gets its own COW image — safe for the exec-or-exit
 * pattern and for everything else). */
static void shim_patch_env(const char *name, const char *value);

static long shim_handle_fork(const long args[6]) {
  ShimMsg *in = shim_roundtrip(SYS_fork, args);
  if (in->kind == IPC_SYSCALL_DONE)
    return (long)in->number; /* refused */
  if (in->kind != IPC_CLONE_GO)
    return -ENOSYS;
  ShimChannel *childch = (ShimChannel *)(g_arena_base + in->args[0]);

  long r = shim_rawsyscall(SYS_clone, SIGCHLD, 0, 0, 0, 0, 0);
  if (r == 0) {
    /* child: fresh single-threaded image; adopt the new channel (the
     * MAP_SHARED arena mapping survived the fork) */
    t_ch = childch;
    g_ch = childch;
    /* rebind the env so a later execve reconnects to OUR channel,
     * not the fork parent's (field zero-padded by the spawner) */
    char offbuf[24];
    unsigned long off = (unsigned long)((char *)childch - g_arena_base);
    int olen = snprintf(offbuf, sizeof offbuf, "%lu", off);
    if (olen > 0)
      shim_patch_env("SHADOWTPU_IPC_OFFSET", offbuf);
    ShimMsg *out = (ShimMsg *)&childch->msg_to_simulator;
    out->kind = IPC_THREAD_START;
    out->number = 0;
    sem_post(&childch->to_simulator.value);
    shim_wait_reply(childch); /* IPC_START: simulator scheduled us */
    return 0;
  }
  /* parent: report the real pid (or -errno) and collect the vpid */
  ShimChannel *ch = cur_ch();
  ShimMsg *out = (ShimMsg *)&ch->msg_to_simulator;
  out->kind = IPC_FORK_RESULT;
  out->number = r;
  sem_post(&ch->to_simulator.value);
  ShimMsg *rep = shim_wait_reply(ch);
  if (rep->kind == IPC_SYSCALL_DONE)
    return (long)rep->number;
  return -ENOSYS;
}

/* Overwrite the VALUE of environ entry `name=` in place (async-signal
 * safe: pure byte stores into this process's own env strings). The
 * spawner pads the value field so the new text always fits. */
static void shim_patch_env(const char *name, const char *value) {
  extern char **environ;
  size_t nlen = strlen(name);
  size_t vlen = strlen(value);
  for (char **e = environ; e && *e; e++) {
    if (strncmp(*e, name, nlen) == 0 && (*e)[nlen] == '=') {
      char *dst = *e + nlen + 1;
      size_t room = strlen(dst);
      if (vlen <= room) {
        /* right-align into the zero-padded field */
        memset(dst, '0', room - vlen);
        memcpy(dst + (room - vlen), value, vlen);
      }
      return;
    }
  }
}

/* execve: ask the simulator (it validates the target and tears down
 * sibling threads on success), flip SHADOWTPU_EXEC so the NEW image's
 * constructor announces itself, then run the real execve through the
 * trampoline. The stacked old seccomp filter keeps trapping — its
 * escape range is the FIXED trampoline page the new shim also uses.
 * On failure the flag flips back and the old image continues. */
static long shim_handle_execve(const long args[6]) {
  ShimMsg *in = shim_roundtrip(SYS_execve, args);
  if (in->kind == IPC_SYSCALL_DONE)
    return (long)in->number;        /* refused (bad path / bad envp) */
  if (in->kind != IPC_SYSCALL_NATIVE)
    return -ENOSYS;
  /* the simulator already flipped SHADOWTPU_EXEC to '1' in the
   * envp the app is passing (plugin-memory write, so it works even
   * for deep-copied env arrays). PR_SET_TSC survives execve but the
   * SIGSEGV handler does not: disarm it or the new image's early
   * rdtsc (glibc init) faults fatally; the new constructor re-arms. */
  prctl(PR_SET_TSC, PR_TSC_ENABLE, 0, 0, 0);
  long r = shim_rawsyscall(SYS_execve, args[0], args[1], args[2], 0, 0,
                           0);
  if (g_trace_traps)
    shim_logf("execve failed r=%ld", r);
  prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);
  shim_patch_env("SHADOWTPU_EXEC", "0");  /* exec failed: still here */
  return r;
}

static long shim_do_syscall(long nr, const long args[6]) {
  uint32_t fd0 = (uint32_t)args[0];
  if (is_fd_gated(nr) &&
      (fd0 < SHADOWTPU_VFD_BASE || fd0 >= SHADOWTPU_VFD_END))
    return shim_rawsyscall(nr, args[0], args[1], args[2], args[3],
                           args[4], args[5]);
  if (nr == SYS_clone) {
    if (!(args[0] & CLONE_VM))
      return shim_handle_fork(args);
    return shim_handle_clone(args);
  }
  if (nr == SYS_fork || nr == SYS_vfork)
    return shim_handle_fork(args);
  if (nr == SYS_rt_sigprocmask) {
    /* native change first (authoritative result, SIGSYS stripped,
     * trap frame mirrored), then inform the simulator so virtual
     * IPC_SIGNAL delivery honors the blocked set; the handler
     * answers DONE(0), never NATIVE (a raw re-execution here would
     * install the unstripped set) */
    long r = shim_sigprocmask(args);
    if (r == 0 && args[1] /* query-only calls change nothing */)
      (void)shim_emulated_syscall(nr, args);
    return r;
  }
  if (nr == SYS_execve)
    return shim_handle_execve(args);
  if (nr == SYS_wait4) {
    /* virtual wait; then reap any real zombie children so the
     * plugin's process table doesn't accumulate them */
    long r = shim_emulated_syscall(nr, args);
    int nst = 0;
    long rp;
    while ((rp = shim_rawsyscall(SYS_wait4, -1, (long)&nst,
                                 1 /* WNOHANG */, 0, 0, 0)) > 0) {
      if (g_trace_traps)
        shim_logf("reaped native pid=%ld status=0x%x", rp,
                  (unsigned)nst);
    }
    return r;
  }
  return shim_emulated_syscall(nr, args);
}

_Static_assert(__builtin_offsetof(CloneBoot, rsp) == 8, "boot abi");
_Static_assert(__builtin_offsetof(CloneBoot, rip) == 16, "boot abi");
_Static_assert(__builtin_offsetof(CloneBoot, rsi) == 72, "boot abi");
_Static_assert(__builtin_offsetof(CloneBoot, rcx) == 120, "boot abi");
_Static_assert(__builtin_offsetof(CloneBoot, rdi) == 128, "boot abi");

/* ---- SIGSYS handler ------------------------------------------------ */

static __thread volatile int g_in_handler
    __attribute__((tls_model("initial-exec"))) = 0;

static void sigsys_handler(int sig, siginfo_t *info, void *vctx) {
  (void)sig;
  ucontext_t *ctx = (ucontext_t *)vctx;
  greg_t *g = ctx->uc_mcontext.gregs;
  if (g_in_handler) {
    /* A syscall made by the shim itself was trapped: filter/config bug
     * (e.g. a stacked stale filter from a wrapper process). Without
     * this guard the kernel force-kills on the doubly-nested SIGSYS
     * with no diagnostics. Report once, then die via SIGKILL (kill is
     * never in the trap lists, so it passes any stacked filter). */
    if (g_in_handler == 1) {
      g_in_handler = 2;
      char buf[96];
      int n = snprintf(buf, sizeof buf,
                       "shadowtpu-shim: nested seccomp trap nr=%lld "
                       "ip=%llx\n", (long long)g[REG_RAX],
                       (unsigned long long)g[REG_RIP]);
      shim_rawsyscall(SYS_write, 2, (long)buf, n, 0, 0, 0);
    }
    long pid = shim_rawsyscall(SYS_getpid, 0, 0, 0, 0, 0, 0);
    shim_rawsyscall(SYS_kill, pid, 9 /* SIGKILL */, 0, 0, 0, 0);
    return;
  }
  if (info->si_code != SYS_SECCOMP)
    return;
  g_in_handler = 1;
  t_trap_ctx = ctx;
  long nr = (long)g[REG_RAX];
  long args[6] = {(long)g[REG_RDI], (long)g[REG_RSI], (long)g[REG_RDX],
                  (long)g[REG_R10], (long)g[REG_R8],  (long)g[REG_R9]};
  if (g_trace_traps)
    shim_logf("trap nr=%ld a0=%ld a1=%ld", nr, args[0], args[1]);
  long saved_errno = errno;
  g[REG_RAX] = shim_do_syscall(nr, args);
  errno = saved_errno;
  t_trap_ctx = NULL;
  g_in_handler = 0;
}

/* ---- seccomp filter ------------------------------------------------ */

/* Always-trapped syscalls: networking, readiness, time, sleep,
 * randomness, identity, lifecycle. */
static const int kTrapSyscalls[] = {
    SYS_socket,       SYS_connect,      SYS_accept,
    SYS_accept4,      SYS_bind,         SYS_listen,
    SYS_sendto,       SYS_recvfrom,     SYS_sendmsg,
    SYS_recvmsg,      SYS_sendmmsg,     SYS_recvmmsg,
    SYS_shutdown,     SYS_getsockname,  SYS_getpeername,
    SYS_getsockopt,   SYS_setsockopt,   SYS_socketpair,
    SYS_epoll_create, SYS_epoll_create1, SYS_epoll_ctl,
    SYS_epoll_wait,   SYS_epoll_pwait,  SYS_poll,
    SYS_ppoll,        SYS_select,       SYS_pselect6,
    /* NOT trapped: clock_gettime/gettimeofday/time/getpid/getrandom.
     * glibc init calls them BEFORE a post-execve image can install
     * its SIGSYS handler (a stale stacked filter would force-kill the
     * new image), and libc time reads go through the vDSO — no
     * syscall — so the filter never reliably caught them anyway. The
     * shim's SYMBOL overrides are the real interposition for these
     * (explicit IPC funnel); raw-syscall users of exactly these five
     * bypass virtualization (documented). */
    SYS_nanosleep,    SYS_clock_nanosleep,
    SYS_alarm,        SYS_setitimer,    SYS_getitimer,
    SYS_timerfd_create, SYS_timerfd_settime, SYS_timerfd_gettime,
    SYS_eventfd,      SYS_eventfd2,     SYS_pipe,
    SYS_pipe2,        SYS_uname,
    SYS_getppid,      SYS_exit,
    SYS_exit_group,   SYS_clone,        SYS_fork,
    SYS_vfork,        SYS_futex,        SYS_sysinfo,
    /* NOT trapped: set_tid_address — glibc calls it during startup,
     * BEFORE a post-execve image has installed its SIGSYS handler
     * (the stale filter would kill the new image). Thread CLEARTID
     * words are captured from clone flags instead; the ptrace
     * backend still sees it (every syscall stops there). */
    /* NOT trapped: open/openat — the dynamic loader of a POST-EXECVE
     * image issues them before its shim constructor can install a
     * SIGSYS handler, and the stale stacked filter would force-kill
     * the new image (same startup window as clock_gettime above).
     * The special paths the simulator must own (/dev/urandom, the
     * simulated /etc/hosts) are caught by the open/openat/fopen
     * SYMBOL overrides below via the explicit funnel instead. */
    SYS_getrusage,    SYS_times,       SYS_sched_getaffinity,
    SYS_sched_setaffinity, SYS_getcpu,
    SYS_gettid,       SYS_tgkill,
    SYS_rt_sigprocmask, SYS_wait4,      SYS_waitid,   SYS_kill,
    SYS_rt_sigaction, SYS_pause,       SYS_rt_sigpending,
    SYS_rt_sigtimedwait, SYS_rt_sigsuspend, SYS_tkill,
    SYS_execve,
#ifdef SYS_clone3
    SYS_clone3,       /* refused with ENOSYS: glibc falls back to clone */
#endif
    /* mknod(at) must emulate regardless of privilege: running the
     * simulator as root would otherwise let a plugin create REAL
     * device nodes natively where an unprivileged run gets EPERM —
     * a privilege-dependent divergence. Neither is issued in the
     * post-execve loader window, so unconditional trapping is safe. */
    SYS_mknod,        SYS_mknodat,
};

static const int kFdGatedSyscalls[] = {
    SYS_read,  SYS_write, SYS_readv,   SYS_writev,   SYS_close,
    SYS_fstat, SYS_lseek, SYS_ioctl,   SYS_fcntl,    SYS_dup,
    SYS_dup2,  SYS_dup3,  SYS_pread64, SYS_pwrite64, SYS_newfstatat,
    SYS_statx, SYS_sendfile,
    /* fd-mediated file family: these reach the handler only when the
     * fd (or dirfd, arg0) is one of OUR virtual descriptors — native
     * fds keep full-speed kernel execution, and the post-execve
     * loader window never holds a VFD so the stale-filter hazard of
     * trapping unconditionally does not apply. */
    SYS_getdents,  SYS_getdents64, SYS_ftruncate, SYS_fsync,
    SYS_fdatasync, SYS_fallocate,  SYS_flock,     SYS_fchmod,
    SYS_fchown,    SYS_fgetxattr,  SYS_fsetxattr, SYS_flistxattr,
    SYS_fremovexattr, SYS_fchdir,  SYS_fstatfs,
    SYS_preadv,    SYS_pwritev,
#ifdef SYS_preadv2
    SYS_preadv2,   SYS_pwritev2,
#endif
    /* advisory I/O: native fds keep full-speed kernel advice (the
     * kernel contract is "may be ignored", so native behavior equals
     * the emulated deterministic success); VFD-backed fds funnel. */
    SYS_fadvise64, SYS_readahead, SYS_sync_file_range, SYS_syncfs,
    /* dirfd(arg0)-relative path family (ref fileat.c): */
    SYS_unlinkat,  SYS_mkdirat,    SYS_readlinkat, SYS_faccessat,
#ifdef SYS_faccessat2
    SYS_faccessat2,
#endif
    SYS_fchmodat,  SYS_fchownat,   SYS_utimensat,  SYS_futimesat,
};

/* renameat/renameat2/linkat carry a SECOND dirfd in arg2 (and
 * symlinkat's only dirfd is arg1): gated on those args separately.
 * None are issued by the post-execve loader window. */
static const int kFd2GatedSyscalls[] = {
    SYS_renameat, SYS_renameat2, SYS_linkat,
};

enum { TGT_NONE = 0, TGT_ALLOW, TGT_TRAP, TGT_KILL, TGT_NRCHK,
       TGT_FDGATE, TGT_FD2GATE, TGT_FD2ARG2, TGT_SYMGATE,
       TGT_MMAPGATE };

typedef struct {
  struct sock_filter f;
  int jt_tgt, jf_tgt; /* symbolic jump targets (TGT_*) */
} Ins;

#define MAX_INS 224

static int shim_install_seccomp(void) {
  Ins prog[MAX_INS];
  int n = 0;
  uint64_t lo = (uint64_t)g_escape_lo;
  uint64_t hi = (uint64_t)g_escape_hi;
  if ((lo >> 32) != (hi >> 32))
    return -1; /* 4 GiB-straddling mapping: cannot express the range */

#define EMIT(code_, k_, jt_, jf_)                                       \
  do {                                                                  \
    if (n >= MAX_INS)                                                   \
      return -1;                                                        \
    prog[n].f.code = (code_);                                           \
    prog[n].f.k = (k_);                                                 \
    prog[n].f.jt = 0;                                                   \
    prog[n].f.jf = 0;                                                   \
    prog[n].jt_tgt = (jt_);                                             \
    prog[n].jf_tgt = (jf_);                                             \
    n++;                                                                \
  } while (0)

  /* arch check */
  EMIT(BPF_LD | BPF_W | BPF_ABS, 4, 0, 0);
  EMIT(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, TGT_NONE, TGT_KILL);
  /* instruction-pointer escape: allow the shim's own syscall insn.
   * seccomp reports the ip *after* the syscall instruction, so the
   * allowed range is (start, end]. */
  EMIT(BPF_LD | BPF_W | BPF_ABS, 12, 0, 0); /* ip high dword */
  EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)(lo >> 32), TGT_NONE,
       TGT_NRCHK);
  EMIT(BPF_LD | BPF_W | BPF_ABS, 8, 0, 0); /* ip low dword */
  EMIT(BPF_JMP | BPF_JGT | BPF_K, (uint32_t)lo, TGT_NONE, TGT_NRCHK);
  EMIT(BPF_JMP | BPF_JGT | BPF_K, (uint32_t)hi, TGT_NRCHK, TGT_ALLOW);

  int nrchk_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 0, 0, 0); /* syscall nr */
  for (size_t i = 0; i < sizeof(kTrapSyscalls) / sizeof(int); i++)
    EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)kTrapSyscalls[i], TGT_TRAP,
         TGT_NONE);
  /* SHADOWTPU_STRICT_TRAPS=1: also trap the startup-window syscalls
   * (clock_gettime/gettimeofday/time/getpid/getrandom/set_tid_address
   * + open/openat) so raw-syscall users of time/randomness fail into
   * the funnel instead of silently reading native values. ONLY for
   * workloads that never execve — a post-execve image dies in the
   * loader window under this filter (documented trade). */
  const char *strict = getenv("SHADOWTPU_STRICT_TRAPS");
  if (strict && strict[0] == '1') {
    static const int kStrict[] = {
        SYS_clock_gettime, SYS_gettimeofday, SYS_time,   SYS_getpid,
        SYS_getrandom,     SYS_set_tid_address, SYS_open, SYS_openat,
    };
    for (size_t i = 0; i < sizeof(kStrict) / sizeof(int); i++)
      EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)kStrict[i], TGT_TRAP,
           TGT_NONE);
  }
  for (size_t i = 0; i < sizeof(kFdGatedSyscalls) / sizeof(int); i++)
    EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)kFdGatedSyscalls[i],
         TGT_FDGATE, TGT_NONE);
  for (size_t i = 0; i < sizeof(kFd2GatedSyscalls) / sizeof(int); i++)
    EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)kFd2GatedSyscalls[i],
         TGT_FD2GATE, TGT_NONE);
  EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)SYS_symlinkat,
       TGT_SYMGATE, TGT_NONE);
  EMIT(BPF_JMP | BPF_JEQ | BPF_K, (uint32_t)SYS_mmap, TGT_MMAPGATE,
       TGT_NONE);
  EMIT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW, 0, 0);

  int fdgate_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 16, 0, 0); /* args[0] low dword */
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_BASE, TGT_NONE,
       TGT_ALLOW);
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_END, TGT_ALLOW,
       TGT_TRAP);

  /* renameat/renameat2/linkat: trap when EITHER dirfd (arg0/arg2) is
   * virtual */
  int fd2gate_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 16, 0, 0); /* args[0] low dword */
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_BASE, TGT_NONE,
       TGT_FD2ARG2);
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_END, TGT_FD2ARG2,
       TGT_TRAP);
  int fd2gate_arg2_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 32, 0, 0); /* args[2] low dword */
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_BASE, TGT_NONE,
       TGT_ALLOW);
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_END, TGT_ALLOW,
       TGT_TRAP);

  /* symlinkat: the only dirfd is arg1 */
  int symgate_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 24, 0, 0); /* args[1] low dword */
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_BASE, TGT_NONE,
       TGT_ALLOW);
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_END, TGT_ALLOW,
       TGT_TRAP);

  /* mmap: fd lives in arg4; anonymous mappings (arg3 & MAP_ANONYMOUS)
   * never reference it and stay native (the post-execve loader's
   * file mmaps use native fds, so they pass the range check) */
  int mmapgate_idx = n;
  EMIT(BPF_LD | BPF_W | BPF_ABS, 40, 0, 0); /* args[3] low dword */
  EMIT(BPF_JMP | BPF_JSET | BPF_K, 0x20 /* MAP_ANONYMOUS */,
       TGT_ALLOW, TGT_NONE);
  EMIT(BPF_LD | BPF_W | BPF_ABS, 48, 0, 0); /* args[4] low dword */
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_BASE, TGT_NONE,
       TGT_ALLOW);
  EMIT(BPF_JMP | BPF_JGE | BPF_K, SHADOWTPU_VFD_END, TGT_ALLOW,
       TGT_TRAP);

  int trap_idx = n;
  EMIT(BPF_RET | BPF_K, SECCOMP_RET_TRAP, 0, 0);
  int allow_idx = n;
  EMIT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW, 0, 0);
  int kill_idx = n;
  EMIT(BPF_RET | BPF_K, SECCOMP_RET_KILL, 0, 0);
#undef EMIT

  /* resolve symbolic jumps */
  struct sock_filter out[MAX_INS];
  for (int i = 0; i < n; i++) {
    out[i] = prog[i].f;
    int tgts[2] = {prog[i].jt_tgt, prog[i].jf_tgt};
    uint8_t *slots[2] = {&out[i].jt, &out[i].jf};
    for (int s = 0; s < 2; s++) {
      int idx;
      switch (tgts[s]) {
      case TGT_NONE:
        continue;
      case TGT_ALLOW:
        idx = allow_idx;
        break;
      case TGT_TRAP:
        idx = trap_idx;
        break;
      case TGT_KILL:
        idx = kill_idx;
        break;
      case TGT_NRCHK:
        idx = nrchk_idx;
        break;
      case TGT_FDGATE:
        idx = fdgate_idx;
        break;
      case TGT_FD2GATE:
        idx = fd2gate_idx;
        break;
      case TGT_FD2ARG2:
        idx = fd2gate_arg2_idx;
        break;
      case TGT_SYMGATE:
        idx = symgate_idx;
        break;
      case TGT_MMAPGATE:
        idx = mmapgate_idx;
        break;
      default:
        return -1;
      }
      int delta = idx - (i + 1);
      if (delta < 0 || delta > 255)
        return -1;
      *slots[s] = (uint8_t)delta;
    }
  }

  struct sock_fprog fprog = {.len = (unsigned short)n, .filter = out};
  /* raw on purpose: the prctl SYMBOL below funnels once g_enabled */
  if (shim_rawsyscall(SYS_prctl, PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0, 0)
      != 0)
    return -1;
  if (syscall(SYS_seccomp, SECCOMP_SET_MODE_FILTER, 0, &fprog) != 0)
    return -1;
  return 0;
}

/* ---- libc overrides (preload_libraries.c analogue) ----------------- */
/* These catch calls glibc would otherwise satisfy from the vDSO
 * without entering the kernel (so seccomp never sees them). They
 * funnel into the same emulation path. */

static long shim_time_syscall(long nr, long a, long b, long c, long d) {
  long args[6] = {a, b, c, d, 0, 0};
  if (!g_enabled)
    return shim_rawsyscall(nr, a, b, c, d, 0, 0);
  return shim_emulated_syscall(nr, args);
}

static int ret_errno(long r) {
  if (r < 0) {
    errno = (int)-r;
    return -1;
  }
  return (int)r;
}

int clock_gettime(clockid_t clk, struct timespec *ts) {
  return ret_errno(
      shim_time_syscall(SYS_clock_gettime, clk, (long)ts, 0, 0));
}

int gettimeofday(struct timeval *restrict tv,
                 void *restrict tz) {
  return ret_errno(
      shim_time_syscall(SYS_gettimeofday, (long)tv, (long)tz, 0, 0));
}

time_t time(time_t *tloc) {
  long r = shim_time_syscall(SYS_time, (long)tloc, 0, 0, 0);
  if (r < 0) {
    errno = (int)-r;
    return (time_t)-1;
  }
  return (time_t)r;
}

int nanosleep(const struct timespec *req, struct timespec *rem) {
  return ret_errno(
      shim_time_syscall(SYS_nanosleep, (long)req, (long)rem, 0, 0));
}

int usleep(useconds_t usec) {
  struct timespec req = {usec / 1000000u, (long)(usec % 1000000u) * 1000};
  return nanosleep(&req, NULL);
}

unsigned int sleep(unsigned int seconds) {
  struct timespec req = {seconds, 0};
  return nanosleep(&req, NULL) == 0 ? 0 : seconds;
}

pid_t getpid(void) {
  /* virtual pid via the explicit funnel (the raw syscall is allowed
   * natively for the post-execve startup window; see kTrapSyscalls) */
  return (pid_t)shim_time_syscall(SYS_getpid, 0, 0, 0, 0);
}

ssize_t getrandom(void *buf, size_t buflen, unsigned int flags) {
  long r = shim_time_syscall(SYS_getrandom, (long)buf, (long)buflen,
                             (long)flags, 0);
  if (r < 0) {
    errno = (int)-r;
    return -1;
  }
  return (ssize_t)r;
}

/* ---- cwd tracking --------------------------------------------------
 * Every data-dir open now funnels and resolves RELATIVE paths against
 * the handler's tracked cwd, so chdir/fchdir must funnel too or the
 * tracked cwd goes stale (the handler replies NATIVE for chdir, so
 * the REAL cwd still moves below). Symbol-level: seccomp cannot trap
 * chdir (glibc startup hazard class) and fchdir's native-fd case
 * never hits the fd gate. */
int chdir(const char *path) {
  if (g_enabled) {
    long args[6] = {(long)path, 0, 0, 0, 0, 0};
    return ret_errno(shim_emulated_syscall(SYS_chdir, args));
  }
  return ret_errno(shim_rawsyscall(SYS_chdir, (long)path, 0, 0, 0, 0,
                                   0));
}

int fchdir(int fd) {
  if (g_enabled) {
    long args[6] = {fd, 0, 0, 0, 0, 0};
    return ret_errno(shim_emulated_syscall(SYS_fchdir, args));
  }
  return ret_errno(shim_rawsyscall(SYS_fchdir, fd, 0, 0, 0, 0, 0));
}

/* ---- resource limits + prctl ---------------------------------------
 * glibc STARTUP raw-calls prlimit64 (RLIMIT_STACK probe), so seccomp
 * cannot trap these without killing post-execve images in the loader
 * window — symbol-level funnels instead, like getpid/getrandom. The
 * handler serves DETERMINISTIC limits (the real machine's must never
 * steer plugin decisions); raw-syscall users bypass (documented). */
struct rlimit;
int getrlimit(int res, struct rlimit *rl) {
  return ret_errno(shim_time_syscall(SYS_getrlimit, res, (long)rl, 0,
                                     0));
}

int setrlimit(int res, const struct rlimit *rl) {
  return ret_errno(shim_time_syscall(SYS_setrlimit, res, (long)rl, 0,
                                     0));
}

int prlimit(pid_t pid, int res, const struct rlimit *nl,
            struct rlimit *ol) {
  long args[6] = {pid, res, (long)nl, (long)ol, 0, 0};
  if (!g_enabled)
    return ret_errno(shim_rawsyscall(SYS_prlimit64, pid, res,
                                     (long)nl, (long)ol, 0, 0));
  return ret_errno(shim_emulated_syscall(SYS_prlimit64, args));
}

int prlimit64(pid_t pid, int res, const struct rlimit *nl,
              struct rlimit *ol) {
  return prlimit(pid, res, nl, ol);
}

int prctl(int option, ...) {
  va_list ap;
  va_start(ap, option);
  long a1 = va_arg(ap, long), a2 = va_arg(ap, long);
  long a3 = va_arg(ap, long), a4 = va_arg(ap, long);
  va_end(ap);
  if (!g_enabled)
    return ret_errno(shim_rawsyscall(SYS_prctl, option, a1, a2, a3,
                                     a4, 0));
  /* a NATIVE reply (anything but PDEATHSIG/NAME) re-executes raw */
  long args[6] = {option, a1, a2, a3, a4, 0};
  return ret_errno(shim_emulated_syscall(SYS_prctl, args));
}

/* ---- special-path file opens --------------------------------------- */
/* The simulator owns these files' CONTENT: the RNG devices must serve
 * the host's seeded deterministic stream (native reads are real
 * randomness), and /etc/hosts must be the SIMULATED name map. Routed
 * through the explicit funnel at the SYMBOL level — trapping
 * open/openat in seccomp would kill post-execve images in the loader
 * startup window (see kTrapSyscalls). Raw-syscall opens of exactly
 * these paths bypass virtualization (documented, like raw
 * clock_gettime). */
static int shim_special_path(const char *p) {
  if (!p)
    return 0;
  return strcmp(p, "/dev/urandom") == 0 ||
         strcmp(p, "/dev/random") == 0 || strcmp(p, "/etc/hosts") == 0 ||
         strcmp(p, "/etc/resolv.conf") == 0 ||
         strcmp(p, "/etc/nsswitch.conf") == 0;
}

int fstatat(int dirfd, const char *path, struct stat *st, int flags);

static int shim_statat_impl(const char *path, void *st, int flags) {
  /* stat of a special path must agree with what open() serves (the
   * real file's size/mtime would leak machine state) */
  long args[6] = {AT_FDCWD, (long)path, (long)st, flags, 0, 0};
  if (g_enabled && shim_special_path(path))
    return ret_errno(shim_emulated_syscall(SYS_newfstatat, args));
  return ret_errno(shim_rawsyscall(SYS_newfstatat, AT_FDCWD,
                                   (long)path, (long)st, flags, 0, 0));
}

int stat(const char *path, struct stat *st) {
  return shim_statat_impl(path, st, 0);
}

int stat64(const char *path, struct stat64 *st) {
  return shim_statat_impl(path, st, 0);
}

int lstat(const char *path, struct stat *st) {
  /* the special paths are not symlinks, but the general fallback
   * must keep lstat semantics */
  return shim_statat_impl(path, st, AT_SYMLINK_NOFOLLOW);
}

int lstat64(const char *path, struct stat64 *st) {
  return shim_statat_impl(path, st, AT_SYMLINK_NOFOLLOW);
}

/* pre-glibc-2.33 binaries call the __xstat family */
int __xstat(int ver, const char *path, struct stat *st) {
  (void)ver;
  return shim_statat_impl(path, st, 0);
}

int __lxstat(int ver, const char *path, struct stat *st) {
  (void)ver;
  return shim_statat_impl(path, st, AT_SYMLINK_NOFOLLOW);
}

int __xstat64(int ver, const char *path, struct stat64 *st) {
  (void)ver;
  return shim_statat_impl(path, st, 0);
}

int __lxstat64(int ver, const char *path, struct stat64 *st) {
  (void)ver;
  return shim_statat_impl(path, st, AT_SYMLINK_NOFOLLOW);
}

int __fxstatat(int ver, int dirfd, const char *path, struct stat *st,
               int flags) {
  (void)ver;
  return fstatat(dirfd, path, st, flags);
}

int __fxstatat64(int ver, int dirfd, const char *path,
                 struct stat64 *st, int flags) {
  (void)ver;
  return fstatat(dirfd, path, (struct stat *)st, flags);
}

static int shim_is_vfd(int fd) {
  return fd >= (int)SHADOWTPU_VFD_BASE && fd < (int)SHADOWTPU_VFD_END;
}

int fstatat(int dirfd, const char *path, struct stat *st, int flags) {
  if (g_enabled && shim_special_path(path)) {
    long args[6] = {AT_FDCWD, (long)path, (long)st, flags, 0, 0};
    return ret_errno(shim_emulated_syscall(SYS_newfstatat, args));
  }
  if (g_enabled && shim_is_vfd(dirfd)) {
    /* dirfd-relative stat against an EMULATED directory: the raw
     * escape below would hand the kernel a fd it has never seen */
    long args[6] = {dirfd, (long)path, (long)st, flags, 0, 0};
    return ret_errno(shim_emulated_syscall(SYS_newfstatat, args));
  }
  return ret_errno(shim_rawsyscall(SYS_newfstatat, dirfd, (long)path,
                                   (long)st, flags, 0, 0));
}

int fstatat64(int dirfd, const char *path, struct stat64 *st,
              int flags) {
  return fstatat(dirfd, path, (struct stat *)st, flags);
}

struct statx;
int statx(int dirfd, const char *path, int flags, unsigned int mask,
          struct statx *stxbuf) {
  if (g_enabled && shim_special_path(path)) {
    long args[6] = {AT_FDCWD, (long)path, flags, (long)mask,
                    (long)stxbuf, 0};
    return ret_errno(shim_emulated_syscall(SYS_statx, args));
  }
  if (g_enabled && shim_is_vfd(dirfd)) {
    long args[6] = {dirfd, (long)path, flags, (long)mask,
                    (long)stxbuf, 0};
    return ret_errno(shim_emulated_syscall(SYS_statx, args));
  }
  return ret_errno(shim_rawsyscall(SYS_statx, dirfd, (long)path, flags,
                                   (long)mask, (long)stxbuf, 0));
}

static int shim_openat_impl(int dirfd, const char *path, int flags,
                            mode_t mode) {
  /* EVERY open funnels (symbol-level interposition has no
   * post-execve loader-window hazard): the handler emulates special
   * paths and data-dir files through its descriptor table (os-backed
   * HostFileDesc — dirfd resolution, deterministic sorted getdents)
   * and answers NATIVE for system paths, which re-execute raw below.
   * Raw-syscall openat of a data path bypasses mediation (documented,
   * like raw clock_gettime; strict-traps mode catches it). */
  if (g_enabled) {
    long args[6] = {dirfd, (long)path, flags, (long)mode, 0, 0};
    /* a NATIVE reply re-executes raw inside shim_emulated_syscall */
    return ret_errno(shim_emulated_syscall(SYS_openat, args));
  }
  return ret_errno(shim_rawsyscall(SYS_openat, dirfd, (long)path,
                                   flags, (long)mode, 0, 0));
}

int open(const char *path, int flags, ...) {
  mode_t mode = 0;
  if ((flags & O_CREAT) || (flags & O_TMPFILE) == O_TMPFILE) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  return shim_openat_impl(AT_FDCWD, path, flags, mode);
}

int open64(const char *path, int flags, ...) {
  mode_t mode = 0;
  if ((flags & O_CREAT) || (flags & O_TMPFILE) == O_TMPFILE) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  return shim_openat_impl(AT_FDCWD, path, flags, mode);
}

int openat(int dirfd, const char *path, int flags, ...) {
  mode_t mode = 0;
  if ((flags & O_CREAT) || (flags & O_TMPFILE) == O_TMPFILE) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  return shim_openat_impl(dirfd, path, flags, mode);
}

int openat64(int dirfd, const char *path, int flags, ...) {
  mode_t mode = 0;
  if ((flags & O_CREAT) || (flags & O_TMPFILE) == O_TMPFILE) {
    va_list ap;
    va_start(ap, flags);
    mode = va_arg(ap, mode_t);
    va_end(ap);
  }
  return shim_openat_impl(dirfd, path, flags, mode);
}

/* fopen reaches the kernel via glibc-internal open (no PLT), which
 * would bypass the funnel — so EVERY fopen is caught at the stream
 * level: the funnel-opened fd (emulated VFD for special/data-dir
 * paths, raw native fd otherwise) is re-wrapped with fdopen, and the
 * fd-gated seccomp filter serves the stream's read/write/fstat/seek
 * on virtual fds. */
static int shim_fopen_flags(const char *mode) {
  int flags;
  switch (mode[0]) {
  case 'r':
    flags = O_RDONLY;
    break;
  case 'w':
    flags = O_WRONLY | O_CREAT | O_TRUNC;
    break;
  case 'a':
    flags = O_WRONLY | O_CREAT | O_APPEND;
    break;
  default:
    return -1;
  }
  for (const char *m = mode + 1; *m; m++) {
    if (*m == '+')
      flags = (flags & ~(O_RDONLY | O_WRONLY)) | O_RDWR;
    else if (*m == 'e')
      flags |= O_CLOEXEC;
    else if (*m == 'x')
      flags |= O_EXCL;
  }
  return flags;
}

static FILE *shim_fopen_impl(const char *path, const char *mode) {
  int flags = shim_fopen_flags(mode);
  if (flags < 0) {
    errno = EINVAL;
    return NULL;
  }
  int fd = shim_openat_impl(AT_FDCWD, path, flags, 0666);
  if (fd < 0)
    return NULL;
  FILE *f = fdopen(fd, mode);
  if (!f)
    close(fd);
  return f;
}

FILE *fopen(const char *path, const char *mode) {
  if (g_enabled)
    return shim_fopen_impl(path, mode);
  static FILE *(*real_fopen)(const char *, const char *);
  if (!real_fopen)
    real_fopen =
        (FILE * (*)(const char *, const char *))(uintptr_t)
            dlsym(RTLD_NEXT, "fopen");
  return real_fopen ? real_fopen(path, mode) : NULL;
}

FILE *fopen64(const char *path, const char *mode) {
  if (g_enabled)
    return shim_fopen_impl(path, mode);
  static FILE *(*real_fopen64)(const char *, const char *);
  if (!real_fopen64)
    real_fopen64 =
        (FILE * (*)(const char *, const char *))(uintptr_t)
            dlsym(RTLD_NEXT, "fopen64");
  return real_fopen64 ? real_fopen64(path, mode) : NULL;
}

/* ---- name resolution (preload_libraries.c:30-120 analogue) --------- */
/* Managed processes resolve simulated hostnames from the simulator's
 * hosts file (dns.c's /etc/hosts emission) without ever touching the
 * real resolver: getaddrinfo/getifaddrs/gethostname are overridden
 * here. File IO below runs natively (the BPF filter only gates
 * virtual-range fds), and none of this runs in signal context. */

static char g_hostname[256];
static char g_hosts_path[512];
static uint32_t g_host_ip_net; /* network byte order; 0 = unknown */

static int shim_parse_ip(const char *s, uint32_t *out_net) {
  /* dotted-quad parser (avoids pulling inet_pton into the shim) */
  uint32_t parts[4];
  int i = 0;
  const char *p = s;
  for (i = 0; i < 4; i++) {
    if (*p < '0' || *p > '9')
      return 0;
    uint32_t v = 0;
    while (*p >= '0' && *p <= '9') {
      v = v * 10 + (uint32_t)(*p - '0');
      if (v > 255)
        return 0;
      p++;
    }
    parts[i] = v;
    if (i < 3) {
      if (*p != '.')
        return 0;
      p++;
    }
  }
  if (*p != '\0')
    return 0;
  *out_net = (uint32_t)((parts[0]) | (parts[1] << 8) | (parts[2] << 16) |
                        (parts[3] << 24));
  return 1;
}

/* The hosts file is immutable for the run: parse it ONCE into a
 * table so lookups at 10k-host scale cost no repeated IO. */
typedef struct {
  char name[64];
  uint32_t ip_net;
} HostEntry;
static HostEntry *g_hosts_tab = NULL;
static size_t g_hosts_n = 0;
static int g_hosts_loaded = 0;

static void shim_load_hosts(void) {
  if (g_hosts_loaded)
    return;
  g_hosts_loaded = 1;
  if (!g_hosts_path[0])
    return;
  FILE *f = fopen(g_hosts_path, "r");
  if (!f)
    return;
  size_t cap = 0;
  char line[512];
  while (fgets(line, sizeof line, f)) {
    char *save = NULL;
    char *ip_tok = strtok_r(line, " \t\r\n", &save);
    if (!ip_tok || ip_tok[0] == '#')
      continue;
    uint32_t ip;
    if (!shim_parse_ip(ip_tok, &ip))
      continue;
    char *tok;
    while ((tok = strtok_r(NULL, " \t\r\n", &save)) != NULL) {
      if (g_hosts_n == cap) {
        cap = cap ? cap * 2 : 64;
        HostEntry *nt = realloc(g_hosts_tab, cap * sizeof *nt);
        if (!nt)
          goto out;
        g_hosts_tab = nt;
      }
      snprintf(g_hosts_tab[g_hosts_n].name,
               sizeof g_hosts_tab[g_hosts_n].name, "%s", tok);
      g_hosts_tab[g_hosts_n].ip_net = ip;
      g_hosts_n++;
    }
  }
out:
  fclose(f);
}

static int shim_lookup_hosts(const char *name, uint32_t *out_net) {
  if (g_hostname[0] && g_host_ip_net && strcmp(name, g_hostname) == 0) {
    *out_net = g_host_ip_net;
    return 1;
  }
  shim_load_hosts();
  for (size_t i = 0; i < g_hosts_n; i++) {
    if (strcmp(g_hosts_tab[i].name, name) == 0) {
      *out_net = g_hosts_tab[i].ip_net;
      return 1;
    }
  }
  return 0;
}

static const char *shim_reverse_hosts(uint32_t ip_net) {
  if (g_host_ip_net && ip_net == g_host_ip_net && g_hostname[0])
    return g_hostname;
  shim_load_hosts();
  for (size_t i = 0; i < g_hosts_n; i++)
    if (g_hosts_tab[i].ip_net == ip_net)
      return g_hosts_tab[i].name;
  return NULL;
}

/* dlsym(RTLD_NEXT) fallbacks: when the shim is dormant/disabled the
 * overrides defer to the real libc so a plain process stays usable. */
#include <dlfcn.h>
#define SHIM_REAL(name) \
  (__typeof__(&name))(uintptr_t)dlsym(RTLD_NEXT, #name)

struct shim_addrinfo_blk {
  struct addrinfo ai;
  struct sockaddr_in sa;
  char canon[256];
};

static struct addrinfo *shim_make_ai(uint32_t ip_net, uint16_t port,
                                     int socktype, int protocol,
                                     int flags, const char *canon) {
  struct shim_addrinfo_blk *b = calloc(1, sizeof *b);
  if (!b)
    return NULL;
  b->sa.sin_family = AF_INET;
  b->sa.sin_port = htons(port);
  b->sa.sin_addr.s_addr = ip_net;
  b->ai.ai_family = AF_INET;
  b->ai.ai_socktype = socktype;
  b->ai.ai_protocol = protocol;
  b->ai.ai_addrlen = sizeof(struct sockaddr_in);
  b->ai.ai_addr = (struct sockaddr *)&b->sa;
  if ((flags & AI_CANONNAME) && canon) {
    snprintf(b->canon, sizeof b->canon, "%s", canon);
    b->ai.ai_canonname = b->canon;
  }
  return &b->ai;
}

int getaddrinfo(const char *node, const char *service,
                const struct addrinfo *hints, struct addrinfo **res) {
  if (!g_enabled) {
    int (*real)(const char *, const char *, const struct addrinfo *,
                struct addrinfo **) = SHIM_REAL(getaddrinfo);
    return real ? real(node, service, hints, res) : EAI_FAIL;
  }
  if (!res)
    return EAI_FAIL;
  int flags = hints ? hints->ai_flags : 0;
  int family = hints ? hints->ai_family : AF_UNSPEC;
  int socktype = hints ? hints->ai_socktype : 0;
  if (family != AF_UNSPEC && family != AF_INET)
    return EAI_FAMILY; /* the simulated internet is IPv4 */

  uint32_t ip_net = 0;
  if (node == NULL) {
    ip_net = (flags & AI_PASSIVE) ? 0u /* INADDR_ANY */
                                  : htonl(0x7F000001u /* loopback */);
  } else if (shim_parse_ip(node, &ip_net)) {
    /* numeric */
  } else if (flags & AI_NUMERICHOST) {
    return EAI_NONAME;
  } else if (!shim_lookup_hosts(node, &ip_net)) {
    return EAI_NONAME;
  }
  uint16_t port = 0;
  if (service) {
    /* numeric services only (no in-sim /etc/services); port 0 is
     * valid (bind-ephemeral idiom) */
    char *end = NULL;
    long p = strtol(service, &end, 10);
    if (end == service || *end != '\0' || p < 0 || p > 65535)
      return EAI_SERVICE;
    port = (uint16_t)p;
  }

  struct addrinfo *head = NULL, **tail = &head;
  const int types[2][2] = {{SOCK_STREAM, IPPROTO_TCP},
                           {SOCK_DGRAM, IPPROTO_UDP}};
  for (int i = 0; i < 2; i++) {
    if (socktype && socktype != types[i][0])
      continue;
    struct addrinfo *ai = shim_make_ai(ip_net, port, types[i][0],
                                       types[i][1], flags, node);
    if (!ai) {
      freeaddrinfo(head);
      return EAI_MEMORY;
    }
    *tail = ai;
    tail = &ai->ai_next;
  }
  if (!head)
    return EAI_SOCKTYPE;
  *res = head;
  return 0;
}

void freeaddrinfo(struct addrinfo *res) {
  if (!g_enabled) {
    void (*real)(struct addrinfo *) = SHIM_REAL(freeaddrinfo);
    if (real) {
      real(res);
      return;
    }
  }
  /* when enabled, every addrinfo came from the override above */
  while (res) {
    struct addrinfo *next = res->ai_next;
    free(res);
    res = next;
  }
}

int gethostname(char *name, size_t len) {
  const char *src = g_hostname;
  struct utsname u;
  if (!g_enabled || !g_hostname[0]) {
    /* fall back to the (emulated, when live) uname nodename */
    if (uname(&u) != 0)
      return -1;
    src = u.nodename;
  }
  size_t need = strlen(src);
  if (len <= need) {
    errno = ENAMETOOLONG;
    return -1;
  }
  memcpy(name, src, need + 1);
  return 0;
}

/* legacy resolver APIs: without these, gethostbyname would leak to the
 * real NSS stack (wrong /etc/hosts, nondeterministic DNS attempts into
 * the simulated network) */
static struct hostent g_he;
static char g_he_name[64];
static char *g_he_aliases[1];
static uint32_t g_he_ip;
static char *g_he_addr_list[2];

struct hostent *gethostbyname(const char *name) {
  if (!g_enabled) {
    struct hostent *(*real)(const char *) = SHIM_REAL(gethostbyname);
    return real ? real(name) : NULL;
  }
  uint32_t ip_net;
  if (!shim_parse_ip(name, &ip_net) &&
      !shim_lookup_hosts(name, &ip_net)) {
    h_errno = HOST_NOT_FOUND;
    return NULL;
  }
  snprintf(g_he_name, sizeof g_he_name, "%s", name);
  g_he_ip = ip_net;
  g_he_aliases[0] = NULL;
  g_he_addr_list[0] = (char *)&g_he_ip;
  g_he_addr_list[1] = NULL;
  g_he.h_name = g_he_name;
  g_he.h_aliases = g_he_aliases;
  g_he.h_addrtype = AF_INET;
  g_he.h_length = 4;
  g_he.h_addr_list = g_he_addr_list;
  return &g_he;
}

int getnameinfo(const struct sockaddr *addr, socklen_t addrlen,
                char *host, socklen_t hostlen, char *serv,
                socklen_t servlen, int flags) {
  if (!g_enabled) {
    int (*real)(const struct sockaddr *, socklen_t, char *, socklen_t,
                char *, socklen_t, int) = SHIM_REAL(getnameinfo);
    return real ? real(addr, addrlen, host, hostlen, serv, servlen,
                       flags)
                : EAI_FAIL;
  }
  if (!addr || addrlen < (socklen_t)sizeof(struct sockaddr_in) ||
      addr->sa_family != AF_INET)
    return EAI_FAMILY;
  const struct sockaddr_in *sa = (const struct sockaddr_in *)addr;
  if (host && hostlen) {
    const char *name = (flags & NI_NUMERICHOST)
                           ? NULL
                           : shim_reverse_hosts(sa->sin_addr.s_addr);
    if (name) {
      snprintf(host, hostlen, "%s", name);
    } else if (flags & NI_NAMEREQD) {
      return EAI_NONAME;
    } else {
      uint32_t ip = ntohl(sa->sin_addr.s_addr);
      snprintf(host, hostlen, "%u.%u.%u.%u", (ip >> 24) & 255,
               (ip >> 16) & 255, (ip >> 8) & 255, ip & 255);
    }
  }
  if (serv && servlen)
    snprintf(serv, servlen, "%u", (unsigned)ntohs(sa->sin_port));
  return 0;
}

struct shim_ifaddrs_blk {
  struct ifaddrs ifa;
  struct sockaddr_in addr, mask, brd;
  char name[16];
};

static struct ifaddrs *shim_make_ifa(const char *name, uint32_t ip_net,
                                     uint32_t mask_net,
                                     unsigned int extra_flags) {
  struct shim_ifaddrs_blk *b = calloc(1, sizeof *b);
  if (!b)
    return NULL;
  snprintf(b->name, sizeof b->name, "%s", name);
  b->ifa.ifa_name = b->name;
  b->ifa.ifa_flags = IFF_UP | IFF_RUNNING | extra_flags;
  b->addr.sin_family = AF_INET;
  b->addr.sin_addr.s_addr = ip_net;
  b->mask.sin_family = AF_INET;
  b->mask.sin_addr.s_addr = mask_net;
  b->brd.sin_family = AF_INET;
  b->brd.sin_addr.s_addr = ip_net | ~mask_net;
  b->ifa.ifa_addr = (struct sockaddr *)&b->addr;
  b->ifa.ifa_netmask = (struct sockaddr *)&b->mask;
  b->ifa.ifa_broadaddr = (struct sockaddr *)&b->brd;
  return &b->ifa;
}

int getifaddrs(struct ifaddrs **ifap) {
  if (!g_enabled) {
    int (*real)(struct ifaddrs **) = SHIM_REAL(getifaddrs);
    if (real)
      return real(ifap);
    errno = ENOSYS;
    return -1;
  }
  if (!ifap) {
    errno = EINVAL;
    return -1;
  }
  struct ifaddrs *lo = shim_make_ifa("lo", htonl(0x7F000001u),
                                     htonl(0xFF000000u), IFF_LOOPBACK);
  if (!lo) {
    errno = ENOMEM;
    return -1;
  }
  if (g_host_ip_net) {
    struct ifaddrs *eth = shim_make_ifa("eth0", g_host_ip_net,
                                        htonl(0xFFFFFFFFu), 0);
    if (!eth) {
      free(lo);
      errno = ENOMEM;
      return -1;
    }
    lo->ifa_next = eth;
  }
  *ifap = lo;
  return 0;
}

void freeifaddrs(struct ifaddrs *ifa) {
  if (!g_enabled) {
    void (*real)(struct ifaddrs *) = SHIM_REAL(freeifaddrs);
    if (real) {
      real(ifa);
      return;
    }
  }
  while (ifa) {
    struct ifaddrs *next = ifa->ifa_next;
    free(ifa);
    ifa = next;
  }
}

/* ---- TSC emulation (preload mode; lib/tsc/tsc.c analogue) ---------- */
/* prctl(PR_SET_TSC, PR_TSC_SIGSEGV) makes every rdtsc/rdtscp raise
 * SIGSEGV; the handler decodes the instruction and synthesizes the
 * counter from SIMULATED time at a nominal 1 GHz (cycles == sim ns —
 * the same convention as the ptrace backend's Tsc emulation), so
 * plugin time reads via TSC are deterministic. */

#ifndef PR_SET_TSC
#define PR_SET_TSC 26
#endif
#ifndef PR_TSC_SIGSEGV
#define PR_TSC_SIGSEGV 2
#endif

/* The app may install its own SIGSEGV handler (Go, JVM, ASan do); the
 * shim must stay first in line or every rdtsc after that would hit
 * the app's handler as an inexplicable fault. sigaction/signal are
 * overridden below to STASH the app's SIGSEGV disposition; real
 * faults chain to it. */
static struct sigaction g_app_segv;
static int g_app_segv_set = 0;
/* resolved once at init: dlsym is not async-signal-safe, and the
 * overridden signal()/sigaction() must never be re-entered from the
 * fault path */
static int (*g_real_sigaction)(int, const struct sigaction *,
                               struct sigaction *) = NULL;

static void shim_chain_segv(int sig, siginfo_t *info, void *vctx) {
  if (g_app_segv_set) {
    if (g_app_segv.sa_flags & SA_SIGINFO) {
      if (g_app_segv.sa_sigaction) {
        g_app_segv.sa_sigaction(sig, info, vctx);
        return;
      }
    } else if (g_app_segv.sa_handler != SIG_DFL &&
               g_app_segv.sa_handler != SIG_IGN &&
               g_app_segv.sa_handler != NULL) {
      g_app_segv.sa_handler(sig);
      return;
    } else if (g_app_segv.sa_handler == SIG_IGN) {
      return;
    }
  }
  /* default: restore SIG_DFL and let the kernel re-raise on return */
  struct sigaction dfl;
  memset(&dfl, 0, sizeof dfl);
  dfl.sa_handler = SIG_DFL;
  if (g_real_sigaction)
    g_real_sigaction(sig, &dfl, NULL);
}

static void sigsegv_handler(int sig, siginfo_t *info, void *vctx) {
  ucontext_t *ctx = (ucontext_t *)vctx;
  greg_t *g = ctx->uc_mcontext.gregs;
  const uint8_t *ip = (const uint8_t *)g[REG_RIP];
  /* an EXECUTE fault (jump through a bad pointer) has si_addr == rip:
   * reading instruction bytes there would fault recursively with
   * SIGSEGV blocked (kernel force-kill) — chain without sniffing */
  int ip_readable = ip && info->si_addr != (void *)ip;
  int is_rdtsc = ip_readable && ip[0] == 0x0F && ip[1] == 0x31;
  int is_rdtscp = ip_readable && ip[0] == 0x0F && ip[1] == 0x01 &&
                  ip[2] == 0xF9;
  if (!g_enabled || (!is_rdtsc && !is_rdtscp)) {
    shim_chain_segv(sig, info, vctx);
    return;
  }
  struct timespec ts;
  long args[6] = {1 /* CLOCK_MONOTONIC */, (long)&ts, 0, 0, 0, 0};
  long r = shim_emulated_syscall(SYS_clock_gettime, args);
  uint64_t cycles = 0;
  if (r == 0)
    cycles = (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
  g[REG_RAX] = (greg_t)(cycles & 0xFFFFFFFFu);
  g[REG_RDX] = (greg_t)(cycles >> 32);
  if (is_rdtscp) {
    g[REG_RCX] = 0; /* IA32_TSC_AUX: virtual cpu 0 */
    g[REG_RIP] += 3;
  } else {
    g[REG_RIP] += 2;
  }
}

int sigaction(int signum, const struct sigaction *act,
              struct sigaction *oldact) {
  int (*real)(int, const struct sigaction *, struct sigaction *) =
      g_real_sigaction ? g_real_sigaction : SHIM_REAL(sigaction);
  if (!g_enabled || signum != SIGSEGV || !real)
    return real ? real(signum, act, oldact)
                : (errno = ENOSYS, -1);
  if (oldact)
    *oldact = g_app_segv_set ? g_app_segv
                             : (struct sigaction){.sa_handler = SIG_DFL};
  if (act) {
    g_app_segv = *act;
    g_app_segv_set = 1;
  }
  return 0; /* the shim's handler stays installed */
}

sighandler_t signal(int signum, sighandler_t handler) {
  if (!g_enabled || signum != SIGSEGV) {
    sighandler_t (*real)(int, sighandler_t) = SHIM_REAL(signal);
    return real ? real(signum, handler) : SIG_ERR;
  }
  sighandler_t old =
      g_app_segv_set ? g_app_segv.sa_handler : SIG_DFL;
  memset(&g_app_segv, 0, sizeof g_app_segv);
  g_app_segv.sa_handler = handler;
  g_app_segv_set = 1;
  return old;
}

/* ---- shim logger (shim_logger.c analogue) -------------------------- */
/* Diagnostics from inside the plugin, stamped with SIMULATED time
 * (the emulated CLOCK_MONOTONIC), written to the fd opened at init
 * from SHADOWTPU_SHIM_LOG (default: stderr, which the spawner
 * redirects into the host's data dir). Uses only raw syscalls +
 * snprintf — safe wherever the funnel is. */

static int g_log_fd = 2;

static void shim_logf(const char *fmt, ...) {
  char buf[256];
  long secs = 0, nanos = 0;
  /* passive timestamp: the simulator publishes sim time into the
   * channel at every dispatch (ShimChannel.sim_now), so tracing never
   * adds a syscall event or an extra delivery boundary */
  ShimChannel *ch = cur_ch();
  if (g_enabled && ch) {
    uint64_t t = ch->sim_now;
    secs = (long)(t / 1000000000ull);
    nanos = (long)(t % 1000000000ull);
  }
  int n = snprintf(buf, sizeof buf, "%02ld:%02ld:%02ld.%09ld [shim] ",
                   secs / 3600, (secs / 60) % 60, secs % 60, nanos);
  va_list ap;
  va_start(ap, fmt);
  n += vsnprintf(buf + n, sizeof buf - (size_t)n, fmt, ap);
  va_end(ap);
  if (n > (int)sizeof buf - 2)
    n = (int)sizeof buf - 2;
  buf[n++] = '\n';
  shim_rawsyscall(SYS_write, g_log_fd, (long)buf, n, 0, 0, 0);
}

/* ---- OpenSSL RNG overrides (openssl_preload analogue) -------------- */
/* The reference ships a separate preload lib overriding OpenSSL's
 * RAND_* so crypto apps (Tor!) draw from the deterministic seeded
 * stream (shadow_openssl_rng.c). Same effect here: the overrides
 * funnel into the trapped getrandom, which the simulator serves from
 * the host's seeded RNG. Signatures are ABI-stable C, so no OpenSSL
 * headers are needed; unlinked symbols simply never bind. */

static int shim_rand_fill(unsigned char *buf, int num) {
  if (num < 0)
    return 0;
  long off = 0;
  while (off < num) {
    long r = g_enabled
                 ? shim_emulated_syscall(
                       SYS_getrandom,
                       (long[6]){(long)(buf + off), num - off, 0, 0, 0,
                                 0})
                 : shim_rawsyscall(SYS_getrandom, (long)(buf + off),
                                   num - off, 0, 0, 0, 0);
    if (r <= 0)
      return 0;
    off += r;
  }
  return 1;
}

int RAND_bytes(unsigned char *buf, int num) {
  return shim_rand_fill(buf, num);
}

int RAND_priv_bytes(unsigned char *buf, int num) {
  return shim_rand_fill(buf, num);
}

int RAND_pseudo_bytes(unsigned char *buf, int num) {
  return shim_rand_fill(buf, num);
}

int RAND_status(void) { return 1; }
int RAND_poll(void) { return 1; }
void RAND_seed(const void *buf, int num) {
  (void)buf;
  (void)num; /* determinism: external entropy is ignored */
}
void RAND_add(const void *buf, int num, double entropy) {
  (void)buf;
  (void)num;
  (void)entropy;
}
void RAND_cleanup(void) {}

/* ---- init ---------------------------------------------------------- */

static void shim_log_fail(const char *msg) {
  /* stderr is redirected to the per-process log by the spawner */
  ssize_t w = write(2, msg, strlen(msg));
  (void)w;
}

__attribute__((constructor)) static void shim_init(void) {
  const char *shm = getenv("SHADOWTPU_SHM");
  const char *off_s = getenv("SHADOWTPU_IPC_OFFSET");
  if (!shm || !off_s)
    return; /* not spawned by the simulator: stay dormant */
  if (getenv("SHADOWTPU_CTOR_TRACE"))
    shim_log_fail("ctor: enter\n");
  shim_setup_trampoline();
  if (getenv("SHADOWTPU_CTOR_TRACE"))
    shim_log_fail(g_escape_lo == (uintptr_t)SHIM_TRAMP_ADDR
                      ? "ctor: tramp fixed\n"
                      : "ctor: tramp FALLBACK\n");

  char path[256];
  if (shm[0] == '/')
    shm++;
  snprintf(path, sizeof(path), "/dev/shm/%s", shm);
  int fd = open(path, O_RDWR | O_CLOEXEC);
  if (fd < 0) {
    shim_log_fail("shadowtpu-shim: cannot open shm arena\n");
    return;
  }
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return;
  }
  void *base = mmap(NULL, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shim_log_fail("shadowtpu-shim: cannot map shm arena\n");
    return;
  }
  g_trace_traps = getenv("SHADOWTPU_TRACE_TRAPS") != NULL;
  const char *logpath = getenv("SHADOWTPU_SHIM_LOG");
  if (logpath) {
    int lfd = open(logpath,
                   O_WRONLY | O_CREAT | O_APPEND | O_CLOEXEC, 0644);
    if (lfd >= 0)
      g_log_fd = lfd;
  }
  g_arena_base = (char *)base;
  g_ch = (ShimChannel *)(g_arena_base + strtoull(off_s, NULL, 10));

  /* RAW rt_sigaction via the trampoline: in a post-execve image the
   * OLD image's stacked seccomp filter is already live and traps
   * glibc's sigaction — before this very handler exists to field it */
  if (shim_raw_sigaction(SIGSYS, (void *)sigsys_handler,
                         SA_SIGINFO | SA_NODEFER) != 0) {
    shim_log_fail("shadowtpu-shim: sigaction(SIGSYS) failed\n");
    return;
  }
  if (getenv("SHADOWTPU_CTOR_TRACE"))
    shim_log_fail("ctor: sigsys installed\n");

  const char *hn = getenv("SHADOWTPU_HOSTNAME");
  if (hn)
    snprintf(g_hostname, sizeof g_hostname, "%s", hn);
  const char *hf = getenv("SHADOWTPU_HOSTS_FILE");
  if (hf)
    snprintf(g_hosts_path, sizeof g_hosts_path, "%s", hf);
  const char *hip = getenv("SHADOWTPU_HOST_IP");
  if (hip)
    shim_parse_ip(hip, &g_host_ip_net);

  /* TSC emulation: installed BEFORE seccomp — rt_sigaction is in the
   * trap list, and a trapped SIGSEGV registration is recorded
   * virtually (sys_rt_sigaction), which must never apply to the
   * shim's own handler. rdtsc executed before this point (dynamic
   * loader) ran natively; every app-visible read from here on is
   * simulated. */
  g_real_sigaction = SHIM_REAL(sigaction);
  if (shim_raw_sigaction(SIGSEGV, (void *)sigsegv_handler,
                         SA_SIGINFO) == 0)
    prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);

  g_enabled = 1;
  if (shim_install_seccomp() != 0) {
    g_enabled = 0;
    shim_log_fail("shadowtpu-shim: seccomp install failed\n");
    return;
  }

  /* post-execve image: announce on the (inherited) channel so the
   * simulator finishes the exec bookkeeping before app code runs */
  const char *execed = getenv("SHADOWTPU_EXEC");
  if (getenv("SHADOWTPU_CTOR_TRACE"))
    shim_log_fail("ctor: seccomp on\n");
  if (execed && strchr(execed, '1') != NULL) {
    if (getenv("SHADOWTPU_CTOR_TRACE"))
      shim_log_fail("ctor: announcing exec\n");
    shim_patch_env("SHADOWTPU_EXEC", "0");
    ShimMsg *out = (ShimMsg *)&g_ch->msg_to_simulator;
    out->kind = IPC_EXEC_DONE;
    out->number = 0;
    sem_post(&g_ch->to_simulator.value);
    shim_wait_reply(g_ch);          /* simulator: teardown + resume */
  }
}
