"""Benchmark: device engine vs CPU serial scheduler on PHOLD.

Prints ONE JSON line:
  {"metric": "packets_routed_per_sec_per_chip", "value": N,
   "unit": "packets/s", "vs_baseline": ratio}

The workload is the PHOLD PDES benchmark (the reference's own perf
probe, src/test/phold/): H hosts on a 2-vertex lossy topology, msgload
messages per host in steady state. `value` is packets routed per wall
second by the device engine on the available accelerator; `vs_baseline`
is the speedup over the single-threaded CPU reference policy running
the identical simulation (the stand-in for the reference's CPU
scheduler on this machine).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# Keep bench runs honest: one process, whatever platform jax selects
# (TPU under the driver, CPU elsewhere).

GML = """graph [ directed 0
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.01 ]
  edge [ source 0 target 1 latency "5 ms" packet_loss 0.01 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.01 ]
]"""

H = 1024           # hosts
MSGLOAD = 4        # steady-state messages per host
DEV_STOP_S = 2.0   # simulated seconds on device
CPU_STOP_S = 0.25  # simulated seconds for the CPU baseline slice


def yaml_cfg(policy: str, stop_s: float) -> str:
    return f"""
general:
  stop_time: {stop_s}s
  seed: 1
network:
  graph:
    type: gml
    inline: |
{_indent(GML, 6)}
experimental:
  scheduler_policy: {policy}
  event_capacity: 64
  outbox_capacity: 32
hosts:
  left:
    quantity: {H // 2}
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload={MSGLOAD} size=64
      start_time: 10ms
  right:
    quantity: {H // 2}
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload={MSGLOAD} size=64
      start_time: 10ms
"""


def _indent(text: str, n: int) -> str:
    pad = " " * n
    return "\n".join(pad + line for line in text.splitlines())


def run_policy(policy: str, stop_s: float) -> tuple[float, int, float]:
    """Returns (wall_seconds, packets_routed, sim_seconds)."""
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller

    cfg = load_config_str(yaml_cfg(policy, stop_s))
    c = Controller(cfg)
    if policy == "tpu":
        # warm-up: compile once on a throwaway run of the same shapes
        t0 = time.perf_counter()
        c.run()
        compile_and_run = time.perf_counter() - t0
        c2 = Controller(cfg)
        c2.runner.engine = c.runner.engine      # reuse compiled program
        t0 = time.perf_counter()
        stats = c2.run()
        wall = time.perf_counter() - t0
        print(f"bench: device compile+first run {compile_and_run:.1f}s, "
              f"steady run {wall:.2f}s", file=sys.stderr)
    else:
        t0 = time.perf_counter()
        stats = c.run()
        wall = time.perf_counter() - t0
    if not stats.ok:
        print(f"bench: WARNING {policy} run not ok (overflow?)",
              file=sys.stderr)
    return wall, stats.packets_sent, stop_s


def main() -> int:
    dev_wall, dev_packets, dev_sim_s = run_policy("tpu", DEV_STOP_S)
    dev_rate = dev_packets / dev_wall

    cpu_wall, cpu_packets, cpu_sim_s = run_policy("serial", CPU_STOP_S)
    cpu_rate = cpu_packets / cpu_wall

    print(f"bench: device {dev_packets} pkts in {dev_wall:.2f}s "
          f"({dev_rate:,.0f}/s; {dev_sim_s / dev_wall:.2f} sim-s/wall-s) | "
          f"cpu {cpu_packets} pkts in {cpu_wall:.2f}s "
          f"({cpu_rate:,.0f}/s)", file=sys.stderr)

    print(json.dumps({
        "metric": "packets_routed_per_sec_per_chip",
        "value": round(dev_rate, 1),
        "unit": "packets/s",
        "vs_baseline": round(dev_rate / cpu_rate, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
