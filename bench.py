"""Benchmark: the tgen ladder on the device engine vs the CPU thread
policy (BASELINE.md's target comparison).

Prints ONE JSON line:
  {"metric": "packets_routed_per_sec_per_chip", "value": N,
   "unit": "packets/s", "vs_baseline": R, ...extras}

Method (honest-numbers rules):
* Workload: the repo's tgen ladder — examples/tgen_100.yaml,
  tgen_1000.yaml and the 10k-host tgen_10000.yaml (the BASELINE.md
  north-star config), unmodified except stop_time for the bounded
  slices below.
* Baseline: the CPU `thread` scheduler policy (thread-per-core; on
  this machine's core count), NOT the serial oracle.
* vs_baseline: device wall-clock vs thread-policy wall-clock on the
  IDENTICAL config and sim interval (a bounded slice so the CPU run
  finishes); reported per rung, headline ratio is the 10k rung's.
* value: device packets routed per wall second over the FULL 30 s
  tgen_10000 run (steady state included), divided by chip count.
* Overflow or backend failure => nonzero exit; the JSON line is still
  emitted (with an "error" field) so the driver always gets a record.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# XLA's cpu_aot_loader logs a multi-KB machine-feature WARNING on
# every CPU start; the driver captures this bench's stderr tail into
# BENCH_*.json records, where that one message drowns every useful
# line. Suppress INFO + WARNING from the C++ layer before any jax
# import (the supervisor's child and the probe subprocesses inherit
# it); errors still surface, and an explicit TF_CPP_MIN_LOG_LEVEL in
# the environment wins.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

RUNGS = [
    # (name, config, slice_stop_s) — slice bounds the CPU baseline run
    ("tgen_100", "examples/tgen_100.yaml", 10.0),
    ("tgen_1000", "examples/tgen_1000.yaml", 4.0),
    ("tgen_10000", "examples/tgen_10000.yaml", 2.5),
]
HEADLINE = "tgen_10000"
FULL_STOP_S = 30.0

if os.environ.get("BENCH_SMOKE"):
    # mechanics-validation mode for CI/local runs (tiny ladder, no
    # full-length run); the driver's real benchmark never sets this
    RUNGS = [("tgen_100", "examples/tgen_100.yaml", 5.0)]
    HEADLINE = "tgen_100"
    FULL_STOP_S = 8.0


def log(msg: str) -> None:
    print(f"bench: {msg}", file=sys.stderr, flush=True)


def _probe_tpu(timeout_s: int = 420) -> str:
    """The TPU relay admits one client and a wedged claim makes
    jax.devices() HANG (not raise) — probe in a subprocess with a hard
    timeout so a dead relay can never stall the bench itself.

    Returns "ok" / "fail" / "timeout". The timeout sits well above
    worst-case cold init, and on expiry the probe gets SIGTERM + a
    grace period before SIGKILL; the probe installs a SIGTERM handler
    that exits via SystemExit so Python cleanup (and any claim release)
    actually runs — default SIGTERM disposition would die as abruptly
    as SIGKILL."""
    p = subprocess.Popen(
        [sys.executable, "-c",
         "import signal, sys; "
         "signal.signal(signal.SIGTERM, lambda *a: sys.exit(3)); "
         "import jax; d=jax.devices(); "
         "print(d[0].platform)"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        out, _ = p.communicate(timeout=timeout_s)
        return "ok" if p.returncode == 0 and "cpu" not in out else "fail"
    except subprocess.TimeoutExpired:
        log(f"backend probe hung >{timeout_s}s (wedged relay?)")
        p.terminate()
        try:
            p.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
        return "timeout"


def backend_record(devs) -> dict:
    """Backend identity stamped into every BENCH_*/MULTICHIP_*
    record: jax/jaxlib versions, platform, and device kinds. Without
    these, records from different backends (a cpu-fallback window vs
    a real v4 window, or a jaxlib upgrade) are silently comparable —
    previously only the aotcache keys knew them. Delegates to the
    cache's own identity helper so the two surfaces agree."""
    from shadow_tpu.device.aotcache import backend_identity

    return backend_identity(devs)


def init_backend():
    """Guarded backend init: probe the accelerator out-of-process
    (a wedged relay hangs rather than raises), retry once, then fall
    back to the CPU platform — the JSON line must always be emitted.
    Returns (devices, fell_back): a fallback run still records numbers
    but the bench exits nonzero and marks the JSON, so a CPU-vs-CPU
    ratio can never masquerade as a device benchmark."""
    from shadow_tpu._jax import jax

    last: Exception | None = None
    if os.environ.get("BENCH_FORCE_FALLBACK"):
        # test hook: drive the cpu-fallback ladder branch (the path
        # that produced BENCH_r05's 0.0) deterministically, without a
        # wedged relay — tests/test_bench_smoke.py uses it
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        log(f"backend: forced cpu fallback x{len(devs)} "
            "(BENCH_FORCE_FALLBACK)")
        return devs, True
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        devs = jax.devices()            # explicitly requested CPU
        log(f"backend: cpu x{len(devs)} (JAX_PLATFORMS=cpu)")
        return devs, False
    # retry once on a clean failure only: after a TIMEOUT the killed
    # probe client has likely wedged the relay, and a second probe
    # would just burn another 420s against a relay that cannot answer
    status = _probe_tpu()
    if status == "fail":
        status = _probe_tpu()
    if status == "ok":
        try:
            devs = jax.devices()
            log(f"backend: {devs[0].platform} x{len(devs)}")
            return devs, False
        except Exception as e:          # noqa: BLE001
            last = e
            log(f"backend init failed after probe: {e}")
    try:
        jax.config.update("jax_platforms", "cpu")
        devs = jax.devices()
        log(f"backend: fell back to cpu x{len(devs)} after: {last}")
        return devs, True
    except Exception as e:              # noqa: BLE001
        raise RuntimeError(f"no jax backend: {e}") from last


TUNE_PATH = os.path.join("artifacts", "TUNE_tpu.json")
_tuned: dict = {}
# config path -> (artifacts/OCC_*.json path, occupancy record) from
# the most recent device run of that config (see run_device)
_occ_records: dict = {}
# config path -> the compile/cache attribution stamped when that
# config's engine was first built this process (later rungs reuse
# the in-process engine and must report the ORIGINAL cold/warm
# attribution, not a misleading zero)
_cache_stamps: dict = {}


def _cache_stamp(c, warm_wall: float = 0.0, since: int = 0) -> dict:
    """Compile/dispatch attribution for a rung record, from the AOT
    compile cache's per-program events (device/aotcache.py):

    * ``compile_s``  — lower+compile walls actually paid (0.0 on a
      full warm start); the old conflated "compile+first run" number
      is split from
    * ``first_dispatch_s`` — the warm-run wall minus every
      cache-layer wall (lower/compile/load/serialize) recorded in
      that window, i.e. the cost of the first real dispatch. `since`
      is the cache's event count when the timed window opened, so a
      capacity plan's warm-up walls (its own events land before the
      window) never masquerade as dispatch time;
    * ``cache_hit``  — True when every run-program build this rung
      hit the cache; None when the cache is off or the backend
      cannot serialize executables (stamped, never silent)."""
    cache = getattr(c.runner, "aot_cache", None)
    if cache is None:
        return {"compile_s": None, "cache_hit": None,
                "compile_cache": "off"}
    rep = cache.report()
    run_ev = [e for e in rep["events"]
              if e["program"] in ("run", "run_ens")]
    ensure_s = sum(e["lower_s"] + e["compile_s"] + e["load_s"]
                   + e["serialize_s"]
                   for e in rep["events"][since:])
    out = {
        "compile_s": rep["compile_s"],
        "cache_hit": (None if rep["unsupported"] or not run_ev
                      else all(e.get("hit") for e in run_ev)),
        "cache_load_s": rep["load_s"],
        "compile_cache": ("unsupported" if rep["unsupported"]
                          else rep["dir"]),
    }
    if warm_wall:
        out["first_dispatch_s"] = round(
            max(0.0, warm_wall - ensure_s), 2)
    return out


def _fmt_s(v) -> str:
    """Stamp value for a log line: 'n/a' when the cache is off or
    the field was not produced, never a garbled 'Nones'."""
    return "n/a" if v is None else f"{v}s"


def _plan_stamp(c, stats) -> dict:
    """Strategy-plan provenance for a rung record (shadow_tpu/tune/):
    which PLAN file steered the run and the knobs it applied — tuned
    and default records must be honestly distinguishable. Provenance
    comes from SimStats (both the device runners AND the Controller's
    hybrid branch populate it — a tpu rung that fell back to hybrid
    still stamps its adopted plan). The record on disk is RE-verified
    against the run's workload fingerprint (tune/plan.verify_workload,
    the same check adoption runs): bench never stamps provenance from
    a fingerprint-mismatched PLAN file, it stamps the refusal
    instead."""
    prov = getattr(stats, "strategy_plan", None)
    if prov is None:
        return {"plan": None}
    from shadow_tpu.device.runner import device_twin
    from shadow_tpu.tune import plan as planmod

    try:
        app = (c.runner.app if getattr(c, "runner", None) is not None
               else device_twin(c.sim))
        rec = planmod.load_plan(prov["path"])
        planmod.verify_workload(rec, app, len(c.sim.hosts),
                                path=prov["path"])
    except (OSError, ValueError) as e:
        log(f"NOT stamping plan provenance from "
            f"{prov.get('path')}: {e}")
        return {"plan": None, "plan_error": str(e)}
    return {"plan": {"path": prov["path"],
                     "knobs": prov["knobs"],
                     "skipped": prov["skipped"],
                     "score": prov.get("score")}}


def _admission_stamp(stats) -> dict:
    """Preflight admission provenance for a rung record
    (device/capacity.py admission_verdict): the verdict, the modeled
    per-device footprint, the budget it was compared against, any
    static overrides (lowered pipeline depth, replica-batch split),
    and the runtime degradation-ladder rung count — a benched wall
    that ran degraded must never be compared against full-footprint
    runs unnoticed."""
    adm = getattr(stats, "admission", None)
    out = {}
    if adm is not None:
        est = adm.get("estimate") or {}
        entry = {"mode": adm.get("mode"),
                 "action": adm.get("action"),
                 "budget": adm.get("budget"),
                 "budget_source": adm.get("budget_source"),
                 "footprint_per_device": est.get("per_device"),
                 "overrides": adm.get("overrides") or {}}
        if adm.get("replica_batch"):
            entry["replica_batch"] = adm["replica_batch"]
        out["admission"] = entry
    if getattr(stats, "degrades", 0):
        out["degrades"] = stats.degrades
    return out


def load_tuned_knobs() -> dict:
    """Best (pop_strategy, burst_pops, outbox_compact) combo measured
    ON CHIP by scripts/tune_10k.py, if a committed sweep artifact
    exists. The gather/sort/VPU cost ratios differ >10x between
    platforms, so the sweep is the authority on TPU; CPU keeps the
    auto defaults. Invalid/missing artifacts mean no overrides
    (auto). outbox_compact is capacity-sensitive — it applies only to
    the swept workload, and run_device_tuned retries without it if
    the full run overflows the slice-validated width."""
    try:
        with open(TUNE_PATH) as f:
            t = json.load(f)
        best = t.get("best") or {}
        if t.get("platform") == "tpu" and best.get("counts_match"):
            knobs = {"pop_strategy": str(best["pop"]),
                     "burst_pops": int(best["burst"])}
            if best.get("compact"):    # 0 = off, not a knob to carry
                # capacity-sensitive: only valid for the exact
                # workload it was swept on (other rungs have other
                # per-phase fan-ins and could overflow loudly)
                knobs["outbox_compact"] = int(best["compact"])
                knobs["workload"] = os.path.normpath(
                    t.get("workload", ""))
            return knobs
    except Exception as e:              # noqa: BLE001
        # a malformed artifact must never abort the bench — auto
        # knobs are always a safe fallback
        log(f"ignoring unreadable {TUNE_PATH}: {e}")
    return {}


def load(config_path: str, policy: str, stop_s: float):
    from shadow_tpu import simtime
    from shadow_tpu.config import load_config

    cfg = load_config(config_path)
    cfg.experimental.scheduler_policy = policy
    cfg.general.stop_time = simtime.from_seconds(stop_s)
    if policy == "tpu" and os.environ.get("BENCH_CAPACITY_PLAN"):
        # opt-in: size every capacity from a measured warm-up slice
        # (device/capacity.py) instead of the configs' static knobs.
        # Traces stay bit-identical unless something overflows, and
        # an overflow re-plans and retries instead of failing. The
        # warm-up must reach real traffic — tgen clients start at 2s
        # sim, so the default stop/8 would measure boot only and eat
        # a re-plan cycle per rung
        plan = os.environ["BENCH_CAPACITY_PLAN"]
        if plan not in ("static", "auto") and \
                not plan.endswith(".json"):
            # the schema's own check runs at load_config time; this
            # assignment is post-validation, so re-check here or a
            # typo dies minutes later as a raw FileNotFoundError
            raise SystemExit(
                f"BENCH_CAPACITY_PLAN={plan!r} is neither 'static', "
                "'auto', nor a path to a saved OCC_*.json record")
        cfg.experimental.capacity_plan = plan
        if cfg.experimental.capacity_plan == "auto":
            cfg.experimental.capacity_warmup = min(
                cfg.general.stop_time, simtime.from_seconds(3.0))
    if policy == "tpu" and os.environ.get("BENCH_STRATEGY_PLAN"):
        # opt-in: adopt a tuned strategy plan (shadow_tpu/tune/) —
        # auto|off|<PLAN_*.json path>. Traces stay bit-identical
        # (determinism_gate --tuned pins it); the records carry the
        # plan provenance so tuned and default rungs never silently
        # compare. The env lands after load_config's schema
        # validation, so re-run the knob's ONE shared check here
        # (schema._keyword_or_path — never a fourth copy of the
        # typo-rejection logic).
        from shadow_tpu.config.schema import _keyword_or_path
        try:
            cfg.experimental.strategy_plan = _keyword_or_path(
                "strategy_plan", os.environ["BENCH_STRATEGY_PLAN"],
                ("auto", "off"),
                "a path to a saved PLAN_*.json strategy record",
                json_record=True)
        except ValueError as e:
            raise SystemExit(f"BENCH_STRATEGY_PLAN: {e}") from e
    if policy == "tpu" and _tuned:
        cfg.experimental.pop_strategy = _tuned["pop_strategy"]
        cfg.experimental.burst_pops = _tuned["burst_pops"]
        if "outbox_compact" in _tuned:
            if _tuned.get("workload") == os.path.normpath(config_path):
                cfg.experimental.outbox_compact = \
                    _tuned["outbox_compact"]
            else:
                log(f"tuned outbox_compact not applied to "
                    f"{config_path} (swept on {_tuned.get('workload')})")
    return cfg


def _plan_and_warm(c, cfg) -> tuple[float, float, dict]:
    """Plan capacities + compile + one boot-length warm run, OUTSIDE
    any timed benchmark window, returning (plan_s, warm_s, stamp).
    The first-dispatch window opens only after the plan and
    init_state, and only cache events recorded inside it are
    subtracted by _cache_stamp — the warm-up SIMULATION's wall (and
    the heap-builder compile) must never masquerade as dispatch
    time. One helper so the ladder and the multichip rung cannot
    drift on that ordering invariant."""
    from shadow_tpu import simtime

    t0 = time.perf_counter()
    c.runner._plan_capacities(cfg.general.stop_time)
    plan_s = time.perf_counter() - t0
    cache = getattr(c.runner, "aot_cache", None)
    ev0 = len(cache.events) if cache is not None else 0
    st = c.runner.engine.init_state(c.sim.starts)
    t0 = time.perf_counter()
    c.runner.engine.run(st, stop=simtime.from_seconds(0.001))
    warm = time.perf_counter() - t0
    return plan_s, warm, _cache_stamp(c, warm_wall=warm, since=ev0)


def run_device(config_path: str, stop_s: float,
               engine_cache: dict,
               segment_s: float = 0.0
               ) -> tuple[float, int, float, dict]:
    """Warm-compiled device run: (wall_s, packets, sim_s,
    cache_stamp). Raises on overflow — a failed capacity plan must
    fail the bench. stop_time is a runtime scalar of the compiled
    program, so one short warm-up run per config covers every slice
    length. segment_s bounds the sim-time of each device dispatch
    (trace-identical splitting) — tunneled TPU relays kill executions
    that run for minutes, so long full runs must not go up as one
    mega-dispatch.

    cache_stamp splits the old conflated "compile+warm" wall into
    compile_s / first_dispatch_s / cache_hit (see _cache_stamp) so
    the perf trajectory tracks cold-start from now on."""
    from shadow_tpu import simtime
    from shadow_tpu.core.controller import Controller

    cfg = load(config_path, "tpu", stop_s)
    if segment_s:
        cfg.experimental.dispatch_segment = \
            simtime.from_seconds(segment_s)
    c = Controller(cfg)
    # under a capacity plan the runner rebuilds the engine from
    # measured occupancy, so a cached statically-sized engine would
    # just be thrown away — plan ahead of the timed window instead
    planned = cfg.experimental.capacity_plan != "static"
    if not planned and config_path in engine_cache:
        c.runner.engine = engine_cache[config_path]
        # the rung reuses the in-process engine: report the
        # attribution from when THIS config's engine was built —
        # including through SimStats, so the runner's loud summary
        # reflects the engine's real cache lineage, not the fresh
        # runner's empty one
        if getattr(c.runner.engine, "aot_cache", None) is not None:
            c.runner.aot_cache = c.runner.engine.aot_cache
        stamp = dict(_cache_stamps.get(config_path, {}))
    elif not planned:
        # compile + a minimal-length run (boot only) to warm the
        # cache; the timed window opens AFTER init_state so the
        # heap-builder compile never counts as dispatch time
        st = c.runner.engine.init_state(c.sim.starts)
        t0 = time.perf_counter()
        c.runner.engine.run(st, stop=simtime.from_seconds(0.001))
        warm = time.perf_counter() - t0
        stamp = _cache_stamp(c, warm_wall=warm)
        log(f"  compile+warm {warm:.1f}s (compile "
            f"{_fmt_s(stamp.get('compile_s'))}, load "
            f"{_fmt_s(stamp.get('cache_load_s'))}, first dispatch "
            f"{_fmt_s(stamp.get('first_dispatch_s'))}, cache_hit="
            f"{stamp.get('cache_hit')})")
        engine_cache[config_path] = c.runner.engine
        _cache_stamps[config_path] = stamp
    else:
        # plan + compile OUTSIDE the timed window, for parity with
        # the static path's warm cache: the warm-up slice, the static
        # engine's compile, and the planned engine's compile must not
        # land in `wall` (the cpu baseline pays none of them). run()
        # sees the runner already planned and skips re-planning.
        plan_s, warm, stamp = _plan_and_warm(c, cfg)
        _cache_stamps[config_path] = stamp
        log(f"  plan {plan_s:.1f}s + compile+warm {warm:.1f}s "
            f"(compile {_fmt_s(stamp.get('compile_s'))}, first "
            f"dispatch {_fmt_s(stamp.get('first_dispatch_s'))}, "
            f"cache_hit={stamp.get('cache_hit')})")
    t0 = time.perf_counter()
    stats = c.run()
    wall = time.perf_counter() - t0
    if not stats.ok:
        raise RuntimeError(
            f"device run of {config_path} (stop={stop_s}s) overflowed "
            "— the capacity plan is wrong; see log for the knob")
    stamp = dict(stamp)
    if stats.telemetry is not None:
        # the flight recorder's per-phase wall attribution
        # (shadow_tpu/obs): the headline record carries it so the
        # perf trajectory shows WHERE the wall went, not just how
        # long it was
        stamp["phase_walls"] = stats.telemetry.get("phases")
        stamp["dominant_phase"] = stats.telemetry.get(
            "dominant_phase")
    # segment-pipeline telemetry (supervise.advance): depth,
    # issue/drain counts, sync wall, overlap efficiency — rides
    # every device rung record so sync-bound vs device-bound wall
    # is attributable from the BENCH record alone
    stamp["pipeline"] = stats.pipeline
    # preflight admission verdict + modeled footprint (and any
    # degradation the run absorbed) ride every device rung record
    stamp.update(_admission_stamp(stats))
    if stats.reshards:
        # a bench run that survived device loss is NOT a clean perf
        # record: stamp the shrink count + the shrunken mesh so the
        # number is never compared against full-mesh runs unnoticed
        stamp["reshards"] = stats.reshards
        stamp["mesh_shards_final"] = c.runner.engine.n_shards
    # strategy-plan provenance (or its loud refusal) rides every
    # device rung record
    stamp.update(_plan_stamp(c, stats))
    if stats.occupancy is not None:
        # measured high-water marks + the capacities that held them;
        # the headline run's record is written to artifacts/ in main()
        # so scripts/tune_10k.py can prune its sweep grid from it
        from shadow_tpu.device import capacity
        _occ_records[config_path] = (
            capacity.record_path(c.runner.engine), stats.occupancy)
    return wall, stats.packets_sent, stop_s, stamp


def run_device_tuned(config_path: str, stop_s: float,
                     engine_cache: dict,
                     segment_s: float = 0.0
                     ) -> tuple[float, int, float, dict]:
    """run_device, but a loud overflow while the tuned outbox_compact
    is applied retries once WITHOUT it: the sweep validates compact on
    a bounded slice, and a steady-state window of the full run can
    legitimately exceed the compacted width — that must cost the knob,
    never the benchmark."""
    try:
        return run_device(config_path, stop_s, engine_cache,
                          segment_s)
    except RuntimeError as e:
        applied = "outbox_compact" in _tuned and \
            _tuned.get("workload") == os.path.normpath(config_path)
        if "overflow" in str(e) and applied:
            _tuned.pop("outbox_compact", None)
            _tuned.pop("workload", None)
            log(f"tuned outbox_compact overflowed on {config_path}; "
                "retrying without it")
            engine_cache.pop(config_path, None)
            return run_device(config_path, stop_s, engine_cache,
                              segment_s)
        raise


def run_cpu_thread(config_path: str, stop_s: float
                   ) -> tuple[float, int, float]:
    from shadow_tpu.core.controller import Controller

    cfg = load(config_path, "thread", stop_s)
    t0 = time.perf_counter()
    stats = Controller(cfg).run()
    wall = time.perf_counter() - t0
    if not stats.ok:
        raise RuntimeError(f"cpu thread run of {config_path} failed")
    return wall, stats.packets_sent, stop_s


MULTICHIP_SLICES = {"tgen_100": 5.0, "tgen_1000": 3.0,
                    "tgen_10000": 2.5}


def run_multichip_rung(n_chips: int, fell_back: bool,
                       bench_t0: float) -> dict:
    """Scale-out rung (n_chips > 1): the tgen workload sharded over
    the whole mesh with `exchange: auto` + an occupancy-driven
    capacity plan, recording per-round exchanged ICI volume alongside
    pkts/s. The dense comparison is the engine's blind 4x auto CAP at
    the same shapes — the padding the occ_x-driven plan replaces —
    so the record shows the exchanged-row reduction directly."""
    from shadow_tpu import simtime
    from shadow_tpu.core.controller import Controller
    from shadow_tpu.device.capacity import dense_auto_cap

    if n_chips < 2:
        return {"skipped": f"{n_chips} chip(s) visible — the "
                           "multichip rung needs a mesh"}
    # headline config on a real mesh; smoke/fallback shrink to the
    # rung the wall budget affords (a cpu-platform tgen_10000 plan +
    # run would blow the supervisor cap and lose the WHOLE record,
    # same hazard the ladder guards against)
    if os.environ.get("BENCH_SMOKE"):
        name = "tgen_100"
    elif fell_back:
        name = "tgen_1000"
        used = time.perf_counter() - bench_t0
        if used > 1600:
            return {"skipped": f"cpu-platform wall budget: {used:.0f}s "
                               "already used"}
    else:
        name = "tgen_10000"
    from shadow_tpu._jax import jax as _jax

    config = f"examples/{name}.yaml"
    slice_s = MULTICHIP_SLICES[name]
    out = {"config": config, "slice_sim_s": slice_s,
           "n_chips": n_chips, **backend_record(_jax.devices())}
    cfg = load(config, "tpu", slice_s)
    cfg.experimental.exchange = "auto"
    cfg.experimental.capacity_plan = "auto"
    cfg.experimental.capacity_warmup = min(
        cfg.general.stop_time, simtime.from_seconds(3.0))
    c = Controller(cfg)
    # plan + compile outside the timed window (same parity rule as
    # the ladder's warm cache)
    plan_s, warm, stamp = _plan_and_warm(c, cfg)
    out.update({k: stamp.get(k) for k in
                ("compile_s", "first_dispatch_s", "cache_hit")})
    log(f"  multichip plan {plan_s:.1f}s + compile+warm {warm:.1f}s "
        f"(compile {_fmt_s(stamp.get('compile_s'))}, cache_hit="
        f"{stamp.get('cache_hit')})")
    t0 = time.perf_counter()
    stats = c.run()
    wall = time.perf_counter() - t0
    if not stats.ok:
        return {**out, "error": "multichip run overflowed"}
    out.update(_plan_stamp(c, stats))
    out.update(_admission_stamp(stats))
    eng = c.runner.engine
    eff = eng.effective
    occ = stats.occupancy or {}
    measured = dict(occ.get("measured") or {})
    measured.update(occ.get("final_measured") or {})
    phases = int(measured.get("phases", 0))
    rounds = max(1, stats.rounds)
    out.update({
        "exchange": eff["exchange"],
        "exchange_auto": occ.get("exchange_auto"),
        "planned": occ.get("planned"),
        "pkts": stats.packets_sent,
        "wall_s": round(wall, 2),
        "pkts_per_s": round(stats.packets_sent / wall, 1),
        "pkts_per_s_per_chip": round(
            stats.packets_sent / wall / n_chips, 1),
        "rounds": stats.rounds,
        "phases": phases,
        # per-shard ICI traffic: buffers ship at capacity, so the
        # static per-flush volume times the flush count IS the wire
        "ici_rows_per_flush": eff["ICI_rows_per_flush"],
        "ici_bytes_per_flush": eff["ICI_bytes_per_flush"],
        "ici_rows_per_round": round(
            eff["ICI_rows_per_flush"] * phases / rounds, 1),
    })
    # the dense blind-headroom pack this plan replaces: the engine's
    # auto 4x CAP at the STATIC config's shapes (occ["static"] — what
    # the pre-planner engine actually ran), not the planned engine's
    # possibly-wider outbox, so the reduction factor is honest
    S = eff["n_shards"]
    static = occ.get("static") or {}
    dense_rows = (S - 1) * dense_auto_cap(
        eng.H_loc,
        int(static.get("outbox_capacity", eff["OB"])),
        int(static.get("event_capacity", eff["E"])), S)
    out["dense_auto_rows_per_flush"] = dense_rows
    if eff["ICI_rows_per_flush"]:
        out["ici_reduction_vs_dense"] = round(
            dense_rows / eff["ICI_rows_per_flush"], 2)
    return out


ENSEMBLE_REPLICAS = 4
ENSEMBLE_SEEDS = [1, 7, 13, 42]
ENSEMBLE_CONFIG = "examples/tgen_100.yaml"
ENSEMBLE_STOP_S = 4.0 if os.environ.get("BENCH_SMOKE") else 5.0


def run_ensemble_rung() -> dict:
    """Ensemble rung: an R-replica seed-sweep campaign (ONE vmapped
    program) vs the cold standalone run R serial processes would each
    repeat. Both walls are COLD — compile included — because that is
    what a user running N processes actually pays; the campaign pays
    one compile for all R replicas, which is the amortization this
    rung makes visible (speedup_vs_r_serial_runs). Aggregate
    packets/s is the campaign's total routed packets over its wall.
    Runs on the cpu-fallback path too (clearly labeled by the record's
    platform field): campaign mechanics must be validated even when no
    device is reachable."""
    from shadow_tpu.config.schema import EnsembleOptions
    from shadow_tpu.core.controller import Controller

    R = ENSEMBLE_REPLICAS
    out = {"config": ENSEMBLE_CONFIG, "replicas": R,
           "seeds": ENSEMBLE_SEEDS, "slice_sim_s": ENSEMBLE_STOP_S}
    cfg = load(ENSEMBLE_CONFIG, "tpu", ENSEMBLE_STOP_S)
    cfg.general.seed = ENSEMBLE_SEEDS[0]
    t0 = time.perf_counter()
    c1 = Controller(cfg)
    s1 = c1.run()
    single_wall = time.perf_counter() - t0
    if not s1.ok:
        return {**out, "error": "standalone run overflowed"}
    if s1.packets_sent == 0:
        return {**out, "error": "standalone run routed 0 packets "
                                "(slice too short?)"}
    out["single_run_wall_s"] = round(single_wall, 2)
    out["single_run_pkts"] = s1.packets_sent
    out["single_run_pkts_per_s"] = round(
        s1.packets_sent / single_wall, 1)
    # the "cold" walls are honest only with the cache state stamped:
    # a repeat bench with a populated AOT cache starts warm, and
    # cache_hit marks exactly that
    s1_stamp = _cache_stamp(c1)
    out["single_run_compile_s"] = s1_stamp.get("compile_s")
    out["single_run_cache_hit"] = s1_stamp.get("cache_hit")

    cfg2 = load(ENSEMBLE_CONFIG, "tpu", ENSEMBLE_STOP_S)
    cfg2.ensemble = EnsembleOptions.from_dict(
        {"replicas": R, "vary": {"seed": ENSEMBLE_SEEDS}})
    t0 = time.perf_counter()
    c2 = Controller(cfg2)
    s2 = c2.run()
    ens_wall = time.perf_counter() - t0
    if not s2.ok:
        return {**out, "error": "campaign overflowed"}
    out["campaign_wall_s"] = round(ens_wall, 2)
    out.update(_admission_stamp(s2))
    s2_stamp = _cache_stamp(c2)
    out["campaign_compile_s"] = s2_stamp.get("compile_s")
    out["campaign_cache_hit"] = s2_stamp.get("cache_hit")
    out["aggregate_pkts"] = s2.packets_sent
    out["aggregate_pkts_per_s"] = round(s2.packets_sent / ens_wall, 1)
    out["r_x_single_run_pkts_per_s"] = round(
        R * out["single_run_pkts_per_s"], 1)
    # the campaign vs R cold serial runs of the same slice: > 1 means
    # the one-compile amortization is real on this platform
    out["speedup_vs_r_serial_runs"] = round(
        R * single_wall / ens_wall, 2)
    out["record"] = c2.runner.record_path()
    # the determinism contract rides along: campaign replica 0 must
    # bit-match the standalone run it was compared against
    import numpy as np
    H = len(c2.sim.hosts)
    chk_e = np.asarray(c2.runner.final_state["chk"])[0, :H]
    chk_s = np.array([h.trace_checksum for h in c1.sim.hosts])
    out["replica0_matches_single"] = bool((chk_e == chk_s).all())
    if not out["replica0_matches_single"]:
        out["error"] = "campaign replica 0 diverged from the " \
                       "standalone run with its seed"
    return out


# topology-representation rung ladder: (label, clusters, spokes/hub).
# The 1M point runs only outside BENCH_SMOKE (sub-second build, but
# the smoke ladder stays tiny on principle).
TOPOLOGY_RUNG_SIZES = [("1k", 20, 49), ("100k", 100, 999)]
TOPOLOGY_RUNG_1M = "examples/tgen_1000000.yaml"


def run_topology_rung() -> dict:
    """Topology-representation rung (docs/topology.md): build
    hierarchical star_clusters tables at 1k/100k vertices — and the
    million-host example config outside BENCH_SMOKE — stamping build
    wall, actual table bytes, and the dense-equivalent bytes
    (12 bytes/pair: int64 latency + float32 reliability). At the 1k
    point the dense pipeline also runs for a wall/byte comparison and
    the factored tables are checked bit-identical to it (the build
    already verifies at V <= 2048; a silent skip would make this rung
    meaningless). Pure host-side numpy — no device work, so the rung
    is identical on every backend."""
    import numpy as np

    from shadow_tpu.device.capacity import fmt_bytes
    from shadow_tpu.topology.generate import generate_star_clusters

    out = {"points": []}
    for label, C, S in TOPOLOGY_RUNG_SIZES:
        params = {"clusters": C, "spokes_per_cluster": S,
                  "hub_latency": "10 ms", "access_latency": "1 ms"}
        t0 = time.perf_counter()
        th = generate_star_clusters(params,
                                    representation="hierarchical")
        h_wall = time.perf_counter() - t0
        V = th.n_vertices
        dense_bytes = 12 * V * V
        pt = {"label": label, "n_vertices": V,
              "n_clusters": th.hier.n_clusters,
              "hier_build_s": round(h_wall, 3),
              "hier_table_bytes": th.table_nbytes(),
              "dense_table_bytes": dense_bytes,
              "reduction": round(dense_bytes / th.table_nbytes(), 1)}
        if V <= 2048:
            t0 = time.perf_counter()
            td = generate_star_clusters(params,
                                        representation="dense")
            pt["dense_build_s"] = round(time.perf_counter() - t0, 3)
            hlat, hrel = th.hier.dense()
            if not (np.array_equal(hlat, td.latency_ns)
                    and np.array_equal(hrel, td.reliability)):
                return {**out, "error": f"{label}: factored tables "
                        "diverged from the dense pipeline"}
        log(f"  topology {label}: V={V} hier "
            f"{fmt_bytes(pt['hier_table_bytes'])} in "
            f"{pt['hier_build_s']}s (dense "
            f"{fmt_bytes(dense_bytes)}, {pt['reduction']}x)")
        out["points"].append(pt)
    if not os.environ.get("BENCH_SMOKE"):
        # the million-host example, through the REAL config path
        # (schema -> load_topology -> generator -> representation)
        from shadow_tpu.config import load_config
        from shadow_tpu.core.controller import load_topology
        cfg = load_config(TOPOLOGY_RUNG_1M)
        t0 = time.perf_counter()
        top = load_topology(cfg)
        wall = time.perf_counter() - t0
        V = top.n_vertices
        budget = int(cfg.experimental.device_memory_budget)
        tb = top.table_nbytes()
        pt = {"label": "1M", "config": TOPOLOGY_RUNG_1M,
              "n_vertices": V, "n_clusters": top.hier.n_clusters,
              "hier_build_s": round(wall, 3),
              "hier_table_bytes": tb,
              "dense_table_bytes": 12 * V * V,
              "reduction": round(12 * V * V / tb, 1),
              "budget_bytes": budget,
              "tables_fit_budget": tb <= budget}
        log(f"  topology 1M: V={V} tables {fmt_bytes(tb)} in "
            f"{pt['hier_build_s']}s — "
            f"{'fit' if pt['tables_fit_budget'] else 'EXCEED'} the "
            f"{fmt_bytes(budget)} example budget (dense would be "
            f"{fmt_bytes(12 * V * V)})")
        out["points"].append(pt)
        if not pt["tables_fit_budget"]:
            out["error"] = "1M tables exceed the example's budget"
    return out


# columnar-boot rung ladder: config per point. The 1M example runs
# only outside BENCH_SMOKE (it boots in seconds now, but the smoke
# ladder stays tiny on principle).
BOOT_RUNG_POINTS = [("1k", "examples/tgen_1000.yaml"),
                    ("100k", "examples/tgen_100000.yaml")]
BOOT_RUNG_1M = ("1M", "examples/tgen_1000000.yaml")
BOOT_RUNG_PATH = os.path.join("artifacts", "BOOT_r16.json")
BOOT_1M_FLOOR_S = 60.0


def run_boot_rung() -> dict:
    """Columnar-boot rung (docs/host_plane.md): wall clock to stand up
    a runnable simulation — controller.build() (columnar host plane) +
    DeviceRunner construction + engine.init_state() — at 1k/100k
    hosts, plus the million-host example outside BENCH_SMOKE. Stamps
    per-stage walls and hosts/s into artifacts/BOOT_r16.json, and
    records whether the columnar fast path actually ran: an object
    build sneaking in would silently bench the wrong thing, so a
    refused plane is an error here, not a fallback. The acceptance
    floor rides along — the 1M point must boot in under 60 s."""
    import gc

    import jax as _jax

    from shadow_tpu.config import load_config
    from shadow_tpu.core.controller import build as build_sim
    from shadow_tpu.device.runner import DeviceRunner
    from shadow_tpu.utils.artifacts import atomic_write_json

    points = list(BOOT_RUNG_POINTS)
    if not os.environ.get("BENCH_SMOKE"):
        points.append(BOOT_RUNG_1M)
    out = {"points": []}
    for label, path in points:
        cfg = load_config(path)
        n = cfg.total_hosts()
        t0 = time.perf_counter()
        sim = build_sim(cfg)
        build_s = time.perf_counter() - t0
        columnar = sim.plane is not None
        t0 = time.perf_counter()
        runner = DeviceRunner(sim)
        engine_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = runner.engine.init_state(sim.starts)
        _jax.block_until_ready(state["ht"])
        init_s = time.perf_counter() - t0
        boot_s = build_s + engine_s + init_s
        pt = {"label": label, "config": path, "n_hosts": n,
              "columnar": columnar,
              "build_s": round(build_s, 3),
              "engine_s": round(engine_s, 3),
              "init_state_s": round(init_s, 3),
              "boot_s": round(boot_s, 3),
              "hosts_per_s": round(n / boot_s, 1)}
        log(f"  boot {label}: {n} hosts in {pt['boot_s']}s "
            f"({pt['hosts_per_s']:,.0f} hosts/s; build "
            f"{pt['build_s']}s, engine {pt['engine_s']}s, "
            f"init_state {pt['init_state_s']}s, "
            f"columnar={columnar})")
        out["points"].append(pt)
        if not columnar:
            out["error"] = (f"{label}: the columnar fast path was "
                            "refused — this rung benches the plane")
        elif label == "1M" and boot_s >= BOOT_1M_FLOOR_S:
            out["error"] = (f"1M boot took {boot_s:.1f}s — the "
                            f"<{BOOT_1M_FLOOR_S:.0f}s floor failed")
        # the 1M heaps are ~2.6 GB on the CPU platform: release them
        # before the next point (or whatever rung follows)
        del state, runner, sim
        gc.collect()
    try:
        atomic_write_json(out, BOOT_RUNG_PATH)
        log(f"  boot record -> {BOOT_RUNG_PATH}")
    except OSError as e:
        log(f"  could not write boot record: {e}")
    return out


PIPELINE_DEPTHS = (1, 2, 4)


def run_pipelined_rung(name: str, config_path: str, stop_s: float
                       ) -> dict:
    """Pipelined-dispatch rung (device/supervise.py segment
    pipeline): the headline workload in the SUPERVISED production
    posture — rotating validated checkpoints, heartbeats, and the
    state-audit word — at pipeline_depth 1/2/4 on one identical
    config. Depth 1 is the serial issue-then-sync loop; deeper
    windows overlap the drain's host-side boundary work (checkpoint
    fetch+compress+write, heartbeat syncs, audit reads) with device
    execution of the in-flight segments. Every depth must route
    identical traffic (bit-identity is the gate's job; the rung
    re-checks the cheap packet counters so a broken window can never
    publish a number).

    Honesty rules: all depths run WARM (one engine, compile excluded
    from every timed window — the serial leg must not pay the audit
    program's cold compile), and the record stamps host_cores:
    overlap converts host-side wall into device-shadowed wall only
    when the host and the device are separate hardware, so on a
    single-core cpu-fallback box the depths measure flat and the
    rung's real-TPU number is the one the ROADMAP campaign item
    collects."""
    import tempfile

    from shadow_tpu import simtime
    from shadow_tpu.core.controller import Controller

    out: dict = {
        "workload": name,
        "slice_sim_s": stop_s,
        "depths_swept": list(PIPELINE_DEPTHS),
        "host_cores": len(os.sched_getaffinity(0))
        if hasattr(os, "sched_getaffinity") else os.cpu_count(),
        # the supervised posture (sim-seconds): segment/checkpoint/
        # heartbeat cadences scale with the slice so the smoke rung
        # and the full rung exercise the same boundary density
        "dispatch_segment_s": round(stop_s / 20, 3),
        "checkpoint_every_s": round(stop_s / 40, 3),
        "heartbeat_s": round(stop_s / 10, 3),
    }
    engine = None
    depths: dict = {}
    pkts0 = None
    with tempfile.TemporaryDirectory() as tmp:
        for depth in PIPELINE_DEPTHS:
            cfg = load(config_path, "tpu", stop_s)
            # this rung measures PIPELINING, not planning: a
            # BENCH_CAPACITY_PLAN=auto run would re-plan and rebuild
            # the engine inside depth 1's timed window (and hand the
            # stale engine to depths 2/4), breaking the one-warm-
            # engine rule the depth comparison depends on — pin the
            # static capacities for every depth instead
            cfg.experimental.capacity_plan = "static"
            cfg.experimental.capacity_warmup = 0
            cfg.general.heartbeat_interval = simtime.from_seconds(
                out["heartbeat_s"])
            ddir = os.path.join(tmp, f"d{depth}")
            os.makedirs(ddir, exist_ok=True)
            cfg.general.data_directory = os.path.join(ddir,
                                                      "shadow.data")
            cfg.experimental.dispatch_segment = simtime.from_seconds(
                out["dispatch_segment_s"])
            cfg.experimental.checkpoint_save = os.path.join(ddir,
                                                            "ck.npz")
            cfg.experimental.checkpoint_every = simtime.from_seconds(
                out["checkpoint_every_s"])
            cfg.experimental.state_audit = True
            cfg.experimental.pipeline_depth = depth
            c = Controller(cfg)
            if engine is None:
                # compile once (the audit word changes the program,
                # so the ladder's engine cache does not apply) and a
                # boot-length warm dispatch, both outside every
                # depth's timed window
                from shadow_tpu._jax import jax
                st = c.runner.engine.init_state(c.sim.starts)
                t0 = time.perf_counter()
                # run() is a pure async enqueue since PR 11: block
                # explicitly, or the warm segment's device work
                # would still be executing when depth 1's timed
                # window opens (and be charged to the serial leg)
                jax.block_until_ready(c.runner.engine.run(
                    st, stop=simtime.from_seconds(0.001)))
                out["compile_warm_s"] = round(
                    time.perf_counter() - t0, 2)
                engine = c.runner.engine
            else:
                c.runner.engine = engine
                if getattr(engine, "aot_cache", None) is not None:
                    c.runner.aot_cache = engine.aot_cache
            t0 = time.perf_counter()
            stats = c.run()
            wall = time.perf_counter() - t0
            if not stats.ok:
                return {**out, "error":
                        f"depth-{depth} run reported not-ok"}
            if pkts0 is None:
                pkts0 = stats.packets_sent
            elif stats.packets_sent != pkts0:
                # same config+seed at every depth must route the
                # same traffic; a divergent window is a determinism
                # bug, not a number worth publishing
                return {**out, "error":
                        f"depth {depth} routed {stats.packets_sent} "
                        f"packets but depth 1 routed {pkts0} on the "
                        "identical config"}
            rec = {
                "wall_s": round(wall, 2),
                "pkts_per_s": round(stats.packets_sent / wall, 1),
                "pipeline": dict(stats.pipeline or {}),
            }
            rec.update(_admission_stamp(stats))
            if stats.telemetry is not None:
                rec["phase_walls"] = stats.telemetry.get("phases")
                rec["dominant_phase"] = stats.telemetry.get(
                    "dominant_phase")
            depths[str(depth)] = rec
            log(f"  depth {depth}: {wall:.2f}s wall, overlap "
                f"{rec['pipeline'].get('overlap_efficiency', 0.0):.0%}"
                f" ({rec['pipeline'].get('issued')} issued, sync "
                f"{rec['pipeline'].get('sync_wall_s')}s)")
    out["depths"] = depths
    out["pkts"] = pkts0
    w1 = depths[str(PIPELINE_DEPTHS[0])]["wall_s"]
    wn = depths[str(PIPELINE_DEPTHS[-1])]["wall_s"]
    out["wall_delta_vs_serial_pct"] = round(100.0 * (w1 - wn) / w1, 1)
    if out["host_cores"] == 1:
        out["note"] = (
            "single-core host: the cpu-fallback 'device' and the "
            "host share one core, so overlapped work cannot reduce "
            "wall here — the flat depths are expected; the real-TPU "
            "window (ROADMAP proof campaign) is where this rung's "
            "overlap converts to wall")
    return out


HYBRID_SWEEP = [40, 200, 1000]      # pairs per rung (VERDICT r4 #3)
HYBRID_BYTES = 100_000
HYBRID_SWEEP_BUDGET_S = 1200        # stop adding rungs past this

HYBRID_GML = """graph [ directed 0
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.001 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.001 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.001 ]
]"""


def _hybrid_cfg(policy: str, data_dir: str, bins: dict,
                pairs: int) -> str:
    gml = "\n".join("      " + ln for ln in HYBRID_GML.splitlines())
    cfg = f"""
general:
  stop_time: 60s
  seed: 1
  data_directory: {data_dir}
network:
  graph:
    type: gml
    inline: |
{gml}
experimental:
  scheduler_policy: {policy}
hosts:
"""
    # servers register first -> sequential IPs from 11.0.0.1 (dns.py
    # _alloc_ip order, reserved .0/.255 skipped); client i dials its
    # own server's IP
    def nth_ip(i: int) -> str:
        ip = (11 << 24) | 1
        for _ in range(i):
            ip += 1
            while ip & 0xFF in (0, 255):
                ip += 1
        return ".".join(str((ip >> s) & 0xFF)
                        for s in (24, 16, 8, 0))

    for i in range(pairs):
        cfg += f"""  server{i}:
    network_node_id: 0
    processes:
    - {{path: {bins['tcp_server']}, args: 8080, start_time: 1s}}
"""
    for i in range(pairs):
        cfg += f"""  client{i}:
    network_node_id: 1
    processes:
    - {{path: {bins['tcp_client']}, args: {nth_ip(i)} 8080 {HYBRID_BYTES}, start_time: 2s}}
"""
    return cfg


def _compile_tcp_bins(tmp: str):
    import shutil
    import subprocess as sp

    cc = shutil.which("cc") or shutil.which("gcc")
    plug = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tests", "plugins")
    if cc is None or not os.path.isdir(plug):
        return None
    bins = {}
    for name in ("tcp_client", "tcp_server"):
        exe = os.path.join(tmp, name)
        sp.run([cc, "-O1", "-o", exe,
                os.path.join(plug, f"{name}.c")], check=True,
               capture_output=True)
        bins[name] = exe
    return bins


def _hybrid_rung(bins: dict, tmp: str, pairs: int) -> dict:
    """One sweep rung: `pairs` real tcp_client/tcp_server pairs
    (seccomp interposition, emulated TCP) under `hybrid` — adaptive
    judge: CPU below hybrid_judge_min_batch, device above — vs the
    identical config on the pure-CPU `thread` policy. Honest on both
    outcomes: judged packets, batch counts, and the wall ratio are
    recorded either way."""
    from shadow_tpu.config import load_config_str
    from shadow_tpu.core.controller import Controller

    out = {"pairs": pairs, "bytes_per_pair": HYBRID_BYTES}
    sums = {}
    for policy in ("thread", "hybrid"):
        data = os.path.join(tmp, f"{policy}{pairs}", "shadow.data")
        cfg = load_config_str(_hybrid_cfg(policy, data, bins, pairs))
        c = Controller(cfg)
        t0 = time.perf_counter()
        stats = c.run()
        wall = time.perf_counter() - t0
        if not stats.ok:
            return {"error": f"{policy} run failed", "pairs": pairs}
        sums[policy] = [h.trace_checksum for h in c.sim.hosts]
        out[f"{policy}_wall_s"] = round(wall, 2)
        if policy == "hybrid":
            j = c.manager.net_judge
            out["judged_packets"] = j.packets + j.cpu_packets
            out["device_batches"] = j.batches
            out["device_packets"] = j.packets
            out["cpu_batches"] = j.cpu_batches
            out["judge_min_batch"] = j.min_batch
            out["judged_pkts_per_s"] = round(
                (j.packets + j.cpu_packets) / wall, 1)
    if sums["thread"] != sums["hybrid"]:
        return {"error": "hybrid trace diverged from cpu thread",
                "pairs": pairs}
    out["hybrid_vs_thread"] = round(
        out["thread_wall_s"] / out["hybrid_wall_s"], 2)
    return out


def run_hybrid_sweep() -> dict:
    """VERDICT r4 #3: judged-pkts/s AND hybrid-vs-thread per batch
    scale — pairs in {40, 200, 1000} — so the crossover (or its
    absence) is measured, not asserted. Later rungs are skipped when
    the sweep exceeds its wall budget (recorded, never silent)."""
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench_hybrid_")
    try:
        bins = _compile_tcp_bins(tmp)
        if bins is None:
            return {"skipped": "no compiler or plugins"}
        sweep: dict = {"rungs": []}
        t0 = time.perf_counter()
        for pairs in HYBRID_SWEEP:
            elapsed = time.perf_counter() - t0
            if elapsed > HYBRID_SWEEP_BUDGET_S:
                sweep["skipped_rungs"] = [
                    p for p in HYBRID_SWEEP if p > pairs] + [pairs]
                sweep["skip_reason"] = (
                    f"sweep budget {HYBRID_SWEEP_BUDGET_S}s exceeded "
                    f"({elapsed:.0f}s)")
                break
            log(f"  hybrid rung: {pairs} pairs")
            r = _hybrid_rung(bins, tmp, pairs)
            log(f"    {r}")
            sweep["rungs"].append(r)
            if "error" in r:
                break
        return sweep
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    result = {
        "metric": "packets_routed_per_sec_per_chip",
        "value": 0.0,
        "unit": "packets/s",
        # None = "no valid ratio" (errors/fallback); only a completed
        # device-vs-cpu ladder sets a number here
        "vs_baseline": None,
    }
    rc = 0
    bench_t0 = time.perf_counter()
    try:
        devs, fell_back = init_backend()
        n_chips = len({d.id for d in devs})
        # backend identity (jax/jaxlib/platform/device kind): records
        # from different backends must never be silently comparable
        result.update(backend_record(devs))
        # explicit stamp: fallback rungs (BENCH_r03-r05) must never
        # be mistaken for TPU trajectory points
        result["fallback"] = bool(fell_back)
        if not fell_back:
            _tuned.update(load_tuned_knobs())
            if _tuned:
                log(f"applying on-chip tuned knobs: {_tuned}")
                result["tuned_knobs"] = dict(_tuned)
        rungs, headline, full_stop = RUNGS, HEADLINE, FULL_STOP_S
        if fell_back:
            result["error"] = ("tpu backend unavailable; numbers are "
                               "from the cpu jax platform")
            rc = 1
            if not os.environ.get("BENCH_SMOKE"):
                # VERDICT r4 weak-1: a fallback artifact must still
                # carry the big rungs (clearly labeled platform: cpu)
                # — run the 1k rung always, the 10k rung if the wall
                # budget allows (guarded below), and shorten the full
                # run. Slices must clear the clients' 2s start_time by
                # enough to route real traffic: the old 2.0s tgen_1000
                # slice ended exactly at client start and benched 0
                # packets (BENCH_r05). Under BENCH_SMOKE the tiny
                # ladder stands: the fallback smoke test drives this
                # exact branch without the big rungs.
                rungs = [("tgen_100", "examples/tgen_100.yaml", 5.0),
                         ("tgen_1000", "examples/tgen_1000.yaml", 3.0),
                         ("tgen_10000", "examples/tgen_10000.yaml",
                          2.5)]
                headline, full_stop = "tgen_1000", 10.0
        engine_cache: dict = {}
        ladder = {}
        last_rung_wall = 0.0
        for name, path, slice_s in rungs:
            if fell_back and name == "tgen_10000":
                # ~10x the 1k rung's wall + compile headroom; skip
                # LOUDLY when it cannot fit the supervisor cap
                est = 12 * last_rung_wall + 240
                used = time.perf_counter() - bench_t0
                if used + est > 1600:
                    ladder[name] = {"skipped":
                                    f"cpu-platform estimate {est:.0f}s "
                                    f"after {used:.0f}s used exceeds "
                                    "the wall budget"}
                    log(f"{name}: skipped ({ladder[name]['skipped']})")
                    continue
            log(f"{name}: device slice ({slice_s}s sim)")
            d_wall, d_pkts, _, d_stamp = run_device_tuned(
                path, slice_s, engine_cache)
            log(f"  device: {d_pkts} pkts in {d_wall:.2f}s "
                f"({d_pkts / d_wall:,.0f}/s)")
            log(f"{name}: cpu thread slice ({slice_s}s sim)")
            c_wall, c_pkts, _ = run_cpu_thread(path, slice_s)
            log(f"  cpu: {c_pkts} pkts in {c_wall:.2f}s "
                f"({c_pkts / c_wall:,.0f}/s)")
            if d_pkts != c_pkts:
                # identical config+seed must route identical traffic;
                # a mismatch means the engines diverged — not a number
                # worth publishing
                raise RuntimeError(
                    f"{name}: device routed {d_pkts} packets but cpu "
                    f"routed {c_pkts} on the same config/seed")
            if d_pkts == 0 or c_pkts == 0:
                # a zero-packet rung makes the throughput ratio 0/0
                # (BENCH_r05's "float division by zero"): the tgen
                # clients start at 2s sim, so any slice that stops at
                # or before that measures boot, not routing — fail
                # with the config's arithmetic, never a bare ZeroDiv
                raise RuntimeError(
                    f"{name}: 0 packets routed on the {slice_s}s sim "
                    f"slice (device={d_pkts}, cpu={c_pkts}) — tgen "
                    "clients start at 2s sim, so the slice must stop "
                    "well past their start_time to carry traffic; "
                    "lengthen the slice or fix the config")
            ratio = (d_pkts / d_wall) / (c_pkts / c_wall)
            ladder[name] = {
                "slice_sim_s": slice_s,
                "device_pkts_per_s": round(d_pkts / d_wall, 1),
                "cpu_thread_pkts_per_s": round(c_pkts / c_wall, 1),
                "speedup": round(ratio, 2),
                # cold-start attribution (compile split from first
                # dispatch; cache_hit marks a warm start) — every
                # BENCH record carries it from now on, as does the
                # strategy-plan provenance (None = default knobs)
                **{k: d_stamp.get(k) for k in
                   ("compile_s", "first_dispatch_s", "cache_hit",
                    "plan", "admission", "degrades")},
            }
            last_rung_wall = d_wall + c_wall
            log(f"  speedup vs thread policy: {ratio:.2f}x")
            if fell_back and name == "tgen_10000" \
                    and "skipped" not in ladder[name]:
                headline = "tgen_10000"
                full_stop = 5.0

        log(f"{headline}: device full run ({full_stop}s sim, "
            "2.5s-sim dispatch segments)")
        headline_path = dict((n, p) for n, p, _ in rungs)[headline]
        f_wall, f_pkts, f_sim, f_stamp = run_device_tuned(
            headline_path, full_stop, engine_cache, segment_s=2.5)
        sim_per_wall = f_sim / f_wall
        log(f"  full: {f_pkts} pkts in {f_wall:.2f}s "
            f"({f_pkts / f_wall:,.0f}/s; {sim_per_wall:.2f} "
            "sim-s/wall-s)")

        result["value"] = round(f_pkts / f_wall / n_chips, 1)
        if not fell_back:
            result["vs_baseline"] = ladder[headline]["speedup"]
        result["sim_s_per_wall_s"] = round(sim_per_wall, 3)
        result["n_chips"] = n_chips
        # headline cold-start attribution: compile_s / cache_hit let
        # the perf trajectory track warm starts (a repeat bench with
        # a populated cache must show cache_hit true and compile_s
        # collapsed)
        result["compile_s"] = f_stamp.get("compile_s")
        result["first_dispatch_s"] = f_stamp.get("first_dispatch_s")
        result["cache_hit"] = f_stamp.get("cache_hit")
        result["compile_cache"] = f_stamp.get("compile_cache")
        # strategy-plan provenance for the headline run (None =
        # default knobs; a fingerprint-mismatched PLAN stamps its
        # refusal as plan_error instead)
        result["plan"] = f_stamp.get("plan")
        if f_stamp.get("plan_error"):
            result["plan_error"] = f_stamp["plan_error"]
        # where the full run's wall went (flight recorder, default
        # summary mode): host/judge/dispatch/exchange/checkpoint/
        # retry/compile/plan walls + the dominant phase
        result["phase_walls"] = f_stamp.get("phase_walls")
        result["dominant_phase"] = f_stamp.get("dominant_phase")
        result["pipeline"] = f_stamp.get("pipeline")
        # preflight admission verdict + modeled footprint for the
        # headline run (and the degrade-rung count if it absorbed a
        # runtime OOM) — same comparability rule as the plan stamp
        result["admission"] = f_stamp.get("admission")
        if f_stamp.get("degrades"):
            result["degrades"] = f_stamp["degrades"]
        result["ladder"] = ladder

        if headline_path in _occ_records:
            # the full run's measured occupancy high-water marks —
            # scripts/tune_10k.py prunes its sweep grid from this
            # record, and capacity_plan: <path> replays it
            from shadow_tpu.device import capacity
            occ_path, occ = _occ_records[headline_path]
            try:
                # atomic tmp+os.replace (utils/artifacts.py): a bench
                # killed mid-write must not leave truncated JSON that
                # a later capacity_plan: <path> run chokes on
                capacity.save_record(occ, occ_path)
                result["occupancy_record"] = occ_path
                log(f"occupancy record -> {occ_path}")
            except OSError as e:
                log(f"could not write occupancy record: {e}")

        log(f"multichip rung: {n_chips} chip(s), exchange auto + "
            "occupancy plan")
        try:
            result["multichip"] = run_multichip_rung(n_chips,
                                                     fell_back,
                                                     bench_t0)
            log(f"  multichip: {result['multichip']}")
            if "error" in result["multichip"]:
                rc = 1
        except Exception as e:          # noqa: BLE001
            result["multichip"] = {"error": str(e)}
            log(f"  multichip rung failed: {e}")
            rc = 1

        log(f"pipelined rung: {headline} at pipeline_depth "
            f"{PIPELINE_DEPTHS} (supervised posture, warm)")
        try:
            result["pipelined"] = run_pipelined_rung(
                headline, headline_path, full_stop)
            log(f"  pipelined: {result['pipelined']}")
            if "error" in result["pipelined"]:
                rc = 1
        except Exception as e:          # noqa: BLE001
            result["pipelined"] = {"error": str(e)}
            log(f"  pipelined rung failed: {e}")
            rc = 1

        log(f"ensemble rung: {ENSEMBLE_REPLICAS}-replica seed sweep "
            f"of {ENSEMBLE_CONFIG} ({ENSEMBLE_STOP_S}s sim, cold "
            "walls)")
        try:
            result["ensemble"] = run_ensemble_rung()
            log(f"  ensemble: {result['ensemble']}")
            if "error" in result["ensemble"]:
                rc = 1
        except Exception as e:          # noqa: BLE001
            result["ensemble"] = {"error": str(e)}
            log(f"  ensemble rung failed: {e}")
            rc = 1

        log("boot rung: columnar host-plane build + init_state "
            "ladder (docs/host_plane.md)")
        try:
            result["boot"] = run_boot_rung()
            if "error" in result["boot"]:
                log(f"  boot rung: {result['boot']['error']}")
                rc = 1
        except Exception as e:          # noqa: BLE001
            result["boot"] = {"error": str(e)}
            log(f"  boot rung failed: {e}")
            rc = 1

        log("topology rung: hierarchical vs dense table build "
            "(host-side, docs/topology.md)")
        try:
            result["topology"] = run_topology_rung()
            if "error" in result["topology"]:
                log(f"  topology rung: {result['topology']['error']}")
                rc = 1
        except Exception as e:          # noqa: BLE001
            result["topology"] = {"error": str(e)}
            log(f"  topology rung failed: {e}")
            rc = 1

        if not os.environ.get("BENCH_SMOKE"):
            log(f"hybrid sweep: pairs in {HYBRID_SWEEP} (adaptive "
                "judge vs cpu thread)")
            try:
                result["hybrid"] = run_hybrid_sweep()
                log(f"  hybrid: {result['hybrid']}")
            except Exception as e:          # noqa: BLE001
                result["hybrid"] = {"error": str(e)}
                log(f"  hybrid sweep failed: {e}")
    except Exception as e:              # noqa: BLE001
        result["error"] = str(e)
        log(f"FAILED: {e}")
        rc = 1
    if "tuned_knobs" in result:
        # the overflow fallback may have dropped outbox_compact
        # mid-run — the artifact must report what actually applied
        result["tuned_knobs"] = {k: v for k, v in _tuned.items()
                                 if k != "workload"}
    print(json.dumps(result), flush=True)
    return rc


def _supervise() -> int:
    """Run the real bench in a child with a hard wall-clock cap: even
    if the relay wedges AFTER the probe (the parent claim can still
    hang inside jax with no interruptible timeout), the supervisor
    kills the child and emits the error JSON — the one-line contract
    holds no matter what the backend does."""
    env = dict(os.environ, SHADOWTPU_BENCH_CHILD="1")
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                         env=env)
    try:
        return p.wait(timeout=3200)
    except subprocess.TimeoutExpired:
        # SIGTERM + grace before SIGKILL: killing the child mid-claim
        # wedges the relay for hours — give it a chance to release
        p.terminate()
        try:
            p.wait(timeout=60)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        print(json.dumps({
            "metric": "packets_routed_per_sec_per_chip",
            "value": 0.0, "unit": "packets/s", "vs_baseline": None,
            "error": "bench timed out (wedged TPU relay?)",
        }), flush=True)
        return 1


if __name__ == "__main__":
    if os.environ.get("SHADOWTPU_BENCH_CHILD") == "1":
        # exit via SystemExit on SIGTERM so the supervisor's grace
        # period lets Python cleanup (claim release) actually run
        import signal
        signal.signal(signal.SIGTERM, lambda *a: sys.exit(3))
        # drop known-noise XLA warning lines at the fd so the tail
        # the driver captures holds meaningful lines only
        from shadow_tpu.utils.stderrfilter import install_fd_filter
        install_fd_filter()
        sys.exit(main())
    sys.exit(_supervise())
