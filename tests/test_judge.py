"""Direct unit tests for the hybrid DeviceJudge (device/judge.py).

The batched device judge is the hybrid policy's hot path, and its
batching THRESHOLD (`hybrid_judge_min_batch`) is the first concrete
target of the strategy autotuner (the 0.96-1.52x regression rungs in
BENCH_tpu.json) — yet until now the module had no isolated coverage:
its correctness rode indirectly on the end-to-end hybrid suites.
These tests pin the unit contracts the tuner leans on:

* power-of-two bucket padding (a handful of compiled shapes, padding
  never leaks into verdicts);
* bit-identity with the CPU NetworkModel's per-packet judgment (same
  threefry chain, same latency matrices) — the property that makes
  the threshold a pure wall-time knob;
* the bootstrap window (no drops before bootstrap_end);
* the batch/packet counters each path maintains;
* threshold ROUTING in a real hybrid run: min_batch 0 sends every
  round to the device, a huge min_batch keeps every round on the
  CPU, and the two traces are bit-identical.
"""

import numpy as np
import pytest

from shadow_tpu.config import load_config_str
from shadow_tpu.core.controller import Controller
from shadow_tpu.core.netmodel import NetworkModel
from shadow_tpu.device.judge import DeviceJudge, _MIN_BUCKET, _bucket
from shadow_tpu.topology.graph import Topology

GML_LOSSY = """graph [ directed 0
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  node [ id 1 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.3 ]
  edge [ source 0 target 1 latency "25 ms" packet_loss 0.3 ]
  edge [ source 1 target 1 latency "10 ms" packet_loss 0.3 ]
]"""


def _judge_pair(seed: int = 7, bootstrap_end: int = 0):
    topo = Topology.from_gml(GML_LOSSY)
    hv = np.array([0, 0, 1, 1], dtype=np.int64)
    nm = NetworkModel(topology=topo, host_vertex=hv, seed=seed,
                      bootstrap_end=bootstrap_end)
    dj = DeviceJudge(topo, hv, seed, bootstrap_end=bootstrap_end)
    return nm, dj


def _traffic(n: int, rng_seed: int = 0):
    rng = np.random.default_rng(rng_seed)
    now = rng.integers(1, 10_000_000_000, n).astype(np.int64)
    src = rng.integers(0, 4, n).astype(np.int32)
    dst = rng.integers(0, 4, n).astype(np.int32)
    pseq = rng.integers(0, 1 << 20, n).astype(np.int32)
    return now, src, dst, pseq


def test_bucket_sizes_are_powers_of_two():
    assert _bucket(1) == _MIN_BUCKET
    assert _bucket(_MIN_BUCKET) == _MIN_BUCKET
    assert _bucket(_MIN_BUCKET + 1) == 2 * _MIN_BUCKET
    assert _bucket(1000) == 1024
    assert _bucket(1025) == 2048
    # a sweep of batch sizes maps to a handful of compiled shapes
    shapes = {_bucket(n) for n in range(1, 5000, 37)}
    assert all(b & (b - 1) == 0 for b in shapes)
    assert len(shapes) <= 6


@pytest.mark.parametrize("n", [1, 3, 255, 256, 257, 700])
def test_batch_verdicts_bit_match_cpu_netmodel(n):
    """The device batch must reproduce the CPU model's per-packet
    verdicts exactly — drop roll AND deliver time — at every batch
    size, including the pad boundaries (padding must never leak)."""
    nm, dj = _judge_pair()
    now, src, dst, pseq = _traffic(n, rng_seed=n)
    delivered, deliver_time = dj.judge_batch(now, src, dst, pseq)
    assert len(delivered) == len(deliver_time) == n
    dropped_some = False
    for i in range(n):
        v = nm.judge(int(now[i]), int(src[i]), int(dst[i]),
                     int(pseq[i]))
        assert bool(delivered[i]) == v.delivered, i
        assert int(deliver_time[i]) == v.deliver_time, i
        dropped_some |= not v.delivered
    if n >= 255:
        # 30% loss: a lossless sample would mean the roll is dead
        assert dropped_some


def test_bootstrap_window_never_drops():
    """Packets sent before bootstrap_end bypass the drop roll (the
    reference's unlimited-bandwidth bootstrap), on both paths."""
    boot = 5_000_000_000
    nm, dj = _judge_pair(bootstrap_end=boot)
    now, src, dst, pseq = _traffic(400, rng_seed=3)
    now = now % boot            # everything inside the window
    delivered, _ = dj.judge_batch(now, src, dst, pseq)
    assert delivered.all()
    v = nm.judge(int(now[0]), int(src[0]), int(dst[0]), int(pseq[0]))
    assert v.delivered


def test_batch_counters():
    """judge_batch maintains the device-side counters only; the CPU
    fallback counters belong to the manager's threshold branch."""
    _, dj = _judge_pair()
    for n in (10, 300):
        dj.judge_batch(*_traffic(n))
    assert dj.batches == 2
    assert dj.packets == 310
    assert dj.cpu_batches == 0 and dj.cpu_packets == 0


def test_min_batch_constructor_plumbing():
    topo = Topology.from_gml(GML_LOSSY)
    hv = np.array([0, 1], dtype=np.int64)
    dj = DeviceJudge(topo, hv, 1, min_batch=777)
    assert dj.min_batch == 777


PHOLD_HYBRID = """
general:
  stop_time: 1s
  seed: 7
network:
  graph:
    type: gml
    inline: |
{gml}
experimental:
  scheduler_policy: hybrid
  hybrid_judge_min_batch: {min_batch}
hosts:
  left:
    quantity: 6
    network_node_id: 0
    processes:
    - path: model:phold
      args: msgload=3 size=64
      start_time: 10ms
  right:
    quantity: 6
    network_node_id: 1
    processes:
    - path: model:phold
      args: msgload=3 size=64
      start_time: 10ms
"""


def _hybrid_run(min_batch: int):
    gml = "\n".join("      " + ln for ln in GML_LOSSY.splitlines())
    cfg = load_config_str(PHOLD_HYBRID.format(gml=gml,
                                              min_batch=min_batch))
    c = Controller(cfg)
    stats = c.run()
    assert stats.ok
    sig = [(h.name, h.trace_checksum, h.events_executed,
            h.packets_sent, h.packets_dropped) for h in c.sim.hosts]
    return sig, c.manager.net_judge


def test_threshold_routes_rounds_and_never_changes_traces():
    """The tuner's contract for hybrid_judge_min_batch: 0 sends every
    round to the device, a threshold above any round size keeps every
    round on the CPU, and the two runs are bit-identical — the knob
    moves WALL time only."""
    sig_dev, j_dev = _hybrid_run(0)
    assert j_dev.batches > 0
    assert j_dev.cpu_batches == 0
    assert j_dev.packets > 0

    sig_cpu, j_cpu = _hybrid_run(10**9)
    assert j_cpu.batches == 0
    assert j_cpu.cpu_batches > 0
    assert j_cpu.cpu_packets == j_dev.packets
    assert sig_cpu == sig_dev
