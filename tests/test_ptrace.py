"""Managed-process tests under the ptrace interposition backend.

The same real-executable plugins as test_managed.py, driven by
PTRACE_SYSEMU instead of the preload shim (the reference runs its
shadow tests once per METHOD — src/test/CMakeLists.txt:36-60 — and so
do we). Plus TSC emulation checks, which only exist on this backend."""

import pytest

from test_managed import (  # noqa: F401  (fixture re-export)
    base_cfg,
    plugins,
    read_stdout,
    run_sim,
)


def ptrace_cfg(data_dir: str, stop: str = "30s") -> str:
    return base_cfg(data_dir, stop) \
        .replace("hosts:\n", "experimental:\n"
                 "  interpose_method: ptrace\nhosts:\n")


def _ptrace_works() -> bool:
    """PTRACE_TRACEME may be blocked in hardened sandboxes."""
    import subprocess
    try:
        p = subprocess.run(
            ["python3", "-c",
             "import ctypes; l=ctypes.CDLL(None);"
             "print(l.ptrace(0,0,0,0))"],
            capture_output=True, timeout=10, text=True)
        if p.returncode != 0 or p.stdout.strip() != "0":
            return False
        # clean up: the probe traced itself to its parent; it exited.
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _ptrace_works(),
                                reason="ptrace unavailable here")


def test_timecheck_under_ptrace(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['timecheck']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "timecheck")
    lines = out.splitlines()
    assert lines[0] == "t0 1.000000000"
    assert lines[1] == "t1 1.100000000"
    assert lines[2] == f"wall {946_684_800 + 1}"
    assert lines[3] == "host alice"
    assert stats.ok


def test_udp_ping_under_ptrace(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['udp_echo']}
      args: 9000 2
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['udp_ping']}
      args: 11.0.0.1 9000 2
      start_time: 2s
"""
    stats, _ = run_sim(cfg, tmp_path)
    client_out = read_stdout(data, "client", "udp_ping")
    assert "reply 0: 'ping 0'" in client_out
    assert "reply 1: 'ping 1'" in client_out
    rtts = [int(line.rsplit("rtt_ms=", 1)[1])
            for line in client_out.splitlines() if "rtt_ms=" in line]
    assert all(50 <= r <= 60 for r in rtts), rtts


def test_tcp_transfer_under_ptrace(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data, stop="60s") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['tcp_server']}
      args: 8080
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['tcp_client']}
      args: 11.0.0.1 8080 50000
      start_time: 2s
"""
    run_sim(cfg, tmp_path)
    server_out = read_stdout(data, "server", "tcp_server")
    client_out = read_stdout(data, "client", "tcp_client")
    sent = [line for line in client_out.splitlines()
            if line.startswith("sent ")][0].split()
    recv = [line for line in server_out.splitlines()
            if line.startswith("received ")][0].split()
    assert sent[1] == recv[1] == "50000"
    assert sent[4] == recv[4]


def test_rdtsc_emulation_deterministic(plugins, tmp_path):
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['rdtsc_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "rdtsc_check")
    lines = out.splitlines()
    # nominal 1 GHz: counter == sim ns. t0 reads at sim t=1s.
    assert lines[0] == "t0 1000000000"
    # 50 ms sleep => 50_000_000 cycles
    assert lines[1] == "dt 50000000"
    assert lines[2] == "p_ge 1"
    assert stats.ok


def test_preload_vs_ptrace_equivalence(plugins, tmp_path):
    """The two interposition backends must produce identical plugin
    output for the same config (reference runs every shadow test under
    both METHODs expecting equivalence)."""
    outs = {}
    for method in ("preload", "ptrace"):
        data = str(tmp_path / method / "shadow.data")
        cfg = base_cfg(data).replace(
            "hosts:\n",
            f"experimental:\n  interpose_method: {method}\nhosts:\n") + f"""
  server:
    network_node_id: 0
    processes:
    - path: {plugins['udp_echo']}
      args: 9000 2
      start_time: 1s
  client:
    network_node_id: 1
    processes:
    - path: {plugins['udp_ping']}
      args: 11.0.0.1 9000 2
      start_time: 2s
"""
        run_sim(cfg, tmp_path / method)
        outs[method] = (read_stdout(data, "client", "udp_ping"),
                        read_stdout(data, "server", "udp_echo"))
    assert outs["preload"] == outs["ptrace"]


def test_pthreads_under_ptrace(plugins, tmp_path):
    """TRACECLONE multi-tracee threads: virtual tids in creation
    order, per-thread simulated sleeps, futex-backed join — the same
    assertions as the preload backend's test (ref thread_ptrace.c
    drives multithreaded tracees, :36-56)."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['threads_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "threads_check")
    lines = out.splitlines()
    assert lines[0] == "main tid==pid: 1"
    assert "thread 0 dtid=1 slept=10ms counter=1" in lines
    assert "thread 1 dtid=2 slept=20ms counter=2" in lines
    assert "thread 2 dtid=3 slept=30ms counter=3" in lines
    assert "joined 0 ret=1" in lines
    assert "joined 2 ret=3" in lines
    assert lines[-1] == "all joined: counter=3 elapsed_ms=30"
    assert stats.ok


def test_pthreads_deterministic_under_ptrace(plugins, tmp_path):
    outs = []
    for run in range(2):
        data = str(tmp_path / f"r{run}" / "shadow.data")
        cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['threads_check']}
      start_time: 1s
"""
        run_sim(cfg, tmp_path / f"r{run}")
        outs.append(read_stdout(data, "alice", "threads_check"))
    assert outs[0] == outs[1]


def test_signals_under_ptrace(plugins, tmp_path):
    """Kernel-injected virtual signals + TRACEFORK children: the same
    assertions as the preload backend's signal test — self-kill runs
    the handler (with its own trapped syscall) before kill returns, a
    forked child's SIGUSR2 EINTRs the parent's nanosleep at the exact
    simulated instant, SIGKILL'd children report WIFSIGNALED."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['signal_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "signal_check").splitlines()
    assert out[0] == "self got1 10 handler_syscall_ok 1"
    assert out[1] == "ignored ok"
    assert out[2] == "eintr 1 errno_ok 1 got2 13 t_ms 150"
    assert out[3] == "sigkill ok 1 signaled 1 sig 9 t_ms 50"
    assert out[4] == "done"
    assert stats.ok


def test_sigmask_under_ptrace(plugins, tmp_path):
    """Blocked-signal contract under ptrace injection: pending-while-
    blocked, sigsuspend's atomic swap, sigtimedwait's synchronous
    consumption, temp p-masks, and thread-directed tgkill."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['sigmask_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "sigmask_check").splitlines()
    assert out[0] == "blocked 1 pending 1 after_unblock 1"
    assert out[1] == "sigsuspend 1 errno_ok 1 got2 1 mask_restored 1"
    assert out[2] == "sigtimedwait 1 si_signo 15 handler_ran 0 t_ms 100"
    assert out[3] == "reaper 1 instant 1"
    assert out[4] == "timeout 1 errno_ok 1 t_ms 250"
    assert out[5] == "ppoll_eintr 1 got1 1 t_ms 80 mask_back 1"
    assert out[6] == "directed held 1 delivered 1"
    assert out[7] == "main_held 1"
    assert out[8] == "done"
    assert stats.ok


def test_fork_under_ptrace(plugins, tmp_path):
    """TRACEFORK: COW fork with virtual pids, wait4 reaping, pipes
    across the fork — same assertions as the preload fork test."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['fork_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "fork_check")
    assert "echild 1" in out
    assert stats.ok


def test_system_spawns_shell_under_ptrace(plugins, tmp_path):
    """system() = posix_spawn = __clone(CLONE_VM|CLONE_VFORK, new
    stack): the fork rewrite + child %rsp redirect must give the COW
    child the clone stack glibc pushed fn/arg onto, the child execs
    /bin/sh (TRACEEXEC), and wait4 reports its exit."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['spawn_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "spawn_check")
    assert "spawned-ok" in out
    assert "system rc=0 exited=1 status=0" in out
    assert stats.ok


def test_clone3_under_ptrace(plugins, tmp_path):
    """Raw clone3 (the musl/Go path, no glibc fallback): thread
    flavor with stack/SETTID/CLEARTID through struct clone_args, and
    fork flavor with wait4 — both fully virtualized."""
    data = str(tmp_path / "shadow.data")
    cfg = ptrace_cfg(data) + f"""
  alice:
    network_node_id: 0
    processes:
    - path: {plugins['clone3_check']}
      start_time: 1s
"""
    stats, _ = run_sim(cfg, tmp_path)
    out = read_stdout(data, "alice", "clone3_check")
    assert "t-child ran" in out
    assert "thread vtid_delta=1 cleared=1" in out
    assert "f-child pid_delta=2" in out
    assert "fork rc=1 exited=1 code=7" in out
    assert "done" in out
    assert stats.ok
